#!/usr/bin/env python
"""Headline benchmark: TPU-offloaded conflict-detection throughput.

Replays a YCSB-A-style stream of commit batches (zipf point keys, 2 read +
1 write conflict ranges per transaction) at BASELINE.json config-2 scale —
100K-transaction batches — through the TPU ConflictSet backend and reports
end-to-end resolved conflict ranges per second against the 1M/s north-star
target (BASELINE.md).  Also measured and printed on the same JSON line:

  vs_oracle      TPU throughput / CPU-oracle throughput on the same stream
                 (the oracle is the SkipList-semantics parity baseline,
                 conflict/oracle.py; reference fdbserver -r skiplisttest,
                 SkipList.cpp:1082)
  p50_resolve_ms p50 single-batch resolve latency, depth-1 dispatch->wait
  parity         "ok" — verdict arrays bit-identical to the oracle on the
                 compared prefix of the stream (asserted, not just reported)

Prints exactly one JSON line with at least:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

import json
import sys
import time

import numpy as np

NORTH_STAR_RANGES_PER_S = 1_000_000.0

TXNS_PER_BATCH = 100_000   # BASELINE.json config 2
READS_PER_TXN = 2
WRITES_PER_TXN = 1
RANGES_PER_TXN = READS_PER_TXN + WRITES_PER_TXN
N_WARMUP = 3
N_BATCHES = 14             # measured
N_PARITY = 3               # prefix batches cross-checked vs the CPU oracle
N_LATENCY = 8              # depth-1 batches for the p50 latency probe
KEYSPACE = 1_000_000
VERSIONS_PER_BATCH = 1_000
WINDOW_BATCHES = 5         # MVCC floor trails this many batches
PIPELINE_DEPTH = 8
CAPACITY = 1 << 21
DELTA_CAPACITY = 1 << 20


def gen_batch(rng: np.random.Generator, version: int, prev: int):
    """One batch as (EncodedBatch, kids, snaps) — fully vectorized."""
    from foundationdb_tpu.conflict.encoded import EncodedBatch
    from foundationdb_tpu.ops.digest import encode_fixed

    t = TXNS_PER_BATCH
    n = t * RANGES_PER_TXN
    kids = (rng.zipf(1.2, size=n) % KEYSPACE).astype(np.int64)
    # Key bytes: b"k" + 14 decimal digits (the proxy hands the resolver raw
    # byte keys; forming digests from them is the backend's timed work, but
    # the byte matrix itself is workload generation).
    mat = np.empty((n, 16), dtype=np.uint8)
    mat[:, 0] = ord("k")
    mat[:, 15] = 0
    x = kids.copy()
    for d in range(14):
        mat[:, 14 - d] = 48 + x % 10
        x //= 10
    snaps = np.maximum(
        prev - rng.integers(0, 2 * VERSIONS_PER_BATCH, size=t), 0)

    nr = t * READS_PER_TXN
    begin = encode_fixed(mat[:, :15])          # key, marker 15
    end = encode_fixed(mat)                    # key + b"\x00", marker 16
    enc = EncodedBatch(
        n_txns=t,
        t_snap=snaps.astype(np.int64),
        t_has_reads=np.ones((t,), dtype=bool),
        r_txn=(np.arange(nr, dtype=np.int32) // READS_PER_TXN),
        r_begin=begin[:, :nr], r_end=end[:, :nr],
        w_txn=np.arange(t, dtype=np.int32),
        w_begin=begin[:, nr:], w_end=end[:, nr:],
        all_point=True,
    )
    return enc, kids, snaps


def to_transactions(kids: np.ndarray, snaps: np.ndarray):
    """Object form of the same batch for the CPU oracle."""
    from foundationdb_tpu.txn.types import CommitTransactionRef, KeyRange
    keys = [b"k%014d" % int(k) for k in kids]
    nr = TXNS_PER_BATCH * READS_PER_TXN
    txns = []
    for t in range(TXNS_PER_BATCH):
        # Same layout as gen_batch: rows [0, 2T) are reads (txn = row//2),
        # rows [2T, 3T) are writes (txn = row - 2T).
        reads = []
        for j in range(READS_PER_TXN):
            k = keys[t * READS_PER_TXN + j]
            reads.append(KeyRange(k, k + b"\x00"))
        writes = []
        for j in range(WRITES_PER_TXN):
            k = keys[nr + t * WRITES_PER_TXN + j]
            writes.append(KeyRange(k, k + b"\x00"))
        txns.append(CommitTransactionRef(
            read_conflict_ranges=reads, write_conflict_ranges=writes,
            mutations=[], read_snapshot=int(snaps[t])))
    return txns


def main() -> None:
    backend = sys.argv[1] if len(sys.argv) > 1 else "tpu"
    if backend not in ("tpu", "cpu"):
        print(f"unknown backend {backend!r}: expected tpu|cpu",
              file=sys.stderr)
        sys.exit(2)
    from foundationdb_tpu.conflict.oracle import OracleConflictSet
    from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet
    from foundationdb_tpu.txn.types import CommitResult

    window = WINDOW_BATCHES * VERSIONS_PER_BATCH
    rng = np.random.default_rng(2026)
    total = (N_WARMUP + N_PARITY if backend == "cpu"
             else N_WARMUP + N_BATCHES + N_LATENCY)
    batches = []
    version = 1_000
    for _ in range(total):
        prev = version
        version += VERSIONS_PER_BATCH
        batches.append((version, *gen_batch(rng, version, prev)))

    def floor(v):
        return max(v - window, 0)

    if backend == "cpu":
        # Oracle-only mode: throughput of the parity baseline on the prefix.
        # Object construction is untimed, matching the vs_oracle denominator
        # in the tpu run.
        cs = OracleConflictSet(0)
        n_ranges = 0
        dt = 0.0
        for v, enc, kids, snaps in batches[:N_WARMUP + N_PARITY]:
            txns = to_transactions(kids, snaps)
            t0 = time.perf_counter()
            cs.resolve(txns, v, floor(v))
            dt += time.perf_counter() - t0
            n_ranges += enc.n_ranges
        value = n_ranges / dt
        print(json.dumps({
            "metric": "conflict_range_checks_per_s", "value": round(value, 1),
            "unit": "ranges/s",
            "vs_baseline": round(value / NORTH_STAR_RANGES_PER_S, 4)}))
        return

    cs = TpuConflictSet(0, capacity=CAPACITY, delta_capacity=DELTA_CAPACITY)

    # Warmup: compile the fused step + merge for this bucket shape (the
    # merge is forced here so its one-time compile can't land mid-measure).
    for v, enc, kids, snaps in batches[:N_WARMUP]:
        cs.resolve_encoded(enc, v, floor(v))
    cs.merge()

    # ---- main throughput phase (pipelined) --------------------------------
    from collections import deque
    inflight = deque()
    n_ranges = 0
    n_txns = 0
    committed = 0
    tpu_results = []
    committed_code = int(CommitResult.COMMITTED)
    t0 = time.perf_counter()
    for v, enc, kids, snaps in batches[N_WARMUP:N_WARMUP + N_BATCHES]:
        inflight.append((enc, cs.resolve_encoded_async(enc, v, floor(v))))
        if len(inflight) > PIPELINE_DEPTH:
            enc_done, h = inflight.popleft()
            codes = h.wait_codes()
            tpu_results.append(codes)
            n_txns += enc_done.n_txns
            n_ranges += enc_done.n_ranges
            committed += int(np.sum(codes == committed_code))
    while inflight:
        enc_done, h = inflight.popleft()
        codes = h.wait_codes()
        tpu_results.append(codes)
        n_txns += enc_done.n_txns
        n_ranges += enc_done.n_ranges
        committed += int(np.sum(codes == committed_code))
    dt = time.perf_counter() - t0
    value = n_ranges / dt

    # ---- p50 resolve latency (depth-1 dispatch -> wait) -------------------
    lats = []
    for v, enc, kids, snaps in batches[N_WARMUP + N_BATCHES:]:
        t1 = time.perf_counter()
        cs.resolve_encoded_async(enc, v, floor(v)).wait_codes()
        lats.append(time.perf_counter() - t1)
    p50_ms = float(np.percentile(lats, 50) * 1e3)

    # ---- oracle on the same stream prefix: parity + relative throughput ---
    oracle = OracleConflictSet(0)
    oracle_ranges = 0
    oracle_dt = 0.0
    mismatches = 0
    for i, (v, enc, kids, snaps) in enumerate(
            batches[:N_WARMUP + N_PARITY]):
        txns = to_transactions(kids, snaps)  # untimed: object construction
        t1 = time.perf_counter()
        want = oracle.resolve(txns, v, floor(v))
        oracle_dt += time.perf_counter() - t1
        oracle_ranges += enc.n_ranges
        if N_WARMUP <= i < N_WARMUP + N_PARITY:
            got = tpu_results[i - N_WARMUP]
            want_codes = np.asarray([int(r) for r in want], dtype=np.int8)
            mismatches += int(np.sum(got != want_codes))
    oracle_rate = oracle_ranges / oracle_dt
    if mismatches:
        print(f"PARITY FAILURE: {mismatches} verdicts differ from the "
              "CPU oracle", file=sys.stderr)
        sys.exit(1)

    commit_rate = committed / max(n_txns, 1)
    print(f"# commit_rate={commit_rate:.3f} oracle={oracle_rate:.0f}/s "
          f"tpu={value:.0f}/s p50={p50_ms:.2f}ms", file=sys.stderr)
    if not 0.01 < commit_rate < 0.99:
        print("degenerate contention config", file=sys.stderr)
        sys.exit(1)

    print(json.dumps({
        "metric": "conflict_range_checks_per_s",
        "value": round(value, 1),
        "unit": "ranges/s",
        "vs_baseline": round(value / NORTH_STAR_RANGES_PER_S, 4),
        "vs_oracle": round(value / oracle_rate, 3),
        "p50_resolve_ms": round(p50_ms, 2),
        "parity": "ok",
        "txns_per_batch": TXNS_PER_BATCH,
    }))


if __name__ == "__main__":
    main()
