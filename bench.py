#!/usr/bin/env python
"""Headline benchmark: TPU-offloaded conflict-detection throughput.

Replays a YCSB-A-style stream of commit batches (zipf point keys, read+write
conflict ranges per transaction — BASELINE.json config 2) through the TPU
ConflictSet backend and reports end-to-end resolved conflict ranges per
second, against the 1M/s north-star target (BASELINE.md).

Equivalent of the reference's `fdbserver -r skiplisttest` microbench
(fdbserver/SkipList.cpp:1082 skipListTest — 500 batches, prints
Mtransactions/sec & Mkeys/sec).

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

import json
import sys
import time

import numpy as np

NORTH_STAR_RANGES_PER_S = 1_000_000.0

TXNS_PER_BATCH = 4096
READS_PER_TXN = 2
WRITES_PER_TXN = 1
N_BATCHES = 64
KEYSPACE = 1_000_000
VERSIONS_PER_BATCH = 1_000
PIPELINE_DEPTH = 8


def _key(kid: int) -> bytes:
    return b"k%014d" % kid


def build_batches(rng: np.random.Generator):
    from foundationdb_tpu.txn.types import (CommitTransactionRef, KeyRange,
                                            key_after)

    batches = []
    version = 1_000
    for _ in range(N_BATCHES):
        prev = version
        version += VERSIONS_PER_BATCH
        kids = rng.zipf(1.2, size=TXNS_PER_BATCH * (READS_PER_TXN +
                                                    WRITES_PER_TXN))
        kids = (kids % KEYSPACE).astype(np.int64)
        txns = []
        p = 0
        for _ in range(TXNS_PER_BATCH):
            reads = []
            for _ in range(READS_PER_TXN):
                k = _key(int(kids[p])); p += 1
                reads.append(KeyRange(k, key_after(k)))
            writes = []
            for _ in range(WRITES_PER_TXN):
                k = _key(int(kids[p])); p += 1
                writes.append(KeyRange(k, key_after(k)))
            # Snapshot within the last ~2 batches: realistic contention.
            snap = int(prev - rng.integers(0, 2 * VERSIONS_PER_BATCH))
            txns.append(CommitTransactionRef(
                read_conflict_ranges=reads, write_conflict_ranges=writes,
                mutations=[], read_snapshot=max(snap, 0)))
        batches.append((txns, version))
    return batches


def main() -> None:
    backend = "tpu"
    if len(sys.argv) > 1:
        backend = sys.argv[1]
    from foundationdb_tpu.conflict.api import new_conflict_set
    from foundationdb_tpu.txn.types import CommitResult

    rng = np.random.default_rng(2026)
    batches = build_batches(rng)
    window = 5 * VERSIONS_PER_BATCH  # MVCC floor trails ~5 batches

    kwargs = {"capacity": 1 << 17} if backend == "tpu" else {}
    cs = new_conflict_set(backend, **kwargs)

    # Warmup: compile the fused step for this bucket shape.
    for txns, version in batches[:3]:
        cs.resolve(txns, version, new_oldest_version=max(version - window, 0))

    pipelined = hasattr(cs, "resolve_async")
    t0 = time.perf_counter()
    n_ranges = 0
    n_txns = 0
    committed = 0
    if pipelined:
        # Keep PIPELINE_DEPTH batches in flight: the device-resident window
        # state carries the batch-to-batch dependency, so dispatches overlap
        # the host<->device round trip (reference proxies likewise keep
        # multiple commit batches in flight across pipeline stages).
        from collections import deque
        inflight = deque()
        for txns, version in batches[3:]:
            inflight.append((txns, cs.resolve_async(
                txns, version, new_oldest_version=max(version - window, 0))))
            if len(inflight) > PIPELINE_DEPTH:
                txns_done, h = inflight.popleft()
                results = h.wait()
                n_txns += len(txns_done)
                n_ranges += len(txns_done) * (READS_PER_TXN + WRITES_PER_TXN)
                committed += sum(1 for r in results
                                 if r == CommitResult.COMMITTED)
        while inflight:
            txns_done, h = inflight.popleft()
            results = h.wait()
            n_txns += len(txns_done)
            n_ranges += len(txns_done) * (READS_PER_TXN + WRITES_PER_TXN)
            committed += sum(1 for r in results
                             if r == CommitResult.COMMITTED)
    else:
        for txns, version in batches[3:]:
            results = cs.resolve(txns, version,
                                 new_oldest_version=max(version - window, 0))
            n_txns += len(txns)
            n_ranges += len(txns) * (READS_PER_TXN + WRITES_PER_TXN)
            committed += sum(1 for r in results
                             if r == CommitResult.COMMITTED)
    dt = time.perf_counter() - t0

    # Sanity: a broken contention config (0% or 100% commits) invalidates the
    # throughput claim; surface it without touching the one-line JSON contract.
    print(f"# commit_rate={committed / max(n_txns, 1):.3f}", file=sys.stderr)

    value = n_ranges / dt
    print(json.dumps({
        "metric": "conflict_range_checks_per_s",
        "value": round(value, 1),
        "unit": "ranges/s",
        "vs_baseline": round(value / NORTH_STAR_RANGES_PER_S, 4),
    }))


if __name__ == "__main__":
    main()
