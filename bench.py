#!/usr/bin/env python
"""Headline benchmark: TPU-offloaded conflict-detection throughput.

Replays a YCSB-A-style stream of commit batches (zipf point keys, 2 read +
1 write conflict ranges per transaction) at BASELINE.json config-2 scale —
100K-transaction batches — through the TPU ConflictSet backend and reports
end-to-end resolved conflict ranges per second against the 1M/s north-star
target (BASELINE.md).  Also measured and printed on the same JSON line:

  vs_oracle        TPU throughput / CPU-oracle throughput on the same stream
                   (the oracle is the SkipList-semantics parity baseline,
                   conflict/oracle.py; reference fdbserver -r skiplisttest,
                   SkipList.cpp:1082)
  p50_resolve_ms   p50 single-batch resolve latency, depth-1 dispatch->wait
  parity           "ok" — verdict arrays bit-identical to the oracle on the
                   compared prefixes of BOTH contention regimes (asserted)
  commit_rate      high-contention regime (zipf 1M keys, heavy aborts)
  commit_rate_low  low-contention regime (uniform 100M keys, ~all commit)

Resilience (the round-3 run produced NO number because one axon-tunnel
outage crashed the process; the round-5 run produced NO number because
probing outlived the driver's timeout): the measurement runs in a CHILD
process, and the parent budgets EVERYTHING from one external deadline
(BENCH_DEADLINE_S).  A provisional fallback JSON line (carrying the
last-known-good TPU figure) is printed FIRST, so even a SIGKILL at any
later point leaves a parseable artifact; the parent then probes the TPU
backend with bounded-timeout trivial jits (the tunnel hangs rather than
erroring when down), runs the child under the remaining budget, and on
persistent TPU failure re-runs the child on the JAX CPU backend so a
real, parity-checked number supersedes the provisional line — with an
"error" field recording the degradation.  The LAST JSON line on stdout is
always the best available result:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

NORTH_STAR_RANGES_PER_S = 1_000_000.0

TXNS_PER_BATCH = 100_000   # BASELINE.json config 2
READS_PER_TXN = 2
WRITES_PER_TXN = 1
RANGES_PER_TXN = READS_PER_TXN + WRITES_PER_TXN
N_WARMUP = 3
N_BATCHES = 14             # measured
N_PARITY = 3               # prefix batches cross-checked vs the CPU oracle
N_LATENCY = 8              # depth-1 batches for the p50 latency probe
N_LOWC = 3                 # low-contention parity batches (all checked)
KEYSPACE = 1_000_000
KEYSPACE_LOW = 100_000_000  # low-contention regime: ~all txns commit
VERSIONS_PER_BATCH = 1_000
WINDOW_BATCHES = 5         # MVCC floor trails this many batches
PIPELINE_DEPTH = 8
CAPACITY = 1 << 21
DELTA_CAPACITY = 1 << 20

# Supervised depth sweep (ISSUE 6): per-depth throughput through the
# pipelined SupervisedConflictSet (CONFLICT_PIPELINE_DEPTH), depths
# 1..SWEEP_MAX_DEPTH, cross-depth verdicts asserted bit-identical.  The
# env var narrows/widens the sweep (e.g. CONFLICT_PIPELINE_DEPTH=2).
SWEEP_MAX_DEPTH = max(1, min(
    int(os.environ.get("CONFLICT_PIPELINE_DEPTH", "3")), 4))
N_SWEEP_WARMUP = 2
N_SWEEP = 4                # measured batches per depth (6 under SMALL)
SWEEP_TXNS = None          # per-batch txns for the sweep (None = main size)
SWEEP_CAPACITY = None      # sweep window sizing (None = main CAPACITY)
SWEEP_DELTA_CAPACITY = None
# Fallback-mode sweep: the XLA-CPU "device" has no transfer link, so the
# pipeline has nothing to hide there (and this container is single-core:
# host pack and XLA compute share the silicon outright).  The sweep
# therefore emulates the ROUND-5 MEASURED axon tunnel transfer profile
# (PERF.md: ~8 MB/s pipelined h2d at ~12.7 B/range packed, ~33 ms d2h
# verdict fetch) as dispatch/fetch-lane sleeps — the latency structure
# the depth-N pipeline exists to overlap.  Real-TPU runs never emulate
# (their transfers are real); the JSON labels the emulation explicitly.
TUNNEL_H2D_MB_S = 8.0
TUNNEL_BYTES_PER_RANGE = 12.7
TUNNEL_D2H_S = 0.033

# BASELINE config 5 (sharded mode only): fill the mesh-sharded window to
# >= 1M in-flight ranges (floor frozen), measure fill throughput and an
# at-capacity conflict probe.  Equi-depth splits (splits_from_sample)
# spread the bench's shared-prefix keyspace across the "kr" shards so
# per-shard windows actually multiply capacity.
CONFIG5_TXNS = 65_536
CONFIG5_TARGET_RANGES = 1_000_000
CONFIG5_CAPACITY = 1 << 22          # total boundaries across shards
CONFIG5_DELTA = 1 << 20

# Multi-resolver sweep (ISSUE 7, `bench.py resolvers`): the SAME seeded
# partition-aligned stream through N = 1/2/4 per-resolver supervised
# backends — each resolver owns an equi-width quarter-cell slice of the
# keyspace and resolves only its fragments (the commit-proxy clip), with
# per-resolver and aggregate ranges/s emitted into BENCH_r07.json.  Each
# resolver's stream is TIMED SEPARATELY on this host; the aggregate
# models the production deployment (one resolver role per process/chip,
# all resolving concurrently) as total_ranges / max(per-resolver
# elapsed) — labeled as such in the JSON.
RSWEEP_NS = (1, 2, 4)
RSWEEP_TXNS = 8_192
RSWEEP_BATCHES = 6           # measured per config (first is compile/warm)
RSWEEP_WARMUP = 1            # leading batches excluded from the rate
RSWEEP_KEYSPACE = 262_144
RSWEEP_CELLS = 4             # finest partition; txns never straddle
RSWEEP_CAPACITY = 1 << 16    # per-resolver window sizing
RSWEEP_DELTA = 1 << 15

PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT", "120"))
# The whole run is budgeted from ONE externally supplied deadline
# (BENCH_DEADLINE_S): round 5 lost its entire window because the probe
# schedule assumed the bench owned its wall clock while the driver's
# timeout fired first (BENCH_r05.json rc=124, parsed=null).  Every phase
# below (probing, TPU child, CPU-fallback child) is clipped to the time
# remaining under the deadline, and a provisional fallback JSON line is
# printed FIRST so even a SIGKILL mid-run leaves a parseable artifact.
# The default must sit comfortably under any sane driver timeout.
DEADLINE_S = float(os.environ.get("BENCH_DEADLINE_S", "1500"))
# Fraction of the deadline reserved for the XLA-CPU fallback child (it
# must still fit after probing + a failed TPU attempt burn their share).
CPU_RESERVE_S = float(os.environ.get("BENCH_CPU_RESERVE",
                                     str(min(600.0, DEADLINE_S * 0.4))))
# Probe schedule inside the budget: tunnel outages are often transient,
# so re-probe every PROBE_INTERVAL_S — but never past the point where the
# CPU fallback could no longer run.
PROBE_INTERVAL_S = int(os.environ.get("BENCH_PROBE_INTERVAL", "120"))
PROBE_TOTAL_S = int(os.environ.get("BENCH_PROBE_TOTAL", "2700"))
CHILD_TIMEOUT_S = int(os.environ.get("BENCH_CHILD_TIMEOUT", "2700"))
CPU_CHILD_TIMEOUT_S = int(os.environ.get("BENCH_CPU_CHILD_TIMEOUT", "2400"))

_START_MONO = time.monotonic()


def _remaining_s() -> float:
    """Seconds left under the external deadline."""
    return DEADLINE_S - (time.monotonic() - _START_MONO)
# Last-known-good real-TPU figure, persisted next to this file on every
# successful TPU run and re-emitted with stale:true on fallback, so an
# outage round still reports the project's actual measured capability.
LKG_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_LKG.json")


def gen_batch(rng: np.random.Generator, version: int, prev: int,
              keyspace: int = KEYSPACE, zipf: bool = True,
              cells: int = 0):
    """One batch as (EncodedBatch, kids, snaps) — fully vectorized.

    cells > 0 partition-aligns the workload (multi-resolver sweep,
    ISSUE 7): txn i's ranges all land in key cell (i % cells), so a
    transaction never straddles a resolver boundary and the per-resolver
    verdict merge is EXACTLY the single-resolver verdict set (straddling
    txns are pessimistic-only in a partitioned plane — a locally
    committed / globally aborted txn leaves its writes in the owner's
    history, as in the reference resolver)."""
    from foundationdb_tpu.conflict.encoded import EncodedBatch
    from foundationdb_tpu.ops.digest import encode_fixed

    t = TXNS_PER_BATCH
    n = t * RANGES_PER_TXN
    if zipf:
        kids = (rng.zipf(1.2, size=n) % keyspace).astype(np.int64)
    else:
        kids = rng.integers(0, keyspace, size=n, dtype=np.int64)
    if cells:
        width = keyspace // cells
        row_txn = np.concatenate([
            np.arange(t * READS_PER_TXN, dtype=np.int64) // READS_PER_TXN,
            np.arange(t * WRITES_PER_TXN, dtype=np.int64) // WRITES_PER_TXN])
        kids = (row_txn % cells) * width + kids % width
    # Key bytes: b"k" + 14 decimal digits (the proxy hands the resolver raw
    # byte keys; forming digests from them is the backend's timed work, but
    # the byte matrix itself is workload generation).
    mat = np.empty((n, 16), dtype=np.uint8)
    mat[:, 0] = ord("k")
    mat[:, 15] = 0
    x = kids.copy()
    for d in range(14):
        mat[:, 14 - d] = 48 + x % 10
        x //= 10
    snaps = np.maximum(
        prev - rng.integers(0, 2 * VERSIONS_PER_BATCH, size=t), 0)

    nr = t * READS_PER_TXN
    begin = encode_fixed(mat[:, :15])          # key, marker 15
    end = encode_fixed(mat)                    # key + b"\x00", marker 16
    enc = EncodedBatch(
        n_txns=t,
        t_snap=snaps.astype(np.int64),
        t_has_reads=np.ones((t,), dtype=bool),
        r_txn=(np.arange(nr, dtype=np.int32) // READS_PER_TXN),
        r_begin=begin[:, :nr], r_end=end[:, :nr],
        w_txn=np.arange(t, dtype=np.int32),
        w_begin=begin[:, nr:], w_end=end[:, nr:],
        all_point=True,
    )
    return enc, kids, snaps


def to_transactions(kids: np.ndarray, snaps: np.ndarray):
    """Object form of the same batch for the CPU oracle."""
    from foundationdb_tpu.txn.types import CommitTransactionRef, KeyRange
    keys = [b"k%014d" % int(k) for k in kids]
    nr = TXNS_PER_BATCH * READS_PER_TXN
    txns = []
    for t in range(TXNS_PER_BATCH):
        # Same layout as gen_batch: rows [0, 2T) are reads (txn = row//2),
        # rows [2T, 3T) are writes (txn = row - 2T).
        reads = []
        for j in range(READS_PER_TXN):
            k = keys[t * READS_PER_TXN + j]
            reads.append(KeyRange(k, k + b"\x00"))
        writes = []
        for j in range(WRITES_PER_TXN):
            k = keys[nr + t * WRITES_PER_TXN + j]
            writes.append(KeyRange(k, k + b"\x00"))
        txns.append(CommitTransactionRef(
            read_conflict_ranges=reads, write_conflict_ranges=writes,
            mutations=[], read_snapshot=int(snaps[t])))
    return txns


def run_parity_regime(make_cs, batches, floor, label: str):
    """Resolve `batches` on a fresh backend AND the oracle; assert verdict
    parity on every batch; return the observed commit rate."""
    from foundationdb_tpu.conflict.oracle import OracleConflictSet
    from foundationdb_tpu.txn.types import CommitResult

    cs = make_cs()
    oracle = OracleConflictSet(0)
    committed = 0
    n = 0
    committed_code = int(CommitResult.COMMITTED)
    for v, enc, kids, snaps in batches:
        got = cs.resolve_encoded_async(enc, v, floor(v)).wait_codes()
        want = oracle.resolve(to_transactions(kids, snaps), v, floor(v))
        want_codes = np.asarray([int(r) for r in want], dtype=np.int8)
        bad = int(np.sum(got != want_codes))
        if bad:
            print(f"PARITY FAILURE ({label}): {bad} verdicts differ "
                  "from the CPU oracle", file=sys.stderr)
            sys.exit(1)
        committed += int(np.sum(got == committed_code))
        n += enc.n_txns
    return committed / max(n, 1)


def run_heat_gate(make_cs, batches, floor, repeats: int = 3):
    """ISSUE 8 overhead gate: the SUPERVISED conflict path — where heat
    telemetry's only hot-path costs live (the mirror's knob-bounded
    abort attribution in conflict/supervisor.py plus the resolver-style
    tracker feed emulated here) — measured on an identical stream with
    HEAT_TELEMETRY_ENABLED off and on.  The stream is short, so the two
    modes are INTERLEAVED `repeats` times and each mode keeps its best
    elapsed (min filters scheduler/allocator noise that would otherwise
    dwarf a sub-percent delta).  Returns a JSON-able dict with both
    ranges/s figures and the overhead percentage; the acceptance gate
    wants |overhead| <= 2%.  Shapes match the main stream, so the
    programs are already compiled."""
    from foundationdb_tpu.conflict.heat import ConflictHeatTracker
    from foundationdb_tpu.conflict.supervisor import SupervisedConflictSet
    from foundationdb_tpu.core.knobs import server_knobs

    prepared = [(v, enc, to_transactions(kids, snaps))
                for v, enc, kids, snaps in batches]
    n_ranges = sum(enc.n_ranges for _v, enc, _t in prepared)
    knobs = server_knobs()
    saved = knobs.HEAT_TELEMETRY_ENABLED
    best = {False: float("inf"), True: float("inf")}
    try:
        for _rep in range(max(1, repeats)):
            for enabled in (False, True):
                knobs.HEAT_TELEMETRY_ENABLED = enabled
                sup = SupervisedConflictSet(make_cs)
                tracker = ConflictHeatTracker()
                t0 = time.perf_counter()
                for v, enc, txns in prepared:
                    h = sup.resolve_encoded_async(enc, v, floor(v),
                                                  transactions=txns)
                    h.wait_codes()
                    # _sample_batch load sampling runs in the resolver
                    # regardless of the knob (it predates the heat
                    # plane), so BOTH modes pay it; the knob-gated delta
                    # is the conflict-attribution feed below.
                    for tr in txns:
                        for r in tr.read_conflict_ranges + \
                                tr.write_conflict_ranges:
                            tracker.sample_load(r.begin, r.end)
                    if enabled:
                        # The resolver's knob-gated feed: only the
                        # attributed (budget-bounded) sample is recorded
                        # — the device path's cost stays bounded
                        # regardless of the batch's abort rate.
                        for i, ranges in h.attribution.items():
                            for b, e in ranges:
                                tracker.record_conflict(b, e)
                best[enabled] = min(best[enabled],
                                    time.perf_counter() - t0)
    finally:
        knobs.HEAT_TELEMETRY_ENABLED = saved
    off, on = n_ranges / best[False], n_ranges / best[True]
    overhead = (off - on) / off * 100.0 if off else 0.0
    return {"disabled_ranges_per_s": round(off, 1),
            "enabled_ranges_per_s": round(on, 1),
            "overhead_pct": round(overhead, 2),
            "batches": len(prepared), "repeats": max(1, repeats)}


class _EmulatedHandle:
    """d2h half of the tunnel emulation: the fetch-lane sleep occupies
    the emulated link before the (instant, XLA-CPU) verdict fetch."""

    def __init__(self, inner):
        self._inner = inner

    def wait_codes(self):
        time.sleep(TUNNEL_D2H_S)
        return self._inner.wait_codes()

    def wait(self):
        time.sleep(TUNNEL_D2H_S)
        return self._inner.wait()


class TunnelEmulatedBackend:
    """Raw device backend behind the ROUND-5 MEASURED axon tunnel
    transfer profile (see TUNNEL_* constants), as sleeps on the
    supervisor's dispatch/fetch lanes: h2d = packed bytes / 8 MB/s before
    the step enqueue, d2h = 33 ms before the verdict fetch.  Fallback
    depth-sweep only, labeled in the JSON — never a headline figure."""

    def __init__(self, inner):
        self._inner = inner

    def resolve_encoded_async(self, enc, now, new_oldest_version=None):
        time.sleep(enc.n_ranges * TUNNEL_BYTES_PER_RANGE /
                   (TUNNEL_H2D_MB_S * 1e6))
        return _EmulatedHandle(self._inner.resolve_encoded_async(
            enc, now, new_oldest_version))

    def __getattr__(self, name):
        return getattr(self._inner, name)


def run_depth_sweep(make_cs, floor, emulate_tunnel):
    """Supervised depth sweep (ISSUE 6): the SAME batch stream through
    the pipelined SupervisedConflictSet at CONFLICT_PIPELINE_DEPTH =
    1..SWEEP_MAX_DEPTH.  In-order verdict delivery makes the pipeline
    invisible to results, so cross-depth verdicts are asserted
    bit-identical (parity vs the oracle rides the main regimes, which
    compare the identical device kernels).  Returns {depth: ranges/s}."""
    global TXNS_PER_BATCH, CAPACITY, DELTA_CAPACITY
    from foundationdb_tpu.conflict.supervisor import SupervisedConflictSet
    from foundationdb_tpu.core.knobs import server_knobs

    knobs = server_knobs()
    saved_depth = knobs.CONFLICT_PIPELINE_DEPTH
    saved_txns = TXNS_PER_BATCH
    saved_caps = (CAPACITY, DELTA_CAPACITY)
    if SWEEP_TXNS:
        TXNS_PER_BATCH = SWEEP_TXNS
    if SWEEP_CAPACITY:
        # The sweep's batch size needs its own window sizing; the main
        # phases keep theirs (round-over-round comparability).
        CAPACITY = SWEEP_CAPACITY
        DELTA_CAPACITY = SWEEP_DELTA_CAPACITY

    def make_device(oldest_version=0):
        dev = make_cs(oldest_version)
        return TunnelEmulatedBackend(dev) if emulate_tunnel else dev

    try:
        rng = np.random.default_rng(909)
        stream = []
        version = 1_000
        for _ in range(N_SWEEP_WARMUP + N_SWEEP):
            prev, version = version, version + VERSIONS_PER_BATCH
            enc, kids, snaps = gen_batch(rng, version, prev)
            stream.append((version, enc, to_transactions(kids, snaps)))
        measured_ranges = sum(e.n_ranges for _v, e, _t in
                              stream[N_SWEEP_WARMUP:])
        per_depth = {}
        ref_codes = None
        for depth in range(1, SWEEP_MAX_DEPTH + 1):
            knobs.CONFLICT_PIPELINE_DEPTH = depth
            sup = SupervisedConflictSet(make_device)
            for v, enc, txns in stream[:N_SWEEP_WARMUP]:
                sup.resolve_encoded_async(
                    enc, v, floor(v), transactions=txns).wait_codes()
            handles = []
            t0 = time.perf_counter()
            for v, enc, txns in stream[N_SWEEP_WARMUP:]:
                handles.append(sup.resolve_encoded_async(
                    enc, v, floor(v), transactions=txns))
            codes = np.concatenate([h.wait_codes() for h in handles])
            dt = time.perf_counter() - t0
            if sup.degraded or sup.stats["fallback_batches"]:
                print(f"depth {depth}: supervised sweep degraded to the "
                      "mirror (not a device measurement)", file=sys.stderr)
                sys.exit(1)
            if ref_codes is None:
                ref_codes = codes
            elif not np.array_equal(ref_codes, codes):
                print("PARITY FAILURE: depth-sweep verdicts diverge "
                      f"between depth 1 and depth {depth}", file=sys.stderr)
                sys.exit(1)
            per_depth[str(depth)] = round(measured_ranges / dt, 1)
            _phase(f"supervised depth {depth}: "
                   f"{measured_ranges / dt:.0f} ranges/s "
                   f"(stalls={sup.stats['pipeline_stalls']})")
        return per_depth
    finally:
        knobs.CONFLICT_PIPELINE_DEPTH = saved_depth
        TXNS_PER_BATCH = saved_txns
        CAPACITY, DELTA_CAPACITY = saved_caps


def run_config5():
    """BASELINE config 5: fill the mesh-sharded window (equi-depth key
    splits, pipelined supervisor) to >= CONFIG5_TARGET_RANGES in-flight
    ranges with the floor frozen, then prove the window answers with an
    at-capacity conflict probe.  Returns the JSON "config5" record."""
    global TXNS_PER_BATCH
    from collections import deque

    import jax

    from foundationdb_tpu.conflict.supervisor import SupervisedConflictSet
    from foundationdb_tpu.core.knobs import server_knobs
    from foundationdb_tpu.parallel.sharded_resolver import (
        ShardedTpuConflictSet)
    from foundationdb_tpu.parallel.sharded_window import (
        make_conflict_mesh, splits_from_sample)
    from foundationdb_tpu.txn.types import (CommitResult,
                                            CommitTransactionRef, KeyRange)

    knobs = server_knobs()
    saved_txns, TXNS_PER_BATCH = TXNS_PER_BATCH, CONFIG5_TXNS
    saved_depth = knobs.CONFLICT_PIPELINE_DEPTH
    depth = min(SWEEP_MAX_DEPTH, 3)
    knobs.CONFLICT_PIPELINE_DEPTH = depth
    try:
        mesh = make_conflict_mesh(jax.devices())
        n_kr = int(mesh.shape["kr"])
        rng = np.random.default_rng(5055)
        # Equi-depth splits from a workload sample: bench keys share the
        # b"k000..." prefix, so lane-0 splits would land EVERYTHING on
        # one shard and void the capacity multiplier.
        sample_enc, _k, _s = gen_batch(rng, 2_000, 1_000,
                                       keyspace=KEYSPACE_LOW, zipf=False)
        splits = splits_from_sample(sample_enc.w_begin, n_kr)

        def make_device(oldest_version=0):
            return ShardedTpuConflictSet(
                mesh, oldest_version, capacity=CONFIG5_CAPACITY // n_kr,
                delta_capacity=CONFIG5_DELTA // n_kr, splits=splits)

        sup = SupervisedConflictSet(make_device)
        _phase(f"config5: filling the {n_kr}-shard window to >= "
               f"{CONFIG5_TARGET_RANGES} in-flight ranges "
               f"({CONFIG5_TXNS} txns/batch, depth {depth})")
        committed_code = int(CommitResult.COMMITTED)
        version = 2_000

        def next_batch():
            nonlocal version
            prev, version = version, version + VERSIONS_PER_BATCH
            enc, kids, snaps = gen_batch(rng, version, prev,
                                         keyspace=KEYSPACE_LOW, zipf=False)
            return version, enc, to_transactions(kids, snaps), kids

        # Warmup/compile batch (also the probe target below).
        v, enc, txns, probe_kids = next_batch()
        codes = sup.resolve_encoded_async(
            enc, v, 0, transactions=txns).wait_codes()
        inserted = int(np.sum(codes == committed_code))
        n_ranges = 0
        batches = 1
        inflight = deque()

        def drain_one():
            nonlocal inserted, n_ranges
            enc_d, h = inflight.popleft()
            c = h.wait_codes()
            inserted += int(np.sum(c == committed_code))
            n_ranges += enc_d.n_ranges

        t0 = time.perf_counter()
        while inserted < CONFIG5_TARGET_RANGES:
            v, enc, txns, _kids = next_batch()
            inflight.append((enc, sup.resolve_encoded_async(
                enc, v, 0, transactions=txns)))
            batches += 1
            while len(inflight) >= depth:
                drain_one()
        while inflight:
            drain_one()
        dt = time.perf_counter() - t0
        if sup.degraded or sup.stats["fallback_batches"]:
            # fallback_batches too: a transient degrade-then-repromote
            # mid-fill would contaminate the fill rate with mirror-speed
            # batches while leaving sup.degraded False at the end.
            print("config5: supervised backend degraded mid-fill",
                  file=sys.stderr)
            sys.exit(1)
        shard_sizes = sup.device.shard_sizes()
        segments = sup.segment_count()      # exact mirror census
        # At-capacity probe: re-read the first batch's COMMITTED write
        # keys at snapshot 0 — every one must conflict against the
        # filled window.  Aborted txns (intra-batch read-write
        # collisions) never inserted their write, so their keys are
        # filtered out (kids[nr + i] is txn i's single write key, and
        # codes[i] is its verdict — gen_batch layout).
        nr = TXNS_PER_BATCH * READS_PER_TXN
        committed_writes = np.asarray(probe_kids[nr:])[
            np.asarray(codes) == committed_code]
        probe = [CommitTransactionRef(
                    read_snapshot=0,
                    read_conflict_ranges=[KeyRange(k, k + b"\x00")])
                 for k in (b"k%014d" % int(x)
                           for x in committed_writes[:2048])]
        verdicts = sup.resolve(probe, version + VERSIONS_PER_BATCH, 0)
        conflicts = sum(1 for x in verdicts if x == CommitResult.CONFLICT)
        rate = n_ranges / dt if dt > 0 else 0.0
        _phase(f"config5: {inserted} in-flight ranges, shards "
               f"{shard_sizes}, fill {rate:.0f} ranges/s, probe "
               f"{conflicts}/{len(probe)} conflicts")
        if conflicts != len(probe):
            print("config5: at-capacity probe missed conflicts",
                  file=sys.stderr)
            sys.exit(1)
        spread = sum(1 for s in shard_sizes if s > 1)
        return {
            "in_flight_ranges": inserted,
            "window_segments": segments,
            "shard_base_sizes": shard_sizes,
            "shards_holding_state": spread,
            "n_shards": n_kr,
            "fill_ranges_per_s": round(rate, 1),
            "fill_batches": batches,
            "txns_per_batch": CONFIG5_TXNS,
            "pipeline_depth": depth,
            "probe_conflicts": conflicts,
        }
    finally:
        TXNS_PER_BATCH = saved_txns
        knobs.CONFLICT_PIPELINE_DEPTH = saved_depth


def _rsweep_fragment(enc, kids, r: int, n_res: int, keyspace: int,
                     cells: int):
    """Resolver r's fragment of a partition-aligned encoded batch: the
    commit-proxy clip in columnar form.  ALL txns stay in the fragment
    (t_snap is full width — the broadcast that keeps every resolver's
    version window advancing); only the range columns are filtered to
    the cells resolver r owns.  Returns (fragment, read_mask, write_mask)
    so the caller can build the matching mirror transactions."""
    from foundationdb_tpu.conflict.encoded import EncodedBatch
    nr = enc.r_txn.shape[0]
    width = keyspace // cells
    row_res = (kids // width) * n_res // cells
    rm = row_res[:nr] == r
    wm = row_res[nr:] == r
    t_has = np.zeros(enc.n_txns, dtype=bool)
    t_has[enc.r_txn[rm]] = True
    frag = EncodedBatch(
        n_txns=enc.n_txns, t_snap=enc.t_snap, t_has_reads=t_has,
        r_txn=enc.r_txn[rm], r_begin=enc.r_begin[:, rm],
        r_end=enc.r_end[:, rm],
        w_txn=enc.w_txn[wm], w_begin=enc.w_begin[:, wm],
        w_end=enc.w_end[:, wm], all_point=enc.all_point)
    return frag, rm, wm


def _rsweep_fragment_txns(kids, snaps, rm, wm, n_txns: int):
    """Object form of one resolver fragment (the supervised backend's
    exact mirror input): every txn present, ranges clipped per the row
    masks — the same shape the resolver receives from the proxy."""
    from foundationdb_tpu.txn.types import CommitTransactionRef, KeyRange
    nr = n_txns * READS_PER_TXN
    keys = [b"k%014d" % int(k) for k in kids]
    txns = []
    for i in range(n_txns):
        reads = []
        for j in range(READS_PER_TXN):
            row = i * READS_PER_TXN + j
            if rm[row]:
                reads.append(KeyRange(keys[row], keys[row] + b"\x00"))
        writes = []
        for j in range(WRITES_PER_TXN):
            row = i * WRITES_PER_TXN + j
            if wm[row]:
                k = keys[nr + row]
                writes.append(KeyRange(k, k + b"\x00"))
        txns.append(CommitTransactionRef(
            read_conflict_ranges=reads, write_conflict_ranges=writes,
            mutations=[], read_snapshot=int(snaps[i])))
    return txns


def run_resolver_sweep(ns=RSWEEP_NS, txns: int = RSWEEP_TXNS,
                       n_batches: int = RSWEEP_BATCHES,
                       keyspace: int = RSWEEP_KEYSPACE,
                       capacity: int = RSWEEP_CAPACITY,
                       delta_capacity: int = RSWEEP_DELTA,
                       seed: int = 707) -> dict:
    """Multi-resolver sweep (ISSUE 7): see the RSWEEP_* constants doc.
    Returns the JSON record; asserts abort-set parity — the merged
    (min-across-resolvers) verdicts of every N must be bit-identical to
    the single-resolver baseline on the same seeded stream."""
    global TXNS_PER_BATCH
    from foundationdb_tpu.conflict.supervisor import SupervisedConflictSet
    from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet

    saved_txns, TXNS_PER_BATCH = TXNS_PER_BATCH, txns
    try:
        rng = np.random.default_rng(seed)
        stream = []
        version = 0
        for _ in range(n_batches):
            prev, version = version, version + VERSIONS_PER_BATCH
            enc, kids, snaps = gen_batch(rng, version, prev,
                                         keyspace=keyspace,
                                         cells=RSWEEP_CELLS)
            stream.append((version, enc, kids, snaps))

        def floor(v):
            return max(0, v - WINDOW_BATCHES * VERSIONS_PER_BATCH)

        per_n = {}
        baseline = None
        for n_res in ns:
            assert RSWEEP_CELLS % n_res == 0, \
                f"resolver count {n_res} must divide {RSWEEP_CELLS} cells"
            # Fragment prep (the proxy's clip) is workload assembly, not
            # resolution — excluded from the timed section.
            frags = []
            for r in range(n_res):
                rows = []
                for v, enc, kids, snaps in stream:
                    frag, rm, wm = _rsweep_fragment(
                        enc, kids, r, n_res, keyspace, RSWEEP_CELLS)
                    rows.append((v, frag, _rsweep_fragment_txns(
                        kids, snaps, rm, wm, enc.n_txns)))
                frags.append(rows)
            elapsed = [0.0] * n_res
            timed_ranges = [0] * n_res
            codes_by_batch = [None] * len(stream)
            for r in range(n_res):
                sup = SupervisedConflictSet(
                    lambda oldest_version=0: TpuConflictSet(
                        oldest_version, capacity=capacity,
                        delta_capacity=delta_capacity))
                for bi, (v, frag, ftxns) in enumerate(frags[r]):
                    t0 = time.perf_counter()
                    codes = sup.resolve_encoded_async(
                        frag, v, floor(v),
                        transactions=ftxns).wait_codes()
                    dt = time.perf_counter() - t0
                    if bi >= RSWEEP_WARMUP:
                        elapsed[r] += dt
                        timed_ranges[r] += frag.n_ranges
                    merged = codes_by_batch[bi]
                    codes_by_batch[bi] = (codes if merged is None
                                          else np.minimum(merged, codes))
                if sup.degraded or sup.stats["fallback_batches"]:
                    print(f"resolver sweep: backend degraded (N={n_res}, "
                          f"r={r})", file=sys.stderr)
                    sys.exit(1)
            if baseline is None:
                baseline = codes_by_batch
            else:
                for bi, (want, got) in enumerate(
                        zip(baseline, codes_by_batch)):
                    assert np.array_equal(want, got), (
                        f"PARITY FAILURE: N={n_res} merged verdicts "
                        f"diverge from the 1-resolver baseline "
                        f"(batch {bi})")
            agg = (sum(timed_ranges) / max(elapsed)) if max(elapsed) else 0.0
            per_n[str(n_res)] = {
                "per_resolver_ranges_per_s": [
                    round(timed_ranges[r] / elapsed[r], 1)
                    if elapsed[r] else 0.0 for r in range(n_res)],
                "per_resolver_ranges": timed_ranges,
                "aggregate_ranges_per_s": round(agg, 1),
            }
            _phase(f"resolver sweep N={n_res}: aggregate "
                   f"{agg:,.0f} ranges/s")
        agg1 = per_n[str(ns[0])]["aggregate_ranges_per_s"]
        return {
            "metric": "multi_resolver_aggregate_ranges_per_s",
            "sweep": per_n,
            "parity": "ok",
            "txns_per_batch": txns,
            "batches": n_batches,
            "warmup_batches": RSWEEP_WARMUP,
            "keyspace": keyspace,
            "cells": RSWEEP_CELLS,
            "capacity_per_resolver": capacity,
            "scaling_vs_1": {
                k: round(v["aggregate_ranges_per_s"] / agg1, 3)
                for k, v in per_n.items()} if agg1 else {},
            "aggregate_model": (
                "per-resolver streams timed separately on this host; "
                "aggregate = total_ranges / max(per-resolver elapsed) — "
                "models one resolver role per process, as deployed"),
        }
    finally:
        TXNS_PER_BATCH = saved_txns


def resolver_sweep_main() -> None:
    """`bench.py resolvers` entry: run the multi-resolver sweep and write
    BENCH_r07.json next to this file (plus the JSON line on stdout)."""
    if os.environ.get("JAX_PLATFORMS") == "cpu" or \
            os.environ.get("BENCH_FORCE_FALLBACK") == "1":
        _force_cpu_backend()
    import jax
    doc = run_resolver_sweep()
    doc["jax_backend"] = jax.default_backend()
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_r07.json")
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(json.dumps(doc))


# ---------------------------------------------------------------------------
# Conflict-aware scheduling bench (ISSUE 12, `bench.py sched`).
#
# Host-side model of the three SCHED_* stages around the exact oracle —
# predictor admission (sched/predictor.py, fed the same rows the
# ratekeeper piggybacks), intra-batch reorder (sched/reorder.py), and
# transaction repair as the commit proxy runs it (a SEPARATE follow-up
# batch for the re-stamped txns; sched/repair.py eligibility) — over the
# main bench's high-contention regime (zipf 1.2 point keys, snapshots
# lagging 0-2 batches).  The measured quantity is commit_rate: committed
# original transactions / total original transactions, each counted ONCE
# no matter how often the scheduler defers or repairs it; goodput is
# committed txns per wall second.  Batch size is scaled to SCHED_TXNS so
# the oracle's intra-batch pass fits the budget; the stages-off
# commit_rate of THIS regime is recorded alongside as the in-regime
# baseline for the 0.144 main-regime figure.
# ---------------------------------------------------------------------------

SCHED_TXNS = int(os.environ.get("SCHED_BENCH_TXNS", "8192"))
SCHED_BATCHES = int(os.environ.get("SCHED_BENCH_BATCHES", "13"))
SCHED_WARMUP = min(3, max(0, SCHED_BATCHES - 2))
SCHED_REPEATS = int(os.environ.get("SCHED_BENCH_REPEATS", "2"))
SCHED_KEYSPACE = KEYSPACE            # the main high-contention keyspace
SCHED_TAG_BUCKETS = 64               # declared-tag granularity
SCHED_LOWC_BATCHES = 3


def _sched_tag(txn, keyspace: int) -> str:
    """The transaction's DECLARED tag: a key-prefix bucket of its first
    read (what a real client would declare about its access pattern —
    the identity the GRV predictor dooms)."""
    k = txn.read_conflict_ranges[0].begin
    return "b%02d" % (int(k[1:15]) * SCHED_TAG_BUCKETS // keyspace)


class SchedBenchPipeline:
    """One stages-configuration pass over a shared transaction stream.

    Mirrors the production wiring stage for stage: the predictor sees
    feed rows shaped exactly like ConflictHeatTracker.feed_rows (per-
    range conflicts + 1-in-8 load samples + per-tag attribution), a
    deferred transaction is re-admitted with a FRESH read version after
    at most SCHED_MAX_DEFERRALS waits, reorder runs at batch assembly,
    and repaired transactions are re-stamped at the aborting batch's
    commit version and re-resolved once in a follow-up batch (half a
    batch interval later, like the proxy's repair batch).  Shared txn
    objects are never mutated — re-stamps go through dataclasses.replace
    — so every configuration replays the identical stream."""

    MAX_DEFERRALS = 3

    def __init__(self, predictor_on: bool, reorder_on: bool,
                 repair_on: bool, keyspace: int,
                 max_attempts: int = 1, ladder_on: bool = False) -> None:
        from foundationdb_tpu.conflict.oracle import OracleConflictSet
        from foundationdb_tpu.sched.predictor import ConflictPredictor
        from foundationdb_tpu.sched.repair import RepairLadder
        self.oracle = OracleConflictSet(0)
        self.pred = ConflictPredictor() if predictor_on else None
        self.reorder_on = reorder_on
        self.repair_on = repair_on
        self.keyspace = keyspace
        # Repair ladder (ISSUE 14): TXN_REPAIR_MAX_ATTEMPTS analog +
        # per-range version-clock backoff, mirroring the proxy's
        # RepairLadder wiring exactly.
        self.max_attempts = max(1, int(max_attempts))
        self.ladder = RepairLadder(
            backoff_versions=VERSIONS_PER_BATCH // 4) if ladder_on else None
        self.stats = {"committed": 0, "total": 0, "deferrals": 0,
                      "repairs": 0, "repairs_ok": 0, "backed_off": 0,
                      "reorder_moved": 0}
        self._deferred: list = []
        # Stages-off verdict codes per counted batch (parity guard).
        self.off_codes: list = []

    def _feed_predictor(self, txlist, attr) -> None:
        rows = {}
        for ti, ranges in attr.items():
            tg = _sched_tag(txlist[ti], self.keyspace)
            for b, e in ranges:
                r = rows.setdefault((b, e), [0, 0, {}])
                r[0] += 1
                r[2][tg] = r[2].get(tg, 0) + 1
        for j in range(0, len(txlist), 8):   # the 1-in-8 load column
            rr = txlist[j].read_conflict_ranges[0]
            r = rows.setdefault((rr.begin, rr.end), [0, 0, {}])
            r[1] += 1
        self.pred.update([(b, e, c, l, tags, {})
                          for (b, e), (c, l, tags) in rows.items()])

    def _resolve(self, entries, version, floor, repair_sink) -> None:
        """One commit batch: reorder -> oracle -> repair collection."""
        import dataclasses as _dc
        from foundationdb_tpu.sched.reorder import moved_count, reorder_batch
        from foundationdb_tpu.sched.repair import repair_eligible
        from foundationdb_tpu.txn.types import CommitResult
        if self.reorder_on and len(entries) > 1:
            order = reorder_batch([e[0] for e in entries], exact_max=2048)
            self.stats["reorder_moved"] += moved_count(order)
            entries = [entries[i] for i in order]
        txlist = [e[0] for e in entries]
        verdicts, _rep = self.oracle.resolve_with_conflicts(
            txlist, version, floor)
        attr = self.oracle.last_attribution
        if self.pred is not None:
            self._feed_predictor(txlist, attr)
        for j, (e, v) in enumerate(zip(entries, verdicts)):
            txn, attempts, _defers, counted = e
            if v == CommitResult.COMMITTED:
                if counted:
                    self.stats["committed"] += 1
                    if attempts:
                        self.stats["repairs_ok"] += 1
                if attempts and self.ladder is not None:
                    # A repaired commit proves the range repairable
                    # again: drop its rungs (proxy reply-loop analog).
                    self.ladder.note_success(
                        (r.begin, r.end) for r in txn.read_conflict_ranges)
            elif v == CommitResult.CONFLICT and self.repair_on:
                culprits = attr.get(j) or []
                if attempts >= self.max_attempts and culprits and \
                        self.ladder is not None:
                    # Budget exhausted and STILL conflicted: back the
                    # culprit range off (proxy _collect_repairs analog);
                    # intermediate rungs keep climbing freely.
                    self.ladder.note_failure(culprits, version)
                if not repair_eligible(txn, culprits, j in attr,
                                       attempts, self.max_attempts):
                    continue
                if attempts > 0 and self.ladder is not None and \
                        not self.ladder.should_attempt(culprits, version):
                    # Backoff gates ladder CLIMBS only; first repairs
                    # stay unconditional (proxy analog).
                    self.stats["backed_off"] += 1
                    continue
                e[0] = _dc.replace(txn, read_snapshot=version)
                e[1] = attempts + 1
                self.stats["repairs"] += 1
                repair_sink.append(e)
        return verdicts

    def run_batch(self, prev, version, floor, txns, counted: bool):
        """One stream step: admission over deferred + fresh arrivals,
        the main commit batch, then the repair follow-up batch."""
        import dataclasses as _dc
        fresh = [[t, 0, 0, counted] for t in txns or []]
        if counted:
            self.stats["total"] += len(fresh)
        arrivals, self._deferred = self._deferred + fresh, []
        admitted = []
        for e in arrivals:
            txn, attempts, defers, _counted = e
            if self.pred is not None and attempts == 0 and \
                    defers < self.MAX_DEFERRALS and \
                    self.pred.is_doomed((_sched_tag(txn, self.keyspace),)):
                e[2] = defers + 1
                self.stats["deferrals"] += 1
                self._deferred.append(e)
                continue
            if defers:
                # Deferred requests acquire their read version at
                # ADMISSION (the whole point of the delay): fresh as of
                # the last committed batch.
                e[0] = _dc.replace(txn, read_snapshot=prev)
            admitted.append(e)
        repairs: list = []
        verdicts = None
        if admitted:
            verdicts = self._resolve(admitted, version, floor, repairs)
        # Repair rungs: each failed re-resolve may retry once more (up to
        # max_attempts) at a later sub-batch version — with max_attempts
        # = 1 this is the original single follow-up batch.
        rung = 1
        step = VERSIONS_PER_BATCH // (self.max_attempts + 1)
        while repairs and rung <= self.max_attempts:
            nxt: list = []
            self._resolve(repairs, version + rung * step, floor, nxt)
            repairs = nxt
            rung += 1
        return admitted, verdicts

    def drained(self) -> bool:
        return not self._deferred


def run_sched_config(stream, keyspace, predictor_on, reorder_on,
                     repair_on, max_attempts=1, ladder_on=False):
    """One full pass of the shared stream through a stages
    configuration; returns (stats, elapsed_s, off_verdict_codes)."""
    pipe = SchedBenchPipeline(predictor_on, reorder_on, repair_on,
                              keyspace, max_attempts=max_attempts,
                              ladder_on=ladder_on)
    off_codes = []
    t0 = time.perf_counter()
    steps = list(stream) + [(None, None, None, False)] * 4  # drain carries
    version = None
    for prev, version_, txns, counted in steps:
        if version_ is None:
            if pipe.drained():
                break
            prev, version_ = version, version + VERSIONS_PER_BATCH
            txns, counted = None, False
        version = version_
        floor = max(0, version - WINDOW_BATCHES * VERSIONS_PER_BATCH)
        admitted, verdicts = pipe.run_batch(prev, version, floor, txns,
                                            counted)
        if verdicts is not None and not (predictor_on or reorder_on or
                                         repair_on):
            off_codes.append(np.asarray([int(v) for v in verdicts],
                                        dtype=np.int8))
    elapsed = time.perf_counter() - t0
    return pipe.stats, elapsed, off_codes


def run_sched_bench() -> dict:
    """The `bench.py sched` measurement: the four stages configurations
    (off / predictor / reorder / repair / all) interleaved best-of over
    one shared high-contention stream, the stages-off parity guard, the
    low-contention regime with every stage on, and (budget permitting)
    a conflict-plane ranges/s spot check against the round-8 figure."""
    global TXNS_PER_BATCH
    from foundationdb_tpu.conflict.oracle import OracleConflictSet
    from foundationdb_tpu.txn.types import CommitResult

    saved_txns, TXNS_PER_BATCH = TXNS_PER_BATCH, SCHED_TXNS
    try:
        rng = np.random.default_rng(4242)
        stream = []
        version = 1_000
        for i in range(SCHED_BATCHES):
            prev, version = version, version + VERSIONS_PER_BATCH
            _enc, kids, snaps = gen_batch(rng, version, prev,
                                          keyspace=SCHED_KEYSPACE)
            stream.append((prev, version, to_transactions(kids, snaps),
                           i >= SCHED_WARMUP))
        _phase(f"sched stream ready: {SCHED_BATCHES} batches x "
               f"{SCHED_TXNS} txns")

        configs = [("off", (False, False, False)),
                   ("predictor", (True, False, False)),
                   ("reorder", (False, True, False)),
                   ("repair", (False, False, True)),
                   ("all", (True, True, True)),
                   # Repair LADDER (ISSUE 14): bounded multi-attempt
                   # re-resolution with per-range version-clock backoff
                   # (TXN_REPAIR_MAX_ATTEMPTS=3 analog) — alone and on
                   # top of every other stage.
                   ("ladder", (False, False, True, 3, True)),
                   ("all+ladder", (True, True, True, 3, True))]
        best = {}
        for rep in range(max(1, SCHED_REPEATS)):
            for name, cfg in configs:
                stats, elapsed, off_codes = run_sched_config(
                    stream, SCHED_KEYSPACE, *cfg)
                cur = best.get(name)
                if cur is not None and cur["stats"] != stats:
                    print(f"sched: nondeterministic commit accounting "
                          f"in config {name!r}", file=sys.stderr)
                    sys.exit(1)
                if cur is None or elapsed < cur["elapsed"]:
                    best[name] = {"stats": stats, "elapsed": elapsed,
                                  "off_codes": off_codes}
                _phase(f"sched rep{rep} {name}: commit_rate="
                       f"{stats['committed'] / max(stats['total'], 1):.3f}"
                       f" ({elapsed:.1f}s)")

        # Knobs-off parity guard: the stages-off pipeline's verdicts must
        # be bit-identical to a plain oracle pass over the same stream —
        # the bench-side face of the abort-set parity battery.
        oracle = OracleConflictSet(0)
        for bi, (prev, v, txns, _c) in enumerate(stream):
            floor = max(0, v - WINDOW_BATCHES * VERSIONS_PER_BATCH)
            want = np.asarray(
                [int(r) for r in oracle.resolve(txns, v, floor)],
                dtype=np.int8)
            got = best["off"]["off_codes"][bi]
            if not np.array_equal(want, got):
                print(f"PARITY FAILURE: stages-off sched pipeline "
                      f"diverges from the plain oracle (batch {bi})",
                      file=sys.stderr)
                sys.exit(1)

        def rate(name):
            s = best[name]["stats"]
            return s["committed"] / max(s["total"], 1)

        def goodput(name):
            s = best[name]["stats"]
            return s["committed"] / max(best[name]["elapsed"], 1e-9)

        # Low-contention regime, every stage ON: the scheduler must be
        # invisible when there is nothing to schedule around.
        rng_low = np.random.default_rng(777)
        low_stream = []
        version = 1_000
        for i in range(SCHED_LOWC_BATCHES):
            prev, version = version, version + VERSIONS_PER_BATCH
            _enc, kids, snaps = gen_batch(rng_low, version, prev,
                                          keyspace=KEYSPACE_LOW,
                                          zipf=False)
            low_stream.append((prev, version,
                               to_transactions(kids, snaps), True))
        low_stats, _el, _oc = run_sched_config(
            low_stream, KEYSPACE_LOW, True, True, True, 3, True)
        commit_rate_low = low_stats["committed"] / max(
            low_stats["total"], 1)
        _phase(f"sched low-contention (all on): {commit_rate_low:.3f}")

        doc = {
            "metric": "sched_commit_rate",
            "regime": {"txns_per_batch": SCHED_TXNS,
                       "batches": SCHED_BATCHES,
                       "warmup_batches": SCHED_WARMUP,
                       "keyspace": SCHED_KEYSPACE,
                       "zipf": 1.2,
                       "repeats": max(1, SCHED_REPEATS)},
            "commit_rate": {name: round(rate(name), 4)
                            for name, _ in configs},
            "goodput_committed_per_s": {
                name: round(goodput(name), 1) for name, _ in configs},
            "stage_counters": {name: best[name]["stats"]
                               for name, _ in configs},
            "vs_off": {name: (round(rate(name) / rate("off"), 3)
                              if rate("off") else None)
                       for name, _ in configs},
            "vs_main_regime_baseline_0144": round(
                rate("all") / 0.144, 3),
            "commit_rate_low": round(commit_rate_low, 4),
            "parity": "ok",
        }
        if rate("all") < 1.5 * 0.144:
            print(f"# WARNING: stages-on commit_rate {rate('all'):.3f} "
                  "below the 1.5x 0.144 acceptance floor",
                  file=sys.stderr)
        if commit_rate_low < 0.8:
            print(f"low-contention regime degenerate under sched: "
                  f"{commit_rate_low:.3f}", file=sys.stderr)
            sys.exit(1)
        return doc
    finally:
        TXNS_PER_BATCH = saved_txns


def run_sched_conflict_plane() -> dict:
    """Round-8-comparable conflict-plane spot check: a short main-regime
    supervised stream (same shapes/knobs as run_heat_gate's measured
    path), so BENCH_r09 carries a ranges/s figure directly against
    BENCH_r08's — the scheduler must not have moved the conflict core."""
    global TXNS_PER_BATCH
    from foundationdb_tpu.conflict.supervisor import SupervisedConflictSet
    from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet

    saved_txns = TXNS_PER_BATCH
    TXNS_PER_BATCH = int(os.environ.get("SCHED_BENCH_PLANE_TXNS",
                                        str(TXNS_PER_BATCH)))
    try:
        rng = np.random.default_rng(909)
        stream = []
        version = 1_000
        for _ in range(5):
            prev, version = version, version + VERSIONS_PER_BATCH
            enc, kids, snaps = gen_batch(rng, version, prev)
            stream.append((version, enc, to_transactions(kids, snaps)))

        def floor(v):
            return max(0, v - WINDOW_BATCHES * VERSIONS_PER_BATCH)

        sup = SupervisedConflictSet(
            lambda oldest_version=0: TpuConflictSet(
                oldest_version, capacity=CAPACITY,
                delta_capacity=DELTA_CAPACITY))
        # First batch is compile/warm; the rest are measured.
        v0, enc0, txns0 = stream[0]
        sup.resolve_encoded_async(enc0, v0, floor(v0),
                                  transactions=txns0).wait_codes()
        n_ranges = 0
        t0 = time.perf_counter()
        for v, enc, txns in stream[1:]:
            sup.resolve_encoded_async(enc, v, floor(v),
                                      transactions=txns).wait_codes()
            n_ranges += enc.n_ranges
        dt = time.perf_counter() - t0
        if sup.degraded or sup.stats["fallback_batches"]:
            print("sched conflict-plane check degraded to the mirror",
                  file=sys.stderr)
            return {"skipped": "supervised backend degraded"}
        return {"ranges_per_s": round(n_ranges / dt, 1),
                "batches": len(stream) - 1,
                "txns_per_batch": TXNS_PER_BATCH}
    finally:
        TXNS_PER_BATCH = saved_txns


def sched_main() -> None:
    """`bench.py sched` entry: run the scheduling bench in-process and
    write BENCH_r09.json next to this file (plus the JSON line)."""
    if os.environ.get("JAX_PLATFORMS") == "cpu" or \
            os.environ.get("BENCH_FORCE_FALLBACK") == "1":
        _force_cpu_backend()
    import jax
    doc = run_sched_bench()
    if os.environ.get("SCHED_BENCH_PLANE", "1") != "0" and \
            _remaining_s() > 240:
        _phase("sched conflict-plane spot check (supervised path)")
        doc["conflict_plane"] = run_sched_conflict_plane()
    else:
        doc["conflict_plane"] = {"skipped": "budget/SCHED_BENCH_PLANE"}
    doc["jax_backend"] = jax.default_backend()
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_r09.json")
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(json.dumps(doc))


# ---------------------------------------------------------------------------
# `bench.py e2e` — end-to-end commits/s through the REAL-TCP cluster
# (ISSUE 14): GRV -> commit proxy -> resolver -> TLog -> reply, measured
# knobs-off then all-knobs-on (columnar wire frames + vectorized proxy
# assembly via LIVE dynamic knobs, client GRV batching + read-version
# lease locally) in ONE run, with per-stage latency-band attribution
# from status cluster.latency_statistics.  `bench.py e2e --smoke` is the
# in-process tier-1 parity gate: knobs-off wire images stay legacy,
# columnar-on abort sets match columnar-off on the same stream, and the
# sim pipeline commits bit-identically with vectorized assembly on.
# ---------------------------------------------------------------------------

E2E_PORT_BASE = int(os.environ.get("E2E_PORT_BASE", "47610"))
E2E_PHASE_S = float(os.environ.get("E2E_PHASE_S", "12"))
# Interleaved repeats, best-of per posture (the sched bench's protocol):
# single off/on pairs are hostage to +-30% single-core box noise and to
# cluster aging (snapshot rollovers, growing stores) biasing whichever
# phase runs later.
E2E_REPEATS = int(os.environ.get("E2E_REPEATS", "2"))
# 32 concurrent committers: the client fan-in regime the GRV lease
# targets, and deep enough CPU saturation that the wire/assembly savings
# surface as throughput (8 clients is latency-bound on this 1-core box).
E2E_CLIENTS = int(os.environ.get("E2E_CLIENTS", "32"))
E2E_LEASE_S = float(os.environ.get("E2E_LEASE_S", "0.1"))
E2E_BOOT_TIMEOUT_S = float(os.environ.get("E2E_BOOT_TIMEOUT", "180"))
E2E_VALUE = b"v" * int(os.environ.get("E2E_VALUE_BYTES", "100"))
E2E_KEYS_PER_TXN = int(os.environ.get("E2E_KEYS_PER_TXN", "3"))

# Three stateless workers + a dedicated log-class worker so the commit
# proxy, resolver and TLog land on DISTINCT processes (placement spreads
# the stateless pool away from the master; the log class is FITNESS_BEST
# for TLogs only): the hot proxy->resolver / proxy->TLog RPCs must cross
# real sockets, not take the same-address local-delivery shortcut.
_E2E_NAMES = {"coord0": (E2E_PORT_BASE, "stateless"),
              "stateless1": (E2E_PORT_BASE + 1, "stateless"),
              "stateless2": (E2E_PORT_BASE + 2, "stateless"),
              "log0": (E2E_PORT_BASE + 3, "log"),
              "storage0": (E2E_PORT_BASE + 4, "storage"),
              "storage1": (E2E_PORT_BASE + 5, "storage")}


def _e2e_spawn_cluster(base: str):
    import shutil
    shutil.rmtree(base, ignore_errors=True)
    os.makedirs(base, exist_ok=True)
    repo = os.path.dirname(os.path.abspath(__file__))
    coords = f"127.0.0.1:{E2E_PORT_BASE}"
    config = json.dumps({"n_storage": 2, "min_workers": len(_E2E_NAMES)})
    procs = {}
    for name, (port, pclass) in _E2E_NAMES.items():
        cmd = [sys.executable, "-m", "foundationdb_tpu.server.fdbserver",
               "--port", str(port), "--coordinators", coords,
               "--datadir", os.path.join(base, name), "--class", pclass,
               "--config", config, "--name", name]
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)
        procs[name] = subprocess.Popen(
            cmd, cwd=repo, env=env,
            stdout=open(os.path.join(base, name + ".out"), "wb"),
            stderr=subprocess.STDOUT)
    return procs, coords


def _e2e_ready(loop, db, procs) -> None:
    async def probe():
        from foundationdb_tpu.core.scheduler import delay
        t = db.create_transaction()
        while True:
            dead = {n: p.poll() for n, p in procs.items()
                    if p.poll() is not None}
            if dead:
                raise RuntimeError(f"processes died at boot: {dead}")
            try:
                t.set(b"\x01e2e-boot-probe", b"up")
                await t.commit()
                return True
            except Exception as e:  # noqa: BLE001 — boot races retry
                try:
                    await t.on_error(e)
                except Exception:  # noqa: BLE001
                    t = db.create_transaction()
                    await delay(0.5)

    loop.run_until(loop.spawn(probe()), timeout=E2E_BOOT_TIMEOUT_S)


def _e2e_phase(loop, db, phase: str, phase_s: float, n_clients: int):
    """Drive n_clients concurrent blind-write committers for phase_s;
    returns (counts, elapsed_s)."""
    counts = {"commits": 0, "conflicts": 0, "errors": 0}

    async def committer(cid: int) -> None:
        from foundationdb_tpu.core.error import FdbError
        from foundationdb_tpu.core.scheduler import delay
        from foundationdb_tpu.core.scheduler import now as _lnow
        stop_at = _lnow() + phase_s
        i = 0
        while _lnow() < stop_at:
            t = db.create_transaction()
            # Keys recycle modulo a bounded working set so phases stay
            # comparable as the store ages.  (The original forcing
            # reason — the per-poll DD shard-metrics walk was O(total
            # keys) — is gone: storage answers quiet-shard polls from
            # the incremental _ShardMetricsCache, ISSUE 15.)
            base_key = b"e2e/%02d/%06d" % (cid, i % 1500)
            i += 1
            try:
                # Read-modify-write: the read makes the txn GRV-bound
                # (blind writes never fetch a read version at all), so
                # the measured path is the FULL pipeline — GRV -> read
                # -> commit -> resolve -> TLog.  Keys are
                # committer-unique: zero expected aborts, so the
                # low-contention abort set must stay empty in both
                # phases.
                await t.get(b"e2e/%02d/prev" % cid)
                for j in range(E2E_KEYS_PER_TXN):
                    t.set(base_key + b"/%d" % j, E2E_VALUE)
                t.set(b"e2e/%02d/prev" % cid, base_key)
                await t.commit()
                counts["commits"] += 1
            except FdbError as e:
                if e.name == "not_committed":
                    counts["conflicts"] += 1
                try:
                    await t.on_error(e)
                except Exception:  # noqa: BLE001
                    counts["errors"] += 1
                    await delay(0.2)
            except Exception:  # noqa: BLE001
                counts["errors"] += 1
                await delay(0.2)

    async def drive() -> None:
        from foundationdb_tpu.core.futures import wait_all
        from foundationdb_tpu.core.scheduler import get_event_loop
        actors = [get_event_loop().spawn(committer(c), f"e2e.committer{c}")
                  for c in range(n_clients)]
        await wait_all(actors)

    t0 = time.perf_counter()
    loop.run_until(loop.spawn(drive()), timeout=phase_s * 4 + 120)
    return counts, time.perf_counter() - t0


def _e2e_status(loop, db) -> dict:
    async def go():
        return await db.cluster.get_status()
    return loop.run_until(loop.spawn(go()), timeout=60)


def _e2e_band_totals(status_doc: dict) -> dict:
    bands = (status_doc.get("cluster", {}) or {}).get(
        "latency_statistics", {}) or {}
    return {name: (int(b.get("count", 0)),
                   float(b.get("mean", 0.0)) * int(b.get("count", 0)))
            for name, b in bands.items()}


def _e2e_attribution(before: dict, after: dict) -> dict:
    """Per-stage {count, mean_ms} over one phase, by differencing the
    lifetime band totals captured before/after it."""
    out = {}
    for name, (c2, t2) in sorted(after.items()):
        c1, t1 = before.get(name, (0, 0.0))
        dc = c2 - c1
        if dc > 0:
            out[name] = {"count": dc,
                         "mean_ms": round((t2 - t1) / dc * 1000.0, 3)}
    return out


def _e2e_rpc_counters(status_doc: dict) -> dict:
    groups = (status_doc.get("cluster", {}) or {}).get("metrics", {}) or {}
    return dict(groups.get("Rpc", {}) or {})


def run_e2e() -> dict:
    """Boot the 4-process real-TCP cluster, measure commits/s knobs-off,
    flip every ISSUE-14 knob live (server side via dynamic knobs, client
    side locally), measure again, attribute stages."""
    from foundationdb_tpu.client.database import open_cluster
    from foundationdb_tpu.core.knobs import client_knobs
    from foundationdb_tpu.core.scheduler import set_event_loop
    from foundationdb_tpu.rpc.network import set_network

    base = os.environ.get("E2E_BASEDIR", "/tmp/fdb_e2e_bench")
    procs, coords = _e2e_spawn_cluster(base)
    loop = None
    try:
        time.sleep(2.5)
        dead = {n: p.poll() for n, p in procs.items()
                if p.poll() is not None}
        if dead:
            raise RuntimeError(f"processes died at boot: {dead}")
        loop, db = open_cluster(coords)
        _e2e_ready(loop, db, procs)
        _phase("e2e cluster up; warmup")

        # Fresh worker metrics docs: per-phase stage attribution differs
        # lifetime band totals, so the registration cadence bounds the
        # sampling error at the phase edges.
        async def fast_register():
            from foundationdb_tpu.client.management import set_knob
            await set_knob(db, "WORKER_REGISTER_INTERVAL_S", 2)
        loop.run_until(loop.spawn(fast_register()), timeout=60)
        _e2e_phase(loop, db, "warm", min(3.0, E2E_PHASE_S), 2)

        def settled_status():
            time.sleep(4.5)   # > 2x the registration interval
            return _e2e_status(loop, db)

        ck = client_knobs()

        def set_posture(on: bool) -> None:
            # Server knobs flip LIVE (dynamic-knob path: committed
            # \xff/knobs/ rows, every worker's knob watch applies them
            # without restart or recovery); client knobs locally — AND
            # the local server-knob registry too: this client process
            # encodes CommitTransactionRequest frames itself, and
            # serde's gate reads the LOCAL registry (the dynamic-knob
            # commit only reaches the workers' watches).
            async def flip():
                from foundationdb_tpu.client.management import set_knob
                await set_knob(db, "RPC_COLUMNAR_ENABLED", int(on))
                await set_knob(db, "PROXY_VECTORIZED_ASSEMBLY", int(on))
            loop.run_until(loop.spawn(flip()), timeout=60)
            from foundationdb_tpu.core.knobs import server_knobs
            server_knobs().RPC_COLUMNAR_ENABLED = bool(on)
            ck.GRV_BATCH_ENABLED = bool(on)
            ck.GRV_LEASE_S = E2E_LEASE_S if on else 0.0
            db._grv_lease = None

        # Prove the columnar path actually engages before any ON window
        # is measured (knob watch applied on every server); measuring
        # phases labeled "on" over legacy frames would silently void
        # the comparison, so a dead knob watch is a hard error.
        set_posture(True)
        deadline = time.monotonic() + 30.0
        engaged = False
        while time.monotonic() < deadline:
            _e2e_phase(loop, db, "flip", 1.0, 1)
            rpc = _e2e_rpc_counters(_e2e_status(loop, db))
            if rpc.get("ColumnarFrames", 0) > 0:
                engaged = True
                break
        if not engaged:
            raise RuntimeError(
                "columnar frames never appeared on the wire: dynamic "
                "knob propagation is broken — refusing to measure")

        # Interleaved repeats with ALTERNATING posture order (off,on /
        # on,off / ...): residual drift (store warm-up, box noise) then
        # lands symmetrically on both postures, and the reported figure
        # is the MEAN across reps.  Each phase is bracketed by settled
        # status captures for attribution (kept from its best rep).
        acc = {"off": [], "on": []}
        for rep in range(max(1, E2E_REPEATS)):
            order = (("off", False), ("on", True))
            if rep % 2:
                order = order[::-1]
            for name, on in order:
                set_posture(on)
                _e2e_phase(loop, db, "settle", 1.5, 2)   # posture settles
                s_before = settled_status()
                counts, elapsed = _e2e_phase(
                    loop, db, f"{name}{rep}", E2E_PHASE_S, E2E_CLIENTS)
                s_after = settled_status()
                rate = counts["commits"] / max(elapsed, 1e-9)
                _phase(f"e2e rep{rep} {name}: {rate:.1f} commits/s")
                acc[name].append({"rate": rate, "counts": counts,
                                  "before": s_before, "after": s_after})

        # ---- peer-health-plane overhead gate (ISSUE 18) ----------------
        # The ISSUE-14 knobs stay ON (the measured posture); ONLY
        # PEER_HEALTH_ENABLED flips, interleaved with alternating order
        # like the main comparison.  The plane's hot-path cost is one
        # knob read per request plus the per-peer sampling arithmetic,
        # so the gate wants |overhead| <= 2%.
        from foundationdb_tpu.core.knobs import server_knobs as _sknobs

        def set_health(on: bool) -> None:
            async def flip():
                from foundationdb_tpu.client.management import set_knob
                await set_knob(db, "PEER_HEALTH_ENABLED", int(on))
            loop.run_until(loop.spawn(flip()), timeout=60)
            _sknobs().PEER_HEALTH_ENABLED = bool(on)

        health = {"off": [], "on": []}
        for rep in range(max(1, E2E_REPEATS)):
            order = (("off", False), ("on", True))
            if rep % 2:
                order = order[::-1]
            for name, on in order:
                set_health(on)
                _e2e_phase(loop, db, "hsettle", 1.5, 2)
                counts, elapsed = _e2e_phase(
                    loop, db, f"health-{name}{rep}", E2E_PHASE_S,
                    E2E_CLIENTS)
                health[name].append(counts["commits"] / max(elapsed, 1e-9))
        set_health(True)   # leave the plane in its default posture
        h_off = sum(health["off"]) / len(health["off"])
        h_on = sum(health["on"]) / len(health["on"])
        h_overhead = (h_off - h_on) / h_off * 100.0 if h_off else 0.0
        _phase(f"e2e health gate: off {h_off:.1f} on {h_on:.1f} "
               f"commits/s ({h_overhead:+.2f}%)")
        if abs(h_overhead) > 2.0:
            print(f"# WARNING: peer-health plane overhead "
                  f"{h_overhead:.2f}% above the 2% gate", file=sys.stderr)

        def fold(phases):
            mean = sum(p["rate"] for p in phases) / len(phases)
            top = max(phases, key=lambda p: p["rate"])
            counts = {k: sum(p["counts"][k] for p in phases)
                      for k in phases[0]["counts"]}
            return {"rate": mean, "counts": counts,
                    "before": top["before"], "after": top["after"],
                    "rates": [round(p["rate"], 1) for p in phases]}

        off, on = fold(acc["off"]), fold(acc["on"])
        doc = {
            "metric": "e2e_commits_per_s",
            "unit": "commits/s",
            "regime": {"clients": E2E_CLIENTS, "phase_s": E2E_PHASE_S,
                       "repeats": max(1, E2E_REPEATS),
                       "keys_per_txn": E2E_KEYS_PER_TXN,
                       "value_bytes": len(E2E_VALUE),
                       "processes": len(procs),
                       "lease_s": E2E_LEASE_S,
                       "transport": "real-tcp"},
            "commits_per_s": {"off": round(off["rate"], 1),
                              "on": round(on["rate"], 1)},
            "per_rep": {"off": off["rates"], "on": on["rates"]},
            "speedup": round(on["rate"] / max(off["rate"], 1e-9), 3),
            "counts": {"off": off["counts"], "on": on["counts"]},
            "stage_attribution_ms": {
                "off": _e2e_attribution(_e2e_band_totals(off["before"]),
                                        _e2e_band_totals(off["after"])),
                "on": _e2e_attribution(_e2e_band_totals(on["before"]),
                                       _e2e_band_totals(on["after"]))},
            "rpc_counters": _e2e_rpc_counters(on["after"]),
            "grv_client_stats": dict(db.grv_stats),
            "health_overhead": {
                "disabled_commits_per_s": round(h_off, 1),
                "enabled_commits_per_s": round(h_on, 1),
                "overhead_pct": round(h_overhead, 2),
                "repeats": max(1, E2E_REPEATS)},
        }
        if doc["speedup"] < 1.5:
            print(f"# WARNING: e2e speedup {doc['speedup']} below the "
                  "1.5x acceptance floor", file=sys.stderr)
        return doc
    finally:
        for p in procs.values():
            p.kill()
        for p in procs.values():
            p.wait()
        from foundationdb_tpu.core.knobs import client_knobs as _ck
        from foundationdb_tpu.core.knobs import server_knobs as _sk
        _ck().GRV_BATCH_ENABLED = False
        _ck().GRV_LEASE_S = 0.0
        _sk().RPC_COLUMNAR_ENABLED = False
        _sk().PEER_HEALTH_ENABLED = True
        set_network(None)
        if loop is not None:
            set_event_loop(None)


# -- `bench.py e2e --smoke`: the in-process tier-1 parity gate ---------------

def _e2e_canonical_request():
    """A fixed, fully-featured hot-RPC payload (also the golden-test
    subject in tests/test_wire_columnar.py)."""
    from foundationdb_tpu.server.interfaces import (
        ResolveTransactionBatchRequest)
    from foundationdb_tpu.txn.types import (CommitTransactionRef, KeyRange,
                                            Mutation, MutationType)
    txns = []
    for i in range(4):
        k = b"smoke/%04d" % i
        txns.append(CommitTransactionRef(
            read_conflict_ranges=[KeyRange(k, k + b"\x00")],
            write_conflict_ranges=[KeyRange(k + b"/w", k + b"/w\x00")],
            mutations=[Mutation(MutationType.SetValue, k + b"/w", b"v" * 8)],
            read_snapshot=900 + i,
            report_conflicting_keys=(i % 2 == 0),
            tenant_id=(7 if i == 3 else -1),
            tag=("hot" if i == 1 else "")))
    return ResolveTransactionBatchRequest(
        prev_version=900, version=1000, last_received_version=800,
        transactions=txns, txn_state_transactions=[2],
        proxy_id="proxy0", span="smoke-span")


def _e2e_sim_commit_run(vectorized: bool):
    """One deterministic sim-cluster commit run (6 actors x RMW
    increments on a shared hot keyspace, conflicts guaranteed); returns
    (per-actor outcome log, final counter values).  The vectorized knob
    changes pure computation only — event interleavings are identical —
    so the two runs must match exactly."""
    from foundationdb_tpu.core.error import FdbError
    from foundationdb_tpu.core.knobs import server_knobs
    from foundationdb_tpu.core.rng import (DeterministicRandom,
                                           set_deterministic_random)
    from foundationdb_tpu.core.scheduler import set_event_loop
    from foundationdb_tpu.rpc.sim import set_simulator
    from foundationdb_tpu.server.cluster import SimCluster
    sk = server_knobs()
    saved = sk.PROXY_VECTORIZED_ASSEMBLY
    sk.PROXY_VECTORIZED_ASSEMBLY = vectorized
    set_deterministic_random(DeterministicRandom(424242))
    try:
        cl = SimCluster(n_resolvers=2, n_storage=2)
        db = cl.database()
        log = []

        async def actor(aid: int) -> None:
            for op in range(12):
                key = b"ctr/%d" % ((aid + op) % 4)   # 4 hot keys
                t = db.create_transaction()
                for attempt in range(8):
                    try:
                        cur = await t.get(key)
                        n = int(cur or b"0") + 1
                        t.set(key, b"%d" % n)
                        v = await t.commit()
                        log.append((aid, op, "ok", n))
                        break
                    except FdbError as e:
                        log.append((aid, op, e.name, attempt))
                        await t.on_error(e)

        async def go():
            from foundationdb_tpu.core.futures import wait_all
            await wait_all([cl.loop.spawn(actor(a), f"smoke.a{a}")
                            for a in range(6)])
            t = db.create_transaction()
            final = [await t.get(b"ctr/%d" % i) for i in range(4)]
            return final

        final = cl.run_until(cl.loop.spawn(go()), timeout=120)
        return log, final
    finally:
        sk.PROXY_VECTORIZED_ASSEMBLY = saved
        set_simulator(None)
        set_event_loop(None)


def run_e2e_smoke() -> dict:
    """Fast in-process parity gate (tier-1 via tests/test_e2e_bench.py):
    (1) knobs-off hot-RPC wire images stay the LEGACY format and
    round-trip; (2) columnar-on abort sets are identical to columnar-off
    on the same contended stream (every batch round-trips the wire both
    ways); (3) sim-pipeline commits are bit-identical with vectorized
    assembly on."""
    global TXNS_PER_BATCH
    from foundationdb_tpu.conflict.oracle import OracleConflictSet
    from foundationdb_tpu.core.knobs import server_knobs
    from foundationdb_tpu.rpc import serde
    from foundationdb_tpu.server.interfaces import (
        ResolveTransactionBatchRequest)
    serde.bootstrap_registry()
    sk = server_knobs()
    doc = {"metric": "e2e_smoke"}

    # (1) knobs-off wire image: legacy tag, exact round trip.
    assert not sk.RPC_COLUMNAR_ENABLED, "smoke requires default knobs"
    req = _e2e_canonical_request()
    blob = serde.encode_message(req)
    assert blob[0] == serde.T_DATACLASS, "knobs-off frame not legacy!"
    assert serde.decode_message(blob) == req
    doc["legacy_wire"] = "ok"

    # (2) columnar-on abort sets == columnar-off on the same stream.
    saved_txns, TXNS_PER_BATCH = TXNS_PER_BATCH, 256
    try:
        rng = np.random.default_rng(1234)
        oa, ob = OracleConflictSet(0), OracleConflictSet(0)
        version = 1_000
        checked = 0
        for _ in range(6):
            prev, version = version, version + VERSIONS_PER_BATCH
            _enc, kids, snaps = gen_batch(rng, version, prev,
                                          keyspace=2048)
            txns = to_transactions(kids, snaps)
            floor = max(0, version - WINDOW_BATCHES * VERSIONS_PER_BATCH)
            wire = ResolveTransactionBatchRequest(
                prev_version=prev, version=version,
                last_received_version=prev, transactions=txns,
                proxy_id="p0")
            sk.RPC_COLUMNAR_ENABLED = False
            off_req = serde.decode_message(serde.encode_message(wire))
            sk.RPC_COLUMNAR_ENABLED = True
            on_blob = serde.encode_message(wire)
            on_req = serde.decode_message(on_blob)
            sk.RPC_COLUMNAR_ENABLED = False
            assert on_blob[0] == serde.T_COLUMNAR
            assert off_req == on_req == wire
            va = oa.resolve(off_req.transactions, version, floor)
            vb = ob.resolve(on_req.transactions, version, floor)
            assert va == vb, "abort sets diverge across wire formats"
            checked += len(va)
        doc["abort_set_parity_txns"] = checked
    finally:
        TXNS_PER_BATCH = saved_txns
        sk.RPC_COLUMNAR_ENABLED = False

    # (3) pipeline commits bit-identical with vectorized assembly.
    log_off, final_off = _e2e_sim_commit_run(vectorized=False)
    log_on, final_on = _e2e_sim_commit_run(vectorized=True)
    assert final_off == final_on, "final state diverges"
    assert log_off == log_on, "commit outcome log diverges"
    doc["pipeline_parity_ops"] = len(log_off)
    doc["parity"] = "ok"
    return doc


def e2e_main() -> None:
    if "--smoke" in sys.argv:
        print(json.dumps(run_e2e_smoke()))
        return
    doc = run_e2e()
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_r10.json")
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(json.dumps(doc))


# ---------------------------------------------------------------------------
# `bench.py reads` — read-path throughput through the REAL-TCP cluster
# (ISSUE 15): a Zipfian hot-key point-read storm and long range scans,
# measured knobs-off then all-read-knobs-on (columnar read RPCs +
# vectorized storage scans via LIVE dynamic knobs) with per-stage
# latency-band attribution, plus an in-process B-tree micro section
# (prefix-compression page ratio, vectorized scan speedup) and an e2e
# commits/s re-run proving the write path did not regress.
# `bench.py reads --smoke` is the in-process tier-1 parity gate.
# ---------------------------------------------------------------------------

READS_PHASE_S = float(os.environ.get("READS_PHASE_S", "10"))
READS_REPEATS = int(os.environ.get("READS_REPEATS", "2"))
READS_CLIENTS = int(os.environ.get("READS_CLIENTS", "24"))
READS_KEYS = int(os.environ.get("READS_KEYS", "4000"))
READS_VALUE_BYTES = int(os.environ.get("READS_VALUE_BYTES", "100"))
READS_POINTS_PER_TXN = int(os.environ.get("READS_POINTS_PER_TXN", "16"))
READS_SCAN_LIMIT = int(os.environ.get("READS_SCAN_LIMIT", "250"))


def _reads_key(i: int) -> bytes:
    # Long shared prefixes: the regime both the columnar reply's
    # prefix-truncated key stream and the B-tree's page compression are
    # built for (tenant/table/row-shaped keyspaces).
    return b"reads/tenant01/users/%08d" % i


def _zipf_idx(r, n: int, log_n: float) -> int:
    """Log-uniform rank (index 0 = the celebrity object)."""
    import math
    return min(n - 1, int(math.exp(r.random() * log_n)) - 1)


def _reads_load(loop, db) -> None:
    value = b"v" * READS_VALUE_BYTES

    async def load() -> None:
        from foundationdb_tpu.core.error import FdbError
        for base in range(0, READS_KEYS, 200):
            t = db.create_transaction()
            while True:
                try:
                    for i in range(base, min(base + 200, READS_KEYS)):
                        t.set(_reads_key(i), value)
                    await t.commit()
                    break
                except FdbError as e:
                    await t.on_error(e)

    loop.run_until(loop.spawn(load()), timeout=300)


def _reads_phase(loop, db, kind: str, phase_s: float, n_clients: int):
    """Drive n_clients concurrent read actors for phase_s; returns
    (counts, elapsed_s).  kind: "point" = Zipfian get storm, "scan" =
    long forward range scans from random offsets."""
    import math
    counts = {"reads": 0, "scans": 0, "rows": 0, "errors": 0}
    log_n = math.log(READS_KEYS)
    end_key = _reads_key(READS_KEYS)

    async def point_actor(cid: int) -> None:
        from foundationdb_tpu.core.scheduler import delay
        from foundationdb_tpu.core.scheduler import now as _lnow
        import random as _random
        r = _random.Random(cid * 7919 + 1)
        stop_at = _lnow() + phase_s
        while _lnow() < stop_at:
            t = db.create_transaction()
            try:
                for _ in range(READS_POINTS_PER_TXN):
                    await t.get(_reads_key(
                        _zipf_idx(r, READS_KEYS, log_n)), snapshot=True)
                    counts["reads"] += 1
            except Exception:  # noqa: BLE001 — chaos-free run; count+pace
                counts["errors"] += 1
                await delay(0.2)

    async def scan_actor(cid: int) -> None:
        from foundationdb_tpu.core.scheduler import delay
        from foundationdb_tpu.core.scheduler import now as _lnow
        import random as _random
        r = _random.Random(cid * 104729 + 1)
        stop_at = _lnow() + phase_s
        while _lnow() < stop_at:
            t = db.create_transaction()
            try:
                lo = r.randrange(max(READS_KEYS - READS_SCAN_LIMIT, 1))
                rows = await t.get_range(_reads_key(lo), end_key,
                                         limit=READS_SCAN_LIMIT,
                                         snapshot=True)
                counts["scans"] += 1
                counts["rows"] += len(rows)
            except Exception:  # noqa: BLE001
                counts["errors"] += 1
                await delay(0.2)

    actor = point_actor if kind == "point" else scan_actor

    async def drive() -> None:
        from foundationdb_tpu.core.futures import wait_all
        from foundationdb_tpu.core.scheduler import get_event_loop
        await wait_all([get_event_loop().spawn(actor(c), f"reads.{kind}{c}")
                        for c in range(n_clients)])

    t0 = time.perf_counter()
    loop.run_until(loop.spawn(drive()), timeout=phase_s * 4 + 120)
    return counts, time.perf_counter() - t0


def run_reads() -> dict:
    """Boot the 6-process real-TCP cluster, load the keyspace, measure
    point-read and range-scan throughput knobs-off, flip the read-path
    knobs live, measure again, attribute stages."""
    from foundationdb_tpu.client.database import open_cluster
    from foundationdb_tpu.core.scheduler import set_event_loop
    from foundationdb_tpu.rpc.network import set_network

    base = os.environ.get("READS_BASEDIR", "/tmp/fdb_reads_bench")
    procs, coords = _e2e_spawn_cluster(base)
    loop = None
    try:
        time.sleep(2.5)
        dead = {n: p.poll() for n, p in procs.items()
                if p.poll() is not None}
        if dead:
            raise RuntimeError(f"processes died at boot: {dead}")
        loop, db = open_cluster(coords)
        _e2e_ready(loop, db, procs)
        _phase("reads cluster up; loading keyspace")
        _reads_load(loop, db)

        async def fast_register():
            from foundationdb_tpu.client.management import set_knob
            await set_knob(db, "WORKER_REGISTER_INTERVAL_S", 2)
        loop.run_until(loop.spawn(fast_register()), timeout=60)

        def settled_status():
            time.sleep(4.5)
            return _e2e_status(loop, db)

        def set_posture(on: bool) -> None:
            # Server knobs flip LIVE via the dynamic-knob path; the
            # LOCAL registry flips too — this client process encodes
            # GetValueRequest/GetKeyValuesRequest frames itself and
            # serde's gate reads the local registry.
            async def flip():
                from foundationdb_tpu.client.management import set_knob
                await set_knob(db, "RPC_COLUMNAR_ENABLED", int(on))
                await set_knob(db, "STORAGE_VECTORIZED_SCAN", int(on))
                await set_knob(db, "BTREE_PREFIX_COMPRESSION", int(on))
            loop.run_until(loop.spawn(flip()), timeout=60)
            from foundationdb_tpu.core.knobs import server_knobs
            server_knobs().RPC_COLUMNAR_ENABLED = bool(on)
            server_knobs().STORAGE_VECTORIZED_SCAN = bool(on)

        # Prove columnar read frames engage before measuring any ON
        # window (same dead-knob-watch guard as `bench.py e2e`).
        set_posture(True)
        deadline = time.monotonic() + 30.0
        engaged = False
        while time.monotonic() < deadline:
            _reads_phase(loop, db, "point", 1.0, 2)
            rpc = _e2e_rpc_counters(_e2e_status(loop, db))
            if rpc.get("ColumnarFrames", 0) > 0:
                engaged = True
                break
        if not engaged:
            raise RuntimeError(
                "columnar frames never appeared on the wire: dynamic "
                "knob propagation is broken — refusing to measure")

        acc = {"off": {"point": [], "scan": []},
               "on": {"point": [], "scan": []}}
        attrib = {}
        for rep in range(max(1, READS_REPEATS)):
            order = (("off", False), ("on", True))
            if rep % 2:
                order = order[::-1]
            for name, on in order:
                set_posture(on)
                _reads_phase(loop, db, "point", 1.0, 2)   # settle
                s_before = settled_status()
                for kind in ("point", "scan"):
                    counts, elapsed = _reads_phase(
                        loop, db, kind, READS_PHASE_S, READS_CLIENTS)
                    if kind == "point":
                        rate = counts["reads"] / max(elapsed, 1e-9)
                    else:
                        rate = counts["rows"] / max(elapsed, 1e-9)
                    _phase(f"reads rep{rep} {name} {kind}: {rate:.0f}/s "
                           f"(errors={counts['errors']})")
                    acc[name][kind].append(
                        {"rate": rate, "counts": counts})
                s_after = settled_status()
                # Per-rep attribution (reps alternate posture order, so
                # publishing only the last rep would silently pick one
                # ordering's warm-up profile).
                attrib.setdefault(name, {})[f"rep{rep}"] = \
                    _e2e_attribution(_e2e_band_totals(s_before),
                                     _e2e_band_totals(s_after))

        def fold(phases):
            mean = sum(p["rate"] for p in phases) / len(phases)
            return {"rate": round(mean, 1),
                    "rates": [round(p["rate"], 1) for p in phases]}

        doc = {
            "metric": "read_path_throughput",
            "regime": {"clients": READS_CLIENTS, "phase_s": READS_PHASE_S,
                       "repeats": max(1, READS_REPEATS),
                       "keys": READS_KEYS,
                       "value_bytes": READS_VALUE_BYTES,
                       "points_per_txn": READS_POINTS_PER_TXN,
                       "scan_limit": READS_SCAN_LIMIT,
                       "processes": len(procs), "transport": "real-tcp"},
            "point_reads_per_s": {
                "off": fold(acc["off"]["point"]),
                "on": fold(acc["on"]["point"])},
            "scan_rows_per_s": {
                "off": fold(acc["off"]["scan"]),
                "on": fold(acc["on"]["scan"])},
            "stage_attribution_ms": attrib,
            "rpc_counters": _e2e_rpc_counters(_e2e_status(loop, db)),
        }
        doc["point_speedup"] = round(
            doc["point_reads_per_s"]["on"]["rate"] /
            max(doc["point_reads_per_s"]["off"]["rate"], 1e-9), 3)
        doc["scan_speedup"] = round(
            doc["scan_rows_per_s"]["on"]["rate"] /
            max(doc["scan_rows_per_s"]["off"]["rate"], 1e-9), 3)
        return doc
    finally:
        for p in procs.values():
            p.kill()
        for p in procs.values():
            p.wait()
        from foundationdb_tpu.core.knobs import server_knobs as _sk
        _sk().RPC_COLUMNAR_ENABLED = False
        _sk().STORAGE_VECTORIZED_SCAN = False
        set_network(None)
        if loop is not None:
            set_event_loop(None)


# -- in-process B-tree micro section ------------------------------------------

def run_btree_micro() -> dict:
    """Prefix-compression page ratio + vectorized scan speedup on the
    B-tree engine, same keyspace shape as the TCP bench (the engine is
    the durable floor under the MVCC window — boot image scans,
    fetch_shard snapshots and storage re-images all walk it)."""
    from foundationdb_tpu.core import (DeterministicRandom, EventLoop,
                                       set_deterministic_random,
                                       set_event_loop)
    from foundationdb_tpu.core.knobs import server_knobs
    from foundationdb_tpu.server.kvstore import open_kv_store
    from foundationdb_tpu.server.sim_fs import SimFileSystem

    sk = server_knobs()
    saved = (sk.BTREE_PREFIX_COMPRESSION, sk.STORAGE_VECTORIZED_SCAN)
    loop = EventLoop(sim=True)
    set_event_loop(loop)
    set_deterministic_random(DeterministicRandom(1511))
    n = int(os.environ.get("READS_BTREE_KEYS", "20000"))
    value = b"v" * 24

    def drive(coro):
        return loop.run_until(loop.spawn(coro), timeout=600)

    def build(compress: bool):
        sk.BTREE_PREFIX_COMPRESSION = compress
        fs = SimFileSystem()
        eng = open_kv_store("btree", fs, "bt")
        drive(eng.recover())
        for base in range(0, n, 500):
            for i in range(base, min(base + 500, n)):
                eng.set(_reads_key(i), value)
            drive(eng.commit())
        return eng

    try:
        plain = build(False)
        comp = build(True)
        live_plain = plain.page_count - len(plain.free)
        live_comp = comp.page_count - len(comp.free)

        def scan_rate(eng, vectorized: bool) -> float:
            sk.STORAGE_VECTORIZED_SCAN = vectorized
            t0 = time.perf_counter()
            rows = 0
            for _ in range(5):
                rows += len(eng.read_range(b"", b"\xff"))
            dt = time.perf_counter() - t0
            assert rows == 5 * n
            return rows / dt

        doc = {
            "keys": n,
            "pages_live": {"plain": live_plain, "compressed": live_comp},
            "page_compression_ratio": round(live_plain /
                                            max(live_comp, 1), 3),
            "full_scan_rows_per_s": {
                "recursive": round(scan_rate(plain, False), 0),
                "vectorized": round(scan_rate(plain, True), 0),
                "vectorized_compressed": round(scan_rate(comp, True), 0),
            },
        }
        doc["scan_speedup"] = round(
            doc["full_scan_rows_per_s"]["vectorized"] /
            max(doc["full_scan_rows_per_s"]["recursive"], 1e-9), 3)
        # Parity while we're here: all three paths, identical rows.
        sk.STORAGE_VECTORIZED_SCAN = False
        a = plain.read_range(b"", b"\xff")
        b = comp.read_range(b"", b"\xff")
        sk.STORAGE_VECTORIZED_SCAN = True
        c = plain.read_range(b"", b"\xff")
        d = comp.read_range(b"", b"\xff")
        assert a == b == c == d and len(a) == n
        doc["parity"] = "ok"
        return doc
    finally:
        sk.BTREE_PREFIX_COMPRESSION, sk.STORAGE_VECTORIZED_SCAN = saved
        set_event_loop(None)


# -- `bench.py reads --smoke`: the in-process tier-1 parity gate -------------

def run_reads_smoke() -> dict:
    """Fast in-process read-path parity gate (tier-1 via
    tests/test_reads_bench.py): (1) knobs-off read-RPC wire images stay
    the LEGACY format and round-trip; (2) columnar-on replies decode to
    objects identical to columnar-off on randomized data; (3) compressed
    vs plain B-tree pages yield identical scan results, both knob
    postures, across a power-fail recovery; (4) the vectorized
    VersionedMap scan is bit-identical to the plain loop on randomized
    MVCC probes; (5) the incremental shard-metrics cache's totals equal
    fresh scans under randomized mutation."""
    import random as _random

    from foundationdb_tpu.core.knobs import server_knobs
    from foundationdb_tpu.rpc import serde
    from foundationdb_tpu.server.interfaces import (GetKeyValuesReply,
                                                    GetKeyValuesRequest,
                                                    GetValueReply)
    serde.bootstrap_registry()
    sk = server_knobs()
    doc = {"metric": "reads_smoke"}
    assert not sk.RPC_COLUMNAR_ENABLED, "smoke requires default knobs"
    assert not sk.STORAGE_VECTORIZED_SCAN
    assert not sk.BTREE_PREFIX_COMPRESSION

    # (1) + (2) wire parity on randomized read payloads.
    rng = _random.Random(1511)
    checked = 0
    for trial in range(40):
        n = rng.randrange(0, 60)
        data = []
        for i in range(n):
            k = _reads_key(rng.randrange(10_000))
            data.append((k, bytes(rng.randrange(256)
                                  for _ in range(rng.randrange(0, 40)))))
        data.sort(key=lambda kv: kv[0])
        if rng.random() < 0.25:
            data.reverse()
        objs = [
            GetKeyValuesReply(data=data, more=rng.random() < 0.5,
                              version=rng.randrange(1 << 40)),
            GetKeyValuesRequest(
                begin=_reads_key(1), end=_reads_key(rng.randrange(2, 9999)),
                version=rng.randrange(1 << 40),
                limit=rng.randrange(1, 1000),
                limit_bytes=rng.randrange(1, 1 << 20),
                reverse=rng.random() < 0.5,
                tag="t" if rng.random() < 0.3 else ""),
            GetValueReply(value=(None if rng.random() < 0.2 else
                                 b"x" * rng.randrange(0, 200)),
                          version=rng.randrange(1 << 40)),
        ]
        for obj in objs:
            leg = serde.encode_message(obj)
            assert leg[0] == serde.T_DATACLASS, "knobs-off frame not legacy!"
            sk.RPC_COLUMNAR_ENABLED = True
            col = serde.encode_message(obj)
            sk.RPC_COLUMNAR_ENABLED = False
            assert col[0] == serde.T_COLUMNAR
            assert serde.decode_message(leg) == obj
            assert serde.decode_message(col) == obj, type(obj).__name__
            checked += 1
    doc["wire_parity_msgs"] = checked

    # (3) compressed vs plain B-tree pages: identical scans (covered in
    # depth by run_btree_micro's parity; here a quick randomized pass
    # with clears + power-fail recovery).
    from foundationdb_tpu.core import (DeterministicRandom, EventLoop,
                                       set_deterministic_random,
                                       set_event_loop)
    from foundationdb_tpu.server.kvstore import open_kv_store
    from foundationdb_tpu.server.sim_fs import SimFileSystem
    loop = EventLoop(sim=True)
    set_event_loop(loop)
    set_deterministic_random(DeterministicRandom(1512))
    try:
        def drive(coro):
            return loop.run_until(loop.spawn(coro), timeout=120)

        stores = {}
        for compress in (False, True):
            sk.BTREE_PREFIX_COMPRESSION = compress
            fs = SimFileSystem()
            eng = open_kv_store("btree", fs, "bt")
            drive(eng.recover())
            r = _random.Random(99)
            for round_ in range(8):
                for _ in range(120):
                    i = r.randrange(3000)
                    if r.random() < 0.85:
                        eng.set(_reads_key(i), b"v%06d" % r.randrange(1 << 20))
                    else:
                        # Narrow clears: wide ones would empty the tree
                        # and starve the scan-parity assertion of rows.
                        eng.clear(_reads_key(i),
                                  _reads_key(i + r.randrange(1, 40)))
                drive(eng.commit())
            fs.power_fail_all()
            eng = open_kv_store("btree", fs, "bt")
            drive(eng.recover())
            stores[compress] = eng
        sk.BTREE_PREFIX_COMPRESSION = False
        scans = {}
        for compress, eng in stores.items():
            for vec in (False, True):
                sk.STORAGE_VECTORIZED_SCAN = vec
                scans[(compress, vec)] = eng.read_range(b"", b"\xff")
        sk.STORAGE_VECTORIZED_SCAN = False
        first = scans[(False, False)]
        assert first and all(s == first for s in scans.values()), \
            "btree page-format/scan-path results diverge"
        doc["btree_parity_rows"] = len(first)

        # (4) VersionedMap vectorized-scan parity on randomized MVCC
        # probes (tombstones, overlapping versions, byte limits).
        from foundationdb_tpu.server.storage import VersionedMap
        vm = VersionedMap()
        r = _random.Random(4242)
        for v in range(1, 400):
            for _ in range(4):
                i = r.randrange(500)
                vm.set(_reads_key(i),
                       None if r.random() < 0.15 else b"u%07d" % v, v)
        probes = 0
        for _ in range(300):
            a, bkey = sorted((r.randrange(520), r.randrange(520)))
            args = (_reads_key(a), _reads_key(bkey), r.randrange(1, 420),
                    r.randrange(1, 40), r.randrange(1, 4000),
                    r.random() < 0.3)
            sk.STORAGE_VECTORIZED_SCAN = False
            plain = vm.range_read(*args)
            sk.STORAGE_VECTORIZED_SCAN = True
            vec = vm.range_read(*args)
            sk.STORAGE_VECTORIZED_SCAN = False
            assert plain == vec, f"range_read diverges at {args}"
            probes += 1
        doc["versioned_map_probes"] = probes

        # (5) incremental shard-metrics cache == fresh scans.
        from foundationdb_tpu.server.storage import _ShardMetricsCache
        vm2 = VersionedMap()
        cache = _ShardMetricsCache()
        vm2._metrics_cache = cache
        bounds = [_reads_key(i) for i in (0, 120, 300, 700, 1000)]
        shards = list(zip(bounds, bounds[1:]))
        ver = 0
        audited = 0
        for round_ in range(30):
            for _ in range(60):
                ver += 1
                i = r.randrange(1000)
                vm2.set(_reads_key(i),
                        None if r.random() < 0.1 else
                        b"w" * r.randrange(1, 60), ver)
            for b, e in shards:
                hit = cache.get(b, e)
                fresh = vm2.range_bytes(b, e, ver)
                if hit is not None:
                    assert hit == fresh, \
                        f"shard cache drifted: {hit} != {fresh}"
                    audited += 1
                cache.put(b, e, *fresh)
        assert audited > 50
        doc["shard_cache_audits"] = audited
    finally:
        sk.BTREE_PREFIX_COMPRESSION = False
        sk.STORAGE_VECTORIZED_SCAN = False
        sk.RPC_COLUMNAR_ENABLED = False
        set_event_loop(None)
    doc["parity"] = "ok"
    return doc


def reads_main() -> None:
    if "--smoke" in sys.argv:
        print(json.dumps(run_reads_smoke()))
        return
    doc = {"metric": "read_path_round11"}
    _phase("btree micro (compression ratio + scan speedup)")
    doc["btree_micro"] = run_btree_micro()
    _phase("real-TCP read bench")
    doc["reads"] = run_reads()
    if os.environ.get("READS_E2E_RECHECK", "1") != "0":
        _phase("e2e commits/s recheck (write path must not regress)")
        doc["e2e_recheck"] = run_e2e()
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "BENCH_r11.json")
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(json.dumps(doc))


def _force_cpu_backend() -> None:
    """Deregister the axon TPU-tunnel plugin: jax initializes ALL
    registered PJRT plugins on first use and the axon client creation can
    BLOCK on a dead tunnel — JAX_PLATFORMS=cpu alone is not enough (same
    workaround as tests/conftest.py)."""
    try:
        import jax
        from jax._src import xla_bridge as _xb
        _xb._backend_factories.pop("axon", None)
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001
        pass


def _phase(msg: str) -> None:
    """Timestamped stderr progress so a hung/slow child is diagnosable
    from the parent's relayed tail (and from a streamed log)."""
    print(f"# [{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr,
          flush=True)


def child_main(backend: str) -> None:
    """The actual measurement (runs in a subprocess; see module doc)."""
    global TXNS_PER_BATCH, N_BATCHES, N_LATENCY, CAPACITY, DELTA_CAPACITY
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        _force_cpu_backend()
    try:
        # Persistent XLA compile cache: the axon tunnel's remote compile
        # costs minutes per program shape; a crashed/retried run should
        # not pay it twice.  Gated on modern jax — on 0.4.x, executables
        # reloaded from this cache for mesh-sharded programs on XLA:CPU
        # were observed to return wrong verdicts and corrupt the heap
        # (tests/conftest.py carries the same gate).
        import jax
        if hasattr(jax, "shard_map"):
            jax.config.update("jax_compilation_cache_dir",
                              os.environ.get("JAX_CACHE_DIR",
                                             "/tmp/jax_bench_cache"))
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 — older jax: cache is best-effort
        pass
    if os.environ.get("BENCH_SMALL") == "1":
        # Degraded (XLA-CPU fallback) sizing.  The fused step is TUNED
        # FOR TPU (row-gather searchsorted, big fused sorts); XLA CPU
        # executes it at ~250 ranges/s — so the fallback stream must be
        # tiny or nothing finishes.  Parity is still asserted on every
        # compared batch; the emitted number is an honest (terrible)
        # CPU figure, marked degraded by the parent's "error" field.
        global N_PARITY, N_LOWC
        TXNS_PER_BATCH = 2_000
        N_BATCHES = 4
        N_PARITY = 2
        N_LATENCY = 2
        N_LOWC = 2
        # Degraded MAIN-figure sizing (unchanged across rounds so the
        # fallback figure stays comparable round over round).
        CAPACITY = 1 << 16
        DELTA_CAPACITY = 1 << 15
        # Depth sweep under the fallback: mid-size batches (so compute,
        # pack and the emulated tunnel transfers are comparable — the
        # regime the pipeline targets) over the emulated link, with its
        # OWN window sizing (run_depth_sweep swaps it in): a 5-batch MVCC
        # window of 16K-txn zipf uniques fits with headroom; delta holds
        # one batch's 2W+2 boundaries without a grow.
        global N_SWEEP, SWEEP_TXNS, SWEEP_CAPACITY, SWEEP_DELTA_CAPACITY
        N_SWEEP = 6
        SWEEP_TXNS = 16_384
        SWEEP_CAPACITY = 1 << 18
        SWEEP_DELTA_CAPACITY = 1 << 16
    from foundationdb_tpu.conflict.oracle import OracleConflictSet
    from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet
    from foundationdb_tpu.txn.types import CommitResult

    window = WINDOW_BATCHES * VERSIONS_PER_BATCH
    rng = np.random.default_rng(2026)
    total = (N_WARMUP + N_PARITY if backend == "cpu"
             else N_WARMUP + N_BATCHES + N_LATENCY)
    _phase(f"generating {total} batches of {TXNS_PER_BATCH} txns")
    batches = []
    version = 1_000
    for _ in range(total):
        prev = version
        version += VERSIONS_PER_BATCH
        batches.append((version, *gen_batch(rng, version, prev)))
    _phase("batches generated")

    def floor(v):
        return max(v - window, 0)

    if backend == "cpu":
        # Oracle-only mode: throughput of the parity baseline on the prefix.
        # Object construction is untimed, matching the vs_oracle denominator
        # in the tpu run.
        cs = OracleConflictSet(0)
        n_ranges = 0
        dt = 0.0
        for v, enc, kids, snaps in batches[:N_WARMUP + N_PARITY]:
            txns = to_transactions(kids, snaps)
            t0 = time.perf_counter()
            cs.resolve(txns, v, floor(v))
            dt += time.perf_counter() - t0
            n_ranges += enc.n_ranges
        value = n_ranges / dt
        print(json.dumps({
            "metric": "conflict_range_checks_per_s", "value": round(value, 1),
            "unit": "ranges/s",
            "vs_baseline": round(value / NORTH_STAR_RANGES_PER_S, 4)}))
        return

    def make_cs(oldest_version=0):
        if os.environ.get("BENCH_BACKEND") == "sharded":
            # BASELINE config 5 axis: the REAL resolve step sharded over
            # every attached device ("kr" mesh); per-shard capacity makes
            # the window size a device-count multiplier.  On one chip
            # this measures shard_map overhead; on a pod slice it is the
            # 1M-in-flight-ranges configuration.
            import jax
            from foundationdb_tpu.parallel.sharded_resolver import (
                ShardedTpuConflictSet, make_conflict_mesh)
            mesh = make_conflict_mesh(jax.devices())
            n_kr = int(mesh.shape["kr"])   # power of two by construction
            _phase(f"sharded backend: {n_kr} 'kr' shard(s) over "
                   f"{len(jax.devices())} device(s)")
            return ShardedTpuConflictSet(
                mesh, oldest_version, capacity=CAPACITY // n_kr,
                delta_capacity=DELTA_CAPACITY // n_kr)
        return TpuConflictSet(oldest_version, capacity=CAPACITY,
                              delta_capacity=DELTA_CAPACITY)

    cs = make_cs()

    # Warmup: compile the fused step + merge for this bucket shape (the
    # merge is forced here so its one-time compile can't land mid-measure).
    for i, (v, enc, kids, snaps) in enumerate(batches[:N_WARMUP]):
        _phase(f"warmup batch {i} (first = step compile)")
        cs.resolve_encoded(enc, v, floor(v))
    _phase("warmup merge (compile)")
    cs.merge()
    _phase("measuring")

    # ---- main throughput phase (pipelined) --------------------------------
    from collections import deque
    inflight = deque()
    n_ranges = 0
    n_txns = 0
    committed = 0
    tpu_results = []
    committed_code = int(CommitResult.COMMITTED)
    t0 = time.perf_counter()
    for v, enc, kids, snaps in batches[N_WARMUP:N_WARMUP + N_BATCHES]:
        inflight.append((enc, cs.resolve_encoded_async(enc, v, floor(v))))
        if len(inflight) > PIPELINE_DEPTH:
            enc_done, h = inflight.popleft()
            codes = h.wait_codes()
            tpu_results.append(codes)
            n_txns += enc_done.n_txns
            n_ranges += enc_done.n_ranges
            committed += int(np.sum(codes == committed_code))
    while inflight:
        enc_done, h = inflight.popleft()
        codes = h.wait_codes()
        tpu_results.append(codes)
        n_txns += enc_done.n_txns
        n_ranges += enc_done.n_ranges
        committed += int(np.sum(codes == committed_code))
    dt = time.perf_counter() - t0
    value = n_ranges / dt
    _phase(f"throughput phase done: {value:.0f} ranges/s")

    # ---- p50 resolve latency (depth-1 dispatch -> wait) -------------------
    lats = []
    for v, enc, kids, snaps in batches[N_WARMUP + N_BATCHES:]:
        t1 = time.perf_counter()
        cs.resolve_encoded_async(enc, v, floor(v)).wait_codes()
        lats.append(time.perf_counter() - t1)
    p50_ms = float(np.percentile(lats, 50) * 1e3)
    _phase(f"latency phase done: p50={p50_ms:.1f}ms; oracle parity next")

    # ---- oracle on the same stream prefix: parity + relative throughput ---
    oracle = OracleConflictSet(0)
    oracle_ranges = 0
    oracle_dt = 0.0
    mismatches = 0
    for i, (v, enc, kids, snaps) in enumerate(
            batches[:N_WARMUP + N_PARITY]):
        txns = to_transactions(kids, snaps)  # untimed: object construction
        t1 = time.perf_counter()
        want = oracle.resolve(txns, v, floor(v))
        oracle_dt += time.perf_counter() - t1
        oracle_ranges += enc.n_ranges
        if N_WARMUP <= i < N_WARMUP + N_PARITY:
            got = tpu_results[i - N_WARMUP]
            want_codes = np.asarray([int(r) for r in want], dtype=np.int8)
            mismatches += int(np.sum(got != want_codes))
    oracle_rate = oracle_ranges / oracle_dt
    if mismatches:
        print(f"PARITY FAILURE: {mismatches} verdicts differ from the "
              "CPU oracle", file=sys.stderr)
        sys.exit(1)

    commit_rate = committed / max(n_txns, 1)
    if not 0.01 < commit_rate < 0.99:
        print("degenerate contention config", file=sys.stderr)
        sys.exit(1)

    # ---- second regime: low contention, every batch parity-checked --------
    # (round-3 review: one heavily-contended regime is not enough; the
    # commit-heavy path exercises different insert/merge behavior.)
    _phase("high-contention parity ok; low-contention regime next")
    lowc = []
    version = 1_000
    for _ in range(N_LOWC):
        prev = version
        version += VERSIONS_PER_BATCH
        lowc.append((version, *gen_batch(rng, version, prev,
                                         keyspace=KEYSPACE_LOW, zipf=False)))
    commit_rate_low = run_parity_regime(make_cs, lowc, floor, "low-contention")
    if commit_rate_low < 0.8:
        print(f"low-contention regime degenerate: {commit_rate_low:.3f}",
              file=sys.stderr)
        sys.exit(1)

    # ---- supervised depth sweep (pipelined dispatch, ISSUE 6) -------------
    # Real device: transfers are real, no emulation.  XLA-CPU fallback:
    # emulate the measured tunnel link on the lanes (see TUNNEL_*).
    emulate_tunnel = os.environ.get(
        "BENCH_TUNNEL_EMU",
        "1" if os.environ.get("JAX_PLATFORMS") == "cpu" else "0") == "1"
    _phase("low-contention parity ok; supervised depth sweep next"
           + (" (emulated tunnel link)" if emulate_tunnel else ""))
    per_depth = run_depth_sweep(make_cs, floor, emulate_tunnel)
    d1 = per_depth.get("1", 0.0)
    best_depth, best_rate = max(per_depth.items(), key=lambda kv: kv[1])
    speedup = best_rate / d1 if d1 else 0.0
    if speedup < 1.2:
        # Informational, not fatal: a loaded box can flatten the overlap;
        # the recorded PERF figure is what the acceptance gate reads.
        print(f"# WARNING: best pipeline speedup {speedup:.2f}x "
              f"(depth {best_depth}) below the 1.2x target",
              file=sys.stderr)

    # ---- heat-telemetry overhead gate (ISSUE 8) ---------------------------
    heat_overhead = None
    if os.environ.get("BENCH_HEAT_GATE", "1") != "0":
        if _remaining_s() > 60:
            _phase("heat-telemetry overhead gate (supervised path, "
                   "enabled vs disabled)")
            heat_overhead = run_heat_gate(
                make_cs, batches[:N_WARMUP + N_LOWC], floor)
            if abs(heat_overhead["overhead_pct"]) > 2.0:
                print(f"# WARNING: heat telemetry overhead "
                      f"{heat_overhead['overhead_pct']:.2f}% above the "
                      "2% gate", file=sys.stderr)
        else:
            heat_overhead = {"skipped": "BENCH_DEADLINE_S budget"}

    # ---- BASELINE config 5: 1M in-flight ranges on the sharded mesh -------
    config5 = None
    if os.environ.get("BENCH_BACKEND") == "sharded" and \
            os.environ.get("BENCH_CONFIG5", "1") != "0":
        config5 = run_config5()

    print(f"# commit_rate={commit_rate:.3f} low={commit_rate_low:.3f} "
          f"oracle={oracle_rate:.0f}/s tpu={value:.0f}/s p50={p50_ms:.2f}ms",
          file=sys.stderr)

    doc = {
        "metric": "conflict_range_checks_per_s",
        "value": round(value, 1),
        "unit": "ranges/s",
        "vs_baseline": round(value / NORTH_STAR_RANGES_PER_S, 4),
        "vs_oracle": round(value / oracle_rate, 3),
        "p50_resolve_ms": round(p50_ms, 2),
        "parity": "ok",
        "commit_rate": round(commit_rate, 3),
        "commit_rate_low": round(commit_rate_low, 3),
        "txns_per_batch": TXNS_PER_BATCH,
        "per_depth": per_depth,
        "pipeline_speedup": round(speedup, 3),
        "pipeline_best_depth": int(best_depth),
        "sweep_txns_per_batch": SWEEP_TXNS or TXNS_PER_BATCH,
    }
    if emulate_tunnel:
        # The fallback sweep ran against the round-5 measured tunnel
        # profile as lane sleeps (no real link on XLA-CPU to overlap).
        doc["sweep_emulated_tunnel"] = {
            "h2d_mb_s": TUNNEL_H2D_MB_S,
            "bytes_per_range": TUNNEL_BYTES_PER_RANGE,
            "d2h_latency_s": TUNNEL_D2H_S,
        }
    if heat_overhead is not None:
        doc["heat_overhead"] = heat_overhead
    if config5 is not None:
        doc["config5"] = config5
    print(json.dumps(doc))


# ---------------------------------------------------------------------------
# Parent orchestration: probe, bounded-timeout child, CPU-jax fallback.
# ---------------------------------------------------------------------------

_PROBE_SRC = ("import jax, numpy as np; "
              "x = jax.jit(lambda a: a + 1)(np.int32(1)); "
              "assert int(np.asarray(x)) == 2; print('probe-ok')")


def _probe_tpu() -> bool:
    """Trivial jit on the default (axon/TPU) backend with a hard timeout.
    The tunnel HANGS rather than erroring when down, so an in-process
    probe could wedge the whole benchmark.  Probes repeat (tunnel outages
    are often transient) but ONLY while the external deadline leaves room
    for the probe itself plus the CPU-fallback reserve — the round-5
    failure mode was probing past the driver's own timeout."""
    probe_deadline = time.monotonic() + PROBE_TOTAL_S
    attempt = 0
    while True:
        budget = min(PROBE_TIMEOUT_S, _remaining_s() - CPU_RESERVE_S)
        if budget <= 5:
            print("# probe window exhausted by BENCH_DEADLINE_S budget",
                  file=sys.stderr)
            return False
        attempt += 1
        started = time.monotonic()
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                timeout=budget, capture_output=True, text=True,
                cwd=os.path.dirname(os.path.abspath(__file__)) or ".")
            if r.returncode == 0 and "probe-ok" in r.stdout:
                return True
            tail = (r.stderr or "").strip().splitlines()[-1:] or ["?"]
            print(f"# tpu probe attempt {attempt} failed: {tail[0]}",
                  file=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"# tpu probe attempt {attempt} timed out "
                  f"({int(budget)}s)", file=sys.stderr)
        remaining = min(probe_deadline - time.monotonic(),
                        _remaining_s() - CPU_RESERVE_S)
        if remaining <= 0:
            return False
        wait = min(max(PROBE_INTERVAL_S - (time.monotonic() - started), 5),
                   remaining)
        print(f"# re-probing in {int(wait)}s "
              f"({int(remaining)}s left in probe window)", file=sys.stderr)
        time.sleep(wait)


def _save_lkg(parsed: dict) -> None:
    if os.environ.get("BENCH_FAKE_CHILD"):
        # Test hook active: never let fabricated numbers overwrite the
        # checked-in last-known-good REAL measurement.
        return
    try:
        rec = dict(parsed)
        rec["measured_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                           time.gmtime())
        with open(LKG_PATH, "w") as f:
            json.dump(rec, f)
    except OSError:
        pass


def _attach_lkg(parsed: dict) -> dict:
    """On a fallback result, carry the last-known-good REAL TPU figure
    (stale: true) so the emitted line never reads as a 400x regression
    when the tunnel — not the backend — was the failure."""
    try:
        with open(LKG_PATH) as f:
            lkg = json.load(f)
    except (OSError, json.JSONDecodeError):
        return parsed
    lkg["stale"] = True
    parsed["last_known_good_tpu"] = lkg
    return parsed


def _run_child(backend: str, platform_env: str, timeout_s: int):
    """Run the measurement child; returns (parsed_json | None, note)."""
    fake = os.environ.get("BENCH_FAKE_CHILD")
    if fake:  # test hook: stand in for the (minutes-long) real child
        return json.loads(fake), ""
    env = dict(os.environ)
    env["BENCH_CHILD"] = "1"
    # Clear any inherited value first: a leftover JAX_PLATFORMS=cpu from a
    # debug shell must not silently turn the nominal TPU measurement into
    # an unmarked CPU run.
    env.pop("JAX_PLATFORMS", None)
    if platform_env:
        env["JAX_PLATFORMS"] = platform_env
    if platform_env == "cpu" and env.get("BENCH_BACKEND") == "sharded" and \
            "xla_force_host_platform_device_count" not in \
            env.get("XLA_FLAGS", ""):
        # The sharded config-5 datapoint needs a mesh even on the XLA-CPU
        # fallback: stand up the 8-device virtual mesh (BASELINE's
        # stand-in until the real tunnel answers).
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            " --xla_force_host_platform_device_count=8"
                            ).strip()
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), backend],
            timeout=timeout_s, capture_output=True, text=True, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)) or ".")
    except subprocess.TimeoutExpired:
        return None, f"child timed out after {timeout_s}s"
    if r.stderr:
        for line in r.stderr.strip().splitlines()[-6:]:
            print(f"# child: {line}", file=sys.stderr)
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-1:] or ["?"]
        return None, f"child rc={r.returncode}: {tail[0][:200]}"
    for line in reversed((r.stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), ""
            except json.JSONDecodeError:
                continue
    return None, "child produced no JSON line"


def _provisional_line() -> None:
    """Print a parseable provisional JSON result IMMEDIATELY (before any
    probing): if the driver's timeout kills this process at ANY later
    point, the captured stdout still ends with (at least) this line, so
    the round records the last-known-good figure instead of parsed=null.
    Every later phase prints a fresh (final) line that supersedes it."""
    print(json.dumps(_attach_lkg({
        "metric": "conflict_range_checks_per_s", "value": 0.0,
        "unit": "ranges/s", "vs_baseline": 0.0, "provisional": True,
        "error": "provisional: measurement still running when emitted "
                 f"(deadline budget {int(DEADLINE_S)}s)"})), flush=True)


def parent_main(backend: str) -> None:
    errors = []
    if backend == "tpu":
        _provisional_line()
        forced = os.environ.get("BENCH_FORCE_FALLBACK") == "1"
        if not forced and _probe_tpu():
            for attempt in range(2):
                budget = _remaining_s() - CPU_RESERVE_S
                if budget <= 30:
                    errors.append("tpu child skipped: deadline budget "
                                  "exhausted")
                    break
                parsed, note = _run_child(
                    "tpu", "", min(CHILD_TIMEOUT_S, budget))
                if parsed is not None:
                    _save_lkg(parsed)
                    print(json.dumps(parsed), flush=True)
                    return
                errors.append(f"tpu run {attempt + 1}: {note}")
                print(f"# {errors[-1]}", file=sys.stderr)
        else:
            errors.append(
                "forced fallback (BENCH_FORCE_FALLBACK=1)" if forced else
                "axon/TPU backend unreachable within the probe budget "
                f"(deadline {int(DEADLINE_S)}s)")
        # Degraded mode: same kernels, same parity assertions, XLA CPU,
        # smaller stream (a full-size run exceeds any sane timeout there).
        print("# falling back to JAX CPU backend", file=sys.stderr)
        os.environ["BENCH_SMALL"] = "1"
        budget = max(_remaining_s() - 15, 30)
        parsed, note = _run_child("tpu", "cpu",
                                  min(CPU_CHILD_TIMEOUT_S, budget))
        if parsed is not None:
            parsed["error"] = ("TPU unavailable; measured on XLA-CPU "
                               "fallback — " + "; ".join(errors))
            print(json.dumps(_attach_lkg(parsed)), flush=True)
            return
        errors.append(f"cpu fallback: {note}")
        print(json.dumps(_attach_lkg({
            "metric": "conflict_range_checks_per_s", "value": 0.0,
            "unit": "ranges/s", "vs_baseline": 0.0,
            "error": "; ".join(errors)})), flush=True)
        return
    # backend == "cpu": oracle-only mode, no TPU involved.
    parsed, note = _run_child("cpu", "cpu",
                              min(CPU_CHILD_TIMEOUT_S,
                                  max(_remaining_s() - 15, 30)))
    if parsed is not None:
        print(json.dumps(parsed), flush=True)
        return
    print(json.dumps({
        "metric": "conflict_range_checks_per_s", "value": 0.0,
        "unit": "ranges/s", "vs_baseline": 0.0, "error": note}), flush=True)


def main() -> None:
    backend = sys.argv[1] if len(sys.argv) > 1 else "tpu"
    if backend == "e2e":
        # End-to-end commits/s (ISSUE 14): real-TCP cluster off/on
        # measurement writing BENCH_r10.json, or --smoke for the
        # in-process tier-1 parity gate.
        e2e_main()
        return
    if backend == "reads":
        # Read-path throughput (ISSUE 15): real-TCP point/scan bench +
        # btree micro + e2e recheck writing BENCH_r11.json, or --smoke
        # for the in-process tier-1 parity gate.
        reads_main()
        return
    if backend == "sched":
        # Conflict-aware scheduling bench (ISSUE 12): in-process (the
        # oracle-model passes need no device budget machinery), writes
        # BENCH_r09.json.
        sched_main()
        return
    if backend == "resolvers":
        # Multi-resolver sweep (ISSUE 7): runs in-process (the sweep's
        # batches are small enough not to need the parent/child budget
        # machinery) and writes BENCH_r07.json.
        resolver_sweep_main()
        return
    if backend == "sharded":
        # Mesh-sharded resolver over every attached device (BASELINE
        # config 5 axis); otherwise identical to the tpu run.
        os.environ["BENCH_BACKEND"] = "sharded"
        backend = "tpu"
    if backend not in ("tpu", "cpu"):
        print(f"unknown backend {backend!r}: expected tpu|cpu|sharded",
              file=sys.stderr)
        sys.exit(2)
    if os.environ.get("BENCH_CHILD") == "1":
        child_main(backend)
    else:
        parent_main(backend)


if __name__ == "__main__":
    main()
