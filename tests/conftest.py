"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform so multi-chip sharding tests
run without TPU hardware (the driver's dryrun does the same)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402

from foundationdb_tpu.core import (DeterministicRandom, EventLoop,  # noqa: E402
                                   set_deterministic_random, set_event_loop)


@pytest.fixture()
def loop():
    """Fresh deterministic sim event loop per test."""
    lp = EventLoop(sim=True)
    set_event_loop(lp)
    set_deterministic_random(DeterministicRandom(1))
    yield lp
    set_event_loop(None)
