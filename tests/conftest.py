"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform so multi-chip sharding tests
run without TPU hardware (the driver's dryrun does the same).

This must be robust to the axon TPU-tunnel site hook: that hook registers an
'axon' PJRT plugin whose client creation can block on the tunnel, and jax
initializes ALL registered plugins on the first backends() call — so merely
setting JAX_PLATFORMS=cpu is not enough.  We deregister the axon factory
before any backend initialization; tests are hermetic and never touch the
tunnel.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

try:  # deregister the axon TPU-tunnel plugin (see module docstring)
    import jax
    from jax._src import xla_bridge as _xb
    _xb._backend_factories.pop("axon", None)
    # The site hook imports jax at interpreter start, latching
    # JAX_PLATFORMS=axon into jax's config; override it explicitly.
    jax.config.update("jax_platforms", "cpu")
    # Persistent XLA compile cache, shared with the bench child's
    # (bench.py JAX_CACHE_DIR): the sharded resolve/merge programs cost
    # tens of seconds of XLA:CPU compile per shape, and recompiling them
    # on every suite run is what pushed test_sharded_resolver past the
    # tier-1 budget (VERDICT round 5, weak #3).  Gated on modern jax
    # (same predicate as parallel/sharded_window.jit_sharded): on 0.4.x,
    # executables DESERIALIZED from this cache for shard_map programs on
    # the virtual CPU mesh returned wrong verdicts and corrupted the heap
    # (cold compiles were always correct; only reloads misbehaved).
    if hasattr(jax, "shard_map"):
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("JAX_CACHE_DIR", "/tmp/jax_bench_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass

import pytest  # noqa: E402

from foundationdb_tpu.core import (DeterministicRandom, EventLoop,  # noqa: E402
                                   set_deterministic_random, set_event_loop)


@pytest.fixture()
def loop():
    """Fresh deterministic sim event loop per test."""
    lp = EventLoop(sim=True)
    set_event_loop(lp)
    set_deterministic_random(DeterministicRandom(1))
    yield lp
    set_event_loop(None)
