"""End-to-end transaction pipeline tests in deterministic simulation.

Exercises the full commit path — client GRV -> commit proxy batching ->
master version allocation -> resolver conflict detection -> TLog push ->
storage pull -> reads — mirroring how the reference tests the pipeline in
simulation (SURVEY.md §3.1-3.3, workloads like Cycle/ConflictRange)."""

import pytest

from foundationdb_tpu.core import FdbError
from foundationdb_tpu.server.cluster import SimCluster
from foundationdb_tpu.server.shardmap import RangeMap
from foundationdb_tpu.server.storage import VersionedMap
from foundationdb_tpu.txn.types import MutationType


@pytest.fixture()
def cluster():
    c = SimCluster(n_resolvers=1, n_storage=2, n_tlogs=1)
    yield c
    from foundationdb_tpu.core import set_event_loop
    from foundationdb_tpu.rpc.sim import set_simulator
    set_simulator(None)
    set_event_loop(None)


def run(cluster, coro, timeout=30):
    return cluster.run_until(cluster.loop.spawn(coro), timeout=timeout)


# ---------------------------------------------------------------------------
# RangeMap / VersionedMap units
# ---------------------------------------------------------------------------

def test_rangemap_split_and_lookup():
    rm = RangeMap(default=0)
    rm.set_range(b"c", b"m", 1)
    rm.set_range(b"f", b"h", 2)
    assert rm.lookup(b"a") == 0
    assert rm.lookup(b"c") == 1
    assert rm.lookup(b"f") == 2
    assert rm.lookup(b"g\xff") == 2
    assert rm.lookup(b"h") == 1
    assert rm.lookup(b"m") == 0
    spans = list(rm.intersecting(b"e", b"i"))
    assert spans == [(b"e", b"f", 1), (b"f", b"h", 2), (b"h", b"i", 1)]


def test_rangemap_coalesce():
    rm = RangeMap(default=0)
    rm.set_range(b"a", b"b", 1)
    rm.set_range(b"b", b"c", 1)
    assert len(list(rm.ranges())) == 3  # [,a) [a,c) [c,)
    rm.set_range(b"a", b"c", 0)
    assert len(list(rm.ranges())) == 1


def test_versioned_map_mvcc():
    vm = VersionedMap()
    vm.set(b"k", b"v1", 10)
    vm.set(b"k", b"v2", 20)
    assert vm.get(b"k", 5) is None
    assert vm.get(b"k", 10) == b"v1"
    assert vm.get(b"k", 15) == b"v1"
    assert vm.get(b"k", 25) == b"v2"
    vm.clear_range(b"a", b"z", 30)
    assert vm.get(b"k", 25) == b"v2"
    assert vm.get(b"k", 30) is None
    vm.forget_before(35)
    assert vm.get(b"k", 40) is None
    assert len(vm) == 0  # tombstone GC'd


def test_versioned_map_range_read():
    vm = VersionedMap()
    for i in range(5):
        vm.set(b"k%d" % i, b"v%d" % i, 10)
    vm.set(b"k2", None, 20)
    data, more = vm.range_read(b"k0", b"k9", 20, 10, 1 << 20)
    assert [k for k, _ in data] == [b"k0", b"k1", b"k3", b"k4"]
    data, more = vm.range_read(b"k0", b"k9", 10, 2, 1 << 20)
    assert len(data) == 2 and more


# ---------------------------------------------------------------------------
# End-to-end pipeline
# ---------------------------------------------------------------------------

def test_commit_and_read(cluster):
    db = cluster.database()

    async def go():
        txn = db.create_transaction()
        txn.set(b"hello", b"world")
        txn.set(b"foo", b"bar")
        v = await txn.commit()
        assert v > 0
        txn2 = db.create_transaction()
        assert await txn2.get(b"hello") == b"world"
        assert await txn2.get(b"foo") == b"bar"
        assert await txn2.get(b"missing") is None
        return v

    assert run(cluster, go()) > 0


def test_read_your_writes(cluster):
    db = cluster.database()

    async def go():
        txn = db.create_transaction()
        txn.set(b"a", b"1")
        assert await txn.get(b"a") == b"1"       # uncommitted, visible to us
        txn.clear(b"a")
        assert await txn.get(b"a") is None
        txn.set(b"a", b"2")
        assert await txn.get(b"a") == b"2"
        await txn.commit()
        txn2 = db.create_transaction()
        assert await txn2.get(b"a") == b"2"

    run(cluster, go())


def test_conflict_aborts_second_writer(cluster):
    db = cluster.database()

    async def go():
        # Both transactions read k then write it, overlapping in time:
        # the second to commit must get not_committed.
        t1 = db.create_transaction()
        t2 = db.create_transaction()
        await t1.get(b"k")
        await t2.get(b"k")
        t1.set(b"k", b"t1")
        t2.set(b"k", b"t2")
        await t1.commit()
        with pytest.raises(FdbError) as ei:
            await t2.commit()
        assert ei.value.name == "not_committed"
        # And the retry loop makes t2 succeed on a fresh snapshot.
        await t2.on_error(ei.value)
        await t2.get(b"k")
        t2.set(b"k", b"t2")
        await t2.commit()
        t3 = db.create_transaction()
        assert await t3.get(b"k") == b"t2"

    run(cluster, go())


def test_blind_writes_do_not_conflict(cluster):
    db = cluster.database()

    async def go():
        t1 = db.create_transaction()
        t2 = db.create_transaction()
        t1.set(b"k", b"1")
        t2.set(b"k", b"2")
        await t1.commit()
        await t2.commit()   # no read conflict ranges -> no conflict

    run(cluster, go())


def test_atomic_add_end_to_end(cluster):
    db = cluster.database()

    async def go():
        import struct
        t = db.create_transaction()
        t.atomic_op(MutationType.AddValue, b"ctr", struct.pack("<q", 5))
        await t.commit()
        t = db.create_transaction()
        t.atomic_op(MutationType.AddValue, b"ctr", struct.pack("<q", 7))
        await t.commit()
        t = db.create_transaction()
        raw = await t.get(b"ctr")
        assert struct.unpack("<q", raw)[0] == 12

    run(cluster, go())


def test_range_read_across_shards(cluster):
    # n_storage=2 splits the keyspace at 0x80; write keys on both sides.
    db = cluster.database()

    async def go():
        txn = db.create_transaction()
        keys = [b"a1", b"a2", b"\x90x", b"\x90y"]
        for i, k in enumerate(keys):
            txn.set(k, b"v%d" % i)
        await txn.commit()
        t2 = db.create_transaction()
        data = await t2.get_range(b"", b"\xfe")
        assert [k for k, _ in data] == sorted(keys)
        # Merge with uncommitted writes + clears.
        t2.set(b"a15", b"new")
        t2.clear(b"\x90x")
        data = await t2.get_range(b"", b"\xfe")
        assert [k for k, _ in data] == [b"a1", b"a15", b"a2", b"\x90y"]

    run(cluster, go())


def test_clear_range_end_to_end(cluster):
    db = cluster.database()

    async def go():
        txn = db.create_transaction()
        for i in range(6):
            txn.set(b"row%d" % i, b"x")
        await txn.commit()
        t2 = db.create_transaction()
        t2.clear(b"row1", b"row4")
        await t2.commit()
        t3 = db.create_transaction()
        data = await t3.get_range(b"row", b"rox")
        assert [k for k, _ in data] == [b"row0", b"row4", b"row5"]

    run(cluster, go())


def test_watch_fires_on_change(cluster):
    db = cluster.database()

    async def go():
        t0 = db.create_transaction()
        t0.set(b"w", b"0")
        await t0.commit()
        t1 = db.create_transaction()
        watch_f = await t1.watch(b"w")
        assert not watch_f.is_ready()
        t2 = db.create_transaction()
        t2.set(b"w", b"1")
        await t2.commit()
        await watch_f   # must fire now

    run(cluster, go())


def test_multi_resolver_and_proxy(cluster):
    del cluster  # use a custom topology
    c = SimCluster(n_resolvers=2, n_storage=4, n_tlogs=2,
                   n_commit_proxies=2, n_grv_proxies=2, replication=2)
    db = c.database()

    async def go():
        # Writes spanning both resolvers' ranges (split at 0x80).
        txn = db.create_transaction()
        txn.set(b"low", b"1")
        txn.set(b"\x90high", b"2")
        await txn.commit()
        t2 = db.create_transaction()
        assert await t2.get(b"low") == b"1"
        assert await t2.get(b"\x90high") == b"2"
        # Conflict via a range spanning both resolvers.
        t3 = db.create_transaction()
        await t3.get_range(b"", b"\xf0")
        t3.set(b"probe", b"x")
        t4 = db.create_transaction()
        t4.set(b"\x90high", b"3")
        await t4.commit()
        with pytest.raises(FdbError):
            await t3.commit()

    c.run_until(c.loop.spawn(go()), timeout=30)
    from foundationdb_tpu.core import set_event_loop
    from foundationdb_tpu.rpc.sim import set_simulator
    set_simulator(None)
    set_event_loop(None)


def test_pipeline_with_tpu_conflict_backend():
    """North-star integration: the resolver's ConflictSet backend selector
    set to the JAX device kernel, driven through the full commit path."""
    c = SimCluster(n_resolvers=1, n_storage=1, n_tlogs=1,
                   conflict_backend="tpu")
    db = c.database()

    async def go():
        t1 = db.create_transaction()
        t1.set(b"k", b"v0")
        await t1.commit()
        # Read-write conflict must be detected by the device kernel.
        ta = db.create_transaction()
        tb = db.create_transaction()
        await ta.get(b"k")
        await tb.get(b"k")
        ta.set(b"k", b"a")
        tb.set(b"k", b"b")
        await ta.commit()
        with pytest.raises(FdbError) as ei:
            await tb.commit()
        assert ei.value.name == "not_committed"
        t3 = db.create_transaction()
        assert await t3.get(b"k") == b"a"

    c.run_until(c.loop.spawn(go()), timeout=30)
    # The "tpu" backend arrives wrapped in the supervision layer (this is
    # the production shape: deadline budget + degrade-to-CPU + exact
    # long-key recheck); the device underneath is the JAX kernel, healthy.
    from foundationdb_tpu.conflict.supervisor import SupervisedConflictSet
    from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet
    cs = c.resolvers[0].conflict_set
    assert isinstance(cs, SupervisedConflictSet)
    assert isinstance(cs.device, TpuConflictSet)
    assert not cs.degraded and cs.stats["device_batches"] > 0
    from foundationdb_tpu.core import set_event_loop
    from foundationdb_tpu.rpc.sim import set_simulator
    set_simulator(None)
    set_event_loop(None)


def test_run_retry_helper(cluster):
    db = cluster.database()

    async def go():
        async def body(txn):
            v = await txn.get(b"n")
            n = int(v or b"0")
            txn.set(b"n", b"%d" % (n + 1))
            return n + 1

        for expected in (1, 2, 3):
            got = await db.create_transaction().run(body)
            assert got == expected

    run(cluster, go())


def test_reverse_range_returns_last_keys(cluster):
    db = cluster.database()

    async def go():
        txn = db.create_transaction()
        for i in range(20):
            txn.set(b"r%02d" % i, b"v%d" % i)
        await txn.commit()
        t2 = db.create_transaction()
        data = await t2.get_range(b"r", b"s", limit=5, reverse=True)
        assert [k for k, _ in data] == [b"r19", b"r18", b"r17", b"r16",
                                        b"r15"]
        # Reverse + RYW overlay.
        t2.set(b"r99", b"new")
        t2.clear(b"r19")
        data = await t2.get_range(b"r", b"s", limit=3, reverse=True)
        assert [k for k, _ in data] == [b"r99", b"r18", b"r17"]

    run(cluster, go())


def test_range_limit_with_clears_no_gaps(cluster):
    db = cluster.database()

    async def go():
        txn = db.create_transaction()
        for i in range(30):
            txn.set(b"g%02d" % i, b"x")
        await txn.commit()
        t2 = db.create_transaction()
        t2.clear(b"g00", b"g10")   # clears shrink the snapshot prefix
        data = await t2.get_range(b"g", b"h", limit=5)
        # Must be the first 5 surviving keys, contiguous — no gaps.
        assert [k for k, _ in data] == [b"g10", b"g11", b"g12", b"g13",
                                        b"g14"]

    run(cluster, go())


def test_conflict_only_transaction_resolves(cluster):
    db = cluster.database()

    async def go():
        # Locking pattern: a txn with only an explicit write conflict range
        # must go through the resolver (and conflict with a later reader).
        t1 = db.create_transaction()
        t2 = db.create_transaction()
        await t2.get(b"lock")           # t2 reads before t1's "write"
        t1.add_write_conflict_range(b"lock", b"lock\x00")
        v = await t1.commit()
        assert v > 0                    # really resolved, not skipped
        t2.add_write_conflict_range(b"other", b"other\x00")
        with pytest.raises(FdbError) as ei:
            await t2.commit()
        assert ei.value.name == "not_committed"

    run(cluster, go())


def test_backoff_is_capped(cluster):
    db = cluster.database()

    async def go():
        from foundationdb_tpu.core.error import err as mkerr
        from foundationdb_tpu.core.knobs import client_knobs
        txn = db.create_transaction()
        for _ in range(20):
            await txn.on_error(mkerr("not_committed"))
        assert txn._backoff <= client_knobs().DEFAULT_MAX_BACKOFF
        assert txn._extra_write_ranges == []

    run(cluster, go(), timeout=60)


def test_transaction_too_old(cluster):
    db = cluster.database()

    async def go():
        txn = db.create_transaction()
        await txn.get(b"k")        # pins an old read version
        # Push the version frontier way past the MVCC window (5s of
        # versions at 1M/s = 5e6; sim time advance drives the master rate).
        from foundationdb_tpu.core.scheduler import delay
        for _ in range(8):
            t = db.create_transaction()
            t.set(b"filler", b"x")
            await t.commit()
            await delay(1.0)
        txn.set(b"k", b"stale")
        with pytest.raises(FdbError) as ei:
            await txn.commit()
        assert ei.value.name in ("transaction_too_old", "not_committed")

    run(cluster, go(), timeout=60)
