"""Cluster-level durability: acked commits survive whole-cluster power loss.

The restarting-test scenario (reference tests/restarting/ + the simulator's
power-loss file semantics, fdbrpc/AsyncFileNonDurable.actor.h): a cluster
takes commits, every machine loses power uncleanly (un-synced writes
dropped/corrupted), the cluster reboots from durable files only —
coordinator generation registers, TLog disk queues, storage engines — and
every acknowledged commit must still be readable.  In-flight (un-acked)
transactions may or may not survive; what's forbidden is losing an ack."""

import pytest

from foundationdb_tpu.core import FdbError
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration

from test_recovery import commit_kv, read_key, teardown  # noqa: F401


def make_cluster(**cfg):
    n_workers = cfg.pop("n_workers", 5)
    n_storage_workers = cfg.pop("n_storage_workers", 2)
    config = DatabaseConfiguration(**cfg)
    return SimFdbCluster(config=config, n_workers=n_workers,
                         n_storage_workers=n_storage_workers)


def test_power_fail_reboot_preserves_acked_commits(teardown):  # noqa: F811
    c = make_cluster()
    db = c.database()
    acked = {}

    async def load():
        for i in range(30):
            k, v = b"key%03d" % i, b"value%03d" % i
            await commit_kv(db, k, v)
            acked[k] = v

    c.run_until(c.loop.spawn(load()), timeout=120)
    assert len(acked) == 30

    c.power_fail_reboot()

    db2 = c.database()

    async def check():
        for k, v in acked.items():
            assert await read_key(db2, k) == v, f"lost acked key {k!r}"
        # The recovered cluster accepts new commits in a later epoch.
        await commit_kv(db2, b"after-reboot", b"yes")
        assert await read_key(db2, b"after-reboot") == b"yes"
        cc = c.current_cc()
        assert cc is not None and cc.db_info.epoch >= 2

    c.run_until(c.loop.spawn(check()), timeout=120)


def test_power_fail_reboot_twice(teardown):  # noqa: F811
    """Two consecutive power-fail/reboot cycles: generation handoff must
    re-persist carried data (TLog.recover_from), or the second reboot
    loses commits from before the first."""
    c = make_cluster()
    db = c.database()

    c.run_until(c.loop.spawn(commit_kv(db, b"gen1", b"a")), timeout=60)
    c.power_fail_reboot()

    db2 = c.database()

    async def mid():
        assert await read_key(db2, b"gen1") == b"a"
        await commit_kv(db2, b"gen2", b"b")

    c.run_until(c.loop.spawn(mid()), timeout=120)
    c.power_fail_reboot()

    db3 = c.database()

    async def final():
        assert await read_key(db3, b"gen1") == b"a"
        assert await read_key(db3, b"gen2") == b"b"

    c.run_until(c.loop.spawn(final()), timeout=120)


def test_storage_worker_power_fail_recovers_from_engine(teardown):  # noqa: F811
    """One storage machine power-fails; its worker reboots, recovers the
    storage role from the engine files, and the data stays readable
    through the recovered replica."""
    c = make_cluster(n_workers=5, n_storage_workers=1, n_storage=1)
    db = c.database()

    async def load():
        for i in range(10):
            await commit_kv(db, b"s%02d" % i, b"v%02d" % i)

    c.run_until(c.loop.spawn(load()), timeout=60)

    # Power-fail the single storage machine, then reboot it in place.
    c.sim.power_fail_machine("mach.worker0")
    from foundationdb_tpu.core.futures import AsyncVar
    from foundationdb_tpu.server.coordination import monitor_leader
    from foundationdb_tpu.server.worker import Worker
    p = c.sim.new_process(name="worker0", machineid="mach.worker0",
                          process_class="storage")
    leader_var = AsyncVar(None)
    p.spawn(monitor_leader(c.coordinator_clients, leader_var),
            "worker0.monitorLeader")
    w = Worker(p, c.coordinator_clients, process_class="storage",
               config=c.config)
    w.run(leader_var)

    async def check():
        from foundationdb_tpu.core.scheduler import delay
        # Wait for the rebooted worker to re-register with its recovered
        # storage role, then force an epoch change: recovery resolves the
        # storage tag to the recovered interface (until DataDistribution
        # lands, re-registration is adopted at recovery time).
        # Workers announce LIVE roles too, so recovered_storage alone no
        # longer distinguishes the rebooted incarnation — match on the new
        # process address.
        while True:
            cc = c.current_cc()
            reg = cc.workers.get("worker0") if cc is not None else None
            if reg is not None and reg.recovered_storage and \
                    reg.worker.init_storage.endpoint.address == p.address:
                break
            await delay(0.1)
        master_proc = c.process_of(c.current_cc().db_info.master)
        c.sim.kill_process(master_proc)
        for i in range(10):
            assert await read_key(db, b"s%02d" % i) == b"v%02d" % i

    c.run_until(c.loop.spawn(check()), timeout=120)
