"""Region replication + KillRegion failover (VERDICT r4 item 4).

Reference: fdbserver/LogRouter.actor.cpp (router pull plane),
TagPartitionedLogSystem.actor.cpp (remote tlog sets, epochEnd on the
remote set), workloads/KillRegion.actor.cpp (fail over, verify, fail
back).  Topology under test (server/log_router.py):

    proxies --twin tags--> primary TLogs <--peek-- LogRouter
        <--peek-- remote TLog <--peek-- remote storage replicas

The failover is the DRAINED switchover (fdbcli-style): writes stop, the
remote plane converges to the last commit, then the primary dc dies and
recovery adopts the remote replicas — no acked commit may be lost.
"""

import pytest

from foundationdb_tpu.client.management import change_configuration
from foundationdb_tpu.core.scheduler import delay
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration
from foundationdb_tpu.server.log_router import twin_tag

from test_recovery import commit_kv, read_key, teardown  # noqa: F401

N_KEYS = 20


def make_region_cluster():
    config = DatabaseConfiguration()
    return SimFdbCluster(config=config, n_workers=5, n_storage_workers=2)


def add_remote_dc(c):
    """The remote dc joins AFTER cold boot (like provisioning a second
    region for an existing cluster): storage for replicas, stateless for
    the remote plane's routers/TLogs — and a CC candidate so the dc can
    elect a controller once the primary dc dies.  Joining post-boot also
    keeps cold-boot storage placement inside dc0 (the primary)."""
    c.add_worker("storage", name="rworker0", dcid="dcR")
    c.add_worker("storage", name="rworker1", dcid="dcR")
    c.add_worker("stateless", name="rworker2", dcid="dcR", campaign=True)


async def _wait_remote_plane(c, timeout_s=60.0):
    waited = 0.0
    while waited < timeout_s:
        cc = c.current_cc()
        info = cc.db_info if cc is not None else None
        if info is not None and getattr(info, "remote_tlogs", None) and \
                getattr(info, "remote_storage", None):
            return info
        await delay(0.5)
        waited += 0.5
    raise AssertionError("remote plane never recruited")


async def _wait_replicas_at(info, version, timeout_s=120.0):
    """Drained convergence: every remote replica applied >= version."""
    waited = 0.0
    while waited < timeout_s:
        roles = [getattr(i, "role", None)
                 for i in info.remote_storage.values()]
        if all(r is not None and r.version.get() >= version for r in roles):
            return
        await delay(0.5)
        waited += 0.5
    raise AssertionError(
        f"replicas never converged to {version}: "
        f"{[(r.id, r.version.get()) for r in roles if r is not None]}")


def test_region_replication_and_drained_failover(teardown):  # noqa: F811
    c = make_region_cluster()
    db = c.database()

    async def load():
        for i in range(N_KEYS):
            await commit_kv(db, b"rk%03d" % i, b"rv%03d" % i)
        return True

    c.run_until(c.loop.spawn(load()), timeout=180)
    add_remote_dc(c)

    async def configure():
        # Turn the region on mid-life: the next epoch recruits routers,
        # a remote TLog, and replicas seeded via fetch from their twins.
        await change_configuration(db, usable_regions=2, remote_dc="dcR")
        return True

    c.run_until(c.loop.spawn(configure()), timeout=120)

    info = c.run_until(c.loop.spawn(_wait_remote_plane(c)), timeout=120)
    assert len(info.log_routers) >= 1
    # Replicas carry TWIN tags of the primary storage tags.
    for tt in info.remote_storage:
        assert twin_tag(tt) in info.storage_servers

    async def drain():
        # A marker commit, then wait until every replica applied it —
        # this also proves the mid-life fetch seeding converged.
        t = db.create_transaction()
        v = None
        while v is None:
            try:
                t.set(b"marker", b"drained")
                v = await t.commit()
            except Exception as e:  # noqa: BLE001
                await t.on_error(e)
        cc = c.current_cc()
        await _wait_replicas_at(cc.db_info, v)
        return True

    c.run_until(c.loop.spawn(drain()), timeout=300)

    # KillRegion: every process in the primary dc dies (workers AND the
    # current CC/master with them).  Coordinators live outside both dcs.
    for p, _w, _cc, _lv in list(c.workers):
        if p.locality.dcid == "dc0":
            c.sim.kill_process(p)

    async def after_failover():
        # The remote dc elects a CC, recovery fails over to the remote
        # plane, and EVERY acked commit is still readable.
        for i in range(N_KEYS):
            assert await read_key(db, b"rk%03d" % i) == b"rv%03d" % i, i
        assert await read_key(db, b"marker") == b"drained"
        await commit_kv(db, b"post-failover", b"yes")
        assert await read_key(db, b"post-failover") == b"yes"
        return True

    c.run_until(c.loop.spawn(after_failover()), timeout=600)

    # The adopted storage set serves under twin tags.
    cc = c.current_cc()
    assert cc is not None
    assert all(t >= 1_000_000 for t in cc.db_info.storage_servers), \
        cc.db_info.storage_servers.keys()


def test_region_recruit_skipped_without_remote_workers(teardown):  # noqa: F811
    """usable_regions=2 with no workers in remote_dc degrades to
    primary-only instead of wedging recovery."""
    config = DatabaseConfiguration(usable_regions=2, remote_dc="dcR")
    c = SimFdbCluster(config=config, n_workers=4, n_storage_workers=2)
    db = c.database()

    async def go():
        await commit_kv(db, b"k", b"v")
        assert await read_key(db, b"k") == b"v"
        return True

    c.run_until(c.loop.spawn(go()), timeout=120)
    cc = c.current_cc()
    assert cc is not None and not cc.db_info.remote_tlogs


def test_remote_plane_heals_in_epoch(teardown):  # noqa: F811
    """In-epoch remote-plane healing: killing the process hosting the
    remote TLog (and router) replaces the plane WITHOUT an epoch change;
    replication converges again on the new plane."""
    c = make_region_cluster()
    db = c.database()

    async def setup():
        for i in range(8):
            await commit_kv(db, b"hk%03d" % i, b"hv%03d" % i)
        return True

    c.run_until(c.loop.spawn(setup()), timeout=180)
    add_remote_dc(c)

    async def configure():
        await change_configuration(db, usable_regions=2, remote_dc="dcR")
        return True

    c.run_until(c.loop.spawn(configure()), timeout=120)
    info = c.run_until(c.loop.spawn(_wait_remote_plane(c)), timeout=120)
    epoch_before = c.current_cc().db_info.epoch
    old_rt_ids = [t.id for t in info.remote_tlogs]

    # Kill every dcR worker hosting a remote TLog or router.
    victims = set()
    for t in list(info.remote_tlogs) + list(info.log_routers):
        p = c.process_of(t)
        if p is not None:
            victims.add(p)
    assert victims
    for p in victims:
        c.sim.kill_process(p)
    # Replacement capacity in the remote dc.
    c.add_worker("stateless", name="rheal0", dcid="dcR")

    async def wait_healed():
        from foundationdb_tpu.core.scheduler import delay
        for _ in range(240):
            cc = c.current_cc()
            info2 = cc.db_info if cc is not None else None
            if info2 is not None and info2.remote_tlogs and \
                    [t.id for t in info2.remote_tlogs] != old_rt_ids:
                return info2
            await delay(0.5)
        raise AssertionError("remote plane never healed")

    info2 = c.run_until(c.loop.spawn(wait_healed()), timeout=300)
    # Same epoch: healed WITHOUT a recovery.
    assert c.current_cc().db_info.epoch == epoch_before
    # Surviving replicas were ADOPTED (same live role objects), not
    # wiped and re-recruited.
    before_roles = {t: getattr(i, "role", None)
                    for t, i in info.remote_storage.items()}
    for t, i in info2.remote_storage.items():
        assert getattr(i, "role", None) is before_roles.get(t), t

    async def converges():
        t = db.create_transaction()
        v = None
        while v is None:
            try:
                t.set(b"post-heal", b"1")
                v = await t.commit()
            except Exception as e:  # noqa: BLE001
                await t.on_error(e)
        await _wait_replicas_at(c.current_cc().db_info, v)
        return True

    assert c.run_until(c.loop.spawn(converges()), timeout=300)
