"""bench.py survivability: a tunnel-outage round must still emit the
last-known-good real-TPU figure (stale-marked) alongside the fallback
number (VERDICT round-4 item 3).

The full fallback path (XLA-CPU child) takes minutes, so the integration
test exercises parent_main with BENCH_FORCE_FALLBACK=1 and a stubbed child
via BENCH_FAKE_CHILD; the LKG persistence helpers are unit-tested directly.
"""

import importlib.util
import json
import os
import subprocess
import sys

_BENCH = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")


def _load_bench():
    spec = importlib.util.spec_from_file_location("bench_under_test", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_attach_lkg_roundtrip(tmp_path):
    bench = _load_bench()
    bench.LKG_PATH = str(tmp_path / "lkg.json")
    measured = {"metric": "conflict_range_checks_per_s", "value": 123456.0,
                "unit": "ranges/s", "vs_baseline": 0.123}
    bench._save_lkg(measured)
    fallback = {"metric": "conflict_range_checks_per_s", "value": 500.0,
                "unit": "ranges/s", "vs_baseline": 0.0005,
                "error": "TPU unavailable"}
    out = bench._attach_lkg(fallback)
    lkg = out["last_known_good_tpu"]
    assert lkg["value"] == 123456.0
    assert lkg["stale"] is True
    assert "measured_at" in lkg
    # The fallback figure itself is untouched.
    assert out["value"] == 500.0


def test_attach_lkg_missing_file(tmp_path):
    bench = _load_bench()
    bench.LKG_PATH = str(tmp_path / "absent.json")
    fallback = {"value": 1.0}
    assert "last_known_good_tpu" not in bench._attach_lkg(fallback)


def test_repo_lkg_checked_in():
    """The repo carries the best real-TPU figure so a fresh checkout's
    outage round still reports measured capability."""
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "BENCH_LKG.json")
    with open(path) as f:
        lkg = json.load(f)
    assert lkg["value"] > 0
    assert lkg["unit"] == "ranges/s"


def test_forced_fallback_emits_both_numbers():
    """End-to-end parent path with a faked child: the emitted JSON line
    carries the degraded value, the error marker, AND the stale LKG."""
    env = dict(os.environ)
    env["BENCH_FORCE_FALLBACK"] = "1"
    env["BENCH_FAKE_CHILD"] = json.dumps(
        {"metric": "conflict_range_checks_per_s", "value": 525.0,
         "unit": "ranges/s", "vs_baseline": 0.0005})
    r = subprocess.run([sys.executable, _BENCH], capture_output=True,
                       text=True, timeout=120, env=env)
    assert r.returncode == 0, r.stderr
    line = [ln for ln in r.stdout.strip().splitlines()
            if ln.startswith("{")][-1]
    out = json.loads(line)
    assert out["value"] == 525.0
    assert "error" in out
    assert out["last_known_good_tpu"]["stale"] is True
    assert out["last_known_good_tpu"]["value"] > 0


def test_provisional_line_precedes_result():
    """The FIRST JSON line is printed before any probing so a driver
    SIGKILL at any later point still leaves a parseable artifact carrying
    the last-known-good TPU figure (VERDICT round-5 'Next round' #1)."""
    env = dict(os.environ)
    env["BENCH_FORCE_FALLBACK"] = "1"       # tunnel forced dead via env
    env["BENCH_DEADLINE_S"] = "60"
    env["BENCH_FAKE_CHILD"] = json.dumps(
        {"metric": "conflict_range_checks_per_s", "value": 525.0,
         "unit": "ranges/s", "vs_baseline": 0.0005})
    r = subprocess.run([sys.executable, _BENCH], capture_output=True,
                       text=True, timeout=120, env=env)
    assert r.returncode == 0, r.stderr
    lines = [ln for ln in r.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) >= 2, "expected provisional + final JSON lines"
    first, last = json.loads(lines[0]), json.loads(lines[-1])
    assert first.get("provisional") is True
    assert first["metric"] == "conflict_range_checks_per_s"
    assert first["last_known_good_tpu"]["value"] > 0   # parsed != null
    assert "provisional" not in last and last["value"] == 525.0


def test_dead_tunnel_respects_deadline_budget():
    """With the tunnel forced dead and a tiny BENCH_DEADLINE_S, the whole
    run (probe + fallback) completes well inside the budget instead of
    probing past it (the round-5 failure mode).  The probe path is real
    (no fake child short-circuit for probing decisions) but the fallback
    child is faked so the test stays fast."""
    import time
    env = dict(os.environ)
    env["BENCH_FORCE_FALLBACK"] = "1"
    env["BENCH_DEADLINE_S"] = "30"
    env["BENCH_FAKE_CHILD"] = json.dumps(
        {"metric": "conflict_range_checks_per_s", "value": 1.0,
         "unit": "ranges/s", "vs_baseline": 0.0})
    t0 = time.monotonic()
    r = subprocess.run([sys.executable, _BENCH], capture_output=True,
                       text=True, timeout=90, env=env)
    elapsed = time.monotonic() - t0
    assert r.returncode == 0, r.stderr
    assert elapsed < 45, f"bench overran its deadline budget: {elapsed:.0f}s"
    last = json.loads([ln for ln in r.stdout.strip().splitlines()
                       if ln.startswith("{")][-1])
    assert last["value"] == 1.0 and "error" in last


def test_heat_overhead_gate_unit():
    """run_heat_gate (ISSUE 8): the enabled-vs-disabled measurement runs
    on the supervised path and records both figures + overhead_pct.
    (The 2% acceptance bound applies to the real bench's batch sizes —
    the per-batch feed cost is knob-bounded and fixed, so this tiny
    smoke config inflates the percentage; here we assert shape and
    knob restoration only.)"""
    import numpy as np

    from foundationdb_tpu.conflict.oracle import OracleConflictSet
    from foundationdb_tpu.core.knobs import server_knobs

    bench = _load_bench()
    bench.TXNS_PER_BATCH = 400
    rng = np.random.default_rng(11)
    batches = []
    version = 1_000
    for _ in range(4):
        prev = version
        version += bench.VERSIONS_PER_BATCH
        batches.append((version, *bench.gen_batch(rng, version, prev)))
    out = bench.run_heat_gate(
        lambda oldest_version=0: OracleConflictSet(oldest_version),
        batches, lambda v: max(v - 5_000_000, 0))
    assert out["disabled_ranges_per_s"] > 0
    assert out["enabled_ranges_per_s"] > 0
    assert "overhead_pct" in out and out["batches"] == 4
    # The measurement must not leak the knob flip.
    assert server_knobs().HEAT_TELEMETRY_ENABLED is True
