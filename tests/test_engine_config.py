"""Committed storage-engine configuration reaches NEW recruits.

Reference: `configure ssd|memory` (fdbclient/ManagementAPI.actor.cpp) —
the engine is part of the committed DatabaseConfiguration, and servers
recruited after the change open the configured store.  Here the worker
only knows its static --config flag, so the recruiting epoch's EFFECTIVE
configuration must ride the InitializeStorageRequest (and ServerDBInfo,
for the DD's mid-epoch replacements)."""

import pytest

from foundationdb_tpu.client.management import change_configuration
from foundationdb_tpu.core.knobs import server_knobs
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration

from test_data_distribution import current_dd
from test_recovery import commit_kv, read_key, teardown  # noqa: F401
from test_storage_wiggle import wiggle_knobs  # noqa: F401


def test_configure_engine_reaches_replacement(teardown):  # noqa: F811
    c = SimFdbCluster(
        config=DatabaseConfiguration(n_storage=2, storage_replication=2),
        n_workers=6, n_storage_workers=3)   # one spare storage worker
    db = c.database()

    async def go():
        from foundationdb_tpu.core.scheduler import delay
        for i in range(20):
            await commit_kv(db, b"ec/%03d" % i, b"v%03d" % i)
        # Commit the engine change; the config bounce recovers an epoch
        # whose effective configuration carries it.
        await change_configuration(db, storage_engine="btree")
        await commit_kv(db, b"ec/post", b"after-configure")
        # Kill one storage machine: the DD replaces it, and the recruit
        # must open the CONFIGURED engine, not the worker's boot default.
        c.sim.power_fail_machine("mach.worker0")
        deadline = 90.0
        dd = None
        while deadline > 0:
            await delay(0.5)
            deadline -= 0.5
            dd = current_dd(c) or dd
            if dd is not None and dd.stats.get("rereplications", 0) > 0 \
                    and dd.moves_in_flight == 0:
                break
        assert dd is not None and dd.stats["rereplications"] > 0
        engines = {t: getattr(ssi, "engine_name", "?")
                   for t, ssi in dd.storage.items() if t in dd.healthy}
        # The replacement (highest tag) runs the configured engine.
        newest = max(engines)
        assert engines[newest] == "btree", engines
        for i in range(20):
            assert await read_key(db, b"ec/%03d" % i) == b"v%03d" % i
        assert await read_key(db, b"ec/post") == b"after-configure"
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=300)


def test_wiggle_migrates_engines(teardown, wiggle_knobs):  # noqa: F811
    """configure storage_engine=btree + perpetual wiggle => every storage
    server is re-imaged onto btree as the rotation reaches it (the
    reference wiggle's storeType-migration purpose)."""
    knobs = wiggle_knobs
    knobs.PERPETUAL_STORAGE_WIGGLE = 1
    knobs.STORAGE_WIGGLE_INTERVAL = 0.5
    c = SimFdbCluster(
        config=DatabaseConfiguration(n_storage=3, storage_replication=2),
        n_workers=6, n_storage_workers=3)
    db = c.database()

    async def go():
        from foundationdb_tpu.core.scheduler import delay
        for i in range(20):
            await commit_kv(db, b"em/%03d" % i, b"v%03d" % i)
        await change_configuration(db, storage_engine="btree")
        await commit_kv(db, b"em/post", b"after")
        deadline = 150.0
        dd = None
        while deadline > 0:
            await delay(1.0)
            deadline -= 1.0
            dd = current_dd(c) or dd
            if dd is None:
                continue
            engines = {t: getattr(ssi, "engine_name", "")
                       for t, ssi in dd.storage.items()
                       if t in dd.healthy}
            if engines and all(e == "btree" for e in engines.values()):
                break
        assert engines and all(e == "btree" for e in engines.values()), \
            engines
        for i in range(20):
            assert await read_key(db, b"em/%03d" % i) == b"v%03d" % i
        assert await read_key(db, b"em/post") == b"after"
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=400)

    # Whole-cluster power-fail AFTER the migration: the boot scan must
    # recover the btree stores (and find no stale .wal twin — the swap
    # deletes the old engine's files) with every acked key intact.
    c.power_fail_reboot()
    db2 = c.database()

    async def check():
        for i in range(20):
            assert await read_key(db2, b"em/%03d" % i) == b"v%03d" % i
        assert await read_key(db2, b"em/post") == b"after"
        await commit_kv(db2, b"em/rebooted", b"yes")
        assert await read_key(db2, b"em/rebooted") == b"yes"
        return True

    assert c.run_until(c.loop.spawn(check()), timeout=200)


def test_boot_scan_drops_stale_engine_twin(teardown):  # noqa: F811
    """Crash window between a migration's commit and its old-file cleanup
    leaves BOTH engine kinds on disk; the boot scan must keep the one
    that is further along and delete the stale twin — twin servers on
    one tag would cross-pop the shared TLog cursor."""
    from foundationdb_tpu.server.kvstore import open_kv_store
    from foundationdb_tpu.server.storage import _META_KEY
    c = SimFdbCluster(
        config=DatabaseConfiguration(n_storage=2, storage_replication=2),
        n_workers=5, n_storage_workers=2)
    db = c.database()

    async def seed():
        for i in range(15):
            await commit_kv(db, b"tw/%02d" % i, b"v%02d" % i)
        # Plant a STALE btree twin (version 0) next to a live memory
        # store — what a crash mid-migration leaves behind.
        dd = current_dd(c)
        tag = sorted(dd.healthy)[0]
        ss = dd.storage[tag].role
        fs = c.sim.fs_for(ss._process)
        twin = open_kv_store("btree", fs, f"storage-{tag}")
        twin.set(_META_KEY, ss._meta_blob(0))
        await twin.commit()
        return tag, ss._process

    tag, proc = c.run_until(c.loop.spawn(seed()), timeout=120)
    c.power_fail_reboot()
    db2 = c.database()

    async def check():
        for i in range(15):
            assert await read_key(db2, b"tw/%02d" % i) == b"v%02d" % i
        return True

    assert c.run_until(c.loop.spawn(check()), timeout=120)
    # The stale twin's file is gone; the live memory store survived.
    fs = c.sim.fs_for(proc)
    assert not fs.exists(f"storage-{tag}.btree")
    assert fs.exists(f"storage-{tag}.wal")
