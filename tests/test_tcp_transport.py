"""Real TCP transport over two OS processes.

Reference: fdbrpc/FlowTransport.actor.cpp — token-addressed delivery over
real sockets with a version handshake.  The server process hosts a durable
KV service (KVStoreMemory semantics, in-memory here); the client (this
test process) round-trips sets/gets through the wire format across a real
process boundary."""

import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SERVER = r"""
import sys
sys.path.insert(0, %(repo)r)
from foundationdb_tpu.rpc.transport import (TcpTransport, TOKEN_KV_GET,
                                            TOKEN_KV_SET, pack_value_reply,
                                            unpack_kv_set)
from foundationdb_tpu.core.wire import Reader

store = {}
t = TcpTransport("127.0.0.1", 0)

def do_set(payload):
    k, v = unpack_kv_set(payload)
    store[k] = v
    return pack_value_reply(b"ok")

def do_get(payload):
    k = Reader(payload).bytes_()
    return pack_value_reply(store.get(k))

t.register(TOKEN_KV_SET, do_set)
t.register(TOKEN_KV_GET, do_get)
print("PORT %%d" %% t.address[1], flush=True)
import time
while True:
    time.sleep(1)
"""


def test_kv_roundtrip_across_os_processes():
    proc = subprocess.Popen(
        [sys.executable, "-c", SERVER % {"repo": REPO}],
        stdout=subprocess.PIPE, text=True)
    try:
        line = proc.stdout.readline().strip()
        assert line.startswith("PORT "), line
        port = int(line.split()[1])

        from foundationdb_tpu.rpc.transport import (
            TcpTransport, TOKEN_KV_GET, TOKEN_KV_SET, pack_kv_get,
            pack_kv_set, unpack_value_reply)
        client = TcpTransport("127.0.0.1", 0)
        addr = ("127.0.0.1", port)
        try:
            for i in range(50):
                r = client.request(addr, TOKEN_KV_SET,
                                   pack_kv_set(b"k%03d" % i, b"v%03d" % i))
                assert unpack_value_reply(r) == b"ok"
            for i in range(50):
                r = client.request(addr, TOKEN_KV_GET,
                                   pack_kv_get(b"k%03d" % i))
                assert unpack_value_reply(r) == b"v%03d" % i
            r = client.request(addr, TOKEN_KV_GET, pack_kv_get(b"missing"))
            assert unpack_value_reply(r) is None
        finally:
            client.close()
    finally:
        proc.kill()
        proc.wait()


def test_handshake_rejects_version_mismatch():
    import socket
    import struct

    from foundationdb_tpu.rpc.transport import MAGIC, TcpTransport
    server = TcpTransport("127.0.0.1", 0)
    try:
        s = socket.create_connection(server.address)
        s.sendall(struct.pack("<IH", MAGIC, 999))   # wrong version
        s.settimeout(5.0)
        assert s.recv(16) == b""                    # closed on us
    finally:
        server.close()
