"""Thread offload (core/threadpool.py — reference flow/IThreadPool.h).

Real mode: blocking work leaves the reactor thread, the reactor keeps
dispatching timers while it runs, and the self-pipe wakes a selector-parked
loop.  Sim mode: inline execution + timer delivery keeps determinism.
"""

import threading
import time

from foundationdb_tpu.core.scheduler import EventLoop, delay, set_event_loop
from foundationdb_tpu.core.threadpool import pool_for, run_blocking


def teardown_function(_fn):
    set_event_loop(None)


def test_real_mode_runs_off_reactor_and_loop_stays_live():
    loop = EventLoop(sim=False)
    set_event_loop(loop)
    reactor_thread = threading.current_thread()
    ticks = []

    async def ticker():
        for _ in range(10):
            await delay(0.01)
            ticks.append(time.monotonic())

    def blocking():
        time.sleep(0.12)
        return threading.current_thread()

    async def main():
        t = loop.spawn(ticker(), "ticker")
        worker = await run_blocking(blocking)
        assert worker is not reactor_thread
        await t
        return True

    assert loop.run_until(loop.spawn(main(), "main"), timeout=10)
    # The ticker kept firing DURING the 120ms block: its ticks span the
    # blocking window instead of bunching after it.
    assert len(ticks) == 10
    spread = ticks[-1] - ticks[0]
    assert spread >= 0.08, f"timers stalled while blocking ran ({spread:.3f}s)"
    pool_for(loop).close()


def test_real_mode_propagates_exceptions():
    loop = EventLoop(sim=False)
    set_event_loop(loop)

    def boom():
        raise ValueError("worker failed")

    async def main():
        try:
            await run_blocking(boom)
        except ValueError as e:
            return str(e)
        return None

    assert loop.run_until(loop.spawn(main(), "main"),
                          timeout=10) == "worker failed"
    pool_for(loop).close()


def test_sim_mode_is_deterministic_inline():
    loop = EventLoop(sim=True)
    set_event_loop(loop)
    order = []

    def work(tag):
        order.append(("ran", tag))
        return tag

    async def main():
        a = await run_blocking(work, "a", sim_cost=0.5)
        order.append(("got", a, loop.now()))
        b = await run_blocking(work, "b")
        order.append(("got", b, loop.now()))
        return True

    assert loop.run_until(loop.spawn(main(), "main"), timeout=30)
    # Inline execution order is the call order; sim_cost charges virtual
    # time; no OS threads are involved.
    assert order[0] == ("ran", "a")
    assert order[1][0:2] == ("got", "a") and order[1][2] >= 0.5
    assert order[2] == ("ran", "b")
    # Sim mode must never create OS threads.
    assert pool_for(loop)._executor is None
