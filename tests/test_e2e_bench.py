"""`bench.py e2e --smoke` — the tier-1 commit-pipeline parity gate
(ISSUE 14) — plus direct parity batteries for the vectorized proxy
batch assembly (PROXY_VECTORIZED_ASSEMBLY) against the plain path."""

import importlib.util
import os
import random

import pytest

from foundationdb_tpu.core.knobs import server_knobs
from foundationdb_tpu.server.cluster import SimCluster
from foundationdb_tpu.server.interfaces import CommitTransactionRequest
from foundationdb_tpu.txn.types import (CommitResult, CommitTransactionRef,
                                        KeyRange, Mutation, MutationType)


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_e2e_under_test",
        os.path.join(os.path.dirname(__file__), os.pardir, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


@pytest.fixture()
def vec_knob():
    k = server_knobs()
    saved = k.PROXY_VECTORIZED_ASSEMBLY
    yield k
    k.PROXY_VECTORIZED_ASSEMBLY = saved


def test_e2e_smoke_gate():
    """The acceptance gate: knobs-off wire images legacy + round-trip,
    columnar-on abort sets identical to columnar-off on one contended
    stream, sim-pipeline commits bit-identical with vectorized assembly
    on.  Any regression here fails tier-1."""
    bench = _load_bench()
    doc = bench.run_e2e_smoke()
    assert doc["parity"] == "ok"
    assert doc["legacy_wire"] == "ok"
    assert doc["abort_set_parity_txns"] > 0
    assert doc["pipeline_parity_ops"] > 0


# ---------------------------------------------------------------------------
# Vectorized assembly parity (direct, randomized)
# ---------------------------------------------------------------------------

def _random_batch(rng, n_txns=60, with_state=True):
    def rand_key():
        return b"k%06d" % rng.randrange(100_000)

    def rand_txn(state=False):
        rr = [KeyRange(*sorted((rand_key(), rand_key() + b"\x00")))
              for _ in range(rng.randrange(0, 4))]
        wr = [KeyRange(*sorted((rand_key(), rand_key() + b"\x00")))
              for _ in range(rng.randrange(0, 3))]
        muts = []
        for _ in range(rng.randrange(0, 4)):
            p = rng.random()
            if p < 0.2:
                a, b = sorted((rand_key(), rand_key()))
                muts.append(Mutation(MutationType.ClearRange, a,
                                     b + b"\x00"))
            elif p < 0.3:
                muts.append(Mutation(
                    MutationType.SetVersionstampedKey,
                    rand_key() + b"\x00" * 10 +
                    (7).to_bytes(4, "little"), b"v"))
            else:
                muts.append(Mutation(MutationType.SetValue, rand_key(),
                                     b"v" * 10))
        if state:
            muts.append(Mutation(MutationType.SetValue,
                                 b"\xff/conf/smoke", b"1"))
        return CommitTransactionRef(
            read_conflict_ranges=rr, write_conflict_ranges=wr,
            mutations=muts, read_snapshot=500,
            report_conflicting_keys=rng.random() < 0.3,
            tenant_id=-1, tag="t1" if rng.random() < 0.2 else "")

    return [CommitTransactionRequest(
        transaction=rand_txn(state=(with_state and i == 7)),
        repair_eligible=(i % 5 == 0)) for i in range(n_txns)]


def test_vectorized_assembly_parity(vec_knob):
    """Resolution fan-out AND mutation->tag routing identical across the
    plain and vectorized builders, over randomized multi-resolver
    batches with state txns, clears, versionstamps and repair flags."""
    cl = SimCluster(n_resolvers=3, n_storage=4, replication=2)
    try:
        proxy = cl.commit_proxies[0]
        rng = random.Random(7)
        for trial in range(4):
            batch = _random_batch(rng)
            verdicts = [CommitResult.COMMITTED if rng.random() < 0.8
                        else CommitResult.CONFLICT for _ in batch]
            vec_knob.PROXY_VECTORIZED_ASSEMBLY = False
            reqs_a, maps_a = proxy._build_resolution_requests(
                batch, 900, 1000)
            msgs_a = proxy._assign_mutations_to_tags(
                batch, list(verdicts), 1000)
            vec_knob.PROXY_VECTORIZED_ASSEMBLY = True
            reqs_b, maps_b = proxy._build_resolution_requests(
                batch, 900, 1000)
            msgs_b = proxy._assign_mutations_to_tags(
                batch, list(verdicts), 1000)
            assert maps_a == maps_b
            assert reqs_a == reqs_b
            assert msgs_a == msgs_b
    finally:
        from foundationdb_tpu.core import set_event_loop
        from foundationdb_tpu.rpc.sim import set_simulator
        set_simulator(None)
        set_event_loop(None)


def test_vectorized_repair_forces_reporting(vec_knob):
    """The repair stage's forced report_conflicting_keys survives the
    vectorized path (it rode a subtle branch in the plain builder)."""
    cl = SimCluster(n_resolvers=2, n_storage=2)
    try:
        proxy = cl.commit_proxies[0]
        k = server_knobs()
        saved = k.SCHED_REPAIR_ENABLED
        k.SCHED_REPAIR_ENABLED = True
        try:
            txn = CommitTransactionRef(
                read_conflict_ranges=[KeyRange(b"a", b"b")],
                write_conflict_ranges=[KeyRange(b"a", b"b")],
                mutations=[], read_snapshot=500)
            batch = [CommitTransactionRequest(transaction=txn,
                                              repair_eligible=True)]
            for on in (False, True):
                vec_knob.PROXY_VECTORIZED_ASSEMBLY = on
                reqs, _ = proxy._build_resolution_requests(batch, 900, 1000)
                sent = [t for r in reqs for t in r.transactions]
                assert sent and all(t.report_conflicting_keys
                                    for t in sent), f"vectorized={on}"
        finally:
            k.SCHED_REPAIR_ENABLED = saved
    finally:
        from foundationdb_tpu.core import set_event_loop
        from foundationdb_tpu.rpc.sim import set_simulator
        set_simulator(None)
        set_event_loop(None)
