"""Elastic recovery tests: dynamic recruitment + epoch-change recovery.

Models the reference's fault-tolerance behavior (SURVEY.md §3.4, §5.3):
any transaction-system failure ends the master's epoch; the cluster
controller recruits a successor which locks the old TLog generation,
recovers surviving tag data, and brings up a fresh transaction system —
while committed data (on storage-class workers) survives."""

import pytest

from foundationdb_tpu.core import FdbError
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration


@pytest.fixture()
def teardown():
    from foundationdb_tpu.core import (DeterministicRandom,
                                       set_deterministic_random)
    set_deterministic_random(DeterministicRandom(7))   # hermetic per test
    yield
    from foundationdb_tpu.core import set_event_loop
    from foundationdb_tpu.rpc.sim import set_simulator
    set_simulator(None)
    set_event_loop(None)


def make_cluster(**cfg):
    n_workers = cfg.pop("n_workers", 5)
    n_storage_workers = cfg.pop("n_storage_workers", 2)
    config = DatabaseConfiguration(**cfg)
    return SimFdbCluster(config=config, n_workers=n_workers,
                         n_storage_workers=n_storage_workers)


async def commit_kv(db, key, value):
    t = db.create_transaction()   # reuse: backoff grows across retries
    while True:
        try:
            t.set(key, value)
            await t.commit()
            return
        except FdbError as e:
            await t.on_error(e)


async def read_key(db, key):
    t = db.create_transaction()
    while True:
        try:
            return await t.get(key)
        except FdbError as e:
            await t.on_error(e)


def test_cold_boot_recruits_and_serves(teardown):
    c = make_cluster()
    db = c.database()

    async def go():
        await commit_kv(db, b"boot", b"ok")
        assert await read_key(db, b"boot") == b"ok"
        cc = c.current_cc()
        assert cc is not None
        assert cc.db_info.epoch == 1
        assert cc.db_info.recovery_state == "accepting_commits"

    c.run_until(c.loop.spawn(go()), timeout=60)


def test_master_worker_kill_triggers_recovery(teardown):
    c = make_cluster()
    db = c.database()

    async def go():
        await commit_kv(db, b"before", b"1")
        cc = c.current_cc()
        epoch1 = cc.db_info.epoch
        master_proc = c.process_of(cc.db_info.master)
        assert master_proc is not None and master_proc.alive
        c.sim.kill_process(master_proc)
        # The new epoch must come up and serve; prior data must survive.
        await commit_kv(db, b"after", b"2")
        assert await read_key(db, b"before") == b"1"
        assert await read_key(db, b"after") == b"2"
        cc2 = c.current_cc()
        assert cc2.db_info.epoch > epoch1

    c.run_until(c.loop.spawn(go()), timeout=300)


def test_resolver_worker_kill_triggers_recovery(teardown):
    # Place the resolver on a different stateless worker than the master
    # (master on stateless[0], resolvers on stateless[i+1]).
    c = make_cluster(n_workers=6, n_storage_workers=2)
    db = c.database()

    async def go():
        await commit_kv(db, b"k0", b"v0")
        cc = c.current_cc()
        resolver_proc = c.process_of(cc.db_info.resolvers[0])
        master_proc = c.process_of(cc.db_info.master)
        assert resolver_proc is not master_proc
        c.sim.kill_process(resolver_proc)
        await commit_kv(db, b"k1", b"v1")
        assert await read_key(db, b"k0") == b"v0"
        assert await read_key(db, b"k1") == b"v1"

    c.run_until(c.loop.spawn(go()), timeout=300)


def test_tlog_kill_with_replication_preserves_data(teardown):
    c = make_cluster(n_workers=6, n_storage_workers=2,
                     n_tlogs=2, log_replication=2)
    db = c.database()

    async def go():
        for i in range(5):
            await commit_kv(db, b"key%d" % i, b"val%d" % i)
        cc = c.current_cc()
        tlog_procs = [c.process_of(t) for t in cc.db_info.tlogs]
        master_proc = c.process_of(cc.db_info.master)
        victim = next(p for p in tlog_procs if p is not master_proc)
        c.sim.kill_process(victim)
        await commit_kv(db, b"post", b"recovery")
        for i in range(5):
            assert await read_key(db, b"key%d" % i) == b"val%d" % i
        assert await read_key(db, b"post") == b"recovery"

    c.run_until(c.loop.spawn(go()), timeout=300)


def test_repeated_recoveries(teardown):
    c = make_cluster(n_workers=7, n_storage_workers=2)
    db = c.database()

    async def go():
        for round_num in range(3):
            await commit_kv(db, b"round%d" % round_num, b"x")
            cc = c.current_cc()
            if cc is None:
                continue
            mp = c.process_of(cc.db_info.master)
            if mp is not None and mp.alive:
                c.sim.kill_process(mp)
        await commit_kv(db, b"final", b"done")
        for round_num in range(3):
            assert await read_key(db, b"round%d" % round_num) == b"x"

    c.run_until(c.loop.spawn(go()), timeout=600)
