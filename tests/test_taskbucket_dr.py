"""TaskBucket (resumable task queue) + DR (second-cluster replication).

Reference: fdbclient/TaskBucket.actor.cpp (claim/timeout/reclaim,
exactly-once finish) and fdbclient/DatabaseBackupAgent.actor.cpp (DR
snapshot + continuous apply + drained switchover)."""

import pytest

from foundationdb_tpu.client.taskbucket import TaskBucket, run_tasks
from foundationdb_tpu.core.error import FdbError
from foundationdb_tpu.core.scheduler import delay
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration

from test_recovery import commit_kv, read_key, teardown  # noqa: F401


def make_cluster(**kw):
    return SimFdbCluster(config=DatabaseConfiguration(), n_workers=4,
                         n_storage_workers=2, **kw)


def test_taskbucket_claim_finish_and_reclaim(teardown):  # noqa: F811
    c = make_cluster()
    db = c.database()
    bucket = TaskBucket(timeout_versions=2_000_000)   # ~2s of versions

    async def go():
        await bucket.add_task(db, "work", {b"n": b"1"})
        task = await bucket.claim_one(db)
        assert task is not None and task.type == "work"
        # Claimed: nothing else claimable.
        assert await bucket.claim_one(db) is None
        # Finish inside a transaction: effects + completion atomic.
        t = db.create_transaction()
        while True:
            try:
                t.set(b"tb/done", b"1")
                await bucket.finish(t, task)
                await t.commit()
                break
            except Exception as e:  # noqa: BLE001
                await t.on_error(e)
        assert await read_key(db, b"tb/done") == b"1"
        assert await bucket.is_empty(db)

        # Crash path: claim then DIE (never finish); the deadline passes
        # (version time flows with commits) and another agent reclaims.
        await bucket.add_task(db, "work", {b"n": b"2"})
        dead = await bucket.claim_one(db)
        assert dead is not None
        for i in range(40):     # burn ~4s of version time
            await commit_kv(db, b"tb/burn", b"%d" % i)
            await delay(0.12)
        re = await bucket.claim_one(db)
        assert re is not None and re.uid == dead.uid
        # The dead agent's late finish must FAIL (reclaimed ownership).
        t = db.create_transaction()
        failed = False
        try:
            t.set(b"tb/dead", b"oops")
            await bucket.finish(t, dead)
            await t.commit()
        except Exception:  # noqa: BLE001
            failed = True
        assert failed
        assert await read_key(db, b"tb/dead") is None
        # The reclaimer finishes cleanly.
        t = db.create_transaction()
        while True:
            try:
                await bucket.finish(t, re)
                await t.commit()
                break
            except Exception as e:  # noqa: BLE001
                await t.on_error(e)
        assert await bucket.is_empty(db)
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=300)


def test_taskbucket_two_agents_split_work(teardown):  # noqa: F811
    c = make_cluster()
    db = c.database()
    bucket = TaskBucket()
    done = []

    async def handler(db_, bucket_, task):
        t = db_.create_transaction()
        while True:
            try:
                t.set(b"tb/out/" + task.params[b"k"], task.params[b"k"])
                await bucket_.finish(t, task)
                await t.commit()
                done.append(task.params[b"k"])
                return
            except Exception as e:  # noqa: BLE001
                await t.on_error(e)

    async def go():
        for i in range(12):
            await bucket.add_task(db, "emit", {b"k": b"%03d" % i})
        stop = {"n": False}
        for a in range(2):
            c.loop.spawn(run_tasks(db, bucket, {"emit": handler},
                                   agent_id=f"a{a}",
                                   stop=lambda: stop["n"]),
                         f"agent{a}")
        for _ in range(300):
            if len(done) >= 12 and await bucket.is_empty(db):
                break
            await delay(0.1)
        stop["n"] = True
        assert sorted(done) == [b"%03d" % i for i in range(12)]
        # Exactly once each.
        assert len(done) == 12
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=300)


def test_dr_to_second_cluster_and_switchover(teardown):  # noqa: F811
    from foundationdb_tpu.client.dr_agent import DatabaseBackupAgent
    src = make_cluster()
    dst = SimFdbCluster(config=DatabaseConfiguration(), n_workers=4,
                        n_storage_workers=2, sim=src.sim, loop=src.loop,
                        name_prefix="drb.")
    src_db = src.database()
    dst_db = dst.database()

    async def go():
        for i in range(15):
            await commit_kv(src_db, b"dr/%03d" % i, b"v%03d" % i)
        agent = DatabaseBackupAgent(src, src_db, dst_db)
        await agent.submit()
        # Writes AFTER submit stream across continuously.
        for i in range(15, 25):
            await commit_kv(src_db, b"dr/%03d" % i, b"v%03d" % i)
        await commit_kv(src_db, b"dr/003", b"updated")
        t = src_db.create_transaction()
        t.atomic_op(__import__(
            "foundationdb_tpu.txn.types", fromlist=["MutationType"]
        ).MutationType.AddValue, b"dr/ctr", (7).to_bytes(8, "little"))
        while True:
            try:
                await t.commit()
                break
            except Exception as e:  # noqa: BLE001
                await t.on_error(e)
        await agent.drain()
        for i in range(25):
            want = b"updated" if i == 3 else b"v%03d" % i
            assert await read_key(dst_db, b"dr/%03d" % i) == want, i
        assert (await read_key(dst_db, b"dr/ctr"))[:1] == b"\x07"
        # Drained switchover: the target is an exact copy and accepts
        # its own writes afterwards.
        await agent.switchover()
        await commit_kv(dst_db, b"dr/post", b"target-live")
        assert await read_key(dst_db, b"dr/post") == b"target-live"
        # The source is LOCKED (reference atomicSwitchover write fence):
        # plain commits bounce with database_locked until an operator
        # unlocks; reads still work.
        t = src_db.create_transaction()
        t.set(b"dr/stale", b"must-not-land")
        try:
            await t.commit()
            raise AssertionError("source accepted a commit after "
                                 "switchover")
        except FdbError as e:
            assert e.name == "database_locked", e.name
        assert await read_key(src_db, b"dr/003") == b"updated"
        from foundationdb_tpu.client.management import unlock_database
        await unlock_database(src_db, b"dr:dr")
        await commit_kv(src_db, b"dr/unlocked", b"ok")
        return True

    assert src.run_until(src.loop.spawn(go()), timeout=600)
