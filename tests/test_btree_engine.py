"""COW B-tree engine: model parity under random ops + power-fail recovery
(reference VersionedBTree.actor.cpp semantics at IKeyValueStore scope)."""

import pytest

from foundationdb_tpu.core import (DeterministicRandom, EventLoop,
                                   set_deterministic_random, set_event_loop)
from foundationdb_tpu.server.kvstore import open_kv_store
from foundationdb_tpu.server.sim_fs import SimFileSystem

_loop = None


def drive(coro):
    return _loop.run_until(_loop.spawn(coro), timeout=60)


def fresh_loop():
    global _loop
    _loop = EventLoop(sim=True)
    set_event_loop(_loop)


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_btree_random_ops_vs_model_with_power_fail(seed):
    fresh_loop()
    set_deterministic_random(DeterministicRandom(seed))
    rng = DeterministicRandom(seed * 101)
    fs = SimFileSystem()
    eng = open_kv_store("btree", fs, "bt")
    drive(eng.recover())
    model = {}
    durable_model = {}
    for round_ in range(30):
        for _ in range(rng.random_int(1, 30)):
            r = rng.random01()
            k = b"k%04d" % rng.random_int(0, 300)
            if r < 0.7:
                v = b"v%06d" % rng.random_int(0, 1 << 20)
                eng.set(k, v)
                model[k] = v
            else:
                hi = b"k%04d" % rng.random_int(0, 300)
                lo, hi = min(k, hi), max(k, hi)
                eng.clear(lo, hi)
                for kk in [kk for kk in model if lo <= kk < hi]:
                    del model[kk]
        if rng.coinflip():
            drive(eng.commit())
            durable_model = dict(model)
        if round_ % 7 == 3:
            # Unclean power failure + fresh engine over the same file.
            fs.power_fail_all()
            eng = open_kv_store("btree", fs, "bt")
            drive(eng.recover())
            model = dict(durable_model)
            # Full scan must equal the last durably committed state.
            assert dict(eng.read_range(b"", b"\xff")) == durable_model
        # Point reads against the in-flight model after commit only.
    drive(eng.commit())
    assert dict(eng.read_range(b"", b"\xff")) == model
    for k, v in list(model.items())[:20]:
        assert eng.read_value(k) == v
    assert eng.read_value(b"missing") is None


def test_btree_splits_and_range_reads():
    fresh_loop()
    set_deterministic_random(DeterministicRandom(9))
    fs = SimFileSystem()
    eng = open_kv_store("btree", fs, "big")
    drive(eng.recover())
    # Enough data to force multiple levels of page splits.
    for i in range(2000):
        eng.set(b"key%06d" % i, b"x" * 50)
        if i % 100 == 99:
            drive(eng.commit())
    drive(eng.commit())
    assert eng.page_count > 10   # really paged
    data = eng.read_range(b"key000500", b"key000600")
    assert len(data) == 100
    assert data[0][0] == b"key000500" and data[-1][0] == b"key000599"
    assert eng.read_range(b"", b"\xff", limit=5).__len__() == 5
    # Survives recovery wholesale.
    eng2 = open_kv_store("btree", fs, "big")
    drive(eng2.recover())
    assert len(eng2.read_range(b"", b"\xff")) == 2000


def test_cluster_on_btree_engine_survives_power_fail():
    """A full cluster storing on the B-tree engine: acked commits survive a
    whole-cluster power-fail reboot (the memory-engine durability test's
    criterion, on the second engine)."""
    from foundationdb_tpu.core import set_event_loop
    from foundationdb_tpu.rpc.sim import set_simulator
    from foundationdb_tpu.server.cluster import SimFdbCluster
    from foundationdb_tpu.server.interfaces import DatabaseConfiguration
    import sys
    sys.path.insert(0, __file__.rsplit("/", 1)[0])
    from test_recovery import commit_kv, read_key

    set_deterministic_random(DeterministicRandom(88))
    c = SimFdbCluster(config=DatabaseConfiguration(storage_engine="btree"),
                      n_workers=5, n_storage_workers=2)
    db = c.database()
    try:
        async def load():
            for i in range(25):
                await commit_kv(db, b"bt/%03d" % i, b"val%03d" % i)
        c.run_until(c.loop.spawn(load()), timeout=120)

        c.power_fail_reboot()
        db2 = c.database()

        async def check():
            for i in range(25):
                assert await read_key(db2, b"bt/%03d" % i) == b"val%03d" % i
            await commit_kv(db2, b"bt/after", b"ok")
            assert await read_key(db2, b"bt/after") == b"ok"
        c.run_until(c.loop.spawn(check()), timeout=120)
    finally:
        set_simulator(None)
        set_event_loop(None)


def test_churn_file_size_plateaus():
    """VERDICT r3 item 7: with the free-list, write/clear churn reuses
    pages — the file stops growing instead of leaking a page per COW."""
    fresh_loop()
    from foundationdb_tpu.server.kvstore_btree import KVStoreBTree
    fs = SimFileSystem()
    eng = KVStoreBTree(fs, "churn")

    async def go():
        for i in range(50):
            eng.set(b"k%04d" % i, b"v" * 100)
        await eng.commit()
        sizes = []
        for round_ in range(30):
            for i in range(50):
                eng.set(b"k%04d" % i, b"w%03d" % round_ + b"v" * 100)
            await eng.commit()
            eng.clear(b"k0010", b"k0040")
            await eng.commit()
            for i in range(10, 40):
                eng.set(b"k%04d" % i, b"v" * 100)
            await eng.commit()
            sizes.append(eng.page_count)
        # Page count must PLATEAU: the last 10 rounds allocate nothing new.
        assert sizes[-1] == sizes[-10], sizes
        assert len(eng.free) > 0
        return True

    assert drive(go())


def test_large_values_round_trip_power_fail():
    """VERDICT r3 item 7: 1MB values stored via overflow page chains
    survive an unclean power failure and read back bit-identical."""
    import hashlib
    fresh_loop()
    from foundationdb_tpu.server.kvstore_btree import KVStoreBTree
    fs = SimFileSystem()
    eng = KVStoreBTree(fs, "big")
    big1 = bytes(range(256)) * 4096            # 1MB, patterned
    big2 = hashlib.sha256(b"x").digest() * 40_000   # ~1.25MB

    async def go():
        eng.set(b"big1", big1)
        eng.set(b"small", b"s")
        await eng.commit()
        eng.set(b"big2", big2)
        await eng.commit()
        # Overwrite big1: its old overflow chain must be freed (reused
        # later), and the new value read back.
        eng.set(b"big1", big1[::-1])
        await eng.commit()
        assert eng.read_value(b"big1") == big1[::-1]
        assert eng.read_value(b"big2") == big2
        return True

    assert drive(go())

    fs.power_fail_all()
    eng2 = KVStoreBTree(fs, "big")

    async def after():
        await eng2.recover()
        assert eng2.read_value(b"big1") == big1[::-1]
        assert eng2.read_value(b"big2") == big2
        assert eng2.read_value(b"small") == b"s"
        # Clearing the big records frees their chains into the free list.
        free0 = len(eng2.free)
        eng2.clear(b"big1", b"big3")
        await eng2.commit()
        assert len(eng2.free) > free0 + 100   # hundreds of overflow pages
        return True

    assert drive(after())


def test_overflow_chain_freed_on_overwrite_and_reused():
    fresh_loop()
    from foundationdb_tpu.server.kvstore_btree import KVStoreBTree
    fs = SimFileSystem()
    eng = KVStoreBTree(fs, "reuse")

    async def go():
        eng.set(b"k", b"A" * 50_000)
        await eng.commit()
        pages_after_first = eng.page_count
        # Overwrite the same big value many times: page count must not
        # grow linearly — freed chains are reused.
        for i in range(10):
            eng.set(b"k", bytes([i]) * 50_000)
            await eng.commit()
        assert eng.page_count <= pages_after_first + 20, (
            eng.page_count, pages_after_first)
        assert eng.read_value(b"k") == bytes([9]) * 50_000
        return True

    assert drive(go())
