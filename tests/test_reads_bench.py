"""`bench.py reads --smoke` — the tier-1 read-path parity gate
(ISSUE 15): knobs-off read-RPC wire images stay legacy and round-trip,
columnar-on replies decode identically to columnar-off, compressed vs
plain B-tree pages yield identical scan results, the vectorized
VersionedMap scan is bit-identical, and the incremental shard-metrics
cache never drifts from fresh scans.  Mirrors the `bench.py e2e --smoke`
gate in tests/test_e2e_bench.py."""

import importlib.util
import os


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench_reads_under_test",
        os.path.join(os.path.dirname(__file__), os.pardir, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench


def test_reads_smoke_gate():
    bench = _load_bench()
    doc = bench.run_reads_smoke()
    assert doc["parity"] == "ok"
    assert doc["wire_parity_msgs"] > 0
    assert doc["btree_parity_rows"] > 0
    assert doc["versioned_map_probes"] > 0
    assert doc["shard_cache_audits"] > 0


def test_read_storm_spec_in_default_matrix():
    """The read-path chaos battery rides the default matrix, so its
    perf-path knobs run under nemesis on every chaos sweep."""
    import importlib.util as iu
    spec = iu.spec_from_file_location(
        "run_chaos_under_test",
        os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                     "run_chaos.py"))
    mod = iu.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert "ReadStormTest.toml" in mod.DEFAULT_SPECS
    from foundationdb_tpu.testing import workload_registry
    assert "ZipfianReadStorm" in workload_registry
    assert "WatchFanout" in workload_registry
