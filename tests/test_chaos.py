"""Deterministic chaos engine (ISSUE 4): unseed verification (same seed
=> bit-identical run), the swizzled nemesis, and live disk fault
injection with checksum-backed detection.

Reference shape: fdbrpc/sim2.actor.cpp (swizzle clogging, BUGGIFY'd disk
faults via AsyncFileNonDurable), fdbserver/workloads/MachineAttrition,
and the TestHarness unseed check."""

import os

import pytest

from foundationdb_tpu.core import (DeterministicRandom, FdbError,
                                   set_deterministic_random,
                                   set_event_loop)
from foundationdb_tpu.core import coverage
from foundationdb_tpu.rpc.sim import set_simulator
from foundationdb_tpu.testing import run_test_twice

SPECS = os.path.join(os.path.dirname(__file__), "specs")

# A shortened ChaosTest (same workload composition as ChaosTest.toml —
# nemesis + Cycle + ConsistencyCheck) for the tier-1 seed-matrix smoke.
FAST_CHAOS_SPEC = """
[[test]]
testTitle = 'FastChaos'
  [[test.workload]]
  testName = 'Cycle'
  nodeCount = 10
  actorCount = 3
  testDuration = 4.0
  [[test.workload]]
  testName = 'ChaosNemesis'
  testDuration = 4.0
  restartDelay = 1.0
  [[test.workload]]
  testName = 'ConsistencyCheck'
"""


@pytest.fixture()
def teardown():
    set_deterministic_random(DeterministicRandom(21))
    yield
    set_simulator(None)
    set_event_loop(None)


# ---------------------------------------------------------------------------
# Unseed verification
# ---------------------------------------------------------------------------

def test_chaos_double_run_unseed_identical(teardown):
    """The acceptance check: a same-seed double run of ChaosTest.toml
    (nemesis + Cycle + ConsistencyCheck) yields identical unseed
    digests, and the nemesis actually exercised its fault loops."""
    spec_text = open(os.path.join(SPECS, "ChaosTest.toml")).read()
    r1, r2 = run_test_twice(spec_text, seed=107)
    assert r1.unseed == r2.unseed and r1.digest == r2.digest
    assert r1.folds == r2.folds and r1.folds > 0
    assert r1.metrics == r2.metrics
    assert r1.metrics["Cycle"]["swaps"] > 0
    # The nemesis did chaos, not nothing: at least one loop fired, and
    # the clean simulation run tripped no nondeterminism-source audit.
    assert (r1.metrics["ChaosNemesis"]["swizzles"] > 0 or
            r1.metrics["ChaosNemesis"]["reboots"] +
            r1.metrics["ChaosNemesis"]["power_fails"] +
            r1.metrics["ChaosNemesis"]["kills"] > 0 or
            r1.metrics["ChaosNemesis"]["partitions"] > 0)
    assert r1.nondeterminism == [] and r2.nondeterminism == []
    assert coverage.covered("ChaosNemesisSwizzle") or \
        coverage.covered("ChaosNemesisAttrition") or \
        coverage.covered("ChaosNemesisPartition")


def test_injected_divergence_fails_unseed_check(teardown):
    """Negative control: a workload that reads the wall clock MUST fail
    the unseed check — proves the verifier detects divergence rather
    than rubber-stamping, and the audit names the source."""
    spec = """
[[test]]
testTitle = 'NondetCanary'
  [[test.workload]]
  testName = 'NondeterminismCanary'
"""
    with pytest.raises(AssertionError) as ei:
        run_test_twice(spec, seed=13, n_workers=5, n_storage_workers=2)
    msg = str(ei.value)
    assert "unseed mismatch" in msg
    # First-divergence report: the checkpoint trail is in the message.
    assert "divergen" in msg      # "first divergent checkpoint" / tail
    # The audit flagged the wall-clock read inside the package.
    assert "time.time_ns" in msg and "workloads.py" in msg


def test_chaos_seed_matrix_smoke(teardown, tmp_path):
    """Tier-1 smoke of scripts/run_chaos.py: 2 seeds through the
    shortened chaos spec, JSON summary records with unseed + repro
    plumbing intact."""
    import importlib.util
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec_mod = importlib.util.spec_from_file_location(
        "run_chaos", os.path.join(here, "scripts", "run_chaos.py"))
    run_chaos = importlib.util.module_from_spec(spec_mod)
    spec_mod.loader.exec_module(run_chaos)

    spec_path = tmp_path / "FastChaos.toml"
    spec_path.write_text(FAST_CHAOS_SPEC)
    records = [run_chaos.run_tuple(str(spec_path), seed, buggify=False,
                                   verify_unseed=False)
               for seed in (301, 302)]
    for rec in records:
        assert rec["ok"], rec
        assert rec["unseed"] == rec["unseed"] & 0xFFFFFFFF
        assert rec["metrics"]["Cycle"]["swaps"] > 0
    # Distinct seeds take distinct paths (statistically certain here).
    assert records[0]["unseed"] != records[1]["unseed"]
    # A failing tuple carries a copy-pastable repro command.
    assert "run_chaos.py" in run_chaos.repro_command(
        str(spec_path), 301, True, False)


@pytest.mark.slow
def test_chaos_full_matrix(teardown, tmp_path):
    """The full seed matrix (chaos trio x 3 seeds, buggify alternating,
    unseed-verified) — the ensemble the smoke test samples."""
    import subprocess
    import sys
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = tmp_path / "chaos.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(here, "scripts", "run_chaos.py"),
         "--seeds", "3", "--verify-unseed", "--json", str(out)],
        cwd=here, capture_output=True, text=True, timeout=3600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    import json
    summary = json.loads(out.read_text())
    assert summary["passed"] == summary["total"]


# ---------------------------------------------------------------------------
# Live disk fault injection: every detection path must fire
# ---------------------------------------------------------------------------

def _drive(loop, coro, timeout=60.0):
    return loop.run_until(loop.spawn(coro), timeout=timeout)


def test_disk_queue_crc_catches_bitrot(loop):
    """Post-sync bit-rot in a WAL record is caught by the frame CRC on
    BOTH read paths: the live spilled-record read raises io_error (never
    serves corrupt data) and recovery keeps only the valid prefix."""
    from foundationdb_tpu.server.disk_queue import DiskQueue
    from foundationdb_tpu.server.sim_fs import (DiskFaultProfile,
                                                SimFileSystem)
    fs = SimFileSystem()
    dq = DiskQueue(fs.open("t.wal"))

    async def go():
        seqs = [dq.push(b"payload-%04d" % i * 8) for i in range(8)]
        await dq.commit()
        # All records durable + readable before the rot.
        assert await dq.read_payload(seqs[3]) is not None
        # Deterministic bit-rot via the fault profile on the next sync.
        fs.set_fault_profile("t.wal", DiskFaultProfile(bitrot_sync_p=1.0))
        dq.push(b"one-more")
        await dq.commit()
        fs.clear_fault_profiles()
        assert coverage.covered("SimDiskBitRotInjected")
        # The flipped bit landed somewhere in the file: SOME read or the
        # recovery scan must detect it — scan every record live.
        hits0 = coverage.hits("DiskQueueCrcCaught")
        detected = False
        for seq in seqs + [dq.next_seq - 1]:
            try:
                await dq.read_payload(seq)
            except FdbError as e:
                assert e.name == "io_error"
                detected = True
        # Recovery over the same rotted file keeps a valid prefix only.
        dq2 = DiskQueue(fs.open("t.wal"))
        records = await dq2.recover()
        if not detected:
            # Rot hit a header, not a payload: recovery's magic/seq/CRC
            # checks truncate at the damaged frame instead.
            assert len(records) < 9
        assert coverage.hits("DiskQueueCrcCaught") >= hits0
        return True

    assert _drive(loop, go())


def test_disk_queue_live_read_crc_io_error(loop):
    """Surgical corruption of one durable payload byte: the live
    read_payload CRC check must raise io_error, not return garbage."""
    from foundationdb_tpu.server.disk_queue import DiskQueue
    from foundationdb_tpu.server.sim_fs import SimFileSystem
    fs = SimFileSystem()
    f = fs.open("q.wal")
    dq = DiskQueue(f)

    async def go():
        from foundationdb_tpu.server.disk_queue import _HDR
        seq = dq.push(b"X" * 64)
        await dq.commit()
        offset, length = dq._index[seq]
        f.durable[offset + 10] ^= 0x40
        hits0 = coverage.hits("DiskQueueCrcCaught")
        with pytest.raises(FdbError) as ei:
            await dq.read_payload(seq)
        assert ei.value.name == "io_error"
        assert coverage.hits("DiskQueueCrcCaught") == hits0 + 1
        # Header rot is caught too: the CRC spans the frame's `popped`
        # trim-frontier field, not just the payload.
        f.durable[offset + 10] ^= 0x40              # heal the payload
        assert await dq.read_payload(seq) is not None
        f.durable[offset - _HDR.size + 12] ^= 0x01  # rot `popped`
        with pytest.raises(FdbError):
            await dq.read_payload(seq)
        return True

    assert _drive(loop, go())


def test_btree_header_slot_crc(loop):
    """A rotted header slot is rejected by its CRC and recovery lands on
    the other (intact) slot — an older but complete tree, never a torn
    one."""
    from foundationdb_tpu.server.kvstore_btree import (PAGE_SIZE,
                                                       KVStoreBTree)
    from foundationdb_tpu.server.sim_fs import SimFileSystem
    fs = SimFileSystem()
    kv = KVStoreBTree(fs, "ss")

    async def go():
        kv.set(b"k1", b"v1")
        await kv.commit()          # commit_seq 1 -> slot 1
        kv.set(b"k2", b"v2")
        await kv.commit()          # commit_seq 2 -> slot 0
        f = fs.open("ss.btree")
        # Rot the NEWER header (slot 0 holds seq 2).
        f.durable[0 * PAGE_SIZE + 8] ^= 0x01
        hits0 = coverage.hits("BTreeSlotCrcCaught")
        kv2 = KVStoreBTree(fs, "ss")
        await kv2.recover()
        assert coverage.hits("BTreeSlotCrcCaught") == hits0 + 1
        # Fell back to the intact slot-1 tree: k1 present, k2 unknown.
        assert kv2.commit_seq == 1
        assert kv2.read_value(b"k1") == b"v1"
        assert kv2.read_value(b"k2") is None
        return True

    assert _drive(loop, go())


def test_storage_io_error_death_and_rerecruitment(teardown):
    """The end-to-end disk-fault contract: an injected io_error on a
    storage engine's fsync kills the process (never limps), and a
    restart on the same machine recovers the engine and rejoins —
    commits keep working throughout on the surviving replicas."""
    from foundationdb_tpu.core.scheduler import delay
    from foundationdb_tpu.server.cluster import SimFdbCluster
    from foundationdb_tpu.server.interfaces import DatabaseConfiguration
    from foundationdb_tpu.server.sim_fs import DiskFaultProfile

    c = SimFdbCluster(config=DatabaseConfiguration(
        n_tlogs=2, log_replication=2, n_storage=3,
        storage_replication=2),
        n_workers=8, n_storage_workers=3)
    db = c.database()

    async def put(key, value):
        t = db.create_transaction()
        while True:
            try:
                t.set(key, value)
                await t.commit()
                return
            except FdbError as e:
                await t.on_error(e)

    async def go():
        # Spread writes so every storage team holds data.
        for i in range(16):
            await put(bytes([i * 16]) + b"/seed", b"v%02d" % i)
        victim = c.workers[0][0]
        assert victim.process_class == "storage"
        fs = c.sim.fs_for(victim)
        hits0 = coverage.hits("StorageIoErrorDeath")
        fs.set_fault_profile("storage-", DiskFaultProfile(
            io_error_sync_p=1.0, max_io_errors=1))
        # Keep writing until the injected fsync error kills the victim.
        for i in range(400):
            await put(b"churn/%04d" % i, b"x")
            if not victim.alive:
                break
            await delay(0.1)
        assert not victim.alive, "io_error never killed the storage server"
        assert coverage.hits("StorageIoErrorDeath") > hits0
        # Survivors keep serving while the victim is down.
        await put(b"during/outage", b"ok")
        # Heal the disk, restart on the same machine (durable files
        # survive), and verify the cluster is whole again.
        fs.clear_fault_profiles()
        c.restart_worker(0)
        await delay(2.0)
        await put(b"after/restart", b"ok")
        t = db.create_transaction()
        while True:
            try:
                assert await t.get(b"after/restart") == b"ok"
                assert await t.get(bytes([0]) + b"/seed") == b"v00"
                break
            except FdbError as e:
                await t.on_error(e)
        assert c.workers[0][0].alive
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=600)


def test_tlog_fsync_io_error_kills_process(loop):
    """A TLog whose WAL fsync fails must die (stop acking), not freeze
    its durable frontier while commits hang — the group-commit actor
    converts the injected io_error into process death."""
    from types import SimpleNamespace
    from foundationdb_tpu.server.disk_queue import DiskQueue
    from foundationdb_tpu.server.sim_fs import (DiskFaultProfile,
                                                SimFileSystem)
    from foundationdb_tpu.server.tlog import TLog
    fs = SimFileSystem()
    fs.set_fault_profile("tlog-", DiskFaultProfile(io_error_sync_p=1.0))
    tlog = TLog("logx", 0, disk_queue=DiskQueue(fs.open("tlog-x.wal")))
    died = []
    tlog._process = SimpleNamespace(die=lambda reason: died.append(reason))
    hits0 = coverage.hits("TLogIoErrorDeath")
    tlog.disk_queue.push(b"record")
    tlog.version.set(1)
    tlog._start_sync()
    loop.run_for(1.0)
    assert died and "TLogDiskError" in died[0]
    assert coverage.hits("TLogIoErrorDeath") == hits0 + 1
    assert tlog.durable_version.get() == 0    # never acked the lost fsync


def test_sim_fs_fault_profile_injection(loop):
    """Profile mechanics: deterministic io_error on write/sync/read,
    budget exhaustion, latency spikes drawn from the deterministic RNG."""
    from foundationdb_tpu.core.scheduler import now
    from foundationdb_tpu.server.sim_fs import (DiskFaultProfile,
                                                SimFileSystem)
    fs = SimFileSystem()
    fs.set_fault_profile("bad", DiskFaultProfile(
        io_error_write_p=1.0, max_io_errors=2))
    bad = fs.open("bad.file")
    ok = fs.open("good.file")

    async def go():
        for _ in range(2):
            with pytest.raises(FdbError) as ei:
                await bad.write(0, b"x")
            assert ei.value.name == "io_error"
        # Budget spent: the disk is healthy again (recovery can proceed).
        await bad.write(0, b"x")
        await bad.sync()
        # Untargeted files never fault.
        await ok.write(0, b"y")
        await ok.sync()
        # Latency spikes stall but succeed.
        fs.set_fault_profile("good", DiskFaultProfile(
            latency_spike_p=1.0, latency_spike_s=0.25))
        t0 = now()
        await ok.write(4, b"z")
        assert now() - t0 >= 0.25
        return True

    assert _drive(loop, go())


def test_btree_data_page_crc(loop):
    """Bit-rot in a DATA page (not the header slots) must fail the
    per-page CRC and raise io_error — a flipped bit that still decodes
    can never be served as a value (review hardening)."""
    from foundationdb_tpu.server.kvstore_btree import (PAGE_SIZE,
                                                       KVStoreBTree)
    from foundationdb_tpu.server.sim_fs import SimFileSystem
    fs = SimFileSystem()
    kv = KVStoreBTree(fs, "ss")

    async def go():
        kv.set(b"key", b"value-a")
        await kv.commit()
        f = fs.open("ss.btree")
        # Page 2 is the first data page (0/1 are header slots); flip one
        # payload bit — the node still DECODES (value bytes change), so
        # only the page CRC can catch it.
        f.durable[2 * PAGE_SIZE + 20] ^= 0x01
        kv2 = KVStoreBTree(fs, "ss")
        # Detection fires at the first touch of the rotted page — the
        # recovery reachability walk reads every live page, so it
        # surfaces there already; a cache-dropped live read would raise
        # the same io_error.  Either way: error, never a wrong value.
        with pytest.raises(FdbError) as ei:
            await kv2.recover()
            kv2.read_value(b"key")
        assert ei.value.name == "io_error"
        return True

    assert _drive(loop, go())
