"""Disaster-recovery nemesis battery (ISSUE 10): undrained region
failover verified against the surfaced failover_version, rolling
coordinator restarts (CoordinationClientInterface re-pointing), fatal
disk faults with worker restart, and online backup + prefix-shifted
restore under chaos.

Tier-1 runs one fast seed of each new spec; the double-run unseed
verification (same seed => bit-identical RunDigest) is slow-marked and
also exercised by scripts/run_chaos.py --verify-unseed, whose default
matrix includes both specs."""

import os

import pytest

from foundationdb_tpu.core import (DeterministicRandom, coverage,
                                   set_deterministic_random,
                                   set_event_loop)
from foundationdb_tpu.rpc.sim import set_simulator
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration
from foundationdb_tpu.testing import run_simulation, run_test_twice

from test_recovery import commit_kv, read_key, teardown  # noqa: F401

SPECS = os.path.join(os.path.dirname(__file__), "specs")

# Dispatch-volume regression guard (ISSUE 10 satellite): the DR waits
# (KillRegion/regionFailover plane + drain polls, BackupWorker url
# watch) run through the shared DR_POLL knob with backoff-after-empty,
# so a chaos-suite run's RunDigest fold count stays bounded.  Measured
# ~45k folds for TwoRegionChaos seed 101 at introduction; a hot-loop
# regression (the pre-PR-4 GRV-starter failure mode) shows up as
# MILLIONS of extra folds, not thousands — the cap leaves ~40x headroom
# for legitimate growth.
TWO_REGION_FOLD_CAP = 2_000_000


def _spec(name: str) -> str:
    return open(os.path.join(SPECS, name)).read()


def test_two_region_chaos_fast_seed(teardown):  # noqa: F811
    """One seed of the region-failover battery: the nemesis provisions a
    remote dc, hard-kills the primary UNDRAINED mid-traffic, recovery
    adopts the remote plane at the surfaced failover_version, the acked
    marker commit survives whenever at/below it, the dead dc is
    re-provisioned, and the async plane fails back onto it — with
    rolling coordinator restarts throughout and Cycle +
    ConsistencyCheck green across the lost-tail truncation."""
    r = run_simulation(_spec("TwoRegionChaosTest.toml"), seed=101)
    m = r.metrics["ChaosNemesis"]
    assert m["region_failovers"] == 1.0
    assert m["failover_version"] > 0
    assert m.get("failback_plane") == 1.0
    assert m["coordinator_restarts"] >= 1
    assert r.metrics["Cycle"]["swaps"] > 0
    assert r.metrics["ConsistencyCheck"]["shards_audited"] >= 1
    assert r.nondeterminism == []
    assert coverage.covered("ChaosRegionFailover")
    assert coverage.covered("ChaosCoordinatorRestart")
    assert coverage.covered("RecoveryRegionFailover")
    # Dispatch-volume guard (see TWO_REGION_FOLD_CAP).
    assert r.folds < TWO_REGION_FOLD_CAP, (
        f"chaos-suite dispatch volume regressed: {r.folds} folds")


def test_backup_restore_chaos_fast_seed(teardown):  # noqa: F811
    """One seed of the backup battery: capture spans nemesis-forced
    epoch changes and a restart-capable fatal disk fault; the sealed
    container restores into the live cluster under a shifted prefix and
    matches the mutation model exactly."""
    r = run_simulation(_spec("BackupRestoreChaosTest.toml"), seed=201)
    m = r.metrics["BackupAndRestore"]
    assert m["mutations"] > 0
    assert m["backup_end_version"] > 0
    assert m["restored_keys"] > 0
    assert r.metrics["Cycle"]["swaps"] > 0
    assert r.nondeterminism == []
    assert coverage.covered("BackupRestoreUnderChaos")


UNDRAINED_LOSS_SPEC = """
# Forced-loss variant of TwoRegionChaosTest: the async plane is clogged
# for a window before the kill, so the marker commit is GUARANTEED to
# be above the surfaced failover_version and must be lost — the ring
# invariant still holds on the truncated (version-consistent) state.
[[test]]
testTitle = 'UndrainedLoss'
  [[test.workload]]
  testName = 'Cycle'
  nodeCount = 10
  actorCount = 2
  testDuration = 6.0
  [[test.workload]]
  testName = 'ChaosNemesis'
  testDuration = 6.0
  restartDelay = 1.0
  swizzle = false
  attrition = false
  partitions = false
  regionFailover = true
  replicationLagBeforeKill = 2.0
  failback = false
  [[test.workload]]
  testName = 'ConsistencyCheck'
"""


def test_undrained_failover_loses_tail_but_stays_consistent(teardown):  # noqa: F811,E501
    """The acceptance-criteria core, loss side: with the async plane
    frozen before the kill, the failover surfaces a REAL lost tail —
    the marker acked inside the window is gone, the surfaced
    failover_version sits below it, and Cycle's ring invariant still
    holds on the adopted state (a version-consistent truncation, not a
    torn mix of tags)."""
    r = run_simulation(UNDRAINED_LOSS_SPEC, seed=301)
    m = r.metrics["ChaosNemesis"]
    assert m["region_failovers"] == 1.0
    assert m["marker_lost"] == 1.0
    assert m["marker_version"] > m["failover_version"]
    # The ring survived the truncation; every replica agrees.
    assert r.metrics["Cycle"]["swaps"] > 0
    assert r.metrics["ConsistencyCheck"]["shards_audited"] >= 1
    assert r.nondeterminism == []


@pytest.mark.slow
def test_two_region_chaos_double_run_unseed(teardown):  # noqa: F811
    """Acceptance: the region battery is bit-identical under same-seed
    double run (RunDigest + unseed + fold count)."""
    r1, r2 = run_test_twice(_spec("TwoRegionChaosTest.toml"), seed=103)
    assert (r1.unseed, r1.digest, r1.folds) == \
        (r2.unseed, r2.digest, r2.folds)
    assert r1.metrics == r2.metrics
    assert r1.metrics["ChaosNemesis"]["region_failovers"] == 1.0


@pytest.mark.slow
def test_backup_restore_chaos_double_run_unseed(teardown):  # noqa: F811
    r1, r2 = run_test_twice(_spec("BackupRestoreChaosTest.toml"), seed=203)
    assert (r1.unseed, r1.digest, r1.folds) == \
        (r2.unseed, r2.digest, r2.folds)
    assert r1.metrics == r2.metrics
    assert r1.metrics["BackupAndRestore"]["restored_keys"] > 0


def test_coordinator_restart_repointing(teardown):  # noqa: F811
    """ISSUE 10 satellite: kill/restart every coordination server, one
    at a time, mid-run.  The durable generation registers recover from
    the machine's files, the leader (re-)election converges through the
    survivors, and the client keeps committing throughout — i.e. its
    CoordinationClientInterface re-points via the well-known-token
    endpoints and the GRV pipeline never wedges (only quorum-LOSS was
    covered before, in test_restarting_quorum.py)."""
    c = SimFdbCluster(config=DatabaseConfiguration(), n_workers=5,
                      n_storage_workers=2)
    db = c.database()

    async def go():
        for i in range(5):
            await commit_kv(db, b"coord/pre%02d" % i, b"v%02d" % i)
        for i in range(len(c.coordinators)):
            # Alternate clean reboot and hard kill+replace-on-same-
            # address; both must leave the old client endpoints valid.
            p = c.restart_coordinator(i, hard=(i % 2 == 1))
            assert p.alive
            # Client work DURING the rolling restart: GRV + commit +
            # read all flow through the (2/3) quorum and then re-reach
            # the restarted server.
            await commit_kv(db, b"coord/during%02d" % i, b"x%02d" % i)
            assert await read_key(db, b"coord/during%02d" % i) == \
                b"x%02d" % i
        # Every pre-restart key still readable; a fresh commit works.
        for i in range(5):
            assert await read_key(db, b"coord/pre%02d" % i) == b"v%02d" % i
        await commit_kv(db, b"coord/post", b"done")
        assert await read_key(db, b"coord/post") == b"done"
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=300)
    # A controller still leads (election state re-converged on the
    # rebuilt coordinators) and all three coordinators serve.
    assert c.current_cc() is not None
    assert all(p.alive for p, _s in c.coordinators)


def test_restart_coordinator_recovers_registers(teardown):  # noqa: F811
    """A HARD coordinator restart must recover its generation registers
    from disk: restart a majority (one at a time, sequentially) and
    then force a full recovery — the new epoch's master reads the
    DBCoreState through the rebuilt quorum."""
    c = SimFdbCluster(config=DatabaseConfiguration(), n_workers=5,
                      n_storage_workers=2)
    db = c.database()

    async def go():
        from foundationdb_tpu.core.scheduler import delay
        await commit_kv(db, b"reg/k", b"v1")
        for i in range(2):          # majority of 3, sequentially
            c.restart_coordinator(i, hard=True)
            await delay(1.0)
        # Force an epoch change: the next master re-reads the cstate
        # from the restarted coordinators' recovered registers.
        cc = c.current_cc()
        assert cc is not None
        proc = c.process_of(cc.db_info.master)
        if proc is not None and proc.alive:
            c.sim.kill_process(proc)
        await commit_kv(db, b"reg/k2", b"v2")
        assert await read_key(db, b"reg/k") == b"v1"
        assert await read_key(db, b"reg/k2") == b"v2"
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=300)
