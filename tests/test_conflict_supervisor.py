"""Supervision layer for device conflict backends (conflict/supervisor.py).

Three contracts under test:

1. **Parity** — the supervised TPU backend (healthy, degraded, or moving
   between the two) produces commit/abort decisions bit-identical to an
   all-oracle run, INCLUDING keys longer than the digest prefix
   (the exact long-key recheck; SURVEY §7 hard part 1, replacing the old
   "conservative-only" guarantee).
2. **Robustness** — a BUGGIFY-killed / timing-out / transiently-erroring
   device never loses a commit batch: in-flight batches replay through
   the exact host mirror, and the backend re-promotes after recovery.
3. **Health machinery** — the failure/latency monitor and the deadline
   guard behave as specified in isolation.
"""

import time

import pytest

from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.conflict.supervisor import (BackendHealthMonitor,
                                                  SupervisedConflictSet,
                                                  host_digest)
from foundationdb_tpu.ops.digest import PREFIX_BYTES
from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet
from foundationdb_tpu.core import DeterministicRandom
from foundationdb_tpu.core.knobs import server_knobs
from foundationdb_tpu.txn import CommitResult, CommitTransactionRef, KeyRange

from test_conflict_oracle import make_domain, random_txn


@pytest.fixture()
def knobs():
    """Mutable server knobs restored after the test."""
    k = server_knobs()
    saved = dict(k.__dict__)
    yield k
    for name, value in saved.items():
        setattr(k, name, value)


def make_tpu(oldest_version=0):
    return TpuConflictSet(oldest_version, capacity=1 << 12)


def make_supervised(**kw):
    return SupervisedConflictSet(make_tpu, **kw)


def never_reprobe_monitor():
    return BackendHealthMonitor(reprobe_interval_s=1e9)


# ---------------------------------------------------------------------------
# 1. Parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("seed", [71, 72])
def test_supervised_matches_oracle_random(seed):
    rng = DeterministicRandom(seed)
    domain = make_domain()
    oracle = OracleConflictSet(0)
    sup = make_supervised()
    now = 0
    for _ in range(25):
        now += rng.random_int(1, 2_000_000)
        batch = [random_txn(rng, domain, now, 4_000_000)
                 for _ in range(rng.random_int(1, 10))]
        new_oldest = now - 5_000_000 if rng.coinflip() else None
        got = sup.resolve(batch, now, new_oldest)
        want = oracle.resolve(batch, now, new_oldest)
        assert got == want, f"divergence at now={now}"
    assert sup.stats["device_batches"] > 0
    assert sup.stats["fallback_batches"] == 0


def random_long_key(rng) -> bytes:
    """Keys past the digest prefix (PREFIX_BYTES..~1000 bytes), biased
    toward shared truncated prefixes so digest collisions actually occur
    (the case the recheck exists for)."""
    prefix = b"p%02d" % rng.random_int(0, 2)
    prefix = prefix + b"x" * (PREFIX_BYTES - len(prefix))
    tail_len = rng.random_int(1, 977)
    tail = bytes(rng.random_int(97, 122) for _ in range(min(tail_len, 8)))
    return prefix + tail * ((tail_len // len(tail)) + 1)


def random_long_txn(rng, now, window):
    """Mixed batch material: truncated long keys, short keys, and ranges
    whose endpoints straddle the truncation boundary."""
    snap = now - rng.random_int(0, window)
    tr = CommitTransactionRef(read_snapshot=max(snap, 0))

    def key():
        pick = rng.random_int(0, 3)
        if pick == 0:
            return b"s%03d" % rng.random_int(0, 30)          # short
        return random_long_key(rng)                          # truncated

    for _ in range(rng.random_int(0, 3)):
        k = key()
        if rng.coinflip():
            tr.read_conflict_ranges.append(KeyRange(k, k + b"\x00"))
        else:
            e = key()
            if k < e:
                tr.read_conflict_ranges.append(KeyRange(k, e))
    for _ in range(rng.random_int(0, 2)):
        k = key()
        tr.write_conflict_ranges.append(KeyRange(k, k + b"\x00"))
    return tr


@pytest.mark.parametrize("seed", [81, 82, 83])
def test_long_key_parity_bit_identical(seed):
    """Keys past the digest prefix: decisions BIT-IDENTICAL to the
    oracle — not
    merely conservative (ISSUE acceptance criterion; replaces
    test_conflict_tpu.test_long_keys_conservative's weaker assertion)."""
    rng = DeterministicRandom(seed)
    oracle = OracleConflictSet(0)
    sup = make_supervised()
    now = 0
    rechecks_before = sup.stats["rechecked_batches"]
    for _ in range(25):
        now += rng.random_int(1, 2_000_000)
        batch = [random_long_txn(rng, now, 4_000_000)
                 for _ in range(rng.random_int(1, 8))]
        new_oldest = now - 5_000_000 if rng.coinflip() else None
        got = sup.resolve(batch, now, new_oldest)
        want = oracle.resolve(batch, now, new_oldest)
        assert got == want, f"long-key divergence at now={now}"
    # The recheck path actually ran (long keys were present), and the
    # device still carried unflagged batches when short-key-only ones
    # appeared — this is a mixed-path parity proof, not oracle-vs-oracle.
    assert sup.stats["rechecked_batches"] > rechecks_before
    assert sup.stats["device_batches"] > 0


def test_digest_collision_commits_exactly():
    """The canonical collision: two truncated keys sharing the full
    digest prefix.  The old conservative backend was allowed to abort the
    non-conflicting reader; the supervised backend must COMMIT it, like
    the oracle."""
    long_a = b"x" * (PREFIX_BYTES + 7)
    long_b = b"x" * PREFIX_BYTES + b"zzz"
    assert host_digest(long_a) == host_digest(long_b)   # really collides
    sup = make_supervised()
    oracle = OracleConflictSet(0)
    w = CommitTransactionRef(
        write_conflict_ranges=[KeyRange(long_a, long_a + b"\x00")])
    assert sup.resolve([w], 100) == oracle.resolve([w], 100)
    r_hit = CommitTransactionRef(
        read_snapshot=50,
        read_conflict_ranges=[KeyRange(long_a, long_a + b"\x00")])
    r_collide = CommitTransactionRef(
        read_snapshot=50,
        read_conflict_ranges=[KeyRange(long_b, long_b + b"\x00")])
    got = sup.resolve([r_hit, r_collide], 200)
    want = oracle.resolve([r_hit, r_collide], 200)
    assert got == want == [CommitResult.CONFLICT, CommitResult.COMMITTED]


def test_taint_flags_short_key_reader_near_widened_insert():
    """A truncated WRITE widens the device insert; a SHORT-key read that
    digest-lands inside the widened region must be rechecked (and decided
    exactly) even though the reader itself has no long keys."""
    sup = make_supervised()
    oracle = OracleConflictSet(0)
    long_w = b"x" * PREFIX_BYTES + b"\x00\x01" + b"tail"  # truncated
    w = CommitTransactionRef(
        write_conflict_ranges=[KeyRange(long_w, long_w + b"\x00")])
    assert sup.resolve([w], 100) == oracle.resolve([w], 100)
    assert sup.stats["taint_size"] > 0
    # PREFIX_BYTES-long read key: untruncated digest, but digest-
    # adjacent to the widened region.  Exact: COMMITTED (keys differ).
    short_r = b"x" * PREFIX_BYTES
    r = CommitTransactionRef(
        read_snapshot=50,
        read_conflict_ranges=[KeyRange(short_r, short_r + b"\x00")])
    got, want = sup.resolve([r], 200), oracle.resolve([r], 200)
    assert got == want == [CommitResult.COMMITTED]


def test_pipelined_async_waits_fold_in_order():
    """resolve_async pipelining: waiting a LATER handle first folds its
    predecessors transparently; verdicts equal a serial oracle run."""
    rng = DeterministicRandom(9)
    domain = make_domain()
    oracle = OracleConflictSet(0)
    sup = make_supervised()
    now = 0
    handles, batches = [], []
    for _ in range(6):
        now += 1_000_000
        batch = [random_txn(rng, domain, now, 3_000_000) for _ in range(5)]
        handles.append(sup.resolve_async(batch, now, now - 5_000_000))
        batches.append((batch, now))
    got_last = handles[-1].wait()            # folds 0..5 in order
    for h, (batch, v) in zip(handles, batches):
        want = oracle.resolve(batch, v, v - 5_000_000)
        assert h.wait() == want
    assert handles[-1].wait() is got_last    # idempotent


# ---------------------------------------------------------------------------
# 2. Robustness / chaos
# ---------------------------------------------------------------------------

def run_chaos_stream(sup, oracle, rng, domain, n_batches, on_batch):
    """Drive identical streams through `sup` and `oracle`, with `on_batch`
    injecting chaos; assert bit-identical verdicts (zero abort-set
    divergence) on EVERY batch."""
    now = 0
    for i in range(n_batches):
        now += 1_000_000
        on_batch(i)
        batch = [random_txn(rng, domain, now, 4_000_000)
                 for _ in range(rng.random_int(1, 8))]
        new_oldest = now - 5_000_000
        got = sup.resolve(batch, now, new_oldest)
        want = oracle.resolve(batch, now, new_oldest)
        assert got == want, f"divergence at batch {i}"
    return now


def test_buggify_backend_death_degrades_and_repromotes():
    """ISSUE acceptance: the device backend is BUGGIFY-killed mid-workload
    — every batch (including those in flight) resolves via the CPU
    fallback with abort sets identical to an all-oracle run, and the
    backend re-promotes once the device recovers."""
    from foundationdb_tpu.core.buggify import force_buggify, unforce_buggify
    rng = DeterministicRandom(17)
    domain = make_domain()
    sup = make_supervised(monitor=never_reprobe_monitor())
    oracle = OracleConflictSet(0)

    def on_batch(i):
        if i == 8:
            # Deterministically fire the BUGGIFY site: the device goes
            # (stickily) dead at this batch's dispatch.
            force_buggify("conflict.device.dead")
        if i == 9:
            # The site fired (sticky death): stop injecting so recovery
            # is observable, but the backend must STAY degraded until the
            # re-probe window opens.
            unforce_buggify("conflict.device.dead")
            assert sup.degraded and sup._buggify_dead
        if i == 16:
            # Device "recovers": clear the sticky death and open the
            # re-probe window; the next dispatch rebuilds from the mirror.
            sup._buggify_dead = False
            sup.monitor.tripped_at = -1e12

    try:
        run_chaos_stream(sup, oracle, rng, domain, 24, on_batch)
    finally:
        unforce_buggify()
    st = sup.status()
    assert st["degrades"] == 1
    assert st["promotions"] == 1
    assert not st["degraded"]
    assert st["fallback_batches"] >= 7          # batches 9..15 via mirror
    assert st["device_batches"] >= 8 + 8        # before death + after revive


def test_inflight_batches_survive_death():
    """Batches already DISPATCHED to the device when it dies replay
    through the mirror in dispatch order: no batch lost, no divergence."""
    rng = DeterministicRandom(23)
    domain = make_domain()
    sup = make_supervised(monitor=never_reprobe_monitor())
    oracle = OracleConflictSet(0)
    now = 0
    handles, batches = [], []
    for _ in range(5):
        now += 1_000_000
        batch = [random_txn(rng, domain, now, 3_000_000) for _ in range(5)]
        handles.append(sup.resolve_async(batch, now, now - 5_000_000))
        batches.append((batch, now))
    sup.force_device_error = "timeout"      # device dies before any wait
    for h, (batch, v) in zip(handles, batches):
        want = oracle.resolve(batch, v, v - 5_000_000)
        assert h.wait() == want
    assert sup.degraded
    assert sup.stats["fallback_batches"] == 5


def test_transient_error_retried_with_backoff(knobs):
    """A transient device error is retried (exponential backoff) and the
    batch still lands on the device — no degrade, no fallback."""
    knobs.CONFLICT_DEVICE_RETRY_BACKOFF_S = 0.0
    sup = make_supervised()
    sup.force_device_error = ["operation_failed"]   # one-shot injection
    w = CommitTransactionRef(write_conflict_ranges=[KeyRange(b"a", b"b")])
    assert sup.resolve([w], 100) == [CommitResult.COMMITTED]
    assert sup.stats["retries"] >= 1
    assert not sup.degraded
    assert sup.stats["fallback_batches"] == 0


def test_deadline_guard_degrades_on_stall(knobs):
    """A device whose resolve stalls past CONFLICT_DEVICE_TIMEOUT_S is
    abandoned mid-call; the batch resolves via the mirror and the backend
    is degraded."""
    knobs.CONFLICT_DEVICE_TIMEOUT_S = 0.1

    class StallingDevice(OracleConflictSet):
        def resolve(self, *a, **kw):
            time.sleep(0.5)
            return super().resolve(*a, **kw)

    sup = SupervisedConflictSet(
        lambda oldest_version=0: StallingDevice(oldest_version),
        monitor=never_reprobe_monitor())
    oracle = OracleConflictSet(0)
    w = CommitTransactionRef(write_conflict_ranges=[KeyRange(b"a", b"b")])
    r = CommitTransactionRef(read_snapshot=50,
                             read_conflict_ranges=[KeyRange(b"a", b"b")])
    assert sup.resolve([w], 100) == oracle.resolve([w], 100)
    assert sup.degraded
    assert sup.resolve([r], 200) == oracle.resolve([r], 200) \
        == [CommitResult.CONFLICT]


def test_promotion_rebuilds_history_from_mirror():
    """History written BEFORE death and DURING degradation must both be
    visible to the device after promotion (the rebuild replay)."""
    sup = make_supervised(monitor=never_reprobe_monitor())
    w1 = CommitTransactionRef(write_conflict_ranges=[KeyRange(b"a", b"b")])
    assert sup.resolve([w1], 100) == [CommitResult.COMMITTED]
    sup.force_device_error = "timeout"
    w2 = CommitTransactionRef(write_conflict_ranges=[KeyRange(b"m", b"n")])
    assert sup.resolve([w2], 200) == [CommitResult.COMMITTED]
    assert sup.degraded
    sup.force_device_error = None
    sup.monitor.tripped_at = -1e12          # re-probe due now
    # Reads with snapshots below each write version: conflicts from BOTH
    # epochs of history, answered by the promoted device.
    r1 = CommitTransactionRef(read_snapshot=50,
                              read_conflict_ranges=[KeyRange(b"a", b"b")])
    r2 = CommitTransactionRef(read_snapshot=150,
                              read_conflict_ranges=[KeyRange(b"m", b"n")])
    r3 = CommitTransactionRef(read_snapshot=150,
                              read_conflict_ranges=[KeyRange(b"x", b"y")])
    got = sup.resolve([r1, r2, r3], 300)
    assert got == [CommitResult.CONFLICT, CommitResult.CONFLICT,
                   CommitResult.COMMITTED]
    assert not sup.degraded
    assert sup.stats["promotions"] == 1


# ---------------------------------------------------------------------------
# 3. Health machinery
# ---------------------------------------------------------------------------

def test_slo_trip_does_not_skip_recheck_of_tripping_batch():
    """Regression: the latency-SLO degrade clears the taint set, but the
    batch that LANDS the final strike must still be judged against the
    pre-degrade taint — its verdicts fold before the degrade."""
    monitor = BackendHealthMonitor(latency_slo_s=1e-9, slo_strikes=2,
                                   reprobe_interval_s=1e9)
    sup = make_supervised(monitor=monitor)
    long_w = b"x" * PREFIX_BYTES + b"\x00\x01" + b"tail"
    w = CommitTransactionRef(
        write_conflict_ranges=[KeyRange(long_w, long_w + b"\x00")])
    assert sup.resolve([w], 100) == [CommitResult.COMMITTED]   # strike 1
    assert sup.stats["taint_size"] > 0 and not sup.degraded
    # Strike 2 trips the monitor; this same batch's short-key read
    # digest-lands inside the widened region — exact answer: COMMITTED.
    short_r = b"x" * PREFIX_BYTES
    r = CommitTransactionRef(
        read_snapshot=50,
        read_conflict_ranges=[KeyRange(short_r, short_r + b"\x00")])
    assert sup.resolve([r], 200) == [CommitResult.COMMITTED]
    assert sup.degraded                     # degrade landed AFTER the fold


def test_health_monitor_failure_threshold():
    t = [0.0]
    m = BackendHealthMonitor(failure_threshold=3, time_fn=lambda: t[0])
    m.record_failure(); m.record_failure()
    assert not m.tripped
    m.record_success(0.01)                  # success resets the streak
    m.record_failure(); m.record_failure()
    assert not m.tripped
    m.record_failure()
    assert m.tripped


def test_health_monitor_latency_slo_strikes():
    m = BackendHealthMonitor(latency_slo_s=0.1, slo_strikes=3,
                             time_fn=lambda: 0.0)
    for _ in range(2):
        m.record_success(0.5)
    assert not m.tripped
    m.record_success(0.01)                  # fast batch resets strikes
    for _ in range(3):
        m.record_success(0.5)
    assert m.tripped


def test_health_monitor_reprobe_backoff():
    t = [0.0]
    m = BackendHealthMonitor(reprobe_interval_s=10.0, reprobe_max_s=1000.0,
                             time_fn=lambda: t[0])
    m.trip()
    assert not m.reprobe_due()
    t[0] = 11.0
    assert m.reprobe_due()
    m.record_probe_failure()                # backoff doubles: 20s now
    t[0] = 25.0
    assert not m.reprobe_due()
    t[0] = 32.0
    assert m.reprobe_due()
    m.reset()
    assert not m.tripped and not m.reprobe_due()


def test_resolve_with_conflicts_reports_ranges():
    """The resolver-facing API keeps working across the fallback: a
    reporting reader gets its conflicting ranges in both modes."""
    sup = make_supervised(monitor=never_reprobe_monitor())
    for fail_first in (False, True):
        if fail_first:
            sup.force_device_error = "timeout"
        w = CommitTransactionRef(
            write_conflict_ranges=[KeyRange(b"k", b"l")])
        r = CommitTransactionRef(
            read_snapshot=50,
            read_conflict_ranges=[KeyRange(b"k", b"l")])
        r.report_conflicting_keys = True
        base = 1000 if fail_first else 0
        verdicts, ranges = sup.resolve_with_conflicts([w, r], base + 100)
        assert verdicts == [CommitResult.COMMITTED, CommitResult.CONFLICT]
        assert ranges == {1: [(b"k", b"l")]}
