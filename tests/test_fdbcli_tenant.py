"""Fast fdbcli tenant/quota smoke against a SIM cluster (ISSUE 2
satellite): the CLI surface round-trips tenant create/list/get/delete
and quota set/get, so the command plumbing can't silently rot.  Uses the
same Cli-over-existing-client trick as test_real_cluster's fdbcli test,
but against the in-process simulated cluster — fast, not slow-marked."""

from foundationdb_tpu.tools.fdbcli import Cli

from test_recovery import make_cluster, teardown  # noqa: F401


def _cli(c):
    cli = Cli.__new__(Cli)
    cli.loop, cli.db = c.loop, c.database()
    return cli


def test_fdbcli_tenant_commands_roundtrip(teardown):  # noqa: F811
    c = make_cluster()
    cli = _cli(c)

    out = cli.dispatch("tenant create web")
    assert "has been created" in out and "id 1" in out
    assert "has been created" in cli.dispatch("tenant create api")
    out = cli.dispatch("tenant list")
    assert "1. web" in out.replace("api", "web") or "web" in out
    assert "api" in out and "web" in out
    out = cli.dispatch("tenant get web")
    assert "id: 1" in out and "prefix:" in out
    assert "not found" in cli.dispatch("tenant get nope")

    # Quotas round-trip and reject unknown tenants.
    assert "set to 12.5 tps" in cli.dispatch("quota set web 12.5")
    assert "12.5 tps" in cli.dispatch("quota get web")
    out = cli.dispatch("quota get")
    assert "web = 12.5 tps" in out
    assert "no quota" in cli.dispatch("quota get api")
    assert cli.dispatch("quota set ghost 1").startswith("ERROR")
    assert "cleared" in cli.dispatch("quota clear web")
    assert "No tenant quotas set" in cli.dispatch("quota get")

    # Delete: refused while non-empty is exercised elsewhere; here the
    # empty tenant deletes and disappears from the listing.
    assert "has been deleted" in cli.dispatch("tenant delete api")
    assert "api" not in cli.dispatch("tenant list")
    # Usage strings on malformed input, not tracebacks.
    assert cli.dispatch("tenant frobnicate").startswith("usage:")
    assert cli.dispatch("quota bogus").startswith("usage:")
    # Help mentions the new command families.
    help_text = cli.dispatch("help")
    assert "tenant create" in help_text and "quota set" in help_text
