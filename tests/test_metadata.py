"""Metadata through the pipeline: \\xff system keys drive the shard map.

Reference: fdbclient/SystemData.cpp key conventions +
fdbserver/ApplyMetadataMutation.cpp:52-61 — a committed
`\\xff/keyServers/` mutation updates every proxy's routing table, rides
the TXS_TAG stream for recovery replay, and is serializable like any
transaction.  These tests prove a shard-map change mid-run reroutes new
mutations with no static rewiring, propagates across proxies via the
resolver state-transaction stream, and survives both an epoch change and
a whole-cluster power-fail reboot."""

import pytest

from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration
from foundationdb_tpu.server.system_data import (key_servers_key,
                                                 key_servers_value)

from test_recovery import commit_kv, read_key, teardown  # noqa: F401


def make_cluster(**cfg):
    n_workers = cfg.pop("n_workers", 5)
    n_storage_workers = cfg.pop("n_storage_workers", 2)
    config = DatabaseConfiguration(**cfg)
    return SimFdbCluster(config=config, n_workers=n_workers,
                        n_storage_workers=n_storage_workers)


async def move_range(db, begin, end, team, restore_team):
    """One metadata transaction assigning [begin, end) to `team` (the
    following range keeps `restore_team`) — what MoveKeys will issue."""
    from foundationdb_tpu.core import FdbError
    t = db.create_transaction()
    t.access_system_keys = True
    while True:
        try:
            t.set(key_servers_key(begin), key_servers_value(team))
            t.set(key_servers_key(end), key_servers_value(restore_team))
            await t.commit()
            return
        except FdbError as e:
            await t.on_error(e)


def storage_role(cluster, tag):
    for _p, w, _cc, _lv in cluster.workers:
        for ss in w.storage_roles:
            if ss.tag == tag:
                return ss
    return None


def test_shard_map_change_reroutes(teardown):  # noqa: F811
    c = make_cluster(n_storage=2)
    db = c.database()

    async def go():
        from foundationdb_tpu.core.scheduler import delay
        await commit_kv(db, b"warm", b"up")
        # Initially [b"", \x80) -> tag 0, so b"zz/..." lives on tag 0.
        await commit_kv(db, b"zz/before", b"old")
        # Move [zz/, zz0) to tag 1's team, transactionally.
        await move_range(db, b"zz/", b"zz0", [1], [0])
        await commit_kv(db, b"zz/x", b"routed")
        assert await read_key(db, b"zz/x") == b"routed"
        await delay(0.3)   # let storage pulls drain
        ss0, ss1 = storage_role(c, 0), storage_role(c, 1)
        assert ss1.data.get(b"zz/x", ss1.version.get()) == b"routed", \
            "new writes must route to the newly assigned team"
        assert ss0.data.get(b"zz/x", ss0.version.get()) is None
        # The pre-move key stayed where it was written.
        assert ss0.data.get(b"zz/before", ss0.version.get()) == b"old"

    c.run_until(c.loop.spawn(go()), timeout=120)


def test_metadata_survives_epoch_change_and_reboot(teardown):  # noqa: F811
    c = make_cluster(n_storage=2)
    db = c.database()

    async def phase1():
        await commit_kv(db, b"warm", b"up")
        await move_range(db, b"zz/", b"zz0", [1], [0])
        await commit_kv(db, b"zz/a", b"1")
        # Epoch change: the new master must replay the TXS_TAG deltas and
        # seed the new proxies with the CURRENT map.
        cc = c.current_cc()
        c.sim.kill_process(c.process_of(cc.db_info.master))
        await commit_kv(db, b"zz/b", b"2")
        from foundationdb_tpu.core.scheduler import delay
        await delay(0.3)
        ss1 = storage_role(c, 1)
        assert ss1.data.get(b"zz/b", ss1.version.get()) == b"2", \
            "post-recovery writes must still route to the moved team"

    c.run_until(c.loop.spawn(phase1()), timeout=120)

    c.power_fail_reboot()
    db2 = c.database()

    async def phase2():
        assert await read_key(db2, b"zz/a") == b"1"
        assert await read_key(db2, b"zz/b") == b"2"
        await commit_kv(db2, b"zz/c", b"3")
        from foundationdb_tpu.core.scheduler import delay
        await delay(0.3)
        ss1 = storage_role(c, 1)
        assert ss1.data.get(b"zz/c", ss1.version.get()) == b"3", \
            "the moved boundary must survive a power-fail reboot"

    c.run_until(c.loop.spawn(phase2()), timeout=120)


def test_system_keys_require_option(teardown):  # noqa: F811
    c = make_cluster()
    db = c.database()

    async def go():
        from foundationdb_tpu.core import FdbError
        t = db.create_transaction()
        with pytest.raises(FdbError) as ei:
            t.set(b"\xff/keyServers/x", b"v")
        assert ei.value.name == "key_outside_legal_range"

    c.run_until(c.loop.spawn(go()), timeout=30)
