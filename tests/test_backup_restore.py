"""Backup/restore v1: snapshot + mutation-log capture, restore to an empty
cluster, invariant-checked (reference FileBackupAgent.actor.cpp +
BackupWorker.actor.cpp:1033).  Writes continue DURING the snapshot (they
must land via the log stream) and include unresolved atomic ops (they must
replay exactly once through the single backup-tag stream)."""

import pytest

from foundationdb_tpu.core import FdbError
from foundationdb_tpu.client.backup import FileBackupAgent, restore
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration
from foundationdb_tpu.server.sim_fs import SimFileSystem
from foundationdb_tpu.txn.types import MutationType

from test_recovery import commit_kv, read_key, teardown  # noqa: F401


async def read_all(db):
    t = db.create_transaction()
    while True:
        try:
            return dict(await t.get_range(b"", b"\xff", limit=100000))
        except FdbError as e:
            await t.on_error(e)


def test_backup_restore_roundtrip(teardown):  # noqa: F811
    src = SimFdbCluster(config=DatabaseConfiguration(), n_workers=5,
                        n_storage_workers=2)
    db = src.database()
    backup_fs = SimFileSystem()

    async def run_backup():
        from foundationdb_tpu.core.scheduler import delay
        # Pre-backup state.
        for i in range(30):
            await commit_kv(db, b"pre/%03d" % i, b"v%03d" % i)
        agent = FileBackupAgent(src, db, backup_fs)
        await agent.submit()
        # Writes AFTER the snapshot version: only the log stream has them.
        for i in range(20):
            await commit_kv(db, b"during/%03d" % i, b"d%03d" % i)
        # Atomic ops: replay must preserve exact accumulation.
        for _ in range(5):
            t = db.create_transaction()
            while True:
                try:
                    t.atomic_op(MutationType.AddValue, b"acc",
                                (3).to_bytes(8, "little"))
                    await t.commit()
                    break
                except FdbError as e:
                    await t.on_error(e)
        # Overwrites and clears after the snapshot.
        await commit_kv(db, b"pre/000", b"overwritten")
        t = db.create_transaction()
        while True:
            try:
                t.clear(b"pre/001", b"pre/003")
                await t.commit()
                break
            except FdbError as e:
                await t.on_error(e)
        await agent.stop()
        return await read_all(db)

    expected = src.run_until(src.loop.spawn(run_backup()), timeout=300)
    assert expected[b"acc"] == (15).to_bytes(8, "little")
    assert expected[b"pre/000"] == b"overwritten"
    assert b"pre/001" not in expected and b"pre/002" not in expected

    # Fresh, empty cluster on its own simulator/event loop.
    from foundationdb_tpu.core import DeterministicRandom, \
        set_deterministic_random
    set_deterministic_random(DeterministicRandom(77))
    dst = SimFdbCluster(config=DatabaseConfiguration(), n_workers=5,
                        n_storage_workers=2)
    db2 = dst.database()

    async def run_restore():
        n = await restore(db2, backup_fs)
        assert n > 0
        return await read_all(db2)

    restored = dst.run_until(dst.loop.spawn(run_restore()), timeout=300)
    assert restored == expected, (
        f"restore divergence: {len(restored)} vs {len(expected)} keys")


def test_backup_capture_survives_recovery_and_agent_death(teardown):  # noqa: F811,E501
    """The backup worker ROLE (server/backup_worker.py) owns log capture:
    it is re-recruited each epoch and resumes from the container tail, so
    neither a recovery nor the submitting agent's death leaves a hole."""
    from foundationdb_tpu.core.scheduler import delay
    src = SimFdbCluster(config=DatabaseConfiguration(), n_workers=5,
                        n_storage_workers=2)
    db = src.database()
    backup_fs = SimFileSystem()

    async def run_backup():
        for i in range(10):
            await commit_kv(db, b"pre/%03d" % i, b"v%03d" % i)
        agent = FileBackupAgent(src, db, backup_fs)
        await agent.submit()
        for i in range(10):
            await commit_kv(db, b"mid/%03d" % i, b"m%03d" % i)
        # Epoch change mid-capture: kill the master; the next epoch
        # re-recruits a backup worker that resumes from the container.
        epoch = src.current_cc().db_info.epoch
        mp = src.process_of(src.current_cc().db_info.master)
        src.sim.kill_process(mp)
        for _ in range(200):
            cc = src.current_cc()
            if cc is not None and cc.db_info.epoch > epoch and \
                    cc.db_info.recovery_state in ("accepting_commits",
                                                  "fully_recovered"):
                break
            await delay(0.25)
        for i in range(10):
            await commit_kv(db, b"post/%03d" % i, b"p%03d" % i)
        await agent.stop()
        return await read_all(db)

    expected = src.run_until(src.loop.spawn(run_backup()), timeout=600)
    assert any(k.startswith(b"post/") for k in expected)

    from foundationdb_tpu.core import DeterministicRandom, \
        set_deterministic_random
    set_deterministic_random(DeterministicRandom(78))
    dst = SimFdbCluster(config=DatabaseConfiguration(), n_workers=5,
                        n_storage_workers=2)
    db2 = dst.database()

    async def run_restore():
        n = await restore(db2, backup_fs)
        assert n > 0
        return await read_all(db2)

    restored = dst.run_until(dst.loop.spawn(run_restore()), timeout=300)
    assert restored == expected, (
        f"restore divergence: {len(restored)} vs {len(expected)} keys")


def test_snapshot_tasks_resume_after_agent_death(teardown):  # noqa: F811
    """The snapshot is a TaskBucket chunk chain: killing the executing
    agent mid-snapshot leaves claimable/reclaimable tasks that a SECOND
    agent finishes — resumable-by-any-agent (reference TaskBucket)."""
    from foundationdb_tpu.core.scheduler import delay
    src = SimFdbCluster(config=DatabaseConfiguration(), n_workers=5,
                        n_storage_workers=2)
    db = src.database()
    backup_fs = SimFileSystem()

    async def go():
        # Enough keys for several 500-key chunks.
        for i in range(60):
            t = db.create_transaction()
            while True:
                try:
                    for j in range(20):
                        t.set(b"bulk/%03d/%02d" % (i, j), b"x%04d" % j)
                    await t.commit()
                    break
                except FdbError as e:
                    await t.on_error(e)
        agent = FileBackupAgent(src, db, backup_fs)
        agent.bucket.timeout = 400_000      # fast reclaim (~0.4s versions)
        # Submit WITHOUT letting the internal agent finish: start it,
        # then kill the executing agent after the FIRST chunk lands.
        start_f = src.loop.spawn(agent.submit(), "submitBackup")
        for _ in range(400):
            if await agent.container.snapshot_complete():
                break
            try:
                backup_fs.open("backup.snap.part0", create=False)
            except FdbError:
                await delay(0.02)
                continue
            if agent._agent_f is not None:
                agent._agent_f.cancel()      # the first agent dies
            break
        # Burn a little version time so its claimed mid-flight task (if
        # any) times out, then a second agent drains the chain.
        for i in range(8):
            await commit_kv(db, b"burn", b"%d" % i)
            await delay(0.08)
        agent.run_agent("agent1")
        for _ in range(600):
            if await agent.container.snapshot_complete():
                break
            await delay(0.05)
        assert await agent.container.snapshot_complete()
        await start_f
        _v, kvs = await agent.container.read_snapshot()
        keys = {k for k, _ in kvs}
        assert all(b"bulk/%03d/00" % i in keys for i in range(60))
        await agent.stop()
        return True

    assert src.run_until(src.loop.spawn(go()), timeout=600)


def test_fast_restore_distributed_agents(teardown):  # noqa: F811
    """Fast restore (reference RestoreLoader/RestoreApplier): snapshot
    parts and per-key-range log replay fan out over a TaskBucket agent
    fleet; killing an agent mid-restore just reassigns its tasks."""
    from foundationdb_tpu.client.backup import restore_distributed
    from foundationdb_tpu.core.scheduler import delay
    src = SimFdbCluster(config=DatabaseConfiguration(), n_workers=5,
                        n_storage_workers=2)
    db = src.database()
    backup_fs = SimFileSystem()

    async def run_backup():
        for i in range(50):
            t = db.create_transaction()
            while True:
                try:
                    for j in range(25):
                        t.set(b"fr/%03d/%02d" % (i, j), b"v%d.%d" % (i, j))
                    await t.commit()
                    break
                except FdbError as e:
                    await t.on_error(e)
        agent = FileBackupAgent(src, db, backup_fs)
        await agent.submit()
        # Post-snapshot writes ride the log: overwrites, clears, atomics.
        await commit_kv(db, b"fr/000/00", b"overwritten")
        t = db.create_transaction()
        while True:
            try:
                t.clear(b"fr/001/", b"fr/002/")
                t.atomic_op(MutationType.AddValue, b"fr/acc",
                            (9).to_bytes(8, "little"))
                await t.commit()
                break
            except FdbError as e:
                await t.on_error(e)
        await agent.stop()
        return await read_all(db)

    expected = src.run_until(src.loop.spawn(run_backup()), timeout=600)
    assert expected[b"fr/000/00"] == b"overwritten"
    assert b"fr/001/00" not in expected

    from foundationdb_tpu.core import DeterministicRandom, \
        set_deterministic_random
    set_deterministic_random(DeterministicRandom(79))
    dst = SimFdbCluster(config=DatabaseConfiguration(), n_workers=5,
                        n_storage_workers=2)
    db2 = dst.database()

    async def run_restore():
        f = dst.loop.spawn(
            restore_distributed(dst, db2, backup_fs, n_agents=3),
            "fastRestore")
        await f
        return await read_all(db2)

    restored = dst.run_until(dst.loop.spawn(run_restore()), timeout=600)
    assert restored == expected, (
        f"fast-restore divergence: {len(restored)} vs {len(expected)}")
