"""Backup/restore v1: snapshot + mutation-log capture, restore to an empty
cluster, invariant-checked (reference FileBackupAgent.actor.cpp +
BackupWorker.actor.cpp:1033).  Writes continue DURING the snapshot (they
must land via the log stream) and include unresolved atomic ops (they must
replay exactly once through the single backup-tag stream)."""

import pytest

from foundationdb_tpu.core import FdbError
from foundationdb_tpu.client.backup import FileBackupAgent, restore
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration
from foundationdb_tpu.server.sim_fs import SimFileSystem
from foundationdb_tpu.txn.types import MutationType

from test_recovery import commit_kv, read_key, teardown  # noqa: F401


async def read_all(db):
    t = db.create_transaction()
    while True:
        try:
            return dict(await t.get_range(b"", b"\xff", limit=100000))
        except FdbError as e:
            await t.on_error(e)


def test_backup_restore_roundtrip(teardown):  # noqa: F811
    src = SimFdbCluster(config=DatabaseConfiguration(), n_workers=5,
                        n_storage_workers=2)
    db = src.database()
    backup_fs = SimFileSystem()

    async def run_backup():
        from foundationdb_tpu.core.scheduler import delay
        # Pre-backup state.
        for i in range(30):
            await commit_kv(db, b"pre/%03d" % i, b"v%03d" % i)
        agent = FileBackupAgent(src, db, backup_fs)
        await agent.submit()
        # Writes AFTER the snapshot version: only the log stream has them.
        for i in range(20):
            await commit_kv(db, b"during/%03d" % i, b"d%03d" % i)
        # Atomic ops: replay must preserve exact accumulation.
        for _ in range(5):
            t = db.create_transaction()
            while True:
                try:
                    t.atomic_op(MutationType.AddValue, b"acc",
                                (3).to_bytes(8, "little"))
                    await t.commit()
                    break
                except FdbError as e:
                    await t.on_error(e)
        # Overwrites and clears after the snapshot.
        await commit_kv(db, b"pre/000", b"overwritten")
        t = db.create_transaction()
        while True:
            try:
                t.clear(b"pre/001", b"pre/003")
                await t.commit()
                break
            except FdbError as e:
                await t.on_error(e)
        await agent.stop()
        return await read_all(db)

    expected = src.run_until(src.loop.spawn(run_backup()), timeout=300)
    assert expected[b"acc"] == (15).to_bytes(8, "little")
    assert expected[b"pre/000"] == b"overwritten"
    assert b"pre/001" not in expected and b"pre/002" not in expected

    # Fresh, empty cluster on its own simulator/event loop.
    from foundationdb_tpu.core import DeterministicRandom, \
        set_deterministic_random
    set_deterministic_random(DeterministicRandom(77))
    dst = SimFdbCluster(config=DatabaseConfiguration(), n_workers=5,
                        n_storage_workers=2)
    db2 = dst.database()

    async def run_restore():
        n = await restore(db2, backup_fs)
        assert n > 0
        return await read_all(db2)

    restored = dst.run_until(dst.loop.spawn(run_restore()), timeout=300)
    assert restored == expected, (
        f"restore divergence: {len(restored)} vs {len(expected)} keys")
