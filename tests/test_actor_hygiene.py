"""Future/actor teardown hygiene (VERDICT round-5 weak #7).

A discarded sim world can hold actors that were spawned but never
stepped; their coroutine objects used to surface as `RuntimeWarning:
coroutine '...' was never awaited` at GC (monitor_leader /
_open_database_loop during workload teardown) — exactly the noise a real
dropped-callback bug would hide behind.  EventLoop.shutdown() (invoked
when set_event_loop replaces a loop) must close them, keeping teardown
warning-clean by construction."""

import gc
import warnings

from foundationdb_tpu.core import EventLoop, set_event_loop


def _collect_warning_clean():
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        gc.collect()


def test_unstarted_actor_teardown_is_warning_clean():
    lp = EventLoop(sim=True)
    set_event_loop(lp)

    async def never_stepped():
        await lp.delay(1.0)

    # Spawned but the loop never runs — the workload-teardown shape.
    lp.spawn(never_stepped(), "a")
    lp.spawn(never_stepped(), "b")
    set_event_loop(None)            # replaces the loop -> shutdown()
    del lp
    _collect_warning_clean()


def test_cluster_connection_teardown_is_warning_clean():
    """The exact VERDICT reproducer: ClusterConnection spawns
    monitor_leader + _open_database_loop; the world is torn down before
    the reactor ever steps them."""
    from foundationdb_tpu.client.database import ClusterConnection
    lp = EventLoop(sim=True)
    set_event_loop(lp)
    conn = ClusterConnection(coordinators=[])
    set_event_loop(None)
    del conn, lp
    _collect_warning_clean()


def test_started_actors_unaffected_by_shutdown():
    """shutdown() must not disturb actors that already ran: their results
    stand, and re-running a fresh loop afterwards works."""
    lp = EventLoop(sim=True)
    set_event_loop(lp)
    results = []

    async def work():
        results.append(1)
        return "done"

    fut = lp.spawn(work(), "w")
    assert lp.run_until(fut) == "done"
    set_event_loop(None)
    assert results == [1]
    _collect_warning_clean()
