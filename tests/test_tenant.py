"""Multi-tenant keyspace (ISSUE 2 tentpole): tenant map CRUD, prefixed
Tenant handles, cross-tenant isolation under BUGGIFY chaos, the commit
proxies' tenant fence, recovery persistence, and the special-keyspace
tenant listing.

Reference shape: fdbclient/Tenant.h + TenantManagement + the tenant
validation in CommitProxyServer."""

import pytest

from foundationdb_tpu.core import FdbError

from test_recovery import make_cluster, teardown  # noqa: F401


def run(c, coro, timeout=300):
    return c.run_until(c.loop.spawn(coro), timeout=timeout)


async def tenant_txn(tenant, fn):
    t = tenant.create_transaction()
    while True:
        try:
            r = await fn(t)
            await t.commit()
            return r
        except FdbError as e:
            await t.on_error(e)


def test_tenant_map_crud_and_metadata_version(teardown):  # noqa: F811
    c = make_cluster()
    db = c.database()

    async def go():
        from foundationdb_tpu.tenant import management as tm
        mv0 = await tm.tenant_metadata_version(db)
        a = await tm.create_tenant(db, b"acme")
        assert a.id >= 1 and len(a.prefix) == 8
        # Idempotent create returns the SAME entry.
        assert await tm.create_tenant(db, b"acme") == a
        b = await tm.create_tenant(db, b"bcorp")
        assert b.id != a.id and b.prefix != a.prefix
        names = [e.name for e in await tm.list_tenants(db)]
        assert names == [b"acme", b"bcorp"]
        assert (await tm.get_tenant(db, b"acme")) == a
        assert (await tm.get_tenant(db, b"nope")) is None
        mv1 = await tm.tenant_metadata_version(db)
        assert mv1 >= mv0 + 2          # one bump per create
        await tm.delete_tenant(db, b"bcorp")
        assert (await tm.get_tenant(db, b"bcorp")) is None
        assert await tm.tenant_metadata_version(db) > mv1
        # Delete is idempotent.
        await tm.delete_tenant(db, b"bcorp")
        # Recreation allocates a FRESH id (prefixes never recycle).
        b2 = await tm.create_tenant(db, b"bcorp")
        assert b2.id > b.id
        # Name validation.
        for bad in (b"", b"\xffx", b"a\x00b", b"x" * 200):
            with pytest.raises(FdbError):
                await tm.create_tenant(db, bad)
        return True

    assert run(c, go())


def test_tenant_handle_isolation_and_rejection(teardown):  # noqa: F811
    c = make_cluster()
    db = c.database()

    async def go():
        from foundationdb_tpu.tenant import management as tm
        await tm.create_tenant(db, b"t1")
        await tm.create_tenant(db, b"t2")
        t1 = await db.open_tenant(b"t1")
        t2 = await db.open_tenant(b"t2")

        async def put(t, v):
            async def fn(txn):
                txn.set(b"shared", v)
                txn.set(b"mine/" + v, v)
            await tenant_txn(t, fn)
        await put(t1, b"one")
        await put(t2, b"two")

        async def read(t, k):
            async def fn(txn):
                return await txn.get(k)
            return await tenant_txn(t, fn)
        # Identical relative key, different values per tenant.
        assert await read(t1, b"shared") == b"one"
        assert await read(t2, b"shared") == b"two"
        # Range reads stay inside the prefix and strip it.
        async def scan(t):
            async def fn(txn):
                return await txn.get_range(b"", b"\xff", limit=100)
            return await tenant_txn(t, fn)
        rows1 = await scan(t1)
        assert [k for k, _v in rows1] == [b"mine/one", b"shared"]
        # Raw view: the data lives under each tenant's committed prefix.
        raw = db.create_transaction()
        while True:
            try:
                got = await raw.get(t2.prefix + b"shared")
                break
            except FdbError as e:
                await raw.on_error(e)
        assert got == b"two"
        # The handle cannot address outside its prefix.
        txn = t1.create_transaction()
        with pytest.raises(FdbError):
            txn.set(b"\xff/system", b"x")
        with pytest.raises(FdbError):
            await txn.get(b"\xff\xff/status/json")
        return True

    assert run(c, go())


def test_two_tenants_same_keys_never_conflict_under_chaos(teardown):  # noqa: F811,E501
    """ISSUE acceptance: two tenants writing IDENTICAL tenant-relative
    keys never conflict with each other and can never read each other's
    data through Tenant handles, under BUGGIFY chaos."""
    from foundationdb_tpu.core import enable_buggify
    c = make_cluster(n_workers=6)
    db = c.database()
    enable_buggify(True)
    try:
        async def go():
            from foundationdb_tpu.core.futures import wait_all
            from foundationdb_tpu.core.scheduler import spawn
            from foundationdb_tpu.tenant import management as tm
            await tm.create_tenant(db, b"ca")
            await tm.create_tenant(db, b"cb")
            ta = await db.open_tenant(b"ca")
            tb = await db.open_tenant(b"cb")
            conflicts = [0]

            async def writer(tenant, tag, rounds=25):
                for i in range(rounds):
                    txn = tenant.create_transaction()
                    while True:
                        try:
                            # Same relative keys from both tenants, with
                            # reads so cross-tenant conflicts WOULD fire
                            # if prefixes ever collided.
                            await txn.get(b"hot")
                            txn.set(b"hot", tag + b"%04d" % i)
                            txn.set(b"k%02d" % (i % 7), tag)
                            await txn.commit()
                            break
                        except FdbError as e:
                            if e.name == "not_committed":
                                conflicts[0] += 1
                            await txn.on_error(e)

            await wait_all([spawn(writer(ta, b"A")),
                            spawn(writer(tb, b"B"))])
            # The two tenants ran interleaved on the same relative keys:
            # NO conflict can have fired (their prefixed keys are
            # disjoint, and nothing else writes in this test).
            assert conflicts[0] == 0, \
                f"{conflicts[0]} cross-tenant conflicts"

            async def read(t, k):
                async def fn(txn):
                    return await txn.get(k)
                return await tenant_txn(t, fn)
            va, vb = await read(ta, b"hot"), await read(tb, b"hot")
            assert va is not None and va.startswith(b"A")
            assert vb is not None and vb.startswith(b"B")
            return True

        assert run(c, go(), timeout=600)
    finally:
        enable_buggify(False)


def test_deleted_tenant_writes_fenced_by_proxy(teardown):  # noqa: F811
    c = make_cluster()
    db = c.database()

    async def go():
        from foundationdb_tpu.tenant import management as tm
        await tm.create_tenant(db, b"victim")
        t = await db.open_tenant(b"victim")

        async def put(txn):
            txn.set(b"a", b"1")
        await tenant_txn(t, put)
        # Delete requires an empty keyspace.
        with pytest.raises(FdbError) as ei:
            await tm.delete_tenant(db, b"victim")
        assert ei.value.name == "tenant_not_empty"

        async def wipe(txn):
            txn.clear(b"", b"\xff")
        await tenant_txn(t, wipe)
        await tm.delete_tenant(db, b"victim")
        # A stale handle's write is rejected by the commit proxy with a
        # SPECIFIC non-retryable error — never not_committed (which
        # would loop), never a silent commit.
        txn = t.create_transaction()
        txn.set(b"zombie", b"x")
        with pytest.raises(FdbError) as ei:
            await txn.commit()
        assert ei.value.name == "tenant_not_found"
        # And a forged tenant id pointing at someone ELSE's prefix is
        # rejected as illegal access.
        await tm.create_tenant(db, b"honest")
        honest = await db.open_tenant(b"honest")
        forged = db.create_transaction()
        forged.tenant_id = honest.entry.id
        forged.set(b"outside-prefix", b"x")   # raw key, not prefixed
        with pytest.raises(FdbError) as ei:
            await forged.commit()
        assert ei.value.name == "illegal_tenant_access"
        return True

    assert run(c, go())


def test_same_batch_delete_fences_tenant_write(teardown):  # noqa: F811
    """Review regression: a tenant delete and a tenant write landing in
    the SAME commit batch must not both commit — the later-in-batch
    write validates against the batch-local tenant-map overlay."""
    c = make_cluster()
    db = c.database()

    async def go():
        from foundationdb_tpu.core.futures import swallow, wait_all
        from foundationdb_tpu.core.scheduler import spawn
        from foundationdb_tpu.tenant import management as tm
        from foundationdb_tpu.tenant.map import tenant_map_key
        entry = await tm.create_tenant(db, b"race")
        t = await db.open_tenant(b"race")
        # Build both commits by hand and fire them CONCURRENTLY so the
        # proxy batches them together (sequential awaits would land in
        # separate batches and prove nothing).
        from foundationdb_tpu.txn.types import strinc
        del_txn = db.create_transaction()
        del_txn.access_system_keys = True
        # Same shape as management.delete_tenant: the emptiness check is
        # a read conflict range over the tenant's whole prefix, so a
        # racing write either aborts this delete (write earlier) or is
        # fenced by the batch-local overlay (write later).
        del_txn.add_read_conflict_range(entry.prefix, strinc(entry.prefix))
        del_txn.clear(tenant_map_key(b"race"))
        wr_txn = t.create_transaction()
        wr_txn.set(b"zombie", b"x")
        f_del = spawn(del_txn.commit())
        f_wr = spawn(wr_txn._inner.commit())
        await wait_all([swallow(f_del), swallow(f_wr)])
        # Whatever the interleaving, the invariant holds: data exists
        # under the prefix ONLY IF the tenant still exists.
        raw = db.create_transaction()
        while True:
            try:
                data = await raw.get(entry.prefix + b"zombie")
                break
            except FdbError as e:
                await raw.on_error(e)
        still_there = (await tm.get_tenant(db, b"race")) is not None
        assert still_there or data is None, (
            "write committed under a deleted tenant's prefix")
        return True

    assert run(c, go())


def test_tenants_survive_recovery(teardown):  # noqa: F811
    """The tenant fence must hold across an epoch change: the new
    proxies' caches are seeded from the master's replayed metadata."""
    c = make_cluster(n_workers=6)
    db = c.database()

    async def go():
        from foundationdb_tpu.core.scheduler import delay
        from foundationdb_tpu.tenant import management as tm
        await tm.create_tenant(db, b"durable")
        t = await db.open_tenant(b"durable")

        async def put(txn):
            txn.set(b"k", b"before")
        await tenant_txn(t, put)
        # Force a recovery: kill the master's process.
        cc = c.current_cc()
        proc = c.process_of(cc.db_info.master)
        c.sim.kill_process(proc)
        await delay(1.0)

        async def put2(txn):
            txn.set(b"k", b"after")
        await tenant_txn(t, put2)      # validated by the NEW epoch's fence

        async def read(txn):
            return await txn.get(b"k")
        assert await tenant_txn(t, read) == b"after"
        # The map survived too.
        assert (await tm.get_tenant(db, b"durable")) is not None
        return True

    assert run(c, go(), timeout=600)


def test_special_keyspace_tenant_map(teardown):  # noqa: F811
    c = make_cluster()
    db = c.database()

    async def go():
        import json
        from foundationdb_tpu.tenant import management as tm
        e1 = await tm.create_tenant(db, b"ska")
        e2 = await tm.create_tenant(db, b"skb")
        t = db.create_transaction()
        p = b"\xff\xff/management/tenant/map/"
        rows = await t.get_range(p, p + b"\xff", limit=10)
        assert [k for k, _v in rows] == [p + b"ska", p + b"skb"]
        doc = json.loads(rows[0][1])
        assert doc["id"] == e1.id
        assert bytes.fromhex(doc["prefix"]) == e1.prefix
        # Point get of one entry.
        t2 = db.create_transaction()
        one = await t2.get(p + b"skb")
        assert json.loads(one)["id"] == e2.id
        assert await t2.get(p + b"nope") is None
        # Review regression: odd names on the READ-ONLY mirror are
        # absent, never name-validation errors (GET agrees with
        # GETRANGE on the same keyspace).
        assert await t2.get(p) is None                    # empty name
        assert await t2.get(p + b"a\x00b") is None        # NUL name
        # Review regression: reverse + limit selects the LAST entries
        # (limit applied in iteration direction, not before reversal).
        await tm.create_tenant(db, b"skc")
        t3 = db.create_transaction()
        tail = await t3.get_range(p, p + b"\xff", limit=2, reverse=True)
        assert [k for k, _v in tail] == [p + b"skc", p + b"skb"]
        return True

    assert run(c, go())


def test_tenant_tag_lossless_no_collisions():
    """ISSUE 4 satellite (PR 3 review nit): tenant_tag must be injective
    over byte names.  The old backslashreplace decoding collapsed
    b"a\\xff" and the literal bytes br"a\\xff" onto one throttle tag,
    cross-wiring two tenants' quotas and metering."""
    from foundationdb_tpu.tenant.map import tenant_tag
    colliding = [
        (b"a\xff", b"a\\xff"),            # the review's exact pair
        (b"\xfe\xff", b"\\xfe\\xff"),     # every byte escaped
        (b"hot\x80", b"hot\\x80"),        # lone continuation byte
    ]
    for left, right in colliding:
        assert left.decode("utf-8", "backslashreplace") == \
            right.decode("utf-8", "backslashreplace"), \
            "pair no longer collides under the OLD encoding; update test"
        assert tenant_tag(left) != tenant_tag(right)
    # Injective across a broad sample of distinct names.
    names = [bytes([a, b]) for a in (0, 0x5C, 0x61, 0xFF)
             for b in (0, 0x5C, 0x62, 0xFE)] + [b"plain", b"pla\\in"]
    tags = {tenant_tag(n) for n in names}
    assert len(tags) == len(names)
    # Printable names stay human-readable (status/fdbcli display).
    assert tenant_tag(b"acme-prod") == "t/acme-prod"


def test_tenant_pack_end_type_audit():
    """ISSUE 4 satellite (PR 3 review nit): a non-bytes range END must
    raise like a non-bytes key does — not silently coerce into a wrong
    (usually empty) range."""
    from types import SimpleNamespace
    from foundationdb_tpu.core import FdbError
    from foundationdb_tpu.tenant.handle import Tenant, TenantTransaction
    from foundationdb_tpu.tenant.map import TenantMapEntry
    tenant = Tenant(db=SimpleNamespace(create_transaction=lambda: None),
                    entry=TenantMapEntry(id=7, name=b"t7"))
    txn = TenantTransaction(SimpleNamespace(), tenant)
    with pytest.raises(FdbError) as ei:
        txn._pack_end("\xff")            # str, the silent-coercion case
    assert ei.value.name == "client_invalid_operation"
    with pytest.raises(FdbError):
        txn._pack_end(3)
    # bytes-like ends still work, including the whole-tenant sentinel.
    from foundationdb_tpu.txn.types import strinc
    assert txn._pack_end(b"\xff") == strinc(tenant.prefix)
    assert txn._pack_end(bytearray(b"zz")) == tenant.prefix + b"zz"
    with pytest.raises(FdbError):
        txn._pack(None)                  # _pack audit still intact
