"""StorageCache role (reference fdbserver/StorageCache.actor.cpp +
CommitProxyServer.actor.cpp:959 cacheTag routing): committed
\xff/cacheRanges/ entries route their mutations onto CACHE_TAG, the
cache role fetches + serves them, and location replies add the cache to
the replica set for hot-shard read scaling."""

import pytest

from foundationdb_tpu.client.management import cache_range, uncache_range
from foundationdb_tpu.core.scheduler import delay
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import (CACHE_TAG,
                                                DatabaseConfiguration,
                                                GetValueRequest)
from foundationdb_tpu.rpc.endpoint import RequestStream

from test_recovery import commit_kv, read_key, teardown  # noqa: F401


def make_cluster():
    return SimFdbCluster(
        config=DatabaseConfiguration(n_storage_caches=1),
        n_workers=5, n_storage_workers=2)


def _cache_role(c):
    for _p, w, _cc, _lv in c.workers:
        for ss in w.storage_roles:
            if ss.tag == CACHE_TAG:
                return ss
    return None


def test_cache_serves_hot_range_and_stays_fresh(teardown):  # noqa: F811
    c = make_cluster()
    db = c.database()

    async def go():
        for i in range(10):
            await commit_kv(db, b"hot/%03d" % i, b"h%03d" % i)
        await commit_kv(db, b"cold/x", b"c")
        await cache_range(db, b"hot/", b"hot0")

        cache = _cache_role(c)
        assert cache is not None
        # The cache fetches the range and serves reads for it.
        for _ in range(100):
            st = cache.shards.lookup(b"hot/000")
            if st and st[0] == "owned":
                break
            await delay(0.2)
        st = cache.shards.lookup(b"hot/000")
        assert st and st[0] == "owned", st

        async def cache_get(key):
            for _ in range(60):
                v = cache.version.get()
                try:
                    reply = await RequestStream.at(
                        cache.interface.get_value.endpoint).get_reply(
                        GetValueRequest(key=key, version=v))
                    return reply.value
                except Exception:  # noqa: BLE001 — still catching up
                    await delay(0.2)
            return None

        assert await cache_get(b"hot/003") == b"h003"
        # Freshness: a NEW commit to the cached range rides CACHE_TAG and
        # reaches the cache without any re-fetch.
        await commit_kv(db, b"hot/003", b"h003v2")
        for _ in range(100):
            if await cache_get(b"hot/003") == b"h003v2":
                break
            await delay(0.2)
        assert await cache_get(b"hot/003") == b"h003v2"
        # Cold keys are NOT cached (absent -> wrong_shard_server).
        st = cache.shards.lookup(b"cold/x")
        assert not st or st[0] != "owned"
        # Clients still read correctly with the cache in the replica set.
        assert await read_key(db, b"hot/003") == b"h003v2"
        assert await read_key(db, b"cold/x") == b"c"
        # Uncache drops the range from the cache role.
        await uncache_range(db, b"hot/")
        for _ in range(100):
            st = cache.shards.lookup(b"hot/000")
            if not st or st[0] != "owned":
                break
            await delay(0.2)
        st = cache.shards.lookup(b"hot/000")
        assert not st or st[0] != "owned"
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=300)


def test_cache_survives_recovery(teardown):  # noqa: F811
    c = make_cluster()
    db = c.database()

    async def go():
        await commit_kv(db, b"hot/a", b"1")
        await cache_range(db, b"hot/", b"hot0")
        cache = _cache_role(c)
        for _ in range(100):
            st = cache.shards.lookup(b"hot/a")
            if st and st[0] == "owned":
                break
            await delay(0.2)
        epoch = c.current_cc().db_info.epoch
        mp = c.process_of(c.current_cc().db_info.master)
        c.sim.kill_process(mp)
        for _ in range(200):
            cc = c.current_cc()
            if cc is not None and cc.db_info.epoch > epoch and \
                    cc.db_info.recovery_state in ("accepting_commits",
                                                  "fully_recovered"):
                break
            await delay(0.25)
        # After the epoch change the (new) cache role re-asserts the
        # registry and re-fetches; a post-recovery commit still reaches
        # whichever cache now serves the range.
        await commit_kv(db, b"hot/a", b"2")
        assert await read_key(db, b"hot/a") == b"2"
        cache2 = _cache_role(c)
        assert cache2 is not None
        for _ in range(200):
            st = cache2.shards.lookup(b"hot/a")
            if st and st[0] == "owned" and \
                    cache2.version.get() > 0:
                break
            await delay(0.25)
        st = cache2.shards.lookup(b"hot/a")
        assert st and st[0] == "owned"
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=400)
