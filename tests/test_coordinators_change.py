"""changeQuorum: migrating the coordinator quorum while the cluster runs.

Reference: fdbclient/ManagementAPI.actor.cpp changeQuorumChecker +
fdbserver/CoordinatedState.actor.cpp MovableCoordinatedState.  The
management API commits the new connection spec to \xff/coordinators; the
master seeds the NEW quorum with the current DBCoreState, writes a forward
marker into the OLD quorum, and ends its epoch.  Forwarded coordinators
answer every election/cstate request with the new spec, so campaigning
CCs, monitoring workers, and clients all chase the quorum to its new home
— after which the old coordinators can be killed outright.
"""

import pytest

from foundationdb_tpu.client.management import (change_coordinators,
                                                get_coordinators)
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration

from test_recovery import commit_kv, read_key, teardown  # noqa: F401


def make_cluster(**cfg):
    n_workers = cfg.pop("n_workers", 5)
    n_storage_workers = cfg.pop("n_storage_workers", 2)
    config = DatabaseConfiguration(**cfg)
    return SimFdbCluster(config=config, n_workers=n_workers,
                         n_storage_workers=n_storage_workers)


def test_change_quorum_live_then_kill_old_coordinators(teardown):  # noqa: F811
    c = make_cluster()
    db = c.database()

    async def load():
        for i in range(10):
            await commit_kv(db, b"pre%03d" % i, b"v%03d" % i)

    c.run_until(c.loop.spawn(load()), timeout=120)

    old = list(c.coordinators)
    for i in range(3):
        c.add_coordinator(name=f"newcoord{i}")
    new = c.coordinators[len(old):]
    new_spec = c.spec_of(new)

    async def change():
        await change_coordinators(db, new_spec)
        assert await get_coordinators(db) == new_spec

    c.run_until(c.loop.spawn(change()), timeout=60)

    # The master notices the committed spec within its poll interval,
    # performs the move, and recovers; old coordinators are forwarded.
    async def wait_moved():
        from foundationdb_tpu.core.scheduler import delay
        for _ in range(120):
            if all(s._forward_spec() == new_spec for _, s in old):
                return
            await delay(0.5)
        raise AssertionError("old coordinators never forwarded")

    c.run_until(c.loop.spawn(wait_moved()), timeout=120)

    # Cluster still serves: acked data readable, new commits succeed.
    async def after_move():
        assert await read_key(db, b"pre000") == b"v000"
        await commit_kv(db, b"post-move", b"yes")
        assert await read_key(db, b"post-move") == b"yes"

    c.run_until(c.loop.spawn(after_move()), timeout=120)

    # Kill EVERY old coordinator; the next recovery must elect and
    # recover entirely through the new quorum.
    for p, _ in old:
        c.sim.kill_process(p)
    # Kill the master's process too: the resulting recovery must elect
    # and read/write the coordinated state entirely on the new quorum.
    cc = c.current_cc()
    mp = c.process_of(cc.db_info.master) if cc is not None else None
    if mp is not None:
        c.sim.kill_process(mp)

    async def after_kill():
        await commit_kv(db, b"post-kill", b"yes")
        assert await read_key(db, b"post-kill") == b"yes"
        assert await read_key(db, b"pre001") == b"v001"

    c.run_until(c.loop.spawn(after_kill()), timeout=240)


def test_change_quorum_rejects_unreachable_target(teardown):  # noqa: F811
    c = make_cluster(n_workers=4, n_storage_workers=2)
    db = c.database()

    c.run_until(c.loop.spawn(commit_kv(db, b"k", b"v")), timeout=120)

    async def bad_change():
        from foundationdb_tpu.core.error import FdbError
        try:
            # Addresses with no coordination servers: the reachability
            # probe must fail rather than commit a spec that would brick
            # the next quorum move.
            await change_coordinators(db, "10.99.0.1:4500,10.99.0.2:4500")
        except FdbError:
            return True
        return False

    f = c.loop.spawn(bad_change())
    c.run_until(f, timeout=90)
    assert f.get() is True
