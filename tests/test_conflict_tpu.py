"""TPU conflict backend parity vs the CPU oracle (on virtual CPU devices).

The contract (BASELINE.json): identical commit/abort decisions vs the
SkipList-semantics baseline.  Short keys (<= PREFIX_BYTES) must match
bit-for-bit; longer keys may only add conflicts (conservative), never miss."""

import numpy as np
import pytest

from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet
from foundationdb_tpu.core import DeterministicRandom
from foundationdb_tpu.txn import CommitResult, CommitTransactionRef, KeyRange

from test_conflict_oracle import make_domain, random_txn


@pytest.fixture(scope="module")
def small_caps():
    return dict(capacity=1 << 12)


@pytest.mark.parametrize("seed", [11, 12, 13])
def test_tpu_matches_oracle_random(seed, small_caps):
    rng = DeterministicRandom(seed)
    domain = make_domain()
    oracle = OracleConflictSet(0)
    tpu = TpuConflictSet(0, **small_caps)
    now = 0
    for _ in range(30):
        now += rng.random_int(1, 2_000_000)
        batch = [random_txn(rng, domain, now, 4_000_000)
                 for _ in range(rng.random_int(1, 10))]
        new_oldest = now - 5_000_000 if rng.coinflip() else None
        got = tpu.resolve(batch, now, new_oldest)
        want = oracle.resolve(batch, now, new_oldest)
        assert got == want, f"divergence at now={now}"


def random_point_txn(rng, nkeys, now, window):
    """Point-only transaction over a hot keyspace: every range is
    [k, k+\\x00), so EncodedBatch marks the batch all_point and the device
    takes the scatter-min fast path (fused.py make_resolve_step)."""
    snap = now - rng.random_int(0, window)
    tr = CommitTransactionRef(read_snapshot=max(snap, 0))
    for _ in range(rng.random_int(0, 4)):
        k = b"k%03d" % rng.random_int(0, nkeys - 1)
        tr.read_conflict_ranges.append(KeyRange(k, k + b"\x00"))
    for _ in range(rng.random_int(0, 3)):
        k = b"k%03d" % rng.random_int(0, nkeys - 1)
        tr.write_conflict_ranges.append(KeyRange(k, k + b"\x00"))
    return tr


@pytest.mark.parametrize("seed", [21, 22, 23])
def test_tpu_point_path_matches_oracle(seed, small_caps):
    from foundationdb_tpu.conflict.encoded import EncodedBatch
    rng = DeterministicRandom(seed)
    oracle = OracleConflictSet(0)
    tpu = TpuConflictSet(0, **small_caps)
    now = 0
    for _ in range(20):
        now += rng.random_int(1, 2_000_000)
        # Hot 12-key space + up to 24 txns forces deep intra-batch chains
        # (writer aborts retracting downstream conflicts) through the
        # point fast path.
        batch = [random_point_txn(rng, 12, now, 4_000_000)
                 for _ in range(rng.random_int(1, 24))]
        assert EncodedBatch.from_transactions(batch).all_point
        new_oldest = now - 5_000_000 if rng.coinflip() else None
        got = tpu.resolve(batch, now, new_oldest)
        want = oracle.resolve(batch, now, new_oldest)
        assert got == want, f"point-path divergence at now={now}"


def test_tpu_basic_sequence(small_caps):
    tpu = TpuConflictSet(0, **small_caps)
    w = CommitTransactionRef(write_conflict_ranges=[KeyRange(b"a", b"c")])
    assert tpu.resolve([w], 100) == [CommitResult.COMMITTED]
    r_old = CommitTransactionRef(read_snapshot=50,
                                 read_conflict_ranges=[KeyRange(b"b", b"d")])
    r_new = CommitTransactionRef(read_snapshot=100,
                                 read_conflict_ranges=[KeyRange(b"b", b"d")])
    r_miss = CommitTransactionRef(read_snapshot=50,
                                  read_conflict_ranges=[KeyRange(b"c", b"d")])
    assert tpu.resolve([r_old, r_new, r_miss], 200) == [
        CommitResult.CONFLICT, CommitResult.COMMITTED, CommitResult.COMMITTED]


def test_tpu_gc_and_rebase(small_caps):
    """Window floor advances; decisions stay correct after GC + rebase."""
    tpu = TpuConflictSet(0, capacity=1 << 12)
    oracle = OracleConflictSet(0)
    rng = DeterministicRandom(7)
    domain = make_domain()
    now = 0
    for i in range(25):
        now += 1_000_000
        batch = [random_txn(rng, domain, now, 3_000_000) for _ in range(6)]
        new_oldest = now - 5_000_000
        got = tpu.resolve(batch, now, new_oldest)
        want = oracle.resolve(batch, now, new_oldest)
        assert got == want
    assert tpu.version_base > 0  # rebase actually happened
    assert tpu.segment_count() < 1 << 12


def test_long_keys_conservative(small_caps):
    """Keys past the digest prefix on the BARE device backend: no missed
    conflicts; extra conflicts allowed.  This is the raw-kernel contract
    only — the production path (SupervisedConflictSet, the default for
    backend "tpu") upgrades it to BIT-IDENTICAL decisions via the host
    exact recheck; see tests/test_conflict_supervisor.py."""
    from foundationdb_tpu.ops.digest import PREFIX_BYTES
    long_a = b"x" * (PREFIX_BYTES + 7)
    long_b = b"x" * PREFIX_BYTES + b"zzz"   # shared prefix, digest-collides
    tpu = TpuConflictSet(0, **small_caps)
    w = CommitTransactionRef(
        write_conflict_ranges=[KeyRange(long_a, long_a + b"\x00")])
    assert tpu.resolve([w], 100) == [CommitResult.COMMITTED]
    # True conflict on the same long key: MUST be caught.
    r_hit = CommitTransactionRef(
        read_snapshot=50,
        read_conflict_ranges=[KeyRange(long_a, long_a + b"\x00")])
    # Digest-collided read of a different key: conservative abort is allowed;
    # commit would also be correct only if digests distinguished them.
    r_collide = CommitTransactionRef(
        read_snapshot=50,
        read_conflict_ranges=[KeyRange(long_b, long_b + b"\x00")])
    got = tpu.resolve([r_hit, r_collide], 200)
    assert got[0] == CommitResult.CONFLICT        # no false negative
    assert got[1] in (CommitResult.CONFLICT, CommitResult.COMMITTED)

    # Short-key reads nearby must be unaffected by long-key widening.
    r_short = CommitTransactionRef(
        read_snapshot=50, read_conflict_ranges=[KeyRange(b"w", b"x")])
    assert tpu.resolve([r_short], 300) == [CommitResult.COMMITTED]


def test_tpu_intra_batch(small_caps):
    tpu = TpuConflictSet(0, **small_caps)
    t0 = CommitTransactionRef(read_snapshot=0,
                              write_conflict_ranges=[KeyRange(b"k", b"l")])
    t1 = CommitTransactionRef(read_snapshot=0,
                              read_conflict_ranges=[KeyRange(b"k", b"l")])
    assert tpu.resolve([t0, t1], 10) == [CommitResult.COMMITTED,
                                         CommitResult.CONFLICT]


def test_tpu_capacity_overflow_recovers():
    """Filling the window past capacity stays correct and bounded.

    gc_interval_batches is set huge so the scheduled merge cadence never
    fires; recovery must come from the delta-occupancy-bound merge scheduling
    plus the merge GC dropping sub-floor segments."""
    tpu = TpuConflictSet(0, capacity=256, gc_interval_batches=1_000_000)
    now = 0
    for i in range(40):
        now += 1_000_000
        # 10 disjoint point writes per batch -> ~20 boundaries/batch
        txns = [CommitTransactionRef(write_conflict_ranges=[
            KeyRange(b"%05d" % (i * 10 + j), b"%05d\x00" % (i * 10 + j))])
            for j in range(10)]
        res = tpu.resolve(txns, now, now - 3_000_000)
        assert all(r == CommitResult.COMMITTED for r in res)
    # Observe a merged state: GC must have kept the window far below the
    # 40-batch * 20-boundary total.
    tpu.merge()
    probe = [CommitTransactionRef(write_conflict_ranges=[
        KeyRange(b"zz", b"zz\x00")])]
    tpu.resolve(probe, now + 1, now - 3_000_000)
    assert tpu.segment_count() <= 256


def test_tpu_overflow_flag_raises():
    """With the window floor pinned at 0, merge GC cannot drop anything, so
    overflowing the capacity must surface the sticky in-kernel flag as an
    error at wait() — never silent mis-verdicts."""
    import pytest
    tpu = TpuConflictSet(0, capacity=256, delta_capacity=256)
    now = 0
    with pytest.raises(Exception, match="capacity exceeded"):
        for i in range(40):
            now += 1_000
            txns = [CommitTransactionRef(write_conflict_ranges=[
                KeyRange(b"%05d" % (i * 10 + j), b"%05d\x00" % (i * 10 + j))])
                for j in range(10)]
            tpu.resolve(txns, now)  # floor never advances


def test_clear_matches_oracle(small_caps):
    """clear(v) sets V(k)=v everywhere but leaves the window floor alone."""
    tpu = TpuConflictSet(0, **small_caps)
    oracle = OracleConflictSet(0)
    for cs in (tpu, oracle):
        cs.resolve([CommitTransactionRef(
            write_conflict_ranges=[KeyRange(b"a", b"b")])], 100)
        cs.clear(400)
    r = CommitTransactionRef(read_snapshot=395,
                             read_conflict_ranges=[KeyRange(b"q", b"r")])
    got, want = tpu.resolve([r], 500), oracle.resolve([r], 500)
    assert got == want == [CommitResult.CONFLICT]


def test_rank_count_duality():
    """rank_count's side-flipping duality vs numpy searchsorted on random
    TIED arrays (the docstring contract, ops/digest.py)."""
    import numpy as np
    from foundationdb_tpu.ops.digest import rank_count
    rng = np.random.default_rng(7)
    for _ in range(50):
        big = np.sort(rng.integers(0, 8, size=rng.integers(1, 40)))
        small = np.sort(rng.integers(0, 8, size=rng.integers(0, 20)))
        left_pos = np.searchsorted(big, small, "left").astype(np.int32)
        right_pos = np.searchsorted(big, small, "right").astype(np.int32)
        got_right = np.asarray(rank_count(left_pos, len(big)))
        got_left = np.asarray(rank_count(right_pos, len(big)))
        want_right = np.searchsorted(small, big, "right")
        want_left = np.searchsorted(small, big, "left")
        assert (got_right == want_right).all(), (big, small)
        assert (got_left == want_left).all(), (big, small)
