"""Read-path fast paths (ISSUE 15): prefix-compressed B-tree leaf pages
(on-disk compat both knob postures), vectorized range scans (btree +
VersionedMap, bit-identical to the plain paths), and the incremental
shard-metrics cache (exact totals, split/merge boundary eviction)."""

import pytest

from foundationdb_tpu.core import (DeterministicRandom, EventLoop,
                                   set_deterministic_random, set_event_loop)
from foundationdb_tpu.core.knobs import server_knobs
from foundationdb_tpu.server.kvstore import open_kv_store
from foundationdb_tpu.server.sim_fs import SimFileSystem
from foundationdb_tpu.server.storage import VersionedMap, _ShardMetricsCache

_loop = None


def drive(coro):
    return _loop.run_until(_loop.spawn(coro), timeout=120)


def fresh_loop():
    global _loop
    _loop = EventLoop(sim=True)
    set_event_loop(_loop)


@pytest.fixture()
def knobs():
    k = server_knobs()
    saved = (k.BTREE_PREFIX_COMPRESSION, k.STORAGE_VECTORIZED_SCAN,
             k.STORAGE_INCREMENTAL_SHARD_METRICS)
    yield k
    (k.BTREE_PREFIX_COMPRESSION, k.STORAGE_VECTORIZED_SCAN,
     k.STORAGE_INCREMENTAL_SHARD_METRICS) = saved
    set_event_loop(None)


def _key(i: int) -> bytes:
    return b"tenant/0001/table/users/row/%08d" % i


# ---------------------------------------------------------------------------
# B-tree prefix compression
# ---------------------------------------------------------------------------

def _build_btree(compress: bool, n=3000, value=b"v" * 20):
    server_knobs().BTREE_PREFIX_COMPRESSION = compress
    fs = SimFileSystem()
    eng = open_kv_store("btree", fs, "bt")
    drive(eng.recover())
    for base in range(0, n, 400):
        for i in range(base, min(base + 400, n)):
            eng.set(_key(i), value)
        drive(eng.commit())
    return fs, eng


def test_btree_compression_packs_more_per_page(knobs):
    fresh_loop()
    set_deterministic_random(DeterministicRandom(3))
    _fs, plain = _build_btree(False)
    _fs, comp = _build_btree(True)
    live_plain = plain.page_count - len(plain.free)
    live_comp = comp.page_count - len(comp.free)
    # 28-byte shared prefixes on 32-byte keys: compression must shrink
    # the live page set materially, not marginally.
    assert live_comp < live_plain * 0.75, (live_plain, live_comp)
    knobs.BTREE_PREFIX_COMPRESSION = False
    assert plain.read_range(b"", b"\xff") == comp.read_range(b"", b"\xff")


def test_btree_on_disk_compat_both_directions(knobs):
    """A compressed store read by a knobs-OFF engine and a plain store
    read by a knobs-ON engine both decode fully (pages self-describe
    via their kind byte), including across power-fail recovery."""
    fresh_loop()
    set_deterministic_random(DeterministicRandom(4))
    for write_compressed in (False, True):
        fs, eng = _build_btree(write_compressed, n=800)
        expect = eng.read_range(b"", b"\xff")
        assert len(expect) == 800
        fs.power_fail_all()
        # Opposite posture at recovery.
        knobs.BTREE_PREFIX_COMPRESSION = not write_compressed
        eng2 = open_kv_store("btree", fs, "bt")
        drive(eng2.recover())
        assert eng2.read_range(b"", b"\xff") == expect
        # Mixed file: keep writing under the new posture — old and new
        # pages coexist.
        for i in range(800, 1000):
            eng2.set(_key(i), b"nv")
        drive(eng2.commit())
        rows = eng2.read_range(b"", b"\xff")
        assert len(rows) == 1000
        assert rows[:800] == expect


def test_btree_knobs_off_pages_bit_identical(knobs):
    """The knobs-off page image must not move: same ops, byte-identical
    files (the on-disk goldens equivalent of the wire guard)."""
    fresh_loop()
    set_deterministic_random(DeterministicRandom(5))
    knobs.BTREE_PREFIX_COMPRESSION = False
    images = []
    for _ in range(2):
        fs, eng = _build_btree(False, n=500)
        f = fs.open("bt.btree")
        images.append(drive(f.read(0, f.size())))
    assert images[0] == images[1]


def test_btree_vectorized_scan_parity_with_overflow(knobs):
    """Vectorized scans must match the recursive path including
    overflow-chained big values and mid-leaf limits."""
    fresh_loop()
    set_deterministic_random(DeterministicRandom(6))
    for compress in (False, True):
        knobs.BTREE_PREFIX_COMPRESSION = compress
        fs = SimFileSystem()
        eng = open_kv_store("btree", fs, "bt")
        drive(eng.recover())
        rng = DeterministicRandom(61)
        for i in range(600):
            size = 3000 if rng.random01() < 0.05 else 20   # some overflow
            eng.set(_key(i), bytes([i % 256]) * size)
        drive(eng.commit())
        knobs.STORAGE_VECTORIZED_SCAN = False
        for lo, hi, limit in ((0, 600, 1 << 30), (37, 411, 55),
                              (100, 101, 1), (599, 600, 10)):
            plain = eng.read_range(_key(lo), _key(hi), limit)
            knobs.STORAGE_VECTORIZED_SCAN = True
            vec = eng.read_range(_key(lo), _key(hi), limit)
            knobs.STORAGE_VECTORIZED_SCAN = False
            assert plain == vec, (compress, lo, hi, limit)


def test_btree_compressed_single_key_and_empty_suffix(knobs):
    """Edge pages: a one-key leaf (prefix == whole key, empty suffix)
    and keys where one IS the shared prefix of the others."""
    fresh_loop()
    set_deterministic_random(DeterministicRandom(7))
    knobs.BTREE_PREFIX_COMPRESSION = True
    fs = SimFileSystem()
    eng = open_kv_store("btree", fs, "bt")
    drive(eng.recover())
    eng.set(b"solo", b"v1")
    drive(eng.commit())
    assert eng.read_value(b"solo") == b"v1"
    eng.set(b"solo/child", b"v2")
    eng.set(b"solo/child2", b"v3")
    drive(eng.commit())
    fs.power_fail_all()
    knobs.BTREE_PREFIX_COMPRESSION = False
    eng2 = open_kv_store("btree", fs, "bt")
    drive(eng2.recover())
    assert eng2.read_range(b"", b"\xff") == [
        (b"solo", b"v1"), (b"solo/child", b"v2"), (b"solo/child2", b"v3")]


# ---------------------------------------------------------------------------
# VersionedMap vectorized scan
# ---------------------------------------------------------------------------

def test_versioned_map_vectorized_parity(knobs):
    fresh_loop()
    vm = VersionedMap()
    rng = DeterministicRandom(42)
    for v in range(1, 300):
        for _ in range(3):
            i = rng.random_int(0, 200)
            vm.set(_key(i), None if rng.random01() < 0.2
                   else b"u%05d" % v, v)
    for _ in range(200):
        a = rng.random_int(0, 210)
        b = rng.random_int(0, 210)
        a, b = min(a, b), max(a, b)
        args = (_key(a), _key(b), rng.random_int(1, 310),
                rng.random_int(1, 30), rng.random_int(1, 2000),
                rng.random01() < 0.4)
        knobs.STORAGE_VECTORIZED_SCAN = False
        plain = vm.range_read(*args)
        knobs.STORAGE_VECTORIZED_SCAN = True
        assert vm.range_read(*args) == plain


# ---------------------------------------------------------------------------
# Incremental shard-metrics cache
# ---------------------------------------------------------------------------

def test_shard_cache_totals_exact_under_mutation():
    vm = VersionedMap()
    cache = _ShardMetricsCache()
    vm._metrics_cache = cache
    rng = DeterministicRandom(9)
    bounds = [_key(i) for i in (0, 50, 200, 400)]
    shards = list(zip(bounds, bounds[1:]))
    ver = 0
    hits = 0
    for _round in range(25):
        for _ in range(40):
            ver += 1
            i = rng.random_int(0, 399)
            vm.set(_key(i), None if rng.random01() < 0.15
                   else b"x" * rng.random_int(1, 50), ver)
        for b, e in shards:
            hit = cache.get(b, e)
            fresh = vm.range_bytes(b, e, ver)
            if hit is not None:
                assert hit == fresh
                hits += 1
            cache.put(b, e, *fresh)
    assert hits >= 40


def test_shard_cache_split_and_merge_boundaries():
    """A split's new sub-ranges miss (end mismatch) and re-scan; a
    merge's put() evicts the absorbed boundary so it cannot keep
    soaking up deltas that belong to the merged shard."""
    vm = VersionedMap()
    cache = _ShardMetricsCache()
    vm._metrics_cache = cache
    for i in range(100):
        vm.set(_key(i), b"v" * 10, i + 1)
    whole = vm.range_bytes(_key(0), _key(100), 1000)
    cache.put(_key(0), _key(100), *whole)
    # Split: polls now come as (0,50) and (50,100) — both must miss.
    assert cache.get(_key(0), _key(50)) is None
    left = vm.range_bytes(_key(0), _key(50), 1000)
    right = vm.range_bytes(_key(50), _key(100), 1000)
    cache.put(_key(0), _key(50), *left)
    cache.put(_key(50), _key(100), *right)
    assert cache.get(_key(0), _key(50)) == left
    # Mutate inside the right half; the right entry tracks it exactly.
    vm.set(_key(77), b"w" * 30, 2000)
    assert cache.get(_key(50), _key(100)) == \
        vm.range_bytes(_key(50), _key(100), 2000)
    # Merge back: put(0,100) must evict the stale (50,100) boundary...
    whole2 = vm.range_bytes(_key(0), _key(100), 2000)
    cache.put(_key(0), _key(100), *whole2)
    # ...so a delta at key 60 lands on the merged entry, not the ghost.
    vm.set(_key(60), b"z" * 44, 3000)
    assert cache.get(_key(0), _key(100)) == \
        vm.range_bytes(_key(0), _key(100), 3000)


def test_shard_cache_rollback_invalidates():
    vm = VersionedMap()
    cache = _ShardMetricsCache()
    vm._metrics_cache = cache
    for i in range(20):
        vm.set(_key(i), b"v", i + 1)
    cache.put(_key(0), _key(20), *vm.range_bytes(_key(0), _key(20), 100))
    vm.rollback(10)
    assert cache.get(_key(0), _key(20)) is None   # wholesale invalidation
    fresh = vm.range_bytes(_key(0), _key(20), 100)
    cache.put(_key(0), _key(20), *fresh)
    assert cache.get(_key(0), _key(20)) == fresh


def test_shard_cache_refresh_expiry():
    cache = _ShardMetricsCache()
    cache.put(b"a", b"b", 100, 5)
    for _ in range(cache.REFRESH_POLLS - 1):
        assert cache.get(b"a", b"b") == (100, 5)
    assert cache.get(b"a", b"b") is None   # expired: forces a re-scan


def test_btree_knob_flip_off_never_wedges_dense_leaves(knobs):
    """A leaf packed under the COMPRESSED size estimate (long shared
    prefix, tiny suffixes) must stay writable after the knob flips OFF:
    its plain encoding can exceed a page, so encode() keeps such pages
    compressed (the knob-flip safety valve) instead of failing every
    commit that touches them."""
    fresh_loop()
    set_deterministic_random(DeterministicRandom(8))
    knobs.BTREE_PREFIX_COMPRESSION = True
    fs = SimFileSystem()
    eng = open_kv_store("btree", fs, "bt")
    drive(eng.recover())
    prefix = b"tenant/" + b"x" * 150 + b"/row/"   # 162-byte shared prefix
    n = 400
    for i in range(n):
        eng.set(prefix + b"%04d" % i, b"v")
    drive(eng.commit())
    expect = eng.read_range(b"", b"\xff")
    assert len(expect) == n
    # Flip OFF and rewrite/clear inside the dense leaves: every commit
    # must succeed and results stay exact.
    knobs.BTREE_PREFIX_COMPRESSION = False
    for i in range(0, n, 7):
        eng.set(prefix + b"%04d" % i, b"w")
    eng.clear(prefix + b"0100", prefix + b"0110")
    drive(eng.commit())
    rows = eng.read_range(b"", b"\xff")
    model = dict(expect)
    for i in range(0, n, 7):
        model[prefix + b"%04d" % i] = b"w"
    for i in range(100, 110):
        model.pop(prefix + b"%04d" % i, None)
    assert rows == sorted(model.items())
    # And the store still recovers cleanly.
    fs.power_fail_all()
    eng2 = open_kv_store("btree", fs, "bt")
    drive(eng2.recover())
    assert eng2.read_range(b"", b"\xff") == rows


# ---------------------------------------------------------------------------
# Client get_range byte budget (limit_bytes)
# ---------------------------------------------------------------------------

def test_get_range_limit_bytes_budget():
    """limit_bytes bounds the TOTAL result bytes across shard chunks
    (crossing row included), composes with RYW overlay rows, works in
    reverse, and 0 keeps the pre-ISSUE-15 unbounded behavior."""
    from foundationdb_tpu.server.cluster import SimCluster
    from foundationdb_tpu.rpc.sim import set_simulator
    cl = SimCluster(n_storage=2)
    try:
        db = cl.database()

        async def go():
            t = db.create_transaction()
            for i in range(60):
                t.set(b"lb/%04d" % i, b"v" * 50)
            await t.commit()
            t = db.create_transaction()
            full = await t.get_range(b"lb/", b"lb0", limit=1000)
            assert len(full) == 60
            # ~57 bytes/row: a 300-byte budget stops after ~6 rows,
            # prefix-exact.
            capped = await t.get_range(b"lb/", b"lb0", limit=1000,
                                       limit_bytes=300)
            assert 0 < len(capped) < 20
            assert capped == full[:len(capped)]
            nbytes = sum(len(k) + len(v) for k, v in capped)
            prev = nbytes - (len(capped[-1][0]) + len(capped[-1][1]))
            assert nbytes >= 300 > prev   # crossing row included
            rcapped = await t.get_range(b"lb/", b"lb0", limit=1000,
                                        reverse=True, limit_bytes=300)
            assert 0 < len(rcapped) < 20
            assert rcapped == full[::-1][:len(rcapped)]
            # RYW rows ride the budget accounting too.
            t.set(b"lb/0001", b"w" * 50)
            capped2 = await t.get_range(b"lb/", b"lb0", limit=1000,
                                        limit_bytes=300)
            assert capped2[1] == (b"lb/0001", b"w" * 50)
            return True

        assert cl.run_until(cl.loop.spawn(go()), timeout=60)
    finally:
        set_simulator(None)
        set_event_loop(None)
