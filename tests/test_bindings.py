"""Binding surface tests: tuple layer + frozen fdb API + stack tester.

Reference: bindings/python/fdb (API shapes), design/tuple.md (encoding),
bindings/bindingtester (the stack-machine cross-check: the same op
stream must behave identically through the frozen binding and through
direct internal-client calls)."""

import numpy as np
import pytest

from foundationdb_tpu.bindings import tuple as fdb_tuple
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration

from test_recovery import teardown  # noqa: F401


# ---------------------------------------------------------------------------
# Tuple layer
# ---------------------------------------------------------------------------

CASES = [
    (),
    (None,),
    (b"", b"\x00", b"a\x00b", b"\xff" * 3),
    ("", "hello", "unié中"),
    (0, 1, -1, 255, 256, -255, -256, 2**40, -(2**40), 2**63 - 1),
    (1.5, -1.5, 0.0, 3.141592653589793, float("inf"), float("-inf")),
    (True, False),
    ((1, (b"nest", None)), "outer"),
    (b"k", 7, "s", (None, b"\x00\xff")),
]


def test_tuple_roundtrip():
    for t in CASES:
        assert fdb_tuple.unpack(fdb_tuple.pack(t)) == t, t


def test_tuple_order_matches_value_order():
    # Packing preserves order within each type family (the layer's core
    # guarantee: sorted keys == sorted tuples).
    ints = sorted([0, 1, -1, 7, -300, 1000, 2**30, -(2**30), 255, 256])
    packed = [fdb_tuple.pack((v,)) for v in ints]
    assert packed == sorted(packed)
    strs = sorted(["", "a", "ab", "b", "a\x00c"])
    packed = [fdb_tuple.pack((s,)) for s in strs]
    assert packed == sorted(packed)
    floats = sorted([-1e9, -1.0, -0.5, 0.0, 0.5, 1.0, 1e9])
    packed = [fdb_tuple.pack((f,)) for f in floats]
    assert packed == sorted(packed)


def test_tuple_range():
    b, e = fdb_tuple.range_of((b"dir",))
    inside = fdb_tuple.pack((b"dir", 1))
    assert b <= inside < e
    assert not b <= fdb_tuple.pack((b"dis",)) < e


# ---------------------------------------------------------------------------
# Frozen API + stack tester
# ---------------------------------------------------------------------------

def make_cluster():
    return SimFdbCluster(config=DatabaseConfiguration(), n_workers=4,
                         n_storage_workers=2)


def test_frozen_api_basics(teardown):  # noqa: F811
    import foundationdb_tpu.bindings.fdb_api as fdb
    fdb._API_VERSION = None
    fdb.api_version(710)
    c = make_cluster()
    db = fdb.open(c.database())

    async def go():
        tr = db.create_transaction()
        tr.set(b"bind/a", b"1")
        tr.add(b"bind/ctr", (5).to_bytes(8, "little"))
        await tr.commit()
        assert tr.get_committed_version() > 0

        tr2 = db.create_transaction()
        assert await tr2.get(b"bind/a") == b"1"
        assert (await tr2.get(b"bind/ctr"))[:1] == b"\x05"
        rows = await tr2.get_range(b"bind/", b"bind0")
        assert [k for k, _v in rows] == [b"bind/a", b"bind/ctr"]
        k = await tr2.get_key(
            fdb.KeySelector.first_greater_or_equal(b"bind/"))
        assert k == b"bind/a"
        k = await tr2.get_key(
            fdb.KeySelector.last_less_or_equal(b"bind/zzz"))
        assert k == b"bind/ctr"
        # cancel() forbids commit until reset.
        tr3 = db.create_transaction()
        tr3.set(b"bind/x", b"y")
        tr3.cancel()
        try:
            await tr3.commit()
            raise AssertionError("commit after cancel must fail")
        except fdb.FDBError as e:
            assert e.code == 1025
        assert await db.get(b"bind/x") is None
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=120)


def test_stack_tester_frozen_vs_direct(teardown):  # noqa: F811
    """The bindingtester cross-check: one op stream, two executors, same
    stack and same final database state."""
    import foundationdb_tpu.bindings.fdb_api as fdb
    from foundationdb_tpu.bindings.stack_tester import (
        DirectClientExecutor, FrozenApiExecutor, StackMachine,
        generate_ops)
    fdb._API_VERSION = None
    fdb.api_version(710)
    c = make_cluster()
    raw_db = c.database()
    fdb_db = fdb.open(raw_db)

    rng = np.random.default_rng(20260731)
    ops = generate_ops(rng, 120)
    ops.append(("COMMIT",))

    async def run_one(executor):
        sm = StackMachine(executor)
        stack = await sm.run(ops)
        tr = raw_db.create_transaction()
        snapshot = await tr.get_range(b"bt/", b"bt0", limit=100000)
        # Wipe for the next executor.
        tr.clear(b"bt/", b"bt0")
        await tr.commit()
        return stack, snapshot

    async def go():
        s1, snap1 = await run_one(FrozenApiExecutor(fdb_db))
        s2, snap2 = await run_one(DirectClientExecutor(raw_db))
        assert s1 == s2, (s1, s2)
        assert snap1 == snap2
        assert snap1 or any(op[0] == "SET" for op in ops) is False
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=300)
