"""TSS pairs (reference tss_count + fdbrpc/TSSComparison.h): shadow
storage servers fed by mirror tags; sampled client reads duplicate to
the shadow out-of-band and divergence is traced, never user-visible."""

import pytest

from foundationdb_tpu.core.scheduler import delay
from foundationdb_tpu.core.trace import get_tracer
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import (DatabaseConfiguration,
                                                TSS_TAG_OFFSET)

from test_recovery import commit_kv, read_key, teardown  # noqa: F401


def _shadow_role(c, primary_tag):
    for _p, w, _cc, _lv in c.workers:
        for ss in w.storage_roles:
            if ss.tag == TSS_TAG_OFFSET + primary_tag:
                return ss
    return None


def test_tss_shadow_tracks_and_detects_divergence(teardown):  # noqa: F811
    c = SimFdbCluster(config=DatabaseConfiguration(tss_count=1),
                      n_workers=5, n_storage_workers=3)
    db = c.database()

    async def go():
        for i in range(12):
            await commit_kv(db, b"t/%03d" % i, b"tv%03d" % i)
        shadow = _shadow_role(c, 0)
        assert shadow is not None
        # The mirror tag feeds the shadow the same stream.
        for _ in range(100):
            if shadow.version.get() > 0 and \
                    await read_key(db, b"t/000") == b"tv000":
                break
            await delay(0.2)
        # Clean reads: comparisons fire, no mismatch.
        before = db.tss_mismatches
        for i in range(12):
            assert await read_key(db, b"t/%03d" % i) == b"tv%03d" % i
        await delay(1.0)     # let out-of-band comparisons complete
        assert db.tss_mismatches == before

        # Sabotage the shadow directly: the NEXT compared read of that
        # key must trace a mismatch without affecting the client result.
        key = None
        for i in range(12):
            k = b"t/%03d" % i
            st = shadow.shards.lookup(k)
            if shadow.data.get(k, shadow.version.get()) is not None:
                key = k
                break
        assert key is not None, "no key landed on the paired shard"
        shadow.data.set(key, b"CORRUPT", shadow.version.get())
        good = await read_key(db, key)
        assert good != b"CORRUPT"          # client result untouched
        for _ in range(100):
            if db.tss_mismatches > before:
                break
            await read_key(db, key)
            await delay(0.1)
        assert db.tss_mismatches > before
        assert get_tracer().find("TSSMismatch")
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=300)


def test_tss_mismatch_quarantines_shadow(teardown):  # noqa: F811
    """VERDICT missing #10 follow-through: a detected mismatch must BENCH
    the shadow (reference storageserver.actor.cpp tssQuarantine), not just
    log — it stops serving reads, the quarantine is recorded in the system
    keyspace, and the client sends no further comparison traffic."""
    from foundationdb_tpu.core.error import FdbError
    from foundationdb_tpu.server.system_data import tss_quarantine_key

    c = SimFdbCluster(config=DatabaseConfiguration(tss_count=1),
                      n_workers=5, n_storage_workers=3)
    db = c.database()

    async def go():
        for i in range(12):
            await commit_kv(db, b"q/%03d" % i, b"qv%03d" % i)
        shadow = _shadow_role(c, 0)
        assert shadow is not None
        for _ in range(100):
            if shadow.version.get() > 0 and \
                    await read_key(db, b"q/000") == b"qv000":
                break
            await delay(0.2)
        key = None
        for i in range(12):
            k = b"q/%03d" % i
            if shadow.data.get(k, shadow.version.get()) is not None:
                key = k
                break
        assert key is not None, "no key landed on the paired shard"
        shadow.data.set(key, b"CORRUPT", shadow.version.get())
        before = db.tss_mismatches
        for _ in range(100):
            if db.tss_mismatches > before:
                break
            await read_key(db, key)
            await delay(0.1)
        assert db.tss_mismatches > before

        # 1. The shadow is benched: flag set, reads answered with errors.
        for _ in range(100):
            if shadow.quarantined:
                break
            await delay(0.1)
        assert shadow.quarantined
        assert get_tracer().find("TSSQuarantineApplied")

        # 2. The marker landed in the system keyspace.
        marker = None
        for _ in range(100):
            t = db.create_transaction()
            t.access_system_keys = True
            try:
                marker = await t.get(tss_quarantine_key(shadow.tag))
            except FdbError as e:
                await t.on_error(e)
                continue
            if marker is not None:
                break
            await delay(0.1)
        assert marker is not None

        # 3. No further comparisons fire (the shadow stays corrupt, the
        # client skips benched pairs, and the quarantined role would
        # error any compare read anyway).
        count = db.tss_mismatches
        for _ in range(10):
            assert await read_key(db, key) == b"qv" + key[-3:]
        await delay(2.0)
        assert db.tss_mismatches == count
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=300)
