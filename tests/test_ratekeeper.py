"""Ratekeeper parity: TLog queue tracking, durability-lag limiting,
per-tag auto-throttling (VERDICT r4 item 5).

Reference: fdbserver/Ratekeeper.actor.cpp:663 (TLog queue tracking),
:991 (updateRate limit reasons), fdbclient/TagThrottle.actor.cpp
(per-tag throttles surfaced through GRV replies).
"""

import pytest

from foundationdb_tpu.core.knobs import server_knobs
from foundationdb_tpu.core.scheduler import delay
from foundationdb_tpu.rpc.endpoint import RequestStream
from foundationdb_tpu.server.grv_proxy import GrvProxy
from foundationdb_tpu.server.interfaces import (GetRawCommittedVersionReply,
                                                GetReadVersionRequest,
                                                MasterInterface,
                                                TLogInterface,
                                                TransactionPriority)
from foundationdb_tpu.server.ratekeeper import (GetRateInfoRequest,
                                                Ratekeeper, Smoother,
                                                StorageQueuingMetricsReply,
                                                TLogQueuingMetricsReply)

from test_recovery import teardown  # noqa: F401


def _world():
    from foundationdb_tpu.core import EventLoop, set_event_loop
    from foundationdb_tpu.rpc.network import SimNetwork, set_network
    from foundationdb_tpu.rpc.sim import Simulator, set_simulator
    lp = EventLoop(sim=True)
    set_event_loop(lp)
    sim = Simulator()
    set_simulator(sim)
    set_network(sim.network)
    return lp, sim


def test_smoother_converges_and_damps():
    s = Smoother(half_life=1.0)
    # 100/s fed for 10 half-lives converges to ~100.
    for i in range(101):
        s.set_total(i * 0.1, i * 10.0)
    assert 85.0 < s.rate() < 101.0, s.rate()
    # One wild sample (a 10000/s instantaneous burst over 10ms) moves the
    # estimate only in proportion to its duration, not its magnitude.
    s.set_total(10.11, 1010.0 + 100.0)
    assert s.rate() < 1000.0, s.rate()


def test_tlog_queue_limits_rate(teardown):  # noqa: F811
    """A TLog whose RESIDENT bytes cross TLOG_LIMIT_BYTES lowers the
    cluster rate.  The spill threshold sits BELOW the limit (reference
    TARGET_BYTES_PER_TLOG 2.4GB vs spill 1.5GB): a lagging peeker's
    backlog spills to disk without throttling; only memory growth that
    spilling can't evict (fsync-bound overload) springs the rate."""
    from foundationdb_tpu.core import EventLoop, set_event_loop
    lp = EventLoop(sim=True)
    set_event_loop(lp)
    knobs = server_knobs()
    assert knobs.TLOG_SPILL_THRESHOLD < knobs.TLOG_LIMIT_BYTES

    rk = Ratekeeper("rk-test", {})
    rk._released._estimate = 1000.0
    # A spilled steady state (resident capped at the spill threshold)
    # does NOT throttle: spill is the relief valve, not a rate signal.
    rk.worst_tlog_queue_bytes = int(knobs.TLOG_SPILL_THRESHOLD)
    rk._update_rate()
    assert rk.tps_limit == float("inf")
    # Memory past the limit (spill can't evict) throttles.
    rk.worst_tlog_queue_bytes = int(knobs.TLOG_LIMIT_BYTES)
    rk._update_rate()
    assert rk.tps_limit < 100.0
    assert rk.limit_reason == "log_server_write_queue"


def test_durability_lag_limits_rate(teardown):  # noqa: F811
    from foundationdb_tpu.core import EventLoop, set_event_loop
    lp = EventLoop(sim=True)
    set_event_loop(lp)
    knobs = server_knobs()
    rk = Ratekeeper("rk-test", {})
    rk._released._estimate = 1000.0
    rk.worst_durability_lag = int(knobs.STORAGE_DURABILITY_LAG_SOFT_MAX)
    rk._update_rate()
    assert rk.tps_limit < 100.0
    assert rk.limit_reason == "storage_server_durability_lag"


class _StubSS:
    """Storage interface stub reporting a configurable busy tag."""

    def __init__(self, p, reply: StorageQueuingMetricsReply) -> None:
        self.queuing_metrics = RequestStream("stub.ss.queuingMetrics")
        p.register(self.queuing_metrics)
        self._reply = reply

        async def serve() -> None:
            async for req in self.queuing_metrics.queue:
                req.reply.send(self._reply)
        p.spawn(serve(), "stub.ss")


class _StubTLog:
    def __init__(self, p, reply: TLogQueuingMetricsReply) -> None:
        self.queuing_metrics = RequestStream("stub.tlog.queuingMetrics")
        p.register(self.queuing_metrics)
        self._reply = reply

        async def serve() -> None:
            async for req in self.queuing_metrics.queue:
                req.reply.send(self._reply)
        p.spawn(serve(), "stub.tlog")


def test_rk_polls_tlogs_and_storage(teardown):  # noqa: F811
    lp, sim = _world()
    p = sim.new_process(name="rkhost")
    knobs = server_knobs()
    ss = _StubSS(p, StorageQueuingMetricsReply(
        queue_bytes=0, durability_lag=0))
    tl = _StubTLog(p, TLogQueuingMetricsReply(
        queue_bytes=int(knobs.TLOG_LIMIT_BYTES), durable_lag=0))
    rk = Ratekeeper("rk-test", {0: ss}, [tl], poll_interval=0.1)
    rk._released._estimate = 1000.0
    rk.run(p)

    async def go():
        await delay(0.5)
        return True

    assert lp.run_until(lp.spawn(go()), timeout=30)
    assert rk.worst_tlog_queue_bytes == int(knobs.TLOG_LIMIT_BYTES)
    assert rk.limit_reason == "log_server_write_queue"
    assert rk.tps_limit < float("inf")


def test_hot_tag_throttled_others_proceed(teardown):  # noqa: F811
    """A saturated storage server whose reads are dominated by one tag
    gets that tag throttled at the GRV proxy while untagged traffic
    proceeds at full speed (reference busy-read auto-throttling)."""
    lp, sim = _world()
    p = sim.new_process(name="host")
    knobs = server_knobs()
    sat = float(knobs.SS_READ_SATURATION_OPS)
    ss = _StubSS(p, StorageQueuingMetricsReply(
        queue_bytes=0, durability_lag=0,
        busiest_read_tag="hot", busiest_read_rate=sat * 0.9,
        total_read_rate=sat * 1.0))
    rk = Ratekeeper("rk-test", {0: ss}, poll_interval=0.05)
    rk.run(p)

    master = MasterInterface()
    for s in master.streams():
        p.register(s)

    async def serve_versions() -> None:
        async for req in master.get_live_committed_version.queue:
            req.reply.send(GetRawCommittedVersionReply(version=1000))
    p.spawn(serve_versions(), "master.stub")

    proxy = GrvProxy("grv-test", master, ratekeeper=rk.interface)
    proxy.run(p)
    grv_ep = proxy.interface.get_consistent_read_version.endpoint
    results = {"hot_done": 0, "plain_lat": []}

    async def hot_flood() -> None:
        # Tagged backlog: must drain only at the throttled tag tps.
        for _ in range(500):
            f = RequestStream.at(grv_ep).get_reply(GetReadVersionRequest(
                priority=TransactionPriority.DEFAULT, tags=("hot",)))
            f.on_ready(lambda _f: results.__setitem__(
                "hot_done", results["hot_done"] + 1))

    async def plain_traffic() -> None:
        from foundationdb_tpu.core.scheduler import now
        for _ in range(30):
            t0 = now()
            await RequestStream.at(grv_ep).get_reply(GetReadVersionRequest(
                priority=TransactionPriority.DEFAULT))
            results["plain_lat"].append(now() - t0)
            await delay(0.05)

    async def go():
        # Feed the RK a per-tag release rate so the throttle has a
        # baseline, and let a poll land the throttle on the proxy.
        await RequestStream.at(rk.interface.get_rate_info.endpoint) \
            .get_reply(GetRateInfoRequest(
                proxy_id="seed", total_released=0,
                tag_released={"hot": 0}))
        await delay(0.1)
        await RequestStream.at(rk.interface.get_rate_info.endpoint) \
            .get_reply(GetRateInfoRequest(
                proxy_id="seed", total_released=2000,
                tag_released={"hot": 2000}))
        await delay(0.3)        # several RK polls -> throttle exists
        assert "hot" in rk.tag_throttles, rk.tag_throttles
        lp.spawn(hot_flood())
        await delay(0.1)
        await plain_traffic()
        await delay(0.5)
        return True

    assert lp.run_until(lp.spawn(go()), timeout=60)
    # Untagged default traffic unaffected...
    assert len(results["plain_lat"]) == 30
    assert max(results["plain_lat"]) < 0.5, results["plain_lat"]
    # ...while the tagged backlog drained at only the throttled tps.
    assert results["hot_done"] < 500, "hot tag was never throttled"
    assert results["hot_done"] >= 1   # but not starved entirely


def test_tag_throttle_expires(teardown):  # noqa: F811
    """Once the storm passes, the throttle lapses after
    AUTO_TAG_THROTTLE_DURATION and the tag flows freely again."""
    lp, sim = _world()
    p = sim.new_process(name="host")
    knobs = server_knobs()
    sat = float(knobs.SS_READ_SATURATION_OPS)
    hot_reply = StorageQueuingMetricsReply(
        queue_bytes=0, durability_lag=0,
        busiest_read_tag="hot", busiest_read_rate=sat * 0.9,
        total_read_rate=sat)
    ss = _StubSS(p, hot_reply)
    rk = Ratekeeper("rk-test", {0: ss}, poll_interval=0.05)
    rk.run(p)

    async def go():
        await delay(0.3)
        assert "hot" in rk.tag_throttles
        # Storm over: the stub now reports an idle server.
        ss._reply = StorageQueuingMetricsReply(
            queue_bytes=0, durability_lag=0)
        await delay(float(knobs.AUTO_TAG_THROTTLE_DURATION) + 1.0)
        assert "hot" not in rk.tag_throttles
        return True

    assert lp.run_until(lp.spawn(go()), timeout=60)
