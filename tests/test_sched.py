"""Conflict-aware transaction scheduling (ISSUE 12): the sched/ package
units (predictor EMAs + doom, greedy/static reorder, repair
eligibility), the role wiring (resolver heat feed -> ratekeeper fold ->
GRV predictor deferral; commit-proxy reorder + repair batches), the
knobs-off abort-set parity guard (verdicts AND frozen reply wire bytes),
starvation-proofing, determinism, the three-surface status agreement,
and the SchedChaosTest double-run with the duplicate-commit audit."""

import json
import os

import pytest

from foundationdb_tpu.conflict.heat import ConflictHeatTracker
from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.core import FdbError
from foundationdb_tpu.core.knobs import server_knobs
from foundationdb_tpu.rpc.endpoint import RequestStream
from foundationdb_tpu.sched.predictor import ConflictPredictor
from foundationdb_tpu.sched.reorder import moved_count, reorder_batch
from foundationdb_tpu.sched.repair import repair_eligible
from foundationdb_tpu.server.cluster import SimCluster
from foundationdb_tpu.server.interfaces import (CommitTransactionRequest,
                                                GetReadVersionRequest,
                                                ResolverHeatRequest)
from foundationdb_tpu.txn.types import (CommitResult, CommitTransactionRef,
                                        KeyRange, Mutation, MutationType)

from test_recovery import make_cluster, teardown  # noqa: F401

SPECS = os.path.join(os.path.dirname(__file__), "specs")


@pytest.fixture()
def knobs():
    """Mutable server knobs restored after the test."""
    k = server_knobs()
    saved = dict(k.__dict__)
    yield k
    for name, value in saved.items():
        setattr(k, name, value)


def _txn(reads=(), writes=(), mutations=(), snap=0, tag=""):
    return CommitTransactionRef(
        read_conflict_ranges=[KeyRange(k, k + b"\x00") for k in reads],
        write_conflict_ranges=[KeyRange(k, k + b"\x00") for k in writes],
        mutations=list(mutations), read_snapshot=snap, tag=tag)


def run(cluster, coro, timeout=60):
    return cluster.run_until(cluster.loop.spawn(coro), timeout=timeout)


# ---------------------------------------------------------------------------
# Predictor: EMA fold, doom mapping, decay, bounds, determinism
# ---------------------------------------------------------------------------

def _rows(conflicts=8, load=1, tag="hot", tenant=7):
    return [(b"k1", b"k1\x00", conflicts, load,
             {tag: conflicts}, {tenant: conflicts})]


def test_predictor_doom_and_decay():
    p = ConflictPredictor(alpha=0.5, abort_p=0.3, min_conflicts=4)
    p.update(_rows())
    # conflicts/(conflicts+load) = 8/9 >> 0.3 and count >= 4: doomed.
    assert p.is_doomed(("hot",))
    assert p.is_doomed((), tenant_id=7)
    assert not p.is_doomed(("cold",))
    assert not p.is_doomed((), tenant_id=8)
    assert p.doomed_range_for(("hot",)) == (b"k1", b"k1\x00")
    # The range stops appearing in the feed: EMA decays, doom lifts.
    for _ in range(8):
        p.update([])
    assert not p.is_doomed(("hot",))
    assert (b"k1", b"k1\x00") not in p.ranges


def test_predictor_thresholds_gate_doom():
    # Below min_conflicts: never doomed no matter the ratio.
    p = ConflictPredictor(abort_p=0.3, min_conflicts=4)
    p.update(_rows(conflicts=2, load=0))
    assert not p.is_doomed(("hot",))
    # Below abort_p: heavy load dilutes the ratio.
    p2 = ConflictPredictor(abort_p=0.5, min_conflicts=1)
    p2.update(_rows(conflicts=5, load=50))
    assert not p2.is_doomed(("hot",))


def test_predictor_table_bound_and_determinism():
    rows = [(b"k%04d" % i, b"k%04d\x00" % i, i % 7 + 1, 1, {"t%d" % i: 1}, {})
            for i in range(200)]
    a = ConflictPredictor(table_max=64)
    b = ConflictPredictor(table_max=64)
    for p in (a, b):
        p.update(rows)
        p.update(rows[:50])
        p.update([])
    assert len(a.ranges) <= 64
    # Same feed -> bit-identical table and status (any PYTHONHASHSEED).
    assert a.ranges == b.ranges
    assert a.status() == b.status()


# ---------------------------------------------------------------------------
# Reorder: greedy topological order + static degradation
# ---------------------------------------------------------------------------

def test_reorder_saves_reader_and_chain():
    # writer(k) before reader(k): original order aborts the reader.
    txns = [_txn(writes=[b"k"]), _txn(reads=[b"k"], writes=[b"c"])]
    order = reorder_batch(txns)
    assert order == [1, 0] and moved_count(order) == 2
    o = OracleConflictSet(0)
    verdicts = o.resolve([txns[i] for i in order], 10, 0)
    assert verdicts == [CommitResult.COMMITTED] * 2
    # A dependency chain unwinds fully: zero intra-batch aborts.
    chain = [_txn(writes=[b"a"]), _txn(reads=[b"a"], writes=[b"b"]),
             _txn(reads=[b"b"], writes=[b"c"])]
    order = reorder_batch(chain)
    o2 = OracleConflictSet(0)
    assert o2.resolve([chain[i] for i in order], 10, 0) == \
        [CommitResult.COMMITTED] * 3


def test_reorder_cycle_deterministic_and_range_overlap():
    # Mutual RMW clique: no order saves both; tiebreak = original index.
    clique = [_txn(reads=[b"h"], writes=[b"h"]),
              _txn(reads=[b"h"], writes=[b"h"])]
    assert reorder_batch(clique) == [0, 1]
    # True range writes overlap point reads (the non-point path).
    wide = [CommitTransactionRef(
        write_conflict_ranges=[KeyRange(b"a", b"z")], read_snapshot=0),
        _txn(reads=[b"m"], writes=[b"zz"])]
    assert reorder_batch(wide) == [1, 0]


def test_reorder_static_path_matches_greedy_intent():
    import random
    rng = random.Random(5)
    txns = [_txn(reads=[b"k%03d" % rng.randrange(30),
                        b"k%03d" % rng.randrange(30)],
                 writes=[b"k%03d" % rng.randrange(30)])
            for _ in range(120)]

    def commits(order):
        o = OracleConflictSet(0)
        v = o.resolve([txns[i] for i in order], 10, 0)
        return sum(1 for x in v if x == CommitResult.COMMITTED)

    base = commits(list(range(len(txns))))
    greedy = commits(reorder_batch(txns))
    static = commits(reorder_batch(txns, exact_max=1))
    assert greedy > base and static > base
    # Both paths are pure functions of the batch: deterministic.
    assert reorder_batch(txns) == reorder_batch(txns)
    assert reorder_batch(txns, exact_max=1) == \
        reorder_batch(txns, exact_max=1)


# ---------------------------------------------------------------------------
# Repair eligibility
# ---------------------------------------------------------------------------

def test_repair_eligibility_gates():
    t = _txn(reads=[b"r"], writes=[b"w"], snap=5)
    culprit = [(b"r", b"r\x00")]
    assert repair_eligible(t, culprit, True, 0, 1)
    assert not repair_eligible(t, culprit, True, 1, 1)     # budget spent
    assert not repair_eligible(t, culprit, False, 0, 1)    # conservative
    assert not repair_eligible(t, [], True, 0, 1)          # no culprits
    # A culprit OUTSIDE the read set (write-write-ish attribution
    # breakage) is never repairable.
    assert not repair_eligible(t, [(b"x", b"x\x00")], True, 0, 1)
    # Clipped sub-range of a declared read IS contained.
    t2 = CommitTransactionRef(
        read_conflict_ranges=[KeyRange(b"a", b"z")], read_snapshot=5)
    assert repair_eligible(t2, [(b"m", b"n")], True, 0, 1)


# ---------------------------------------------------------------------------
# Heat tracker feed rows + ratekeeper fold
# ---------------------------------------------------------------------------

def test_heat_feed_rows_carry_identity_and_decay():
    t = ConflictHeatTracker(sample_every=1)
    for _ in range(4):
        t.record_conflict(b"hot", b"hot\x00", tenant_id=3, tag="t/web")
    t.sample_load(b"hot", b"hot\x00")
    rows = t.feed_rows(4)
    assert rows == [(b"hot", b"hot\x00", 4, 1, {"t/web": 4}, {3: 4})]
    t.decay()
    assert t.feed_rows(4)[0][4] == {"t/web": 2}
    t.decay(), t.decay()
    assert t.feed_rows(4) == []
    assert not t.range_tags and not t.range_tenants


def test_ratekeeper_fold_merges_rows():
    from foundationdb_tpu.server.ratekeeper import Ratekeeper
    folded = Ratekeeper._fold_conflict_heat(
        [[(b"a", b"b", 3, 1, {"x": 3}, {})],
         [(b"a", b"b", 2, 1, {"x": 1, "y": 1}, {5: 2}),
          (b"c", b"d", 9, 0, {}, {})]], top_k=8)
    assert folded[0] == (b"c", b"d", 9, 0, {}, {})
    assert folded[1] == (b"a", b"b", 5, 2, {"x": 4, "y": 1}, {5: 2})


# ---------------------------------------------------------------------------
# GRV admission deferral: doom -> bounded deferrals -> admission
# ---------------------------------------------------------------------------

def test_grv_deferral_bounded_and_starvation_proof(teardown, knobs):
    knobs.SCHED_PREDICTOR_ENABLED = True
    knobs.SCHED_ADMISSION_DELAY_S = 0.05
    knobs.SCHED_MAX_DEFERRALS = 3
    c = SimCluster()
    g = c.grv_proxies[0]
    g.predictor.update([(b"h", b"h\x00", 50, 1, {"doomtag": 50}, {})])
    assert g.predictor.is_doomed(("doomtag",))

    async def grv(tag):
        from foundationdb_tpu.core.scheduler import now
        t0 = now()
        reply = await RequestStream.at(
            g.interface.get_consistent_read_version.endpoint).get_reply(
            GetReadVersionRequest(tags=(tag,) if tag else ()))
        return reply.version, now() - t0

    # Doomed tag: deferred exactly SCHED_MAX_DEFERRALS times, then
    # admitted unconditionally (starvation-proof) — the reply ARRIVES
    # and waited at least the deferral delays.
    version, waited = run(c, grv("doomtag"))
    assert version >= 0
    assert g.metrics.counter("SchedDeferrals").value == 3
    assert waited >= 0.1   # >= 3 jittered deferral delays
    assert not g._sched_deferred
    # Clean tag: admitted without deferral.
    _v, waited2 = run(c, grv("cleantag"))
    assert g.metrics.counter("SchedDeferrals").value == 3
    assert waited2 < 0.05
    doc = g.scheduler_status()
    assert doc["deferrals"] == 3 and doc["doomed_tags"] == ["doomtag"]


def test_grv_deferral_off_by_default(teardown):
    c = SimCluster()
    g = c.grv_proxies[0]
    g.predictor.update([(b"h", b"h\x00", 50, 1, {"doomtag": 50}, {})])

    async def grv():
        reply = await RequestStream.at(
            g.interface.get_consistent_read_version.endpoint).get_reply(
            GetReadVersionRequest(tags=("doomtag",)))
        return reply.version

    run(c, grv())
    assert g.metrics.counter("SchedDeferrals").value == 0


# ---------------------------------------------------------------------------
# Resolver heat feed stream
# ---------------------------------------------------------------------------

def test_resolver_heat_feed_stream(teardown, knobs):
    c = SimCluster()
    r = c.resolvers[0]
    r.heat.record_conflict(b"hot", b"hot\x00", tag="t/x", tenant_id=2)

    async def poll():
        return await RequestStream.at(r.interface.heat.endpoint).get_reply(
            ResolverHeatRequest(top_k=8))

    rows = run(c, poll())
    assert rows == [(b"hot", b"hot\x00", 1, 0, {"t/x": 1}, {2: 1})]
    knobs.HEAT_TELEMETRY_ENABLED = False
    assert run(c, poll()) == []


# ---------------------------------------------------------------------------
# Commit-proxy reorder + repair, end to end through the real pipeline
# ---------------------------------------------------------------------------

def _commit_req(txn, repair=False, attempt=0):
    from foundationdb_tpu.core.futures import Promise
    req = CommitTransactionRequest(transaction=txn, repair_eligible=repair,
                                   repair_attempt=attempt)
    req.reply = Promise()
    return req


def _drive_batch(c, reqs):
    p = c.commit_proxies[0]

    async def go():
        p.local_batch_number += 1
        await p._commit_batch(list(reqs), p.local_batch_number)
        out = []
        for req in reqs:
            f = req.reply.get_future()
            try:
                out.append(("ok", (await f).version))
            except FdbError as e:
                out.append(("err", e.name))
        return out

    return run(c, go())


def test_proxy_reorder_saves_intra_batch_reader(teardown, knobs):
    knobs.SCHED_REORDER_ENABLED = True
    c = SimCluster()
    # writer(k) enqueued before reader(k): without reorder the reader
    # aborts intra-batch (test_reorder_saves_reader proves that on the
    # oracle); through the proxy with the knob on, BOTH commit.
    reqs = [
        _commit_req(_txn(writes=[b"k"],
                         mutations=[Mutation(MutationType.SetValue,
                                             b"k", b"1")])),
        _commit_req(_txn(reads=[b"k"], writes=[b"c"],
                         mutations=[Mutation(MutationType.SetValue,
                                             b"c", b"2")])),
    ]
    out = _drive_batch(c, reqs)
    assert [kind for kind, _ in out] == ["ok", "ok"], out
    p = c.commit_proxies[0]
    assert p.metrics.counter("ReorderBatches").value == 1
    assert p.metrics.counter("ReorderSwaps").value == 2
    assert p.scheduler_status()["reorder_swaps"] == 2


def test_proxy_repair_commits_stale_optin(teardown, knobs):
    knobs.SCHED_REPAIR_ENABLED = True
    c = SimCluster()
    db = c.database()

    async def seed():
        t = db.create_transaction()
        t.set(b"hot", b"v1")
        await t.commit()
        return t.committed_version

    cv = run(c, seed())
    # A STALE read guard on b"hot" + a blind write: classic repairable
    # abort.  Opt-in -> server re-stamps and commits; the client sees
    # SUCCESS, one batch later.
    stale = _txn(reads=[b"hot"], writes=[b"blind"], snap=max(cv - 1, 0),
                 mutations=[Mutation(MutationType.SetValue,
                                     b"blind", b"x")])
    out = _drive_batch(c, [_commit_req(stale, repair=True)])
    assert out[0][0] == "ok", out
    p = c.commit_proxies[0]
    assert p.metrics.counter("RepairAttempted").value == 1
    assert p.metrics.counter("RepairSucceeded").value == 1
    assert p.metrics.counter("RepairExhausted").value == 0

    # The blind write landed EXACTLY once.
    async def read():
        t = db.create_transaction()
        return await t.get(b"blind")
    assert run(c, read()) == b"x"

    # The identical non-opt-in transaction still bounces to the client.
    stale2 = _txn(reads=[b"hot"], writes=[b"blind2"], snap=max(cv - 1, 0),
                  mutations=[Mutation(MutationType.SetValue,
                                      b"blind2", b"x")])
    out2 = _drive_batch(c, [_commit_req(stale2, repair=False)])
    assert out2[0] == ("err", "not_committed")
    assert p.metrics.counter("RepairAttempted").value == 1


def test_proxy_repair_exhausts_budget(teardown, knobs):
    knobs.SCHED_REPAIR_ENABLED = True
    knobs.TXN_REPAIR_MAX_ATTEMPTS = 1
    c = SimCluster()
    db = c.database()

    async def seed():
        t = db.create_transaction()
        t.set(b"hot", b"v1")
        await t.commit()
        return t.committed_version

    cv = run(c, seed())
    # A request arriving with its repair budget already spent (the
    # re-enqueued shape) that aborts AGAIN: the abort goes back to the
    # client and RepairExhausted counts it.
    stale = _txn(reads=[b"hot"], writes=[b"blind"], snap=max(cv - 1, 0),
                 mutations=[Mutation(MutationType.SetValue,
                                     b"blind", b"x")])
    out = _drive_batch(c, [_commit_req(stale, repair=True, attempt=1)])
    assert out[0] == ("err", "not_committed")
    p = c.commit_proxies[0]
    assert p.metrics.counter("RepairAttempted").value == 0
    assert p.metrics.counter("RepairExhausted").value == 1


# ---------------------------------------------------------------------------
# Knobs-off abort-set parity: verdicts AND reply wire bytes
# ---------------------------------------------------------------------------

def _parity_stream(waves=10, per_wave=16, seed=3):
    import random
    rng = random.Random(seed)
    stream = []
    for w in range(waves):
        txns = []
        for _ in range(per_wave):
            ks = [b"p%03d" % rng.randrange(40) for _ in range(3)]
            txns.append(_txn(reads=ks[:2], writes=[ks[2]],
                             snap=max(0, 1000 * (w - rng.randint(1, 2)))))
        stream.append((1000 * w, 1000 * (w + 1), txns))
    return stream


def test_knobs_off_abort_set_parity(teardown):
    """With every SCHED_* knob off (the defaults), the proxy->resolver->
    min-merge pipeline's verdicts are bit-identical to a direct oracle
    pass in ARRIVAL order — no reorder, no repair, no deferral leaks."""
    assert not server_knobs().SCHED_PREDICTOR_ENABLED
    assert not server_knobs().SCHED_REORDER_ENABLED
    assert not server_knobs().SCHED_REPAIR_ENABLED
    stream = _parity_stream()
    c = SimCluster()
    p = c.commit_proxies[0]

    async def through_pipeline():
        from foundationdb_tpu.core.futures import wait_all
        verdicts = []
        for prev, version, txns in stream:
            batch = [CommitTransactionRequest(transaction=t) for t in txns]
            requests, index_maps = p._build_resolution_requests(
                batch, prev, version)
            futures = [RequestStream.at(r.resolve.endpoint).get_reply(req)
                       for r, req in zip(p.resolvers, requests)]
            resolutions = await wait_all(futures)
            p.last_resolved_version = version
            verdicts.append([int(v) for v in p._determine_committed(
                batch, index_maps, resolutions)])
        return verdicts

    got = run(c, through_pipeline())
    oracle = OracleConflictSet(0)
    want = [[int(v) for v in oracle.resolve(txns, version)]
            for _prev, version, txns in stream]
    assert got == want
    flat = [v for wave in want for v in wave]
    assert flat.count(int(CommitResult.CONFLICT)) > 3   # non-degenerate
    cp = c.commit_proxies[0]
    assert cp.metrics.counter("ReorderBatches").value == 0
    assert cp.metrics.counter("RepairAttempted").value == 0


# Pre-scheduler ResolveTransactionBatchReply wire image, frozen at PR 12:
# committed=[COMMITTED, CONFLICT], empty state txns, one conflicting
# range for txn 1, attribution_exact {1: True}.  If a later change adds
# or reorders reply fields, the encoded bytes change and this test
# fails — the "batch reply bytes bit-identical" guard made executable.
_FROZEN_REPLY_HEX = (
    "0b1c0000005265736f6c76655472616e73616374696f6e42617463685265706c79"
    "0400000009000000636f6d6d69747465640802000000100c000000436f6d6d6974"
    "526573756c74030200000000000000100c000000436f6d6d6974526573756c7403"
    "00000000000000001200000073746174655f7472616e73616374696f6e73080000"
    "000012000000636f6e666c696374696e675f72616e6765730a0100000003010000"
    "000000000008010000000902000000060100000061060100000062110000006174"
    "747269627574696f6e5f65786163740a0100000003010000000000000001"
)


def test_reply_wire_bytes_frozen(teardown):
    from foundationdb_tpu.rpc.serde import bootstrap_registry, encode_message
    from foundationdb_tpu.server.interfaces import (
        ResolveTransactionBatchReply)
    bootstrap_registry()
    reply = ResolveTransactionBatchReply(
        committed=[CommitResult.COMMITTED, CommitResult.CONFLICT],
        conflicting_ranges={1: [(b"a", b"b")]},
        attribution_exact={1: True})
    blob = encode_message(reply)
    want = bytes.fromhex(_FROZEN_REPLY_HEX)
    assert blob == want, (
        "ResolveTransactionBatchReply wire image changed — the sched "
        "stages promise knobs-off replies bit-identical to pre-PR-12; "
        f"got {blob.hex()}")


# ---------------------------------------------------------------------------
# Status / special keys / fdbcli agreement (the PR-8 pattern)
# ---------------------------------------------------------------------------

def test_scheduler_three_surfaces_agree(teardown, knobs):
    knobs.SCHED_PREDICTOR_ENABLED = True
    knobs.SCHED_REORDER_ENABLED = True
    knobs.SCHED_REPAIR_ENABLED = True
    from foundationdb_tpu.tools.fdbcli import Cli
    c = make_cluster()
    db = c.database()

    async def traffic():
        # One guaranteed repair: seed, then a stale opt-in blind write.
        t = db.create_transaction()
        t.set(b"hot", b"v")
        await t.commit()
        t2 = db.create_transaction()
        t2.repairable = True
        t2.tag = "sched-e2e"
        t2.set_read_version(max(t.committed_version - 1, 0))
        t2.add_read_conflict_range(b"hot", b"hot\x00")
        t2.set(b"blind", b"1")
        await t2.commit()
        doc = await db.cluster.get_status()
        t3 = db.create_transaction()
        rows = await t3.get_range(b"\xff\xff/metrics/scheduler/",
                                  b"\xff\xff/metrics/scheduler0",
                                  limit=100)
        point = await db.create_transaction().get(rows[0][0]) \
            if rows else None
        return doc, rows, point

    doc, rows, point = run(c, traffic(), timeout=120)
    sched = doc["cluster"]["scheduler"]
    assert sched["enabled"] == {"predictor": True, "reorder": True,
                                "repair": True}
    assert sched["totals"]["repairs_attempted"] >= 1
    assert sched["totals"]["repairs_succeeded"] >= 1
    # Special keys render the same document.
    assert rows, "scheduler special keys empty"
    parsed = {k: json.loads(v) for k, v in rows}
    totals_row = parsed[b"\xff\xff/metrics/scheduler/totals"]
    assert totals_row["repairs_attempted"] == \
        sched["totals"]["repairs_attempted"]
    assert totals_row["enabled"] == sched["enabled"]
    assert point == rows[0][1]          # point get == range row
    # fdbcli metrics renders the same counters.
    cli = Cli.__new__(Cli)
    cli.loop, cli.db = c.loop, db
    out = cli.dispatch("metrics sched")
    assert "Scheduler (predictor=on, reorder=on, repair=on)" in out
    assert "repairs=%d" % sched["totals"]["repairs_attempted"] in out


# ---------------------------------------------------------------------------
# Review-hardening regressions: tenant doom at admission, disown-vs-fetch
# races, [knobs] validation atomicity
# ---------------------------------------------------------------------------

def test_grv_deferral_by_tenant_identity(teardown, knobs):
    """The per-tenant doom path is consultable at admission: a GRV
    carrying only a tenant id (no tags) defers like a doomed tag."""
    knobs.SCHED_PREDICTOR_ENABLED = True
    knobs.SCHED_MAX_DEFERRALS = 2
    c = SimCluster()
    g = c.grv_proxies[0]
    g.predictor.update([(b"h", b"h\x00", 50, 1, {}, {42: 50})])
    assert g.predictor.is_doomed((), tenant_id=42)

    async def grv():
        return (await RequestStream.at(
            g.interface.get_consistent_read_version.endpoint).get_reply(
            GetReadVersionRequest(tenant_id=42))).version

    assert run(c, grv()) >= 0
    assert g.metrics.counter("SchedDeferrals").value == 2


def test_disown_during_inflight_fetch(teardown, monkeypatch):
    """A disown fence landing while the range's ACQUIRING fetch is still
    in flight closes the range at fetch completion (newer than the
    fetch's min_version); a stale fence from an earlier tenure loses."""
    from foundationdb_tpu.core.scheduler import delay
    from foundationdb_tpu.server.interfaces import FetchKeysRequest
    from foundationdb_tpu.server.storage import StorageServer

    orig = StorageServer._fetch_shard

    async def slow_fetch_shard(self, req):
        await delay(0.2)   # hold the snapshot so the fence lands mid-fetch
        return await orig(self, req)

    monkeypatch.setattr(StorageServer, "_fetch_shard", slow_fetch_shard)
    c = SimCluster(n_storage=2)
    ss0, ss1 = c.storage
    db = c.database()

    async def seed():
        t = db.create_transaction()
        t.set(b"\x90seed", b"v")
        await t.commit()
        return t.committed_version

    cv = run(c, seed())
    assert cv >= 1   # source serves snapshots at >= cv

    async def race(disown_version, min_version):
        from foundationdb_tpu.core.futures import Promise
        req = FetchKeysRequest(begin=b"\x90a", end=b"\x90m",
                               sources=[ss0.interface],
                               min_version=min_version)
        req.reply = Promise()
        ss1._process.spawn(ss1._fetch_keys(req), "test.fetch")
        await delay(0.05)
        assert ss1.shards.lookup(b"\x90b")[0] == "fetching"
        ss1._disown_shard(b"\x90a", b"\x90m", disown_version)
        assert ss1.shards.lookup(b"\x90b")[0] == "fetching"  # deferred
        await req.reply.get_future()
        return ss1.shards.lookup(b"\x90b")[0]

    # Fence NEWER than the acquiring move: the range must close.
    assert run(c, race(disown_version=cv + 500,
                       min_version=cv)) == "absent"
    # Fence OLDER than the acquiring move: the re-acquisition wins.
    assert run(c, race(disown_version=max(cv - 1, 0),
                       min_version=cv)) == "owned"


def test_spec_knob_validation_is_atomic(teardown, knobs):
    """A typo'd [knobs] name rejects the spec WITHOUT leaking earlier
    overrides into the process."""
    from foundationdb_tpu.testing.tester import run_simulation
    spec = {"knobs": {"SCHED_REPAIR_ENABLED": True,
                      "SCHED_REORDR_ENABLED": True},
            "test": []}
    with pytest.raises(KeyError, match="SCHED_REORDR_ENABLED"):
        run_simulation(spec, 1)
    assert server_knobs().SCHED_REPAIR_ENABLED is False


# ---------------------------------------------------------------------------
# Chaos: double-run unseed + duplicate-commit audit + coverage
# ---------------------------------------------------------------------------

def test_sched_chaos_double_run(teardown):
    from foundationdb_tpu.core import coverage
    from foundationdb_tpu.testing.tester import run_test_twice
    r1, r2 = run_test_twice(
        os.path.join(SPECS, "SchedChaosTest.toml"), seed=12345)
    assert r1.unseed == r2.unseed and r1.digest == r2.digest
    m = r1.metrics["SchedRepairLoad"]
    assert m["acked"] > 0
    # All three stages actually ran under the nemesis.
    assert coverage.covered("ProxyTxnRepaired")
    assert coverage.covered("ProxyTxnRepairCommitted")
    assert coverage.covered("GrvSchedDeferral")
    assert coverage.covered("ProxyBatchReordered")
    assert coverage.covered("ChaosNemesisResolverKill")


# ---------------------------------------------------------------------------
# Bench smoke: the sched subcommand's measurement core at toy scale
# ---------------------------------------------------------------------------

def test_bench_sched_smoke(monkeypatch):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_sched_under_test",
        os.path.join(os.path.dirname(__file__), os.pardir, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    monkeypatch.setattr(bench, "SCHED_TXNS", 512)
    monkeypatch.setattr(bench, "SCHED_BATCHES", 5)
    monkeypatch.setattr(bench, "SCHED_WARMUP", 2)
    monkeypatch.setattr(bench, "SCHED_REPEATS", 1)
    monkeypatch.setattr(bench, "SCHED_LOWC_BATCHES", 1)
    doc = bench.run_sched_bench()
    assert doc["parity"] == "ok"
    rates = doc["commit_rate"]
    assert set(rates) == {"off", "predictor", "reorder", "repair", "all",
                          "ladder", "all+ladder"}
    assert all(0.0 <= v <= 1.0 for v in rates.values())
    # The stages help (or at worst do nothing) on the contended stream.
    assert rates["all"] >= rates["off"]
    assert rates["repair"] >= rates["off"]
    # The multi-attempt ladder lifts the single-attempt repair ceiling
    # (ISSUE 14 satellite: TXN_REPAIR_MAX_ATTEMPTS > 1 honored).
    assert rates["ladder"] >= rates["repair"]
    assert doc["commit_rate_low"] >= 0.95
    counters = doc["stage_counters"]
    assert counters["off"]["repairs"] == 0
    assert counters["off"]["deferrals"] == 0
    assert counters["all"]["repairs"] > 0
    assert counters["ladder"]["repairs"] > counters["repair"]["repairs"]


# ---------------------------------------------------------------------------
# Repair ladder (ISSUE 14): per-range version-clock backoff
# ---------------------------------------------------------------------------

def test_repair_ladder_rungs_and_expiry():
    from foundationdb_tpu.sched.repair import RepairLadder
    lad = RepairLadder(backoff_versions=100, table_max=8)
    r = (b"a", b"b")
    assert lad.should_attempt([r], 1000)
    lad.note_failure([r], 1000)
    # Blocked for the base window, open after it.
    assert not lad.should_attempt([r], 1050)
    assert lad.should_attempt([r], 1100)
    # A second exhaustion doubles the rung.
    lad.note_failure([r], 1100)
    assert not lad.should_attempt([r], 1250)
    assert lad.should_attempt([r], 1300)
    # Unrelated ranges never blocked; a mixed culprit list is blocked if
    # ANY member is.
    assert lad.should_attempt([(b"x", b"y")], 1150)
    assert not lad.should_attempt([(b"x", b"y"), r], 1150)


def test_repair_ladder_success_clears():
    from foundationdb_tpu.sched.repair import RepairLadder
    lad = RepairLadder(backoff_versions=100)
    r = (b"a", b"b")
    lad.note_failure([r], 1000)
    assert not lad.should_attempt([r], 1001)
    lad.note_success([r])
    assert lad.should_attempt([r], 1001)
    # And the rung count reset with it: next failure is back at rung 1.
    lad.note_failure([r], 2000)
    assert lad.should_attempt([r], 2100)
    # Entries are keyed by resolver-CLIPPED culprit fragments; a success
    # reported with the FULL declared read range must still clear them
    # (containment, not equality).
    lad.note_failure([(b"m", b"mm")], 3000)
    lad.note_success([(b"a", b"z")])
    assert lad.should_attempt([(b"m", b"mm")], 3001)
    # ...but an unrelated span clears nothing.
    lad.note_failure([(b"m", b"mm")], 4000)
    lad.note_success([(b"x", b"z")])
    assert not lad.should_attempt([(b"m", b"mm")], 4001)


def test_repair_ladder_table_bound():
    from foundationdb_tpu.sched.repair import RepairLadder
    lad = RepairLadder(backoff_versions=1000, table_max=4)
    # Overfill with live entries: trim keeps the LATEST-expiring (most
    # blocked) ones.
    for i in range(10):
        lad.note_failure([(b"k%02d" % i, b"k%02d\x00" % i)], 100 + i)
    assert len(lad._entries) <= 4
    assert (b"k09", b"k09\x00") in lad._entries
    # Expired entries trim first.
    lad2 = RepairLadder(backoff_versions=10, table_max=4)
    for i in range(4):
        lad2.note_failure([(b"e%d" % i, b"e%d\x00" % i)], 0)
    lad2.note_failure([(b"live", b"live\x00")], 10_000)
    lad2.note_failure([(b"live2", b"live2\x00")], 10_000)
    assert (b"live", b"live\x00") in lad2._entries


def test_proxy_repair_ladder_wiring(knobs, teardown):  # noqa: F811
    """The proxy's _collect_repairs honors TXN_REPAIR_MAX_ATTEMPTS > 1
    and consults the ladder only for CLIMBS (attempt >= 1): first
    repairs are never backed off."""
    import dataclasses as _dc
    from foundationdb_tpu.server.cluster import SimCluster
    knobs.SCHED_REPAIR_ENABLED = True
    knobs.TXN_REPAIR_MAX_ATTEMPTS = 3
    cl = SimCluster(n_resolvers=1, n_storage=1)
    proxy = cl.commit_proxies[0]
    txn = CommitTransactionRef(
        read_conflict_ranges=[KeyRange(b"hot", b"hot\x00")],
        write_conflict_ranges=[KeyRange(b"w", b"w\x00")],
        mutations=[], read_snapshot=500)
    culprits = {0: [(b"hot", b"hot\x00")]}
    exact = {0: True}

    def collect(attempt, version):
        req = CommitTransactionRequest(
            transaction=txn, repair_eligible=True, repair_attempt=attempt)
        from foundationdb_tpu.core.futures import Promise
        req.reply = Promise()
        repaired: set = set()
        out = proxy._collect_repairs(
            [req], [CommitResult.CONFLICT], {}, dict(culprits),
            dict(exact), version, repaired)
        return out

    # Attempts below the budget re-enqueue with attempt+1.
    assert collect(0, 1000) and collect(0, 1000)[0].repair_attempt == 1
    assert collect(1, 1000)[0].repair_attempt == 2
    assert collect(2, 1000)[0].repair_attempt == 3
    # Budget exhausted: no repair, and the range climbs a backoff rung.
    assert collect(3, 2000) == []
    assert proxy._repair_ladder.blocked_count(2001) == 1
    # A CLIMB into the blocked range is deferred...
    assert collect(1, 2001) == []
    assert proxy.metrics.counter("RepairBackedOff").value == 1
    # ...but a FIRST repair of the same range is not.
    assert collect(0, 2001) != []
    assert "repairs_backed_off" in proxy.scheduler_status()


def test_flowlint_clean_on_sched_package():
    """The new package lints clean on its own (the repo-wide empty-
    baseline gate in test_flowlint covers the rest of the PR)."""
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "flowlint.py"),
         os.path.join(repo, "foundationdb_tpu", "sched"),
         "--baseline", "none"],
        capture_output=True, text=True, cwd=repo)
    assert r.returncode == 0, r.stdout + r.stderr
