"""Data distribution v1: shard split under load, MoveKeys, re-replication.

Reference: fdbserver/DataDistribution.actor.cpp (teamTracker :3506),
DataDistributionTracker.actor.cpp (split on size), MoveKeys.actor.cpp
(two-phase handoff).  VERDICT round-2 done-criteria: a replication=2
cluster kills one storage server and a ConsistencyCheck-style replica
audit passes after re-replication; a hot shard splits under load."""

import pytest

from foundationdb_tpu.core.knobs import server_knobs
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration

from test_recovery import commit_kv, read_key, teardown  # noqa: F401


def make_cluster(**cfg):
    n_workers = cfg.pop("n_workers", 6)
    n_storage_workers = cfg.pop("n_storage_workers", 3)
    config = DatabaseConfiguration(**cfg)
    return SimFdbCluster(config=config, n_workers=n_workers,
                         n_storage_workers=n_storage_workers)


def current_dd(cluster):
    cc = cluster.current_cc()
    if cc is None or cc.db_info.data_distributor is None:
        return None
    return getattr(cc.db_info.data_distributor, "role", None)


async def consistency_audit(cluster, db):
    from foundationdb_tpu.testing.workloads import ConsistencyCheckWorkload
    w = ConsistencyCheckWorkload(cluster, db, {})
    assert await w.check()
    return w.metrics["shards_audited"]


def test_hot_shard_splits_under_load(teardown):  # noqa: F811
    knobs = server_knobs()
    old = knobs.DD_SHARD_SPLIT_BYTES
    knobs.DD_SHARD_SPLIT_BYTES = 2000
    try:
        c = make_cluster(n_storage=2)
        db = c.database()

        async def go():
            from foundationdb_tpu.core.scheduler import delay
            # ~6KB into the first shard (keys < \x80): must split.
            for i in range(60):
                await commit_kv(db, b"hot/%04d" % i, b"v" * 80)
            dd = current_dd(c)
            deadline = 30.0
            while dd.stats["splits"] == 0 and deadline > 0:
                await delay(0.5)
                deadline -= 0.5
            assert dd.stats["splits"] >= 1, "hot shard never split"
            # Routing still works after the metadata split.
            await commit_kv(db, b"hot/post", b"ok")
            assert await read_key(db, b"hot/post") == b"ok"
            assert await read_key(db, b"hot/0000") == b"v" * 80
        c.run_until(c.loop.spawn(go()), timeout=300)
    finally:
        knobs.DD_SHARD_SPLIT_BYTES = old


def test_cleared_shards_merge_back(teardown):  # noqa: F811
    """Split under load, then clear the data: the DD merges the cold
    adjacent shards back so the boundary map is bounded under churn
    (reference DataDistributionTracker.actor.cpp shardMerger; VERDICT r4
    item 6)."""
    knobs = server_knobs()
    old_split = knobs.DD_SHARD_SPLIT_BYTES
    old_merge = knobs.DD_SHARD_MERGE_BYTES
    knobs.DD_SHARD_SPLIT_BYTES = 2000
    knobs.DD_SHARD_MERGE_BYTES = 500
    try:
        c = make_cluster(n_storage=2)
        db = c.database()

        async def go():
            from foundationdb_tpu.core.scheduler import delay
            for i in range(60):
                await commit_kv(db, b"churn/%04d" % i, b"v" * 80)
            dd = current_dd(c)
            deadline = 30.0
            while dd.stats["splits"] < 1 and deadline > 0:
                await delay(0.5)
                deadline -= 0.5
            assert dd.stats["splits"] >= 1, "shard never split"
            peak = len(dd.map)
            # Clear everything: the shards are now empty and adjacent with
            # identical teams -> merge candidates.
            t = db.create_transaction()
            while True:
                try:
                    t.clear(b"churn/", b"churn0")
                    await t.commit()
                    break
                except Exception as e:   # noqa: BLE001
                    await t.on_error(e)
            deadline = 60.0
            while dd.stats.get("merges", 0) < 1 and deadline > 0:
                await delay(0.5)
                deadline -= 0.5
            assert dd.stats.get("merges", 0) >= 1, "no shard merged"
            assert len(dd.map) < peak
            # Routing still correct after the merge.
            await commit_kv(db, b"churn/post", b"ok")
            assert await read_key(db, b"churn/post") == b"ok"
        c.run_until(c.loop.spawn(go()), timeout=300)
    finally:
        knobs.DD_SHARD_SPLIT_BYTES = old_split
        knobs.DD_SHARD_MERGE_BYTES = old_merge


def test_storage_death_rereplication_and_audit(teardown):  # noqa: F811
    c = make_cluster(n_storage=3, storage_replication=2)
    db = c.database()

    async def go():
        from foundationdb_tpu.core.scheduler import delay
        for i in range(40):
            await commit_kv(db, b"rr/%04d" % i, b"val%04d" % i)
        await commit_kv(db, b"\x90spread", b"hi")   # second region too
        # Kill one storage server's process (power-fail its machine).
        dd = current_dd(c)
        assert dd is not None
        c.sim.power_fail_machine("mach.worker0")
        # DD notices, re-replicates every shard that lost a replica.
        deadline = 60.0
        while deadline > 0:
            await delay(0.5)
            deadline -= 0.5
            dd = current_dd(c) or dd
            if dd.stats["rereplications"] > 0 and dd.moves_in_flight == 0:
                break
        assert dd.stats["rereplications"] > 0, "no re-replication happened"
        # Every key still readable; every shard's replicas byte-identical.
        for i in range(40):
            assert await read_key(db, b"rr/%04d" % i) == b"val%04d" % i
        audited = await consistency_audit(c, db)
        assert audited >= 1

    c.run_until(c.loop.spawn(go()), timeout=300)


def test_consistency_audit_clean_cluster(teardown):  # noqa: F811
    c = make_cluster(n_storage=2, storage_replication=2)
    db = c.database()

    async def go():
        from foundationdb_tpu.core.scheduler import delay
        for i in range(20):
            await commit_kv(db, b"cc/%03d" % i, b"v%03d" % i)
        await delay(0.3)   # let replicas drain
        audited = await consistency_audit(c, db)
        assert audited >= 2

    c.run_until(c.loop.spawn(go()), timeout=120)


def test_fetch_shard_floors_snapshot_at_min_version(teardown):  # noqa: F811
    """ADVICE r3 (high): a fetch-shard snapshot served below the MoveKeys
    phase-1 commit version would miss mutations routed only to the old
    team.  The source must wait until its applied version reaches
    req.min_version before serving."""
    from foundationdb_tpu.core import EventLoop, set_event_loop
    from foundationdb_tpu.core.futures import Promise
    from foundationdb_tpu.server.interfaces import FetchShardRequest
    from foundationdb_tpu.server.storage import StorageServer

    lp = EventLoop(sim=True)
    set_event_loop(lp)
    ss = StorageServer("ss-test", tag=0, log_system=None)
    ss.shards.set_range(b"", b"\xff\xff", ("owned", 0))
    ss.data.set(b"a", b"old", 5)
    ss.version.set(5)

    p = Promise()
    req = FetchShardRequest(begin=b"", end=b"\xff", min_version=10,
                            reply=p)
    from foundationdb_tpu.core.scheduler import delay
    f = lp.spawn(ss._fetch_shard(req))
    lp.run_until(delay(0.1))
    assert not p.get_future().is_ready(), \
        "snapshot served below the phase-1 floor"
    # The lagging source catches up: a write at v8 (the in-between window
    # the floor exists to capture) then the phase-1 version itself.
    ss.data.set(b"b", b"in-between", 8)
    ss.version.set(10)
    reply = lp.run_until(p.get_future(), timeout=5)
    lp.run_until(f, timeout=5)
    assert reply.version >= 10
    assert (b"b", b"in-between") in reply.data


def test_fetch_shard_stalled_source_raises_future_version(teardown):  # noqa: F811
    """A live-but-stalled source must raise future_version (bounded wait)
    so the destination falls through to its next source instead of
    wedging the move forever."""
    from foundationdb_tpu.core import EventLoop, set_event_loop
    from foundationdb_tpu.core.error import FdbError
    from foundationdb_tpu.core.futures import Promise
    from foundationdb_tpu.server.interfaces import FetchShardRequest
    from foundationdb_tpu.server.storage import StorageServer

    lp = EventLoop(sim=True)
    set_event_loop(lp)
    ss = StorageServer("ss-test", tag=0, log_system=None)
    ss.version.set(5)
    p = Promise()
    f = lp.spawn(ss._fetch_shard(FetchShardRequest(
        begin=b"", end=b"\xff", min_version=10, reply=p)))
    lp.run_until(f, timeout=30)
    assert p.get_future().is_error()
    try:
        p.get_future().get()
    except FdbError as e:
        assert e.name == "future_version"


def test_resolution_change_versions_strictly_increase(teardown):  # noqa: F811
    """ADVICE r3 (low): two balancing moves with no intervening commit
    must not share a change version (proxies dedup by version and would
    silently drop the second change)."""
    from foundationdb_tpu.server.master import Master

    m = Master.__new__(Master)
    m.version = 100
    m.resolution_changes_version = 0
    # Mimic the balancing assignment twice with no version allocation.
    for _ in range(2):
        m.resolution_changes_version = max(
            m.version + 1, m.resolution_changes_version + 1)
    assert m.resolution_changes_version == 102
