"""Coordination layer tests: generation registers, coordinated state
quorum semantics, leader election + failover.

Models reference behavior: CoordinatedState read/write linearizability
(fdbserver/CoordinatedState.actor.cpp), coordinator-majority leader
election with heartbeat expiry (fdbserver/Coordination.actor.cpp,
LeaderElection.h)."""

import pytest

from foundationdb_tpu.core import FdbError
from foundationdb_tpu.core.futures import AsyncVar
from foundationdb_tpu.rpc.sim import Simulator, set_simulator
from foundationdb_tpu.server.coordination import (CoordinatedState,
                                                  CoordinationClientInterface,
                                                  CoordinationServer,
                                                  try_become_leader)


@pytest.fixture()
def sim(loop):
    s = Simulator()
    set_simulator(s)
    yield s
    set_simulator(None)


def make_coordinators(sim, n):
    servers, clients = [], []
    for i in range(n):
        p = sim.new_process(name=f"coord{i}")
        cs = CoordinationServer(f"coord{i}")
        cs.run(p)
        servers.append((p, cs))
        clients.append(CoordinationClientInterface(cs))
    return servers, clients


def test_coordinated_state_read_write(loop, sim):
    _, clients = make_coordinators(sim, 3)
    st = CoordinatedState(clients)

    async def go():
        assert await st.read() is None
        await st.write(b"state-v1")
        st2 = CoordinatedState(clients)
        assert await st2.read() == b"state-v1"
        await st2.write(b"state-v2")
        st3 = CoordinatedState(clients)
        assert await st3.read() == b"state-v2"

    loop.run_until(loop.spawn(go()), timeout=30)


def test_coordinated_state_conflict(loop, sim):
    _, clients = make_coordinators(sim, 3)

    async def go():
        a = CoordinatedState(clients)
        b = CoordinatedState(clients)
        await a.read()
        await b.read()            # b's read invalidates a's generation
        await b.write(b"from-b")
        with pytest.raises(FdbError) as ei:
            await a.write(b"from-a")
        assert ei.value.name == "coordinated_state_conflict"
        c = CoordinatedState(clients)
        assert await c.read() == b"from-b"

    loop.run_until(loop.spawn(go()), timeout=30)


def test_coordinated_state_survives_minority_failure(loop, sim):
    servers, clients = make_coordinators(sim, 3)

    async def go():
        st = CoordinatedState(clients)
        await st.read()
        await st.write(b"durable")
        sim.kill_process(servers[0][0])    # minority down
        st2 = CoordinatedState(clients)
        assert await st2.read() == b"durable"
        await st2.write(b"still-works")
        st3 = CoordinatedState(clients)
        assert await st3.read() == b"still-works"

    loop.run_until(loop.spawn(go()), timeout=30)


def test_leader_election_single_winner(loop, sim):
    _, clients = make_coordinators(sim, 3)
    observed = [AsyncVar(None), AsyncVar(None)]

    async def go():
        from foundationdb_tpu.core.scheduler import delay, spawn
        c1 = spawn(try_become_leader(clients, "cand-A", observed[0],
                                     change_id=100))
        c2 = spawn(try_become_leader(clients, "cand-B", observed[1],
                                     change_id=200))
        for _ in range(100):
            await delay(0.2)
            l0, l1 = observed[0].get(), observed[1].get()
            if l0 is not None and l1 is not None:
                break
        l0, l1 = observed[0].get(), observed[1].get()
        # Both observers agree; the lower change_id (100, "cand-A") wins.
        assert l0 is not None and l1 is not None
        assert l0.change_id == l1.change_id == 100
        assert l0.serialized_info == "cand-A"
        c1.cancel()
        c2.cancel()

    loop.run_until(loop.spawn(go()), timeout=120)


def test_leader_failover(loop, sim):
    servers, clients = make_coordinators(sim, 3)
    observed = [AsyncVar(None), AsyncVar(None)]

    async def go():
        from foundationdb_tpu.core.scheduler import delay, spawn
        # Leader A campaigns from a process we can kill.
        leader_proc = sim.new_process(name="leaderA")
        leader_proc.spawn(try_become_leader(clients, "A", observed[0],
                                            change_id=1))
        c2 = spawn(try_become_leader(clients, "B", observed[1],
                                     change_id=2))
        for _ in range(100):
            await delay(0.2)
            if observed[1].get() is not None:
                break
        assert observed[1].get().serialized_info == "A"
        # Kill A: its heartbeats stop; B must take over within the expiry.
        sim.kill_process(leader_proc)
        for _ in range(200):
            await delay(0.2)
            cur = observed[1].get()
            if cur is not None and cur.serialized_info == "B":
                break
        assert observed[1].get().serialized_info == "B"
        c2.cancel()

    loop.run_until(loop.spawn(go()), timeout=300)
