"""ISSUE 3: the unified metrics subsystem — registry counters/rates,
mergeable histogram snapshots, LatencyBand emission cadence, supervisor
degrade/promote transition counters, the cross-role commit_debug
timeline, trace file hygiene, and the TraceEvent lint."""

import json
import os
import subprocess
import sys

import pytest

from foundationdb_tpu.core.histogram import CounterCollection, Histogram
from foundationdb_tpu.core.metrics import (HistogramSnapshot,
                                           MetricsRegistry,
                                           get_metrics_registry,
                                           set_metrics_registry)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def registry():
    """Fresh process registry so collections of other tests don't leak in."""
    fresh = MetricsRegistry()
    prev = set_metrics_registry(fresh)
    yield fresh
    set_metrics_registry(prev)


# ---------------------------------------------------------------------------
# Histogram snapshots: merge + percentile math at bucket edges
# ---------------------------------------------------------------------------

def test_histogram_snapshot_merge_and_bucket_edges(registry):
    # Bucket i spans (1us * 2^(i-1), 1us * 2^i]; a sample exactly at a
    # bucket's upper bound must land in that bucket, and percentile()
    # must return the bucket's UPPER bound.
    h = Histogram("G", "op")
    for _ in range(50):
        h.record(1e-6)          # bucket 0, bound 1us
    for _ in range(50):
        h.record(2e-6)          # bucket 1, bound 2us (exactly at edge)
    assert h.percentile(0.50) == 1e-6      # 50th sample is in bucket 0
    assert h.percentile(0.51) == 2e-6      # first bucket-1 sample
    assert h.percentile(0.99) == 2e-6

    # Merge must equal one histogram holding all samples.
    h1 = Histogram("G", "a")
    h2 = Histogram("G", "b")
    both = Histogram("G", "ab")
    for us, target in ((1, h1), (1000, h2)):
        for _ in range(100):
            target.record(us * 1e-6)
            both.record(us * 1e-6)
    merged = HistogramSnapshot.merged([h1.snapshot(), h2.snapshot()])
    ref = both.snapshot()
    assert merged.buckets == ref.buckets
    assert merged.count == ref.count == 200
    for p in (0.25, 0.5, 0.75, 0.95, 0.99):
        assert merged.percentile(p) == ref.percentile(p)
    assert merged.min == ref.min and merged.max == ref.max
    s = merged.to_status()
    assert s["count"] == 200 and s["p50"] == 1e-6 and s["p99"] >= 1e-3


def test_histogram_lifetime_survives_roll(registry):
    # roll() feeds the periodic LatencyBand (interval-scoped) but
    # to_status()/snapshot() keep the lifetime distribution.
    h = Histogram("G", "op")
    for _ in range(10):
        h.record(1e-3)
    interval = h.roll()
    assert interval.count == 10
    assert h.roll().count == 0           # nothing new this interval
    assert h.to_status()["count"] == 10  # lifetime retained
    h.record(1e-3)
    assert h.to_status()["count"] == 11


# ---------------------------------------------------------------------------
# Registry: registration, counter sums, rates
# ---------------------------------------------------------------------------

def test_registry_counters_and_aggregation(registry):
    c1 = CounterCollection("CommitProxy", "p0")
    c2 = CounterCollection("CommitProxy", "p1")
    c3 = CounterCollection("Resolver", "r0")
    assert set(registry.collections("CommitProxy")) == {c1, c2}
    c1.counter("TxnCommitted").add(7)
    c2.counter("TxnCommitted").add(5)
    c3.counter("TxnResolved").add(3)
    c1.histogram("Commit").record(2e-3)
    c2.histogram("Commit").record(8e-3)
    agg = registry.aggregate_counters()
    assert agg["CommitProxy"]["TxnCommitted"] == 12
    assert agg["Resolver"]["TxnResolved"] == 3
    band = registry.merged_histogram("CommitProxy", "Commit")
    assert band.count == 2 and band.percentile(0.99) >= 8e-3
    doc = registry.to_status()
    assert doc["CommitProxy"]["counters"]["TxnCommitted"] == 12
    assert doc["CommitProxy"]["latency_statistics"]["Commit"]["count"] == 2
    json.dumps(doc)
    # rate_and_roll: delta since last emission over dt.
    assert c1.counter("TxnCommitted").rate_and_roll(2.0) == 3.5
    assert c1.counter("TxnCommitted").rate_and_roll(2.0) == 0.0
    # The registry holds collections weakly: a dead role's collection
    # disappears (no unbounded growth across recruitments).
    del c3
    import gc
    gc.collect()
    assert registry.collections("Resolver") == []


# ---------------------------------------------------------------------------
# LatencyBand emission cadence under the sim clock
# ---------------------------------------------------------------------------

def test_latency_band_emission_cadence(registry, loop):
    from foundationdb_tpu.core.knobs import server_knobs
    from foundationdb_tpu.core.scheduler import delay
    from foundationdb_tpu.core.trace import Tracer, get_tracer, set_tracer
    set_tracer(Tracer())
    interval = float(server_knobs().METRICS_EMIT_INTERVAL)
    coll = CounterCollection("TestRole", "t0")

    async def driver():
        loop.spawn(coll.emit_loop())
        # One sample mid-interval-1, one mid-interval-2, none in 3 —
        # offsets keep the recorder off the emitter's tick instants.
        coll.counter("Ops").add(4)
        coll.histogram("OpLatency").record(3e-3)
        await delay(interval * 1.2)              # -> interval 2
        coll.counter("Ops").add(4)
        coll.histogram("OpLatency").record(3e-3)
        await delay(interval * 2.0)              # through interval 3
        return True

    assert loop.run_until(loop.spawn(driver()), timeout=60)
    bands = get_tracer().find("LatencyBand")
    assert len(bands) == 2, bands       # idle interval 3 emitted nothing
    for b in bands:
        assert b["Group"] == "TestRole" and b["Op"] == "OpLatency"
        assert b["Count"] == 1 and b["P50"] > 0 and b["P99"] >= b["P50"]
        assert b["PerSec"] > 0
    # The periodic Metrics event carries counter values + rates and keeps
    # firing even in idle intervals (it is the liveness signal).
    mev = get_tracer().find("TestRoleMetrics")
    assert len(mev) == 3
    assert mev[0]["Ops"] == 4 and mev[0]["OpsPerSec"] > 0
    assert mev[-1]["OpsPerSec"] == 0.0


# ---------------------------------------------------------------------------
# Supervisor degrade/promote transitions as counters
# ---------------------------------------------------------------------------

def test_supervisor_transition_counters(registry):
    from foundationdb_tpu.conflict.oracle import OracleConflictSet
    from foundationdb_tpu.conflict.supervisor import (BackendHealthMonitor,
                                                      SupervisedConflictSet)
    from foundationdb_tpu.txn.types import (CommitResult,
                                            CommitTransactionRef, KeyRange)

    def txn(i):
        return CommitTransactionRef(
            write_conflict_ranges=[KeyRange(b"k%03d" % i,
                                            b"k%03d\x00" % i)])

    sup = SupervisedConflictSet(
        lambda oldest_version=0: OracleConflictSet(oldest_version),
        monitor=BackendHealthMonitor(failure_threshold=1,
                                     reprobe_interval_s=0.0))
    c = sup.metrics.counters
    assert sup.resolve([txn(0)], 100) == [CommitResult.COMMITTED]
    assert c["DeviceBatches"].value == 1
    assert c["DeviceTxns"].value == 1
    assert "Dispatch" in sup.metrics.histograms

    sup.force_device_error = "operation_failed"
    assert sup.resolve([txn(1)], 200) == [CommitResult.COMMITTED]
    sup.force_device_error = None
    assert sup.degraded
    assert c["Degrades"].value == 1
    assert c["FallbackBatches"].value == 1

    # reprobe_interval 0: the next resolve promotes straight back.
    assert sup.resolve([txn(2)], 300) == [CommitResult.COMMITTED]
    assert not sup.degraded
    assert c["Promotions"].value == 1
    st = sup.status()
    assert st["degrades"] == 1 and st["promotions"] == 1
    assert "latency_statistics" in st
    assert st["latency_statistics"]["Dispatch"]["count"] >= 1
    # Transition counters ride the TpuBackend group for status rollup.
    assert get_metrics_registry().aggregate_counters()[
        "TpuBackend"]["Degrades"] == 1


# ---------------------------------------------------------------------------
# commit_debug: cross-role GRV -> reply timeline from a sim cluster
# ---------------------------------------------------------------------------

def test_commit_debug_reconstructs_full_timeline():
    from foundationdb_tpu.core import (DeterministicRandom,
                                       set_deterministic_random,
                                       set_event_loop)
    from foundationdb_tpu.core.trace import Tracer, get_tracer, set_tracer
    from foundationdb_tpu.rpc.sim import set_simulator
    from foundationdb_tpu.server.cluster import SimCluster
    from foundationdb_tpu.tools.commit_debug import (REQUIRED_STAGES,
                                                     build_timelines,
                                                     is_complete,
                                                     render_waterfall,
                                                     stage_summary)
    set_tracer(Tracer())
    set_deterministic_random(DeterministicRandom(7))
    c = SimCluster(n_resolvers=2, n_storage=2, n_tlogs=2)
    try:
        db = c.database()

        async def go():
            t = db.create_transaction()
            t.debug_id = "dbg-tl"
            await t.get(b"timeline-key")       # forces a real GRV
            t.set(b"timeline-key", b"v1")
            await t.commit()
            return True

        assert c.run_until(c.loop.spawn(go()), timeout=60)
        timelines = build_timelines(list(get_tracer().ring))
        assert "dbg-tl" in timelines, timelines.keys()
        tl = timelines["dbg-tl"]
        assert is_complete(tl), (
            f"missing stages: "
            f"{[r for r in REQUIRED_STAGES if not any(r in loc for _, loc in tl)]}")
        # Causal order along the waterfall.
        times = {loc: t for t, loc in tl}
        assert times["GrvProxy.reply"] <= times["NativeAPI.commit.Before"]
        assert times["CommitProxy.batchStart"] <= \
            times["CommitProxy.afterResolution"]
        assert times["CommitProxy.afterResolution"] <= \
            times["CommitProxy.afterTLogCommit"]
        assert times["CommitProxy.afterTLogCommit"] <= \
            times["NativeAPI.commit.After"]
        # Renderers produce usable text.
        out = render_waterfall("dbg-tl", tl)
        assert "dbg-tl" in out and "TLog" in out
        rows = stage_summary(timelines)
        assert rows and all(len(r) == 4 for r in rows)
    finally:
        set_simulator(None)
        set_event_loop(None)


def test_commit_debug_cli_reads_jsonl(tmp_path):
    # The CLI path: JSONL file in, waterfall + summary out.
    events = [
        {"Type": "TransactionDebug", "Time": 0.0, "DebugID": "d1",
         "Location": "NativeAPI.getConsistentReadVersion.Before"},
        {"Type": "TransactionDebug", "Time": 0.001, "DebugID": "d1",
         "Location": "GrvProxy.reply"},
        {"Type": "CommitDebug", "Time": 0.002, "DebugID": "d1",
         "Location": "CommitProxy.batch:p0.b1"},
        {"Type": "CommitDebug", "Time": 0.003, "DebugID": "p0.b1",
         "Location": "CommitProxy.batchStart"},
        {"Type": "CommitDebug", "Time": 0.004, "DebugID": "p0.b1",
         "Location": "TLog.log0.commit"},
    ]
    p = tmp_path / "trace.0.jsonl"
    p.write_text("\n".join(json.dumps(e) for e in events) +
                 "\ngarbage-torn-tail")
    out = subprocess.run(
        [sys.executable, "-m", "foundationdb_tpu.tools.commit_debug",
         str(p)], capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, out.stderr
    assert "d1" in out.stdout and "TLog.log0.commit" in out.stdout
    assert "Stage summary" in out.stdout
    assert "p0.b1" not in out.stdout.split("Commit timeline")[0]


# ---------------------------------------------------------------------------
# Trace file hygiene: roll + final TraceStats
# ---------------------------------------------------------------------------

def test_tracer_rolls_and_reports_stats(tmp_path):
    from foundationdb_tpu.core.trace import Tracer
    path = str(tmp_path / "trace.0.jsonl")
    tr = Tracer(path=path, roll_bytes=400, keep_files=2, flush_every=1)
    for i in range(60):
        tr.emit({"Type": "Filler", "Severity": 10, "N": i})
    tr.emit({"Type": "Boom", "Severity": 40})
    assert tr.error_count == 1
    tr.close()
    # Rolled generations exist, bounded by keep_files.
    assert os.path.exists(str(tmp_path / "trace.1.jsonl"))
    assert os.path.exists(str(tmp_path / "trace.2.jsonl"))
    assert not os.path.exists(str(tmp_path / "trace.3.jsonl"))
    # close() leaves a final TraceStats with the error count (the
    # "Tracer.close() loses error_count" fix).
    last = open(path).read().strip().splitlines()[-1]
    stats = json.loads(last)
    assert stats["Type"] == "TraceStats"
    assert stats["ErrorCount"] == 1 and stats["Events"] == 61
    # All 61 real events + TraceStats survive across the generations.
    total = 0
    for f in ("trace.0.jsonl", "trace.1.jsonl", "trace.2.jsonl"):
        total += len((tmp_path / f).read_text().strip().splitlines())
    assert total <= 62          # keep_files bounds retention


# ---------------------------------------------------------------------------
# Status surfacing: per-stage bands + cluster.metrics rollup
# ---------------------------------------------------------------------------

def test_status_collects_stage_bands(registry):
    from types import SimpleNamespace
    from foundationdb_tpu.server.status import (collect_cluster_metrics,
                                                collect_latency_bands)

    def role(group, rid, hists, counters=()):
        r = SimpleNamespace(metrics=CounterCollection(group, rid))
        for name, val in hists:
            r.metrics.histogram(name).record(val)
        for name, n in counters:
            r.metrics.counter(name).add(n)
        return SimpleNamespace(role=r)

    grv = role("GrvProxy", "g0", [("GRVLatency", 1e-3), ("QueueWait", 1e-4)],
               [("TxnStarted", 5)])
    cp = role("CommitProxy", "p0",
              [("Commit", 5e-3), ("BatchAssembly", 1e-3),
               ("Resolution", 2e-3), ("TLogLogging", 1e-3),
               ("Reply", 5e-4), ("VersionWait", 2e-4)],
              [("TxnCommitted", 9)])
    backend = SimpleNamespace(metrics=CounterCollection("TpuBackend", "b0"))
    backend.metrics.histogram("Dispatch").record(4e-4)
    backend.metrics.histogram("InflightDepth").record(2.0)
    backend.metrics.counter("DeviceBatches").add(2)
    backend.metrics.counter("PipelineStalls").add(3)
    res_role = SimpleNamespace(
        metrics=CounterCollection("Resolver", "r0"), conflict_set=backend)
    res_role.metrics.histogram("Resolve").record(3e-4)
    res = SimpleNamespace(role=res_role)
    tlog = role("TLog", "l0", [("Append", 1e-4), ("DurableWait", 5e-4)])
    ss = role("StorageServer", "s0",
              [("ReadLatency", 2e-4), ("TLogPeek", 1e-4)])

    info = SimpleNamespace(grv_proxies=[grv], commit_proxies=[cp],
                           resolvers=[res], tlogs=[tlog],
                           storage_servers={0: ss})
    bands = collect_latency_bands(info)
    for key in ("grv", "grv_queue", "commit", "commit_batch_assembly",
                "commit_resolution", "commit_tlog_logging", "commit_reply",
                "resolver_resolve", "tlog_append", "tlog_durable",
                "storage_read", "storage_fetch", "tpu_dispatch",
                "tpu_inflight_depth"):
        assert key in bands, (key, sorted(bands))
        for stat in ("p50", "p95", "p99", "count", "mean"):
            assert stat in bands[key]
    assert bands["tpu_dispatch"]["count"] == 1
    assert bands["tpu_inflight_depth"]["mean"] == 2.0
    metrics = collect_cluster_metrics(info)
    assert metrics["CommitProxy"]["TxnCommitted"] == 9
    assert metrics["TpuBackend"]["DeviceBatches"] == 2
    assert metrics["TpuBackend"]["PipelineStalls"] == 3
    json.dumps({"latency_statistics": bands, "metrics": metrics})


def test_status_json_and_fdbcli_metrics_live():
    """Acceptance: `status json` exposes p50/p95/p99 bands for grv,
    commit sub-stages, and the resolver conflict check on a live sim
    cluster, and `fdbcli metrics` renders them."""
    from foundationdb_tpu.core import (DeterministicRandom,
                                       set_deterministic_random,
                                       set_event_loop)
    from foundationdb_tpu.rpc.sim import set_simulator
    from foundationdb_tpu.server.cluster import SimFdbCluster
    from foundationdb_tpu.server.interfaces import DatabaseConfiguration
    from foundationdb_tpu.tools.fdbcli import Cli
    set_deterministic_random(DeterministicRandom(7))
    try:
        c = SimFdbCluster(config=DatabaseConfiguration(),
                          n_workers=5, n_storage_workers=2)
        db = c.database()

        async def go():
            from foundationdb_tpu.core import FdbError
            for i in range(8):
                t = db.create_transaction()
                while True:
                    try:
                        await t.get(b"mk%02d" % i)
                        t.set(b"mk%02d" % i, b"v")
                        await t.commit()
                        break
                    except FdbError as e:
                        await t.on_error(e)
            return await db.cluster.get_status()

        status = c.run_until(c.loop.spawn(go()), timeout=120)
        json.dumps(status)
        bands = status["cluster"]["latency_statistics"]
        for key in ("grv", "commit", "commit_batch_assembly",
                    "commit_resolution", "commit_tlog_logging",
                    "resolver_resolve"):
            assert key in bands, sorted(bands)
            b = bands[key]
            assert b["count"] >= 1 and b["p50"] > 0
            assert b["p50"] <= b["p95"] <= b["p99"]
        metrics = status["cluster"]["metrics"]
        assert metrics["CommitProxy"]["TxnCommitted"] >= 8
        assert metrics["GrvProxy"]["TxnStarted"] >= 8
        assert "TLog" in status["cluster"]["metrics"]
        assert "logs" in status["cluster"]["roles"]

        cli = Cli.__new__(Cli)
        cli.loop, cli.db = c.loop, db
        out = cli.dispatch("metrics")
        assert "Latency bands" in out and "commit_resolution" in out
        assert "Counters:" in out and "TxnCommitted" in out
    finally:
        set_simulator(None)
        set_event_loop(None)


# ---------------------------------------------------------------------------
# CI lint: TraceEvent naming + schema drift
# ---------------------------------------------------------------------------

def test_trace_event_lint_clean():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "check_trace_events.py")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr


def test_trace_event_lint_catches_violations(tmp_path):
    (tmp_path / "a.py").write_text(
        'TraceEvent("badCase").detail("K", 1).log()\n'
        'TraceEvent("Dup").detail("A", 1).log()\n')
    (tmp_path / "b.py").write_text('TraceEvent("Dup").detail("B", 1).log()\n')
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        from check_trace_events import check
    finally:
        sys.path.pop(0)
    errors = check(str(tmp_path))
    assert any("badCase" in e for e in errors)
    assert any("Dup" in e and "different detail schemas" in e
               for e in errors)
