"""Real multi-process cluster: OS processes, real sockets, real disks.

VERDICT round-3 item 2 done-criteria: a pytest spawning >= 3 OS processes
on localhost (coordinator + roles), passing CycleTest, then killing one
process and observing recovery over real sockets.  Reference:
flow/Net2.actor.cpp:1400 (real reactor), fdbrpc/FlowTransport.actor.cpp:355,
:919 (wire handshake + token dispatch) — here core/scheduler.py reactor +
rpc/real_network.py + rpc/serde.py carrying the FULL role-interface surface
(the same Worker/CC/Coordination code that runs under simulation).
"""

import json
import os
import shutil
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASE_PORT = 47400
COORDS = f"127.0.0.1:{BASE_PORT}"
CONFIG = json.dumps({"n_storage": 2, "min_workers": 3})

NAMES = {"coord0": (BASE_PORT, "stateless"),
         "stateless1": (BASE_PORT + 1, "stateless"),
         "storage0": (BASE_PORT + 2, "storage"),
         "storage1": (BASE_PORT + 3, "storage")}


def _spawn(base, name, suffix=""):
    port, pclass = NAMES[name]
    cmd = [sys.executable, "-m", "foundationdb_tpu.server.fdbserver",
           "--port", str(port), "--coordinators", COORDS,
           "--datadir", os.path.join(base, name), "--class", pclass,
           "--config", CONFIG, "--name", name + suffix]
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.Popen(
        cmd, cwd=REPO, env=env,
        stdout=open(os.path.join(base, name + suffix + ".out"), "wb"),
        stderr=subprocess.STDOUT)


BOOT_TIMEOUT = 180.0   # wall-clock bound on boot + first recovery


@pytest.fixture
def real_cluster(tmp_path):
    base = str(tmp_path)
    procs = {n: _spawn(base, n) for n in NAMES}
    # Client world in THIS process: real loop + real network.
    from foundationdb_tpu.client.database import open_cluster
    from foundationdb_tpu.core.scheduler import set_event_loop
    from foundationdb_tpu.rpc.network import set_network
    time.sleep(2.5)
    dead = {n: p.poll() for n, p in procs.items() if p.poll() is not None}
    assert not dead, f"processes died at boot: {dead}"
    loop, db = open_cluster(COORDS)

    # Wait for ACTUAL availability (a committed probe) before handing the
    # cluster to a test: boot time is 4 subprocess interpreters importing
    # jax plus an election and a recovery, all in real time — on a loaded
    # machine that alone can eat a phase's entire wall-clock budget, so
    # phase timeouts must start AFTER availability (tier-1 deflake:
    # timing assumption, not a retry).  Process death during the wait
    # fails fast with the culprit instead of timing out blind.
    async def ready_probe():
        from foundationdb_tpu.core.scheduler import delay
        t = db.create_transaction()
        while True:
            crashed = {n: p.poll() for n, p in procs.items()
                       if p.poll() is not None}
            assert not crashed, f"processes died during boot: {crashed}"
            try:
                t.set(b"\x01boot-probe", b"up")
                await t.commit()
                return True
            except Exception as e:  # noqa: BLE001
                try:
                    await t.on_error(e)
                except Exception:   # noqa: BLE001 — non-retryable: fresh
                    t = db.create_transaction()
                    await delay(0.5)

    assert loop.run_until(loop.spawn(ready_probe()), timeout=BOOT_TIMEOUT)
    try:
        yield base, procs, loop, db
    finally:
        for p in procs.values():
            p.kill()
        for p in procs.values():
            p.wait()
        set_network(None)
        set_event_loop(None)


async def _commit_kv(db, k, v):
    t = db.create_transaction()
    while True:
        try:
            t.set(k, v)
            return await t.commit()
        except Exception as e:
            await t.on_error(e)


async def _read_key(db, k):
    t = db.create_transaction()
    while True:
        try:
            return await t.get(k)
        except Exception as e:
            await t.on_error(e)


def test_real_cluster_cycle_and_kill_recovery(real_cluster):
    base, procs, loop, db = real_cluster
    from foundationdb_tpu.testing.workloads import CycleWorkload

    async def cycle_phase():
        # minSwaps=1: progress is asserted below, so the workload must
        # guarantee at least one committed swap even when a loaded
        # machine stretches every commit past the wall-clock window.
        w = CycleWorkload(None, db, {"testDuration": 2.0, "actorCount": 2,
                                     "nodeCount": 12, "minSwaps": 1})
        await w.setup()
        await w.start()
        assert await w.check(), "cycle invariant violated"
        return w.metrics.get("swaps", 0)

    swaps = loop.run_until(loop.spawn(cycle_phase()), timeout=90)
    assert swaps > 0, "no swap transactions committed"

    # Kill the process hosting the TLog — a transaction-system member —
    # and restart it from its datadir (the fdbmonitor role).  The master
    # locks the disk-recovered old TLog generation and recovers into a new
    # epoch; committed data must survive.
    victim = next(n for n in NAMES
                  if os.path.isdir(os.path.join(base, n)) and
                  any(f.startswith("tlog-")
                      for f in os.listdir(os.path.join(base, n))))
    procs[victim].kill()
    procs[victim].wait()
    time.sleep(1.0)
    procs[victim] = _spawn(base, victim, suffix=".r2")

    async def post_kill():
        await _commit_kv(db, b"post-kill", b"recovered")
        assert await _read_key(db, b"post-kill") == b"recovered"
        w = CycleWorkload(None, db, {"nodeCount": 12})
        assert await w.check(), "cycle invariant violated after recovery"
        return "ok"

    assert loop.run_until(loop.spawn(post_kill()), timeout=120) == "ok"


def test_real_cluster_storage_restart_preserves_data(real_cluster):
    base, procs, loop, db = real_cluster

    async def phase1():
        for i in range(20):
            await _commit_kv(db, b"sk%03d" % i, b"sv%03d" % i)
        return "ok"

    assert loop.run_until(loop.spawn(phase1()), timeout=90) == "ok"

    # Kill the process hosting storage engines and restart it; its engine
    # files re-image the storage roles and reads must return committed data.
    victim = next(n for n in NAMES
                  if os.path.isdir(os.path.join(base, n)) and
                  any(f.startswith("storage-")
                      for f in os.listdir(os.path.join(base, n))))
    procs[victim].kill()
    procs[victim].wait()
    time.sleep(1.0)
    procs[victim] = _spawn(base, victim, suffix=".r2")

    async def phase2():
        assert await _read_key(db, b"sk007") == b"sv007"
        await _commit_kv(db, b"sk100", b"sv100")
        assert await _read_key(db, b"sk100") == b"sv100"
        return "ok"

    assert loop.run_until(loop.spawn(phase2()), timeout=120) == "ok"


def test_fdbcli_against_real_cluster(real_cluster):
    """The fdbcli ops surface (reference fdbcli/fdbcli.actor.cpp) drives a
    real multi-process cluster end-to-end: data commands, status,
    configuration, exclusion bookkeeping."""
    base, procs, loop, db = real_cluster
    from foundationdb_tpu.tools.fdbcli import Cli

    cli = Cli.__new__(Cli)
    cli.loop, cli.db = loop, db    # reuse the fixture's client world

    assert cli.dispatch("set cli-key cli-value") == "Committed"
    assert "cli-value" in cli.dispatch("get cli-key")
    assert cli.dispatch("set cli-key2 v2") == "Committed"
    out = cli.dispatch("getrange cli- cli0 10")
    assert "cli-key" in out and "cli-key2" in out and "(2 results)" in out
    assert cli.dispatch("clear cli-key") == "Committed"
    assert "not found" in cli.dispatch("get cli-key")
    out = cli.dispatch("status")
    assert "Recovery state" in out
    out = cli.dispatch("status json")
    assert '"cluster"' in out
    assert "ERROR" not in cli.dispatch("getconfiguration")
    assert "Excluded tags: none" in cli.dispatch("excluded")
    assert "unknown command" in cli.dispatch("bogus")
