"""Dynamic knobs (the config-DB analog): committed \xff/knobs/ overrides
apply to running workers live — no restart, no recovery.

Reference: fdbserver/ConfigNode.actor.cpp + ConfigBroadcaster.actor.cpp +
LocalConfiguration; here the store is ordinary transactional keys and
each worker watches the change marker (worker.py _knob_watch)."""

import pytest

from foundationdb_tpu.client.management import (get_knob_overrides,
                                                set_knob)
from foundationdb_tpu.core.knobs import server_knobs
from foundationdb_tpu.core.scheduler import delay
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration

from test_recovery import commit_kv, read_key, teardown  # noqa: F401


def test_dynamic_knob_applies_live_without_recovery(teardown):  # noqa: F811
    c = SimFdbCluster(config=DatabaseConfiguration(), n_workers=4,
                      n_storage_workers=2)
    db = c.database()
    original = server_knobs().DD_SHARD_SPLIT_BYTES

    async def go():
        await commit_kv(db, b"k", b"v")
        epoch_before = c.current_cc().db_info.epoch
        await set_knob(db, "DD_SHARD_SPLIT_BYTES", original * 2)
        # The worker watch applies it without any epoch change.
        for _ in range(100):
            if server_knobs().DD_SHARD_SPLIT_BYTES == original * 2:
                break
            await delay(0.2)
        assert server_knobs().DD_SHARD_SPLIT_BYTES == original * 2
        assert c.current_cc().db_info.epoch == epoch_before
        assert (await get_knob_overrides(db)
                )["server/DD_SHARD_SPLIT_BYTES"] == str(original * 2)
        # Overrides survive a recovery (they are committed data): kill
        # the master, wait for the next epoch, knob still applied.
        mp = c.process_of(c.current_cc().db_info.master)
        c.sim.kill_process(mp)
        for _ in range(200):
            cc = c.current_cc()
            if cc is not None and cc.db_info.epoch > epoch_before and \
                    cc.db_info.recovery_state in ("accepting_commits",
                                                  "fully_recovered"):
                break
            await delay(0.25)
        assert await read_key(db, b"k") == b"v"
        assert server_knobs().DD_SHARD_SPLIT_BYTES == original * 2
        # Unknown knob names are ignored (warning), never wedge the watch.
        await set_knob(db, "NO_SUCH_KNOB_EXISTS", 7)
        await set_knob(db, "DD_SHARD_SPLIT_BYTES", original * 3)
        for _ in range(100):
            if server_knobs().DD_SHARD_SPLIT_BYTES == original * 3:
                break
            await delay(0.2)
        assert server_knobs().DD_SHARD_SPLIT_BYTES == original * 3
        return True

    try:
        assert c.run_until(c.loop.spawn(go()), timeout=300)
    finally:
        server_knobs().DD_SHARD_SPLIT_BYTES = original
