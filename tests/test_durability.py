"""Durability tests: simulated power loss, DiskQueue recovery scans, and
storage-engine crash consistency.

Models reference behavior: AsyncFileNonDurable's lose/corrupt-on-power-
fail (fdbrpc/AsyncFileNonDurable.actor.h:511-552), DiskQueue checksum
recovery (fdbserver/DiskQueue.actor.cpp), KeyValueStoreMemory snapshot +
WAL recovery (fdbserver/KeyValueStoreMemory.actor.cpp)."""

import pytest

from foundationdb_tpu.core import DeterministicRandom, set_deterministic_random
from foundationdb_tpu.server.disk_queue import DiskQueue
from foundationdb_tpu.server.kvstore import KVStoreMemory, open_kv_store
from foundationdb_tpu.server.sim_fs import SimFileSystem


@pytest.fixture()
def fs(loop):
    set_deterministic_random(DeterministicRandom(5))
    return SimFileSystem()


def run(loop, coro):
    return loop.run_until(loop.spawn(coro), timeout=600)


def test_disk_queue_roundtrip(loop, fs):
    async def go():
        q = DiskQueue(fs.open("q"))
        s1 = q.push(b"alpha")
        s2 = q.push(b"beta")
        await q.commit()
        q2 = DiskQueue(fs.open("q"))
        recs = await q2.recover()
        assert recs == [(s1, b"alpha"), (s2, b"beta")]
        # pop is durable via the next append's header; pop(s1) trims
        # records <= s1 only, so beta survives.
        q2.pop(s1)
        q2.push(b"gamma")
        await q2.commit()
        q3 = DiskQueue(fs.open("q"))
        recs = await q3.recover()
        assert [p for _s, p in recs] == [b"beta", b"gamma"]

    run(loop, go())


def test_disk_queue_unsynced_tail_lost(loop, fs):
    async def go():
        q = DiskQueue(fs.open("q"))
        q.push(b"durable1")
        await q.commit()
        q.push(b"never-synced")
        blob = b"".join(q._pending)
        q._pending = []
        await q.file.write(q._write_offset, blob)   # written, NOT synced
        fs.power_fail_all()
        q2 = DiskQueue(fs.open("q"))
        recs = await q2.recover()
        # The synced prefix always survives; the un-synced tail may or may
        # not — but NEVER a corrupt record (checksum gate).
        assert [p for _s, p in recs][:1] == [b"durable1"]
        assert all(p in (b"durable1", b"never-synced") for _s, p in recs)

    run(loop, go())


def test_kvstore_commit_survives_power_fail(loop, fs):
    async def go():
        kv = open_kv_store("memory", fs, "sq/ss0")
        await kv.recover()
        kv.set(b"a", b"1")
        kv.set(b"b", b"2")
        await kv.commit()                 # acked
        kv.set(b"c", b"3")                # never committed
        fs.power_fail_all()
        kv2 = open_kv_store("memory", fs, "sq/ss0")
        await kv2.recover()
        assert kv2.read_value(b"a") == b"1"
        assert kv2.read_value(b"b") == b"2"
        assert kv2.read_value(b"c") is None
        assert kv2.read_range(b"", b"\xff") == [(b"a", b"1"), (b"b", b"2")]

    run(loop, go())


def test_kvstore_snapshot_and_wal_replay(loop, fs):
    async def go():
        kv = KVStoreMemory(fs, "snap")
        kv.SNAPSHOT_EVERY_BYTES = 64      # force frequent snapshots
        await kv.recover()
        for i in range(20):
            kv.set(b"k%03d" % i, b"v%03d" % i)
            await kv.commit()
        kv.clear(b"k000", b"k005")
        await kv.commit()
        kv2 = KVStoreMemory(fs, "snap")
        await kv2.recover()
        data = kv2.read_range(b"", b"\xff")
        assert [k for k, _ in data] == [b"k%03d" % i for i in range(5, 20)]

    run(loop, go())


def test_kvstore_randomized_crash_consistency(loop, fs):
    """Acked commits ALWAYS survive; the un-acked tail vanishes atomically
    (the ConflictRange-style model cross-check, applied to durability)."""
    async def go():
        import random
        rng = random.Random(1234)
        model = {}
        kv = open_kv_store("memory", fs, "crash")
        await kv.recover()
        for round_no in range(30):
            staged = {}
            for _ in range(rng.randrange(1, 6)):
                if rng.random() < 0.75 or not model:
                    k = b"key%02d" % rng.randrange(30)
                    v = b"val%06d" % rng.randrange(1 << 20)
                    kv.set(k, v)
                    staged[k] = v
                else:
                    lo = rng.randrange(30)
                    hi = min(30, lo + rng.randrange(1, 6))
                    b, e = b"key%02d" % lo, b"key%02d" % hi
                    kv.clear(b, e)
                    for k in [k for k in model if b <= k < e]:
                        staged[k] = None
            await kv.commit()             # acked: must survive any crash
            for k, v in staged.items():
                if v is None:
                    model.pop(k, None)
                else:
                    model[k] = v
            if rng.random() < 0.4:
                fs.power_fail_all()       # crash + reboot
                kv = open_kv_store("memory", fs, "crash")
                await kv.recover()
                actual = dict(kv.read_range(b"", b"\xff"))
                assert actual == model, (
                    f"round {round_no}: {actual} != {model}")
        fs.power_fail_all()
        kv = open_kv_store("memory", fs, "crash")
        await kv.recover()
        assert dict(kv.read_range(b"", b"\xff")) == model

    run(loop, go())
