"""The REAL resolve step sharded over an 8-virtual-device CPU mesh.

VERDICT round-4 item 2 done-criteria: the full TpuConflictSet per-batch
program (too-old, base+delta history query, intra-batch fixpoint, clipped
insert, verdict codes) runs under shard_map with the history bits
max-combined over mesh axis "kr", and its verdicts are bit-identical to
the CPU oracle on randomized batches — point AND general ranges, across
merges, floor advances, rebases, and overflow surfacing.

Reference semantics: the proxy min-combining per-key-range resolver
verdicts, CommitProxyServer.actor.cpp:800-806.
"""

import numpy as np
import pytest

from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet
from foundationdb_tpu.core import DeterministicRandom
from foundationdb_tpu.parallel.sharded_resolver import ShardedTpuConflictSet
from foundationdb_tpu.parallel.sharded_window import make_conflict_mesh
from foundationdb_tpu.txn import CommitResult, CommitTransactionRef, KeyRange

from test_conflict_oracle import make_domain, random_txn
from test_conflict_tpu import random_point_txn


@pytest.fixture(scope="module")
def mesh():
    return make_conflict_mesh(n_devices=8)   # kr=4, q=2


def test_mesh_shape(mesh):
    assert mesh.shape["kr"] >= 2, "need real key-range sharding to test"


@pytest.mark.slow          # heavyweight shapes: many XLA compiles; the
@pytest.mark.parametrize("seed", [31, 32])   # light parity proofs below
def test_sharded_matches_oracle_general(mesh, seed):  # stay in tier-1
    """Random GENERAL ranges (spanning shard splits) through the sharded
    step vs the oracle; merges every 3 batches; floor advances+rebases."""
    rng = DeterministicRandom(seed)
    domain = make_domain()
    oracle = OracleConflictSet(0)
    cs = ShardedTpuConflictSet(mesh, 0, capacity=1 << 10,
                               delta_capacity=1 << 9,
                               gc_interval_batches=3)
    now = 0
    for _ in range(16):
        now += rng.random_int(1, 2_000_000)
        batch = [random_txn(rng, domain, now, 4_000_000)
                 for _ in range(rng.random_int(1, 10))]
        new_oldest = now - 5_000_000 if rng.coinflip() else None
        got = cs.resolve(batch, now, new_oldest)
        want = oracle.resolve(batch, now, new_oldest)
        assert got == want, f"divergence at now={now}"
    assert cs.version_base > 0          # a rebase actually happened
    assert sum(cs.shard_sizes()) >= mesh.shape["kr"]


@pytest.mark.slow
@pytest.mark.parametrize("seed", [41, 42])
def test_sharded_matches_oracle_points(mesh, seed):
    """Hot point-key batches (deep intra-batch chains) through the sharded
    sort-free path; every key owned by exactly one shard."""
    rng = DeterministicRandom(seed)
    oracle = OracleConflictSet(0)
    cs = ShardedTpuConflictSet(mesh, 0, capacity=1 << 10,
                               delta_capacity=1 << 9,
                               gc_interval_batches=4)
    now = 0
    for _ in range(12):
        now += rng.random_int(1, 2_000_000)
        batch = [random_point_txn(rng, 12, now, 4_000_000)
                 for _ in range(rng.random_int(1, 24))]
        new_oldest = now - 5_000_000 if rng.coinflip() else None
        got = cs.resolve(batch, now, new_oldest)
        want = oracle.resolve(batch, now, new_oldest)
        assert got == want, f"point divergence at now={now}"


def test_sharded_matches_single_device(mesh):
    """Shard count must be invisible: the sharded backend and the
    single-device backend agree verdict-for-verdict on the same stream
    (keys spread across the whole digest space so every shard owns some)."""
    rng = DeterministicRandom(5)
    single = TpuConflictSet(0, capacity=1 << 12)
    sharded = ShardedTpuConflictSet(mesh, 0, capacity=1 << 10,
                                    delta_capacity=1 << 9,
                                    gc_interval_batches=3)
    now = 0
    for i in range(10):
        now += 1_000_000
        batch = []
        for _ in range(8):
            # Keys with random leading byte -> uniform across shards.
            k = bytes([rng.random_int(0, 255)]) + b"k%04d" % rng.random_int(
                0, 50)
            tr = CommitTransactionRef(
                read_snapshot=max(now - rng.random_int(0, 3_000_000), 0))
            if rng.coinflip():
                tr.read_conflict_ranges.append(KeyRange(k, k + b"\x00"))
            tr.write_conflict_ranges.append(KeyRange(k, k + b"\x00"))
            batch.append(tr)
        new_oldest = now - 5_000_000
        got = sharded.resolve(batch, now, new_oldest)
        want = single.resolve(batch, now, new_oldest)
        assert got == want, f"sharded != single at batch {i}"


def test_sharded_ranges_straddle_splits(mesh):
    """A write range spanning MULTIPLE shards' key ranges conflicts with
    reads landing in each of them — the clipped insert must cover every
    shard's portion, and the history combine must surface hits found on
    any shard."""
    cs = ShardedTpuConflictSet(mesh, 0, capacity=1 << 10,
                               delta_capacity=1 << 9)
    # [\x10, \xf0) spans all 4 shard splits (which are at lane-0 values
    # 0x40/0x80/0xc0...).
    w = CommitTransactionRef(
        write_conflict_ranges=[KeyRange(b"\x10", b"\xf0")])
    assert cs.resolve([w], 100) == [CommitResult.COMMITTED]
    readers = []
    for lead in (0x11, 0x55, 0x99, 0xdd):
        readers.append(CommitTransactionRef(
            read_snapshot=50,
            read_conflict_ranges=[KeyRange(bytes([lead]),
                                           bytes([lead]) + b"\x00")]))
    # One reader entirely outside the written range commits.
    readers.append(CommitTransactionRef(
        read_snapshot=50,
        read_conflict_ranges=[KeyRange(b"\xf5", b"\xf6")]))
    got = cs.resolve(readers, 200)
    assert got == [CommitResult.CONFLICT] * 4 + [CommitResult.COMMITTED]


def test_sharded_overflow_flag_raises(mesh):
    """Pinned floor + tiny per-shard capacity: the sticky overflow flag of
    ANY shard must surface at wait() (flags pmax-combined)."""
    cs = ShardedTpuConflictSet(mesh, 0, capacity=256, delta_capacity=256)
    now = 0
    with pytest.raises(Exception, match="capacity exceeded"):
        for i in range(60):
            now += 1_000
            # All keys share a leading byte -> ONE shard takes every insert.
            txns = [CommitTransactionRef(write_conflict_ranges=[
                KeyRange(b"\x01%05d" % (i * 10 + j),
                         b"\x01%05d\x00" % (i * 10 + j))])
                for j in range(10)]
            cs.resolve(txns, now)      # floor never advances


def test_supervised_sharded_degrades_and_repromotes(mesh):
    """The supervision layer over the MESH-SHARDED backend: device killed
    mid-stream -> exact CPU fallback; promotion rebuilds the whole sharded
    window from the mirror (digest-split state re-created across shards)."""
    from foundationdb_tpu.conflict.supervisor import BackendHealthMonitor
    rng = DeterministicRandom(55)
    sup = ShardedTpuConflictSet.supervised(
        mesh, capacity=1 << 10, delta_capacity=1 << 9,
        monitor=BackendHealthMonitor(reprobe_interval_s=1e9))
    oracle = OracleConflictSet(0)
    now = 0
    for i in range(9):
        now += 1_000_000
        if i == 3:
            sup.force_device_error = "timeout"      # kill mid-stream
        if i == 6:
            sup.force_device_error = None           # device recovers
            sup.monitor.tripped_at = -1e12
        batch = []
        for _ in range(6):
            # Random leading byte -> keys land on every shard.
            k = bytes([rng.random_int(0, 255)]) + b"k%03d" % rng.random_int(
                0, 40)
            tr = CommitTransactionRef(
                read_snapshot=max(now - rng.random_int(0, 3_000_000), 0))
            if rng.coinflip():
                tr.read_conflict_ranges.append(KeyRange(k, k + b"\x00"))
            tr.write_conflict_ranges.append(KeyRange(k, k + b"\x00"))
            batch.append(tr)
        got = sup.resolve(batch, now, now - 5_000_000)
        want = oracle.resolve(batch, now, now - 5_000_000)
        assert got == want, f"supervised-sharded divergence at batch {i}"
    st = sup.status()
    assert st["degrades"] == 1 and st["promotions"] == 1
    assert not st["degraded"]
    assert isinstance(sup.device, ShardedTpuConflictSet)
    assert sum(sup.device.shard_sizes()) >= 1


def test_custom_equi_depth_splits_match_oracle(mesh):
    """splits_from_sample cuts inside a shared key prefix (every key
    starts b"k000...") so the window actually spreads across shards —
    verdicts stay bit-identical to the oracle and more than one shard
    holds boundaries.  (The default lane-0 splits put this workload
    entirely on one shard.)"""
    from foundationdb_tpu.ops.digest import encode_keys
    from foundationdb_tpu.parallel.sharded_window import splits_from_sample

    def key(i):
        return b"k%014d" % (i * 37 % 500)

    sample = encode_keys([key(i) for i in range(500)])
    splits = splits_from_sample(sample, mesh.shape["kr"])
    assert (splits[1:] != splits[:-1]).any(axis=1).all(), "degenerate cuts"
    rng = DeterministicRandom(77)
    oracle = OracleConflictSet(0)
    cs = ShardedTpuConflictSet(mesh, 0, capacity=1 << 10,
                               delta_capacity=1 << 9,
                               gc_interval_batches=3, splits=splits)
    now = 0
    for _ in range(8):
        now += 1_000_000
        batch = []
        for _t in range(rng.random_int(1, 16)):
            k = key(rng.random_int(0, 499))
            kr = key(rng.random_int(0, 499))
            batch.append(CommitTransactionRef(
                read_snapshot=max(now - rng.random_int(0, 3_000_000), 0),
                read_conflict_ranges=[KeyRange(kr, kr + b"\x00")],
                write_conflict_ranges=[KeyRange(k, k + b"\x00")]))
        new_oldest = now - 5_000_000
        got = cs.resolve(batch, now, new_oldest)
        want = oracle.resolve(batch, now, new_oldest)
        assert got == want, f"divergence at now={now}"
    sizes = cs.shard_sizes()
    assert sum(1 for s in sizes if s > 1) >= 2, (
        f"window not actually spread across shards: {sizes}")
