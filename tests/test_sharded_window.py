"""Sharded conflict window (parallel/sharded_window.py) parity tests.

Runs on the 8-virtual-CPU-device mesh from conftest; checks that the
kr-sharded window with psum OR-reduce gives bit-identical conflict decisions
to the single-device window kernels for randomized batches."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from foundationdb_tpu.conflict.window import (make_window_state, window_insert,
                                              window_query)
from foundationdb_tpu.ops.digest import KEY_LANES, encode_keys
from foundationdb_tpu.parallel import ShardedWindow, make_conflict_mesh


def _rand_key(rng, max_len=12):
    return bytes(rng.integers(0, 256, size=int(rng.integers(1, max_len)),
                              dtype=np.uint8))


def _rand_ranges(rng, n):
    begins, ends = [], []
    for _ in range(n):
        a, b = _rand_key(rng), _rand_key(rng)
        if a == b:
            b = a + b"\x00"
        begins.append(min(a, b))
        ends.append(max(a, b))
    return begins, ends


def test_sharded_matches_single_device():
    rng = np.random.default_rng(7)
    mesh = make_conflict_mesh()
    assert mesh.shape["kr"] * mesh.shape["q"] == len(jax.devices())
    cap = 1 << 12
    sw = ShardedWindow(mesh, capacity=cap)
    ref = make_window_state(cap, 0)

    R = 64  # divisible by q axis
    W = 32
    version = 0
    import jax.numpy as jnp
    for batch in range(6):
        version += 100
        rb, re = _rand_ranges(rng, R)
        wb, we = _rand_ranges(rng, W)
        qb = encode_keys(rb)
        qe = encode_keys(re, round_up=True)
        snap = rng.integers(0, version, size=R).astype(np.int32)
        qvalid = np.ones((R,), dtype=bool)
        wbe = encode_keys(wb)
        wee = encode_keys(we, round_up=True)
        wvalid = np.ones((W,), dtype=bool)

        bits, ovf = sw.resolve_step(qb, qe, snap, qvalid,
                                    wbe, wee, wvalid, version)
        assert not bool(ovf)

        ref_bits = window_query(ref.bk, ref.bv, jnp.asarray(qb),
                                jnp.asarray(qe), jnp.asarray(snap),
                                jnp.asarray(qvalid))
        ref, ref_ovf = window_insert(ref, jnp.asarray(wbe), jnp.asarray(wee),
                                     jnp.asarray(wvalid), jnp.int32(version))
        assert not bool(ref_ovf)
        np.testing.assert_array_equal(np.asarray(bits), np.asarray(ref_bits))


def test_sharded_gc_preserves_decisions():
    rng = np.random.default_rng(11)
    mesh = make_conflict_mesh()
    sw = ShardedWindow(mesh, capacity=1 << 10)
    import jax.numpy as jnp

    W = 16
    R = 32
    for v in (100, 200, 300):
        wb, we = _rand_ranges(rng, W)
        sw.resolve_step(np.zeros((KEY_LANES, R), np.uint32),
                        np.zeros((KEY_LANES, R), np.uint32),
                        np.zeros((R,), np.int32), np.zeros((R,), bool),
                        encode_keys(wb), encode_keys(we, round_up=True),
                        np.ones((W,), bool), v)
    rb, re = _rand_ranges(rng, R)
    qb, qe = encode_keys(rb), encode_keys(re, round_up=True)
    snap = np.full((R,), 150, dtype=np.int32)
    valid = np.ones((R,), bool)
    noW = np.zeros((KEY_LANES, W), np.uint32)
    noV = np.zeros((W,), bool)
    before, _ = sw.resolve_step(qb, qe, snap, valid, noW, noW, noV, 400)
    sw.gc(oldest_rel=150)  # floor below every live decision boundary we query
    after, _ = sw.resolve_step(qb, qe, snap, valid, noW, noW, noV, 401)
    np.testing.assert_array_equal(np.asarray(before), np.asarray(after))
