"""Restarting test: quorum migration survives a whole-cluster restart.

Phase 1 boots a real-process cluster whose quorum is coordinator A, runs
changeQuorum onto a standby coordinator B (booted with --coordination),
and waits until every process's fdb.cluster file has been rewritten by
the forward replies.  Then every process is SIGKILLed and phase 2
restarts all of them EXCEPT the old coordinator — recovery must elect
and read the coordinated state purely through the new quorum, with the
old one gone for good.

Reference: fdbclient/ManagementAPI.actor.cpp changeQuorum (cluster-file
rewrite on LeaderInfo.forward) + tests/restarting/ two-phase specs.
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASE_PORT = 47620
OLD_COORDS = f"127.0.0.1:{BASE_PORT}"
NEW_COORDS = f"127.0.0.1:{BASE_PORT + 4}"
CONFIG = json.dumps({"n_storage": 2, "min_workers": 3})

NAMES = {"coord0": (BASE_PORT, "stateless", False),
         "stateless1": (BASE_PORT + 1, "stateless", False),
         "storage0": (BASE_PORT + 2, "storage", False),
         "storage1": (BASE_PORT + 3, "storage", False),
         "newcoord": (BASE_PORT + 4, "stateless", True)}


def _spawn(base, name, generation):
    port, pclass, coordination = NAMES[name]
    cmd = [sys.executable, "-m", "foundationdb_tpu.server.fdbserver",
           "--port", str(port), "--coordinators", OLD_COORDS,
           "--datadir", os.path.join(base, name), "--class", pclass,
           "--config", CONFIG, "--name", f"{name}.g{generation}"]
    if coordination:
        cmd.append("--coordination")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.Popen(
        cmd, cwd=REPO, env=env,
        stdout=open(os.path.join(base, f"{name}.g{generation}.out"), "wb"),
        stderr=subprocess.STDOUT)


def _client(spec):
    from foundationdb_tpu.client.database import open_cluster
    return open_cluster(spec)


def _teardown_client():
    from foundationdb_tpu.core.scheduler import set_event_loop
    from foundationdb_tpu.rpc.network import get_network, set_network
    try:
        get_network().close()
    except Exception:
        pass
    set_network(None)
    set_event_loop(None)


async def _commit_kv(db, k, v):
    t = db.create_transaction()
    while True:
        try:
            t.set(k, v)
            return await t.commit()
        except Exception as e:
            await t.on_error(e)


async def _read_key(db, k):
    t = db.create_transaction()
    while True:
        try:
            return await t.get(k)
        except Exception as e:
            await t.on_error(e)


def _cluster_files(base, names):
    out = {}
    for n in names:
        path = os.path.join(base, n, "fdb.cluster")
        try:
            with open(path) as f:
                out[n] = f.read().strip()
        except OSError:
            out[n] = None
    return out


def test_quorum_migration_survives_restart(tmp_path):
    base = str(tmp_path)
    procs = {n: _spawn(base, n, 1) for n in NAMES}
    try:
        time.sleep(2.5)
        dead = {n: p.poll() for n, p in procs.items()
                if p.poll() is not None}
        assert not dead, f"phase-1 processes died at boot: {dead}"
        loop, db = _client(OLD_COORDS)

        async def phase1():
            for i in range(10):
                await _commit_kv(db, b"q/%03d" % i, b"v%03d" % i)
            from foundationdb_tpu.client.management import \
                change_coordinators
            await change_coordinators(db, NEW_COORDS)
            return True

        # Generous run_until budgets (here and in phase 2), matching the
        # cluster-file wait below: on a single-core box late in a full
        # tier-1 run, the five server processes and this client share one
        # starved core and wall-clock progress is many times slower than
        # standalone (~7 s); the phases themselves are verified fast.
        assert loop.run_until(loop.spawn(phase1()), timeout=180)
        _teardown_client()

        # Every process learns the move via forward replies and rewrites
        # its fdb.cluster; wait for all of them (incl. the old
        # coordinator's own worker half).  Generous deadline: under a
        # full-suite run the five server processes share one starved
        # core and wall-clock progress is ~5x slower than standalone.
        deadline = time.time() + 150
        while time.time() < deadline:
            files = _cluster_files(base, NAMES)
            if all(v == NEW_COORDS for v in files.values()):
                break
            time.sleep(1.0)
        else:
            raise AssertionError(
                f"cluster files never converged: {_cluster_files(base, NAMES)}")

        # SaveAndKill, then phase 2 WITHOUT the old coordinator.
        for p in procs.values():
            p.kill()
        for p in procs.values():
            p.wait()
        time.sleep(1.0)

        survivors = [n for n in NAMES if n != "coord0"]
        procs = {n: _spawn(base, n, 2) for n in survivors}
        time.sleep(2.5)
        dead = {n: p.poll() for n, p in procs.items()
                if p.poll() is not None}
        assert not dead, f"phase-2 processes died at boot: {dead}"
        loop, db = _client(NEW_COORDS)

        async def phase2():
            for i in range(10):
                assert await _read_key(db, b"q/%03d" % i) == b"v%03d" % i
            await _commit_kv(db, b"post-migrate", b"alive")
            assert await _read_key(db, b"post-migrate") == b"alive"
            return True

        assert loop.run_until(loop.spawn(phase2()), timeout=300)
        _teardown_client()
    finally:
        for p in procs.values():
            p.kill()
        for p in procs.values():
            p.wait()
