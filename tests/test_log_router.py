"""LogRouter + remote-TLog feeder: the cross-region replication plane.

Reference: fdbserver/LogRouter.actor.cpp:308 pullAsyncData; remote tlog
sets in TagPartitionedLogSystem.actor.cpp.  Data pushed to primary TLogs
under remote twin tags flows primary TLog -> LogRouter -> remote TLog ->
remote storage pull, with pops propagating back so every tier trims.
"""

import pytest

from foundationdb_tpu.core.futures import Promise
from foundationdb_tpu.server.commit_proxy import LogSystemClient
from foundationdb_tpu.server.disk_queue import DiskQueue
from foundationdb_tpu.server.interfaces import (TLogCommitRequest,
                                                TLogPeekRequest,
                                                TLogPopRequest)
from foundationdb_tpu.server.log_router import (REMOTE_TAG_OFFSET,
                                                LogRouter, is_remote_tag,
                                                remote_tlog_feeder,
                                                twin_tag)
from foundationdb_tpu.server.sim_fs import SimFileSystem
from foundationdb_tpu.server.tlog import TLog
from foundationdb_tpu.txn.types import Mutation, MutationType

from test_recovery import teardown  # noqa: F401


def _world():
    from foundationdb_tpu.core import EventLoop, set_event_loop
    from foundationdb_tpu.rpc.sim import Simulator, set_simulator
    lp = EventLoop(sim=True)
    set_event_loop(lp)
    sim = Simulator()
    set_simulator(sim)
    return lp, sim


def test_twin_tag_involution():
    assert twin_tag(3) == REMOTE_TAG_OFFSET + 3
    assert twin_tag(twin_tag(3)) == 3
    assert is_remote_tag(twin_tag(0)) and not is_remote_tag(0)


async def _commit(tlog, version, prev, messages):
    p = Promise()
    await tlog._commit(TLogCommitRequest(
        version=version, prev_version=prev, known_committed_version=prev,
        messages=messages, reply=p))
    return await p.get_future()


def test_router_feeds_remote_tlog(teardown):  # noqa: F811
    """Twin-tagged commits on the primary TLog arrive at the remote TLog
    (contiguous version chain, durable), and pops flow back to trim the
    router buffer."""
    lp, sim = _world()
    fs = SimFileSystem()

    primary = TLog("plog0", disk_queue=DiskQueue(fs.open("p.wal")))
    pproc = sim.new_process(name="plog0")
    primary.run(pproc)
    primary_ls = LogSystemClient([primary.interface])

    router = LogRouter("router0", primary_ls)
    rproc = sim.new_process(name="router0")
    router.run(rproc)
    router_ls = LogSystemClient([router.interface])

    remote = TLog("rlog0", disk_queue=DiskQueue(fs.open("r.wal")))
    mproc = sim.new_process(name="rlog0")
    remote.run(mproc)
    t0r, t1r = twin_tag(0), twin_tag(1)
    mproc.spawn(remote_tlog_feeder(remote, router_ls, [t0r, t1r]),
                "rlog0.feeder")

    async def go():
        v = 0
        # Commit 30 versions; tags 0/1 get primary copies AND twin copies
        # (what the proxy's region routing produces); version 17 carries
        # only tag 0 so the feeder must align cross-tag frontiers.
        for i in range(30):
            prev, v = v, v + 1
            msgs = {0: [Mutation(MutationType.SetValue, b"a%03d" % i,
                                 b"x" * 50)],
                    t0r: [Mutation(MutationType.SetValue, b"a%03d" % i,
                                   b"x" * 50)]}
            if i != 17:
                msgs[1] = [Mutation(MutationType.SetValue, b"b%03d" % i,
                                    b"y")]
                msgs[t1r] = [Mutation(MutationType.SetValue, b"b%03d" % i,
                                      b"y")]
            await _commit(primary, v, prev, msgs)
        # Remote converges to the full frontier.
        await remote.durable_version.when_at_least(30)
        p = Promise()
        await remote._peek(TLogPeekRequest(tag=t0r, begin=1, reply=p))
        reply = await p.get_future()
        versions = [vv for vv, _m in reply.messages]
        assert versions == list(range(1, 31)), versions
        assert reply.messages[0][1][0].param1 == b"a000"
        p2 = Promise()
        await remote._peek(TLogPeekRequest(tag=t1r, begin=1, reply=p2))
        got1 = [vv for vv, _m in (await p2.get_future()).messages]
        assert 18 not in got1 and len(got1) == 29
        # Pops track the REMOTE REPLICAS' applied points: until a replica
        # pops the remote TLog, the router (and primary) must RETAIN the
        # twin backlog — it is the recovery source for a lagging replica
        # across generation changes.
        from foundationdb_tpu.core.scheduler import delay as _delay
        await _delay(2.0)
        assert router.buffered_bytes > 0
        assert primary.poppedtags.get(t0r, 0) == 0
        # Remote storage-style consumption pops the remote TLog; the
        # feeder forwards those applied points router-ward, which trims
        # the router buffer and the primary's twin tags.
        remote._pop(TLogPopRequest(tag=t0r, to=30))
        remote._pop(TLogPopRequest(tag=t1r, to=30))
        for _ in range(100):
            if router.buffered_bytes == 0 and \
                    primary.poppedtags.get(t0r, 0) >= 29:
                break
            await _delay(0.1)
        assert router.buffered_bytes == 0
        assert primary.poppedtags.get(t0r, 0) >= 29
        return True

    assert lp.run_until(lp.spawn(go()), timeout=300)


def test_remote_tlog_lockable_for_failover(teardown):  # noqa: F811
    """A region failover locks the remote TLog like an old generation:
    end_version reflects the contiguous fed frontier, and peeks after the
    lock still serve everything (the recovery data path)."""
    lp, sim = _world()
    fs = SimFileSystem()
    primary = TLog("plog0", disk_queue=DiskQueue(fs.open("p.wal")))
    primary.run(sim.new_process(name="plog0"))
    router = LogRouter("router0", LogSystemClient([primary.interface]))
    router.run(sim.new_process(name="router0"))
    remote = TLog("rlog0", disk_queue=DiskQueue(fs.open("r.wal")))
    rproc = sim.new_process(name="rlog0")
    remote.run(rproc)
    tr = twin_tag(0)
    rproc.spawn(remote_tlog_feeder(
        remote, LogSystemClient([router.interface]), [tr]), "feeder")

    async def go():
        v = 0
        for i in range(10):
            prev, v = v, v + 1
            await _commit(primary, v, prev, {
                tr: [Mutation(MutationType.SetValue, b"k%d" % i, b"v")]})
        await remote.durable_version.when_at_least(10)
        from foundationdb_tpu.server.interfaces import TLogLockRequest
        p = Promise()
        await remote._lock(TLogLockRequest(epoch=2, reply=p))
        reply = await p.get_future()
        assert reply.end_version >= 10
        assert remote.stopped
        p2 = Promise()
        await remote._peek(TLogPeekRequest(tag=tr, begin=1, reply=p2))
        assert len((await p2.get_future()).messages) == 10
        return True

    assert lp.run_until(lp.spawn(go()), timeout=300)
