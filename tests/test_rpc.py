"""RPC + simulator tests: delivery, latency, clog, partition, kill/reboot.

Models the reference's fdbrpc behavior observable from above: typed
request/reply, broken_promise on process death, clog delays, partitions as
connection failures (SURVEY.md §2.2)."""

import pytest

from foundationdb_tpu.core import FdbError, wait_any
from foundationdb_tpu.rpc import (RequestStream, SimProcess, Simulator,
                                  set_simulator)
from foundationdb_tpu.rpc.endpoint import RequestStreamStub
from foundationdb_tpu.rpc.failure_monitor import (wait_failure_client,
                                                  wait_failure_server)


class EchoRequest:
    def __init__(self, x):
        self.x = x


@pytest.fixture()
def sim(loop):
    s = Simulator()
    set_simulator(s)
    yield s
    set_simulator(None)


def start_echo_server(p: SimProcess) -> RequestStream:
    rs = RequestStream("echo")
    p.register(rs)

    async def serve():
        async for req in rs.queue:
            req.reply.send(req.x * 2)

    p.spawn(serve(), "echo")
    return rs


def test_request_reply(loop, sim):
    server = sim.new_process(name="server")
    client = sim.new_process(name="client")
    rs = start_echo_server(server)

    async def go():
        stub = RequestStreamStub(rs.endpoint)
        return await stub.get_reply(EchoRequest(21), client.address)

    assert loop.run_until(loop.spawn(go()), timeout=5) == 42
    assert loop.now() > 0  # latency took virtual time


def test_dead_process_breaks_promise(loop, sim):
    server = sim.new_process(name="server")
    client = sim.new_process(name="client")
    rs = start_echo_server(server)
    ep = rs.endpoint
    sim.kill_process(server)

    async def go():
        with pytest.raises(FdbError) as ei:
            await RequestStreamStub(ep).get_reply(EchoRequest(1),
                                                  client.address)
        assert ei.value.name == "broken_promise"

    loop.run_until(loop.spawn(go()), timeout=5)


def test_reboot_invalidates_old_endpoints(loop, sim):
    server = sim.new_process(name="server")
    client = sim.new_process(name="client")
    rs = start_echo_server(server)
    old_ep = rs.endpoint
    sim.reboot_process(server)
    new_rs = start_echo_server(server)  # re-register after reboot

    async def go():
        with pytest.raises(FdbError):
            await RequestStreamStub(old_ep).get_reply(EchoRequest(1),
                                                      client.address)
        # New endpoint works.
        return await RequestStreamStub(new_rs.endpoint).get_reply(
            EchoRequest(5), client.address)

    assert loop.run_until(loop.spawn(go()), timeout=5) == 10


def test_clog_delays_delivery(loop, sim):
    server = sim.new_process(name="server")
    client = sim.new_process(name="client")
    rs = start_echo_server(server)
    sim.clog_pair(client, server, 2.0)

    async def go():
        t0 = loop.now()
        r = await RequestStreamStub(rs.endpoint).get_reply(
            EchoRequest(3), client.address)
        return r, loop.now() - t0

    r, dt = loop.run_until(loop.spawn(go()), timeout=10)
    assert r == 6
    assert dt >= 2.0  # waited out the clog


def test_partition_fails_connection(loop, sim):
    server = sim.new_process(name="server")
    client = sim.new_process(name="client")
    rs = start_echo_server(server)
    sim.partition(client, server)

    async def go():
        stub = RequestStreamStub(rs.endpoint)
        r = await stub.try_get_reply(EchoRequest(1))  # note: from server ip
        with pytest.raises(FdbError):
            await stub.get_reply(EchoRequest(1), client.address)
        sim.heal()
        return await stub.get_reply(EchoRequest(4), client.address)

    assert loop.run_until(loop.spawn(go()), timeout=10) == 8


def test_wait_failure_detects_death(loop, sim):
    server = sim.new_process(name="server")
    rs = RequestStream("waitFailure")
    server.register(rs)
    server.spawn(wait_failure_server(rs), "wfServer")
    ep = rs.endpoint

    async def go():
        watcher = loop.spawn(wait_failure_client(ep, timeout=0.5))
        # Server alive: watcher must not fire within a few heartbeats.
        idx, _ = await wait_any([watcher, loop.delay(2.0)])
        assert idx == 1, "waitFailure fired on a live server"
        sim.kill_process(server)
        await watcher  # now it must return

    loop.run_until(loop.spawn(go()), timeout=30)


def test_kill_machine_kills_all(loop, sim):
    p1 = sim.new_process(machineid="mA", name="p1")
    p2 = sim.new_process(machineid="mA", name="p2")
    p3 = sim.new_process(machineid="mB", name="p3")
    sim.kill_machine("mA")
    assert not p1.alive and not p2.alive and p3.alive


def test_determinism_same_seed_same_timings(loop, sim):
    # Two runs with the same seed produce identical reply timestamps.
    def run_once():
        from foundationdb_tpu.core import (DeterministicRandom, EventLoop,
                                           set_deterministic_random,
                                           set_event_loop)
        lp = EventLoop(sim=True)
        set_event_loop(lp)
        set_deterministic_random(DeterministicRandom(7))
        s = Simulator()
        set_simulator(s)
        server = s.new_process(name="server")
        client = s.new_process(name="client")
        rs = start_echo_server(server)
        times = []

        async def go():
            stub = RequestStreamStub(rs.endpoint)
            for i in range(20):
                await stub.get_reply(EchoRequest(i), client.address)
                times.append(lp.now())

        lp.run_until(lp.spawn(go()), timeout=60)
        set_simulator(None)
        return times

    assert run_once() == run_once()


def test_serde_schema_evolution():
    """Tagged name-keyed fields give protocol evolution (reference
    ObjectSerializer/FileIdentifier): a NEWER sender's extra fields are
    skipped by an older receiver, and an OLDER sender's missing fields
    take the receiver's dataclass defaults — mixed-version clusters can
    exchange requests across an upgrade."""
    import dataclasses
    from foundationdb_tpu.core.wire import Reader, Writer
    from foundationdb_tpu.rpc import serde

    @serde.register
    @dataclasses.dataclass
    class EvolveMsgV2:
        a: int = 1
        b: bytes = b"x"
        added_in_v2: str = "default"

    # Simulate a V1 sender (no `added_in_v2`): hand-encode the tagged
    # form with a field subset.
    w = Writer()
    w.u8(serde.T_DATACLASS).str_("EvolveMsgV2")
    w.u32(1)
    w.str_("a")
    serde.encode_value(w, 42)
    got = serde.decode_value(Reader(w.done()))
    assert got.a == 42 and got.b == b"x" and got.added_in_v2 == "default"

    # Simulate a V3 sender (extra field unknown to us): append a field
    # this class does not declare — it must be SKIPPED, not an error.
    w = Writer()
    w.u8(serde.T_DATACLASS).str_("EvolveMsgV2")
    w.u32(2)
    w.str_("a")
    serde.encode_value(w, 7)
    w.str_("added_in_v3")
    serde.encode_value(w, ["future", "payload"])
    got = serde.decode_value(Reader(w.done()))
    assert got.a == 7 and not hasattr(got, "added_in_v3")

    # Round-trip of the full current schema still exact.
    w = Writer()
    serde.encode_value(w, EvolveMsgV2(a=5, b=b"z", added_in_v2="live"))
    got = serde.decode_value(Reader(w.done()))
    assert got == EvolveMsgV2(5, b"z", "live")


def test_unclog_releases_inflight_messages(loop, sim):
    """Interface clogs are re-evaluated at DELIVERY time (ISSUE 4
    swizzle): a message captured mid-clog is released shortly after
    unclog_process, not held until the original clog expiry."""
    server = sim.new_process(name="server")
    client = sim.new_process(name="client")
    rs = start_echo_server(server)

    async def go():
        sim.clog_process(server, seconds=30.0)
        t0 = loop.now()
        reply = RequestStreamStub(rs.endpoint).get_reply(
            EchoRequest(4), client.address)
        from foundationdb_tpu.core import delay
        await delay(1.0)
        assert not reply.is_ready()         # held by the clog
        sim.unclog_process(server)
        assert await reply == 8
        # Released within the bounded re-check hop, not at t0 + 30.
        assert loop.now() - t0 < 2.0
        return True

    assert loop.run_until(loop.spawn(go()), timeout=60)


def test_clog_extension_keeps_holding(loop, sim):
    """The converse: extending an interface clog AFTER a send keeps the
    in-flight message held past its original expiry."""
    server = sim.new_process(name="server")
    client = sim.new_process(name="client")
    rs = start_echo_server(server)

    async def go():
        from foundationdb_tpu.core import delay
        sim.clog_process(server, seconds=1.0)
        t0 = loop.now()
        reply = RequestStreamStub(rs.endpoint).get_reply(
            EchoRequest(3), client.address)
        sim.clog_process(server, seconds=5.0)   # extend before delivery
        await delay(2.0)
        assert not reply.is_ready()
        assert await reply == 6
        assert loop.now() - t0 >= 5.0
        return True

    assert loop.run_until(loop.spawn(go()), timeout=60)
