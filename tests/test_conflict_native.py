"""Native C++ conflict backend: randomized parity vs the Python oracle
(the same contract the TPU backend holds; reference SkipList.cpp)."""

import shutil

import pytest

from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.core import DeterministicRandom

from test_conflict_oracle import make_domain, random_txn
from test_conflict_tpu import random_point_txn

needs_gxx = pytest.mark.skipif(shutil.which("g++") is None,
                               reason="no g++ toolchain")


@needs_gxx
@pytest.mark.parametrize("seed", [41, 42, 43, 44])
def test_native_matches_oracle_random(seed):
    from foundationdb_tpu.conflict.native import NativeConflictSet
    rng = DeterministicRandom(seed)
    domain = make_domain()
    oracle = OracleConflictSet(0)
    native = NativeConflictSet(0)
    now = 0
    for i in range(60):
        now += rng.random_int(1, 2_000_000)
        if i % 2:
            batch = [random_point_txn(rng, 12, now, 4_000_000)
                     for _ in range(rng.random_int(1, 24))]
        else:
            batch = [random_txn(rng, domain, now, 4_000_000)
                     for _ in range(rng.random_int(1, 10))]
        new_oldest = now - 5_000_000 if rng.coinflip() else None
        got = native.resolve(batch, now, new_oldest)
        want = oracle.resolve(batch, now, new_oldest)
        assert got == want, f"native divergence at batch {i} (now={now})"
    assert native.segment_count() >= 1


@needs_gxx
def test_native_backend_selector():
    from foundationdb_tpu.conflict.api import new_conflict_set
    from foundationdb_tpu.conflict.native import NativeConflictSet
    cs = new_conflict_set("native", oldest_version=0)
    assert isinstance(cs, NativeConflictSet)
