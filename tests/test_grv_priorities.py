"""GRV priority queues: batch-priority load cannot starve default.

Reference: fdbserver/GrvProxyServer.actor.cpp:389 (priority queues),
:702 (transactionStarter releasing against distinct normal/batch budgets)
and Ratekeeper.actor.cpp:991 (separate batch limit with tighter targets).
VERDICT round-3 item 5 done-criterion: a batch-priority flood must not
delay default-priority GRVs under overload.
"""

import pytest

from foundationdb_tpu.core.scheduler import delay
from foundationdb_tpu.rpc.endpoint import RequestStream
from foundationdb_tpu.server.grv_proxy import GrvProxy
from foundationdb_tpu.server.interfaces import (GetRawCommittedVersionReply,
                                                GetRawCommittedVersionRequest,
                                                GetReadVersionRequest,
                                                MasterInterface,
                                                TransactionPriority)

from test_recovery import teardown  # noqa: F401


def _world():
    from foundationdb_tpu.core import EventLoop, set_event_loop
    from foundationdb_tpu.rpc.network import SimNetwork, set_network
    from foundationdb_tpu.rpc.sim import Simulator, set_simulator
    lp = EventLoop(sim=True)
    set_event_loop(lp)
    sim = Simulator()
    set_simulator(sim)
    set_network(sim.network)
    return lp, sim


async def _serve_versions(master: MasterInterface) -> None:
    async for req in master.get_live_committed_version.queue:
        req.reply.send(GetRawCommittedVersionReply(version=1000))


def test_batch_flood_cannot_starve_default_grvs(teardown):  # noqa: F811
    lp, sim = _world()
    p = sim.new_process(name="grvhost")
    master = MasterInterface()
    for s in master.streams():
        p.register(s)
    p.spawn(_serve_versions(master), "master.stub")

    proxy = GrvProxy("grv-test", master)
    proxy.run(p)
    # Overload regime straight from the ratekeeper model: normal budget
    # 100 tps, batch collapsed to 5 tps (batch throttles first).
    proxy._rate = 100.0
    proxy._batch_rate = 5.0

    grv_ep = proxy.interface.get_consistent_read_version.endpoint
    results = {"batch_done": 0, "default_lat": []}

    async def flood_batch() -> None:
        # 2000 batch-priority GRVs queued at once: at 5 tps this backlog
        # takes ~400s — it must NOT block default traffic behind it.
        for _ in range(2000):
            f = RequestStream.at(grv_ep).get_reply(GetReadVersionRequest(
                priority=TransactionPriority.BATCH))
            f.on_ready(lambda _f: results.__setitem__(
                "batch_done", results["batch_done"] + 1))

    async def default_traffic() -> None:
        from foundationdb_tpu.core.scheduler import now
        for _ in range(40):
            t0 = now()
            await RequestStream.at(grv_ep).get_reply(GetReadVersionRequest(
                priority=TransactionPriority.DEFAULT))
            results["default_lat"].append(now() - t0)
            await delay(0.05)

    async def go():
        lp.spawn(flood_batch())
        await delay(0.2)         # the flood is queued first
        await default_traffic()
        await delay(1.0)
        return True

    assert lp.run_until(lp.spawn(go()), timeout=60)
    # Every default GRV was served promptly despite the queued flood...
    assert len(results["default_lat"]) == 40
    assert max(results["default_lat"]) < 0.5, results["default_lat"]
    # ...while the batch backlog drained at only ~5 tps (strictly limited).
    assert results["batch_done"] < 100, results["batch_done"]
    assert results["batch_done"] >= 1   # but not starved entirely


def test_immediate_priority_bypasses_budgets(teardown):  # noqa: F811
    lp, sim = _world()
    p = sim.new_process(name="grvhost")
    master = MasterInterface()
    for s in master.streams():
        p.register(s)
    p.spawn(_serve_versions(master), "master.stub")
    proxy = GrvProxy("grv-test", master)
    proxy.run(p)
    proxy._rate = 0.001          # normal traffic fully throttled
    proxy._batch_rate = 0.001
    proxy.transaction_budget = 0.0
    proxy.batch_budget = 0.0

    grv_ep = proxy.interface.get_consistent_read_version.endpoint

    async def go():
        from foundationdb_tpu.core.scheduler import now
        t0 = now()
        r = await RequestStream.at(grv_ep).get_reply(GetReadVersionRequest(
            priority=TransactionPriority.IMMEDIATE))
        assert r.version == 1000
        return now() - t0

    lat = lp.run_until(lp.spawn(go()), timeout=30)
    assert lat < 0.5, lat


def test_ratekeeper_batch_limit_collapses_first(teardown):  # noqa: F811
    """The batch spring zone sits below the normal one: as the worst
    storage queue grows, batch_tps hits ~0 while normal tps is still
    unlimited or generous."""
    from foundationdb_tpu.core import EventLoop, set_event_loop
    from foundationdb_tpu.core.knobs import server_knobs
    from foundationdb_tpu.server.ratekeeper import Ratekeeper

    lp = EventLoop(sim=True)
    set_event_loop(lp)
    rk = Ratekeeper("rk-test", {})
    rk._released._estimate = 1000.0   # smoothed 1000 tps observed
    target = float(server_knobs().STORAGE_LIMIT_BYTES)
    spring = max(target * 0.2, 1.0)

    rk.worst_queue_bytes = 0
    rk._update_rate()
    assert rk.tps_limit == float("inf")
    assert rk.batch_tps_limit == float("inf")

    # Inside the batch spring zone only: batch throttled, normal not.
    rk.worst_queue_bytes = int(target - 1.5 * spring)
    rk._update_rate()
    assert rk.tps_limit == float("inf")
    assert rk.batch_tps_limit < 1000

    # At the normal threshold: batch ~0, normal begins throttling.
    rk.worst_queue_bytes = int(target - spring + spring * 0.5)
    rk._update_rate()
    assert rk.batch_tps_limit <= 1.0
    assert rk.tps_limit < float("inf")
