"""Depth-N dispatch pipeline (conflict/supervisor.py) + hoisted delta
table (conflict/fused.py delta_table_step) — ISSUE 6 battery.

Contracts under test:

1. **Pipeline parity** — at depths 1..3, abort sets delivered through the
   pipelined supervisor are bit-identical to a serial all-oracle run,
   including under every conflict.device.* BUGGIFY site.
2. **Loss-free, in-order degrade** — a device failure mid-pipeline
   replays every in-flight batch through the exact mirror IN SUBMISSION
   ORDER; no batch is lost and no verdict reorders.
3. **Occupancy accounting** — the depth bound is enforced (fold before
   dispatch on a full pipeline) and surfaced (PipelineStalls counter,
   InflightDepth histogram, conflict_backend status).
4. **Hoisted delta table** — the table threaded through the step always
   equals a fresh rebuild over the live delta, and the per-batch resolve
   step contains NO build_sparse_table (the ISSUE 6 op-count assertion).
"""

import numpy as np
import pytest

from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.conflict.supervisor import (BackendHealthMonitor,
                                                  SupervisedConflictSet)
from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet
from foundationdb_tpu.core import DeterministicRandom
from foundationdb_tpu.core.buggify import force_buggify, unforce_buggify
from foundationdb_tpu.core.knobs import server_knobs
from foundationdb_tpu.txn import CommitResult, CommitTransactionRef, KeyRange

from test_conflict_oracle import make_domain, random_txn


@pytest.fixture()
def knobs():
    k = server_knobs()
    saved = dict(k.__dict__)
    yield k
    for name, value in saved.items():
        setattr(k, name, value)


def make_tpu(oldest_version=0):
    return TpuConflictSet(oldest_version, capacity=1 << 12)


def make_supervised(**kw):
    return SupervisedConflictSet(make_tpu, **kw)


def never_reprobe_monitor():
    return BackendHealthMonitor(reprobe_interval_s=1e9)


def drive_pipelined(sup, oracle, rng, domain, n_batches, depth,
                    on_batch=None):
    """Drive identical streams through `sup` (async, up to `depth` handles
    outstanding) and the serial oracle; assert bit-identical verdicts on
    every batch, in submission order.  Returns delivered batch count."""
    outstanding = []
    now = 0
    delivered = 0

    def deliver(h, batch, v):
        nonlocal delivered
        want = oracle.resolve(batch, v, v - 5_000_000)
        assert h.wait() == want, f"divergence at version {v}"
        delivered += 1

    for i in range(n_batches):
        now += 1_000_000
        if on_batch is not None:
            on_batch(i)
        batch = [random_txn(rng, domain, now, 4_000_000)
                 for _ in range(rng.random_int(1, 8))]
        outstanding.append(
            (sup.resolve_async(batch, now, now - 5_000_000), batch, now))
        while len(outstanding) >= depth:
            deliver(*outstanding.pop(0))
    while outstanding:
        deliver(*outstanding.pop(0))
    return delivered


# ---------------------------------------------------------------------------
# 1. Pipeline parity, healthy and under every BUGGIFY site
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("depth", [1, 2, 3])
def test_pipeline_parity_bit_identical(knobs, depth):
    knobs.CONFLICT_PIPELINE_DEPTH = depth
    rng = DeterministicRandom(100 + depth)
    domain = make_domain()
    sup = make_supervised()
    oracle = OracleConflictSet(0)
    n = drive_pipelined(sup, oracle, rng, domain, 20, depth)
    assert n == 20
    assert sup.stats["device_batches"] == 20
    assert sup.stats["fallback_batches"] == 0


@pytest.mark.parametrize("depth", [1, 2, 3])
@pytest.mark.parametrize("site", ["timeout", "transient", "dead"])
def test_pipeline_parity_under_buggify(knobs, site, depth):
    """Each conflict.device.* site fired mid-stream at every depth:
    abort sets stay bit-identical to the oracle and every dispatched
    batch is delivered (zero lost)."""
    knobs.CONFLICT_PIPELINE_DEPTH = depth
    knobs.CONFLICT_DEVICE_RETRY_BACKOFF_S = 0.0
    site_seed = {"timeout": 1, "transient": 2, "dead": 3}[site]
    rng = DeterministicRandom(17 * depth + site_seed)
    domain = make_domain()
    sup = make_supervised(monitor=never_reprobe_monitor())
    oracle = OracleConflictSet(0)

    def on_batch(i):
        if i == 6:
            force_buggify(f"conflict.device.{site}")
        if i == 10:
            unforce_buggify(f"conflict.device.{site}")
            sup._buggify_dead = False       # device "recovers"
            sup.monitor.tripped_at = -1e12  # open the re-probe window

    try:
        n = drive_pipelined(sup, oracle, rng, domain, 18, depth,
                            on_batch=on_batch)
    finally:
        unforce_buggify()
    assert n == 18                          # no batch lost
    assert sup.stats["device_batches"] + sup.stats["fallback_batches"] == 18
    # A forced site exhausts the retry budget too: every variant degrades
    # while forced, and re-promotes once the device "recovers".
    assert sup.stats["degrades"] >= 1
    assert sup.stats["promotions"] >= 1
    assert sup.stats["device_batches"] > 0


# ---------------------------------------------------------------------------
# 2. Mid-pipeline degrade: loss-free, strictly in submission order
# ---------------------------------------------------------------------------

def test_mid_pipeline_degrade_in_order_no_loss(knobs):
    """Six batches in flight at depth 6; the device dies after the first
    fold.  The remaining five replay through the exact mirror in
    SUBMISSION order (mirror resolve versions strictly ascending), all
    bit-identical to the oracle — no batch lost, none reordered."""
    knobs.CONFLICT_PIPELINE_DEPTH = 6
    rng = DeterministicRandom(23)
    domain = make_domain()
    sup = make_supervised(monitor=never_reprobe_monitor())
    oracle = OracleConflictSet(0)

    seen_versions = []
    orig = sup._mirror.resolve_with_conflicts

    def spy(txns, now, new_oldest_version=None):
        seen_versions.append(now)
        return orig(txns, now, new_oldest_version)

    sup._mirror.resolve_with_conflicts = spy

    handles, batches = [], []
    now = 0
    for _ in range(6):
        now += 1_000_000
        batch = [random_txn(rng, domain, now, 3_000_000) for _ in range(5)]
        handles.append(sup.resolve_async(batch, now, now - 5_000_000))
        batches.append((batch, now))

    # First batch folds on the healthy device...
    want0 = oracle.resolve(batches[0][0], batches[0][1],
                           batches[0][1] - 5_000_000)
    assert handles[0].wait() == want0
    # ...then the device dies with five batches in flight.
    sup.force_device_error = "timeout"
    for h, (batch, v) in list(zip(handles, batches))[1:]:
        want = oracle.resolve(batch, v, v - 5_000_000)
        assert h.wait() == want
    assert sup.degraded
    assert sup.stats["fallback_batches"] == 5            # zero lost
    assert seen_versions == sorted(seen_versions)        # in order
    assert len(seen_versions) == 5


def test_pipelined_dispatch_failure_discards_later_device_verdicts(knobs):
    """A dispatch failure with batches in flight poisons device state:
    EVERY unfolded batch — predecessors whose device verdicts were
    already computed included — replays through the exact mirror, so no
    possibly-corrupt device verdict is ever delivered."""
    knobs.CONFLICT_PIPELINE_DEPTH = 4
    sup = make_supervised(monitor=never_reprobe_monitor())
    oracle = OracleConflictSet(0)
    w = CommitTransactionRef(write_conflict_ranges=[KeyRange(b"a", b"b")])
    r = CommitTransactionRef(read_snapshot=50,
                             read_conflict_ranges=[KeyRange(b"a", b"b")])
    h0 = sup.resolve_async([w], 100)
    sup.force_device_error = "timeout"      # fires at the next dispatch
    h1 = sup.resolve_async([r], 200)
    h2 = sup.resolve_async([r], 300)
    assert h0.wait() == oracle.resolve([w], 100)
    assert h1.wait() == oracle.resolve([r], 200) == [CommitResult.CONFLICT]
    assert h2.wait() == oracle.resolve([r], 300) == [CommitResult.CONFLICT]
    assert sup.degraded and sup.stats["fallback_batches"] == 3


# ---------------------------------------------------------------------------
# 3. Depth bound, stall counter, occupancy surfacing
# ---------------------------------------------------------------------------

def test_depth_bound_enforced_and_stalls_counted(knobs):
    knobs.CONFLICT_PIPELINE_DEPTH = 2
    rng = DeterministicRandom(31)
    domain = make_domain()
    sup = make_supervised()
    now = 0
    handles = []
    for _ in range(5):
        now += 1_000_000
        batch = [random_txn(rng, domain, now, 3_000_000) for _ in range(3)]
        handles.append(sup.resolve_async(batch, now))
        assert len(sup._pending) <= 2       # bound enforced at dispatch
    # Three dispatches found the pipeline full and folded the oldest.
    assert sup.stats["pipeline_stalls"] == 3
    assert handles[0].folded and handles[2].folded      # folded early...
    handles[-1].wait()                                  # ...all delivered
    assert all(h.folded for h in handles)
    st = sup.status()
    assert st["pipeline_stalls"] == 3
    depth_band = st["latency_statistics"]["InflightDepth"]
    assert depth_band["count"] == 5
    assert depth_band["max"] == 2.0
    assert sup.metrics.counters["PipelineStalls"].value == 3


def test_sync_resolve_never_stalls(knobs):
    """The resolver's synchronous path folds every batch immediately:
    depth never builds up and the stall counter stays silent."""
    knobs.CONFLICT_PIPELINE_DEPTH = 2
    sup = make_supervised()
    for i in range(5):
        w = CommitTransactionRef(
            write_conflict_ranges=[KeyRange(b"k%d" % i, b"k%d\x00" % i)])
        assert sup.resolve([w], 100 * (i + 1)) == [CommitResult.COMMITTED]
    assert sup.stats["pipeline_stalls"] == 0
    assert sup.metrics.histograms["InflightDepth"].max == 1.0


# ---------------------------------------------------------------------------
# 4. Encoded-batch dispatch (the bench/bulk path)
# ---------------------------------------------------------------------------

def test_encoded_dispatch_parity(knobs):
    from foundationdb_tpu.conflict.encoded import EncodedBatch
    knobs.CONFLICT_PIPELINE_DEPTH = 2
    sup = make_supervised()
    oracle = OracleConflictSet(0)
    rng = DeterministicRandom(41)
    now = 0
    outstanding = []
    for _ in range(6):
        now += 1_000_000
        txns = []
        for _t in range(8):
            k = b"p%05d" % rng.random_int(0, 40)
            kr = b"p%05d" % rng.random_int(0, 40)
            txns.append(CommitTransactionRef(
                read_snapshot=max(now - rng.random_int(0, 3_000_000), 0),
                read_conflict_ranges=[KeyRange(kr, kr + b"\x00")],
                write_conflict_ranges=[KeyRange(k, k + b"\x00")]))
        enc = EncodedBatch.from_transactions(txns)
        h = sup.resolve_encoded_async(enc, now, now - 5_000_000,
                                      transactions=txns)
        outstanding.append((h, txns, now))
        if len(outstanding) > 2:
            hd, txd, vd = outstanding.pop(0)
            want = oracle.resolve(txd, vd, vd - 5_000_000)
            got = hd.wait_codes()
            assert np.array_equal(
                got, np.asarray([int(x) for x in want], dtype=np.int8))
    for hd, txd, vd in outstanding:
        want = oracle.resolve(txd, vd, vd - 5_000_000)
        assert hd.wait() == want
    assert sup.stats["device_batches"] == 6


def test_encoded_dispatch_requires_transactions():
    from foundationdb_tpu.conflict.encoded import EncodedBatch
    sup = make_supervised()
    txns = [CommitTransactionRef(
        write_conflict_ranges=[KeyRange(b"a", b"a\x00")])]
    enc = EncodedBatch.from_transactions(txns)
    with pytest.raises(TypeError):
        sup.resolve_encoded_async(enc, 100)


# ---------------------------------------------------------------------------
# 5. Hoisted delta table: equivalence + the op-count assertion
# ---------------------------------------------------------------------------

def point_batch(rng, now, n_txns, keyspace=200):
    txns = []
    for _ in range(n_txns):
        k = b"h%06d" % rng.random_int(0, keyspace)
        kr = b"h%06d" % rng.random_int(0, keyspace)
        txns.append(CommitTransactionRef(
            read_snapshot=max(now - rng.random_int(0, 3_000_000), 0),
            read_conflict_ranges=[KeyRange(kr, kr + b"\x00")],
            write_conflict_ranges=[KeyRange(k, k + b"\x00")]))
    return txns


def test_hoisted_delta_table_matches_rebuild():
    """The table threaded through the step (built at insert time by
    delta_table_step) must equal a fresh build_sparse_table over the live
    delta after EVERY batch — including across a merge (delta reset) and
    on the general interval path — on random windows."""
    from foundationdb_tpu.ops.rangemax import build_sparse_table
    cs = TpuConflictSet(0, capacity=1 << 12, delta_capacity=1 << 8,
                        gc_interval_batches=4)
    rng = DeterministicRandom(59)
    now = 0
    for i in range(10):
        now += 1_000_000
        txns = point_batch(rng, now, rng.random_int(1, 12))
        if i % 3 == 2:
            # A range read routes this batch through the general
            # (non-compact) interval program.
            txns.append(CommitTransactionRef(
                read_snapshot=now - 500_000,
                read_conflict_ranges=[KeyRange(b"h", b"i")]))
        cs.resolve(txns, now, now - 5_000_000)
        got = np.asarray(cs.dtable)
        want = np.asarray(build_sparse_table(cs.dv))
        assert np.array_equal(got, want), f"table drift after batch {i}"
    assert cs.profile["merges"] >= 1        # the merge path was crossed


def test_resolve_step_contains_no_table_build():
    """ISSUE 6 acceptance: build_sparse_table no longer executes inside
    the per-batch resolve step.  Both step programs (compact point and
    general interval) are traced at fresh shapes with the table builder
    replaced by a tripwire — any in-step build would fire it.  (The
    builder still runs, legitimately, in delta_table_step and the merge
    program.)"""
    from foundationdb_tpu.conflict import fused

    def tripwire(values):
        raise AssertionError(
            "build_sparse_table traced inside the per-batch resolve step")

    fused.make_resolve_step.cache_clear()
    fused.make_resolve_step_compact.cache_clear()
    orig = fused.build_sparse_table
    fused.build_sparse_table = tripwire
    try:
        cs = TpuConflictSet(0, capacity=1 << 11, delta_capacity=1 << 7,
                            gc_interval_batches=1 << 30)
        rng = DeterministicRandom(61)
        # Compact point path (fresh shapes -> fresh trace under tripwire).
        cs.resolve(point_batch(rng, 1_000_000, 5), 1_000_000)
        # General interval path.
        cs.resolve([CommitTransactionRef(
            read_snapshot=500_000,
            read_conflict_ranges=[KeyRange(b"h", b"i")],
            write_conflict_ranges=[KeyRange(b"j", b"k")])], 2_000_000)
    finally:
        fused.build_sparse_table = orig
        fused.make_resolve_step.cache_clear()
        fused.make_resolve_step_compact.cache_clear()


# ---------------------------------------------------------------------------
# 6. The overlap mechanism itself
# ---------------------------------------------------------------------------

def test_pipeline_overlaps_device_link_latency(knobs):
    """The reason the pipeline exists: transfer-style IDLE latency on the
    device link (sleeps on dispatch/wait — the axon tunnel's ~0.9 s h2d
    / 33 ms d2h profile in miniature) is hidden at depth >= 2.  Sleeps
    are idle time, so this holds even on a single-core host; margins are
    generous because it asserts that overlap EXISTS, not a ratio."""
    import time as _t

    class _LinkHandle:
        def __init__(self, results):
            self._results = results

        def wait(self):
            _t.sleep(0.04)                  # d2h link occupancy
            return self._results

    class SlowLinkDevice(OracleConflictSet):
        def resolve_async(self, txns, now, new_oldest_version=None):
            _t.sleep(0.04)                  # h2d link occupancy
            return _LinkHandle(
                super().resolve(txns, now, new_oldest_version))

    def run_at(depth):
        knobs.CONFLICT_PIPELINE_DEPTH = depth
        sup = SupervisedConflictSet(
            lambda oldest_version=0: SlowLinkDevice(oldest_version),
            monitor=never_reprobe_monitor())
        w = [CommitTransactionRef(
            write_conflict_ranges=[KeyRange(b"a", b"b")])]
        t0 = _t.monotonic()
        handles = [sup.resolve_async(w, 100 * (i + 1)) for i in range(8)]
        for h in handles:
            h.wait()
        dt = _t.monotonic() - t0
        assert not sup.degraded
        return dt

    t1 = run_at(1)
    t3 = run_at(3)
    assert t1 > 0.55, f"depth-1 serialization lost? {t1:.3f}s"
    assert t3 < 0.75 * t1, (
        f"no pipeline overlap: depth3 {t3:.3f}s vs depth1 {t1:.3f}s")
