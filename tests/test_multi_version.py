"""Multi-version client (reference MultiVersionTransaction.actor.cpp):
implementation selection by protocol version, transparent swap on a
protocol change, in-flight transactions retrying onto the new impl."""

import dataclasses

import pytest

from foundationdb_tpu.client.database import ClusterConnection, Database
from foundationdb_tpu.client.multi_version import MultiVersionDatabase
from foundationdb_tpu.rpc.real_network import PROTOCOL_VERSION
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration

from test_recovery import teardown  # noqa: F711,F401


def make_cluster():
    return SimFdbCluster(config=DatabaseConfiguration(), n_workers=4,
                         n_storage_workers=2)


def test_multi_version_selects_and_switches(teardown):  # noqa: F811
    c = make_cluster()
    created = []

    def factory(cluster):
        db = Database(cluster)
        created.append(db)
        return db

    cluster = ClusterConnection(c.coordinator_clients)
    mv = MultiVersionDatabase(cluster, {PROTOCOL_VERSION: factory,
                                        PROTOCOL_VERSION + 1: factory})

    async def go():
        await mv.wait_ready()
        # The CC reported the cluster protocol; the matching impl serves.
        assert mv.active_protocol == PROTOCOL_VERSION
        assert len(created) == 1
        t = mv.create_transaction()
        while True:
            try:
                t.set(b"mv/a", b"1")
                await t.commit()
                break
            except Exception as e:  # noqa: BLE001
                await t.on_error(e)

        # Cluster upgrade: the reported protocol bumps; the monitor swaps
        # implementations and the OLD transaction's next use raises the
        # retryable cluster_version_changed, landing on the new impl via
        # its ordinary retry loop.
        t2 = mv.create_transaction()
        assert await t2.get(b"mv/a") == b"1"
        info = cluster.client_info.get()
        cluster.client_info.set(dataclasses.replace(
            info, protocol_version=PROTOCOL_VERSION + 1))
        for _ in range(50):
            if mv.active_protocol == PROTOCOL_VERSION + 1:
                break
            from foundationdb_tpu.core.scheduler import delay
            await delay(0.05)
        assert mv.active_protocol == PROTOCOL_VERSION + 1
        assert len(created) == 2
        while True:
            try:
                t2.set(b"mv/b", b"2")
                await t2.commit()
                break
            except Exception as e:  # noqa: BLE001
                assert getattr(e, "name", "") in (
                    "cluster_version_changed", "not_committed",
                    "commit_unknown_result", "transaction_too_old")
                await t2.on_error(e)
        t3 = mv.create_transaction()
        assert await t3.get(b"mv/b") == b"2"
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=120)
    mv.close()


def test_multi_version_unknown_protocol_blocks(teardown):  # noqa: F811
    c = make_cluster()
    cluster = ClusterConnection(c.coordinator_clients)
    # Registry has only a WRONG protocol: the database must stay
    # unavailable (reference: no matching client library), not misbehave.
    mv = MultiVersionDatabase(cluster, {999: lambda cl: Database(cl)})

    async def go():
        from foundationdb_tpu.core.scheduler import delay
        for _ in range(20):
            await delay(0.1)
            if mv.active_protocol is not None:
                break
        assert mv.active_db is None
        t = mv.create_transaction()
        try:
            await t.get(b"k")
            return False
        except Exception as e:  # noqa: BLE001
            return getattr(e, "name", "") == "cluster_version_changed"

    assert c.run_until(c.loop.spawn(go()), timeout=60)
    mv.close()
