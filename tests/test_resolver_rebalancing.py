"""Resolver key-range rebalancing under a skewed workload.

Reference: fdbserver/masterserver.actor.cpp:1318 resolutionBalancing +
Resolver.actor.cpp:341-348 metrics/split endpoints.  VERDICT round-2
done-criterion: a skewed workload moves resolver boundaries live while
conflict verdicts stay correct — checked here via exactly-one-wins
semantics on conflicting pairs that straddle the moved boundary, and an
old-snapshot read that must still conflict through the PREVIOUS owner's
window (the proxy's per-version ownership history)."""

import pytest

from foundationdb_tpu.core import FdbError
from foundationdb_tpu.core.knobs import server_knobs
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration

from test_recovery import commit_kv, read_key, teardown  # noqa: F401


def make_cluster():
    return SimFdbCluster(
        config=DatabaseConfiguration(n_resolvers=2),
        n_workers=6, n_storage_workers=2)


def proxy_roles(cluster):
    cc = cluster.current_cc()
    return [p.role for p in cc.db_info.commit_proxies]


def test_skewed_load_moves_boundary_with_correct_verdicts(teardown):  # noqa: F811
    knobs = server_knobs()
    old_min = knobs.RESOLUTION_BALANCING_MIN_LOAD
    knobs.RESOLUTION_BALANCING_MIN_LOAD = 20
    try:
        c = make_cluster()
        db = c.database()

        async def go():
            from foundationdb_tpu.core.scheduler import delay
            # Heavy skew: all load below \x80 -> resolver 0's range.
            moved = False
            for round_ in range(120):
                for i in range(10):
                    await commit_kv(db, b"skew/%03d" % (round_ * 10 + i),
                                    b"x")
                proxies = proxy_roles(c)
                if any(p._resolver_changes_hwm > 0 for p in proxies):
                    moved = True
                    break
            assert moved, "no resolver boundary change was adopted"
            # After the move: conflicting pairs still behave exactly-one-
            # wins wherever the key lands.
            for i in range(12):
                key = b"skew/%03d" % (i * 17)
                t1 = db.create_transaction()
                t2 = db.create_transaction()
                await t1.get(key)
                await t2.get(key)
                t1.set(key, b"a")
                t2.set(key, b"b")
                outcomes = []
                for t in (t1, t2):
                    try:
                        await t.commit()
                        outcomes.append("ok")
                    except FdbError as e:
                        assert e.name == "not_committed", e.name
                        outcomes.append("conflict")
                assert outcomes == ["ok", "conflict"], outcomes
            await delay(0.2)

        c.run_until(c.loop.spawn(go()), timeout=600)
    finally:
        knobs.RESOLUTION_BALANCING_MIN_LOAD = old_min


def test_old_snapshot_conflicts_across_moved_boundary(teardown):  # noqa: F811
    """A read at a snapshot taken BEFORE a boundary move must still see
    conflicts recorded by the previous owner: the proxy's ownership
    history routes the check to every in-window owner."""
    knobs = server_knobs()
    old_min = knobs.RESOLUTION_BALANCING_MIN_LOAD
    knobs.RESOLUTION_BALANCING_MIN_LOAD = 20
    try:
        c = make_cluster()
        db = c.database()

        async def go():
            # Old-snapshot reader: grab a read version FIRST.
            t_old = db.create_transaction()
            await t_old.get(b"skew/000")          # snapshot pinned now
            # Writer commits to the key, then skewed load forces a move.
            await commit_kv(db, b"skew/000", b"new")
            moved = False
            for round_ in range(120):
                for i in range(10):
                    await commit_kv(db, b"skew/%03d" % (round_ * 10 + i + 1),
                                    b"x")
                if any(p._resolver_changes_hwm > 0 for p in proxy_roles(c)):
                    moved = True
                    break
            assert moved, "no boundary move happened"
            # The old-snapshot txn now writes: its read of skew/000 at the
            # old snapshot MUST conflict with the committed write even if
            # skew/000's range moved to the other resolver since.
            t_old.set(b"probe", b"1")
            with pytest.raises(FdbError) as ei:
                await t_old.commit()
            assert ei.value.name in ("not_committed", "transaction_too_old")

        c.run_until(c.loop.spawn(go()), timeout=600)
    finally:
        knobs.RESOLUTION_BALANCING_MIN_LOAD = old_min
