"""Versionstamped operations end-to-end.

Reference: fdbclient/CommitTransaction.h:55-96 (SetVersionstampedKey/Value
transformed at the commit proxy: the 10-byte slot addressed by a 4-byte
little-endian offset suffix becomes 8B big-endian commit version + 2B
batch index) and NativeAPI.actor.cpp:5094 (the client's versionstamp
future resolves after the commit)."""

import pytest

from foundationdb_tpu.core import FdbError
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration

from test_recovery import commit_kv, read_key, teardown  # noqa: F401


def make_cluster():
    return SimFdbCluster(config=DatabaseConfiguration(), n_workers=5,
                         n_storage_workers=2)


def test_versionstamped_key_and_future(teardown):  # noqa: F811
    c = make_cluster()
    db = c.database()

    async def go():
        t = db.create_transaction()
        template = b"vs/" + b"\x00" * 10          # slot at offset 3
        while True:
            try:
                t.set_versionstamped_key(template, 3, b"payload")
                vs_f = t.get_versionstamp()
                v = await t.commit()
                break
            except FdbError as e:
                await t.on_error(e)
        stamp = vs_f.get()
        assert len(stamp) == 10
        assert int.from_bytes(stamp[:8], "big") == v
        # The formed key exists with the stamp spliced in.
        expected_key = b"vs/" + stamp
        t2 = db.create_transaction()
        got = await t2.get(expected_key)
        assert got == b"payload"
        # And nothing was written under the raw template.
        assert await t2.get(template) is None

    c.run_until(c.loop.spawn(go()), timeout=60)


def test_versionstamped_value(teardown):  # noqa: F811
    c = make_cluster()
    db = c.database()

    async def go():
        t = db.create_transaction()
        tmpl = b"prefix-" + b"\x00" * 10 + b"-suffix"
        while True:
            try:
                t.set_versionstamped_value(b"vv/key", tmpl, 7)
                vs_f = t.get_versionstamp()
                await t.commit()
                break
            except FdbError as e:
                await t.on_error(e)
        stamp = vs_f.get()
        got = await read_key(db, b"vv/key")
        assert got == b"prefix-" + stamp + b"-suffix"

    c.run_until(c.loop.spawn(go()), timeout=60)


def test_versionstamps_are_ordered_and_unique(teardown):  # noqa: F811
    c = make_cluster()
    db = c.database()

    async def go():
        stamps = []
        for i in range(6):
            t = db.create_transaction()
            while True:
                try:
                    t.set_versionstamped_key(b"ord/" + b"\x00" * 10, 4,
                                             b"%d" % i)
                    f = t.get_versionstamp()
                    await t.commit()
                    break
                except FdbError as e:
                    await t.on_error(e)
            stamps.append(f.get())
        assert stamps == sorted(stamps)
        assert len(set(stamps)) == len(stamps)
        # All six formed keys are readable in stamp order.
        t2 = db.create_transaction()
        kvs = await t2.get_range(b"ord/", b"ord0", limit=100)
        assert [v for _k, v in kvs] == [b"%d" % i for i in range(6)]

    c.run_until(c.loop.spawn(go()), timeout=60)


def test_ryw_read_of_versionstamped_key_is_unreadable(teardown):  # noqa: F811
    c = make_cluster()
    db = c.database()

    async def go():
        t = db.create_transaction()
        tmpl = b"ur/" + b"\x00" * 10
        t.set_versionstamped_key(tmpl, 3, b"v")
        with pytest.raises(FdbError) as ei:
            await t.get(tmpl + (3).to_bytes(4, "little"))
        assert ei.value.name == "accessed_unreadable"

    c.run_until(c.loop.spawn(go()), timeout=60)
