"""Conflicting-keys reporting (VERDICT round-3 item 10).

Reference: fdbserver/workloads/ReportConflictingKeys.actor.cpp:31 (the
randomized cross-check of reported ranges against a model) and
fdbclient/SpecialKeySpace.actor.h:140 (the \xff\xff/transaction/
conflicting_keys surface).  The resolver reports WHICH read ranges
conflicted; the client surfaces them RYW-style on the retry.
"""

import pytest

from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration
from foundationdb_tpu.txn.types import (CommitResult, CommitTransactionRef,
                                        KeyRange)

from test_recovery import commit_kv, teardown  # noqa: F401


def _txn(reads, writes, snap, report=True):
    return CommitTransactionRef(
        read_conflict_ranges=[KeyRange(b, e) for b, e in reads],
        write_conflict_ranges=[KeyRange(b, e) for b, e in writes],
        mutations=[], read_snapshot=snap, report_conflicting_keys=report)


def test_oracle_reports_exact_conflicting_ranges(teardown):  # noqa: F811
    cs = OracleConflictSet(0)
    # Seed history: writes at version 10 over [b, c) and [m, n).
    v, _ = cs.resolve_with_conflicts(
        [_txn([], [(b"b", b"c"), (b"m", b"n")], 0, report=False)], 10)
    assert v == [CommitResult.COMMITTED]
    # A txn at snapshot 5 reading [a,b) (clean), [b,c) (dirty), [m,z)
    # (dirty): exactly the two overlapping ranges must be reported.
    verdicts, ranges = cs.resolve_with_conflicts(
        [_txn([(b"a", b"b"), (b"b", b"c"), (b"m", b"z")], [], 5)], 20)
    assert verdicts == [CommitResult.CONFLICT]
    assert ranges == {0: [(b"b", b"c"), (b"m", b"z")]}
    # Without the report flag nothing is collected.
    verdicts, ranges = cs.resolve_with_conflicts(
        [_txn([(b"b", b"c")], [], 5, report=False)], 30)
    assert verdicts == [CommitResult.CONFLICT]
    assert ranges == {}
    # Intra-batch: txn 1 reads what txn 0 (same batch) writes.
    verdicts, ranges = cs.resolve_with_conflicts(
        [_txn([], [(b"q", b"r")], 25, report=False),
         _txn([(b"q", b"qq")], [], 25)], 40)
    assert verdicts == [CommitResult.COMMITTED, CommitResult.CONFLICT]
    assert ranges == {1: [(b"q", b"qq")]}


def test_oracle_report_randomized_cross_check(teardown):  # noqa: F811
    """ReportConflictingKeys-style: every reported range must GENUINELY
    overlap a write newer than the snapshot (no false positives), and a
    conflicted reporter must report at least one range."""
    import random
    rng = random.Random(7)
    cs = OracleConflictSet(0)
    committed_writes = []   # (version, begin, end)
    version = 100
    for round_ in range(60):
        version += 10
        txns = []
        for _ in range(rng.randrange(1, 5)):
            reads = [(b"k%02d" % (s := rng.randrange(40)),
                      b"k%02d" % rng.randrange(s + 1, 42))
                     for _ in range(rng.randrange(0, 3))]
            writes = [(b"k%02d" % (s := rng.randrange(40)),
                       b"k%02d" % rng.randrange(s + 1, 42))
                      for _ in range(rng.randrange(0, 2))]
            snap = version - rng.randrange(5, 40)
            txns.append(_txn(reads, writes, snap))
        verdicts, reported = cs.resolve_with_conflicts(txns, version)
        # Track which writes are in history (surviving writers only).
        intra = []
        for t, (txn, vd) in enumerate(zip(txns, verdicts)):
            if vd == CommitResult.CONFLICT:
                assert t in reported and reported[t], \
                    "conflicted reporter reported nothing"
            if vd == CommitResult.COMMITTED:
                for w in txn.write_conflict_ranges:
                    intra.append((version, w.begin, w.end))
            for b, e in reported.get(t, ()):
                hit = any(wv > txn.read_snapshot and b < we and wb < e
                          for wv, wb, we in committed_writes + intra)
                assert hit, f"reported range ({b},{e}) overlaps no " \
                            f"newer write (snap={txn.read_snapshot})"
        committed_writes.extend(intra)


def test_client_surfaces_conflicting_keys(teardown):  # noqa: F811
    """End-to-end: two clients race on one key; the loser's retry reads
    \xff\xff/transaction/conflicting_keys and finds the culprit range."""
    c = SimFdbCluster(config=DatabaseConfiguration(), n_workers=5,
                      n_storage_workers=2)
    db = c.database()

    async def go():
        from foundationdb_tpu.core.error import FdbError
        await commit_kv(db, b"hot", b"0")
        t1 = db.create_transaction()
        t1.report_conflicting_keys = True
        v = await t1.get(b"hot")
        # A rival commit lands between t1's read and its commit.
        await commit_kv(db, b"hot", b"rival")
        t1.set(b"hot", v + b"+1")
        try:
            await t1.commit()
            raise AssertionError("expected not_committed")
        except FdbError as e:
            assert e.name == "not_committed"
        # RYW-style surface on the retry (before on_error resets).
        p = t1.CONFLICTING_KEYS_PREFIX
        rows = await t1.get_range(p, p + b"\xff")
        assert rows, "no conflicting keys surfaced"
        assert rows[0][0] == p + b"hot" and rows[0][1] == b"\x01"
        assert rows[1][1] == b"\x00"
        assert await t1.get(p + b"hot") == b"\x01"
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=60)
