"""Per-tenant quotas through the tag-throttle machinery (ISSUE 2):
storage meters reads per tenant tag, the ratekeeper turns committed
quotas into standing tag throttles, GRV proxies hold the hot tenant —
and the quiet tenant's latency stays at its no-contention baseline."""

import pytest

from foundationdb_tpu.core.knobs import server_knobs
from foundationdb_tpu.core.scheduler import delay
from foundationdb_tpu.rpc.endpoint import RequestStream
from foundationdb_tpu.server.grv_proxy import GrvProxy
from foundationdb_tpu.server.interfaces import (GetRawCommittedVersionReply,
                                                GetReadVersionRequest,
                                                MasterInterface,
                                                TransactionPriority)
from foundationdb_tpu.server.ratekeeper import (Ratekeeper,
                                                StorageQueuingMetricsReply)

from test_ratekeeper import _StubSS, _world
from test_recovery import make_cluster, teardown  # noqa: F401


def test_quota_installs_standing_throttle(teardown):  # noqa: F811
    """A committed quota is a STANDING ceiling (it never lapses while the
    quota exists), lifts the moment the quota is cleared, and does NOT
    latch a transient auto-throttle forever."""
    lp, sim = _world()
    p = sim.new_process(name="host")
    ss = _StubSS(p, StorageQueuingMetricsReply(
        queue_bytes=0, durability_lag=0,
        tag_read_ops={"t/hot": 500.0}, tag_read_bytes={"t/hot": 32000.0}))
    rk = Ratekeeper("rk-test", {0: ss}, poll_interval=0.05)
    rk.tenant_quotas = {"t/hot": 25.0}      # as the quota poll would set
    rk.run(p)

    async def go():
        from foundationdb_tpu.core.scheduler import now
        knobs = server_knobs()
        await delay(0.3)
        assert rk.effective_throttles().get("t/hot") == 25.0
        # Measured read metering aggregated for status.
        assert rk.tag_read_ops.get("t/hot") == 500.0
        assert rk.tag_read_bytes.get("t/hot") == 32000.0
        # Standing: still throttled LONG past the auto-throttle duration.
        await delay(float(knobs.AUTO_TAG_THROTTLE_DURATION) + 1.0)
        assert "t/hot" in rk.effective_throttles()
        # Regression (review finding): a TRANSIENT auto-throttle tighter
        # than the quota must expire normally — the quota must not latch
        # it.  While both exist, the tighter value wins.
        rk.tag_throttles["t/hot"] = (2.0, now() + 0.5)
        assert rk.effective_throttles()["t/hot"] == 2.0
        await delay(float(knobs.AUTO_TAG_THROTTLE_DURATION) + 1.0)
        assert rk.effective_throttles()["t/hot"] == 25.0   # storm passed
        # Quota cleared -> ceiling lifts immediately.
        rk.tenant_quotas = {}
        assert "t/hot" not in rk.effective_throttles()
        return True

    assert lp.run_until(lp.spawn(go()), timeout=60)


def test_hot_tenant_capped_quiet_tenant_unaffected(teardown):  # noqa: F811
    """ISSUE acceptance shape at the GRV proxy: the quota-throttled hot
    tenant's backlog drains at quota tps while the quiet tenant's GRV
    latency stays at its no-contention baseline."""
    lp, sim = _world()
    p = sim.new_process(name="host")
    ss = _StubSS(p, StorageQueuingMetricsReply(
        queue_bytes=0, durability_lag=0,
        tag_read_ops={"t/hot": 800.0}))
    rk = Ratekeeper("rk-test", {0: ss}, poll_interval=0.05)
    rk.tenant_quotas = {"t/hot": 10.0}
    rk.run(p)

    master = MasterInterface()
    for s in master.streams():
        p.register(s)

    async def serve_versions() -> None:
        async for req in master.get_live_committed_version.queue:
            req.reply.send(GetRawCommittedVersionReply(version=1000))
    p.spawn(serve_versions(), "master.stub")

    proxy = GrvProxy("grv-test", master, ratekeeper=rk.interface)
    proxy.run(p)
    grv_ep = proxy.interface.get_consistent_read_version.endpoint
    results = {"hot_done": 0, "quiet_lat": []}

    async def hot_flood() -> None:
        for _ in range(300):
            f = RequestStream.at(grv_ep).get_reply(GetReadVersionRequest(
                priority=TransactionPriority.DEFAULT, tags=("t/hot",)))
            f.on_ready(lambda _f: results.__setitem__(
                "hot_done", results["hot_done"] + 1))

    async def quiet_traffic() -> None:
        from foundationdb_tpu.core.scheduler import now
        for _ in range(20):
            t0 = now()
            await RequestStream.at(grv_ep).get_reply(GetReadVersionRequest(
                priority=TransactionPriority.DEFAULT, tags=("t/quiet",)))
            results["quiet_lat"].append(now() - t0)
            await delay(0.05)

    async def go():
        await delay(0.3)              # quota throttle lands on the proxy
        assert "t/hot" in rk.effective_throttles()
        lp.spawn(hot_flood())
        await delay(0.1)
        await quiet_traffic()
        await delay(1.0)
        return True

    assert lp.run_until(lp.spawn(go()), timeout=60)
    assert len(results["quiet_lat"]) == 20
    assert max(results["quiet_lat"]) < 0.5, results["quiet_lat"]
    # Hot tenant drained only at the quota rate (~10 tps over ~1.1s
    # observed window, plus the initial bucket) — nowhere near 300.
    assert 1 <= results["hot_done"] < 150, results["hot_done"]


def test_quota_end_to_end_in_sim(teardown):  # noqa: F811
    """Full-stack: `quota set` as committed data -> ratekeeper quota poll
    (worker-injected db client) -> standing throttle visible in status
    -> tenant-tagged traffic metered on storage servers."""
    c = make_cluster(n_workers=6)
    db = c.database()

    async def go():
        from foundationdb_tpu.core import FdbError
        from foundationdb_tpu.tenant import management as tm
        from foundationdb_tpu.tenant.map import tenant_tag
        await tm.create_tenant(db, b"hot")
        await tm.set_tenant_quota(db, b"hot", 5.0)
        assert await tm.get_tenant_quotas(db) == {b"hot": 5.0}
        # Unknown tenants cannot carry quotas.
        try:
            await tm.set_tenant_quota(db, b"ghost", 1.0)
            raise AssertionError("quota on unknown tenant accepted")
        except FdbError as e:
            assert e.name == "tenant_not_found"
        tenant = await db.open_tenant(b"hot")

        async def put(t):
            t.set(b"k", b"v")
        txn = tenant.create_transaction()
        while True:
            try:
                await put(txn)
                await txn.commit()
                break
            except FdbError as e:
                await txn.on_error(e)
        # Drive tagged reads so storage samples the tenant tag.
        for _ in range(30):
            t = tenant.create_transaction()
            while True:
                try:
                    await t.get(b"k")
                    break
                except FdbError as e:
                    await t.on_error(e)
        # Let the ratekeeper's quota poll + storage poll land.
        from foundationdb_tpu.core.scheduler import delay as _delay
        tag = tenant_tag(b"hot")
        for _ in range(40):
            await _delay(0.5)
            cc = c.current_cc()
            rk_iface = cc.db_info.ratekeeper if cc is not None else None
            rk = getattr(rk_iface, "role", None)
            if rk is not None and tag in rk.effective_throttles():
                break
        assert rk is not None
        assert rk.tenant_quotas.get(tag) == 5.0
        assert rk.effective_throttles().get(tag) == 5.0
        # Visible in status JSON (status.py tenants section).
        status = await db.cluster.get_status()
        tdoc = status["cluster"]["tenants"]
        assert tdoc["quotas"].get(tag) == 5.0
        assert tag in tdoc["throttled_tags"]
        assert tdoc["num_tenants"] == 1
        # Proxy-side write metering surfaced per tenant.
        roles = status["cluster"]["roles"]["commit_proxies"]
        writes = {}
        for entry in roles.values():
            for n, v in entry.get("tenants", {})["write_ops"].items():
                writes[n] = writes.get(n, 0) + v
        assert writes.get("hot", 0) >= 1
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=600)
