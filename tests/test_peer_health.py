"""Gray-failure observability (ISSUE 18): the peer-health plane.

A gray-clogged link (latency inflated, delivery still succeeds) is
invisible to failure monitoring — only the per-peer telemetry (transport
RTT EMAs + the worker health monitor's ping verdicts, server/health.py)
can see it.  This battery proves:

* unit: PeerMetrics EMA/window arithmetic;
* a quiescent cluster reports ZERO degraded peers (no false positives);
* a grayClog-ed link is reported degraded within the detection budget
  (3 stats-emit intervals) IDENTICALLY on all three surfaces: status
  cluster.peer_health, the \xff\xff/metrics/peer_health/ special keys,
  and fdbcli `metrics`;
* one gray link never convicts a process under the default K=2
  reporter bar, while K=1 convicts (and ages out when reports stop);
* the knob-gated CC_HEALTH_TRIGGERED_RECOVERY hook: OFF (default) a
  degraded TLog host never triggers recovery; ON it does;
* double-run unseed verification: the whole plane (pings, verdicts,
  re-registrations, grayClog nemesis) is sim-deterministic.
"""

import json
import os

import pytest

from foundationdb_tpu.core.knobs import server_knobs
from foundationdb_tpu.core.scheduler import delay, now
from foundationdb_tpu.core.trace import (Severity, Tracer, get_tracer,
                                         set_tracer)
from foundationdb_tpu.rpc.peer_metrics import EMA_ALPHA, PeerMetrics

from test_recovery import commit_kv, make_cluster, teardown  # noqa: F401

SPECS = os.path.join(os.path.dirname(__file__), "specs")

# The sim Worker announces stats every 10s (worker._stats_announce_loop);
# ISSUE 18's detection budget is three emit intervals end to end.
EMIT_INTERVAL_S = 10.0
DETECTION_BUDGET_S = 3 * EMIT_INTERVAL_S


@pytest.fixture()
def knobs():
    """Mutable server knobs restored after the test."""
    k = server_knobs()
    saved = dict(k.__dict__)
    yield k
    for name, value in saved.items():
        setattr(k, name, value)


# ---------------------------------------------------------------------------
# Unit: PeerMetrics arithmetic
# ---------------------------------------------------------------------------

def test_peer_metrics_ema_and_window():
    pm = PeerMetrics("1.2.3.4:1")
    assert pm.rtt_ema is None
    pm.record_rtt(0.100, at=1.0)
    assert pm.rtt_ema == pytest.approx(0.100)   # first sample seeds the EMA
    pm.record_rtt(0.200, at=2.0)
    assert pm.rtt_ema == pytest.approx(
        (1 - EMA_ALPHA) * 0.100 + EMA_ALPHA * 0.200)
    pm.record_timeout()
    pm.record_disconnect()
    assert pm.take_window() == (4, 2)           # 2 replies + 2 failures
    assert pm.take_window() == (0, 0)           # window resets
    doc = pm.to_doc()
    assert doc["replies"] == 2 and doc["timeouts"] == 1
    assert doc["disconnects"] == 1


# ---------------------------------------------------------------------------
# Quiescent cluster: zero false positives
# ---------------------------------------------------------------------------

def test_quiescent_cluster_zero_degraded(teardown):  # noqa: F811
    set_tracer(Tracer())
    c = make_cluster()
    db = c.database()

    async def go():
        await commit_kv(db, b"q", b"1")
        # Several full verdict windows of healthy pings.
        await delay(8.0)
        return await db.cluster.get_status()

    doc = c.run_until(c.loop.spawn(go()), timeout=120)
    ph = doc["cluster"]["peer_health"]
    assert ph["links"] == []
    assert ph["degraded_processes"] == []
    assert doc["cluster"]["degraded_processes"] == []
    assert not get_tracer().find("PeerDegraded")
    # Severity ledger (satellite: status cluster.messages) counts the
    # boot's events; a healthy run has info traffic and no errors.
    msgs = doc["cluster"]["messages"]
    assert msgs["severity_counts"].get("info", 0) > 0
    assert msgs["severity_counts"].get("error", 0) == 0
    assert msgs["error_count"] == 0
    assert msgs["events_emitted"] > 0
    # Staleness stamps: every live worker reported recently.
    procs = doc["cluster"]["processes"]
    assert procs and all(not p["stale"] for p in procs.values()), procs
    assert all(p["seconds_since_last_report"] >= 0.0
               for p in procs.values())


# ---------------------------------------------------------------------------
# grayClog -> detection on all three surfaces within the budget
# ---------------------------------------------------------------------------

def test_gray_link_detected_on_all_three_surfaces(teardown):  # noqa: F811
    from foundationdb_tpu.tools.fdbcli import Cli
    set_tracer(Tracer())
    c = make_cluster()
    db = c.database()
    a, b = c.workers[0][0], c.workers[1][0]

    async def go():
        await commit_kv(db, b"g", b"1")
        await delay(3.0)               # health monitors discover peers
        t_fault = now()
        c.sim.gray_clog_pair(a, b, 0.2, 600.0)
        doc = None
        while now() < t_fault + DETECTION_BUDGET_S:
            await delay(1.0)
            doc = await db.cluster.get_status()
            if doc["cluster"]["peer_health"]["links"]:
                break
        detect_s = now() - t_fault
        t = db.create_transaction()
        rows = await t.get_range(b"\xff\xff/metrics/peer_health/",
                                 b"\xff\xff/metrics/peer_health0",
                                 limit=100)
        point = await db.create_transaction().get(rows[0][0]) if rows \
            else None
        return doc, detect_s, rows, point

    def row_key(raw):
        # report_age advances between two status renders of the same
        # link — identity is the (reporter, peer, since) edge.
        r = json.loads(raw)
        return (r["reporter"], r["peer"], r["since"])

    doc, detect_s, rows, point = c.run_until(c.loop.spawn(go()),
                                             timeout=240)
    # 1. status: the degraded LINK names the grayed pair, both ways.
    ph = doc["cluster"]["peer_health"]
    assert ph["links"], f"no degraded link within {detect_s:.1f}s"
    assert detect_s <= DETECTION_BUDGET_S
    ips = {a.address.ip, b.address.ip}
    for row in ph["links"]:
        assert row["reporter_address"].split(":")[0] in ips, row
        assert row["peer"].split(":")[0] in ips, row
        assert row["rtt_ema"] is None or row["rtt_ema"] > \
            server_knobs().PEER_DEGRADED_LATENCY_S or \
            row["timeout_fraction"] >= server_knobs().PEER_TIMEOUT_FRACTION
    # ONE gray link blames each endpoint at one reporter — under the
    # default K=2 bar neither process is convicted.
    assert ph["required_reporters"] == 2
    assert ph["degraded_processes"] == []
    assert doc["cluster"]["degraded_processes"] == []
    # PeerDegraded fired at SevWarn (satellite: severity filter).
    evs = get_tracer().find("PeerDegraded", min_severity=Severity.Warn)
    assert evs and all(e["Severity"] == Severity.Warn for e in evs)
    assert not get_tracer().find("PeerDegraded",
                                 min_severity=Severity.Error)
    # 2. special keys render the same links (same doc by construction).
    link_rows = [(k, v) for k, v in rows
                 if k.startswith(b"\xff\xff/metrics/peer_health/link/")]
    assert len(link_rows) == len(ph["links"])
    parsed = [json.loads(v) for _k, v in link_rows]
    assert sorted((r["reporter"], r["peer"]) for r in parsed) == \
        sorted((r["reporter"], r["peer"]) for r in ph["links"])
    assert point is not None            # point get sees the same link
    assert row_key(point) == row_key(rows[0][1])
    # 3. fdbcli `metrics` prints the same section.
    cli = Cli.__new__(Cli)
    cli.loop, cli.db = c.loop, db
    out = cli.dispatch("metrics peer_health")
    assert "Peer health" in out, out
    assert any(row["peer"] in out for row in ph["links"]), out


# ---------------------------------------------------------------------------
# Conviction bar + recovery knob (off-posture and on)
# ---------------------------------------------------------------------------

def _tlog_host_ip(cc) -> str:
    """ip of the worker hosting the current generation's first TLog."""
    iface = cc.db_info.tlogs[0]
    for v in vars(iface).values():
        ep = getattr(v, "_endpoint", None) or getattr(v, "ep", None)
        if ep is not None:
            return ep.address.ip
    raise AssertionError("no endpoint on TLog interface")


def test_single_reporter_convicts_and_recovery_stays_off(
        teardown, knobs):  # noqa: F811
    """K=1: one gray link convicts both endpoints — and with
    CC_HEALTH_TRIGGERED_RECOVERY off (default) a degraded TLog host
    still never triggers a recovery (bit-identical off-posture)."""
    set_tracer(Tracer())
    knobs.CC_DEGRADATION_REPORTERS = 1
    c = make_cluster()
    db = c.database()

    async def go():
        await commit_kv(db, b"k", b"1")
        cc = c.current_cc()
        epoch0 = cc.db_info.epoch
        tlog_ip = _tlog_host_ip(cc)
        victim = next(p for p, *_ in c.workers
                      if p.address.ip == tlog_ip)
        other = next(p for p, *_ in c.workers
                     if p.address.ip != tlog_ip)
        await delay(3.0)
        c.sim.gray_clog_pair(victim, other, 0.2, 600.0)
        deadline = now() + DETECTION_BUDGET_S
        doc = None
        while now() < deadline:
            await delay(1.0)
            doc = await db.cluster.get_status()
            if doc["cluster"]["degraded_processes"]:
                break
        # Grace period: the recovery hook would fire within a ping
        # interval of conviction if it were (wrongly) armed.  Re-fetch:
        # by now BOTH endpoints of the link have crossed hysteresis.
        await delay(5.0)
        doc = await db.cluster.get_status()
        return doc, epoch0, tlog_ip, c.current_cc().db_info.epoch

    doc, epoch0, tlog_ip, epoch1 = c.run_until(c.loop.spawn(go()),
                                               timeout=240)
    degraded = doc["cluster"]["peer_health"]["degraded_processes"]
    assert degraded, doc["cluster"]["peer_health"]
    assert any(e["address"].split(":")[0] == tlog_ip for e in degraded)
    assert all(len(e["reporters"]) >= 1 for e in degraded)
    # Knob off: no recovery, no trigger event — ever.
    assert epoch1 == epoch0
    assert not get_tracer().find("CCHealthTriggeredRecovery")


def test_health_triggered_recovery_when_enabled(teardown, knobs):  # noqa: F811
    set_tracer(Tracer())
    knobs.CC_DEGRADATION_REPORTERS = 1
    knobs.CC_HEALTH_TRIGGERED_RECOVERY = True
    c = make_cluster()
    db = c.database()

    async def go():
        await commit_kv(db, b"r", b"1")
        cc = c.current_cc()
        epoch0 = cc.db_info.epoch
        tlog_ip = _tlog_host_ip(cc)
        victim = next(p for p, *_ in c.workers
                      if p.address.ip == tlog_ip)
        other = next(p for p, *_ in c.workers
                     if p.address.ip != tlog_ip)
        await delay(3.0)
        c.sim.gray_clog_pair(victim, other, 0.2, 600.0)
        deadline = now() + DETECTION_BUDGET_S + 15.0
        while now() < deadline:
            await delay(1.0)
            if get_tracer().find("CCHealthTriggeredRecovery"):
                break
        c.sim.ungray_pair(victim, other)
        # The triggered recovery must complete back to a serving state.
        while now() < deadline + 60.0:
            cc2 = c.current_cc()
            if cc2 is not None and cc2.db_info.epoch > epoch0 and \
                    cc2.db_info.recovery_state in ("accepting_commits",
                                                   "fully_recovered"):
                return epoch0, cc2.db_info.epoch
            await delay(1.0)
        return epoch0, c.current_cc().db_info.epoch

    epoch0, epoch1 = c.run_until(c.loop.spawn(go()), timeout=300)
    evs = get_tracer().find("CCHealthTriggeredRecovery")
    assert evs, "recovery hook never fired with the knob on"
    assert evs[0]["Role"] in ("tlog", "resolver")
    assert epoch1 > epoch0
    # ... and commits still flow afterwards.
    c.run_until(c.loop.spawn(commit_kv(db, b"r2", b"2")), timeout=120)


# ---------------------------------------------------------------------------
# Determinism: the whole plane under the unseed verifier
# ---------------------------------------------------------------------------

GRAY_SPEC = """
[[test]]
testTitle = 'GrayFailureDeterminism'

  [[test.workload]]
  testName = 'Cycle'
  nodeCount = 8
  actorCount = 3
  testDuration = 8.0

  [[test.workload]]
  testName = 'ChaosNemesis'
  testDuration = 8.0
  swizzle = false
  attrition = false
  partitions = false
  grayClog = true

  [[test.workload]]
  testName = 'ConsistencyCheck'
"""


def test_gray_failure_double_run_unseed_identical(teardown):  # noqa: F811
    """Same seed, two runs, with pings, verdict flips, event-driven
    re-registrations and the grayClog nemesis all inside the digest:
    unseed, digest and fold counts must be bit-identical."""
    from foundationdb_tpu.testing import run_test_twice
    r1, r2 = run_test_twice(GRAY_SPEC, seed=311)
    assert r1.unseed == r2.unseed and r1.digest == r2.digest
    assert r1.folds == r2.folds and r1.folds > 0
    assert r1.nondeterminism == [] and r2.nondeterminism == []
