"""Tests for the core runtime (futures, actors, scheduler, streams, knobs)."""

import pytest

from foundationdb_tpu.core import (AsyncVar, FdbError, Future, Promise,
                                   PromiseStream, TaskPriority, buggify,
                                   delay, enable_buggify, err, now, quorum,
                                   spawn, wait_all, wait_any)


def test_promise_future_basic(loop):
    p = Promise()
    f = p.get_future()
    assert not f.is_ready()
    p.send(42)
    assert f.is_ready() and f.get() == 42


def test_future_error(loop):
    p = Promise()
    p.send_error(err("not_committed"))
    with pytest.raises(FdbError) as ei:
        p.get_future().get()
    assert ei.value.code == 1020


def test_actor_await_chain(loop):
    async def child(x):
        await delay(1.0)
        return x * 2

    async def parent():
        a = await spawn(child(10))
        b = await spawn(child(a))
        return b

    result = loop.run_until(spawn(parent()))
    assert result == 40
    assert loop.now() == pytest.approx(2.0)


def test_actor_error_propagation(loop):
    async def failing():
        await delay(0.5)
        raise err("transaction_too_old")

    async def catching():
        try:
            await spawn(failing())
        except FdbError as e:
            return e.code

    assert loop.run_until(spawn(catching())) == 1007


def test_actor_cancellation(loop):
    state = {"cleaned": False}

    async def long_actor():
        try:
            await delay(1000.0)
        finally:
            state["cleaned"] = True

    f = spawn(long_actor())
    loop.run_for(1.0)
    f.cancel()
    loop.run_for(1.0)
    assert state["cleaned"]
    assert f.is_error() and f.error.code == 1101  # operation_cancelled


def test_deterministic_ordering(loop):
    """Two identical runs interleave identically."""
    def run_once():
        from foundationdb_tpu.core import (DeterministicRandom, EventLoop,
                                           set_deterministic_random,
                                           set_event_loop)
        lp = EventLoop(sim=True)
        set_event_loop(lp)
        set_deterministic_random(DeterministicRandom(7))
        order = []

        async def worker(name, n):
            from foundationdb_tpu.core import deterministic_random
            for _ in range(n):
                await delay(deterministic_random().random01() * 0.01)
                order.append(name)

        fs = [spawn(worker(f"w{i}", 5)) for i in range(4)]
        lp.run_until(wait_all(fs))
        return order

    assert run_once() == run_once()


def test_wait_any_and_quorum(loop):
    async def sleeper(t, v):
        await delay(t)
        return v

    f = wait_any([spawn(sleeper(5.0, "slow")), spawn(sleeper(1.0, "fast"))])
    idx, val = loop.run_until(f)
    assert (idx, val) == (1, "fast")

    q = quorum([spawn(sleeper(1.0, 1)), spawn(sleeper(2.0, 2)),
                spawn(sleeper(30.0, 3))], 2)
    loop.run_until(q)
    assert loop.now() < 10.0


def test_promise_stream(loop):
    ps = PromiseStream()

    async def producer():
        for i in range(5):
            await delay(0.1)
            ps.send(i)
        ps.close()

    async def consumer():
        got = []
        async for v in ps:
            got.append(v)
        return got

    spawn(producer())
    assert loop.run_until(spawn(consumer())) == [0, 1, 2, 3, 4]


def test_async_var(loop):
    av = AsyncVar(1)

    async def watcher():
        seen = [av.get()]
        while len(seen) < 3:
            await av.on_change()
            seen.append(av.get())
        return seen

    async def setter():
        await delay(0.1)
        av.set(2)
        await delay(0.1)
        av.set(3)

    f = spawn(watcher())
    spawn(setter())
    assert loop.run_until(f) == [1, 2, 3]


def test_priority_ordering(loop):
    """Same-time callbacks run in priority order, then FIFO."""
    order = []
    loop.call_at(1.0, lambda: order.append("low"), TaskPriority.Low)
    loop.call_at(1.0, lambda: order.append("high"), TaskPriority.TLogCommit)
    loop.call_at(1.0, lambda: order.append("high2"), TaskPriority.TLogCommit)
    loop.drain()
    assert order == ["high", "high2", "low"]


def test_buggify_deterministic(loop):
    enable_buggify(True)
    fires1 = [buggify("test-site") for _ in range(100)]
    enable_buggify(False)
    assert not any(buggify("test-site") for _ in range(10))
    assert isinstance(fires1[0], bool)


def test_virtual_time_jump(loop):
    """Sim time jumps over idle periods instantly."""
    import time as wall

    async def long_wait():
        await delay(3600.0)
        return now()

    t0 = wall.monotonic()
    result = loop.run_until(spawn(long_wait()))
    assert result == pytest.approx(3600.0)
    assert wall.monotonic() - t0 < 1.0


def test_cancel_with_async_cleanup(loop):
    """A cancelled actor's finally-block awaits still run to completion."""
    state = {"flushed": False}

    async def flush():
        await delay(0.5)
        state["flushed"] = True

    async def worker():
        try:
            await delay(1000.0)
        finally:
            await spawn(flush())

    f = spawn(worker())
    loop.run_for(1.0)
    f.cancel()
    assert f.is_error() and f.error.code == 1101
    loop.run_for(10.0)
    assert state["flushed"]


def test_cancel_before_start(loop):
    """Cancelling before the first step means the body never runs."""
    state = {"ran": False}

    async def body():
        state["ran"] = True

    f = spawn(body())
    f.cancel()
    loop.drain()
    assert not state["ran"]
    assert f.is_error() and f.error.code == 1101


def test_dropped_promise_breaks(loop):
    import gc
    p = Promise()
    fut = p.get_future()
    del p
    gc.collect()
    assert fut.is_error() and fut.error.code == 1100  # broken_promise


def test_combinator_no_callback_leak(loop):
    from foundationdb_tpu.core import wait_any

    shutdown = Promise()

    async def looper():
        for _ in range(5):
            await wait_any([shutdown.get_future(), delay(0.1)])

    loop.run_until(spawn(looper()))
    assert len(shutdown.get_future()._callbacks) == 0


def test_quorum_impossible(loop):
    from foundationdb_tpu.core import ready_future
    q = quorum([ready_future(1)], 2)
    assert q.is_error()


def test_run_until_deadlock_is_not_timeout(loop):
    p = Promise()  # keep alive: a dropped promise would break instead
    with pytest.raises(FdbError) as ei:
        loop.run_until(p.get_future())
    assert ei.value.code == 4100  # internal_error, not timed_out
