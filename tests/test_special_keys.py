"""SpecialKeySpace modules (reference SpecialKeySpace.actor.cpp): status
json and the management mirror readable through plain transaction gets,
alongside the existing conflicting-keys module."""

import json

import pytest

from foundationdb_tpu.client.management import exclude_servers
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration

from test_recovery import commit_kv, teardown  # noqa: F401


def test_status_json_and_management_special_keys(teardown):  # noqa: F811
    c = SimFdbCluster(config=DatabaseConfiguration(), n_workers=5,
                      n_storage_workers=3)
    db = c.database()

    async def go():
        await commit_kv(db, b"k", b"v")
        t = db.create_transaction()
        raw = await t.get(b"\xff\xff/status/json")
        assert raw is not None
        doc = json.loads(raw)
        assert doc["cluster"]["database_available"] is True
        assert doc["cluster"]["coordinators"]["quorum"]
        # Process sections carry SystemMonitor-style machine stats.
        procs = doc["cluster"]["processes"]
        assert procs and any("cpu" in p for p in procs.values())
        # Management module mirrors the exclusion list.
        t2 = db.create_transaction()
        assert await t2.get(b"\xff\xff/management/excluded/2") is None
        await exclude_servers(db, [2])
        t3 = db.create_transaction()
        assert await t3.get(b"\xff\xff/management/excluded/2") == b"1"
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=180)
