"""Hedged storage reads (reference fdbrpc/LoadBalance.actor.h second
requests): a slow-but-alive replica costs the hedge delay, not its full
stall — the duplicate request to the next replica wins the race."""

import pytest

from foundationdb_tpu.client.database import Database
from foundationdb_tpu.core.scheduler import EventLoop, delay, set_event_loop
from foundationdb_tpu.rpc.endpoint import RequestStream
from foundationdb_tpu.rpc.network import SimNetwork, set_network
from foundationdb_tpu.rpc.sim import Simulator, set_simulator


class _StubSSI:
    def __init__(self, sim, name, reply_value, latency):
        self.process = sim.new_process(name=name)
        self.stream = RequestStream(f"{name}.get")
        self.process.register(self.stream)
        self.latency = latency
        self.reply_value = reply_value
        self.process.spawn(self._serve(), f"{name}.serve")

    async def _serve(self):
        async for req in self.stream.queue:
            self.process.spawn(self._answer(req), "answer")

    async def _answer(self, req):
        await delay(self.latency)
        req.reply.send(self.reply_value)


class _Req:
    reply = None


def teardown_function(_fn):
    set_simulator(None)
    set_network(None)
    set_event_loop(None)


def test_hedge_beats_slow_replica():
    loop = EventLoop(sim=True)
    set_event_loop(loop)
    sim = Simulator()
    set_simulator(sim)
    db = Database.__new__(Database)
    db._replica_latency = {}
    db._rr = 0
    slow = _StubSSI(sim, "slow", b"from-slow", latency=10.0)
    fast = _StubSSI(sim, "fast", b"from-fast", latency=0.005)
    # History says `slow` used to be the better replica (band 0 vs 1), so
    # the first read PREFERS it — the stall is only survivable via the
    # hedge.  (With no history the band round-robin may dodge the test.)
    db._replica_latency[db._replica_key(fast)] = 0.06

    async def go():
        t0 = loop.now()
        reply = await db.read_replica(
            [slow, fast], lambda s: s.stream, lambda: _Req())
        took = loop.now() - t0
        # The hedge fired after ~75ms and the fast replica answered;
        # nothing waited the 10s stall.
        assert reply == b"from-fast"
        assert took < 1.0, took
        # The laggard was demoted: the NEXT read prefers the fast one
        # outright (no hedge delay at all).
        t1 = loop.now()
        reply = await db.read_replica(
            [slow, fast], lambda s: s.stream, lambda: _Req())
        assert reply == b"from-fast"
        assert loop.now() - t1 < 0.05
        return True

    assert loop.run_until(loop.spawn(go(), "go"), timeout=60)
