"""Tenant-salted digest fast path (ISSUE 2 acceptance): randomized
tenant-prefixed workloads produce BIT-IDENTICAL commit/abort verdicts
between the supervised TPU backend and the CPU oracle, and tenant-
relative short keys never route through the supervisor's long-key exact
recheck (taint/recheck counters stay zero).

Why this holds: an 8-byte tenant prefix fills exactly the digest's
tenant-salt column (ops/digest.py SALT_LANES), leaving the full 23-byte
relative span for the tenant's own key — so prefixed keys of relative
length <= 23 digest exactly (total <= PREFIX_BYTES = 31)."""

import pytest

from foundationdb_tpu.conflict.encoded import EncodedBatch
from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.conflict.supervisor import SupervisedConflictSet
from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet
from foundationdb_tpu.core import DeterministicRandom
from foundationdb_tpu.ops.digest import PREFIX_BYTES, SALT_BYTES
from foundationdb_tpu.tenant.map import tenant_prefix
from foundationdb_tpu.txn import CommitTransactionRef, KeyRange


def make_supervised():
    return SupervisedConflictSet(
        lambda oldest_version=0: TpuConflictSet(oldest_version,
                                                capacity=1 << 12))


def random_tenant_txn(rng, now, window, n_tenants=4):
    """Point reads/writes on tenant-prefixed keys with relative length
    <= 23 — the shape ALL tenant traffic has (tenant/handle.py)."""
    snap = now - rng.random_int(0, window)
    tr = CommitTransactionRef(read_snapshot=max(snap, 0))

    def key():
        p = tenant_prefix(rng.random_int(1, n_tenants))
        rel = b"k%02d" % rng.random_int(0, 40)
        if rng.coinflip():
            rel += b"/sub%08d" % rng.random_int(0, 99)   # up to 16 bytes
        assert len(rel) <= PREFIX_BYTES - SALT_BYTES
        return p + rel

    for _ in range(rng.random_int(0, 3)):
        k = key()
        tr.read_conflict_ranges.append(KeyRange(k, k + b"\x00"))
    for _ in range(rng.random_int(0, 2)):
        k = key()
        tr.write_conflict_ranges.append(KeyRange(k, k + b"\x00"))
    return tr


@pytest.mark.parametrize("seed", [131, 132, 133])
def test_tenant_abort_set_parity_and_fast_path(seed):
    """Bit-identical verdicts TPU-vs-oracle on tenant-prefixed traffic,
    with ZERO batches routed through the exact long-key recheck."""
    rng = DeterministicRandom(seed)
    oracle = OracleConflictSet(0)
    sup = make_supervised()
    now = 0
    for _ in range(30):
        now += rng.random_int(1, 2_000_000)
        batch = [random_tenant_txn(rng, now, 4_000_000)
                 for _ in range(rng.random_int(1, 10))]
        new_oldest = now - 5_000_000 if rng.coinflip() else None
        got = sup.resolve(batch, now, new_oldest)
        want = oracle.resolve(batch, now, new_oldest)
        assert got == want, f"tenant parity divergence at now={now}"
    # Fast-path assertion (ISSUE acceptance): tenant-relative short keys
    # must NOT hit the long-key machinery — no recheck, no taint, and
    # the device (not the mirror fallback) carried every batch.
    assert sup.stats["rechecked_batches"] == 0, sup.stats
    assert sup.stats["taint_size"] == 0
    assert sup.stats["fallback_batches"] == 0
    assert sup.stats["device_batches"] == 30


def test_tenant_point_batches_take_compact_path():
    """Tenant-prefixed point batches qualify for the all_point compact
    device layout (the cheapest kernel): the salt column keeps them
    under the digest prefix."""
    txns = []
    for tid in (1, 2, 3):
        txns.append(CommitTransactionRef(
            read_snapshot=0,
            read_conflict_ranges=[KeyRange(
                tenant_prefix(tid) + b"k", tenant_prefix(tid) + b"k\x00")],
            write_conflict_ranges=[KeyRange(
                tenant_prefix(tid) + b"w%02d" % tid,
                tenant_prefix(tid) + b"w%02d\x00" % tid)]))
    enc = EncodedBatch.from_transactions(txns)
    assert enc.all_point
    packed = TpuConflictSet._pack_compact(enc)
    assert packed is not None and packed["compact"]
    # The salt column carries the tenant prefixes: lane pair (0, 1)
    # equals each key's first 8 bytes big-endian.
    import numpy as np
    salts = enc.w_salt
    assert salts.shape[0] == 2
    expect = [int.from_bytes(tenant_prefix(t), "big") for t in (1, 2, 3)]
    got = (salts[0].astype(np.uint64) << np.uint64(32)) | \
        salts[1].astype(np.uint64)
    assert list(got) == expect


def test_cross_tenant_same_relative_key_no_conflict_on_device():
    """Two tenants writing the SAME relative key never conflict at the
    resolver: their salted digests differ in the salt column."""
    sup = make_supervised()
    oracle = OracleConflictSet(0)
    ka = tenant_prefix(1) + b"hot"
    kb = tenant_prefix(2) + b"hot"
    w_a = CommitTransactionRef(
        write_conflict_ranges=[KeyRange(ka, ka + b"\x00")])
    assert sup.resolve([w_a], 100) == oracle.resolve([w_a], 100)
    # Tenant 2 reads its own "hot" at an old snapshot: tenant 1's write
    # must NOT conflict it; tenant 1's own reader MUST conflict.
    r_b = CommitTransactionRef(
        read_snapshot=50,
        read_conflict_ranges=[KeyRange(kb, kb + b"\x00")])
    r_a = CommitTransactionRef(
        read_snapshot=50,
        read_conflict_ranges=[KeyRange(ka, ka + b"\x00")])
    got = sup.resolve([r_b, r_a], 200)
    want = oracle.resolve([r_b, r_a], 200)
    from foundationdb_tpu.txn import CommitResult
    assert got == want == [CommitResult.COMMITTED, CommitResult.CONFLICT]
    assert sup.stats["rechecked_batches"] == 0
