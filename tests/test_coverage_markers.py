"""Coverage markers (reference flow TEST() + TestHarness coverage ledger):
the registry records which interesting code paths tests exercised; the
ensemble runner reports never-hit markers."""

from foundationdb_tpu.core import coverage


def test_coverage_registry_and_hits():
    coverage.register("UnitTestOnlyMarker")
    assert not coverage.covered("UnitTestOnlyMarker")
    assert "UnitTestOnlyMarker" in coverage.missing()
    coverage.test_coverage("UnitTestOnlyMarker")
    coverage.test_coverage("UnitTestOnlyMarker")
    assert coverage.covered("UnitTestOnlyMarker")
    assert coverage.hits("UnitTestOnlyMarker") == 2
    assert "UnitTestOnlyMarker" not in coverage.missing()
    # The built-in ledger knows the codebase's marked paths even before
    # they fire.
    assert "RecoveryRegionFailover" in coverage.report()
    # Disaster-recovery nemesis battery (ISSUE 10): run_chaos.py's
    # summary ledger must list these whether or not a run hit them.
    for marker in ("ChaosRegionFailover", "ChaosCoordinatorRestart",
                   "ChaosFatalDiskRestart", "BackupRestoreUnderChaos",
                   "ChaosNemesisGrayClog"):
        assert marker in coverage.report(), marker
