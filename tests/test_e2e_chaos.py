"""E2eThroughputTest chaos battery (ISSUE 14): every commit-pipeline
fast-path knob ON under the swizzle nemesis + resolver attrition, with
the exactly-once repair audit and double-run unseed verification —
perf-path claims must hold under chaos, not quiescence."""

import os

from test_recovery import teardown  # noqa: F401

SPECS = os.path.join(os.path.dirname(__file__), "specs")


def test_e2e_throughput_chaos_double_run(teardown):  # noqa: F811
    from foundationdb_tpu.core import coverage
    from foundationdb_tpu.core.knobs import client_knobs, server_knobs
    from foundationdb_tpu.testing.tester import run_test_twice
    r1, r2 = run_test_twice(
        os.path.join(SPECS, "E2eThroughputTest.toml"), seed=4242)
    assert r1.unseed == r2.unseed and r1.digest == r2.digest
    # The workload mix actually ran and audited.
    m = r1.metrics["SchedRepairLoad"]
    assert m["acked"] > 0 and m["failed"] == 0
    assert m["acked"] <= m["hot_total"] <= m["acked"] + m["unknown"]
    assert r1.metrics["Cycle"]["swaps"] > 0
    assert r1.metrics["ReadWrite"]["operations"] > 0
    assert r1.metrics["ConsistencyCheck"]["shards_audited"] > 0
    # Repair (ladder posture, TXN_REPAIR_MAX_ATTEMPTS=2) exercised under
    # the nemesis.
    assert coverage.covered("ProxyTxnRepaired")
    assert coverage.covered("ChaosNemesisResolverKill")
    # Spec knob overrides were restored (client knobs included — the
    # lease/batch posture must not leak into later tests).
    assert server_knobs().RPC_COLUMNAR_ENABLED is False
    assert server_knobs().PROXY_VECTORIZED_ASSEMBLY is False
    assert client_knobs().GRV_BATCH_ENABLED is False
    assert client_knobs().GRV_LEASE_S == 0.0


def test_e2e_spec_in_chaos_matrix():
    """run_chaos.py runs the spec by default (the seed-matrix runner's
    coverage ledger keeps it honest)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "run_chaos_under_test",
        os.path.join(os.path.dirname(__file__), os.pardir, "scripts",
                     "run_chaos.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert "E2eThroughputTest.toml" in mod.DEFAULT_SPECS
