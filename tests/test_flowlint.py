"""flowlint (ISSUES 5 + 9): rule-engine behavior, one positive fixture
per rule with exact FTL id + line assertions, suppression/baseline
round-trips, the clean-repo gate (tier-1's static-analysis entry, the
way test_metrics.py runs check_trace_events), the ISSUE-9 dataflow
layer (CFG/def-use/lockset unit battery + FTL010/011/012 + widened
FTL005), --changed incremental mode, and cross-process unseed
reproduction with PYTHONHASHSEED pinned (the ROADMAP chaos follow-up,
driven by the HashOrderCanary workload)."""

import ast
import json
import os
import re
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "flowlint")
FLOWLINT = os.path.join(REPO, "scripts", "flowlint.py")

from foundationdb_tpu.analysis.dataflow import FunctionDataflow
from foundationdb_tpu.analysis.engine import (Analyzer, is_actor,
                                              load_baseline,
                                              write_baseline)
from foundationdb_tpu.analysis.rules import make_rules

EXPECT = re.compile(r"(FTL\d{3}):(\d+)")

N_RULES = 12    # FTL001..FTL012 (FTL000 = unparseable-file pseudo-rule)


def _scan(roots, baseline=None):
    return Analyzer(make_rules()).run(roots, baseline)


def _expected_fixture_findings():
    """(rule, relpath, line) triples from the `# expect:` marker lines
    committed inside each fixture."""
    exp = set()
    for dirpath, dirnames, filenames in os.walk(FIXTURES):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, FIXTURES).replace(os.sep, "/")
            with open(path) as f:
                for line in f:
                    if "# expect:" in line:
                        for m in EXPECT.finditer(line):
                            exp.add((m.group(1), rel, int(m.group(2))))
    return exp


# ---------------------------------------------------------------------------
# Fixtures: every rule fires with its exact id and line, nothing extra
# ---------------------------------------------------------------------------

def test_fixture_findings_exact():
    expected = _expected_fixture_findings()
    assert len(expected) >= N_RULES, "fixture markers went missing"
    # Every rule id is represented by at least one fixture expectation.
    assert {f"FTL{i:03d}" for i in range(1, N_RULES + 1)} <= \
        {rule for rule, _, _ in expected}
    result = _scan([FIXTURES])
    got = {(f.rule, f.path, f.line) for f in result.new}
    assert got == expected, (
        f"unexpected: {sorted(got - expected)}\n"
        f"missing: {sorted(expected - got)}")


def test_clean_fixture_has_no_findings():
    result = _scan([os.path.join(FIXTURES, "clean.py")])
    assert result.new == [] and result.suppressed == 0


def test_unparseable_file_reported_not_skipped(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    result = _scan([str(tmp_path)])
    assert [f.rule for f in result.new] == ["FTL000"]


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------

def test_inline_suppression_by_id(tmp_path):
    (tmp_path / "a.py").write_text(
        "import time\n"
        "t = time.monotonic()  # flowlint: disable=FTL001 -- fixture\n")
    result = _scan([str(tmp_path)])
    assert result.new == [] and result.suppressed == 1


def test_suppression_wrong_id_does_not_apply(tmp_path):
    (tmp_path / "a.py").write_text(
        "import time\n"
        "t = time.monotonic()  # flowlint: disable=FTL006\n")
    result = _scan([str(tmp_path)])
    assert [f.rule for f in result.new] == ["FTL001"]


def test_file_wide_suppression(tmp_path):
    (tmp_path / "a.py").write_text(
        "# flowlint: disable-file=FTL001 -- fixture\n"
        "import time\n"
        "t1 = time.monotonic()\n"
        "t2 = time.time()\n")
    result = _scan([str(tmp_path)])
    assert result.new == [] and result.suppressed == 2


def test_file_wide_suppression_covers_cross_file_ftl007(tmp_path):
    """disable-file=FTL007 removes the file's callsites from the
    cross-file schema comparison — finish()-time findings must not
    bypass the suppression mechanism."""
    (tmp_path / "a.py").write_text(
        "# flowlint: disable-file=FTL007 -- divergent schema on purpose\n"
        'TraceEvent("Shared").detail("A", 1).log()\n')
    (tmp_path / "b.py").write_text(
        'TraceEvent("Shared").detail("B", 1).log()\n')
    result = _scan([str(tmp_path)])
    assert result.new == [], [f.message for f in result.new]
    # And the control: without the suppression the drift IS reported.
    (tmp_path / "a.py").write_text(
        'TraceEvent("Shared").detail("A", 1).log()\n')
    result = _scan([str(tmp_path)])
    assert [f.rule for f in result.new] == ["FTL007"]


def test_suppress_all(tmp_path):
    (tmp_path / "a.py").write_text(
        "import time\n"
        "t = time.monotonic()  # flowlint: disable=all\n")
    result = _scan([str(tmp_path)])
    assert result.new == [] and result.suppressed == 1


# ---------------------------------------------------------------------------
# Baseline round-trip
# ---------------------------------------------------------------------------

def test_baseline_round_trip(tmp_path):
    first = _scan([FIXTURES])
    assert first.new, "fixtures must produce findings"
    baseline_path = str(tmp_path / "baseline.json")
    write_baseline(baseline_path, first.new)

    second = _scan([FIXTURES], load_baseline(baseline_path))
    assert second.new == [] and second.exit_code == 0
    assert len(second.baselined) == len(first.new)

    # Dropping one entry resurfaces exactly that finding as NEW.
    entries = load_baseline(baseline_path)
    dropped = entries.pop(0)
    third = _scan([FIXTURES], entries)
    assert len(third.new) == 1
    assert third.new[0].rule == dropped["rule"]
    assert third.new[0].path == dropped["path"]


def test_baseline_is_line_insensitive(tmp_path):
    src = "import time\nt = time.monotonic()\n"
    (tmp_path / "a.py").write_text(src)
    r1 = _scan([str(tmp_path)])
    baseline_path = str(tmp_path / "b.json")
    write_baseline(baseline_path, r1.new)
    # Shift the violation down two lines: still baselined.
    (tmp_path / "a.py").write_text("# pad\n# pad\n" + src)
    r2 = _scan([str(tmp_path)], load_baseline(baseline_path))
    assert r2.new == [] and len(r2.baselined) == 1


def test_single_file_scan_matches_directory_scan_identity(tmp_path):
    """Directly linting one package file yields the same root-relative
    finding path as a directory scan of the package: module exemptions
    (REAL_ONLY_MODULES, 'server/') keep applying and a baseline written
    by the full scan still covers the direct-file lint."""
    # The exemption case: a REAL_ONLY module's sanctioned wall-clock
    # reads must not resurface when the file is linted directly.
    target = os.path.join(REPO, "foundationdb_tpu", "core", "scheduler.py")
    result = _scan([target])
    assert result.new == [], [f"{f.path}:{f.line} {f.rule}"
                              for f in result.new]
    # The baseline-identity case, on a synthetic package.
    pkg = tmp_path / "pkg"
    (pkg / "server").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "server" / "__init__.py").write_text("")
    mod = pkg / "server" / "mod.py"
    mod.write_text("import time\nt = time.monotonic()\n")
    dir_scan = _scan([str(pkg)])
    file_scan = _scan([str(mod)])
    assert {f.key() for f in file_scan.new} == \
        {f.key() for f in dir_scan.new} and dir_scan.new, \
        (dir_scan.new, file_scan.new)
    baseline = [{"rule": f.rule, "path": f.path, "message": f.message}
                for f in dir_scan.new]
    rebased = _scan([str(mod)], baseline)
    assert rebased.new == [] and len(rebased.baselined) == 1


# ---------------------------------------------------------------------------
# CLI: the tier-1 clean-repo gate + JSON output
# ---------------------------------------------------------------------------

def test_repo_is_flowlint_clean():
    """`python scripts/flowlint.py foundationdb_tpu` exits 0 against the
    committed baseline (the ISSUE 5 acceptance gate)."""
    out = subprocess.run(
        [sys.executable, FLOWLINT,
         os.path.join(REPO, "foundationdb_tpu")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr


def test_committed_baseline_within_budget():
    entries = load_baseline(os.path.join(REPO, "flowlint_baseline.json"))
    assert len(entries) <= 10, (
        "baseline grew past the 10-finding budget: fix violations "
        "instead of grandfathering them")


def test_cli_json_format():
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--format", "json", "--baseline",
         "none", FIXTURES],
        capture_output=True, text=True)
    assert out.returncode == 1
    doc = json.loads(out.stdout)
    assert doc["counts"]["new"] == len(doc["findings"]) > 0
    f = doc["findings"][0]
    assert set(f) == {"rule", "path", "line", "message"}


def test_cli_write_baseline_conflicts_with_baseline_none():
    """--write-baseline with --baseline none must error out, NOT fall
    back to silently overwriting the committed default baseline."""
    committed = os.path.join(REPO, "flowlint_baseline.json")
    before = open(committed).read()
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--baseline", "none",
         "--write-baseline", FIXTURES],
        capture_output=True, text=True)
    assert out.returncode == 2
    assert "conflicts" in out.stderr
    assert open(committed).read() == before


def test_cli_list_rules():
    out = subprocess.run([sys.executable, FLOWLINT, "--list-rules"],
                         capture_output=True, text=True)
    assert out.returncode == 0
    for i in range(1, N_RULES + 1):
        assert f"FTL{i:03d}" in out.stdout


def test_list_rules_matches_readme_table():
    """No rule-list drift (ISSUE 9): the shipped rule set and README's
    rule table must name exactly the same FTL ids — a rule added
    without a doc row (or vice versa) fails tier-1."""
    out = subprocess.run([sys.executable, FLOWLINT, "--list-rules"],
                         capture_output=True, text=True)
    cli_ids = set(re.findall(r"FTL\d{3}", out.stdout))
    with open(os.path.join(REPO, "README.md")) as f:
        readme_ids = set(re.findall(r"^\| (FTL\d{3}) ", f.read(), re.M))
    assert readme_ids == cli_ids, (
        f"README table vs --list-rules drift: only in README "
        f"{sorted(readme_ids - cli_ids)}, only in CLI "
        f"{sorted(cli_ids - readme_ids)}")


# ---------------------------------------------------------------------------
# Dataflow layer (ISSUE 9): CFG / def-use / lockset unit battery
# ---------------------------------------------------------------------------

def _cfg(src: str, name=None) -> FunctionDataflow:
    tree = ast.parse(textwrap.dedent(src))
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                (name is None or n.name == name):
            return FunctionDataflow(n)
    raise AssertionError("no function in snippet")


def _load(cfg: FunctionDataflow, name: str, nth: int = 0):
    loads = [(ld, nd) for ld, nd in cfg.loads if ld.id == name]
    return loads[nth]


def _reach_lines(cfg, name, nth=0):
    ld, nd = _load(cfg, name, nth)
    return sorted((d.lineno, crossed)
                  for d, crossed in cfg.reaching(nd, name))


def test_cfg_branch_join_merges_both_defs():
    cfg = _cfg("""\
        async def f(c):
            x = 1
            if c:
                x = 2
            use(x)
        """)
    assert _reach_lines(cfg, "x") == [(2, False), (4, False)]


def test_cfg_branch_else_kills_one_path():
    cfg = _cfg("""\
        async def f(c):
            if c:
                x = 1
            else:
                x = 2
            use(x)
        """)
    assert _reach_lines(cfg, "x") == [(3, False), (5, False)]


def test_cfg_rebind_after_await_kills_stale_fact():
    cfg = _cfg("""\
        async def f(self):
            x = self.a
            await g()
            x = self.b
            use(x)
        """)
    assert _reach_lines(cfg, "x") == [(4, False)]


def test_cfg_await_marks_facts_crossed():
    cfg = _cfg("""\
        async def f(self):
            x = self.a
            await g()
            use(x)
        """)
    assert _reach_lines(cfg, "x") == [(2, True)]


def test_cfg_await_result_def_is_not_crossed():
    # `x = await g()` defines x AFTER the suspension: fresh, not stale.
    cfg = _cfg("""\
        async def f(self):
            x = await g()
            use(x)
        """)
    assert _reach_lines(cfg, "x") == [(2, False)]


def test_cfg_loop_back_edge_carries_crossed_fact():
    cfg = _cfg("""\
        async def f(items):
            x = make()
            for i in items:
                use(x)
                await g()
        """)
    # First iteration sees the pre-loop def uncrossed; every later one
    # sees it through the await barrier on the back edge.
    assert _reach_lines(cfg, "x") == [(2, False), (2, True)]


def test_cfg_try_except_handler_sees_body_defs():
    cfg = _cfg("""\
        def f():
            try:
                x = 1
                risky()
            except ValueError:
                x = 2
            return x
        """)
    assert _reach_lines(cfg, "x") == [(3, False), (6, False)]


def test_cfg_finally_reachable_through_return():
    # The regression the transport sweep hit: `try: return ... finally:`
    # must leave the finalbody REACHABLE, with its own with-lock region
    # intact — otherwise FTL012 sees an empty lockset there.
    cfg = _cfg("""\
        async def f(self, fut):
            try:
                with self._lock:
                    return fut.result(timeout=1)
            finally:
                with self._lock:
                    cleanup(self)
        """)
    cleanup_calls = [
        (c, nd) for c, nd in cfg.calls
        if isinstance(c.func, ast.Name) and c.func.id == "cleanup"]
    assert len(cleanup_calls) == 1
    _, nd = cleanup_calls[0]
    assert cfg.lockset(nd) == frozenset({"self._lock"})


def test_cfg_nested_function_excluded_from_parent():
    cfg = _cfg("""\
        async def outer(self):
            x = self.a

            def inner():
                return x

            await g()
            return 1
        """, name="outer")
    # inner's read of x is NOT a load of the outer CFG...
    assert [ld.id for ld, _ in cfg.loads if ld.id == "x"] == []
    # ... and inner gets its own dataflow with no defs of x.
    inner = _cfg("""\
        async def outer(self):
            x = self.a

            def inner():
                return x

            await g()
            return 1
        """, name="inner")
    ld, nd = _load(inner, "x")
    assert inner.reaching(nd, "x") == []


def test_cfg_lockset_with_region_and_release():
    cfg = _cfg("""\
        async def f(self, fut):
            with self._lock:
                a = fut.wait()
            b = fut.wait()
        """)
    (_, nd_a), (_, nd_b) = cfg.calls[0], cfg.calls[1]
    assert cfg.lockset(nd_a) == frozenset({"self._lock"})
    assert cfg.lockset(nd_b) == frozenset()


def test_cfg_lockset_acquire_release_pair():
    cfg = _cfg("""\
        async def f(self, fut):
            self._lock.acquire()
            a = fut.wait()
            self._lock.release()
            b = fut.wait()
        """)
    waits = [(c, nd) for c, nd in cfg.calls
             if isinstance(c.func, ast.Attribute) and c.func.attr == "wait"]
    assert cfg.lockset(waits[0][1]) == frozenset({"self._lock"})
    assert cfg.lockset(waits[1][1]) == frozenset()


def test_cfg_conditional_acquire_is_not_held():
    # acquire(timeout=...) / acquire(blocking=False) can FAIL: a MUST
    # analysis never counts it as held (review catch — the unsound
    # direction for FTL012).
    cfg = _cfg("""\
        async def f(self, fut):
            self._lock.acquire(timeout=0.1)
            x = fut.result()
        """)
    assert cfg.acquired_locks == set()
    assert cfg.lockset(cfg.calls[-1][1]) == frozenset()


def test_cfg_lockset_meet_is_intersection():
    # Held on only ONE path into the node => not held (MUST analysis).
    cfg = _cfg("""\
        async def f(self, c, fut):
            if c:
                self._lock.acquire()
            x = fut.result()
        """)
    results = [(c, nd) for c, nd in cfg.calls
               if isinstance(c.func, ast.Attribute)
               and c.func.attr == "result"]
    assert cfg.lockset(results[0][1]) == frozenset()


def test_cfg_async_with_is_barrier_not_lock():
    cfg = _cfg("""\
        async def f(self):
            async with self._aio_lock:
                await g()
        """)
    assert cfg.acquired_locks == set()
    (_, nd) = cfg.awaits[0]
    assert cfg.lockset(nd) == frozenset()


def test_ftl005_same_named_helper_is_ambiguous(tmp_path):
    """A set-returning helper NAME shared with a non-set function in
    the same file is ambiguous at a callsite and must not taint it
    (review catch — the FTL002 same-name rule applied to FTL005)."""
    (tmp_path / "a.py").write_text(textwrap.dedent("""\
        class A:
            def make(self):
                return {"x", "y"}

        class B:
            def make(self):
                return ["x", "y"]

            def walk(self):
                s = self.make()
                return [i for i in s]
        """))
    result = _scan([str(tmp_path)])
    assert result.new == [], [f.message for f in result.new]


def test_ftl010_mutable_attrs_are_class_scoped(tmp_path):
    """An attribute name mutated in ONE class must not taint the
    same-named init-frozen attribute of another class in the file
    (review catch — the FTL009 scope lesson)."""
    (tmp_path / "a.py").write_text(textwrap.dedent("""\
        class Churner:
            def churn(self):
                self.cache = {}

            async def bad(self):
                c = self.cache
                await g()
                return c

        class Frozen:
            def __init__(self):
                self.cache = {}

            async def ok(self):
                c = self.cache
                await g()
                return c
        """))
    result = _scan([str(tmp_path)])
    assert [(f.rule, f.line) for f in result.new] == [("FTL010", 8)]


def test_ftl010_comprehension_copy_exempt_generator_flagged(tmp_path):
    """An eager comprehension is a copy snapshot (same policy as
    set()/list() calls); a GENERATOR expression reads the shared state
    lazily — after the await — and stays flagged (review catch)."""
    (tmp_path / "a.py").write_text(textwrap.dedent("""\
        class C:
            def churn(self):
                self.healthy = {}

            async def ok_comp(self):
                pool = {t for t in self.healthy}
                await g()
                return pool

            async def bad_genexp(self):
                pool = (t for t in self.healthy)
                await g()
                return list(pool)
        """))
    result = _scan([str(tmp_path)])
    assert [(f.rule, f.line) for f in result.new] == [("FTL010", 13)]


def test_ftl010_tuple_unpack_targets_count_as_mutable(tmp_path):
    """Tuple-unpack and chained self-attribute assignments make their
    attrs mutable for FTL010's prescan (review catch: a bare
    `for t in targets: ... break` missed both forms)."""
    (tmp_path / "a.py").write_text(textwrap.dedent("""\
        class C:
            def swap(self):
                self.alpha, self.beta = self.beta, self.alpha

            def chain(self, v):
                self.gamma = self.delta = v

            async def snap(self):
                a = self.alpha
                d = self.delta
                await g()
                return a, d
        """))
    result = _scan([str(tmp_path)])
    assert sorted({f.rule for f in result.new}) == ["FTL010"]
    assert len(result.new) == 2, [f.message for f in result.new]


def test_is_actor_helper():
    tree = ast.parse(
        "async def a():\n    pass\n"
        "def s():\n    pass\n"
        "f = lambda: 0\n")
    async_fn, sync_fn, lam_assign = tree.body
    assert is_actor(async_fn)
    assert not is_actor(sync_fn)
    assert not is_actor(lam_assign.value)
    assert not is_actor(tree)


# ---------------------------------------------------------------------------
# --changed incremental mode
# ---------------------------------------------------------------------------

def _git(repo, *args):
    out = subprocess.run(["git", "-C", str(repo), "-c", "user.name=t",
                          "-c", "user.email=t@t"] + list(args),
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    return out


def test_cli_changed_mode(tmp_path):
    """--changed lints exactly the files `git diff` names, with full
    baseline/suppression semantics, and exits clean when nothing
    changed."""
    repo = tmp_path / "r"
    pkg = repo / "pkg"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "clean.py").write_text("x = 1\n")
    dirty = pkg / "dirty.py"
    dirty.write_text("y = 1\n")
    _git(repo, "init", "-q")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-qm", "seed")

    # Nothing changed: clean exit, zero files scanned.
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--changed", "HEAD", "--baseline",
         "none", str(pkg)], capture_output=True, text=True)
    assert out.returncode == 0 and "0 file(s) scanned" in out.stdout

    # A violation in one changed file: only that file is linted.
    dirty.write_text("import time\nt = time.monotonic()\n")
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--changed", "HEAD", "--baseline",
         "none", str(pkg)], capture_output=True, text=True)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "dirty.py" in out.stdout and "FTL001" in out.stdout
    assert "1 file(s) scanned" in out.stdout

    # Suppression semantics identical to a full scan.
    dirty.write_text(
        "import time\n"
        "t = time.monotonic()  # flowlint: disable=FTL001 -- test\n")
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--changed", "HEAD", "--baseline",
         "none", str(pkg)], capture_output=True, text=True)
    assert out.returncode == 0 and "1 suppressed" in out.stdout

    # UNTRACKED files are included too: a brand-new module is the one
    # most likely to carry new findings, and `git diff` never lists it.
    (pkg / "fresh.py").write_text("import time\nt = time.time()\n")
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--changed", "HEAD", "--baseline",
         "none", str(pkg)], capture_output=True, text=True)
    assert out.returncode == 1 and "fresh.py" in out.stdout

    # ... under EVERY lint root, not just the first (ls-files --others
    # runs from the repo toplevel, unlike the cwd-scoped default).
    pkg2 = repo / "pkg2"
    pkg2.mkdir()
    (pkg2 / "other.py").write_text("import time\nt2 = time.time()\n")
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--changed", "HEAD", "--baseline",
         "none", str(pkg), str(pkg2)], capture_output=True, text=True)
    assert out.returncode == 1 and "other.py" in out.stdout


def test_cli_changed_through_symlinked_root(tmp_path):
    """A checkout reached through a symlink (macOS /tmp, symlinked CI
    workspaces) must not silently lint zero files: git's resolved
    toplevel and the symlink-spelled lint root are realpath'd to the
    same prefix (review catch)."""
    real = tmp_path / "real"
    (real / "pkg").mkdir(parents=True)
    (real / "pkg" / "mod.py").write_text("x = 1\n")
    _git(real, "init", "-q")
    _git(real, "add", "-A")
    _git(real, "commit", "-qm", "seed")
    link = tmp_path / "link"
    os.symlink(real, link)
    (link / "pkg" / "mod.py").write_text(
        "import time\nt = time.monotonic()\n")
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--changed", "HEAD", "--baseline",
         "none", str(link / "pkg")], capture_output=True, text=True)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "1 file(s) scanned" in out.stdout


def test_cli_changed_conflicts_with_write_baseline(tmp_path):
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--changed", "--write-baseline",
         str(tmp_path)], capture_output=True, text=True)
    assert out.returncode == 2
    assert "full scan" in out.stderr


# ---------------------------------------------------------------------------
# Cross-process unseed reproduction (PYTHONHASHSEED pinned)
# ---------------------------------------------------------------------------

CANARY_SPEC = """
[[test]]
testTitle = 'HashCanary'
  [[test.workload]]
  testName = 'HashOrderCanary'
"""

_CANARY_RUNNER = (
    "import json, sys\n"
    "from foundationdb_tpu.testing import run_simulation\n"
    f"r = run_simulation({CANARY_SPEC!r}, 11, audit=False)\n"
    "print(json.dumps({'unseed': r.unseed, 'digest': r.digest,"
    " 'folds': r.folds}))\n")


def _canary_in_subprocess(hash_seed: str) -> dict:
    env = dict(os.environ, PYTHONHASHSEED=hash_seed, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", _CANARY_RUNNER],
                         capture_output=True, text=True, cwd=REPO,
                         env=env, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_cross_process_unseed_with_pinned_hash_seed():
    """run_test_twice's contract ACROSS processes: two fresh interpreters
    with the same PYTHONHASHSEED replay the str-set-order-sensitive
    canary bit-identically (ROADMAP chaos follow-up, closed here)."""
    a = _canary_in_subprocess("0")
    b = _canary_in_subprocess("0")
    assert a == b, f"pinned-hash-seed runs diverged: {a} vs {b}"


def test_hash_order_canary_is_actually_sensitive():
    """The negative control controls: DIFFERENT pinned hash seeds give
    different str-set orders, which the canary folds into the unseed —
    proving the pin is load-bearing, not vacuous."""
    a = _canary_in_subprocess("1")
    b = _canary_in_subprocess("2")
    assert (a["unseed"], a["digest"]) != (b["unseed"], b["digest"]), (
        "canary failed to observe hash-order difference — it no longer "
        "guards the PYTHONHASHSEED pin")
