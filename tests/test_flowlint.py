"""flowlint (ISSUE 5): rule-engine behavior, one positive fixture per
rule with exact FTL id + line assertions, suppression/baseline
round-trips, the clean-repo gate (tier-1's static-analysis entry, the
way test_metrics.py runs check_trace_events), and cross-process unseed
reproduction with PYTHONHASHSEED pinned (the ROADMAP chaos follow-up,
driven by the HashOrderCanary workload)."""

import json
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "flowlint")
FLOWLINT = os.path.join(REPO, "scripts", "flowlint.py")

from foundationdb_tpu.analysis.engine import (Analyzer, load_baseline,
                                              write_baseline)
from foundationdb_tpu.analysis.rules import make_rules

EXPECT = re.compile(r"(FTL\d{3}):(\d+)")


def _scan(roots, baseline=None):
    return Analyzer(make_rules()).run(roots, baseline)


def _expected_fixture_findings():
    """(rule, relpath, line) triples from the `# expect:` marker lines
    committed inside each fixture."""
    exp = set()
    for dirpath, dirnames, filenames in os.walk(FIXTURES):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, FIXTURES).replace(os.sep, "/")
            with open(path) as f:
                for line in f:
                    if "# expect:" in line:
                        for m in EXPECT.finditer(line):
                            exp.add((m.group(1), rel, int(m.group(2))))
    return exp


# ---------------------------------------------------------------------------
# Fixtures: every rule fires with its exact id and line, nothing extra
# ---------------------------------------------------------------------------

def test_fixture_findings_exact():
    expected = _expected_fixture_findings()
    assert len(expected) >= 9, "fixture markers went missing"
    # Every rule id is represented by at least one fixture expectation.
    assert {f"FTL{i:03d}" for i in range(1, 10)} <= \
        {rule for rule, _, _ in expected}
    result = _scan([FIXTURES])
    got = {(f.rule, f.path, f.line) for f in result.new}
    assert got == expected, (
        f"unexpected: {sorted(got - expected)}\n"
        f"missing: {sorted(expected - got)}")


def test_clean_fixture_has_no_findings():
    result = _scan([os.path.join(FIXTURES, "clean.py")])
    assert result.new == [] and result.suppressed == 0


def test_unparseable_file_reported_not_skipped(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    result = _scan([str(tmp_path)])
    assert [f.rule for f in result.new] == ["FTL000"]


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------

def test_inline_suppression_by_id(tmp_path):
    (tmp_path / "a.py").write_text(
        "import time\n"
        "t = time.monotonic()  # flowlint: disable=FTL001 -- fixture\n")
    result = _scan([str(tmp_path)])
    assert result.new == [] and result.suppressed == 1


def test_suppression_wrong_id_does_not_apply(tmp_path):
    (tmp_path / "a.py").write_text(
        "import time\n"
        "t = time.monotonic()  # flowlint: disable=FTL006\n")
    result = _scan([str(tmp_path)])
    assert [f.rule for f in result.new] == ["FTL001"]


def test_file_wide_suppression(tmp_path):
    (tmp_path / "a.py").write_text(
        "# flowlint: disable-file=FTL001 -- fixture\n"
        "import time\n"
        "t1 = time.monotonic()\n"
        "t2 = time.time()\n")
    result = _scan([str(tmp_path)])
    assert result.new == [] and result.suppressed == 2


def test_file_wide_suppression_covers_cross_file_ftl007(tmp_path):
    """disable-file=FTL007 removes the file's callsites from the
    cross-file schema comparison — finish()-time findings must not
    bypass the suppression mechanism."""
    (tmp_path / "a.py").write_text(
        "# flowlint: disable-file=FTL007 -- divergent schema on purpose\n"
        'TraceEvent("Shared").detail("A", 1).log()\n')
    (tmp_path / "b.py").write_text(
        'TraceEvent("Shared").detail("B", 1).log()\n')
    result = _scan([str(tmp_path)])
    assert result.new == [], [f.message for f in result.new]
    # And the control: without the suppression the drift IS reported.
    (tmp_path / "a.py").write_text(
        'TraceEvent("Shared").detail("A", 1).log()\n')
    result = _scan([str(tmp_path)])
    assert [f.rule for f in result.new] == ["FTL007"]


def test_suppress_all(tmp_path):
    (tmp_path / "a.py").write_text(
        "import time\n"
        "t = time.monotonic()  # flowlint: disable=all\n")
    result = _scan([str(tmp_path)])
    assert result.new == [] and result.suppressed == 1


# ---------------------------------------------------------------------------
# Baseline round-trip
# ---------------------------------------------------------------------------

def test_baseline_round_trip(tmp_path):
    first = _scan([FIXTURES])
    assert first.new, "fixtures must produce findings"
    baseline_path = str(tmp_path / "baseline.json")
    write_baseline(baseline_path, first.new)

    second = _scan([FIXTURES], load_baseline(baseline_path))
    assert second.new == [] and second.exit_code == 0
    assert len(second.baselined) == len(first.new)

    # Dropping one entry resurfaces exactly that finding as NEW.
    entries = load_baseline(baseline_path)
    dropped = entries.pop(0)
    third = _scan([FIXTURES], entries)
    assert len(third.new) == 1
    assert third.new[0].rule == dropped["rule"]
    assert third.new[0].path == dropped["path"]


def test_baseline_is_line_insensitive(tmp_path):
    src = "import time\nt = time.monotonic()\n"
    (tmp_path / "a.py").write_text(src)
    r1 = _scan([str(tmp_path)])
    baseline_path = str(tmp_path / "b.json")
    write_baseline(baseline_path, r1.new)
    # Shift the violation down two lines: still baselined.
    (tmp_path / "a.py").write_text("# pad\n# pad\n" + src)
    r2 = _scan([str(tmp_path)], load_baseline(baseline_path))
    assert r2.new == [] and len(r2.baselined) == 1


def test_single_file_scan_matches_directory_scan_identity(tmp_path):
    """Directly linting one package file yields the same root-relative
    finding path as a directory scan of the package: module exemptions
    (REAL_ONLY_MODULES, 'server/') keep applying and a baseline written
    by the full scan still covers the direct-file lint."""
    # The exemption case: a REAL_ONLY module's sanctioned wall-clock
    # reads must not resurface when the file is linted directly.
    target = os.path.join(REPO, "foundationdb_tpu", "core", "scheduler.py")
    result = _scan([target])
    assert result.new == [], [f"{f.path}:{f.line} {f.rule}"
                              for f in result.new]
    # The baseline-identity case, on a synthetic package.
    pkg = tmp_path / "pkg"
    (pkg / "server").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "server" / "__init__.py").write_text("")
    mod = pkg / "server" / "mod.py"
    mod.write_text("import time\nt = time.monotonic()\n")
    dir_scan = _scan([str(pkg)])
    file_scan = _scan([str(mod)])
    assert {f.key() for f in file_scan.new} == \
        {f.key() for f in dir_scan.new} and dir_scan.new, \
        (dir_scan.new, file_scan.new)
    baseline = [{"rule": f.rule, "path": f.path, "message": f.message}
                for f in dir_scan.new]
    rebased = _scan([str(mod)], baseline)
    assert rebased.new == [] and len(rebased.baselined) == 1


# ---------------------------------------------------------------------------
# CLI: the tier-1 clean-repo gate + JSON output
# ---------------------------------------------------------------------------

def test_repo_is_flowlint_clean():
    """`python scripts/flowlint.py foundationdb_tpu` exits 0 against the
    committed baseline (the ISSUE 5 acceptance gate)."""
    out = subprocess.run(
        [sys.executable, FLOWLINT,
         os.path.join(REPO, "foundationdb_tpu")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr


def test_committed_baseline_within_budget():
    entries = load_baseline(os.path.join(REPO, "flowlint_baseline.json"))
    assert len(entries) <= 10, (
        "baseline grew past the 10-finding budget: fix violations "
        "instead of grandfathering them")


def test_cli_json_format():
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--format", "json", "--baseline",
         "none", FIXTURES],
        capture_output=True, text=True)
    assert out.returncode == 1
    doc = json.loads(out.stdout)
    assert doc["counts"]["new"] == len(doc["findings"]) > 0
    f = doc["findings"][0]
    assert set(f) == {"rule", "path", "line", "message"}


def test_cli_write_baseline_conflicts_with_baseline_none():
    """--write-baseline with --baseline none must error out, NOT fall
    back to silently overwriting the committed default baseline."""
    committed = os.path.join(REPO, "flowlint_baseline.json")
    before = open(committed).read()
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--baseline", "none",
         "--write-baseline", FIXTURES],
        capture_output=True, text=True)
    assert out.returncode == 2
    assert "conflicts" in out.stderr
    assert open(committed).read() == before


def test_cli_list_rules():
    out = subprocess.run([sys.executable, FLOWLINT, "--list-rules"],
                         capture_output=True, text=True)
    assert out.returncode == 0
    for i in range(1, 10):
        assert f"FTL{i:03d}" in out.stdout


# ---------------------------------------------------------------------------
# Cross-process unseed reproduction (PYTHONHASHSEED pinned)
# ---------------------------------------------------------------------------

CANARY_SPEC = """
[[test]]
testTitle = 'HashCanary'
  [[test.workload]]
  testName = 'HashOrderCanary'
"""

_CANARY_RUNNER = (
    "import json, sys\n"
    "from foundationdb_tpu.testing import run_simulation\n"
    f"r = run_simulation({CANARY_SPEC!r}, 11, audit=False)\n"
    "print(json.dumps({'unseed': r.unseed, 'digest': r.digest,"
    " 'folds': r.folds}))\n")


def _canary_in_subprocess(hash_seed: str) -> dict:
    env = dict(os.environ, PYTHONHASHSEED=hash_seed, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", _CANARY_RUNNER],
                         capture_output=True, text=True, cwd=REPO,
                         env=env, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_cross_process_unseed_with_pinned_hash_seed():
    """run_test_twice's contract ACROSS processes: two fresh interpreters
    with the same PYTHONHASHSEED replay the str-set-order-sensitive
    canary bit-identically (ROADMAP chaos follow-up, closed here)."""
    a = _canary_in_subprocess("0")
    b = _canary_in_subprocess("0")
    assert a == b, f"pinned-hash-seed runs diverged: {a} vs {b}"


def test_hash_order_canary_is_actually_sensitive():
    """The negative control controls: DIFFERENT pinned hash seeds give
    different str-set orders, which the canary folds into the unseed —
    proving the pin is load-bearing, not vacuous."""
    a = _canary_in_subprocess("1")
    b = _canary_in_subprocess("2")
    assert (a["unseed"], a["digest"]) != (b["unseed"], b["digest"]), (
        "canary failed to observe hash-order difference — it no longer "
        "guards the PYTHONHASHSEED pin")
