"""flowlint (ISSUES 5 + 9 + 11): rule-engine behavior, one positive
fixture per rule with exact FTL id + line assertions,
suppression/baseline round-trips, the clean-repo gate (tier-1's
static-analysis entry, the way test_metrics.py runs
check_trace_events), the ISSUE-9 dataflow layer (CFG/def-use/lockset
unit battery + FTL010/011/012 + widened FTL005), the ISSUE-11
interprocedural layer (call-graph resolution, summary fixpoints,
caller-held lockset seeding, FTL013/FTL014, the summary cache),
--changed incremental mode, and cross-process unseed reproduction with
PYTHONHASHSEED pinned (the ROADMAP chaos follow-up, driven by the
HashOrderCanary workload)."""

import ast
import json
import os
import re
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "flowlint")
FLOWLINT = os.path.join(REPO, "scripts", "flowlint.py")

from foundationdb_tpu.analysis.dataflow import FunctionDataflow
from foundationdb_tpu.analysis.engine import (Analyzer, is_actor,
                                              load_baseline,
                                              write_baseline)
from foundationdb_tpu.analysis.rules import make_rules

EXPECT = re.compile(r"(FTL\d{3}):(\d+)")

N_RULES = 18    # FTL001..FTL018 (FTL000 = unparseable-file pseudo-rule)


def _scan(roots, baseline=None):
    return Analyzer(make_rules()).run(roots, baseline)


def _expected_fixture_findings():
    """(rule, relpath, line) triples from the `# expect:` marker lines
    committed inside each fixture."""
    exp = set()
    for dirpath, dirnames, filenames in os.walk(FIXTURES):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, FIXTURES).replace(os.sep, "/")
            with open(path) as f:
                for line in f:
                    if "# expect:" in line:
                        for m in EXPECT.finditer(line):
                            exp.add((m.group(1), rel, int(m.group(2))))
    return exp


# ---------------------------------------------------------------------------
# Fixtures: every rule fires with its exact id and line, nothing extra
# ---------------------------------------------------------------------------

def test_fixture_findings_exact():
    expected = _expected_fixture_findings()
    assert len(expected) >= N_RULES, "fixture markers went missing"
    # Every rule id is represented by at least one fixture expectation.
    assert {f"FTL{i:03d}" for i in range(1, N_RULES + 1)} <= \
        {rule for rule, _, _ in expected}
    result = _scan([FIXTURES])
    got = {(f.rule, f.path, f.line) for f in result.new}
    assert got == expected, (
        f"unexpected: {sorted(got - expected)}\n"
        f"missing: {sorted(expected - got)}")


def test_clean_fixture_has_no_findings():
    result = _scan([os.path.join(FIXTURES, "clean.py")])
    assert result.new == [] and result.suppressed == 0


def test_unparseable_file_reported_not_skipped(tmp_path):
    (tmp_path / "broken.py").write_text("def f(:\n")
    result = _scan([str(tmp_path)])
    assert [f.rule for f in result.new] == ["FTL000"]


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------

def test_inline_suppression_by_id(tmp_path):
    (tmp_path / "a.py").write_text(
        "import time\n"
        "t = time.monotonic()  # flowlint: disable=FTL001 -- fixture\n")
    result = _scan([str(tmp_path)])
    assert result.new == [] and result.suppressed == 1


def test_suppression_wrong_id_does_not_apply(tmp_path):
    (tmp_path / "a.py").write_text(
        "import time\n"
        "t = time.monotonic()  # flowlint: disable=FTL006\n")
    result = _scan([str(tmp_path)])
    assert [f.rule for f in result.new] == ["FTL001"]


def test_file_wide_suppression(tmp_path):
    (tmp_path / "a.py").write_text(
        "# flowlint: disable-file=FTL001 -- fixture\n"
        "import time\n"
        "t1 = time.monotonic()\n"
        "t2 = time.time()\n")
    result = _scan([str(tmp_path)])
    assert result.new == [] and result.suppressed == 2


def test_file_wide_suppression_covers_cross_file_ftl007(tmp_path):
    """disable-file=FTL007 removes the file's callsites from the
    cross-file schema comparison — finish()-time findings must not
    bypass the suppression mechanism."""
    (tmp_path / "a.py").write_text(
        "# flowlint: disable-file=FTL007 -- divergent schema on purpose\n"
        'TraceEvent("Shared").detail("A", 1).log()\n')
    (tmp_path / "b.py").write_text(
        'TraceEvent("Shared").detail("B", 1).log()\n')
    result = _scan([str(tmp_path)])
    assert result.new == [], [f.message for f in result.new]
    # And the control: without the suppression the drift IS reported.
    (tmp_path / "a.py").write_text(
        'TraceEvent("Shared").detail("A", 1).log()\n')
    result = _scan([str(tmp_path)])
    assert [f.rule for f in result.new] == ["FTL007"]


def test_suppress_all(tmp_path):
    (tmp_path / "a.py").write_text(
        "import time\n"
        "t = time.monotonic()  # flowlint: disable=all\n")
    result = _scan([str(tmp_path)])
    assert result.new == [] and result.suppressed == 1


# ---------------------------------------------------------------------------
# Baseline round-trip
# ---------------------------------------------------------------------------

def test_baseline_round_trip(tmp_path):
    first = _scan([FIXTURES])
    assert first.new, "fixtures must produce findings"
    baseline_path = str(tmp_path / "baseline.json")
    write_baseline(baseline_path, first.new)

    second = _scan([FIXTURES], load_baseline(baseline_path))
    assert second.new == [] and second.exit_code == 0
    assert len(second.baselined) == len(first.new)

    # Dropping one entry resurfaces exactly that finding as NEW.
    entries = load_baseline(baseline_path)
    dropped = entries.pop(0)
    third = _scan([FIXTURES], entries)
    assert len(third.new) == 1
    assert third.new[0].rule == dropped["rule"]
    assert third.new[0].path == dropped["path"]


def test_baseline_is_line_insensitive(tmp_path):
    src = "import time\nt = time.monotonic()\n"
    (tmp_path / "a.py").write_text(src)
    r1 = _scan([str(tmp_path)])
    baseline_path = str(tmp_path / "b.json")
    write_baseline(baseline_path, r1.new)
    # Shift the violation down two lines: still baselined.
    (tmp_path / "a.py").write_text("# pad\n# pad\n" + src)
    r2 = _scan([str(tmp_path)], load_baseline(baseline_path))
    assert r2.new == [] and len(r2.baselined) == 1


def test_single_file_scan_matches_directory_scan_identity(tmp_path):
    """Directly linting one package file yields the same root-relative
    finding path as a directory scan of the package: module exemptions
    (REAL_ONLY_MODULES, 'server/') keep applying and a baseline written
    by the full scan still covers the direct-file lint."""
    # The exemption case: a REAL_ONLY module's sanctioned wall-clock
    # reads must not resurface when the file is linted directly.
    target = os.path.join(REPO, "foundationdb_tpu", "core", "scheduler.py")
    result = _scan([target])
    assert result.new == [], [f"{f.path}:{f.line} {f.rule}"
                              for f in result.new]
    # The baseline-identity case, on a synthetic package.
    pkg = tmp_path / "pkg"
    (pkg / "server").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "server" / "__init__.py").write_text("")
    mod = pkg / "server" / "mod.py"
    mod.write_text("import time\nt = time.monotonic()\n")
    dir_scan = _scan([str(pkg)])
    file_scan = _scan([str(mod)])
    assert {f.key() for f in file_scan.new} == \
        {f.key() for f in dir_scan.new} and dir_scan.new, \
        (dir_scan.new, file_scan.new)
    baseline = [{"rule": f.rule, "path": f.path, "message": f.message}
                for f in dir_scan.new]
    rebased = _scan([str(mod)], baseline)
    assert rebased.new == [] and len(rebased.baselined) == 1


# ---------------------------------------------------------------------------
# CLI: the tier-1 clean-repo gate + JSON output
# ---------------------------------------------------------------------------

def test_repo_is_flowlint_clean():
    """`python scripts/flowlint.py foundationdb_tpu` exits 0 against the
    committed baseline (the ISSUE 5 acceptance gate)."""
    out = subprocess.run(
        [sys.executable, FLOWLINT,
         os.path.join(REPO, "foundationdb_tpu")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr


def test_committed_baseline_within_budget():
    entries = load_baseline(os.path.join(REPO, "flowlint_baseline.json"))
    assert len(entries) <= 10, (
        "baseline grew past the 10-finding budget: fix violations "
        "instead of grandfathering them")


def test_cli_json_format():
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--format", "json", "--baseline",
         "none", FIXTURES],
        capture_output=True, text=True)
    assert out.returncode == 1
    doc = json.loads(out.stdout)
    assert doc["counts"]["new"] == len(doc["findings"]) > 0
    f = doc["findings"][0]
    assert set(f) == {"rule", "path", "line", "message"}


def test_cli_write_baseline_conflicts_with_baseline_none():
    """--write-baseline with --baseline none must error out, NOT fall
    back to silently overwriting the committed default baseline."""
    committed = os.path.join(REPO, "flowlint_baseline.json")
    before = open(committed).read()
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--baseline", "none",
         "--write-baseline", FIXTURES],
        capture_output=True, text=True)
    assert out.returncode == 2
    assert "conflicts" in out.stderr
    assert open(committed).read() == before


def test_cli_list_rules():
    out = subprocess.run([sys.executable, FLOWLINT, "--list-rules"],
                         capture_output=True, text=True)
    assert out.returncode == 0
    for i in range(1, N_RULES + 1):
        assert f"FTL{i:03d}" in out.stdout


def test_list_rules_matches_readme_table():
    """No rule-list drift (ISSUE 9): the shipped rule set and README's
    rule table must name exactly the same FTL ids — a rule added
    without a doc row (or vice versa) fails tier-1."""
    out = subprocess.run([sys.executable, FLOWLINT, "--list-rules"],
                         capture_output=True, text=True)
    cli_ids = set(re.findall(r"FTL\d{3}", out.stdout))
    with open(os.path.join(REPO, "README.md")) as f:
        readme_ids = set(re.findall(r"^\| (FTL\d{3}) ", f.read(), re.M))
    assert readme_ids == cli_ids, (
        f"README table vs --list-rules drift: only in README "
        f"{sorted(readme_ids - cli_ids)}, only in CLI "
        f"{sorted(cli_ids - readme_ids)}")


# ---------------------------------------------------------------------------
# Dataflow layer (ISSUE 9): CFG / def-use / lockset unit battery
# ---------------------------------------------------------------------------

def _cfg(src: str, name=None) -> FunctionDataflow:
    tree = ast.parse(textwrap.dedent(src))
    for n in ast.walk(tree):
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                (name is None or n.name == name):
            return FunctionDataflow(n)
    raise AssertionError("no function in snippet")


def _load(cfg: FunctionDataflow, name: str, nth: int = 0):
    loads = [(ld, nd) for ld, nd in cfg.loads if ld.id == name]
    return loads[nth]


def _reach_lines(cfg, name, nth=0):
    ld, nd = _load(cfg, name, nth)
    return sorted((d.lineno, crossed)
                  for d, crossed in cfg.reaching(nd, name))


def test_cfg_branch_join_merges_both_defs():
    cfg = _cfg("""\
        async def f(c):
            x = 1
            if c:
                x = 2
            use(x)
        """)
    assert _reach_lines(cfg, "x") == [(2, False), (4, False)]


def test_cfg_branch_else_kills_one_path():
    cfg = _cfg("""\
        async def f(c):
            if c:
                x = 1
            else:
                x = 2
            use(x)
        """)
    assert _reach_lines(cfg, "x") == [(3, False), (5, False)]


def test_cfg_rebind_after_await_kills_stale_fact():
    cfg = _cfg("""\
        async def f(self):
            x = self.a
            await g()
            x = self.b
            use(x)
        """)
    assert _reach_lines(cfg, "x") == [(4, False)]


def test_cfg_await_marks_facts_crossed():
    cfg = _cfg("""\
        async def f(self):
            x = self.a
            await g()
            use(x)
        """)
    assert _reach_lines(cfg, "x") == [(2, True)]


def test_cfg_await_result_def_is_not_crossed():
    # `x = await g()` defines x AFTER the suspension: fresh, not stale.
    cfg = _cfg("""\
        async def f(self):
            x = await g()
            use(x)
        """)
    assert _reach_lines(cfg, "x") == [(2, False)]


def test_cfg_loop_back_edge_carries_crossed_fact():
    cfg = _cfg("""\
        async def f(items):
            x = make()
            for i in items:
                use(x)
                await g()
        """)
    # First iteration sees the pre-loop def uncrossed; every later one
    # sees it through the await barrier on the back edge.
    assert _reach_lines(cfg, "x") == [(2, False), (2, True)]


def test_cfg_try_except_handler_sees_body_defs():
    cfg = _cfg("""\
        def f():
            try:
                x = 1
                risky()
            except ValueError:
                x = 2
            return x
        """)
    assert _reach_lines(cfg, "x") == [(3, False), (6, False)]


def test_cfg_finally_reachable_through_return():
    # The regression the transport sweep hit: `try: return ... finally:`
    # must leave the finalbody REACHABLE, with its own with-lock region
    # intact — otherwise FTL012 sees an empty lockset there.
    cfg = _cfg("""\
        async def f(self, fut):
            try:
                with self._lock:
                    return fut.result(timeout=1)
            finally:
                with self._lock:
                    cleanup(self)
        """)
    cleanup_calls = [
        (c, nd) for c, nd in cfg.calls
        if isinstance(c.func, ast.Name) and c.func.id == "cleanup"]
    assert len(cleanup_calls) == 1
    _, nd = cleanup_calls[0]
    assert cfg.lockset(nd) == frozenset({"self._lock"})


def test_cfg_nested_function_excluded_from_parent():
    cfg = _cfg("""\
        async def outer(self):
            x = self.a

            def inner():
                return x

            await g()
            return 1
        """, name="outer")
    # inner's read of x is NOT a load of the outer CFG...
    assert [ld.id for ld, _ in cfg.loads if ld.id == "x"] == []
    # ... and inner gets its own dataflow with no defs of x.
    inner = _cfg("""\
        async def outer(self):
            x = self.a

            def inner():
                return x

            await g()
            return 1
        """, name="inner")
    ld, nd = _load(inner, "x")
    assert inner.reaching(nd, "x") == []


def test_cfg_lockset_with_region_and_release():
    cfg = _cfg("""\
        async def f(self, fut):
            with self._lock:
                a = fut.wait()
            b = fut.wait()
        """)
    (_, nd_a), (_, nd_b) = cfg.calls[0], cfg.calls[1]
    assert cfg.lockset(nd_a) == frozenset({"self._lock"})
    assert cfg.lockset(nd_b) == frozenset()


def test_cfg_lockset_acquire_release_pair():
    cfg = _cfg("""\
        async def f(self, fut):
            self._lock.acquire()
            a = fut.wait()
            self._lock.release()
            b = fut.wait()
        """)
    waits = [(c, nd) for c, nd in cfg.calls
             if isinstance(c.func, ast.Attribute) and c.func.attr == "wait"]
    assert cfg.lockset(waits[0][1]) == frozenset({"self._lock"})
    assert cfg.lockset(waits[1][1]) == frozenset()


def test_cfg_conditional_acquire_is_not_held():
    # acquire(timeout=...) / acquire(blocking=False) can FAIL: a MUST
    # analysis never counts it as held (review catch — the unsound
    # direction for FTL012).
    cfg = _cfg("""\
        async def f(self, fut):
            self._lock.acquire(timeout=0.1)
            x = fut.result()
        """)
    assert cfg.acquired_locks == set()
    assert cfg.lockset(cfg.calls[-1][1]) == frozenset()


def test_cfg_lockset_meet_is_intersection():
    # Held on only ONE path into the node => not held (MUST analysis).
    cfg = _cfg("""\
        async def f(self, c, fut):
            if c:
                self._lock.acquire()
            x = fut.result()
        """)
    results = [(c, nd) for c, nd in cfg.calls
               if isinstance(c.func, ast.Attribute)
               and c.func.attr == "result"]
    assert cfg.lockset(results[0][1]) == frozenset()


def test_cfg_async_with_is_barrier_not_lock():
    cfg = _cfg("""\
        async def f(self):
            async with self._aio_lock:
                await g()
        """)
    assert cfg.acquired_locks == set()
    (_, nd) = cfg.awaits[0]
    assert cfg.lockset(nd) == frozenset()


def test_ftl005_same_named_helper_is_ambiguous(tmp_path):
    """A set-returning helper NAME shared with a non-set function in
    the same file is ambiguous at a callsite and must not taint it
    (review catch — the FTL002 same-name rule applied to FTL005)."""
    (tmp_path / "a.py").write_text(textwrap.dedent("""\
        class A:
            def make(self):
                return {"x", "y"}

        class B:
            def make(self):
                return ["x", "y"]

            def walk(self):
                s = self.make()
                return [i for i in s]
        """))
    result = _scan([str(tmp_path)])
    assert result.new == [], [f.message for f in result.new]


def test_ftl010_mutable_attrs_are_class_scoped(tmp_path):
    """An attribute name mutated in ONE class must not taint the
    same-named init-frozen attribute of another class in the file
    (review catch — the FTL009 scope lesson)."""
    (tmp_path / "a.py").write_text(textwrap.dedent("""\
        class Churner:
            def churn(self):
                self.cache = {}

            async def bad(self):
                c = self.cache
                await g()
                return c

        class Frozen:
            def __init__(self):
                self.cache = {}

            async def ok(self):
                c = self.cache
                await g()
                return c
        """))
    result = _scan([str(tmp_path)])
    assert [(f.rule, f.line) for f in result.new] == [("FTL010", 8)]


def test_ftl010_comprehension_copy_exempt_generator_flagged(tmp_path):
    """An eager comprehension is a copy snapshot (same policy as
    set()/list() calls); a GENERATOR expression reads the shared state
    lazily — after the await — and stays flagged (review catch)."""
    (tmp_path / "a.py").write_text(textwrap.dedent("""\
        class C:
            def churn(self):
                self.healthy = {}

            async def ok_comp(self):
                pool = {t for t in self.healthy}
                await g()
                return pool

            async def bad_genexp(self):
                pool = (t for t in self.healthy)
                await g()
                return list(pool)
        """))
    result = _scan([str(tmp_path)])
    assert [(f.rule, f.line) for f in result.new] == [("FTL010", 13)]


def test_ftl010_tuple_unpack_targets_count_as_mutable(tmp_path):
    """Tuple-unpack and chained self-attribute assignments make their
    attrs mutable for FTL010's prescan (review catch: a bare
    `for t in targets: ... break` missed both forms)."""
    (tmp_path / "a.py").write_text(textwrap.dedent("""\
        class C:
            def swap(self):
                self.alpha, self.beta = self.beta, self.alpha

            def chain(self, v):
                self.gamma = self.delta = v

            async def snap(self):
                a = self.alpha
                d = self.delta
                await g()
                return a, d
        """))
    result = _scan([str(tmp_path)])
    assert sorted({f.rule for f in result.new}) == ["FTL010"]
    assert len(result.new) == 2, [f.message for f in result.new]


def test_is_actor_helper():
    tree = ast.parse(
        "async def a():\n    pass\n"
        "def s():\n    pass\n"
        "f = lambda: 0\n")
    async_fn, sync_fn, lam_assign = tree.body
    assert is_actor(async_fn)
    assert not is_actor(sync_fn)
    assert not is_actor(lam_assign.value)
    assert not is_actor(tree)


# ---------------------------------------------------------------------------
# Interprocedural layer (ISSUE 11): call graph, summaries, seeding
# ---------------------------------------------------------------------------

from foundationdb_tpu.analysis.summaries import ProgramIndex

INTERPROC = os.path.join(FIXTURES, "interproc")


def _write_pkg(tmp_path, files):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    for name, src in files.items():
        p = pkg / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return pkg


def _program(root):
    pi = ProgramIndex.for_roots([str(root)])
    pi.link()
    return pi


def test_interproc_fixture_exact_both_directions():
    """The multi-file fixture package scanned ALONE (cross-file
    resolution within it): findings == markers exactly, both ways."""
    exp = set()
    for dirpath, dirnames, filenames in os.walk(INTERPROC):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, fn),
                                  INTERPROC).replace(os.sep, "/")
            with open(os.path.join(dirpath, fn)) as f:
                for line in f:
                    if "# expect:" in line:
                        for m in EXPECT.finditer(line):
                            exp.add((m.group(1), rel, int(m.group(2))))
    # Every interproc rule is represented: the caller-held FTL012
    # shape, the chain rule, the alias rule, the widened 001/005.
    assert {"FTL001", "FTL005", "FTL012", "FTL013", "FTL014"} <= \
        {r for r, _, _ in exp}
    result = _scan([INTERPROC])
    got = {(f.rule, f.path, f.line) for f in result.new}
    assert got == exp, (f"unexpected: {sorted(got - exp)}\n"
                        f"missing: {sorted(exp - got)}")


def test_callgraph_cross_file_resolution(tmp_path):
    pkg = _write_pkg(tmp_path, {
        "a.py": """\
            def helper():
                return 1

            class Base:
                def shared(self):
                    return 2

            class Maker:
                def __init__(self):
                    self.x = 1
            """,
        "b.py": """\
            import pkg.a as direct
            from . import a as amod
            from .a import Base, Maker, helper

            class Sub(Base):
                def go(self):
                    return self.shared()

                def go2(self):
                    return super().shared()

            def calls(obj):
                helper()
                amod.helper()
                direct.helper()
                Maker()
                obj.mystery()
            """})
    g = _program(pkg).graph
    assert g.resolve("b.py", None, ["name", "helper"]) == "a.py::helper"
    assert g.resolve("b.py", None, ["attr", "amod", "helper"]) == \
        "a.py::helper"
    assert g.resolve("b.py", None, ["attr", "direct", "helper"]) == \
        "a.py::helper"
    assert g.resolve("b.py", "Sub", ["self", "shared"]) == \
        "a.py::Base.shared"
    assert g.resolve("b.py", "Sub", ["super", "shared"]) == \
        "a.py::Base.shared"
    assert g.resolve("b.py", None, ["name", "Maker"]) == \
        "a.py::Maker.__init__"
    assert g.resolve("b.py", None, ["name", "nonesuch"]) is None
    assert g.resolve("b.py", None, ["opaque", "mystery"]) is None
    # The unknown receiver call feeds the conservatism set.
    assert "mystery" in g.unresolved_names


def test_summary_may_block_fixpoint(tmp_path):
    pkg = _write_pkg(tmp_path, {
        "w.py": """\
            def leaf(fut):
                return fut.result()

            def mid(fut):
                return leaf(fut)

            def bounded(fut, timeout):
                return fut.result(timeout=timeout)

            def via_bounded(fut):
                return bounded(fut, 1.0)

            async def aleaf(fut):
                return fut.result()

            def spawns_only(fut):
                aleaf(fut)

            async def awaits_it(fut):
                return await anested(fut)

            async def anested(fut):
                return fut.result()
            """})
    pi = _program(pkg)
    fid = "w.py::{}".format
    assert pi.may_block(fid("leaf"))
    assert pi.may_block(fid("mid"))           # depth-2 chain
    assert not pi.may_block(fid("bounded"))   # timeout forwarded
    assert not pi.may_block(fid("via_bounded"))
    assert pi.may_block(fid("aleaf"))         # its own body blocks...
    assert not pi.may_block(fid("spawns_only"))   # ...but a plain call
    #                                     never runs an async callee
    assert not pi.may_block(fid("awaits_it"))     # awaited edges are
    #                                     FTL011's territory, not 013's
    chain = pi.block_chain(fid("mid"))
    assert chain[-1].endswith(".result() with no timeout")
    assert any("leaf" in hop for hop in chain)


def test_summary_set_valued_fixpoint(tmp_path):
    pkg = _write_pkg(tmp_path, {
        "s.py": """\
            def grounded(x):
                if x:
                    return {1}
                return bounce(x)

            def bounce(x):
                return grounded(x)

            def pure_cycle(x):
                return pure_cycle2(x)

            def pure_cycle2(x):
                return pure_cycle(x)

            def not_always(x):
                if x:
                    return {1}
                return [1]
            """})
    pi = _program(pkg)
    assert pi.set_valued("s.py::grounded")
    assert pi.set_valued("s.py::bounce")      # SCC converges via base case
    assert not pi.set_valued("s.py::pure_cycle")  # no base: not grounded
    assert not pi.set_valued("s.py::not_always")


def test_entry_lockset_seeding_meet(tmp_path):
    pkg = _write_pkg(tmp_path, {
        "r.py": """\
            import threading

            class AllLocked:
                def __init__(self):
                    self._lock = threading.Lock()

                def _helper(self):
                    return 1

                def a(self):
                    with self._lock:
                        self._helper()

                def b(self):
                    with self._lock:
                        self._helper()

            class OneUnlocked:
                def __init__(self):
                    self._lock = threading.Lock()

                def _helper(self):
                    return 1

                def a(self):
                    with self._lock:
                        self._helper()

                def b(self):
                    self._helper()

            class Escaped:
                def __init__(self):
                    self._lock = threading.Lock()

                def _helper(self):
                    return 1

                def a(self, loop):
                    with self._lock:
                        loop.call_soon(self._helper)
                        self._helper()

            class Public:
                def __init__(self):
                    self._lock = threading.Lock()

                def helper(self):
                    return 1

                def a(self):
                    with self._lock:
                        self.helper()
            """})
    pi = _program(pkg)
    assert pi.entry_locks("r.py", "AllLocked._helper") == \
        frozenset({"self._lock"})
    assert pi.entry_locks("r.py", "OneUnlocked._helper") == frozenset()
    assert pi.entry_locks("r.py", "Escaped._helper") == frozenset()
    assert pi.entry_locks("r.py", "Public.helper") == frozenset()


def test_entry_seeding_disabled_under_virtual_dispatch(tmp_path):
    """Review catch: Base.run() calls self._m() lock-free, Sub
    OVERRIDES _m — static resolution sends Base's callsite to Base._m,
    so Sub._m would see only its locked caller and be wrongly seeded.
    Any override relation (either direction, or an unresolved base)
    disqualifies the method from all-callers-known seeding."""
    pkg = _write_pkg(tmp_path, {
        "v.py": """\
            import threading

            class Base:
                def _m(self):
                    return 0

                def run(self):
                    self._m()

            class Sub(Base):
                def __init__(self):
                    self._lock = threading.Lock()
                    self._count = 0

                def _m(self):
                    self._count = 0

                def locked_caller(self):
                    with self._lock:
                        self._count += 1
                        self._m()
            """})
    pi = _program(pkg)
    assert pi.entry_locks("v.py", "Sub._m") == frozenset()
    # ... and the FTL012 race Base.run's dispatch path creates FIRES.
    result = _scan([str(pkg)])
    assert [(f.rule, f.line) for f in result.new
            if f.rule == "FTL012"], "override silenced a real race"


def test_lock_arg_through_alias_canonicalizes(tmp_path):
    """Review catch: a lock passed through a local alias
    (``the_lock = self._lock; self._bump(the_lock)``) must unify with
    the directly-passed attribute — NOT read as a different lock per
    caller (false FTL014) or defeat param canonicalization."""
    pkg = _write_pkg(tmp_path, {
        "al.py": """\
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()

                def _bump(self, use_lock):
                    with use_lock:
                        return 1

                def a(self):
                    self._bump(self._lock)

                def b(self):
                    the_lock = self._lock
                    self._bump(the_lock)
            """})
    pi = _program(pkg)
    assert pi.param_canon("al.py", "C._bump") == \
        {"use_lock": "self._lock"}
    assert pi.param_conflicts == []


def test_sibling_roots_rel_collision_is_dropped(tmp_path):
    """Review catch: two scan roots both containing utils.py share one
    rel-path identity — keeping both would resolve one package's calls
    against the other's facts.  Colliding rels drop out of the program
    (intraprocedural-only), they are never cross-wired."""
    for name, body in (("pkgA", "def helper():\n    return {1}\n"),
                       ("pkgB", "def helper():\n    return [1]\n")):
        d = tmp_path / name
        d.mkdir()
        (d / "utils.py").write_text(body)
    pi = ProgramIndex.for_roots([str(tmp_path / "pkgA"),
                                 str(tmp_path / "pkgB")])
    pi.link()
    assert pi._collisions == {"utils.py"}
    assert "utils.py" not in pi.facts
    # The full Analyzer run over both roots stays coherent (no phantom
    # cross-package findings, no crash).
    result = _scan([str(tmp_path / "pkgA"), str(tmp_path / "pkgB")])
    assert result.new == [], [f.message for f in result.new]


def test_lock_param_canonicalization_and_conflict(tmp_path):
    pkg = _write_pkg(tmp_path, {
        "p.py": """\
            import threading

            class Agree:
                def __init__(self):
                    self._lock = threading.Lock()

                def _bump(self, use_lock):
                    with use_lock:
                        return 1

                def a(self):
                    self._bump(self._lock)

                def b(self):
                    self._bump(self._lock)

            class Disagree:
                def __init__(self):
                    self._a_lock = threading.Lock()
                    self._b_lock = threading.Lock()

                def _bump(self, use_lock):
                    with use_lock:
                        return 1

                def a(self):
                    self._bump(self._a_lock)

                def b(self):
                    self._bump(self._b_lock)
            """})
    pi = _program(pkg)
    assert pi.param_canon("p.py", "Agree._bump") == \
        {"use_lock": "self._lock"}
    assert pi.param_canon("p.py", "Disagree._bump") == {}
    assert [(c[1], c[3]) for c in pi.param_conflicts] == \
        [("Disagree._bump", "use_lock")]


def test_trace_roll_is_suppression_free():
    """The ISSUE-11 acceptance bullet: core/trace.py carries ZERO
    FTL012 suppressions — the caller-held seeding proves _roll's
    contract — and the file lints clean directly (the single-file scan
    still links the whole package, so the seeding applies)."""
    trace_py = os.path.join(REPO, "foundationdb_tpu", "core", "trace.py")
    with open(trace_py) as f:
        src = f.read()
    assert "disable=FTL012" not in src
    result = _scan([trace_py])
    assert result.new == [], [f"{f.line} {f.rule}" for f in result.new]


def test_ftl013_finding_renders_chain(tmp_path):
    pkg = _write_pkg(tmp_path, {
        "h.py": """\
            def wait_done(fut):
                return fut.result()

            def drain(fut):
                return wait_done(fut)
            """,
        "m.py": """\
            import threading
            from .h import drain

            class P:
                def __init__(self):
                    self._lock = threading.Lock()

                def bad(self, fut):
                    with self._lock:
                        return drain(fut)
            """})
    result = _scan([str(pkg)])
    ftl13 = [f for f in result.new if f.rule == "FTL013"]
    assert len(ftl13) == 1
    msg = ftl13[0].message
    assert "self._lock" in msg and "->" in msg
    assert "h.py::drain" in msg and "h.py::wait_done" in msg


def test_cli_dump_callgraph():
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--dump-callgraph", "--summary-cache",
         "none", os.path.join(REPO, "foundationdb_tpu", "core",
                              "trace.py")],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    rows = json.loads(out.stdout)
    edges = {(r["caller"], r["callee"]) for r in rows}
    # The canonical caller-held edge, resolved by self-dispatch.
    assert ("core/trace.py::Tracer.emit",
            "core/trace.py::Tracer._roll") in edges
    # Unresolved callees are kept (debugging view), as null.
    assert any(r["callee"] is None for r in rows)


def test_summary_cache_staleness(tmp_path):
    """The cache is keyed by content hash: editing a HELPER file (while
    scanning with --changed-style single roots) must invalidate its
    entry — a stale summary would hide the new transitive block.  A
    corrupt cache degrades to re-parsing, never crashes."""
    pkg = _write_pkg(tmp_path, {
        "h.py": """\
            def drain(fut):
                return fut.result(timeout=1.0)
            """,
        "m.py": """\
            import threading
            from .h import drain

            class P:
                def __init__(self):
                    self._lock = threading.Lock()

                def maybe_bad(self, fut):
                    with self._lock:
                        return drain(fut)
            """})
    cache = str(tmp_path / "cache.json")
    args = [sys.executable, FLOWLINT, "--baseline", "none",
            "--summary-cache", cache, str(pkg)]
    out = subprocess.run(args, capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    assert os.path.exists(cache)
    # Make the helper unbounded: the cached summary for h.py is stale
    # (hash mismatch) and must be re-extracted -> FTL013 in m.py.
    (pkg / "h.py").write_text(
        "def drain(fut):\n    return fut.result()\n")
    out = subprocess.run(args, capture_output=True, text=True)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "FTL013" in out.stdout
    # Corrupt cache: fail-soft, identical outcome.
    with open(cache, "w") as f:
        f.write("{not json")
    out = subprocess.run(args, capture_output=True, text=True)
    assert out.returncode == 1 and "FTL013" in out.stdout


def test_changed_mode_links_unchanged_program(tmp_path):
    """--changed lints ONLY the changed file but still sees the whole
    program through the summary layer: a new lock-held call into an
    UNCHANGED helper's blocking chain is caught."""
    repo = tmp_path / "r"
    pkg = repo / "pkg"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "h.py").write_text(
        "def drain(fut):\n    return fut.result()\n")
    dirty = pkg / "m.py"
    dirty.write_text("x = 1\n")
    _git(repo, "init", "-q")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-qm", "seed")
    dirty.write_text(textwrap.dedent("""\
        import threading
        from .h import drain

        class P:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self, fut):
                with self._lock:
                    return drain(fut)
        """))
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--changed", "HEAD", "--baseline",
         "none", "--summary-cache", "none", str(pkg)],
        capture_output=True, text=True)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "FTL013" in out.stdout and "1 file(s) scanned" in out.stdout


def test_run_chaos_embeds_new_rules():
    """run_chaos embeds findings by SHELLING the CLI, so the new rules
    ride along automatically: --list-rules (the same rule registry the
    embedded scan uses AND the tier-1 clean-repo gate runs) must carry
    FTL013/FTL014 and the ISSUE-13 FTL015/FTL016, and collect_flowlint
    must return the CLI's counts for the clean repo."""
    out = subprocess.run([sys.executable, FLOWLINT, "--list-rules"],
                         capture_output=True, text=True)
    assert "FTL013" in out.stdout and "FTL014" in out.stdout
    assert "FTL015" in out.stdout and "FTL016" in out.stdout
    import importlib.util
    spec_mod = importlib.util.spec_from_file_location(
        "run_chaos", os.path.join(REPO, "scripts", "run_chaos.py"))
    run_chaos = importlib.util.module_from_spec(spec_mod)
    spec_mod.loader.exec_module(run_chaos)
    doc = run_chaos.collect_flowlint()
    assert doc["exit_code"] == 0, doc
    assert doc["counts"]["new"] == 0
    assert doc["findings"] == []


# ---------------------------------------------------------------------------
# Object-sensitive engine (ISSUE 13): type inference, lock identity,
# FTL015 lock-ordering cycles, FTL016 promise protocol
# ---------------------------------------------------------------------------

OBJSENSE = os.path.join(FIXTURES, "objsense")


def test_objsense_fixture_exact_both_directions():
    """The object-sensitivity fixture package scanned ALONE: findings
    == markers exactly, both ways — two-instance no-alias stays CLEAN,
    the AB/BA and three-lock cycles fire, the receiver-typed dispatch
    battery resolves (and its ambiguous case stays quiet), and the
    promise-protocol battery fires exactly on its leaks."""
    exp = set()
    for dirpath, dirnames, filenames in os.walk(OBJSENSE):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, fn),
                                  OBJSENSE).replace(os.sep, "/")
            with open(os.path.join(dirpath, fn)) as f:
                for line in f:
                    if "# expect:" in line:
                        for m in EXPECT.finditer(line):
                            exp.add((m.group(1), rel, int(m.group(2))))
    assert {"FTL013", "FTL015", "FTL016"} <= {r for r, _, _ in exp}
    result = _scan([OBJSENSE])
    got = {(f.rule, f.path, f.line) for f in result.new}
    assert got == exp, (f"unexpected: {sorted(got - exp)}\n"
                        f"missing: {sorted(exp - got)}")


def test_type_inference_lattice(tmp_path):
    """The local type-inference lattice: constructor assignments,
    annotations, factory returns (through the returns-instance
    fixpoint, incl. factory-through-factory), and self-attribute types
    each resolve a receiver-typed call; a join of two different types
    is UNKNOWN (the call stays unresolved and keeps feeding the
    conservatism set)."""
    pkg = _write_pkg(tmp_path, {
        "eng.py": """\
            class Engine:
                def op(self):
                    return 1

            class Other:
                def op(self):
                    return 2

            def make():
                return Engine()

            def chain():
                return make()

            class Holder:
                def __init__(self):
                    self.eng = Engine()

                def via_attr(self):
                    self.eng.op()

            def via_ctor():
                e = Engine()
                e.op()

            def via_ann(e: Engine):
                e.op()

            def via_factory():
                e = make()
                e.op()

            def via_chained_factory():
                e = chain()
                e.op()

            def ambiguous(c):
                if c:
                    e = Engine()
                else:
                    e = Other()
                e.op()
            """})
    pi = _program(pkg)
    g = pi.graph

    def targets_of(qname):
        return [t for _, t in
                pi.calls_with_targets(f"eng.py::{qname}") if t]

    for fn in ("via_ctor", "via_ann", "via_factory",
               "via_chained_factory"):
        assert "eng.py::Engine.op" in targets_of(fn), fn
    assert "eng.py::Engine.op" in targets_of("Holder.via_attr")
    assert targets_of("ambiguous") == []    # join of two types: unknown
    assert "op" in g.unresolved_names       # ... and stays conservative
    # The returns-instance fixpoint behind the factory cases.
    assert g.returns_instance["eng.py::make"] == ("eng.py", "Engine")
    assert g.returns_instance["eng.py::chain"] == ("eng.py", "Engine")
    # resolve_type unit shapes.
    assert g.resolve_type("eng.py", None, ["call", "name", "Engine"]) \
        == ("eng.py", "Engine")
    assert g.resolve_type("eng.py", "Holder", ["selfattr", "eng"]) == \
        ("eng.py", "Engine")
    assert g.resolve_type("eng.py", None, ["ann", "name", "Other"]) == \
        ("eng.py", "Other")
    assert g.resolve_type("eng.py", None,
                          ["call", "name", "nonesuch"]) is None


def test_returns_instance_judges_names_at_their_def_site(tmp_path):
    """Review catch: tracing `return y` through `y = x` must read x's
    defs as of the ASSIGNMENT, not the return — a rebind of x in
    between (`y = x; x = Other(); return y`) would otherwise re-type
    the factory to the wrong class (the unsound direction: wrongly
    resolved callees can silence real findings)."""
    pkg = _write_pkg(tmp_path, {
        "m.py": """\
            class Promise:
                def send(self, v=None):
                    pass

            class Database:
                def op(self):
                    pass

            def make():
                x = Promise()
                y = x
                x = Database()
                return y
            """})
    pi = _program(pkg)
    assert pi.graph.returns_instance.get("m.py::make") == \
        ("m.py", "Promise")


def test_typed_resolution_preserves_seeding(tmp_path):
    """The motivating precision win: a RESOLVED receiver-typed call no
    longer poisons same-named functions out of caller-held-lockset
    seeding (before ISSUE 13 every obj.method() was an unknown callee
    whose terminal name disqualified the whole name)."""
    pkg = _write_pkg(tmp_path, {
        "s.py": """\
            import threading

            class Engine:
                def op(self):
                    return 1

            class Guarded:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def _op(self):
                    self._n += 1

                def run(self, eng: Engine):
                    eng.op()
                    with self._lock:
                        self._op()
            """})
    pi = _program(pkg)
    assert "op" not in pi.graph.unresolved_names
    assert pi.entry_locks("s.py", "Guarded._op") == \
        frozenset({"self._lock"})


def test_lock_identity_role_keying(tmp_path):
    """Instance-role keying: the allocation-site owner unifies an
    inherited lock across Base/Sub frames; two instances held in
    different FIELDS get distinct role identities (plus the shared
    class-generic identity for class-level ordering); module locks are
    file-scoped; a function-local lock has no shared identity."""
    pkg = _write_pkg(tmp_path, {
        "w.py": """\
            import threading

            _MOD_LOCK = threading.Lock()

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()

            class SubWorker(Worker):
                def sub_op(self):
                    with self._lock:
                        return 1

            class Pair:
                def __init__(self):
                    self.a = Worker()
                    self.b = Worker()

                def use(self):
                    with self.a._lock:
                        return 1

            def local_only():
                tmp_lock = threading.Lock()
                with tmp_lock:
                    return 1
            """})
    pi = _program(pkg)
    assert pi.lock_identities("w.py", "Worker", "self._lock") == \
        ["w.py::Worker#_lock"]
    assert pi.lock_identities("w.py", "SubWorker", "self._lock") == \
        ["w.py::Worker#_lock"]      # inherited: the ALLOCATING class
    ia = pi.lock_identities("w.py", "Pair", "self.a._lock")
    ib = pi.lock_identities("w.py", "Pair", "self.b._lock")
    assert ia[0] == "w.py::Pair#a._lock"
    assert ib[0] == "w.py::Pair#b._lock"
    assert "w.py::Worker#_lock" in ia and "w.py::Worker#_lock" in ib
    assert pi.lock_identities("w.py", None, "_MOD_LOCK") == \
        ["w.py#_MOD_LOCK"]
    assert pi.lock_identities("w.py", None, "tmp_lock") == []


_TWO_INSTANCE_ONE_WAY = """\
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0

        def locked_op(self):
            with self._lock:
                self._n += 1

    class Pair:
        def __init__(self):
            self.a = Worker()
            self.b = Worker()

        def cross(self):
            with self.a._lock:
                self.b.locked_op()
"""


def test_two_instance_conflation_silenced_and_real_cycle_fires(tmp_path):
    """The deleted-fix regression for the conflation FTL015 was built
    to avoid: one-directional nesting between two same-class instances
    is CLEAN (name-keyed identities would read it as a self-cycle),
    while adding the REVERSE direction creates a true role-level AB/BA
    cycle that fires."""
    pkg = _write_pkg(tmp_path, {"p.py": _TWO_INSTANCE_ONE_WAY})
    result = _scan([str(pkg)])
    assert [f for f in result.new if f.rule == "FTL015"] == [], \
        [f.message for f in result.new]

    pkg2 = tmp_path / "pkg2"
    pkg2.mkdir()
    (pkg2 / "__init__.py").write_text("")
    (pkg2 / "p.py").write_text(textwrap.dedent(
        _TWO_INSTANCE_ONE_WAY +
        "\n"
        "        def cross_rev(self):\n"
        "            with self.b._lock:\n"
        "                self.a.locked_op()\n"))
    result = _scan([str(pkg2)])
    ftl15 = [f for f in result.new if f.rule == "FTL015"]
    assert len(ftl15) == 1, [f.message for f in result.new]
    msg = ftl15[0].message
    assert "Pair#a._lock" in msg and "Pair#b._lock" in msg


_PR10_SHAPE_HEAD = """\
    class Promise:
        def send(self, value=None):
            pass

        def send_error(self, e):
            pass

        def get_future(self):
            return self

    class CC:
        def __init__(self):
            self.db_info = {}

        def handle_open_database(self, known_epoch, epoch):
            reply = Promise()
            if epoch > known_epoch:
                reply.send(self.db_info)
"""


def test_ftl016_refires_on_pr10_promise_leak_shape(tmp_path):
    """The deleted-fix regression for the PR-10 bug class: a deposed
    CC's long-poll reply neither sent nor broken on the parked branch
    (distilled).  The leaky shape fires; the PR-10 fix shape (the
    explicit break on the other branch) is silent."""
    pkg = _write_pkg(tmp_path, {
        "cc.py": _PR10_SHAPE_HEAD + """\
            return reply.get_future()
        """})
    result = _scan([str(pkg)])
    ftl16 = [f for f in result.new if f.rule == "FTL016"]
    assert [(f.path, f.line) for f in ftl16] == [("cc.py", 16)], \
        [f.message for f in result.new]

    pkg2 = tmp_path / "pkg2"
    pkg2.mkdir()
    (pkg2 / "__init__.py").write_text("")
    (pkg2 / "cc.py").write_text(textwrap.dedent(
        _PR10_SHAPE_HEAD + """\
            else:
                reply.send_error(RuntimeError("deposed"))
            return reply.get_future()
        """))
    result = _scan([str(pkg2)])
    assert [f for f in result.new if f.rule == "FTL016"] == [], \
        [f.message for f in result.new]


def test_repo_promise_paths_are_clean_shapes():
    """The cleanup-sweep anchors: the repo files carrying the PR-10
    fixes and the closure-escape promise patterns (scheduler delay,
    threadpool run, both network send_request paths) lint FTL016-clean
    when scanned directly — each was a triaged false-positive class
    (closure hand-off / finally-break) the analysis must keep
    understanding."""
    for rel in ("server/cluster_controller.py", "core/scheduler.py",
                "core/threadpool.py", "rpc/network.py",
                "rpc/real_network.py"):
        target = os.path.join(REPO, "foundationdb_tpu", *rel.split("/"))
        result = _scan([target])
        bad = [f for f in result.new if f.rule in ("FTL015", "FTL016")]
        assert bad == [], (rel, [f"{f.line} {f.rule}" for f in bad])


def test_param_canon_is_object_sensitive(tmp_path):
    """Two callers spelling the textually-identical ``self._lock`` from
    DIFFERENT classes pass two different lock objects: the parameter
    must CONFLICT (FTL014), not silently unify — the two-instances-
    one-name fiction FTL012/013/014 were re-grounded away from."""
    pkg = _write_pkg(tmp_path, {
        "m.py": """\
            import threading

            def _locked_add(use_lock, n):
                with use_lock:
                    return n + 1

            def _locked_solo(use_lock, n):
                with use_lock:
                    return n + 1

            class A:
                def __init__(self):
                    self._lock = threading.Lock()

                def go(self):
                    return _locked_add(self._lock, 1)

                def solo(self):
                    return _locked_solo(self._lock, 1)

            class B:
                def __init__(self):
                    self._lock = threading.Lock()

                def go(self):
                    return _locked_add(self._lock, 2)
            """})
    pi = _program(pkg)
    assert [(c[1], c[3]) for c in pi.param_conflicts] == \
        [("_locked_add", "use_lock")]
    # One class only: unifies — on the qualified identity, since the
    # callee's frame has no `self` binding for the caller's object.
    assert pi.param_canon("m.py", "_locked_solo") == \
        {"use_lock": "m.py::A#_lock"}


def test_summary_cache_stamp_invalidates_on_analysis_upgrade(tmp_path):
    """ISSUE 13 satellite: cache entries are keyed by (content hash,
    analysis-version stamp).  A cache whose entries carry an OLDER
    stamp but matching hashes must be treated as stale — its facts
    predate the current extractor (here: simulated by stripping the
    ISSUE-13 keys), and serving them would silence FTL016 for the
    unchanged helper file."""
    pkg = _write_pkg(tmp_path, {
        "h.py": """\
            class Promise:
                def send(self, value=None):
                    pass

                def get_future(self):
                    return self

            def make_reply():
                return Promise()
            """,
        "m.py": """\
            from .h import make_reply

            def serve(ready):
                p = make_reply()
                if ready:
                    p.send(1)
                return p.get_future()
            """})
    cache = str(tmp_path / "cache.json")
    args = [sys.executable, FLOWLINT, "--baseline", "none",
            "--summary-cache", cache, str(pkg / "m.py")]
    out = subprocess.run(args, capture_output=True, text=True)
    assert out.returncode == 1 and "FTL016" in out.stdout, \
        out.stdout + out.stderr
    # Doctor the cache into a pre-upgrade one: stamps roll back, the
    # ISSUE-13 fact keys vanish, hashes stay CORRECT.
    with open(cache) as f:
        doc = json.load(f)
    for entry in doc["files"].values():
        entry["stamp"] = 1
        for fn in entry["facts"]["functions"].values():
            fn.pop("rets_type", None)
            fn.pop("leaks", None)
            fn.pop("acquisitions", None)
    with open(cache, "w") as f:
        json.dump(doc, f)
    out = subprocess.run(args, capture_output=True, text=True)
    assert out.returncode == 1 and "FTL016" in out.stdout, (
        "stale-stamp cache entry was served: " + out.stdout + out.stderr)


def test_local_instance_locks_have_no_shared_identity(tmp_path):
    """Review catch: two functions each nesting their OWN fresh
    instances' locks in opposite orders share no lock object — the
    textual fallback identity for dotted non-self keys aliased them
    into a false FTL015 cycle; function-local paths now contribute no
    identity at all."""
    pkg = _write_pkg(tmp_path, {
        "m.py": """\
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

            def f():
                a, b = W(), W()
                with a._lock:
                    with b._lock:
                        return 1

            def g():
                a, b = W(), W()
                with b._lock:
                    with a._lock:
                        return 1
            """})
    result = _scan([str(pkg)])
    assert [f for f in result.new if f.rule == "FTL015"] == [], \
        [f.message for f in result.new]


def test_ftl016_exit_walk_stops_at_function_boundary(tmp_path):
    """Review catch: the return-through-finally exemption walked past
    the enclosing function, so a module-level try/finally around a WHOLE
    def silenced every leak inside it."""
    pkg = _write_pkg(tmp_path, {
        "m.py": """\
            class Promise:
                def send(self, v=None):
                    pass

                def get_future(self):
                    return self

            try:
                def serve(ready):
                    p = Promise()
                    if ready:
                        p.send(1)
                    return p.get_future()
            finally:
                pass
            """})
    result = _scan([str(pkg)])
    assert [(f.rule, f.line) for f in result.new] == [("FTL016", 10)], \
        [f.message for f in result.new]


def test_ftl016_sees_creations_inside_except_handlers(tmp_path):
    """Review catch: handler entries are reachable only through the
    (excluded) exception edges, so their own promise creations never
    entered the fixpoint — a caught handler KEEPS RUNNING, so they seed
    as entry points with empty facts."""
    pkg = _write_pkg(tmp_path, {
        "m.py": """\
            class Promise:
                def send(self, v=None):
                    pass

                def get_future(self):
                    return self

            def serve(risky, ready):
                try:
                    risky()
                except Exception:
                    p = Promise()
                    if ready:
                        p.send(1)
                    return p.get_future()
                return None
            """})
    result = _scan([str(pkg)])
    assert [(f.rule, f.line) for f in result.new] == [("FTL016", 12)], \
        [f.message for f in result.new]


def test_fresh_local_lock_param_never_fabricates_identity(tmp_path):
    """Review catch: a per-call fresh local lock passed through
    canonicalized lock params must not mint a shared 'concrete'
    identity — two threads never contend on a lock created fresh per
    invocation, so no FTL015 cycle can involve it."""
    pkg = _write_pkg(tmp_path, {
        "m.py": """\
            import threading

            _MOD_LOCK = threading.Lock()

            def _helper_acquire(use_lock):
                with use_lock:
                    return 1

            def _helper_nested(use_lock):
                with use_lock:
                    with _MOD_LOCK:
                        return 1

            def f():
                tmp_lock = threading.Lock()
                with _MOD_LOCK:
                    _helper_acquire(tmp_lock)
                _helper_nested(tmp_lock)
            """})
    result = _scan([str(pkg)])
    assert [f for f in result.new if f.rule == "FTL015"] == [], \
        [f.message for f in result.new]


def test_class_body_lock_attr_is_an_allocation_site(tmp_path):
    """Review catch: `_lock = threading.Lock()` at CLASS BODY level is
    one shared allocation site — Base and Sub methods passing
    ``self._lock`` must unify on Base's identity, not conflict as two
    per-class fabrications."""
    pkg = _write_pkg(tmp_path, {
        "m.py": """\
            import threading

            def _helper(use_lock):
                with use_lock:
                    return 1

            class Base:
                _lock = threading.Lock()

                def m(self):
                    _helper(self._lock)

            class Sub(Base):
                def n(self):
                    _helper(self._lock)
            """})
    pi = _program(pkg)
    assert pi.lock_identities("m.py", "Sub", "self._lock") == \
        ["m.py::Base#_lock"]
    assert pi.param_conflicts == []
    result = _scan([str(pkg)])
    assert [f for f in result.new if f.rule == "FTL014"] == [], \
        [f.message for f in result.new]


def test_ftl016_fall_off_the_end_exit(tmp_path):
    """Review catch: a conditional resolve as the LAST statement leaks
    on the fall-through path — the exit is an EDGE out of the branch
    test (which still has successors), so successor-less-node exit
    detection alone missed the rule's own motivating shape."""
    pkg = _write_pkg(tmp_path, {
        "m.py": """\
            class Promise:
                def send(self, v=None):
                    pass

                def send_error(self, e):
                    pass

            def leaky(ok):
                p = Promise()
                if ok:
                    p.send(1)

            def ok_both_branches(ready):
                p = Promise()
                if ready:
                    p.send(1)
                else:
                    p.send_error(RuntimeError())
            """})
    result = _scan([str(pkg)])
    assert [(f.rule, f.line) for f in result.new] == [("FTL016", 9)], \
        [f.message for f in result.new]


def test_passthrough_lock_param_does_not_conflict(tmp_path):
    """Review catch: two wrappers forwarding their OWN param into a
    shared locked helper must not read as two distinct fabricated
    locks (false FTL014) — a forwarded param resolves through the
    caller's canon or stays unknown."""
    pkg = _write_pkg(tmp_path, {
        "m.py": """\
            import threading

            _MOD_LOCK = threading.Lock()

            def _helper(use_lock):
                with use_lock:
                    return 1

            def _w1(lk_lock):
                return _helper(lk_lock)

            def _w2(lk_lock):
                return _helper(lk_lock)

            def f():
                _w1(_MOD_LOCK)
                _w2(_MOD_LOCK)
            """})
    result = _scan([str(pkg)])
    assert [f for f in result.new if f.rule == "FTL014"] == [], \
        [f.message for f in result.new]


def test_ftl016_return_inside_finalbody_is_an_exit(tmp_path):
    """Review catch: a return INSIDE a finalbody exits the function
    directly — its own try must not exempt it (there is no further
    finally to resolve the promise)."""
    pkg = _write_pkg(tmp_path, {
        "m.py": """\
            class Promise:
                def send(self, v=None):
                    pass

                def get_future(self):
                    return self

            def leaky_in_finally():
                p = Promise()
                try:
                    pass
                finally:
                    return p.get_future()
            """})
    result = _scan([str(pkg)])
    assert [(f.rule, f.line) for f in result.new] == [("FTL016", 9)], \
        [f.message for f in result.new]


def test_ftl016_return_through_finally(tmp_path):
    """Review catch: a return inside try-with-finalbody completes
    NORMALLY through the finalbody — the leak facts must ride that
    path, so an unresolved promise still fires while one the finalbody
    resolves stays quiet."""
    pkg = _write_pkg(tmp_path, {
        "m.py": """\
            class Promise:
                def send(self, v=None):
                    pass

                def break_promise(self):
                    pass

                def get_future(self):
                    return self

            def leaky():
                try:
                    p = Promise()
                    return p.get_future()
                finally:
                    pass

            def healed():
                try:
                    p = Promise()
                    return p.get_future()
                finally:
                    p.break_promise()
            """})
    result = _scan([str(pkg)])
    assert [(f.rule, f.line) for f in result.new] == [("FTL016", 13)], \
        [f.message for f in result.new]


def test_cli_sarif_format():
    """--format sarif: valid SARIF 2.1.0 shape — tool rule metadata for
    the whole registry, error-level results with rule id + location,
    and the FTL015 witness chain riding the message text."""
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--format", "sarif", "--baseline",
         "none", OBJSENSE],
        capture_output=True, text=True)
    assert out.returncode == 1
    doc = json.loads(out.stdout)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "flowlint"
    rule_ids = {r["id"] for r in driver["rules"]}
    assert {f"FTL{i:03d}" for i in range(1, N_RULES + 1)} <= rule_ids
    assert run["results"], "fixtures must produce results"
    for r in run["results"]:
        assert r["level"] == "error"
        loc = r["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith(".py")
        assert loc["region"]["startLine"] >= 1
    ftl15 = [r for r in run["results"] if r["ruleId"] == "FTL015"]
    assert ftl15 and " then " in ftl15[0]["message"]["text"]
    assert ftl15[0]["locations"][0]["physicalLocation"][
        "artifactLocation"]["uri"] == "cycles.py"


# ---------------------------------------------------------------------------
# --changed incremental mode
# ---------------------------------------------------------------------------

def _git(repo, *args):
    out = subprocess.run(["git", "-C", str(repo), "-c", "user.name=t",
                          "-c", "user.email=t@t"] + list(args),
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    return out


def test_cli_changed_mode(tmp_path):
    """--changed lints exactly the files `git diff` names, with full
    baseline/suppression semantics, and exits clean when nothing
    changed."""
    repo = tmp_path / "r"
    pkg = repo / "pkg"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "clean.py").write_text("x = 1\n")
    dirty = pkg / "dirty.py"
    dirty.write_text("y = 1\n")
    _git(repo, "init", "-q")
    _git(repo, "add", "-A")
    _git(repo, "commit", "-qm", "seed")

    # Nothing changed: clean exit, zero files scanned.
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--changed", "HEAD", "--baseline",
         "none", str(pkg)], capture_output=True, text=True)
    assert out.returncode == 0 and "0 file(s) scanned" in out.stdout

    # A violation in one changed file: only that file is linted.
    dirty.write_text("import time\nt = time.monotonic()\n")
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--changed", "HEAD", "--baseline",
         "none", str(pkg)], capture_output=True, text=True)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "dirty.py" in out.stdout and "FTL001" in out.stdout
    assert "1 file(s) scanned" in out.stdout

    # Suppression semantics identical to a full scan.
    dirty.write_text(
        "import time\n"
        "t = time.monotonic()  # flowlint: disable=FTL001 -- test\n")
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--changed", "HEAD", "--baseline",
         "none", str(pkg)], capture_output=True, text=True)
    assert out.returncode == 0 and "1 suppressed" in out.stdout

    # UNTRACKED files are included too: a brand-new module is the one
    # most likely to carry new findings, and `git diff` never lists it.
    (pkg / "fresh.py").write_text("import time\nt = time.time()\n")
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--changed", "HEAD", "--baseline",
         "none", str(pkg)], capture_output=True, text=True)
    assert out.returncode == 1 and "fresh.py" in out.stdout

    # ... under EVERY lint root, not just the first (ls-files --others
    # runs from the repo toplevel, unlike the cwd-scoped default).
    pkg2 = repo / "pkg2"
    pkg2.mkdir()
    (pkg2 / "other.py").write_text("import time\nt2 = time.time()\n")
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--changed", "HEAD", "--baseline",
         "none", str(pkg), str(pkg2)], capture_output=True, text=True)
    assert out.returncode == 1 and "other.py" in out.stdout


def test_cli_changed_through_symlinked_root(tmp_path):
    """A checkout reached through a symlink (macOS /tmp, symlinked CI
    workspaces) must not silently lint zero files: git's resolved
    toplevel and the symlink-spelled lint root are realpath'd to the
    same prefix (review catch)."""
    real = tmp_path / "real"
    (real / "pkg").mkdir(parents=True)
    (real / "pkg" / "mod.py").write_text("x = 1\n")
    _git(real, "init", "-q")
    _git(real, "add", "-A")
    _git(real, "commit", "-qm", "seed")
    link = tmp_path / "link"
    os.symlink(real, link)
    (link / "pkg" / "mod.py").write_text(
        "import time\nt = time.monotonic()\n")
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--changed", "HEAD", "--baseline",
         "none", str(link / "pkg")], capture_output=True, text=True)
    assert out.returncode == 1, out.stdout + out.stderr
    assert "1 file(s) scanned" in out.stdout


def test_cli_changed_conflicts_with_write_baseline(tmp_path):
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--changed", "--write-baseline",
         str(tmp_path)], capture_output=True, text=True)
    assert out.returncode == 2
    assert "full scan" in out.stderr


# ---------------------------------------------------------------------------
# Cross-process unseed reproduction (PYTHONHASHSEED pinned)
# ---------------------------------------------------------------------------

CANARY_SPEC = """
[[test]]
testTitle = 'HashCanary'
  [[test.workload]]
  testName = 'HashOrderCanary'
"""

_CANARY_RUNNER = (
    "import json, sys\n"
    "from foundationdb_tpu.testing import run_simulation\n"
    f"r = run_simulation({CANARY_SPEC!r}, 11, audit=False)\n"
    "print(json.dumps({'unseed': r.unseed, 'digest': r.digest,"
    " 'folds': r.folds}))\n")


def _canary_in_subprocess(hash_seed: str) -> dict:
    env = dict(os.environ, PYTHONHASHSEED=hash_seed, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", _CANARY_RUNNER],
                         capture_output=True, text=True, cwd=REPO,
                         env=env, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_cross_process_unseed_with_pinned_hash_seed():
    """run_test_twice's contract ACROSS processes: two fresh interpreters
    with the same PYTHONHASHSEED replay the str-set-order-sensitive
    canary bit-identically (ROADMAP chaos follow-up, closed here)."""
    a = _canary_in_subprocess("0")
    b = _canary_in_subprocess("0")
    assert a == b, f"pinned-hash-seed runs diverged: {a} vs {b}"


def test_hash_order_canary_is_actually_sensitive():
    """The negative control controls: DIFFERENT pinned hash seeds give
    different str-set orders, which the canary folds into the unseed —
    proving the pin is load-bearing, not vacuous."""
    a = _canary_in_subprocess("1")
    b = _canary_in_subprocess("2")
    assert (a["unseed"], a["digest"]) != (b["unseed"], b["digest"]), (
        "canary failed to observe hash-order difference — it no longer "
        "guards the PYTHONHASHSEED pin")


# ---------------------------------------------------------------------------
# Container sensitivity & ownership protocol (ISSUE 20)
# ---------------------------------------------------------------------------

def test_container_lock_element_identity_named_in_finding(tmp_path):
    """``with self._locks[shard]:`` enters the lockset as the ONE
    may-alias element identity per container (``self._locks[*]``) —
    before ISSUE 20 the subscripted receiver keyed as nothing and the
    lock rules were blind to sharded locks entirely."""
    pkg = _write_pkg(tmp_path, {
        "m.py": """\
            class T:
                def __init__(self):
                    self._locks = {}

                async def bad(self, k, fut):
                    with self._locks[k]:
                        await fut
            """})
    result = _scan([str(pkg)])
    found = [f for f in result.new if f.rule == "FTL011"]
    assert len(found) == 1 and "self._locks[*]" in found[0].message, \
        [f.message for f in result.new]


def test_container_lock_cycle_through_elements(tmp_path):
    """FTL015 sees lock-order cycles THROUGH container elements: gate
    then element in one method, element then gate in another, is a
    cycle on the element identity."""
    pkg = _write_pkg(tmp_path, {
        "m.py": """\
            import threading

            class T:
                def __init__(self):
                    self._gate_lock = threading.Lock()
                    self._locks = {}

                def a(self, k):
                    with self._gate_lock:
                        with self._locks[k]:
                            return 1

                def b(self, k):
                    with self._locks[k]:
                        with self._gate_lock:
                            return 1
            """})
    result = _scan([str(pkg)])
    cycles = [f for f in result.new if f.rule == "FTL015"]
    assert cycles and any("_locks[*]" in f.message for f in cycles), \
        [f.message for f in result.new]


def test_container_lock_elements_do_not_unify_across_classes(tmp_path):
    """Element identities are allocation-site-owned (PR-13 style): two
    classes both spelling ``self._locks[k]`` hold two DIFFERENT
    containers' elements — opposite nesting against a shared module
    lock is not a cycle."""
    pkg = _write_pkg(tmp_path, {
        "m.py": """\
            import threading

            _MOD_LOCK = threading.Lock()

            class A:
                def __init__(self):
                    self._locks = {}

                def m(self, k):
                    with _MOD_LOCK:
                        with self._locks[k]:
                            return 1

            class B:
                def __init__(self):
                    self._locks = {}

                def n(self, k):
                    with self._locks[k]:
                        with _MOD_LOCK:
                            return 1
            """})
    result = _scan([str(pkg)])
    assert [f for f in result.new if f.rule == "FTL015"] == [], \
        [f.message for f in result.new]


def test_optional_and_union_annotations_type_the_receiver(tmp_path):
    """ISSUE 20 annotation lattice: ``Optional[C]``, ``C | None`` and
    STRING forward references all feed the receiver type, so a typed
    param's method call resolves and the blocking summary composes
    through it (FTL013)."""
    pkg = _write_pkg(tmp_path, {
        "m.py": """\
            import threading
            import time
            from typing import Optional

            class Worker:
                def block(self):
                    time.sleep(1)

            def _poke_opt(w: Optional[Worker]):
                if w is not None:
                    w.block()

            def _poke_str(w: "Worker | None"):
                if w is not None:
                    w.block()

            class M:
                def __init__(self):
                    self._lock = threading.Lock()

                def via_optional(self, w):
                    with self._lock:
                        _poke_opt(w)

                def via_string_union(self, w):
                    with self._lock:
                        _poke_str(w)
            """})
    result = _scan([str(pkg)])
    lines = sorted(f.line for f in result.new if f.rule == "FTL013")
    assert len(lines) == 2, [f"{f.rule}:{f.line} {f.message}"
                             for f in result.new]


def test_dict_element_annotation_types_subscripted_receiver(tmp_path):
    """``self._workers: Dict[str, Worker]`` gives the SUBSCRIPTED
    receiver an element type: ``self._workers[k].block()`` resolves
    through the selfelem texpr and the held-lock blocking chain fires
    (FTL013)."""
    pkg = _write_pkg(tmp_path, {
        "m.py": """\
            import threading
            import time
            from typing import Dict

            class Worker:
                def block(self):
                    time.sleep(1)

            class M:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._workers: Dict[str, Worker] = {}

                def bad(self, k):
                    with self._lock:
                        self._workers[k].block()
            """})
    result = _scan([str(pkg)])
    found = [f for f in result.new if f.rule == "FTL013"]
    assert len(found) == 1 and "block" in found[0].message, \
        [f.message for f in result.new]


_FTL017_PKG = {
    "flow.py": """\
        class Promise:
            def send(self, value=None):
                pass

            def send_error(self, error=None):
                pass

            def get_future(self):
                return self
        """,
    "registry.py": """\
        from .flow import Promise

        class Registry:
            def __init__(self):
                self._waiters = []

            def subscribe(self):
                p = Promise()
                self._waiters.append(p)
                return p.get_future()
        """}

_FTL017_DRAINER = """\
    from .registry import Registry

    class Drainer(Registry):
        def flush(self, value):
            for p in self._waiters:
                p.send(value)
            self._waiters.clear()
    """


def test_ftl017_fires_at_creation_line_and_drain_silences(tmp_path):
    """The undrained registry fires AT THE CREATION LINE (where the
    hang is debugged from); adding a drain anywhere in the package —
    here a subclass in ANOTHER file, unified through the MRO field
    identity — silences it with no suppression."""
    pkg = _write_pkg(tmp_path, _FTL017_PKG)
    result = _scan([str(pkg)])
    assert [(f.rule, f.path, f.line) for f in result.new] == \
        [("FTL017", "registry.py", 8)], [f.message for f in result.new]

    (pkg / "drainer.py").write_text(textwrap.dedent(_FTL017_DRAINER))
    result = _scan([str(pkg)])
    assert result.new == [], [f.message for f in result.new]


def test_ftl017_drain_deletion_refires(tmp_path):
    """Deleting the one drain site re-fires the park — the sanction is
    recomputed from the live program, never latched."""
    pkg = _write_pkg(tmp_path, _FTL017_PKG)
    (pkg / "drainer.py").write_text(textwrap.dedent(_FTL017_DRAINER))
    assert _scan([str(pkg)]).new == []

    (pkg / "drainer.py").write_text(textwrap.dedent("""\
        from .registry import Registry

        class Drainer(Registry):
            def flush(self, value):
                pass
        """))
    result = _scan([str(pkg)])
    assert [(f.rule, f.line) for f in result.new] == [("FTL017", 8)], \
        [f.message for f in result.new]


def test_ftl017_owned_annotation_is_the_escape_hatch(tmp_path):
    """``# flowlint: owned -- <why>`` on the CREATION line sanctions a
    registry drained outside the package's sight — and only that line:
    the un-annotated park in the same class still fires."""
    pkg = _write_pkg(tmp_path, dict(_FTL017_PKG, **{
        "registry.py": """\
            from .flow import Promise

            class Registry:
                def __init__(self):
                    self._waiters = []
                    self._external = []

                def subscribe(self):
                    p = Promise()
                    self._waiters.append(p)
                    return p.get_future()

                def adopt(self):
                    q = Promise()  # flowlint: owned -- harness drains it
                    self._external.append(q)
                    return q.get_future()
            """}))
    result = _scan([str(pkg)])
    assert [(f.rule, f.line) for f in result.new] == [("FTL017", 9)], \
        [f.message for f in result.new]


def test_summary_cache_staleness_guards_container_facts(tmp_path):
    """ISSUE 20 satellite: the ownership protocol is only as sound as
    the cache.  With the drain CACHED in a sibling file, (a) tampered
    facts under a CURRENT stamp are served — the drain vanishes and
    FTL017 fires, proving the facts really come from the cache; (b)
    rolling the stamp back to a pre-upgrade value forces re-extraction
    and the drain returns.  Both directions pin ANALYSIS_VERSION as
    the thing that saves correctness after an extractor upgrade."""
    pkg = _write_pkg(tmp_path, _FTL017_PKG)
    (pkg / "drainer.py").write_text(textwrap.dedent(_FTL017_DRAINER))
    cache = str(tmp_path / "cache.json")
    args = [sys.executable, FLOWLINT, "--baseline", "none",
            "--summary-cache", cache, str(pkg / "registry.py")]
    out = subprocess.run(args, capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr

    with open(cache) as f:
        doc = json.load(f)
    entry = next(e for rel, e in doc["files"].items()
                 if rel.endswith("drainer.py"))
    for fn in entry["facts"]["functions"].values():
        fn["drains"] = []
        fn["drain_forwards"] = []
    with open(cache, "w") as f:
        json.dump(doc, f)
    out = subprocess.run(args, capture_output=True, text=True)
    assert out.returncode == 1 and "FTL017" in out.stdout, (
        "tampered cached container facts were NOT served — the cache "
        "test has no teeth: " + out.stdout + out.stderr)

    for e in doc["files"].values():
        e["stamp"] = 1
    with open(cache, "w") as f:
        json.dump(doc, f)
    out = subprocess.run(args, capture_output=True, text=True)
    assert out.returncode == 0, (
        "stale-stamp entry with doctored container facts was served: "
        + out.stdout + out.stderr)


def test_ftl018_real_wire_registry_is_clean():
    """The shipped _GOLDEN_FROZEN_FIELDS registry matches the shipped
    interface dataclasses exactly — no grafted field, no ghost elide,
    no removed frozen field, with zero suppressions."""
    result = _scan([
        os.path.join(REPO, "foundationdb_tpu", "rpc", "serde.py"),
        os.path.join(REPO, "foundationdb_tpu", "server",
                     "interfaces.py")])
    assert [f for f in result.new if f.rule == "FTL018"] == [], \
        [f.message for f in result.new if f.rule == "FTL018"]


def test_ftl007_real_span_points_are_clean():
    """Every literal trace_batch_event location in the package follows
    the Role.point grammar and every f-string location has a static
    CamelCase head — the commit-debug waterfall keeps bucketing."""
    result = _scan([os.path.join(REPO, "foundationdb_tpu")])
    assert [f for f in result.new if f.rule == "FTL007"] == [], \
        [f.message for f in result.new if f.rule == "FTL007"]


def test_cli_stats_shape(tmp_path):
    """--stats prints machine-parseable JSON to STDOUT (findings move
    to stderr): per-rule finding/suppression counts for every shipped
    rule and the scan/link/total phase timings."""
    pkg = _write_pkg(tmp_path, {
        "m.py": """\
            import time

            def f():
                return time.time()
            """})
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--baseline", "none", "--stats",
         str(pkg)],
        capture_output=True, text=True)
    assert out.returncode == 1, out.stdout + out.stderr
    stats = json.loads(out.stdout)
    assert set(stats) == {"version", "files_scanned", "counts",
                          "rules", "phases"}
    assert set(stats["counts"]) == {"new", "baselined", "suppressed"}
    assert set(stats["rules"]) == \
        {f"FTL{i:03d}" for i in range(1, N_RULES + 1)}
    assert stats["rules"]["FTL001"]["findings"] == 1
    assert all(set(v) == {"findings", "suppressed"}
               for v in stats["rules"].values())
    assert set(stats["phases"]) == {"scan", "link", "total"}
    assert all(isinstance(v, float) and v >= 0
               for v in stats["phases"].values())
    assert "FTL001" in out.stderr      # findings went to stderr


def test_scan_time_budget(tmp_path):
    """PERF budget as tier-1: the full-package scan stays under 5s
    (phase-timed inside the process, startup excluded) and a warm
    --changed pass under 1.5s wall — the edit-lint loop stays
    interactive as rules accumulate."""
    import time
    cache = str(tmp_path / "cache.json")
    target = os.path.join(REPO, "foundationdb_tpu")
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--summary-cache", cache, "--stats",
         target],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    total = json.loads(out.stdout)["phases"]["total"]
    assert total <= 5.0, f"full scan {total:.2f}s blew the 5s budget"

    t0 = time.monotonic()
    out = subprocess.run(
        [sys.executable, FLOWLINT, "--summary-cache", cache,
         "--changed", "HEAD", target],
        capture_output=True, text=True)
    elapsed = time.monotonic() - t0
    assert out.returncode == 0, out.stdout + out.stderr
    assert elapsed <= 1.5, (
        f"warm --changed took {elapsed:.2f}s against the 1.5s budget")
