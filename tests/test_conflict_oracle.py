"""Oracle ConflictSet vs brute-force model (ConflictRange.actor.cpp style).

The brute model tracks last-write versions per concrete key over a small
finite key domain. Because all range endpoints are drawn from that domain,
the piecewise version function is exactly determined by its values on the
domain points, so the model is an exact executable spec."""

import pytest

from foundationdb_tpu.conflict.oracle import (OracleConflictSet,
                                              VersionHistory,
                                              combine_write_ranges)
from foundationdb_tpu.core import DeterministicRandom
from foundationdb_tpu.txn import (CommitResult, CommitTransactionRef,
                                  KeyRange)


def make_domain():
    """Small ordered key universe; all endpoints come from here."""
    alphabet = [b"a", b"b", b"c", b"d"]
    keys = [b""]
    for a in alphabet:
        keys.append(a)
        for b2 in alphabet:
            keys.append(a + b2)
    keys.append(b"\xff")
    return sorted(set(keys))


class BruteModel:
    """Exact spec: per-domain-point versions + reference batch semantics."""

    def __init__(self, domain):
        self.domain = domain
        self.v = {k: 0 for k in domain}
        self.oldest = 0

    def query_max(self, b, e):
        vs = [self.v[k] for k in self.domain if b <= k < e]
        return max(vs) if vs else -1 << 62

    def resolve(self, txns, now, new_oldest=None):
        n = len(txns)
        too_old = [tr.read_snapshot < self.oldest and bool(tr.read_conflict_ranges)
                   for tr in txns]
        conflict = [False] * n
        for t, tr in enumerate(txns):
            if too_old[t]:
                continue
            for r in tr.read_conflict_ranges:
                if self.query_max(r.begin, r.end) > tr.read_snapshot:
                    conflict[t] = True
                    break
        surviving = []
        for t, tr in enumerate(txns):
            if conflict[t]:
                continue
            c = too_old[t]
            if not c:
                for r in tr.read_conflict_ranges:
                    if any(r.begin < we and wb < r.end for wb, we in surviving):
                        c = True
                        break
            conflict[t] = c
            if not c:
                surviving += [(w.begin, w.end) for w in tr.write_conflict_ranges
                              if w.begin < w.end]
        for wb, we in surviving:
            for k in self.domain:
                if wb <= k < we:
                    self.v[k] = now
        if new_oldest is not None and new_oldest > self.oldest:
            self.oldest = new_oldest
        return [CommitResult.TOO_OLD if too_old[t]
                else CommitResult.CONFLICT if conflict[t]
                else CommitResult.COMMITTED for t in range(n)]


def random_range(rng, domain):
    i = rng.random_int(0, len(domain) - 1)
    j = rng.random_int(i + 1, len(domain))
    return KeyRange(domain[i], domain[j])


def random_txn(rng, domain, now, window):
    snap = now - rng.random_int(0, window)
    tr = CommitTransactionRef(read_snapshot=max(snap, 0))
    for _ in range(rng.random_int(0, 4)):
        tr.read_conflict_ranges.append(random_range(rng, domain))
    for _ in range(rng.random_int(0, 3)):
        tr.write_conflict_ranges.append(random_range(rng, domain))
    return tr


@pytest.mark.parametrize("seed", [1, 2, 3, 4, 5])
def test_oracle_matches_brute_model(seed):
    rng = DeterministicRandom(seed)
    domain = make_domain()
    oracle = OracleConflictSet(0)
    model = BruteModel(domain)
    now = 0
    for _ in range(60):
        now += rng.random_int(1, 2_000_000)
        batch = [random_txn(rng, domain, now, 4_000_000)
                 for _ in range(rng.random_int(1, 12))]
        new_oldest = now - 5_000_000 if rng.coinflip() else None
        got = oracle.resolve(batch, now, new_oldest)
        want = model.resolve(batch, now, new_oldest)
        assert got == want, f"divergence at now={now}: {got} vs {want}"


def test_version_history_basics():
    h = VersionHistory(0)
    h.insert(b"b", b"d", 10)
    assert h.query_max(b"a", b"b") == 0
    assert h.query_max(b"a", b"b\x00") == 10
    assert h.query_max(b"b", b"c") == 10
    assert h.query_max(b"d", b"e") == 0
    h.insert(b"c", b"e", 20)
    assert h.query_max(b"b", b"c") == 10
    assert h.query_max(b"c", b"d") == 20
    assert h.query_max(b"d", b"z") == 20
    assert h.query_max(b"e", b"z") == 0
    # overwrite interior fully
    h.insert(b"a", b"z", 30)
    assert h.query_max(b"", b"\xff") == 30
    assert h.query_max(b"", b"a") == 0
    assert h.query_max(b"z", b"\xff") == 0


def test_version_history_point_writes():
    h = VersionHistory(0)
    h.insert(b"k", b"k\x00", 5)
    assert h.query_max(b"k", b"k\x00") == 5
    assert h.query_max(b"j", b"k") == 0
    assert h.query_max(b"k\x00", b"l") == 0


def test_remove_before_is_decision_invariant():
    rng = DeterministicRandom(99)
    domain = make_domain()
    a, b = OracleConflictSet(0), OracleConflictSet(0)
    now = 0
    for _ in range(40):
        now += rng.random_int(1, 1_000_000)
        batch = [random_txn(rng, domain, now, 3_000_000)
                 for _ in range(rng.random_int(1, 8))]
        # a: GC aggressively every batch; b: advance floor but skip compaction
        ra = a.resolve(batch, now, now - 3_000_000)
        b.oldest_version = max(b.oldest_version, now - 3_000_000)
        rb = b.resolve(batch, now, None)
        assert ra == rb
    assert a.history.segment_count() <= b.history.segment_count()


def test_too_old_requires_read_ranges():
    cs = OracleConflictSet(100)
    w = CommitTransactionRef(read_snapshot=0,
                             write_conflict_ranges=[KeyRange(b"a", b"b")])
    assert cs.resolve([w], 200) == [CommitResult.COMMITTED]
    r = CommitTransactionRef(read_snapshot=0,
                             read_conflict_ranges=[KeyRange(b"a", b"b")])
    assert cs.resolve([r], 300) == [CommitResult.TOO_OLD]


def test_intra_batch_order_dependence():
    """An aborted earlier writer does NOT block a later reader."""
    cs = OracleConflictSet(0)
    # seed history: write x at v10
    seed = CommitTransactionRef(write_conflict_ranges=[KeyRange(b"x", b"y")],
                                read_snapshot=0)
    assert cs.resolve([seed], 10) == [CommitResult.COMMITTED]
    # t0 reads x at snapshot 5 -> conflicts with v10 write; also writes k.
    t0 = CommitTransactionRef(read_snapshot=5,
                              read_conflict_ranges=[KeyRange(b"x", b"y")],
                              write_conflict_ranges=[KeyRange(b"k", b"l")])
    # t1 reads k at snapshot 15: t0 aborted, so no intra-batch conflict.
    t1 = CommitTransactionRef(read_snapshot=15,
                              read_conflict_ranges=[KeyRange(b"k", b"l")])
    # t2 writes m and survives; t3 reads m -> intra-batch conflict.
    t2 = CommitTransactionRef(read_snapshot=15,
                              write_conflict_ranges=[KeyRange(b"m", b"n")])
    t3 = CommitTransactionRef(read_snapshot=15,
                              read_conflict_ranges=[KeyRange(b"m", b"n")])
    got = cs.resolve([t0, t1, t2, t3], 20)
    assert got == [CommitResult.CONFLICT, CommitResult.COMMITTED,
                   CommitResult.COMMITTED, CommitResult.CONFLICT]


def test_exact_snapshot_boundary():
    """A write AT the snapshot version does not conflict (strict >)."""
    cs = OracleConflictSet(0)
    w = CommitTransactionRef(write_conflict_ranges=[KeyRange(b"a", b"b")])
    cs.resolve([w], 10)
    r_at = CommitTransactionRef(read_snapshot=10,
                                read_conflict_ranges=[KeyRange(b"a", b"b")])
    r_below = CommitTransactionRef(read_snapshot=9,
                                   read_conflict_ranges=[KeyRange(b"a", b"b")])
    assert cs.resolve([r_at], 11) == [CommitResult.COMMITTED]
    assert cs.resolve([r_below], 12) == [CommitResult.CONFLICT]


def test_combine_write_ranges():
    got = combine_write_ranges([(b"c", b"e"), (b"a", b"b"), (b"b", b"c"),
                                (b"d", b"f"), (b"x", b"x")])
    assert got == [(b"a", b"f")]


@pytest.mark.parametrize("seed", [5, 6, 7, 8])
def test_insert_many_equals_sequential_inserts(seed):
    """insert_many (the one-pass batch merge the supervisor's mirror and
    the oracle's step 4 use) is bit-identical to per-range insert() for
    combine_write_ranges output, on random histories."""
    rng = DeterministicRandom(seed)
    for _round in range(20):
        seq = VersionHistory(0)
        # Random pre-existing history via sequential inserts.
        for _ in range(rng.random_int(0, 30)):
            b = b"%03d" % rng.random_int(0, 60)
            e = b"%03d" % rng.random_int(0, 60)
            if b < e:
                seq.insert(b, e, rng.random_int(1, 100))
        batch = VersionHistory(0)
        batch.keys, batch.vals = list(seq.keys), list(seq.vals)
        ranges = combine_write_ranges([
            (b"%03d" % rng.random_int(0, 60), b"%03d" % rng.random_int(0, 60))
            for _ in range(rng.random_int(0, 12))])
        v = rng.random_int(101, 200)
        for b, e in ranges:
            seq.insert(b, e, v)
        batch.insert_many(ranges, v)
        assert batch.keys == seq.keys and batch.vals == seq.vals


def test_insert_many_touching_boundaries():
    """Edge cases: range begin/end exactly on existing boundaries, and a
    range whose end coincides with a later range's vicinity."""
    seq = VersionHistory(0)
    seq.insert(b"b", b"d", 5)
    seq.insert(b"f", b"h", 7)
    batch = VersionHistory(0)
    batch.keys, batch.vals = list(seq.keys), list(seq.vals)
    ranges = [(b"a", b"b"), (b"d", b"f"), (b"h", b"j")]
    for b, e in ranges:
        seq.insert(b, e, 9)
    batch.insert_many(ranges, 9)
    assert batch.keys == seq.keys and batch.vals == seq.vals
