"""Restarting tests: save disk state, restart EVERY process from disk in
fresh interpreters, verify invariants (VERDICT round-3 item 9).

Reference: tests/restarting/ (two-phase specs: the first half runs a
workload then SaveAndKill.actor.cpp persists the cluster layout and kills
every process; the second half — possibly a different binary — restarts
from the same data directories and checks the workload's invariants).
Here each fdbserver is a real OS process (server/fdbserver.py); phase 2
re-execs every one of them from its datadir, so recovery runs purely from
durable state in brand-new interpreters — the upgrade-test scaffold.
"""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASE_PORT = 47500
COORDS = f"127.0.0.1:{BASE_PORT}"
CONFIG = json.dumps({"n_storage": 2, "min_workers": 3})

NAMES = {"coord0": (BASE_PORT, "stateless"),
         "stateless1": (BASE_PORT + 1, "stateless"),
         "storage0": (BASE_PORT + 2, "storage"),
         "storage1": (BASE_PORT + 3, "storage")}


def _spawn(base, name, generation):
    port, pclass = NAMES[name]
    cmd = [sys.executable, "-m", "foundationdb_tpu.server.fdbserver",
           "--port", str(port), "--coordinators", COORDS,
           "--datadir", os.path.join(base, name), "--class", pclass,
           "--config", CONFIG, "--name", f"{name}.g{generation}"]
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.Popen(
        cmd, cwd=REPO, env=env,
        stdout=open(os.path.join(base, f"{name}.g{generation}.out"), "wb"),
        stderr=subprocess.STDOUT)


def _client():
    from foundationdb_tpu.client.database import open_cluster
    return open_cluster(COORDS)


def _teardown_client():
    from foundationdb_tpu.core.scheduler import set_event_loop
    from foundationdb_tpu.rpc.network import get_network, set_network
    try:
        get_network().close()
    except Exception:
        pass
    set_network(None)
    set_event_loop(None)


async def _commit_kv(db, k, v):
    t = db.create_transaction()
    while True:
        try:
            t.set(k, v)
            return await t.commit()
        except Exception as e:
            await t.on_error(e)


async def _read_key(db, k):
    t = db.create_transaction()
    while True:
        try:
            return await t.get(k)
        except Exception as e:
            await t.on_error(e)


def test_whole_cluster_restart_from_disk(tmp_path):
    base = str(tmp_path)
    N = 12
    procs = {n: _spawn(base, n, 1) for n in NAMES}
    try:
        time.sleep(2.5)
        dead = {n: p.poll() for n, p in procs.items()
                if p.poll() is not None}
        assert not dead, f"phase-1 processes died at boot: {dead}"
        loop, db = _client()

        async def phase1():
            # A cycle ring (the classic restarting-test invariant) plus
            # plain data.
            for i in range(N):
                await _commit_kv(db, b"ring/%03d" % i,
                                 b"ring/%03d" % ((i + 1) % N))
            for i in range(20):
                await _commit_kv(db, b"data/%03d" % i, b"v%03d" % i)
            return True

        assert loop.run_until(loop.spawn(phase1()), timeout=90)
        _teardown_client()

        # SaveAndKill: stop EVERY process.  SIGKILL — recovery must work
        # from exactly what was durable, like a power failure.
        for p in procs.values():
            p.kill()
        for p in procs.values():
            p.wait()
        time.sleep(1.0)

        # Phase 2: fresh interpreters over the same data directories.
        procs = {n: _spawn(base, n, 2) for n in NAMES}
        time.sleep(2.5)
        dead = {n: p.poll() for n, p in procs.items()
                if p.poll() is not None}
        assert not dead, f"phase-2 processes died at boot: {dead}"
        loop, db = _client()

        async def phase2():
            # Cycle invariant holds across the restart.
            seen = set()
            k = b"ring/%03d" % 0
            for _ in range(N):
                assert k not in seen
                seen.add(k)
                k = await _read_key(db, k)
                assert k is not None, "ring broken"
            assert k == b"ring/%03d" % 0 and len(seen) == N
            for i in range(20):
                assert await _read_key(db, b"data/%03d" % i) == b"v%03d" % i
            # The restarted cluster accepts new commits.
            await _commit_kv(db, b"post-restart", b"alive")
            assert await _read_key(db, b"post-restart") == b"alive"
            return True

        assert loop.run_until(loop.spawn(phase2()), timeout=120)
        _teardown_client()
    finally:
        for p in procs.values():
            p.kill()
        for p in procs.values():
            p.wait()
