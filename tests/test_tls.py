"""Mutual-TLS transport (reference flow/TLSConfig + the TLS transport):
a TLS cluster serves TLS clients, and a plaintext client cannot join."""

import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASE_PORT = 47860
COORDS = f"127.0.0.1:{BASE_PORT}"
CONFIG = json.dumps({"n_storage": 2, "min_workers": 3})


def _gen_cert(base):
    cert = os.path.join(base, "fdb.pem")
    key = os.path.join(base, "fdb.key")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", cert, "-days", "2",
         "-subj", "/CN=fdb-test"],
        check=True, capture_output=True)
    return cert, key


def _spawn(base, name, port, pclass, cert, key):
    cmd = [sys.executable, "-m", "foundationdb_tpu.server.fdbserver",
           "--port", str(port), "--coordinators", COORDS,
           "--datadir", os.path.join(base, name), "--class", pclass,
           "--config", CONFIG, "--name", name,
           "--tls-cert", cert, "--tls-key", key, "--tls-ca", cert]
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
    return subprocess.Popen(
        cmd, cwd=REPO, env=env,
        stdout=open(os.path.join(base, f"{name}.out"), "wb"),
        stderr=subprocess.STDOUT)


def _teardown_client():
    from foundationdb_tpu.core.scheduler import set_event_loop
    from foundationdb_tpu.rpc.network import get_network, set_network
    try:
        get_network().close()
    except Exception:
        pass
    set_network(None)
    set_event_loop(None)


def test_tls_cluster_serves_tls_clients_and_rejects_plaintext(tmp_path):
    base = str(tmp_path)
    cert, key = _gen_cert(base)
    names = {"c0": (BASE_PORT, "stateless"),
             "w1": (BASE_PORT + 1, "stateless"),
             "s0": (BASE_PORT + 2, "storage"),
             "s1": (BASE_PORT + 3, "storage")}
    procs = {n: _spawn(base, n, p, c, cert, key)
             for n, (p, c) in names.items()}
    try:
        time.sleep(3.0)
        dead = {n: p.poll() for n, p in procs.items()
                if p.poll() is not None}
        assert not dead, f"processes died at boot: {dead}"

        from foundationdb_tpu.client.database import open_cluster
        tls = {"cert": cert, "key": key, "ca": cert}
        loop, db = open_cluster(COORDS, tls=tls)

        async def go():
            t = db.create_transaction()
            while True:
                try:
                    t.set(b"tls/k", b"tls/v")
                    await t.commit()
                    break
                except Exception as e:  # noqa: BLE001
                    await t.on_error(e)
            t2 = db.create_transaction()
            while True:
                try:
                    return await t2.get(b"tls/k")
                except Exception as e:  # noqa: BLE001
                    await t2.on_error(e)

        assert loop.run_until(loop.spawn(go()), timeout=90) == b"tls/v"
        _teardown_client()

        # A PLAINTEXT client cannot join a TLS cluster: its GRV attempts
        # hit connection-level failures, never data.
        loop2, db2 = open_cluster(COORDS)

        async def plain():
            from foundationdb_tpu.core.error import FdbError
            t = db2.create_transaction()
            try:
                from foundationdb_tpu.core.futures import wait_any
                from foundationdb_tpu.core.scheduler import delay
                f = loop2.spawn(t.get(b"tls/k"), "plainGet")
                idx, _ = await wait_any([f, delay(10.0)])
                if idx == 1:
                    return True          # wedged on handshake: rejected
                try:
                    f.get()
                    return False         # plaintext read SUCCEEDED: bad
                except FdbError:
                    return True
            except FdbError:
                return True

        assert loop2.run_until(loop2.spawn(plain()), timeout=60)
        _teardown_client()
    finally:
        for p in procs.values():
            p.kill()
        for p in procs.values():
            p.wait()
