"""Replication policy DSL (reference flow/ReplicationPolicy.h):
PolicyOne / PolicyAcross / PolicyAnd select and validate teams over
locality attributes; three_data_hall composes them."""

from foundationdb_tpu.server.policy import (PolicyAcross, PolicyAnd,
                                            PolicyOne, policy_from_config,
                                            three_data_hall)


def c(i, **loc):
    return (i, loc)


def test_across_selects_distinct_zones():
    p = PolicyAcross(2, "zoneid")
    cands = [c(0, zoneid="a"), c(1, zoneid="a"), c(2, zoneid="b")]
    team = p.select(cands)
    assert team is not None and len(team) == 2
    assert {t[1]["zoneid"] for t in team} == {"a", "b"}
    assert p.validate(team)
    assert not p.validate([c(0, zoneid="a"), c(1, zoneid="a")])
    # Impossible: only one zone available.
    assert p.select([c(0, zoneid="a"), c(1, zoneid="a")]) is None


def test_missing_locality_counts_unique():
    p = PolicyAcross(2, "zoneid")
    team = p.select([c(0), c(1)])
    assert team is not None and len(team) == 2


def test_three_data_hall():
    p = three_data_hall()
    assert p.n_required() == 6
    cands = [c(f"{h}{z}{i}", data_hall=h, zoneid=f"{h}{z}")
             for h in "ABC" for z in "12" for i in range(2)]
    team = p.select(cands)
    assert team is not None and len(team) == 6
    assert p.validate(team)
    halls = {m[1]["data_hall"] for m in team}
    assert halls == {"A", "B", "C"}
    # Losing a whole hall invalidates.
    assert not p.validate([m for m in team if m[1]["data_hall"] != "A"])


def test_policy_and():
    p = PolicyAnd(PolicyAcross(2, "zoneid"), PolicyAcross(2, "dcid"))
    cands = [c(0, zoneid="z1", dcid="d1"), c(1, zoneid="z2", dcid="d1"),
             c(2, zoneid="z3", dcid="d2")]
    team = p.select(cands)
    assert team is not None and p.validate(team)
    dcs = {m[1]["dcid"] for m in team}
    assert len(dcs) == 2


def test_policy_from_config():
    assert policy_from_config(1).name() == "One"
    p = policy_from_config(3)
    assert p.n_required() == 3
    assert "Across(3,zoneid" in p.name()
