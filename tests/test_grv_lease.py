"""Client read-version leases + client-side GRV batching (ISSUE 14,
client/database.py): knobs-off parity (one GRV per transaction, exactly
as before), lease hits/expiry/commit-floor causality, batching fan-out
through one transaction_count=N request, and the plain-request-only
gates (tags/tenants/debug ids always reach the proxy)."""

import pytest

from foundationdb_tpu.core.knobs import client_knobs
from foundationdb_tpu.server.cluster import SimCluster


@pytest.fixture()
def cluster():
    c = SimCluster(n_resolvers=1, n_storage=2, n_tlogs=1)
    yield c
    from foundationdb_tpu.core import set_event_loop
    from foundationdb_tpu.rpc.sim import set_simulator
    set_simulator(None)
    set_event_loop(None)


@pytest.fixture()
def grv_knobs():
    k = client_knobs()
    saved = (k.GRV_BATCH_ENABLED, k.GRV_LEASE_S)
    yield k
    k.GRV_BATCH_ENABLED, k.GRV_LEASE_S = saved


def run(cluster, coro, timeout=30):
    return cluster.run_until(cluster.loop.spawn(coro), timeout=timeout)


def _grv_requests(cluster) -> int:
    return cluster.grv_proxies[0].metrics.counter("TxnStarted").value


async def _rw_txn(db, key: bytes):
    t = db.create_transaction()
    await t.get(key)
    t.set(key, b"x")
    return await t.commit()


def test_default_posture_one_grv_per_txn(cluster, grv_knobs):
    """Knobs off: every reading transaction issues its own GRV — the
    pre-ISSUE-14 client, bit for bit."""
    db = cluster.database()

    async def go():
        for i in range(5):
            await _rw_txn(db, b"k%d" % i)
    run(cluster, go())
    assert db.grv_stats["leased"] == 0
    assert db.grv_stats["batched"] == 0
    assert db.grv_stats["requests"] == 5
    assert _grv_requests(cluster) == 5


def test_lease_serves_repeat_grvs(cluster, grv_knobs):
    grv_knobs.GRV_LEASE_S = 5.0
    db = cluster.database()

    async def go():
        for i in range(6):
            await _rw_txn(db, b"k%d" % i)
    run(cluster, go())
    # First txn pays the GRV; the rest ride the lease.
    assert db.grv_stats["requests"] == 1
    assert db.grv_stats["leased"] == 5
    assert _grv_requests(cluster) == 1


def test_lease_floor_follows_own_commits(cluster, grv_knobs):
    """Read-your-own-writes per client: a commit bumps the lease floor,
    so the NEXT leased transaction reads at >= the commit version."""
    grv_knobs.GRV_LEASE_S = 5.0
    db = cluster.database()

    async def go():
        t = db.create_transaction()
        await t.get(b"k")
        t.set(b"k", b"v1")
        v_commit = await t.commit()
        t2 = db.create_transaction()
        assert await t2.get(b"k") == b"v1"   # leased, but not stale
        rv = await t2._ensure_read_version()
        assert rv >= v_commit
    run(cluster, go())
    assert db.grv_stats["leased"] >= 1


def test_lease_expires(cluster, grv_knobs):
    grv_knobs.GRV_LEASE_S = 0.5
    db = cluster.database()

    async def go():
        from foundationdb_tpu.core.scheduler import delay
        await _rw_txn(db, b"a")
        await delay(1.0)          # virtual time blows past the lease
        await _rw_txn(db, b"b")
    run(cluster, go())
    assert db.grv_stats["requests"] == 2


def test_lease_expiry_never_slides_under_traffic(cluster, grv_knobs):
    """Continuous lease hits must NOT refresh the expiry: the staleness
    bound is measured from a real proxy round trip, so a hot loop still
    pays one GRV per lease window (regression: re-noting the cached
    reply at consumption slid the lease forever -> 1 request total)."""
    grv_knobs.GRV_LEASE_S = 0.5
    db = cluster.database()

    async def go():
        from foundationdb_tpu.core.scheduler import delay
        for _ in range(10):       # 2.0s of virtual time, 0.2s apart
            t = db.create_transaction()
            await t.get(b"hot")   # read-only: no commit-floor bumps
            await delay(0.2)
    run(cluster, go())
    # 2.0s / 0.5s lease windows => real acquisitions keep happening
    # (background refreshes in the back half of each window), never 1.
    assert 3 <= db.grv_stats["requests"] <= 9, db.grv_stats
    # And the refreshes were BACKGROUND renewals, not blocking misses.
    assert db.grv_stats["refreshes"] >= 2, db.grv_stats


def test_late_grv_reply_cannot_arm_lease_below_own_commit(cluster,
                                                          grv_knobs):
    """A GRV reply resolved BEFORE this client's commit but delivered
    after it (lease empty at delivery) must not arm the lease below the
    commit — the next leased transaction would miss our own write."""
    grv_knobs.GRV_LEASE_S = 5.0
    db = cluster.database()

    async def go():
        t = db.create_transaction()
        await t.get(b"k")
        t.set(b"k", b"v1")
        v = await t.commit()
        db._grv_lease = None   # model: lease expired, a reply in flight
        from foundationdb_tpu.server.interfaces import GetReadVersionReply
        db._note_grv_reply(GetReadVersionReply(version=v - 10))
        t2 = db.create_transaction()
        assert await t2.get(b"k") == b"v1"
        assert (await t2._ensure_read_version()) >= v
    run(cluster, go())


def test_batching_folds_concurrent_grvs(cluster, grv_knobs):
    grv_knobs.GRV_BATCH_ENABLED = True
    db = cluster.database()

    async def go():
        from foundationdb_tpu.core.futures import wait_all
        from foundationdb_tpu.core.scheduler import spawn
        txns = [db.create_transaction() for _ in range(6)]
        versions = await wait_all(
            [spawn(t._ensure_read_version()) for t in txns])
        assert len(set(versions)) == 1   # one reply fanned out
    run(cluster, go())
    assert db.grv_stats["requests"] == 1
    assert db.grv_stats["batched"] == 5   # joiners beyond the opener
    gp = cluster.grv_proxies[0].metrics
    # The proxy charged the true transaction count...
    assert gp.counter("TxnStarted").value == 6
    # ...from one batched client request.
    assert gp.counter("ClientBatchedGrvRequests").value == 1


def test_non_plain_requests_bypass_lease_and_batch(cluster, grv_knobs):
    grv_knobs.GRV_LEASE_S = 5.0
    grv_knobs.GRV_BATCH_ENABLED = True
    db = cluster.database()

    async def go():
        await _rw_txn(db, b"seed")      # warms the lease
        t = db.create_transaction()
        t.tag = "hot"                   # tagged: proxy-side throttling
        await t.get(b"k")
        t2 = db.create_transaction()
        t2.debug_id = "dbg-1"           # traced: must hit the proxy
        await t2.get(b"k")
    run(cluster, go())
    # seed + tagged + traced each paid a real request; only reads after
    # the seed could lease (none here — both others bypass).
    assert db.grv_stats["requests"] == 3
    assert db.grv_stats["leased"] == 0
