"""fdbmonitor (reference fdbmonitor/fdbmonitor.cpp): supervises real
fdbserver OS processes — crash restart with backoff, conf reload adding
and removing sections, clean teardown."""

import os
import signal
import time

from foundationdb_tpu.tools.fdbmonitor import FdbMonitor


def _write_conf(path, ports, datadir_base, extra=""):
    sections = "\n".join(
        f"[fdbserver.{p}]\ndatadir = {datadir_base}/{p}\n" for p in ports)
    with open(path, "w") as f:
        f.write(f"""
[general]
restart-delay = 0.2
restart-backoff-max = 2

[fdbserver]
class = stateless
coordinators = 127.0.0.1:{ports[0]}
{extra}
{sections}
""")


def test_monitor_restarts_crashed_child_and_reloads_conf(tmp_path):
    conf = str(tmp_path / "foundationdb.conf")
    _write_conf(conf, [47820, 47821], str(tmp_path))
    logs = []
    mon = FdbMonitor(conf, log=logs.append)
    mon.load_conf()
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            mon.poll_once()
            if all(c.proc is not None and c.proc.poll() is None
                   for c in mon.children.values()):
                break
            time.sleep(0.1)
        assert set(mon.children) == {47820, 47821}
        assert all(c.proc is not None for c in mon.children.values())

        # Crash one child: the monitor restarts it (with backoff).
        victim = mon.children[47821]
        pid1 = victim.proc.pid
        os.kill(pid1, signal.SIGKILL)
        deadline = time.time() + 30
        while time.time() < deadline:
            mon.poll_once()
            p = mon.children[47821].proc
            if p is not None and p.poll() is None and p.pid != pid1:
                break
            time.sleep(0.1)
        p = mon.children[47821].proc
        assert p is not None and p.pid != pid1 and p.poll() is None
        assert mon.children[47821].restarts == 1

        # Conf reload: drop one section, add another.
        _write_conf(conf, [47820, 47822], str(tmp_path))
        deadline = time.time() + 30
        while time.time() < deadline:
            mon.poll_once()
            if set(mon.children) == {47820, 47822} and \
                    mon.children[47822].proc is not None:
                break
            time.sleep(0.1)
        assert set(mon.children) == {47820, 47822}
        assert mon.children[47822].proc.poll() is None
    finally:
        for c in mon.children.values():
            mon._stop_child(c)
