"""Sampling profiler + slow-task detection (reference flow/Profiler.actor.cpp
+ Net2 slow-task TraceEvents)."""

import time

from foundationdb_tpu.core.profiler import (SamplingProfiler,
                                            install_slow_task_detection)
from foundationdb_tpu.core.scheduler import EventLoop, set_event_loop
from foundationdb_tpu.core.trace import get_tracer


def teardown_function(_fn):
    set_event_loop(None)


def test_slow_task_emits_trace_event():
    loop = EventLoop(sim=False)
    set_event_loop(loop)
    install_slow_task_detection(loop, threshold_s=0.05)
    before = len(get_tracer().find("SlowTask"))

    async def hog():
        time.sleep(0.12)        # deliberately blocks the reactor
        return True

    assert loop.run_until(loop.spawn(hog(), "hog"), timeout=10)
    events = get_tracer().find("SlowTask")
    assert len(events) > before
    assert events[-1]["DurationMs"] >= 100


def test_sampling_profiler_catches_hot_function():
    prof = SamplingProfiler(interval_s=0.002)
    prof.start()

    def busy_function():
        x = 0
        deadline = time.monotonic() + 0.3
        while time.monotonic() < deadline:
            x += 1
        return x

    busy_function()
    prof.stop()
    assert prof.total > 20
    report = prof.report()
    assert any("busy_function" in stack for _frac, stack in report), report
