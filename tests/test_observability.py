"""Latency histograms + counters with periodic emission (reference
flow/Histogram.h:59, fdbrpc/Stats.h:70-183 traceCounters) and their
surfacing in the status JSON's roles section."""

import json

import pytest

from foundationdb_tpu.core.histogram import CounterCollection, Histogram
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration

from test_recovery import commit_kv, read_key, teardown  # noqa: F401


def test_histogram_percentiles():
    h = Histogram("t", "x")
    for us in [1, 10, 100, 1000, 10000]:
        for _ in range(20):
            h.record(us * 1e-6)
    assert h.count == 100
    # p50 falls in the 100us bucket's range (log-scale upper bounds).
    assert 64e-6 <= h.percentile(0.50) <= 256e-6
    assert h.percentile(0.99) >= 8e-3
    s = h.to_status()
    assert s["count"] == 100 and s["min"] > 0 and s["max"] >= 1e-2

    c = CounterCollection("G", "r1")
    c.counter("ops").add(5)
    c.counter("ops").add(3)
    assert c.counter("ops").value == 8
    assert c.counter("ops").rate_and_roll(2.0) == 4.0
    assert c.counter("ops").rate_and_roll(2.0) == 0.0


def test_commit_span_correlates_proxy_resolver_tlog(teardown):  # noqa: F811,E501
    """ISSUE 2 satellite: the commit proxy mints one span per batch and
    stamps it onto the resolution and TLog-commit hops, so CommitDebug
    trace events form a cross-process timeline keyed by the span — plus
    a client debug id correlates to the batch span."""
    from foundationdb_tpu.core.trace import get_tracer
    c = SimFdbCluster(config=DatabaseConfiguration(n_resolvers=2),
                      n_workers=5, n_storage_workers=2)
    db = c.database()

    async def go():
        t = db.create_transaction()
        t.debug_id = "dbg-42"
        from foundationdb_tpu.core import FdbError
        while True:
            try:
                t.set(b"span-key", b"v")
                await t.commit()
                break
            except FdbError as e:
                await t.on_error(e)
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=120)
    events = get_tracer().find("CommitDebug")
    assert events, "no CommitDebug events traced"
    # The client's debug id was correlated to SOME batch span at the
    # proxy...
    linked = [e for e in events if e.get("DebugID") == "dbg-42"]
    assert linked, events[-5:]
    span = linked[-1]["Location"].split(":", 1)[1]
    # ...and that same span shows up at batch start, every resolver it
    # fanned out to, and the TLog append — the full commit pipeline.
    locs = {e["Location"] for e in events if e.get("DebugID") == span}
    assert any(loc == "CommitProxy.batchStart" for loc in locs), locs
    assert any(loc.startswith("Resolver.") for loc in locs), locs
    assert any(loc.startswith("TLog.") for loc in locs), locs


def test_tcp_envelope_carries_span():
    """The serde envelope + TCP frame carry a span context end-to-end,
    and the server installs it as the ambient span while the handler
    runs (stamped onto TraceEvents)."""
    from foundationdb_tpu.core.trace import (TraceEvent, get_current_span,
                                             get_tracer, set_current_span)
    from foundationdb_tpu.rpc.serde import decode_envelope, encode_envelope
    from foundationdb_tpu.rpc.transport import TcpTransport

    blob = encode_envelope({"op": "ping"}, span="span-abc")
    value, span = decode_envelope(blob)
    assert value == {"op": "ping"} and span == "span-abc"
    # Ambient span is attached when none is given explicitly.
    prev = set_current_span("ambient-1")
    try:
        _v, s2 = decode_envelope(encode_envelope(b"x"))
        assert s2 == "ambient-1"
    finally:
        set_current_span(prev)

    server = TcpTransport()
    client = TcpTransport()
    seen = {}

    def handler(payload: bytes) -> bytes:
        seen["span"] = get_current_span()
        TraceEvent("TcpSpanProbe").detail("Payload", len(payload)).log()
        return b"pong"

    server.register(0x77, handler)
    try:
        reply = client.request(server.address, 0x77, b"ping",
                               timeout=10.0, span="wire-span-9")
        assert reply == b"pong"
        assert seen["span"] == "wire-span-9"
        probes = get_tracer().find("TcpSpanProbe")
        assert probes and probes[-1]["SpanContext"] == "wire-span-9"
    finally:
        client.close()
        server.close()


def test_status_includes_role_latencies(teardown):  # noqa: F811
    c = SimFdbCluster(config=DatabaseConfiguration(),
                      n_workers=5, n_storage_workers=2)
    db = c.database()

    async def go():
        for i in range(10):
            await commit_kv(db, b"m%02d" % i, b"v")
            await read_key(db, b"m%02d" % i)
        status = await db.cluster.get_status()
        json.dumps(status)
        roles = status["cluster"]["roles"]
        cp = next(iter(roles["commit_proxies"].values()))
        assert cp["counters"]["TxnCommitted"] >= 10
        commit_lat = cp["latency_statistics"]["Commit"]
        assert commit_lat["count"] >= 1 and commit_lat["p50"] > 0
        grv = next(iter(roles["grv_proxies"].values()))
        assert grv["counters"]["TxnStarted"] >= 10
        res = next(iter(roles["resolvers"].values()))
        assert res["latency_statistics"]["Resolve"]["count"] >= 1
        ss = next(iter(roles["storage_servers"].values()))
        assert ss["latency_statistics"]["ReadLatency"]["count"] >= 1

    c.run_until(c.loop.spawn(go()), timeout=60)
