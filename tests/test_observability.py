"""Latency histograms + counters with periodic emission (reference
flow/Histogram.h:59, fdbrpc/Stats.h:70-183 traceCounters) and their
surfacing in the status JSON's roles section."""

import json

import pytest

from foundationdb_tpu.core.histogram import CounterCollection, Histogram
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration

from test_recovery import commit_kv, read_key, teardown  # noqa: F401


def test_histogram_percentiles():
    h = Histogram("t", "x")
    for us in [1, 10, 100, 1000, 10000]:
        for _ in range(20):
            h.record(us * 1e-6)
    assert h.count == 100
    # p50 falls in the 100us bucket's range (log-scale upper bounds).
    assert 64e-6 <= h.percentile(0.50) <= 256e-6
    assert h.percentile(0.99) >= 8e-3
    s = h.to_status()
    assert s["count"] == 100 and s["min"] > 0 and s["max"] >= 1e-2

    c = CounterCollection("G", "r1")
    c.counter("ops").add(5)
    c.counter("ops").add(3)
    assert c.counter("ops").value == 8
    assert c.counter("ops").rate_and_roll(2.0) == 4.0
    assert c.counter("ops").rate_and_roll(2.0) == 0.0


def test_status_includes_role_latencies(teardown):  # noqa: F811
    c = SimFdbCluster(config=DatabaseConfiguration(),
                      n_workers=5, n_storage_workers=2)
    db = c.database()

    async def go():
        for i in range(10):
            await commit_kv(db, b"m%02d" % i, b"v")
            await read_key(db, b"m%02d" % i)
        status = await db.cluster.get_status()
        json.dumps(status)
        roles = status["cluster"]["roles"]
        cp = next(iter(roles["commit_proxies"].values()))
        assert cp["counters"]["TxnCommitted"] >= 10
        commit_lat = cp["latency_statistics"]["Commit"]
        assert commit_lat["count"] >= 1 and commit_lat["p50"] > 0
        grv = next(iter(roles["grv_proxies"].values()))
        assert grv["counters"]["TxnStarted"] >= 10
        res = next(iter(roles["resolvers"].values()))
        assert res["latency_statistics"]["Resolve"]["count"] >= 1
        ss = next(iter(roles["storage_servers"].values()))
        assert ss["latency_statistics"]["ReadLatency"]["count"] >= 1

    c.run_until(c.loop.spawn(go()), timeout=60)
