"""Control-plane tests: ratekeeper admission control + status JSON.

Reference behaviors modeled: Ratekeeper.actor.cpp spring-damped rate
limiting consumed by GRV proxies; Status.actor.cpp clusterGetStatus
document shape."""

import json

import pytest

from foundationdb_tpu.core import FdbError
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration
from foundationdb_tpu.server.ratekeeper import Ratekeeper


@pytest.fixture()
def teardown():
    from foundationdb_tpu.core import (DeterministicRandom,
                                       set_deterministic_random)
    set_deterministic_random(DeterministicRandom(11))
    yield
    from foundationdb_tpu.core import set_event_loop
    from foundationdb_tpu.rpc.sim import set_simulator
    set_simulator(None)
    set_event_loop(None)


def test_ratekeeper_spring_model():
    rk = Ratekeeper("rk-test", {})
    from foundationdb_tpu.core.knobs import server_knobs
    target = server_knobs().STORAGE_LIMIT_BYTES
    # Healthy queues: unlimited.
    rk.worst_queue_bytes = 0
    rk._update_rate()
    assert rk.tps_limit == float("inf")
    assert rk.limit_reason == "workload"
    # Queue deep in the spring: limited below the observed release rate.
    rk._released._estimate = 1000.0   # smoothed 1000 tps observed
    rk.worst_queue_bytes = int(target)              # fully saturated
    rk._update_rate()
    assert rk.tps_limit < 1000
    assert rk.limit_reason == "storage_server_write_queue_size"
    # Mid-spring: limit between 0 and observed rate.
    rk.worst_queue_bytes = int(target * 0.9)
    rk._update_rate()
    assert 0 < rk.tps_limit <= 1001


def test_grv_rate_budget_enforced(teardown):
    """With the ratekeeper forced into limiting, GRV throughput is bounded
    near the budget instead of being released instantly."""
    c = SimFdbCluster(config=DatabaseConfiguration(),
                      n_workers=5, n_storage_workers=2)
    db = c.database()

    async def go():
        # Boot end-to-end first (proves the recruited ratekeeper + GRV rate
        # lease path doesn't break normal traffic)...
        t = db.create_transaction()
        while True:
            try:
                t.set(b"x", b"1"); await t.commit(); break
            except FdbError as e:
                await t.on_error(e)
        # ...then verify the token-bucket release math directly.
        from foundationdb_tpu.server.grv_proxy import GrvProxy
        from foundationdb_tpu.server.interfaces import (
            GetReadVersionRequest, TransactionPriority)
        gp = GrvProxy("gtest", None)
        gp._rate = 10.0
        # token accrual: 0.5s at 10 tps -> 5 tokens, capped at rate.
        gp.queues[TransactionPriority.DEFAULT] = [
            GetReadVersionRequest() for _ in range(20)]
        gp.queues[TransactionPriority.IMMEDIATE] = [
            GetReadVersionRequest(priority=TransactionPriority.IMMEDIATE)
            for _ in range(3)]
        budget = min(0.0 + gp._rate * 0.5, gp._rate)
        batch, charged, _bc = gp._drain(budget, float("inf"))
        # IMMEDIATE always released and NOT charged; default charged.
        assert len(batch) == 3 + 5
        assert charged == 5
        assert len(gp.queues[TransactionPriority.DEFAULT]) == 15
        # Fractional budget releases at most one txn and carries the debt.
        batch, charged, _bc = gp._drain(0.1, float("inf"))
        assert len(batch) == 1 and charged == 1
        assert (0.1 - charged) < 0      # caller keeps the deficit

    c.run_until(c.loop.spawn(go()), timeout=60)


def test_better_master_reelection(teardown):
    """Placement fitness + betterMasterExists (reference
    ClusterController.actor.cpp:2214, :3576; VERDICT r4 item 7): the
    master initially lands on a stateless-class worker; when a dedicated
    master-class worker joins, the CC re-recruits onto it and the cluster
    keeps committing."""
    from foundationdb_tpu.core.scheduler import delay

    c = SimFdbCluster(config=DatabaseConfiguration(),
                      n_workers=5, n_storage_workers=2)
    db = c.database()

    async def go():
        t = db.create_transaction()
        while True:
            try:
                t.set(b"bme", b"v1"); await t.commit(); break
            except FdbError as e:
                await t.on_error(e)
        cc = c.current_cc()
        epoch0 = cc.db_info.epoch
        old_master_proc = c.process_of(cc.db_info.master)
        assert old_master_proc.process_class == "stateless"
        # A dedicated master-class worker joins: strictly better fitness.
        c.add_worker(pclass="master", name="workerM")
        deadline = 40.0
        while deadline > 0:
            cc = c.current_cc()
            if cc is not None and cc.db_info.epoch > epoch0 and \
                    cc.db_info.recovery_state in ("accepting_commits",
                                                  "fully_recovered"):
                proc = c.process_of(cc.db_info.master)
                if proc is not None and proc.process_class == "master":
                    break
            await delay(0.5)
            deadline -= 0.5
        assert deadline > 0, "master never re-recruited onto better worker"
        # Stable: no epoch thrash once placement is optimal.
        epoch1 = c.current_cc().db_info.epoch
        await delay(5.0)
        assert c.current_cc().db_info.epoch == epoch1
        # And the database still works across the re-election.
        t = db.create_transaction()
        while True:
            try:
                t.set(b"bme2", b"v2"); await t.commit(); break
            except FdbError as e:
                await t.on_error(e)
        from test_recovery import read_key
        assert await read_key(db, b"bme") == b"v1"
        assert await read_key(db, b"bme2") == b"v2"

    c.run_until(c.loop.spawn(go()), timeout=120)


def test_status_json_document(teardown):
    c = SimFdbCluster(config=DatabaseConfiguration(n_resolvers=2),
                      n_workers=5, n_storage_workers=2)
    db = c.database()

    async def go():
        t = db.create_transaction()
        while True:
            try:
                t.set(b"statuskey", b"v"); await t.commit(); break
            except FdbError as e:
                await t.on_error(e)
        status = await db.cluster.get_status()
        json.dumps(status)   # must be JSON-serializable
        assert status["client"]["database_status"]["available"]
        cl = status["cluster"]
        assert cl["recovery_state"]["name"] == "accepting_commits"
        assert cl["generation"] >= 1
        assert cl["configuration"]["resolvers"] == 2
        assert cl["configuration"]["storage_servers"] == 2
        assert len(cl["processes"]) == 5
        assert cl["data"]["total_kv_size_bytes"] >= 0
        assert "qos" in cl

    c.run_until(c.loop.spawn(go()), timeout=60)
