"""Database configuration as committed \xff/conf/ data (VERDICT r3 item 4).

Reference: fdbclient/DatabaseConfiguration.h (configuration parsed from
system keys), fdbclient/ManagementAPI.actor.cpp changeConfig (written
transactionally), SystemData \xff/conf/ conventions.  Done-criterion: a
configuration change survives a whole-cluster power-fail reboot BECAUSE
it lives in the database — and recovery sizes recruitment from it.
"""

import pytest

from foundationdb_tpu.client.management import (change_configuration,
                                                get_configuration)
from foundationdb_tpu.core.scheduler import delay
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration

from test_recovery import commit_kv, read_key, teardown  # noqa: F401


async def _wait_recovered(c, want_resolvers, want_proxies, deadline=60.0):
    while deadline > 0:
        cc = c.current_cc()
        if cc is not None and cc.db_info.recovery_state in (
                "accepting_commits", "fully_recovered"):
            info = cc.db_info
            if len(info.resolvers) == want_resolvers and \
                    len(info.commit_proxies) == want_proxies:
                return True
        await delay(0.5)
        deadline -= 0.5
    return False


def test_config_change_is_transactional_and_survives_power_fail(teardown):  # noqa: F811
    c = SimFdbCluster(config=DatabaseConfiguration(), n_workers=6,
                      n_storage_workers=2)
    db = c.database()

    async def phase1():
        for i in range(10):
            await commit_kv(db, b"c%02d" % i, b"v%02d" % i)
        # One serializable transaction changes the role counts.
        await change_configuration(db, n_resolvers=2, n_commit_proxies=2)
        assert (await get_configuration(db))["n_resolvers"] == b"2"
        # The epoch ends and recovery recruits the NEW shape.
        assert await _wait_recovered(c, 2, 2), "new counts never recruited"
        # Data and writes fine through the new transaction system.
        assert await read_key(db, b"c05") == b"v05"
        await commit_kv(db, b"after-change", b"yes")
        return True

    assert c.run_until(c.loop.spawn(phase1()), timeout=120)

    # Whole-cluster unclean power failure + cold restart: the conf lives
    # in the database (cstate snapshot + txs replay), so the rebooted
    # cluster MUST come back with 2 resolvers / 2 proxies.
    c.power_fail_reboot()
    db2 = c.database()

    async def phase2():
        assert await _wait_recovered(c, 2, 2, deadline=90.0), \
            "config lost across power failure"
        assert await read_key(db2, b"c05") == b"v05"
        assert await read_key(db2, b"after-change") == b"yes"
        assert (await get_configuration(db2))["n_commit_proxies"] == b"2"
        # And it remains changeable afterwards.
        await change_configuration(db2, n_resolvers=1)
        assert await _wait_recovered(c, 1, 2), "change-back never adopted"
        return True

    assert c.run_until(c.loop.spawn(phase2()), timeout=180)
