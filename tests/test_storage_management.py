"""Storage recruitment + exclusion (VERDICT round-3 item 3).

Reference: fdbserver/DataDistribution.actor.cpp:629 (DDTeamCollection),
:4488 (storageServerTracker — a dead server is REPLACED),
fdbclient/ManagementAPI.actor.cpp (excludeServers).  Done-criteria:
kill one of three storage servers -> a replacement is recruited on an
idle storage worker -> consistency check passes at full replication;
an excluded server is drained empty while staying available as a
fetch source.
"""

import pytest

from foundationdb_tpu.core.scheduler import delay
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration

from test_recovery import commit_kv, read_key, teardown  # noqa: F401


def make_cluster(**cfg):
    n_workers = cfg.pop("n_workers", 7)
    n_storage_workers = cfg.pop("n_storage_workers", 4)
    config = DatabaseConfiguration(**cfg)
    return SimFdbCluster(config=config, n_workers=n_workers,
                        n_storage_workers=n_storage_workers)


def current_dd(cluster):
    cc = cluster.current_cc()
    if cc is None or cc.db_info.data_distributor is None:
        return None
    return getattr(cc.db_info.data_distributor, "role", None)


async def full_replication_audit(cluster, db, replication):
    """Every shard's team has `replication` HEALTHY members and replicas
    agree (ConsistencyCheck + team-size check)."""
    from foundationdb_tpu.testing.workloads import ConsistencyCheckWorkload
    dd = current_dd(cluster)
    for begin, _end, _t in dd.map.ranges():
        team = dd.map.lookup(begin)
        if team is None:
            continue
        live = [t for t in team if t in dd.healthy]
        assert len(live) >= replication, (begin, team, sorted(dd.healthy))
    w = ConsistencyCheckWorkload(cluster, db, {})
    assert await w.check()
    return True


def test_storage_death_recruits_replacement(teardown):  # noqa: F811
    # 3 storage servers on 4 storage workers: one idle spare to recruit on.
    c = make_cluster(n_storage=3, storage_replication=2)
    db = c.database()

    async def go():
        for i in range(30):
            await commit_kv(db, b"rk/%04d" % i, b"val%04d" % i)
        await commit_kv(db, b"\x90spread", b"hi")
        dd = current_dd(c)
        assert dd is not None
        tags0 = set(dd.storage)
        # Kill the worker hosting tag 0's storage role.
        victim = c.process_of(dd.storage[0])
        c.sim.power_fail_machine(victim.locality.machineid)
        # DD must recruit a REPLACEMENT (fresh tag) on the spare storage
        # worker, re-replicate, and RETIRE the dead tag.
        deadline = 60.0
        while deadline > 0:
            dd = current_dd(c)
            if dd is not None and (set(dd.storage) - tags0) and \
                    not dd.moves_in_flight:
                healthy_teams = all(
                    len([t for t in (dd.map.lookup(b) or [])
                         if t in dd.healthy]) >= 2
                    for b, _e, _t in dd.map.ranges()
                    if dd.map.lookup(b) is not None)
                if healthy_teams:
                    break
            await delay(0.5)
            deadline -= 0.5
        assert deadline > 0, "no replacement recruited / teams not healed"
        assert await full_replication_audit(c, db, 2)
        # Data still correct through the healed teams.
        for i in range(30):
            assert await read_key(db, b"rk/%04d" % i) == b"val%04d" % i
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=300)


def test_exclude_drains_server(teardown):  # noqa: F811
    from foundationdb_tpu.client.management import (exclude_servers,
                                                    excluded_servers,
                                                    include_servers)
    c = make_cluster(n_storage=3, storage_replication=2)
    db = c.database()

    async def go():
        for i in range(30):
            await commit_kv(db, b"ex/%04d" % i, b"v%04d" % i)
        dd = current_dd(c)
        await exclude_servers(db, [1])
        assert 1 in await excluded_servers(db)
        # Drain: no shard's team may contain tag 1 afterwards.
        deadline = 90.0
        while deadline > 0:
            dd = current_dd(c)
            if dd is not None and 1 in dd.excluded and \
                    not dd.moves_in_flight:
                teams = [dd.map.lookup(b) for b, _e, _t in dd.map.ranges()]
                if all(t is None or 1 not in t for t in teams):
                    break
            await delay(0.5)
            deadline -= 0.5
        assert deadline > 0, "excluded server never drained"
        # The drained server ends EMPTY (vacate is a one-way send: allow
        # it to land); data intact elsewhere.  Ownership is checked by
        # data presence — a fresh SS's shard map defaults to owned until
        # narrowed, so the map alone can't witness the drain.
        ss = dd.storage[1].role
        deadline = 15.0
        while deadline > 0:
            live, _more = ss.data.range_read(
                b"", b"\xff", ss.version.get(), 1 << 20, 1 << 30)
            if not live:
                break
            await delay(0.25)
            deadline -= 0.25
        assert deadline > 0, f"drained server still holds {len(live)} keys"
        for i in range(30):
            assert await read_key(db, b"ex/%04d" % i) == b"v%04d" % i
        assert await full_replication_audit(c, db, 2)
        # Re-include: the tag becomes a placement candidate again.
        await include_servers(db, [1])
        deadline = 30.0
        while deadline > 0:
            if 1 not in current_dd(c).excluded:
                break
            await delay(0.5)
            deadline -= 0.5
        assert deadline > 0
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=300)
