"""Known-bad fixture: FTL003 broad except in actor swallows cancellation."""
# expect: FTL003:8 FTL003:15 FTL002:43


async def actor_bare():
    try:
        await do_work()
    except:                     # noqa: E722 - swallows ActorCancelled
        pass


async def actor_base():
    try:
        await do_work()
    except BaseException:       # swallows ActorCancelled
        log()


async def actor_ok_reraise():
    try:
        await do_work()
    except BaseException:       # NOT flagged: re-raises
        log()
        raise


async def actor_ok_on_error(txn):
    try:
        await do_work()
    except BaseException as e:  # NOT flagged: on_error re-raises
        await txn.on_error(e)


async def actor_ok_narrow():
    try:
        await do_work()
    except Exception:           # NOT flagged: ActorCancelled is a
        pass                    # BaseException by design (core/error.py)


def sync_fn():
    try:
        do_work()
    except BaseException:       # NOT flagged: not an actor
        pass


async def do_work():
    return None


def log():
    return None
