"""Known-bad fixture: FTL006 blocking call inside an actor."""
# expect: FTL006:8 FTL006:9 FTL006:11
import os
import time


async def actor():
    time.sleep(0.5)             # stalls the whole reactor
    with open("state.dat") as f:    # bypasses sim_fs
        data = f.read()
    fd = os.open("raw.dat", 0)
    return data, fd


def sync_helper():
    # NOT flagged: not lexically inside an actor (host-side tool code).
    with open("spec.toml") as f:
        return f.read()
