"""Known-bad fixture: FTL010 stale shared-state snapshot across await
(the hazard class Flow's ACTOR compiler rejects at compile time)."""
# expect: FTL010:23 FTL010:30 FTL010:59

REGISTRY = {}


class Backend:
    def __init__(self):
        self._device = None
        self._epoch = 0
        self.id = "b0"          # only assigned here: immutable binding

    def degrade(self):
        self._device = None
        self._epoch += 1

    async def bad_snapshot(self):
        dev = self._device
        await wait()
        # BAD: the await may have degraded/promoted the backend; `dev`
        # still points at the pre-await device object.
        return dev.step()

    async def bad_loop(self):
        dev = self._device
        while True:
            await wait()
            # BAD: every iteration trusts the pre-loop snapshot.
            x = dev.step()
            if x:
                return x

    async def ok_rebound(self):
        dev = self._device
        await wait()
        dev = self._device      # re-bound after the await: clean
        return dev.step()

    async def ok_declared_state(self):
        dev = self._device      # flowlint: state
        await wait()
        return dev.step()       # declared state (Flow keyword): clean

    async def ok_copy_snapshot(self):
        epoch = int(self._epoch)
        await wait()
        return epoch            # explicit immutable copy: clean

    async def ok_immutable_binding(self):
        name = self.id
        await wait()
        return name             # self.id never reassigned: clean


async def bad_module_global():
    entry = REGISTRY.get("x")
    await wait()
    return entry.value          # BAD: REGISTRY is shared module state


def sync_reader(backend):
    dev = backend._device       # not an actor: no await barriers
    return dev


async def wait():
    return None
