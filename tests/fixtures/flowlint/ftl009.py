"""Known-bad fixture: FTL009 knob-name typos vs core/knobs.py fields."""
# expect: FTL009:9 FTL009:11 FTL009:16 FTL009:21 FTL009:50

from foundationdb_tpu.core.knobs import client_knobs, server_knobs


def bad():
    knobs = server_knobs()
    t = knobs.CONFLICT_DEVICE_TIMEOUT_SEC       # typo of ..._TIMEOUT_S
    # a getattr default would mask the typo forever
    d = getattr(knobs, "CONFLICT_PIPELINE_DEPHT", 1)
    return t, d


def bad_chained():
    return server_knobs().TPU_CONFLICT_CAPASITY  # typo of ..._CAPACITY


def bad_client():
    ck = client_knobs()
    return ck.KEY_SIZE_LIMITS                   # typo of KEY_SIZE_LIMIT


def good():
    knobs = server_knobs()
    ok1 = knobs.CONFLICT_DEVICE_TIMEOUT_S       # real ServerKnobs field
    ok2 = getattr(knobs, "CONFLICT_PIPELINE_DEPTH")
    ok3 = server_knobs().TPU_CONFLICT_CAPACITY
    ok4 = client_knobs().KEY_SIZE_LIMIT
    ok5 = knobs.override                        # method: not ALL-CAPS
    other = object()
    ok6 = other.NOT_A_KNOB_RECEIVER             # untracked receiver
    return ok1, ok2, ok3, ok4, ok5, ok6


def good_scoped_server():
    knobs = server_knobs()
    return knobs.CONFLICT_DEVICE_TIMEOUT_S


def good_scoped_client():
    # Same variable name bound to a DIFFERENT knob class in a sibling
    # scope: the per-scope map must not cross-resolve these.
    knobs = client_knobs()
    return knobs.KEY_SIZE_LIMIT


def bad_scoped_client():
    knobs = client_knobs()
    return knobs.CONFLICT_DEVICE_TIMEOUT_S      # ServerKnobs field, not Client
