"""Known-bad fixture: FTL001 wall-clock/entropy in sim-reachable code.

Markers below drive tests/test_flowlint.py: every `# expect: FTLnnn:<line>`
must be produced exactly, and nothing else."""
# expect: FTL001:15
# expect: FTL001:19
# expect: FTL001:23
# expect: FTL001:27
import os
import random
import time as _time


def stamp():
    return _time.monotonic()


def entropy():
    return os.urandom(8)


def draw():
    return random.randrange(10)


def stamp2():
    return _time.time_ns()


def fine_seeded():
    # NOT flagged: a seeded instance is deterministic.
    return random.Random(7).random()
