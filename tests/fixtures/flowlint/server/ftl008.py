"""Known-bad fixture: FTL008 hardcoded tunable in a server/ hot path."""
# expect: FTL008:4

_RETRY_BACKOFF_S = 0.25         # float tunable: belongs in core/knobs.py

_MAGIC = 0x0FDB                 # NOT flagged: int format constant
_OP_SET = 0                     # NOT flagged: int opcode
lowercase_float = 0.5           # NOT flagged: not a CONSTANT name
