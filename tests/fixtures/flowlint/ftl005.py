"""Known-bad fixture: FTL005 set iteration order (PYTHONHASHSEED hazard)."""
# expect: FTL005:7 FTL005:9 FTL005:11


def bad(names):
    out = []
    for n in set(names):                    # set() call
        out.append(n)
    for n in {"a", "b", "c"}:               # set literal
        out.append(n)
    return [x for x in frozenset(names)]    # comprehension over frozenset


def good(names):
    out = []
    for n in sorted(set(names)):            # NOT flagged: sorted
        out.append(n)
    for k in {"a": 1, "b": 2}:              # NOT flagged: dicts are
        out.append(k)                       # insertion-ordered
    return out
