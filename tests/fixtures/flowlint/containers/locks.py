"""Container-element lock identity: ``with self._locks[shard]:``
collapses to ONE may-alias element identity per container allocation
site (``self._locks[*]``), so the lock rules see subscripted
acquisitions at all — holding any element across an await is FTL011
exactly like a scalar lock."""
# expect: FTL011:18

import threading


class ShardedTable:
    def __init__(self):
        self._locks = {}
        self._rows = {}

    async def bad_await_holding_element(self, shard, fut):
        with self._locks[shard]:
            await fut               # BAD: element lock held across await

    def ok_sync_update(self, shard, value):
        with self._locks[shard]:
            self._rows[shard] = value

    def lock_for(self, shard):
        if shard not in self._locks:
            self._locks[shard] = threading.Lock()
        return self._locks[shard]
