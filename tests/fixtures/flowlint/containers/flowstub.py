"""Minimal Promise/PromiseStream stand-ins for the container-ownership
battery (FTL017) — enough surface for the type lattice to resolve
receivers, no scheduler behind them."""


class Promise:
    def __init__(self):
        self.sent = False

    def send(self, value):
        self.sent = True

    def send_error(self, error):
        self.sent = True

    def break_promise(self):
        self.sent = True

    def get_future(self):
        return self

    def is_set(self):
        return self.sent


class PromiseStream:
    def __init__(self):
        self.queue = []

    def send(self, value):
        self.queue.append(value)

    def send_error(self, error):
        self.queue.append(error)

    def close(self):
        self.queue = None

    def pop(self):
        return self.queue.pop(0)

    def empty(self):
        return not self.queue
