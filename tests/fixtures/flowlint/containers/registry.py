"""FTL017 battery: a promise PARKED in a ``self.<field>`` container is
only a sanctioned FTL016 hand-off if some in-package function DRAINS
that field — extracts elements and resolves them.  The registries here
are never drained (the distilled ISSUE-10 deposed-CC shape: one parked
waiter per request, hanging until GC luck), except where annotated
``# flowlint: owned`` or drained through a forwarded helper."""
# expect: FTL017:19 FTL017:24

from .flowstub import Promise


class LongPollRegistry:
    def __init__(self):
        self._waiters = []
        self._stash = []
        self._external = []

    def subscribe(self):
        p = Promise()               # BAD: _waiters is never drained
        self._waiters.append(p)
        return p.get_future()

    def stash(self):
        p = Promise()               # BAD: popped below, never resolved
        self._stash.append(p)
        return p.get_future()

    def rebalance(self):
        # A pop whose element is DISCARDED is not a drain — nothing is
        # ever sent or broken, so `stash` above still fires.
        if self._stash:
            self._stash.pop()

    def adopt(self):
        q = Promise()  # flowlint: owned -- drained by the harness-side poller
        self._external.append(q)
        return q.get_future()


class FanoutRegistry:
    """Cross-function drain: each element is handed to a helper that
    resolves it — sanctioned through the bottom-up forward summaries
    (drain_forwards composed with the helper's resolver params)."""

    def __init__(self):
        self._parked = []

    def subscribe(self):
        p = Promise()               # OK: drain_all -> _resolve drains
        self._parked.append(p)
        return p.get_future()

    def drain_all(self, value):
        for p in self._parked:
            self._resolve(p, value)
        self._parked.clear()

    def _resolve(self, p, value):
        p.send(value)
