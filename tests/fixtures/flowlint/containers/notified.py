"""Drained registries distilled from the repo — ALL CLEAN, no
suppressions: the heap-of-tuples version gate (core/notified.py's
shape) and the chained ``pop(0).send`` gate.  FTL017 must recognize
both drain idioms (tuple-unpack of heappop, pop-call receiver of a
resolver) or the real package would light up."""

import heapq

from .flowstub import Promise


class Notified:
    """when_at_least parks ``(version, seq, promise)`` on a heap;
    set_value pops and sends every ripe waiter."""

    def __init__(self):
        self._value = 0
        self._seq = 0
        self._waiters = []

    def when_at_least(self, version):
        if self._value >= version:
            p = Promise()
            p.send(self._value)
            return p.get_future()
        p = Promise()
        self._seq += 1
        heapq.heappush(self._waiters, (version, self._seq, p))
        return p.get_future()

    def set_value(self, value):
        self._value = value
        while self._waiters and self._waiters[0][0] <= value:
            _, _, p = heapq.heappop(self._waiters)
            p.send(value)


class _Gate:
    """data_distribution's FIFO lock shape: release resolves the head
    waiter straight off the pop call."""

    def __init__(self):
        self._queue = []

    def wait(self):
        p = Promise()
        self._queue.append(p)
        return p.get_future()

    def release(self):
        if self._queue:
            self._queue.pop(0).send(None)


class Broadcaster:
    """cluster_controller's _publish shape: the atomic tuple swap
    (``waiters, self._waiters = self._waiters, []``) then a fan-out
    loop over the swapped-out batch."""

    def __init__(self):
        self._waiters = []

    def subscribe(self):
        p = Promise()
        self._waiters.append(p)
        return p.get_future()

    def publish(self, value):
        waiters, self._waiters = self._waiters or [], []
        for p in waiters:
            p.send(value)
