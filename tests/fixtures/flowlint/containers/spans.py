"""FTL007 span-point battery: ``trace_batch_event`` locations must
follow the 'Role.point' grammar (dotted, CamelCase head) or the
commit-debug waterfall tool drops them on the floor; an f-string needs
a static CamelCase head so the waterfall can still bucket by role."""
# expect: FTL007:20 FTL007:21 FTL007:24 FTL007:25


def trace_batch_event(event_type, debug_id, location):
    """Local stand-in with the real three-positional signature."""
    return (event_type, debug_id, location)


class Recorder:
    def __init__(self):
        self.span = "s"

    def emit(self, name):
        trace_batch_event("CommitDebug", self.span,
                          "CommitProxy.batchStart")             # OK
        trace_batch_event("CommitDebug", self.span, "lowercase.point")
        trace_batch_event("CommitDebug", self.span, "NoDotHere")
        trace_batch_event("CommitDebug", self.span,
                          f"Rpc.encode.{name}")                 # OK
        trace_batch_event("CommitDebug", self.span, f"{name}.encode")
        trace_batch_event("CommitDebug", self.span, f"bad head {name}")
