"""FTL018 battery: wire-evolution hazards against a (fictional)
golden-frozen registry.  The registries mirror rpc/serde.py's shape;
the struct names are invented so the real package's goldens never
collide with the fixture's."""
# expect: FTL018:34 FTL018:44 FTL018:48 FTL018:57

from dataclasses import dataclass, field
from typing import Any, ClassVar, List

_GOLDEN_FROZEN_FIELDS = {
    "PingRequest": ("token", "version"),
    "PongReply": ("token", "echo"),
    "StatusRequest": ("detail",),
    "LegacyProbe": ("probe_id", "deadline"),
    "BumpedReply": ("rows",),
}

_ELIDE_DEFAULT_FIELDS = {
    "PongReply": ("trace_id",),
    "StatusRequest": ("verbose", "ghost_field"),
}

_CODEC_VERSIONS = {
    "BumpedReply": 2,
}


@dataclass
class PingRequest:
    token: str
    version: int = 0
    # BAD: grafted beyond the frozen list — not elided, not
    # version-gated; the previous release's decoder rejects the frame.
    hops: int = 0
    reply: Any = None               # never travels: skipped


@dataclass
class PongReply:
    token: str
    echo: bytes = b""
    # BAD: elide-sanctioned but NO default — a legacy frame without the
    # field cannot fill it (not format-transparent).
    trace_id: str


@dataclass
class StatusRequest:
    # Class line is BAD too: the elide registry names 'ghost_field',
    # which does not exist here (registry drift).
    KIND: ClassVar[str] = "status"
    detail: int = 0
    verbose: bool = False           # OK: elided at its default


@dataclass
class LegacyProbe:
    # BAD (class line): frozen field 'deadline' no longer exists —
    # frames encoded by the frozen format no longer decode.
    probe_id: int = 0


@dataclass
class BumpedReply:
    rows: List[bytes] = field(default_factory=list)
    # OK: the _CODEC_VERSIONS bump sanctions the new field.
    checksum: int = 0
