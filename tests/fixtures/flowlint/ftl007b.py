"""Second module of the FTL007 schema-drift pair (see ftl007.py)."""


def emit():
    TraceEvent("DriftType").detail("Beta", 2).log()
