"""Known-bad fixture: FTL012 lockset discipline, modeled on the PR-6
supervisor race — dispatch/fetch-lane bookkeeping (`_needs`,
`_delta_bound`) corrected under ``self._lock`` on one lane but
snapshotted lock-free on the other."""
# expect: FTL012:24 FTL012:26

import threading


class RacyBackend:
    def __init__(self):
        self._lock = threading.Lock()
        self._needs = {}
        self._delta_bound = 1
        self._profile = {"batches": 0}

    def correct_fetch(self, seq, size):
        with self._lock:
            self._needs[seq] = size
            self._delta_bound += size

    def racy_dispatch(self):
        # BAD: lock-free snapshot of the lock-guarded dict.
        snap = dict(self._needs)
        # BAD: lock-free write racing the guarded += above.
        self._delta_bound = 1
        return snap

    def unguarded_everywhere(self):
        # Never written under the lock anywhere: not flagged.
        self._profile["batches"] += 1


class FixedBackend:
    def __init__(self):
        self._lock = threading.Lock()
        self._needs = {}

    def fetch(self, seq, size):
        with self._lock:
            self._needs[seq] = size

    def dispatch(self):
        with self._lock:
            snap = dict(self._needs)    # guarded snapshot: clean
        return snap
