"""Two-instance no-alias battery (ISSUE 13): a CLEAN file.

Two instances of one class held in different fields share the
attribute NAME ``self._lock`` but are different lock objects.  With
name-keyed identities, ``cross()`` — a's lock held while b's method
locks b — reads as ``self._lock`` nested inside ``self._lock``: a
spurious self-cycle.  Object-sensitive identities key the two roles
apart (``Pair#a._lock`` vs ``Pair#b._lock``), and since only the
a-then-b order exists, there is no cycle and NOTHING fires."""

import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def locked_op(self):
        with self._lock:
            self._n += 1


class Pair:
    def __init__(self):
        self.a = Worker()
        self.b = Worker()

    def cross(self):
        with self.a._lock:
            self.b.locked_op()      # clean: a-before-b is the ONLY order

    def cross_again(self):
        with self.a._lock:
            self.b.locked_op()      # clean: same direction, still acyclic
