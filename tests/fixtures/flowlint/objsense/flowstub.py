"""Distilled core/futures.py write-end API for the FTL016 fixtures —
the protocol surface only (send / send_error / break_promise / close
resolve; get_future / is_set / pop / empty read)."""


class Promise:
    def __init__(self):
        self.sent = False

    def send(self, value=None):
        self.sent = True

    def send_error(self, e):
        self.sent = True

    def break_promise(self):
        self.sent = True

    def get_future(self):
        return self

    def is_set(self):
        return self.sent


class PromiseStream:
    def __init__(self):
        self.queue = []

    def send(self, value=None):
        self.queue.append(value)

    def send_error(self, e):
        self.queue = None

    def close(self):
        self.queue = None

    def pop(self):
        return self.queue

    def empty(self):
        return not self.queue
