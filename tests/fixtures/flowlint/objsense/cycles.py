"""Known-bad battery for FTL015 lock-ordering cycles: the AB/BA
two-class cycle (composed through receiver-typed calls — an
annotation-typed back edge and an attribute-typed forward edge) and a
three-lock module-level cycle."""

import threading


class Beta:
    def __init__(self):
        self._lock = threading.Lock()
        self._hits = 0

    def poke(self):
        with self._lock:
            self._hits += 1

    def back(self, owner: Alpha):
        with self._lock:
            owner.grab()            # BAD half: Beta lock, then Alpha lock


class Alpha:
    def __init__(self):
        self._lock = threading.Lock()
        self._beta = Beta()
        self._gen = 0

    def grab(self):
        with self._lock:
            self._gen += 1

    def forward(self):
        with self._lock:
            self._beta.poke()       # BAD half: Alpha lock, then Beta lock


_ALPHA_LOCK = threading.Lock()
_BRAVO_LOCK = threading.Lock()
_CHARLIE_LOCK = threading.Lock()


def take_ab():
    with _ALPHA_LOCK:
        with _BRAVO_LOCK:           # BAD: A then B ...
            return 1


def take_bc():
    with _BRAVO_LOCK:
        with _CHARLIE_LOCK:         # ... B then C ...
            return 2


def take_ca():
    with _CHARLIE_LOCK:
        with _ALPHA_LOCK:           # ... C then A: a three-lock cycle
            return 3

# expect: FTL015:35 FTL015:45
