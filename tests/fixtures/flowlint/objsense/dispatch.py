"""Receiver-typed dispatch battery (ISSUE 13): ``obj.method()`` calls
resolved through the local type-inference pass — constructor
assignment, class-typed attribute, factory return, annotation — feed
the interprocedural rules (here: FTL013's transitive blocking, which
an unknown callee could never reach); an AMBIGUOUS receiver stays an
unknown callee and must invent nothing."""

import threading


class Engine:
    def wait_done(self, fut):
        return fut.result()         # the unbounded block

    def wait_bounded(self, fut, timeout):
        return fut.result(timeout=timeout)


class OtherEngine:
    def wait_done(self, fut):
        return fut.result(timeout=1.0)


def make_engine():
    return Engine()


class Caller:
    def __init__(self):
        self._lock = threading.Lock()
        self._eng = Engine()

    def bad_attr_typed(self, fut):
        with self._lock:
            return self._eng.wait_done(fut)     # BAD: selfattr-typed

    def bad_ctor_typed(self, fut):
        eng = Engine()
        with self._lock:
            return eng.wait_done(fut)           # BAD: constructor-typed

    def bad_factory_typed(self, fut):
        eng = make_engine()
        with self._lock:
            return eng.wait_done(fut)           # BAD: factory-typed

    def ok_annotation_bounded(self, eng: Engine, fut):
        with self._lock:
            return eng.wait_bounded(fut, 1.0)   # clean: timeout wrapper

    def ok_ambiguous(self, flip, fut):
        if flip:
            eng = Engine()
        else:
            eng = OtherEngine()
        with self._lock:
            return eng.wait_done(fut)           # clean: receiver unknown

# expect: FTL013:35 FTL013:40 FTL013:45
