"""FTL016 promise-protocol battery: a locally created Promise/
PromiseStream must be sent, broken, or handed off on EVERY path —
the ISSUE-10 deposed-CC bug class (a parked reply neither sent nor
broken hangs its waiter until GC luck).  Factory-created promises are
tracked through the returns-instance summary; escapes (stored, passed,
returned) transfer ownership and satisfy the protocol."""

from .flowstub import Promise, PromiseStream


def make_reply():
    return Promise()


class Server:
    def __init__(self):
        self.waiters = []
        self.value = 0

    def ok_sent_on_all_paths(self, ready):
        p = Promise()
        if ready:
            p.send(self.value)
        else:
            p.send_error(RuntimeError("not ready"))
        return p.get_future()

    def ok_broken_on_miss(self, ready):
        p = Promise()
        if ready:
            p.send(self.value)
        else:
            p.break_promise()
        return p.get_future()

    def bad_leaked_on_one_branch(self, ready):
        p = Promise()               # BAD: not-ready branch forgets p
        if ready:
            p.send(self.value)
        return p.get_future()

    def bad_factory_leak(self, ready):
        p = make_reply()            # BAD: early return forgets p
        if not ready:
            return None
        p.send(self.value)
        return p.get_future()

    def ok_escapes_into_registry(self):
        p = Promise()
        self.waiters.append(p)      # handed off: the registry owns it
        return p.get_future()

    def notify_all(self):
        # The ownership-protocol consumer (FTL017): without this drain
        # the append above would be a park into a registry nobody
        # empties — exactly the hang the escape rule trusts away.
        waiters, self.waiters = self.waiters, []
        for p in waiters:
            p.send(self.value)

    def ok_returned_whole(self):
        p = Promise()
        return p                    # handed off: the caller owns it

    def bad_stream_never_closed(self):
        s = PromiseStream()         # BAD: popped, never closed/handed off
        fut = s.pop()
        return fut

    def ok_stream_closed(self):
        s = PromiseStream()
        s.send(1)
        s.close()
        return s.pop()

# expect: FTL016:37 FTL016:43 FTL016:67
