"""Known-bad fixture: FTL007 TraceEvent naming + schema drift (with
ftl007b.py, which emits 'DriftType' with a different detail schema)."""
# expect: FTL007:0 FTL007:7


def emit():
    TraceEvent("badCamelName").detail("K", 1).log()
    TraceEvent("DriftType").detail("Alpha", 1).log()
    TraceEvent("GoodName").detail("K", 1).log()     # NOT flagged
