"""Known-bad fixture: FTL002 un-awaited coroutine call."""
# expect: FTL002:10


async def refill_cache():
    return 1


async def driver():
    refill_cache()          # coroutine built and dropped: never runs
    await refill_cache()    # NOT flagged: awaited


def sync_driver():
    refill_cache()          # expect-line: also flagged outside async


# expect: FTL002:15
