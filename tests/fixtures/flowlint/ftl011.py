"""Known-bad fixture: FTL011 await / unbounded wait while holding a
threading lock (deadlock + event-loop-stall hazard)."""
# expect: FTL011:16 FTL011:21 FTL011:26

import threading


class Pipeline:
    def __init__(self):
        self._lock = threading.Lock()
        self._aio = make_async_lock()

    async def bad_await_in_lock(self):
        with self._lock:
            # BAD: lock held across the suspension.
            await step()

    async def bad_result_in_lock(self, fut):
        with self._lock:
            # BAD: unbounded block inside the critical section.
            return fut.result()

    async def bad_acquire_release(self, fut):
        self._lock.acquire()
        # BAD: unbounded wait between acquire() and release().
        x = fut.wait()
        self._lock.release()
        return x

    async def ok_timeout(self, fut):
        with self._lock:
            return fut.result(timeout=1.0)      # bounded: clean

    async def ok_release_before_await(self):
        with self._lock:
            snap = 1
        await step()                # lock already released: clean
        return snap

    async def ok_async_lock(self):
        async with self._aio:
            await step()            # async lock is reactor-safe: clean


def make_async_lock():
    return None


async def step():
    return None
