"""Fixture with NO findings: the idiomatic versions of everything the
other fixtures do wrong.  flowlint must report nothing here."""


async def actor(txn, loop):
    from foundationdb_tpu.core.scheduler import delay
    await delay(0.5)
    txn.set(b"key", b"value")
    try:
        await txn.commit()
    except Exception:
        raise


def deterministic(rng, names):
    for n in sorted(set(names)):
        rng.random01()
    return loop_time(None)


def loop_time(loop):
    return loop.now() if loop else 0.0
