"""Known-bad fixture: FTL004 str literal flows into a bytes-key API."""
# expect: FTL004:6 FTL004:7 FTL004:8 FTL004:9 FTL004:10 FTL004:11 FTL004:15 FTL004:16 FTL004:20 FTL004:21


def bad(txn):
    txn.set("tenant/map", b"v")             # str key
    txn.set(b"k", "value")                  # str value
    txn.clear_range("a", b"b")              # str begin
    txn.watch(f"watch/{1}")                 # f-string key
    txn.get_range("p/" + chr(49), b"q")     # str concat begin
    txn.atomic_op("add", "counter", b"\x01")


def bad_pack(self):
    self._pack("relative-key")
    self._pack_end("end-key")


def bad_kw(txn):
    txn.get_range(begin=b"a", end="b")
    txn.set(b"k", value=f"count-{1}")   # kv keyword defeats unary exempt


def good(txn, sig):
    txn.set(b"k", b"v")
    txn.clear_range(b"a", b"b")
    sig.set("kill")             # NOT flagged: unary .set is a signal
    cfg = {}
    cfg.get("name")             # NOT flagged: dict.get excluded
    self_pack = None
    return cfg, self_pack
