"""Known-bad fixture: FTL001 wall-clock reads reached VIA HELPERS from
sim-reachable code — the static verification of the REAL_ONLY-modules
"never imported on a sim path" construction."""
# expect: FTL001:10

from .rpc.real_network import read_guarded, read_wall


def bad_stamp():
    return read_wall()              # BAD: chains to time.monotonic()


def ok_guarded(loop):
    return read_guarded(loop)       # mode-guarded callee: clean
