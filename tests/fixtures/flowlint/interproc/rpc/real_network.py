"""Models a REAL_ONLY module (the path suffix ``rpc/real_network.py``
is on rules.REAL_ONLY_MODULES): direct wall-clock reads are sanctioned
HERE — reaching one from a sim-reachable module is the interprocedural
FTL001 finding, reported at the caller (clocks.py)."""

import time


def read_wall():
    return time.monotonic()         # exempt here: real-only module


def read_guarded(loop):
    """EventLoop.now()'s shape: the ``sim`` branch marks the function
    mode-guarded, so the read never propagates to sim callers."""
    if loop.sim:
        return 0.0
    return time.monotonic()
