"""Known-bad fixture: FTL005 through in-package call chains DEEPER
than the one same-file hop the per-file pass resolves — cross-file
imports, depth-2 helper chains, and recursion (SCC convergence)."""
# expect: FTL005:11 FTL005:15 FTL005:20

from .helpers import deep_tags, rec_tags


def bad_deep(txns):
    tags = deep_tags(txns)
    return [t for t in tags]        # BAD: depth-2 cross-file set chain


def bad_recursive(txns):
    for t in rec_tags(txns, 3):     # BAD: recursion converges set-valued
        use(t)


def bad_via_local(txns):
    for t in local_chain(txns):     # BAD: same-file chain deeper than 1 hop
        use(t)


def local_chain(txns):
    return deep_tags(txns)


def ok_rebound(txns):
    tags = sorted(deep_tags(txns))
    for t in tags:                  # sorted: deterministic, clean
        use(t)


def use(t):
    return t
