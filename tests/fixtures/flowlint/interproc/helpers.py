"""Cross-file targets for the interproc fixtures: blocking wrappers
(with and without a forwarded timeout) and set-returning helper chains
(incl. recursion, so the SCC fixpoint has something to converge on).
Nothing in THIS file is a finding — the hazards live at the callers."""


def wait_done(fut):
    return fut.result()             # unbounded: may-block summary root


def wait_bounded(fut, timeout):
    return fut.result(timeout=timeout)  # timeout forwarded: never blocks


def drain(fut):
    return wait_done(fut)           # depth-2 link of the FTL013 chain


def tags_of(txns):
    return {t.tag for t in txns}


def deep_tags(txns):
    return tags_of(txns)            # depth-2 set-valued chain


def rec_tags(txns, depth):
    if depth == 0:
        return {t.tag for t in txns}
    return rec_tags(txns, depth - 1)    # recursion: GFP converges to set


def churn(fut):
    return churn2(fut)              # mutually recursive blockers: the


def churn2(fut):
    return churn(fut) or fut.wait()     # SCC still converges may-block
