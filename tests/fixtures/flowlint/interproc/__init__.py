"""Interprocedural fixture package (ISSUE 11): multi-file shapes the
per-function dataflow cannot see — cross-file dispatch, caller-held
locksets, transitive blocking chains, deep set-valued chains, lock
aliasing, unknown-callee conservatism.  Every expected finding carries
an exact ``# expect: FTLnnn:<line>`` marker; tests assert got ==
expected in BOTH directions."""
