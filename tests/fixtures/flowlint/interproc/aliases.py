"""Known-bad/known-good battery for FTL014 lock-alias discipline: a
single-valued alias (local or parameter) PARTICIPATES in the lockset
join/meet; an ambiguous one is flagged and contributes nothing."""
# expect: FTL014:48 FTL012:49 FTL014:62

import threading


class AliasJoin:
    """``lk = self._lock; with lk:`` canonicalizes to the attribute:
    the alias-guarded write and the directly-guarded write meet on the
    SAME lock — clean (previously the alias dropped out and this
    class was a false positive waiting to happen)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def via_alias(self):
        lk = self._lock
        with lk:
            self._n += 1            # clean: alias == self._lock

    def direct(self):
        with self._lock:
            self._n = 2


class AliasSplit:
    """The alias binds DIFFERENT locks on different paths: its region
    guards no one provable lock (FTL014), so the write inside it has
    an empty lockset and races the guarded site (FTL012)."""

    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()
        self._n = 0

    def guarded(self):
        with self._a_lock:
            self._n = 1

    def ambiguous(self, c):
        if c:
            lk = self._a_lock
        else:
            lk = self._b_lock
        with lk:                    # BAD: which lock is held here?
            self._n = 2             # BAD: empty lockset vs guarded()


class LockParamSplit:
    """A lock PARAMETER whose callers pass different locks: no
    cross-site discipline can be established through it (FTL014)."""

    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()
        self._n = 0

    def _locked_add(self, use_lock):
        with use_lock:              # BAD: a different lock per caller
            self._n += 1

    def add_a(self):
        self._locked_add(self._a_lock)

    def add_b(self):
        self._locked_add(self._b_lock)


class LockParamJoin:
    """Every caller passes the SAME lock: the parameter canonicalizes
    to it and the guarded sites meet — clean."""

    def __init__(self):
        self._lock = threading.Lock()
        self._n = 0

    def _bump(self, use_lock):
        with use_lock:
            self._n += 1            # clean: use_lock == self._lock

    def outer(self):
        self._bump(self._lock)

    def direct(self):
        with self._lock:
            self._n = 3
