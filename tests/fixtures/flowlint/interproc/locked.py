"""Known-bad/known-good battery for caller-held locksets (FTL012
seeding — the ``Tracer._roll`` shape) and transitive blocking under a
lock (FTL013), incl. unknown-callee conservatism."""
# expect: FTL012:44 FTL012:64 FTL013:78 FTL013:82

import threading

from .helpers import churn, drain, wait_bounded


class Roller:
    """core/trace.py::Tracer._roll, distilled: a private helper whose
    EVERY caller holds the lock — the entry lockset is seeded with the
    meet of the callsite locksets, so the lock-free-looking writes are
    provably guarded and nothing fires (the 3 suppressions ISSUE 11
    removed from trace.py)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._fh = None
        self._bytes = 0

    def _roll(self):
        self._fh = object()         # clean: caller holds the lock
        self._bytes = 0             # clean: caller holds the lock

    def emit(self):
        with self._lock:
            self._bytes += 1
            if self._bytes > 10:
                self._roll()


class LeakyRoller:
    """Same shape with ONE lock-free caller: the meet over callsites is
    empty, seeding dies, and the race re-fires — the regression guard
    for the removed trace.py suppressions."""

    def __init__(self):
        self._lock = threading.Lock()
        self._bytes = 0

    def _roll(self):
        self._bytes = 0             # BAD: emit_unlocked calls lock-free

    def emit(self):
        with self._lock:
            self._bytes += 1
            self._roll()

    def emit_unlocked(self):
        self._roll()


class EscapedRoller:
    """The helper ESCAPES (handed to a callback): an invisible caller
    might hold no lock, so seeding must not apply — conservative."""

    def __init__(self):
        self._lock = threading.Lock()
        self._bytes = 0

    def _roll(self):
        self._bytes = 0             # BAD: address-taken, callers unknown

    def emit(self, loop):
        with self._lock:
            self._bytes += 1
            loop.call_soon(self._roll)


class Pipeline:
    def __init__(self):
        self._lock = threading.Lock()

    def bad_transitive(self, fut):
        with self._lock:
            return drain(fut)       # BAD: chain drain -> wait_done -> .result()

    def bad_recursive(self, fut):
        with self._lock:
            return churn(fut)       # BAD: mutually-recursive blocker SCC

    def ok_bounded(self, fut):
        with self._lock:
            return wait_bounded(fut, 1.0)   # timeout checked through wrapper

    def ok_unknown(self, obj):
        with self._lock:
            return obj.mystery()    # unknown callee: no invented finding
