"""Known-bad fixture: widened FTL005 — set-valuedness tracked through
the def-use chains (assignments, set-returning helpers, set-annotated
parameters, set operators), not just syntactic set iteration."""
# expect: FTL005:15 FTL005:21 FTL005:26 FTL005:33

from typing import Set


def _tags_of(txns):
    return {t.tag for t in txns}


def bad_assigned(names):
    s = set(names)
    for n in s:                     # BAD: s holds a set
        use(n)


def bad_helper(txns):
    tags = _tags_of(txns)
    return [t for t in tags]        # BAD: helper returns a set


def bad_param(tags: Set[str]):
    out = []
    for t in tags:                  # BAD: set-typed parameter
        out.append(t)
    return out


def bad_union(names, extras):
    merged = set(names) | set(extras)
    for x in merged:                # BAD: union of two sets
        use(x)


def ok_rebound(names):
    s = set(names)
    s = sorted(s)
    for n in s:                     # re-bound to a sorted list: clean
        use(n)


def ok_sorted_wrap(tags: Set[str]):
    return [t for t in sorted(tags)]    # sorted(): clean


def use(x):
    return x
