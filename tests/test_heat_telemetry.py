"""Cluster heat telemetry (ISSUE 8): the conflict-range / read-hot-spot
sampling plane — tracker determinism and decay, exact vs conservative
abort attribution (oracle + supervised device path), the unified
resolver sample table, and the end-to-end surface agreement between
status cluster.heat, the \xff\xff/metrics/ special keys and `fdbcli
top` on a planted hot range; plus a double-run unseed test proving the
plane (sampling, decay, emission cadence) is sim-deterministic."""

import json
import os

import pytest

from foundationdb_tpu.conflict.heat import ConflictHeatTracker
from foundationdb_tpu.conflict.oracle import OracleConflictSet
from foundationdb_tpu.conflict.supervisor import SupervisedConflictSet
from foundationdb_tpu.core import FdbError
from foundationdb_tpu.core.knobs import server_knobs
from foundationdb_tpu.txn.types import (CommitResult, CommitTransactionRef,
                                        KeyRange)

from test_recovery import make_cluster, teardown  # noqa: F401

SPECS = os.path.join(os.path.dirname(__file__), "specs")


@pytest.fixture()
def knobs():
    """Mutable server knobs restored after the test."""
    k = server_knobs()
    saved = dict(k.__dict__)
    yield k
    for name, value in saved.items():
        setattr(k, name, value)


def _txn(reads=(), writes=(), snap=0, report=False, tenant=-1, tag=""):
    return CommitTransactionRef(
        read_conflict_ranges=[KeyRange(b, e) for b, e in reads],
        write_conflict_ranges=[KeyRange(b, e) for b, e in writes],
        mutations=[], read_snapshot=snap, report_conflicting_keys=report,
        tenant_id=tenant, tag=tag)


# ---------------------------------------------------------------------------
# ConflictHeatTracker: decay, top-K, bounds, determinism
# ---------------------------------------------------------------------------

def test_tracker_records_and_ranks():
    t = ConflictHeatTracker(sample_every=1)
    for _ in range(5):
        t.record_conflict(b"hot", b"hot\x00", tenant_id=3, tag="t/web")
    t.record_conflict(b"cold", b"cold\x00")
    t.sample_load(b"hot", b"hot\x00")
    top = t.top_conflicts(2)
    assert top[0][:3] == (b"hot", b"hot\x00", 5)
    assert top[1][:3] == (b"cold", b"cold\x00", 1)
    assert t.tenants == {3: 5}
    assert t.tags == {"t/web": 5}
    doc = t.to_status(1)
    assert doc["top_conflict_ranges"][0]["conflicts"] == 5
    assert doc["top_conflict_ranges"][0]["begin_hex"] == b"hot".hex()
    assert doc["busiest_tags"] == [{"tag": "t/web", "conflicts": 5}]
    assert doc["busiest_tenants"] == [{"tenant_id": 3, "conflicts": 5}]


def test_tracker_decay_halves_and_drops():
    t = ConflictHeatTracker(sample_every=1)
    for _ in range(4):
        t.record_conflict(b"a", b"b", tenant_id=1, tag="x")
    t.record_conflict(b"c", b"d")
    t.decay()
    assert t.ranges[(b"a", b"b")] == [0, 2]
    assert (b"c", b"d") not in t.ranges       # single hit aged out
    assert t.tenants == {1: 2} and t.tags == {"x": 2}
    t.decay()
    t.decay()
    assert not t.ranges and not t.tenants and not t.tags


def test_tracker_load_sampling_every_nth():
    t = ConflictHeatTracker(sample_every=8)
    hits = sum(t.sample_load(b"k%d" % i, b"k%d\x00" % i)
               for i in range(64))
    assert hits == 8                          # exactly one in eight
    assert t.total_load == 8


def test_tracker_table_bound_by_halving():
    t = ConflictHeatTracker(sample_every=1, table_max=64)
    for i in range(1000):
        t.record_conflict(b"k%04d" % i, b"k%04d\x00" % i)
    assert len(t.ranges) <= 64 + 1


def test_tracker_deterministic_across_instances():
    def feed(t):
        for i in range(300):
            k = b"k%02d" % (i % 17)
            t.sample_load(k, k + b"\x00")
            if i % 3 == 0:
                t.record_conflict(k, k + b"\x00", tenant_id=i % 5,
                                  tag="t%d" % (i % 4))
            if i % 97 == 0:
                t.decay()
        return t.to_status(8)

    assert feed(ConflictHeatTracker()) == feed(ConflictHeatTracker())


def test_tracker_split_load_projection():
    """Two sampled ranges sharing a begin merge their load mass on that
    begin key — the shape _serve_split consumed from the old begin-keyed
    dict."""
    t = ConflictHeatTracker(sample_every=1)
    t.sample_load(b"b", b"c")
    t.sample_load(b"b", b"d")
    t.sample_load(b"e", b"f")
    t.record_conflict(b"zz", b"zz\x00")   # conflict-only: no load mass
    assert t.split_load(b"a", b"z") == [(b"b", 2), (b"e", 1)]
    assert t.split_load(b"c", b"z") == [(b"e", 1)]


# ---------------------------------------------------------------------------
# Exact attribution: oracle, supervisor device path, budget + counter
# ---------------------------------------------------------------------------

def test_oracle_attributes_all_aborted_txns():
    """last_attribution covers non-reporters too (first culprit), while
    the client-facing reported dict stays reporters-only."""
    cs = OracleConflictSet(0)
    cs.resolve_with_conflicts([_txn(writes=[(b"h", b"i")])], 10)
    verdicts, reported = cs.resolve_with_conflicts(
        [_txn(reads=[(b"a", b"b"), (b"h", b"i")], snap=5),
         _txn(reads=[(b"h", b"i")], snap=5, report=True)], 20)
    assert verdicts == [CommitResult.CONFLICT, CommitResult.CONFLICT]
    assert reported == {1: [(b"h", b"i")]}
    assert cs.last_attribution == {0: [(b"h", b"i")],
                                   1: [(b"h", b"i")]}
    assert cs.last_attribution_exact == {0: True, 1: True}


def test_oracle_attribute_conflicts_matches_resolve():
    """The read-only attribute_conflicts (the supervisor's device-path
    probe) reproduces resolve_with_conflicts' own attribution, given the
    same pre-batch history and the final verdicts."""
    from foundationdb_tpu.core import DeterministicRandom
    from test_conflict_oracle import make_domain, random_txn
    rng = DeterministicRandom(99)
    domain = make_domain()
    a, b = OracleConflictSet(0), OracleConflictSet(0)
    now = 0
    for _ in range(20):
        now += rng.random_int(1, 2_000_000)
        batch = [random_txn(rng, domain, now, 4_000_000)
                 for _ in range(rng.random_int(1, 8))]
        verdicts = b.resolve(batch, now)     # b lags one batch behind a
        probed = a.attribute_conflicts(batch, verdicts)
        a.resolve_with_conflicts(batch, now)
        want = {t: rs[:1] if not getattr(batch[t],
                                         "report_conflicting_keys", False)
                else rs
                for t, rs in a.last_attribution.items()}
        got = {t: rs[:1] if not getattr(batch[t],
                                        "report_conflicting_keys", False)
               else rs for t, rs in probed.items()}
        assert got == want, f"attribution divergence at now={now}"


def test_supervisor_device_path_exact_attribution(knobs):
    """Device-resolved batches get budget-bounded EXACT attribution via
    the mirror; the whole-read-set fallback past the budget is counted
    in ConservativeAttribution (satellite 1)."""
    from test_conflict_supervisor import make_tpu
    knobs.CONFLICT_ATTRIBUTION_SAMPLE = 1
    sup = SupervisedConflictSet(make_tpu)
    sup.resolve([_txn(writes=[(b"h", b"h\x00")])], 10)
    # Two aborted readers of the same dirty key; budget covers one.
    verdicts, _ = sup.resolve_with_conflicts(
        [_txn(reads=[(b"h", b"h\x00")], snap=5),
         _txn(reads=[(b"a", b"b"), (b"h", b"h\x00")], snap=5)], 20)
    assert verdicts == [CommitResult.CONFLICT, CommitResult.CONFLICT]
    assert sup.stats["device_batches"] == 2
    assert sup.last_attribution == {0: [(b"h", b"h\x00")]}
    assert sup.last_attribution_exact == {0: True}
    assert sup.stats["exact_attribution"] == 1
    assert sup.stats["conservative_attribution"] == 1
    assert sup.metrics.counter("ConservativeAttribution").value == 1


def test_supervisor_reporters_get_exact_ranges(knobs):
    """A reporter inside the attribution budget now gets the TRUE
    culprit range from the device path, not its whole read set (the old
    conservative-only behavior)."""
    from test_conflict_supervisor import make_tpu
    sup = SupervisedConflictSet(make_tpu)
    oracle = OracleConflictSet(0)
    seed = [_txn(writes=[(b"h", b"h\x00")])]
    sup.resolve(list(seed), 10)
    oracle.resolve(list(seed), 10)
    batch = [_txn(reads=[(b"a", b"b"), (b"h", b"h\x00"), (b"x", b"y")],
                  snap=5, report=True)]
    got_v, got_r = sup.resolve_with_conflicts(list(batch), 20)
    want_v, want_r = oracle.resolve_with_conflicts(list(batch), 20)
    assert got_v == want_v == [CommitResult.CONFLICT]
    assert got_r == want_r == {0: [(b"h", b"h\x00")]}
    assert sup.stats["device_batches"] > 0   # not a mirror fallback


def test_supervisor_attribution_disabled_by_master_knob(knobs):
    from test_conflict_supervisor import make_tpu
    knobs.HEAT_TELEMETRY_ENABLED = False
    sup = SupervisedConflictSet(make_tpu)
    sup.resolve([_txn(writes=[(b"h", b"h\x00")])], 10)
    verdicts, _ = sup.resolve_with_conflicts(
        [_txn(reads=[(b"h", b"h\x00")], snap=5)], 20)
    assert verdicts == [CommitResult.CONFLICT]
    assert sup.last_attribution == {}
    assert sup.stats["exact_attribution"] == 0
    assert sup.stats["conservative_attribution"] == 1


# ---------------------------------------------------------------------------
# Resolver: unified sample table + heat feed
# ---------------------------------------------------------------------------

def test_resolver_feed_and_split_unified(loop):
    """One table serves both: _sample_batch load lands in the split
    projection; aborted txns land in the conflict column with tenant/tag
    breakdowns (fed from the backend's attribution)."""
    from foundationdb_tpu.server.resolver import Resolver
    r = Resolver("r-heat", backend="cpu")
    seed = [_txn(writes=[(b"hot", b"hot\x00")])]
    committed, _ = r.conflict_set.resolve_with_conflicts(seed, 10)
    r._sample_batch(seed)
    batch = [_txn(reads=[(b"hot", b"hot\x00")], snap=5, tenant=7,
                  tag="t/web"),
             _txn(reads=[(b"cold", b"cold\x00")],
                  writes=[(b"cold", b"cold\x00")], snap=15)]
    committed, _ = r.conflict_set.resolve_with_conflicts(batch, 20)
    assert committed == [CommitResult.CONFLICT, CommitResult.COMMITTED]
    r._sample_batch(batch)
    r._record_conflict_heat(batch, committed, r.conflict_set, 1)
    top = r.heat.top_conflicts(4)
    assert top[0][:3] == (b"hot", b"hot\x00", 1)
    assert r.heat.tenants == {7: 1}
    assert r.heat.tags == {"t/web": 1}
    assert r.metrics.counter("HeatConflictRanges").value == 1
    # Load sampling (every 8th range) feeds the same table the split
    # server projects; force enough mass to show up.
    for _ in range(32):
        r._sample_batch(batch)
    assert any(b == b"cold" for b, _v in r.heat.split_load(b"", b"\xff"))
    doc = r.heat_status()
    assert doc["top_conflict_ranges"][0]["begin"] == "hot"


def test_resolver_feed_respects_master_knob(loop, knobs):
    from foundationdb_tpu.server.resolver import Resolver
    knobs.HEAT_TELEMETRY_ENABLED = False
    r = Resolver("r-off", backend="cpu")
    seed = [_txn(writes=[(b"hot", b"hot\x00")])]
    r.conflict_set.resolve_with_conflicts(seed, 10)
    batch = [_txn(reads=[(b"hot", b"hot\x00")], snap=5)]
    committed, _ = r.conflict_set.resolve_with_conflicts(batch, 20)
    r._record_conflict_heat(batch, committed, r.conflict_set, 1)
    assert r.heat.top_conflicts(1) == []


# ---------------------------------------------------------------------------
# End to end: planted hot range -> status == special keys == fdbcli top
# ---------------------------------------------------------------------------

def _drive_conflicts(db, n=6, key=b"hotkey", tag="hot-tag"):
    """n read-modify-write pairs on `key`; the second txn of each pair
    aborts (its snapshot predates the first's commit)."""
    async def go():
        aborted = 0
        for i in range(n):
            t1 = db.create_transaction()
            t2 = db.create_transaction()
            t2.tag = tag
            await t1.get(key)
            await t2.get(key)
            t1.set(key, b"a%d" % i)
            t2.set(key, b"b%d" % i)
            await t1.commit()
            try:
                await t2.commit()
            except FdbError as e:
                assert e.name == "not_committed", e.name
                aborted += 1
        return aborted
    return go


def test_e2e_hot_range_all_three_surfaces(teardown):  # noqa: F811
    from foundationdb_tpu.core.trace import Tracer, get_tracer, set_tracer
    from foundationdb_tpu.tools.fdbcli import Cli
    set_tracer(Tracer())
    c = make_cluster()
    db = c.database()
    aborted = c.run_until(c.loop.spawn(_drive_conflicts(db)()), timeout=120)
    assert aborted >= 4   # the planted hot range really conflicted

    async def read_surfaces():
        # A burst of reads makes the hosting shard read-hot too.
        t = db.create_transaction()
        for _ in range(200):
            await t.get(b"hotkey", snapshot=True)
        # Let the heat emission cadence tick at least once.
        from foundationdb_tpu.core.scheduler import delay
        await delay(2 * float(server_knobs().METRICS_EMIT_INTERVAL))
        doc = await db.cluster.get_status()
        t2 = db.create_transaction()
        rows = await t2.get_range(b"\xff\xff/metrics/conflict_ranges/",
                                  b"\xff\xff/metrics/conflict_ranges0",
                                  limit=100)
        hot_rows = await t2.get_range(b"\xff\xff/metrics/read_hot_ranges/",
                                      b"\xff\xff/metrics/read_hot_ranges0",
                                      limit=100)
        point = None
        if rows:
            t3 = db.create_transaction()
            point = await t3.get(rows[0][0])
        return doc, rows, hot_rows, point

    doc, rows, hot_rows, point = c.run_until(
        c.loop.spawn(read_surfaces()), timeout=120)

    # 1. status cluster.heat names the planted range on some resolver.
    heat = doc["cluster"]["heat"]
    tops = [row for rdoc in heat["conflict_ranges"].values()
            for row in rdoc["top_conflict_ranges"]]
    assert any(row["begin"] == "hotkey" for row in tops), tops
    assert any(t["tag"] == "hot-tag" for t in heat["busiest_tags"])
    # 2. the special-key mirror agrees (same doc, row per range).
    assert rows, "conflict_ranges special keys empty"
    parsed = [json.loads(v) for _k, v in rows]
    assert any(r["begin"] == "hotkey" for r in parsed), parsed
    assert point == rows[0][1]   # point get == range row
    # ... and the read-hot module reports the hosting shard.
    assert hot_rows, "read_hot_ranges special keys empty"
    hot_parsed = [json.loads(v) for _k, v in hot_rows]
    assert all(r["read_ops_per_sec"] > 0 for r in hot_parsed)
    assert heat["read_hot_ranges"], heat
    # 3. fdbcli top renders the same tables.
    cli = Cli.__new__(Cli)
    cli.loop, cli.db = c.loop, db
    out = cli.dispatch("top")
    assert "hotkey" in out and "Read-hot shards" in out
    assert "hot-tag" in out
    # The resolver ALSO emitted HotConflictRange trace events.
    evs = get_tracer().find("HotConflictRange")
    assert any(e.get("Begin") == "b'hotkey'" or "hotkey" in str(e.get(
        "Begin")) for e in evs), evs[:3]


def test_commit_conflict_detail_in_waterfall(teardown):  # noqa: F811
    """Satellite 3: a debug-tagged aborted txn gets a
    CommitConflictDetail event naming its conflicting ranges and the
    attribution mode, and commit_debug surfaces it."""
    from foundationdb_tpu.core.trace import Tracer, get_tracer, set_tracer
    from foundationdb_tpu.tools.commit_debug import conflict_details
    set_tracer(Tracer())
    c = make_cluster()
    db = c.database()

    async def go(debug_id, report):
        t1 = db.create_transaction()
        t2 = db.create_transaction()
        t2.debug_id = debug_id
        t2.report_conflicting_keys = report
        await t1.get(b"ck")
        await t2.get(b"ck")
        await t2.get(b"other")       # extra clean read (over-blame bait)
        t1.set(b"ck", b"1")
        t2.set(b"ck", b"2")
        await t1.commit()
        try:
            await t2.commit()
            return False
        except FdbError as e:
            return e.name == "not_committed"

    assert c.run_until(c.loop.spawn(go("dbg-exact", True)), timeout=120)
    assert c.run_until(c.loop.spawn(go("dbg-cons", False)), timeout=120)
    details = conflict_details(list(get_tracer().ring))
    assert "dbg-exact" in details and "dbg-cons" in details, [
        e for e in get_tracer().ring
        if e.get("Type") == "CommitConflictDetail"]
    # Reporter: the resolver-pinned TRUE culprit only — exact.
    d = details["dbg-exact"]
    assert "ck" in d["ranges"] and "other" not in d["ranges"]
    assert d["exact"] is True
    # Non-reporter: the proxy falls back to the whole read set —
    # conservative, and marked as such.
    d = details["dbg-cons"]
    assert "ck" in d["ranges"] and "other" in d["ranges"]
    assert d["exact"] is False


# ---------------------------------------------------------------------------
# Determinism: the heat plane under the unseed verifier
# ---------------------------------------------------------------------------

HEAT_SPEC = """
[[test]]
testTitle = 'HeatDeterminism'

  [[test.workload]]
  testName = 'Cycle'
  nodeCount = 8
  actorCount = 4
  testDuration = 6.0
"""


def test_heat_plane_double_run_unseed_identical(teardown):  # noqa: F811
    """Same seed, two runs, with the heat plane active (sampling, decay,
    HotConflictRange/ReadHotShard emission all inside the sim): unseed,
    digest and fold counts must be bit-identical.  testDuration exceeds
    METRICS_EMIT_INTERVAL so the emission cadence is inside the digest."""
    from foundationdb_tpu.testing import run_test_twice
    r1, r2 = run_test_twice(HEAT_SPEC, seed=211)
    assert r1.unseed == r2.unseed and r1.digest == r2.digest
    assert r1.folds == r2.folds and r1.folds > 0
    assert r1.nondeterminism == [] and r2.nondeterminism == []


def test_metrics_rows_distinct_for_shared_begin():
    """Two hot ranges sharing a begin key ([a,b) and [a,c)) must stay
    distinct special-key rows — the row key embeds begin AND end."""
    from foundationdb_tpu.client.database import Transaction
    heat = {"conflict_ranges": {"r0": {"top_conflict_ranges": [
        {"begin": "a", "end": "b", "begin_hex": "61", "end_hex": "62",
         "conflicts": 3, "load": 0},
        {"begin": "a", "end": "c", "begin_hex": "61", "end_hex": "63",
         "conflicts": 2, "load": 0}]}},
        "read_hot_ranges": {"5": [
            {"begin": "a", "end": "b", "begin_hex": "61", "end_hex": "62",
             "read_ops_per_sec": 9.0, "read_bytes_per_sec": 1.0,
             "storage_server": "ss5"}]}}
    rows = Transaction._heat_rows(Transaction.__new__(Transaction), heat)
    keys = [k for k, _v in rows]
    assert len(keys) == len(set(keys)) == 3
    assert keys == sorted(keys)
    assert json.loads(dict(rows)[
        b"\xff\xff/metrics/conflict_ranges/r0/61-63"])["conflicts"] == 2


def test_collect_heat_busiest_folds_full_tables():
    """Cluster-wide busiest tags/tenants fold the resolvers' FULL
    decayed tables: a tag below every per-resolver top-K cut can still
    be the cluster's busiest."""
    from types import SimpleNamespace

    from foundationdb_tpu.server.status import collect_heat

    def fake_resolver(rid, tags):
        heat = ConflictHeatTracker()
        for tag, n in tags.items():
            for _ in range(n):
                heat.record_conflict(b"k", b"k\x00", tag=tag)
        role = SimpleNamespace(id=rid, heat=heat,
                               heat_status=lambda h=heat: h.to_status(2))
        return SimpleNamespace(role=role)

    # "bg" ranks 3rd (below k=2... but cluster-wide it dominates: 4+4+4
    # vs "a0".. peaking at 5 on one resolver only.
    resolvers = [
        fake_resolver("r0", {"x0": 9, "y0": 8, "bg": 4}),
        fake_resolver("r1", {"x1": 9, "y1": 8, "bg": 4}),
        fake_resolver("r2", {"x2": 9, "y2": 8, "bg": 4}),
    ]
    info = SimpleNamespace(resolvers=resolvers)
    doc = collect_heat(info, {})
    busiest = doc["busiest_tags"]
    assert busiest[0] == {"tag": "bg", "conflicts": 12}, busiest
