"""TLog spill-by-reference + memory backpressure.

Reference: fdbserver/TLogServer.actor.cpp:293 (TLogData spill fields) and
:1584 (tLogPeekMessages serving spilled tags from the DiskQueue).  VERDICT
round-3 item 6 done-criteria: stall a storage server's pulls, push many
times the memory limit, TLog memory stays bounded, and the stalled tag
catches up afterward (peeks return everything, served from disk).
"""

import pytest

from foundationdb_tpu.core.knobs import server_knobs
from foundationdb_tpu.core.futures import Promise
from foundationdb_tpu.server.disk_queue import DiskQueue
from foundationdb_tpu.server.interfaces import (TLogCommitRequest,
                                                TLogPeekRequest,
                                                TLogPopRequest)
from foundationdb_tpu.server.sim_fs import SimFileSystem
from foundationdb_tpu.server.tlog import TLog
from foundationdb_tpu.txn.types import Mutation, MutationType

from test_recovery import teardown  # noqa: F401


def _world():
    from foundationdb_tpu.core import EventLoop, set_event_loop
    lp = EventLoop(sim=True)
    set_event_loop(lp)
    return lp


async def _commit(tlog, version, prev, messages):
    p = Promise()
    await tlog._commit(TLogCommitRequest(
        version=version, prev_version=prev, known_committed_version=prev,
        messages=messages, reply=p))
    return await p.get_future()


def test_stalled_tag_spills_and_catches_up(teardown):  # noqa: F811
    knobs = server_knobs()
    old = knobs.TLOG_SPILL_THRESHOLD
    knobs.TLOG_SPILL_THRESHOLD = 50_000
    try:
        lp = _world()
        fs = SimFileSystem()
        tlog = TLog("spill-test", disk_queue=DiskQueue(fs.open("t.wal")))

        async def go():
            payload = b"x" * 1000
            # Tag 0 is STALLED (never pops); tag 1 pops along.  Push ~500KB
            # = 10x the 50KB memory limit.
            v = 0
            for i in range(500):
                prev, v = v, v + 1
                await _commit(tlog, v, prev, {
                    0: [Mutation(MutationType.SetValue,
                                 b"k%04d" % i, payload)],
                    1: [Mutation(MutationType.SetValue,
                                 b"j%04d" % i, b"small")],
                })
                tlog._pop(TLogPopRequest(tag=1, to=v))
            # Memory stayed bounded despite the 500KB backlog on tag 0.
            assert tlog.bytes_in_memory <= 60_000, tlog.bytes_in_memory
            assert tlog.bytes_spilled > 300_000, tlog.bytes_spilled
            assert tlog.spilled.get(0), "nothing was spilled by reference"
            # The stalled tag catches up: a peek from the beginning returns
            # EVERY version, the spilled prefix served from the queue file.
            p = Promise()
            await tlog._peek(TLogPeekRequest(tag=0, begin=1, reply=p))
            reply = await p.get_future()
            versions = [v for v, _m in reply.messages]
            assert versions == list(range(1, 501)), (
                f"missing versions: got {len(versions)}")
            payloads_ok = all(
                m[0].param2 == payload for _v, m in reply.messages)
            assert payloads_ok
            # After the laggard pops, spilled refs and disk records trim.
            tlog._pop(TLogPopRequest(tag=0, to=500))
            assert not tlog.spilled.get(0)
            assert tlog.bytes_in_memory <= 1000, tlog.bytes_in_memory
            return True

        assert lp.run_until(lp.spawn(go()), timeout=120)
    finally:
        knobs.TLOG_SPILL_THRESHOLD = old


def test_peek_paginates_by_bytes(teardown):  # noqa: F811
    """A catch-up peek of a large spilled backlog is paged by
    TLOG_PEEK_DESIRED_BYTES (reference tLogPeekMessages DESIRED_TOTAL_BYTES):
    each reply stays under the budget (plus one entry), end/max_known_version
    point at the cut so the puller re-peeks for the rest, and following
    reply.end reconstructs the full stream with no gaps or duplicates."""
    knobs = server_knobs()
    old_spill = knobs.TLOG_SPILL_THRESHOLD
    old_peek = knobs.TLOG_PEEK_DESIRED_BYTES
    knobs.TLOG_SPILL_THRESHOLD = 50_000
    knobs.TLOG_PEEK_DESIRED_BYTES = 20_000
    try:
        lp = _world()
        fs = SimFileSystem()
        tlog = TLog("page-test", disk_queue=DiskQueue(fs.open("t.wal")))

        async def go():
            payload = b"x" * 1000
            v = 0
            for i in range(300):
                prev, v = v, v + 1
                await _commit(tlog, v, prev, {
                    0: [Mutation(MutationType.SetValue,
                                 b"k%04d" % i, payload)]})
            assert tlog.bytes_spilled > 0
            got = []
            begin = 1
            rounds = 0
            while True:
                p = Promise()
                await tlog._peek(TLogPeekRequest(tag=0, begin=begin, reply=p))
                reply = await p.get_future()
                nbytes = sum(m.expected_size()
                             for _v, msgs in reply.messages for m in msgs)
                # Budget + at most one overshooting entry.
                assert nbytes <= 20_000 + 2000, nbytes
                got.extend(v for v, _m in reply.messages)
                if reply.end > 300:
                    break
                # Truncated replies must not let the puller skip ahead.
                assert reply.max_known_version == reply.end - 1
                begin = reply.end
                rounds += 1
                assert rounds < 100
            assert got == list(range(1, 301)), (len(got), got[:5], got[-5:])
            assert rounds >= 5, f"never paginated (rounds={rounds})"
            return True

        assert lp.run_until(lp.spawn(go()), timeout=120)
    finally:
        knobs.TLOG_SPILL_THRESHOLD = old_spill
        knobs.TLOG_PEEK_DESIRED_BYTES = old_peek


def test_spill_survives_reboot(teardown):  # noqa: F811
    """Spilled data lives in the DiskQueue, so a rebooted TLog recovers it
    like any other record (from_disk replays the whole surviving queue)."""
    knobs = server_knobs()
    old = knobs.TLOG_SPILL_THRESHOLD
    knobs.TLOG_SPILL_THRESHOLD = 10_000
    try:
        lp = _world()
        fs = SimFileSystem()
        tlog = TLog("spill-reboot", disk_queue=DiskQueue(fs.open("t.wal")))

        async def phase1():
            v = 0
            for i in range(100):
                prev, v = v, v + 1
                await _commit(tlog, v, prev, {
                    0: [Mutation(MutationType.SetValue,
                                 b"k%04d" % i, b"y" * 500)]})
            assert tlog.bytes_spilled > 0
            return True

        assert lp.run_until(lp.spawn(phase1()), timeout=60)
        fs.power_fail_all()

        async def phase2():
            t2 = await TLog.from_disk("spill-reboot",
                                      DiskQueue(fs.open("t.wal")))
            p = Promise()
            await t2._peek(TLogPeekRequest(tag=0, begin=1, reply=p))
            reply = await p.get_future()
            # Every acked version survived (commit acks only after fsync).
            assert [v for v, _m in reply.messages] == list(range(1, 101))
            return True

        assert lp.run_until(lp.spawn(phase2()), timeout=60)
    finally:
        knobs.TLOG_SPILL_THRESHOLD = old
