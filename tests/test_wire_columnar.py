"""Columnar hot-RPC wire frames (ISSUE 14, rpc/serde.py).

Golden-guards BOTH formats of the three hot commit-pipeline messages —
the knobs-off LEGACY image must never move (mixed-version clusters
depend on it; sha256-frozen like the PR-12 reply-bytes guard) and the
columnar image is frozen as full hex — plus mixed-format interop (a
columnar encoder talking to a decoder whose own knob is off, and vice
versa, through a real resolve), prefix-truncation edge cases, the
legacy fallback for payload shapes outside the codec vocabulary, and
the Encode/Decode observability counters."""

import hashlib

import pytest

from foundationdb_tpu.core.knobs import server_knobs
from foundationdb_tpu.rpc import serde
from foundationdb_tpu.server.interfaces import (
    ResolveTransactionBatchReply, ResolveTransactionBatchRequest,
    TLogCommitRequest)
from foundationdb_tpu.txn.types import (CommitResult, CommitTransactionRef,
                                        KeyRange, Mutation, MutationType)

serde.bootstrap_registry()


@pytest.fixture()
def columnar_knob():
    k = server_knobs()
    saved = k.RPC_COLUMNAR_ENABLED
    yield k
    k.RPC_COLUMNAR_ENABLED = saved


def canonical_request():
    txns = []
    for i in range(4):
        k = b"golden/%04d" % i
        txns.append(CommitTransactionRef(
            read_conflict_ranges=[KeyRange(k, k + b"\x00")],
            write_conflict_ranges=[KeyRange(k + b"/w", k + b"/w\x00")],
            mutations=[Mutation(MutationType.SetValue, k + b"/w", b"v" * 8)],
            read_snapshot=900 + i,
            report_conflicting_keys=(i % 2 == 0),
            tenant_id=(7 if i == 3 else -1),
            tag=("hot" if i == 1 else "")))
    return ResolveTransactionBatchRequest(
        prev_version=900, version=1000, last_received_version=800,
        transactions=txns, txn_state_transactions=[2],
        proxy_id="proxy0", span="golden-span")


def canonical_reply():
    return ResolveTransactionBatchReply(
        committed=[CommitResult.COMMITTED, CommitResult.CONFLICT,
                   CommitResult.TOO_OLD, CommitResult.COMMITTED],
        conflicting_ranges={1: [(b"golden/0001", b"golden/0001\x00")]},
        attribution_exact={1: True},
        state_transactions=[(1000, "proxy1", 0,
                             [Mutation(MutationType.SetValue,
                                       b"\xff/g", b"1")],
                             CommitResult.COMMITTED)])


def canonical_commit_request():
    from foundationdb_tpu.server.interfaces import CommitTransactionRequest
    return CommitTransactionRequest(
        transaction=CommitTransactionRef(
            read_conflict_ranges=[KeyRange(b"golden/r", b"golden/r\x00")],
            write_conflict_ranges=[KeyRange(b"golden/w", b"golden/w\x00")],
            mutations=[Mutation(MutationType.SetValue, b"golden/w",
                                b"v" * 8),
                       Mutation(MutationType.AddValue, b"golden/ctr",
                                b"\x01")],
            read_snapshot=12345, tag="hot"),
        debug_id="dbg-7", repair_eligible=True, repair_attempt=1)


def canonical_tlog():
    return TLogCommitRequest(
        prev_version=900, version=1000, known_committed_version=850,
        messages={0: [Mutation(MutationType.SetValue,
                               b"golden/%04d" % i, b"v" * 8)
                      for i in range(3)],
                  0xFFFFFFFE: [Mutation(MutationType.SetValue,
                                        b"\xff/keyServers/golden", b"t")]},
        span="golden-span")


# Frozen wire images.  The LEGACY sha256 is the knobs-off guard: any
# byte change breaks mixed-version clusters mid-rollout.  The COLUMNAR
# hex freezes format version 1 end to end.
REQ_LEGACY_SHA = \
    "dacbdc9111cb9a9b59a95c1b07097676ce0a0f6edbad872b536becccb018aa08"
REPLY_LEGACY_SHA = \
    "e99e1d2c735bd71ef94b5f61ff8a4019e083d8a07fffdfac5add9d6869568d97"
TLOG_LEGACY_SHA = \
    "c2d534147c3fa97582fedb57dacbbe6d153856a69332f408a05145cb34ca1c50"

REQ_COLUMNAR_HEX = (
    "121e0000005265736f6c76655472616e73616374696f6e426174636852657175"
    "65737401880ed00fc00c0670726f7879300b676f6c64656e2d7370616e010204"
    "01c80101010108c60101010103686f7401c40101010104c2010101010e000000"
    "0018000b676f6c64656e2f303030300b01000b022f770d01000d000008767676"
    "7676767676000b676f6c64656e2f303030310b01000b022f770d01000d000008"
    "7676767676767676000b676f6c64656e2f303030320b01000b022f770d01000d"
    "0000087676767676767676000b676f6c64656e2f303030330b01000b022f770d"
    "01000d0000087676767676767676"
)
REPLY_COLUMNAR_HEX = (
    "121c0000005265736f6c76655472616e73616374696f6e42617463685265706c"
    "79010402000102010101010302000b676f6c64656e2f303030310b0100080100"
    "0000090500000003e803000000000000070600000070726f7879310300000000"
    "0000000008010000000b080000004d75746174696f6e03000000040000007479"
    "7065100c0000004d75746174696f6e5479706503000000000000000006000000"
    "706172616d310603000000ff2f6706000000706172616d32060100000031100c"
    "000000436f6d6d6974526573756c74030200000000000000"
)
TLOG_COLUMNAR_HEX = (
    "1211000000544c6f67436f6d6d69745265717565737401880ed00fa40d0b676f"
    "6c64656e2d7370616e020003fcffffff1f010000000008000b676f6c64656e2f"
    "3030303000087676767676767676000b676f6c64656e2f303030310008767676"
    "7676767676000b676f6c64656e2f30303032000876767676767676760013ff2f"
    "6b6579536572766572732f676f6c64656e000174"
)
CREQ_LEGACY_SHA = \
    "88d3853d6658b53412cb6d65f8f369d61c27da09e6621770b870d4744a83c78b"
CREQ_COLUMNAR_HEX = (
    "1218000000436f6d6d69745472616e73616374696f6e52657175657374010301"
    "056462672d3708f1c00101010203686f740002080008676f6c64656e2f720801"
    "00070177080100080000087676767676767676000a676f6c64656e2f63747200"
    "0101"
)


def _encode(obj, columnar: bool) -> bytes:
    k = server_knobs()
    saved = k.RPC_COLUMNAR_ENABLED
    k.RPC_COLUMNAR_ENABLED = columnar
    try:
        return serde.encode_message(obj)
    finally:
        k.RPC_COLUMNAR_ENABLED = saved


# ---------------------------------------------------------------------------
# Goldens
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("make,legacy_sha,columnar_hex", [
    (canonical_request, REQ_LEGACY_SHA, REQ_COLUMNAR_HEX),
    (canonical_reply, REPLY_LEGACY_SHA, REPLY_COLUMNAR_HEX),
    (canonical_tlog, TLOG_LEGACY_SHA, TLOG_COLUMNAR_HEX),
    (canonical_commit_request, CREQ_LEGACY_SHA, CREQ_COLUMNAR_HEX),
], ids=["request", "reply", "tlog", "commit"])
def test_wire_goldens(make, legacy_sha, columnar_hex):
    obj = make()
    legacy = _encode(obj, columnar=False)
    assert legacy[0] == serde.T_DATACLASS
    assert hashlib.sha256(legacy).hexdigest() == legacy_sha, \
        "knobs-off wire image CHANGED — mixed-version clusters break"
    col = _encode(obj, columnar=True)
    assert col[0] == serde.T_COLUMNAR
    assert col.hex() == columnar_hex, \
        "columnar frame format CHANGED — bump _COLUMNAR_VERSION instead"
    # Both decode to the identical object.
    assert serde.decode_message(legacy) == obj
    assert serde.decode_message(col) == obj
    # And columnar is actually smaller (the point of the format).
    assert len(col) < len(legacy)


# ---------------------------------------------------------------------------
# Mixed-format interop
# ---------------------------------------------------------------------------

def test_mixed_format_interop_resolver(columnar_knob):
    """A columnar-encoding proxy talks to a resolver whose own knob is
    OFF (decode is format-transparent), and a legacy proxy talks to a
    columnar-enabled resolver — verdicts identical both ways."""
    from foundationdb_tpu.conflict.oracle import OracleConflictSet
    req = canonical_request()
    # New encoder -> legacy-posture decoder.
    blob = _encode(req, columnar=True)
    columnar_knob.RPC_COLUMNAR_ENABLED = False
    decoded_a = serde.decode_message(blob)
    # Legacy encoder -> columnar-posture decoder.
    blob = _encode(req, columnar=False)
    columnar_knob.RPC_COLUMNAR_ENABLED = True
    decoded_b = serde.decode_message(blob)
    columnar_knob.RPC_COLUMNAR_ENABLED = False
    assert decoded_a == decoded_b == req
    va = OracleConflictSet(0).resolve(decoded_a.transactions, 1000, 0)
    vb = OracleConflictSet(0).resolve(decoded_b.transactions, 1000, 0)
    assert va == vb


def test_mixed_format_reply_direction(columnar_knob):
    rep = canonical_reply()
    blob = _encode(rep, columnar=True)
    columnar_knob.RPC_COLUMNAR_ENABLED = False
    assert serde.decode_message(blob) == rep
    blob = _encode(rep, columnar=False)
    columnar_knob.RPC_COLUMNAR_ENABLED = True
    assert serde.decode_message(blob) == rep


# ---------------------------------------------------------------------------
# Codec edge cases
# ---------------------------------------------------------------------------

def test_columnar_edge_payloads(columnar_knob):
    """Empty batch, empty keys, 100KB values (> u16), huge/negative
    versions and tenant ids, all mutation types, empty tag maps."""
    cases = [
        ResolveTransactionBatchRequest(
            prev_version=0, version=0, last_received_version=-1,
            transactions=[], proxy_id="", span=""),
        ResolveTransactionBatchRequest(
            prev_version=(1 << 60), version=(1 << 60) + 5,
            last_received_version=-(1 << 40),
            transactions=[CommitTransactionRef(
                read_conflict_ranges=[KeyRange(b"", b"\xff\xff")],
                write_conflict_ranges=[],
                mutations=[Mutation(MutationType.ClearRange, b"",
                                    b"\xff" * 300),
                           Mutation(MutationType.CompareAndClear,
                                    b"k", b"v" * 100_000)],
                read_snapshot=(1 << 60) + 9,   # above version: zigzag
                tenant_id=(1 << 40))],
            proxy_id="p"),
        TLogCommitRequest(
            prev_version=1, version=2, known_committed_version=0,
            messages={}, span=""),
        TLogCommitRequest(
            prev_version=1, version=2, known_committed_version=0,
            messages={0xFFFFFFFE: [], 3: [Mutation(
                MutationType.AddValue, b"\x00" * 64, b"")]}),
        ResolveTransactionBatchReply(committed=[]),
    ]
    for obj in cases:
        col = _encode(obj, columnar=True)
        leg = _encode(obj, columnar=False)
        assert serde.decode_message(col) == obj
        assert serde.decode_message(leg) == obj


def test_columnar_shared_prefix_compresses(columnar_knob):
    """Keys sharing long prefixes shrink dramatically — the whole point
    of the prefix-truncated key stream."""
    prefix = b"tenant/0000000001/table/users/row/"
    txns = [CommitTransactionRef(
        read_conflict_ranges=[KeyRange(prefix + b"%09d" % i,
                                       prefix + b"%09d\x00" % i)],
        write_conflict_ranges=[],
        mutations=[], read_snapshot=10) for i in range(64)]
    req = ResolveTransactionBatchRequest(
        prev_version=9, version=10, last_received_version=8,
        transactions=txns, proxy_id="p")
    col = _encode(req, columnar=True)
    leg = _encode(req, columnar=False)
    assert serde.decode_message(col) == req
    assert len(col) * 5 < len(leg), (len(col), len(leg))


def test_columnar_fallback_for_foreign_shapes(columnar_knob):
    """A reply whose conflicting ranges are NOT plain (bytes, bytes)
    tuples falls back to the legacy format transparently (the codec
    must never ship bytes it cannot reproduce)."""
    rep = ResolveTransactionBatchReply(
        committed=[CommitResult.CONFLICT],
        conflicting_ranges={0: [KeyRange(b"a", b"b")]},   # KeyRange, not tuple
        attribution_exact={0: True})
    blob = _encode(rep, columnar=True)
    assert blob[0] == serde.T_DATACLASS   # fell back
    assert serde.decode_message(blob) == rep


def test_unknown_columnar_version_rejected(columnar_knob):
    blob = bytearray(_encode(canonical_tlog(), columnar=True))
    # name is length-prefixed after the tag; the version byte follows.
    name_len = int.from_bytes(blob[1:5], "little")
    blob[5 + name_len] = 99
    from foundationdb_tpu.core.error import FdbError
    with pytest.raises(FdbError):
        serde.decode_message(bytes(blob))


def test_prefix_len_unit():
    from foundationdb_tpu.rpc.serde import _prefix_len
    assert _prefix_len(b"", b"abc") == 0
    assert _prefix_len(b"abc", b"abc") == 3
    assert _prefix_len(b"abc", b"abd") == 2
    assert _prefix_len(b"abc", b"abcdef") == 3
    assert _prefix_len(b"xbc", b"abc") == 0
    assert _prefix_len(b"a" * 1000, b"a" * 999 + b"b") == 999


def test_encode_decode_bands_recorded(columnar_knob):
    """The Rpc collection's Encode/Decode histograms + frame counters
    move for hot types in BOTH formats (e2e stage attribution feed)."""
    col = serde._rpc_collection()
    base_cf = col.counter("ColumnarFrames").value
    base_lf = col.counter("LegacyFrames").value
    enc0 = col.histogram("Encode").snapshot().count
    dec0 = col.histogram("Decode").snapshot().count
    req = canonical_request()
    serde.decode_message(_encode(req, columnar=True))
    serde.decode_message(_encode(req, columnar=False))
    assert col.counter("ColumnarFrames").value == base_cf + 1
    assert col.counter("LegacyFrames").value == base_lf + 1
    assert col.histogram("Encode").snapshot().count == enc0 + 2
    assert col.histogram("Decode").snapshot().count == dec0 + 2


# ---------------------------------------------------------------------------
# Read-path wire frames (ISSUE 15): goldens + mixed-format interop
# ---------------------------------------------------------------------------

def canonical_gkv_request():
    from foundationdb_tpu.server.interfaces import GetKeyValuesRequest
    return GetKeyValuesRequest(
        begin=b"golden/table/row/0010", end=b"golden/table/row/0450",
        version=1000, limit=250, limit_bytes=1 << 20, reverse=False,
        tag="hot")


def canonical_gkv_reply():
    from foundationdb_tpu.server.interfaces import GetKeyValuesReply
    return GetKeyValuesReply(
        data=[(b"golden/table/row/%04d" % i, b"val-%04d" % i)
              for i in range(10, 16)], more=True, version=1000)


def canonical_gv_reply():
    from foundationdb_tpu.server.interfaces import GetValueReply
    return GetValueReply(value=b"payload-bytes", version=1000)


GKV_REQ_LEGACY_SHA = \
    "8d4ec1e98461660dc524fbc4615daf8dda904eacbc46816f9653b4054150fced"
GKV_REQ_COLUMNAR_HEX = (
    "12130000004765744b657956616c756573526571756573740102d00ffa018080"
    "4015676f6c64656e2f7461626c652f726f772f30303130120334353003686f74"
)
GKV_REPLY_LEGACY_SHA = \
    "285ac4303d4f01cc31a6fa0f48931e0fa31d0ed9ecf83464af56448a2634babf"
# NOTE the version byte 0x02 after the name: the read-reply family is
# format v2 (keys stream + value-length column; v1 interleaved values
# into the key stream and is REJECTED, not misdecoded).
GKV_REPLY_COLUMNAR_HEX = (
    "12110000004765744b657956616c7565735265706c790201d00f060015676f6c"
    "64656e2f7461626c652f726f772f303031301401311401321401331401341401"
    "3508080808080876616c2d3030313076616c2d3030313176616c2d3030313276"
    "616c2d3030313376616c2d3030313476616c2d30303135"
)
GV_REPLY_LEGACY_SHA = \
    "bf82db7996d802a7c29842812c5911a6546289f54eb51ff1210d5219e3898690"
GV_REPLY_COLUMNAR_HEX = (
    "120d00000047657456616c75655265706c7901010d7061796c6f61642d627974"
    "6573d00f"
)


@pytest.mark.parametrize("make,legacy_sha,columnar_hex", [
    (canonical_gkv_request, GKV_REQ_LEGACY_SHA, GKV_REQ_COLUMNAR_HEX),
    (canonical_gkv_reply, GKV_REPLY_LEGACY_SHA, GKV_REPLY_COLUMNAR_HEX),
    (canonical_gv_reply, GV_REPLY_LEGACY_SHA, GV_REPLY_COLUMNAR_HEX),
], ids=["gkv_request", "gkv_reply", "gv_reply"])
def test_read_path_wire_goldens(make, legacy_sha, columnar_hex):
    obj = make()
    legacy = _encode(obj, columnar=False)
    assert legacy[0] == serde.T_DATACLASS
    assert hashlib.sha256(legacy).hexdigest() == legacy_sha, \
        "knobs-off wire image CHANGED — mixed-version clusters break"
    col = _encode(obj, columnar=True)
    assert col[0] == serde.T_COLUMNAR
    assert col.hex() == columnar_hex, \
        "columnar frame format CHANGED — bump the codec version instead"
    assert serde.decode_message(legacy) == obj
    assert serde.decode_message(col) == obj
    assert len(col) < len(legacy)


def test_read_reply_mixed_format_interop(columnar_knob):
    """Columnar storage -> legacy-posture client and vice versa: the
    decoded reply objects are identical both ways (decode is
    format-transparent, so the knob can flip per process mid-rollout)."""
    for make in (canonical_gkv_request, canonical_gkv_reply,
                 canonical_gv_reply):
        obj = make()
        blob = _encode(obj, columnar=True)
        columnar_knob.RPC_COLUMNAR_ENABLED = False
        decoded_a = serde.decode_message(blob)
        blob = _encode(obj, columnar=False)
        columnar_knob.RPC_COLUMNAR_ENABLED = True
        decoded_b = serde.decode_message(blob)
        columnar_knob.RPC_COLUMNAR_ENABLED = False
        assert decoded_a == decoded_b == obj


def test_read_reply_edge_payloads(columnar_knob):
    """Empty replies, empty keys/values, reverse-ordered rows, big
    values, huge versions — both formats round-trip identically."""
    from foundationdb_tpu.server.interfaces import (GetKeyValuesReply,
                                                    GetKeyValuesRequest,
                                                    GetValueReply)
    cases = [
        GetKeyValuesReply(data=[], more=False, version=0),
        GetKeyValuesReply(data=[(b"", b"")], more=True, version=-5),
        GetKeyValuesReply(
            data=[(b"k/%03d" % i, b"x" * 3000) for i in (5, 4, 3)],
            more=False, version=(1 << 60)),
        GetKeyValuesRequest(begin=b"", end=b"\xff\xff", version=0,
                            limit=1, limit_bytes=1),
        GetValueReply(value=None, version=7),
        GetValueReply(value=b"", version=7),
    ]
    for obj in cases:
        assert serde.decode_message(_encode(obj, columnar=True)) == obj
        assert serde.decode_message(_encode(obj, columnar=False)) == obj


def test_read_reply_v1_frame_rejected(columnar_knob):
    """A v1-stamped GetKeyValuesReply frame (the PR-14 interleaved
    layout) must be REJECTED loudly — misdecoding it as v2 would hand
    garbage rows to a transaction."""
    blob = bytearray(_encode(canonical_gkv_reply(), columnar=True))
    name_len = int.from_bytes(blob[1:5], "little")
    assert blob[5 + name_len] == 2
    blob[5 + name_len] = 1
    from foundationdb_tpu.core.error import FdbError
    with pytest.raises(FdbError):
        serde.decode_message(bytes(blob))


def test_read_reply_foreign_shape_falls_back(columnar_knob):
    """Rows that are not plain (bytes, bytes) fall back to the legacy
    format transparently (the codec never ships bytes it cannot
    reproduce)."""
    from foundationdb_tpu.server.interfaces import GetKeyValuesReply
    rep = GetKeyValuesReply(data=[("strkey", b"v")], more=False, version=1)
    blob = _encode(rep, columnar=True)
    assert blob[0] == serde.T_DATACLASS   # fell back
    assert serde.decode_message(blob) == rep
