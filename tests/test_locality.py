"""Locality-aware teams + replica load balancing (VERDICT r3 item 8).

Reference: fdbrpc/Locality.h (LocalityData), fdbrpc/ReplicationPolicy.h
(PolicyAcross zoneid), fdbrpc/LoadBalance.actor.h (replica selection with
failover).  Done-criteria: a zone kill keeps every shard available with
cross-zone teams; a single replica's death causes ZERO client errors.
"""

import pytest

from foundationdb_tpu.core.scheduler import delay
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration, zone_of

from test_recovery import commit_kv, read_key, teardown  # noqa: F401


def current_dd(cluster):
    cc = cluster.current_cc()
    if cc is None or cc.db_info.data_distributor is None:
        return None
    return getattr(cc.db_info.data_distributor, "role", None)


def test_cold_boot_teams_span_zones(teardown):  # noqa: F811
    # 4 storage workers in 2 zones, replication 2: every team must span
    # both zones — never two replicas in one failure zone.
    c = SimFdbCluster(config=DatabaseConfiguration(
        n_storage=4, storage_replication=2),
        n_workers=8, n_storage_workers=4, n_zones=2)
    db = c.database()

    async def go():
        await commit_kv(db, b"seed", b"1")
        dd = current_dd(c)
        seen_zones = set()
        for begin, _e, _t in dd.map.ranges():
            team = dd.map.lookup(begin)
            if not team:
                continue
            zones = [zone_of(dd.storage[t]) for t in team]
            assert len(set(zones)) == len(zones), (
                f"team {team} not zone-diverse: {zones}")
            seen_zones.update(zones)
        # Guard against vacuous passes: the localities must be the REAL
        # configured zones, not per-server fallback pseudo-zones.
        assert seen_zones == {"z0", "z1"}, seen_zones
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=60)


def test_zone_kill_keeps_all_shards_available(teardown):  # noqa: F811
    c = SimFdbCluster(config=DatabaseConfiguration(
        n_storage=4, storage_replication=2),
        n_workers=8, n_storage_workers=4, n_zones=2)
    db = c.database()

    async def go():
        for i in range(24):
            await commit_kv(db, b"zk/%03d" % i, b"v%03d" % i)
        await commit_kv(db, b"\x90far", b"v")
        # Kill EVERY process in zone z0 (two storage machines at once).
        c.sim.kill_zone("z0")
        # All data must stay readable from the surviving zone's replicas
        # (cross-zone teams guarantee one survivor per shard).
        for i in range(24):
            assert await read_key(db, b"zk/%03d" % i) == b"v%03d" % i
        assert await read_key(db, b"\x90far") == b"v"
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=120)


def test_replica_death_zero_client_errors(teardown):  # noqa: F811
    """Reads after one replica dies must succeed WITHOUT surfacing an
    error to the application — the client fails over inside the read
    (reference LoadBalance: transport errors choose another replica)."""
    c = SimFdbCluster(config=DatabaseConfiguration(
        n_storage=2, storage_replication=2),
        n_workers=6, n_storage_workers=2)
    db = c.database()

    async def go():
        for i in range(10):
            await commit_kv(db, b"rf/%03d" % i, b"v%03d" % i)
        dd = current_dd(c)
        victim = c.process_of(dd.storage[0])
        c.sim.kill_process(victim)
        # Direct gets with NO retry loop: any raised error fails the test.
        for i in range(10):
            t = db.create_transaction()
            v = await t.get(b"rf/%03d" % i)
            assert v == b"v%03d" % i
        # Range reads fail over too.
        t = db.create_transaction()
        rows = await t.get_range(b"rf/", b"rf0")
        assert len(rows) == 10
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=120)


def test_tlog_teams_zone_diverse(teardown):  # noqa: F811
    """Weak-spot fix (VERDICT r4 weak 8): TLog recruitment interleaves
    failure zones so the modular team mapping places a tag's log replicas
    in distinct zones (reference PolicyAcross(zoneid) for tlog teams)."""
    from foundationdb_tpu.core.scheduler import delay
    from foundationdb_tpu.server.cluster import SimFdbCluster
    from foundationdb_tpu.server.interfaces import DatabaseConfiguration
    c = SimFdbCluster(
        config=DatabaseConfiguration(n_tlogs=2, log_replication=2),
        n_workers=5, n_storage_workers=2)
    # Log-class workers concentrated two-per-zone: naive id ordering
    # would team both replicas into one zone.
    for i, z in enumerate(["zA", "zA", "zB", "zB"]):
        c.add_worker("log", name=f"logw{i}", zoneid=z)
    db = c.database()

    async def go():
        await commit_kv(db, b"zz", b"1")
        # Force a recovery so recruitment sees the log-class workers.
        mp = c.process_of(c.current_cc().db_info.master)
        epoch = c.current_cc().db_info.epoch
        c.sim.kill_process(mp)
        for _ in range(200):
            cc = c.current_cc()
            if cc is not None and cc.db_info.epoch > epoch and \
                    cc.db_info.recovery_state in ("accepting_commits",
                                                  "fully_recovered"):
                break
            await delay(0.25)
        await commit_kv(db, b"zz", b"2")
        tlogs = c.current_cc().db_info.tlogs
        assert len(tlogs) == 2
        zones = []
        for t in tlogs:
            p = c.process_of(t)
            zones.append(p.locality.zoneid)
        # A team is consecutive tlogs (mod n): with 2 tlogs the team IS
        # both — they must be in different zones.
        assert zones[0] != zones[1], zones
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=240)
