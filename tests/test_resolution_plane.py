"""Partitioned resolution plane (ISSUE 7): proxy fan-out range splitting,
\xff broadcast, empty-fragment version advance, N-resolver abort-set
parity, boundary seeding/persistence, and the multi-resolver bench sweep.

Reference shape: ResolutionRequestBuilder (CommitProxyServer.actor.cpp:88)
clips each transaction's conflict ranges per resolver via keyResolvers and
sends EVERY resolver every batch; the verdict is the min across resolvers;
system/metadata work reaches all resolvers."""

import random

import pytest

from foundationdb_tpu.core import FdbError
from foundationdb_tpu.core.futures import wait_all
from foundationdb_tpu.rpc.endpoint import RequestStream
from foundationdb_tpu.server.cluster import SimCluster, SimFdbCluster
from foundationdb_tpu.server.interfaces import (CommitTransactionRequest,
                                                DatabaseConfiguration,
                                                RESOLVER_ALL)
from foundationdb_tpu.server.master import (DBCoreState,
                                            _key_resolver_ranges,
                                            _valid_resolver_ranges,
                                            seed_resolver_boundaries)
from foundationdb_tpu.server.system_data import SYSTEM_KEYS_BEGIN
from foundationdb_tpu.txn.types import (CommitResult, CommitTransactionRef,
                                        KeyRange, Mutation, MutationType)


@pytest.fixture()
def teardown():
    from foundationdb_tpu.core import (DeterministicRandom,
                                       set_deterministic_random)
    set_deterministic_random(DeterministicRandom(7))
    yield
    from foundationdb_tpu.core import set_event_loop
    from foundationdb_tpu.rpc.sim import set_simulator
    set_simulator(None)
    set_event_loop(None)


def run(cluster, coro, timeout=60):
    return cluster.run_until(cluster.loop.spawn(coro), timeout=timeout)


def _txn(reads=(), writes=(), mutations=(), snapshot=0):
    return CommitTransactionRef(
        read_conflict_ranges=[KeyRange(b, e) for b, e in reads],
        write_conflict_ranges=[KeyRange(b, e) for b, e in writes],
        mutations=list(mutations), read_snapshot=snapshot)


def _reqs(proxy, txns, prev, version):
    batch = [CommitTransactionRequest(transaction=t) for t in txns]
    requests, index_maps = proxy._build_resolution_requests(
        batch, prev, version)
    return batch, requests, index_maps


# ---------------------------------------------------------------------------
# Range splitting at batch assembly
# ---------------------------------------------------------------------------

def test_fragment_straddles_boundary(teardown):
    """A conflict range spanning a resolver boundary is clipped into one
    fragment per owner; each owner sees exactly its part."""
    c = SimCluster(n_resolvers=2)
    p = c.commit_proxies[0]
    txns = [_txn(reads=[(b"a", b"\x90")],
                 writes=[(b"\xa0", b"\xa0\x00")])]
    _b, requests, index_maps = _reqs(p, txns, 0, 1000)
    assert len(requests) == 2
    # Resolver 0 owns [b"", b"\x80"): gets the clipped lower read part.
    r0 = requests[0].transactions[0]
    assert [(r.begin, r.end) for r in r0.read_conflict_ranges] == \
        [(b"a", b"\x80")]
    assert r0.write_conflict_ranges == []
    # Resolver 1 owns [b"\x80", \xff): upper read part + the write.
    r1 = requests[1].transactions[0]
    assert [(r.begin, r.end) for r in r1.read_conflict_ranges] == \
        [(b"\x80", b"\x90")]
    assert [(w.begin, w.end) for w in r1.write_conflict_ranges] == \
        [(b"\xa0", b"\xa0\x00")]
    assert index_maps[0] == [0] and index_maps[1] == [0]


def test_system_ranges_reach_all_resolvers(teardown):
    """\xff conflict ranges are owned by EVERY resolver (RESOLVER_ALL):
    even a mutation-free system read fans out to the whole plane, and a
    range spanning the user/system boundary reaches non-owners with just
    its system part."""
    c = SimCluster(n_resolvers=3)
    p = c.commit_proxies[0]
    sysk = b"\xff/conf/x"
    txns = [
        # Pure system read, NO mutations (not a state txn).
        _txn(reads=[(sysk, sysk + b"\x00")]),
        # User+system straddle: write [b"\xf0", \xff/z).
        _txn(writes=[(b"\xf0", b"\xff/z")]),
    ]
    _b, requests, _im = _reqs(p, txns, 0, 1000)
    for idx, req in enumerate(requests):
        assert len(req.transactions) == 2, f"resolver {idx} missed a txn"
        t0, t1 = req.transactions
        assert [(r.begin, r.end) for r in t0.read_conflict_ranges] == \
            [(sysk, sysk + b"\x00")]
        spans = [(w.begin, w.end) for w in t1.write_conflict_ranges]
        assert (SYSTEM_KEYS_BEGIN, b"\xff/z") in spans
        # Only the user-space owner (resolver 2: [b"\xaa", \xff)) also
        # holds the user part.
        assert ((b"\xf0", SYSTEM_KEYS_BEGIN) in spans) == (idx == 2)


def test_empty_fragment_advances_version_chain(teardown):
    """Every resolver receives every batch — a commit touching only
    resolver 0's range still advances resolver 1's version window in
    lockstep (the version-chain contiguity the plane depends on)."""
    c = SimCluster(n_resolvers=2)
    db = c.database()

    async def go():
        t = db.create_transaction()
        t.set(b"a-key", b"v")         # resolver 0's range only
        await t.commit()
        return t.committed_version

    cv = run(c, go())
    assert cv > 0
    assert c.resolvers[0].version.get() == cv
    assert c.resolvers[1].version.get() == cv
    assert c.resolvers[1].resolved_batches == \
        c.resolvers[0].resolved_batches > 0


# ---------------------------------------------------------------------------
# N-resolver vs 1-resolver abort-set parity
# ---------------------------------------------------------------------------

CELLS = 4
CELL_KEYS = 64


def _parity_stream(seed=11, waves=16, per_wave=24):
    """Deterministic wave stream, partition-aligned to CELLS quarter
    cells (a txn never straddles a resolver boundary — straddling
    globally-aborted txns leave pessimistic writes in owner histories,
    exactly as in the reference, so bit-parity is only promised for
    aligned workloads).  Snapshots lag 1-2 waves for real conflicts;
    every 5th wave carries a \xff state transaction (broadcast)."""
    rng = random.Random(seed)
    # Cell prefixes on the N=4 static split points (0x00/0x40/0x80/0xc0):
    # the 4-cell alignment nests into the 2- and 1-resolver partitions.
    bounds = [bytes([(256 * i) // CELLS]) for i in range(CELLS)]

    def key(cell, i):
        return bounds[cell] + b"/k%03d" % i

    stream = []
    for w in range(waves):
        version = 1000 * (w + 1)
        prev = 1000 * w
        txns = []
        for _ in range(per_wave):
            cell = rng.randrange(CELLS)
            snapshot = max(0, 1000 * (w - rng.randint(1, 2)))
            ks = [key(cell, rng.randrange(CELL_KEYS)) for _ in range(3)]
            txns.append(_txn(
                reads=[(k, k + b"\x00") for k in ks[:2]],
                writes=[(ks[2], ks[2] + b"\x00")],
                snapshot=snapshot))
        if w % 5 == 1:
            sysk = b"\xff/parity/%02d" % rng.randrange(4)
            txns.append(_txn(
                reads=[(sysk, sysk + b"\x00")],
                writes=[(sysk, sysk + b"\x00")],
                mutations=[Mutation(MutationType.SetValue, sysk, b"v")],
                snapshot=max(0, 1000 * (w - 1))))
        stream.append((prev, version, txns))
    return stream


def _resolve_stream(n_resolvers, stream):
    c = SimCluster(n_resolvers=n_resolvers)
    p = c.commit_proxies[0]

    async def go():
        verdicts = []
        for prev, version, txns in stream:
            batch, requests, index_maps = _reqs(p, txns, prev, version)
            futures = [
                RequestStream.at(r.resolve.endpoint).get_reply(req)
                for r, req in zip(p.resolvers, requests)]
            resolutions = await wait_all(futures)
            p.last_resolved_version = version
            verdicts.append([int(v) for v in p._determine_committed(
                batch, index_maps, resolutions)])
        return verdicts

    out = run(c, go())
    from foundationdb_tpu.core import set_event_loop
    from foundationdb_tpu.rpc.sim import set_simulator
    set_simulator(None)
    set_event_loop(None)
    return out


def test_abort_set_parity_1_2_4(teardown):
    """Acceptance: 2- and 4-resolver planes produce BIT-IDENTICAL
    commit/abort verdicts to the single-resolver baseline on the same
    seeded aligned workload, through the real proxy clip -> resolver RPC
    -> min-merge path."""
    stream = _parity_stream()
    base = _resolve_stream(1, stream)
    flat = [v for wave in base for v in wave]
    # The stream must actually exercise both outcomes to mean anything.
    assert flat.count(int(CommitResult.CONFLICT)) > 5
    assert flat.count(int(CommitResult.COMMITTED)) > 5
    assert _resolve_stream(2, stream) == base
    assert _resolve_stream(4, stream) == base


# ---------------------------------------------------------------------------
# Boundary seeding + DBCoreState persistence
# ---------------------------------------------------------------------------

def test_seed_resolver_boundaries_equidepth():
    # 8 shards clustered under a shared prefix: equi-depth cuts come
    # from the shard map, NOT static byte splits (which would land the
    # whole prefix on one resolver).
    shards = [(b"", b"k1", [0])] + [
        (b"k%d" % i, b"k%d" % (i + 1), [0]) for i in range(1, 8)]
    cuts = seed_resolver_boundaries(shards, 4)
    assert len(cuts) == 3
    assert all(c.startswith(b"k") for c in cuts)
    assert cuts == sorted(cuts)
    # Too-coarse shard map (cold boot): static byte splits.
    assert seed_resolver_boundaries([(b"", b"\xff", [0])], 4) == \
        [b"\x40", b"\x80", b"\xc0"]
    assert seed_resolver_boundaries(shards, 1) == []
    # Knob off: static splits even with a rich shard map.
    from foundationdb_tpu.core.knobs import server_knobs
    knobs = server_knobs()
    saved = knobs.RESOLVER_BOUNDARY_EQUIDEPTH
    knobs.RESOLVER_BOUNDARY_EQUIDEPTH = False
    try:
        assert seed_resolver_boundaries(shards, 2) == [b"\x80"]
    finally:
        knobs.RESOLVER_BOUNDARY_EQUIDEPTH = saved


def test_key_resolver_ranges_shape():
    ranges = _key_resolver_ranges(2)
    assert ranges == [(b"", b"\x80", 0), (b"\x80", b"\xff", 1),
                      (b"\xff", b"\xff\xff", RESOLVER_ALL)]
    user = [r for r in ranges if r[2] != RESOLVER_ALL]
    assert _valid_resolver_ranges(user, 2)
    assert not _valid_resolver_ranges(user, 1)       # index out of plane
    # Count INCREASE must re-seed: a 2-way split adopted by a 4-resolver
    # epoch would leave resolvers 2/3 with no user keyspace.
    assert not _valid_resolver_ranges(user, 4)
    assert not _valid_resolver_ranges([], 2)
    assert not _valid_resolver_ranges(
        [(b"", b"\x80", 0)], 2)                      # hole before \xff
    assert not _valid_resolver_ranges(
        [(b"", b"\x80", 0), (b"\x90", b"\xff", 1)], 2)   # gap


def test_dbcorestate_resolver_ranges_roundtrip():
    st = DBCoreState(
        epoch=3, recovery_version=500, n_resolvers=2,
        tlog_ids=["log0"], storage_ids={0: "ss0"},
        key_servers_ranges=[(b"", b"\xff\xff", [0])],
        resolver_ranges=[(b"", b"k5", 0), (b"k5", b"\xff", 1)])
    out = DBCoreState.unpack(st.pack())
    assert out.resolver_ranges == [(b"", b"k5", 0), (b"k5", b"\xff", 1)]
    assert out.n_resolvers == 2
    # A pre-plane blob (no trailing resolver section) unpacks to [] and
    # fails validation -> recovery re-seeds.  Strip the failover record
    # (u32+i64+i64 = 20 bytes, ISSUE 10) AND the resolver section's
    # (empty) u16 count to reconstruct the legacy form.
    st2 = DBCoreState(epoch=1, recovery_version=0, tlog_ids=["log0"],
                      storage_ids={})
    legacy = st2.pack()[:-22]
    out2 = DBCoreState.unpack(legacy)
    assert out2.resolver_ranges == []
    assert out2.failover_epoch == 0 and out2.failover_version == 0
    assert not _valid_resolver_ranges(out2.resolver_ranges, 1)


# ---------------------------------------------------------------------------
# Recovery: persisted boundaries adopted, plane survives resolver death
# ---------------------------------------------------------------------------

async def _commit_kv(db, key, value):
    t = db.create_transaction()
    while True:
        try:
            t.set(key, value)
            await t.commit()
            return t.committed_version
        except FdbError as e:
            await t.on_error(e)


async def _wait_recovered(cluster, min_epoch=0, timeout=80.0):
    from foundationdb_tpu.core.scheduler import delay, now
    deadline = now() + timeout
    while now() < deadline:
        cc = cluster.current_cc()
        if cc is not None and cc.db_info.epoch >= min_epoch and \
                cc.db_info.recovery_state in ("accepting_commits",
                                              "fully_recovered"):
            return cc
        await delay(0.5)
    raise TimeoutError("cluster did not recover")


async def _read_cstate(cluster):
    from foundationdb_tpu.server.coordination import CoordinatedState
    raw = await CoordinatedState(cluster.coordinator_clients).read()
    return DBCoreState.coerce(raw)


def test_resolver_plane_recovery_continuity(teardown):
    """Resolver death -> full recovery: the next epoch recruits the same
    resolver count, ADOPTS the persisted boundaries from DBCoreState,
    and commits keep flowing (verdict continuity probed by a
    read-your-write across the plane change)."""
    c = SimFdbCluster(config=DatabaseConfiguration(n_resolvers=2),
                      n_workers=5, n_storage_workers=2)
    db = c.database()

    async def go():
        cc = await _wait_recovered(c)
        epoch1 = cc.db_info.epoch
        assert len(cc.db_info.resolvers) == 2
        st1 = await _read_cstate(c)
        assert st1.n_resolvers == 2
        assert _valid_resolver_ranges(st1.resolver_ranges, 2)
        await _commit_kv(db, b"plane/before", b"1")

        # Kill the worker hosting resolver 0 (the chaos satellite's
        # targeted attrition, deterministically).
        victim = c.process_of(cc.db_info.resolvers[0])
        assert victim is not None
        idx = next(i for i, e in enumerate(c.workers)
                   if e[0] is victim)
        c.sim.kill_process(victim)
        cc2 = await _wait_recovered(c, min_epoch=epoch1 + 1)
        c.restart_worker(idx)
        assert len(cc2.db_info.resolvers) == 2
        st2 = await _read_cstate(c)
        # Boundaries adopted across the epoch change, not re-seeded away.
        assert st2.resolver_ranges == st1.resolver_ranges
        # db_info surfaces the plane (status cluster.resolution source).
        rr = cc2.db_info.resolver_ranges
        assert rr and rr[-1][2] == RESOLVER_ALL
        await _commit_kv(db, b"plane/after", b"2")
        t = db.create_transaction()
        assert await t.get(b"plane/before") == b"1"
        assert await t.get(b"plane/after") == b"2"
        return True

    assert run(c, go(), timeout=180)


def test_resolver_count_knob_overrides_config(teardown):
    """RESOLVER_COUNT pins the plane size regardless of the committed
    configuration (takes effect at recruitment)."""
    from foundationdb_tpu.core.knobs import server_knobs
    knobs = server_knobs()
    saved = knobs.RESOLVER_COUNT
    knobs.RESOLVER_COUNT = 3
    try:
        c = SimFdbCluster(config=DatabaseConfiguration(n_resolvers=1),
                          n_workers=5, n_storage_workers=2)

        async def go():
            cc = await _wait_recovered(c)
            return len(cc.db_info.resolvers)

        assert run(c, go(), timeout=120) == 3
    finally:
        knobs.RESOLVER_COUNT = saved


# ---------------------------------------------------------------------------
# Status / fdbcli surfaces
# ---------------------------------------------------------------------------

def test_status_resolution_plane(teardown):
    c = SimFdbCluster(config=DatabaseConfiguration(n_resolvers=2),
                      n_workers=5, n_storage_workers=2)
    db = c.database()

    async def go():
        from foundationdb_tpu.server.status import build_status
        cc = await _wait_recovered(c)
        await _commit_kv(db, b"res/status", b"1")
        return await build_status(cc)

    doc = run(c, go(), timeout=120)
    res = doc["cluster"]["resolution"]
    assert res["count"] == 2
    assert len(res["resolvers"]) == 2
    assert any(r["resolver"] == "all" for r in res["ranges"])
    for rid, entry in res["resolvers"].items():
        assert rid.startswith("resolver")
        assert "txn_conflicts" in entry and "txn_resolved" in entry
    assert sum(e["txn_resolved"] for e in res["resolvers"].values()) > 0
    # ... and `fdbcli metrics` renders the per-resolver table.
    from foundationdb_tpu.tools.fdbcli import Cli
    cli = Cli.__new__(Cli)
    cli.loop, cli.db = c.loop, c.database()
    out = cli.dispatch("metrics")
    assert "Resolution plane (2 resolvers):" in out
    assert out.count("resolver") >= 2 and "-> all" in out


# ---------------------------------------------------------------------------
# flowlint FTL009 covers the new knobs
# ---------------------------------------------------------------------------

def test_ftl009_knows_resolver_knobs(tmp_path):
    from foundationdb_tpu.analysis.rules import KnobNameRule
    fields = KnobNameRule._load_fields()["ServerKnobs"]
    assert "RESOLVER_COUNT" in fields
    assert "RESOLVER_BOUNDARY_EQUIDEPTH" in fields
    # ... and a typo'd use of one is CAUGHT.
    from foundationdb_tpu.analysis.engine import run_flowlint
    bad = tmp_path / "mod.py"
    bad.write_text(
        "from foundationdb_tpu.core.knobs import server_knobs\n"
        "n = server_knobs().RESOLVER_COUNTS\n")
    result = run_flowlint([str(bad)])
    assert any(f.rule == "FTL009" for f in result.new)


# ---------------------------------------------------------------------------
# bench.py multi-resolver sweep (satellite): tier-1 runs N=1/2 tiny;
# the N=4 sweep is slow-marked per the issue.
# ---------------------------------------------------------------------------

def _sweep(ns):
    import importlib.util
    import os
    path = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")
    spec = importlib.util.spec_from_file_location("bench_rsweep", path)
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    return bench.run_resolver_sweep(
        ns=ns, txns=512, n_batches=4, keyspace=16384,
        capacity=1 << 13, delta_capacity=1 << 12)


def test_bench_resolver_sweep_parity_n2():
    doc = _sweep((1, 2))
    assert doc["parity"] == "ok"
    assert set(doc["sweep"]) == {"1", "2"}
    assert doc["sweep"]["2"]["aggregate_ranges_per_s"] > 0
    assert len(doc["sweep"]["2"]["per_resolver_ranges_per_s"]) == 2


@pytest.mark.slow
def test_bench_resolver_sweep_n4():
    doc = _sweep((1, 2, 4))
    assert doc["parity"] == "ok"
    # Aggregate conflict-check throughput increases with resolver count
    # (the acceptance gate; generous floor — tiny batches under-sell it).
    a1 = doc["sweep"]["1"]["aggregate_ranges_per_s"]
    a4 = doc["sweep"]["4"]["aggregate_ranges_per_s"]
    assert a4 > a1
