"""Perpetual storage wiggle: DD rotates through the storage pool, draining
one server at a time and letting it refill.

Reference: fdbserver/DataDistribution.actor.cpp storage wiggle (the
perpetual_storage_wiggle configuration; StorageWiggler rotation state) —
every replica is periodically rewritten in place, the reference's
mechanism for storage-engine migrations and latent-error scrubbing.
"""

import pytest

from foundationdb_tpu.core.knobs import server_knobs
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration

from test_data_distribution import consistency_audit, current_dd
from test_recovery import commit_kv, read_key, teardown  # noqa: F401


@pytest.fixture
def wiggle_knobs():
    k = server_knobs()
    orig = (k.PERPETUAL_STORAGE_WIGGLE, k.STORAGE_WIGGLE_INTERVAL)
    yield k
    k.PERPETUAL_STORAGE_WIGGLE, k.STORAGE_WIGGLE_INTERVAL = orig


def test_wiggle_rotates_and_data_survives(teardown, wiggle_knobs):  # noqa: F811,E501
    knobs = wiggle_knobs
    knobs.PERPETUAL_STORAGE_WIGGLE = 1
    knobs.STORAGE_WIGGLE_INTERVAL = 0.5
    c = SimFdbCluster(
        config=DatabaseConfiguration(n_storage=3, storage_replication=2),
        n_workers=6, n_storage_workers=3)
    db = c.database()

    async def go():
        from foundationdb_tpu.core.scheduler import delay
        for i in range(30):
            await commit_kv(db, b"wg/%03d" % i, b"val%03d" % i)
        dd = current_dd(c)
        assert dd is not None
        # A full rotation: every healthy tag wiggled at least once.
        n_tags = len(dd.healthy)
        deadline = 120.0
        while deadline > 0 and dd.stats["wiggles"] < n_tags:
            await delay(0.5)
            deadline -= 0.5
            dd = current_dd(c) or dd
        assert dd.stats["wiggles"] >= n_tags, dd.stats
        assert not dd.wiggling           # re-admitted after each drain
        # Data unharmed, replicas byte-identical.
        for i in range(30):
            assert await read_key(db, b"wg/%03d" % i) == b"val%03d" % i
        assert await consistency_audit(c, db) >= 1
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=300)


def test_wiggle_refuses_without_headroom(teardown, wiggle_knobs):  # noqa: F811,E501
    """pool == replication: wiggling would force under-replication, so the
    wiggler must skip (and keep skipping) rather than degrade."""
    knobs = wiggle_knobs
    knobs.PERPETUAL_STORAGE_WIGGLE = 1
    knobs.STORAGE_WIGGLE_INTERVAL = 0.2
    c = SimFdbCluster(
        config=DatabaseConfiguration(n_storage=2, storage_replication=2),
        n_workers=5, n_storage_workers=2)
    db = c.database()

    async def go():
        from foundationdb_tpu.core.scheduler import delay
        for i in range(10):
            await commit_kv(db, b"nh/%02d" % i, b"v%02d" % i)
        dd = current_dd(c)
        assert dd is not None
        await delay(5.0)
        assert dd.stats["wiggles"] == 0
        for i in range(10):
            assert await read_key(db, b"nh/%02d" % i) == b"v%02d" % i
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=120)
