"""Workload-harness tests: TOML specs driving chaos + invariant workloads
against a full simulated cluster (reference `fdbserver -r simulation -f
tests/fast/CycleTest.toml`, SURVEY.md §3.5/§4)."""

import os

import pytest

from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration
from foundationdb_tpu.testing import load_spec, run_test

SPECS = os.path.join(os.path.dirname(__file__), "specs")


@pytest.fixture()
def teardown():
    from foundationdb_tpu.core import (DeterministicRandom,
                                       set_deterministic_random)
    set_deterministic_random(DeterministicRandom(21))
    yield
    from foundationdb_tpu.core import set_event_loop
    from foundationdb_tpu.rpc.sim import set_simulator
    set_simulator(None)
    set_event_loop(None)


def test_cycle_spec_under_chaos(teardown):
    c = SimFdbCluster(config=DatabaseConfiguration(n_tlogs=2,
                                                   log_replication=2),
                      n_workers=7, n_storage_workers=2)
    spec = load_spec(os.path.join(SPECS, "CycleTest.toml"))

    async def go():
        metrics = await run_test(c, spec)
        assert metrics["Cycle"]["swaps"] > 0
        assert metrics["Attrition"]["kills"] >= 1
        return metrics

    metrics = c.run_until(c.loop.spawn(go()), timeout=1200)
    print("metrics:", metrics)


def test_serializability_spec(teardown):
    c = SimFdbCluster(config=DatabaseConfiguration(n_resolvers=2),
                      n_workers=5, n_storage_workers=2)
    spec = load_spec(os.path.join(SPECS, "SerializabilityTest.toml"))

    async def go():
        return await run_test(c, spec)

    metrics = c.run_until(c.loop.spawn(go()), timeout=600)
    assert metrics["ReadWrite"]["operations"] > 0


def test_unknown_workload_rejected(teardown):
    c = SimFdbCluster(config=DatabaseConfiguration())
    spec = load_spec("""
[[test]]
testTitle = 'Bogus'
  [[test.workload]]
  testName = 'DoesNotExist'
""")

    async def go():
        try:
            await run_test(c, spec)
        except KeyError as e:
            return str(e)
        return None

    assert "DoesNotExist" in c.run_until(c.loop.spawn(go()), timeout=30)


def _run_spec(spec_name, buggify=False, **cluster_kw):
    from foundationdb_tpu.core import enable_buggify
    cfg = cluster_kw.pop("config", None) or DatabaseConfiguration(
        n_tlogs=2, log_replication=2)
    c = SimFdbCluster(config=cfg,
                      n_workers=cluster_kw.pop("n_workers", 7),
                      n_storage_workers=cluster_kw.pop("n_storage_workers", 2))
    spec = load_spec(os.path.join(SPECS, spec_name))
    enable_buggify(buggify)
    try:
        async def go():
            return await run_test(c, spec)
        return c.run_until(c.loop.spawn(go()), timeout=1200)
    finally:
        enable_buggify(False)


def test_api_correctness_spec(teardown):
    m = _run_spec("ApiCorrectnessTest.toml", buggify=True)
    assert m["ApiCorrectness"]["transactions"] > 0


def test_tenant_spec_under_chaos(teardown):
    """TenantManagement workload (ISSUE 2): tenant lifecycle + cross-
    tenant isolation under clogging chaos, from its TOML spec (also run
    by scripts/run_ensemble.py)."""
    m = _run_spec("TenantTest.toml", buggify=True)
    assert m["TenantManagement"]["tenant_ops"] > 0


def test_rollback_spec(teardown):
    m = _run_spec("RollbackTest.toml", buggify=True)
    assert m["Rollback"]["recoveries_forced"] >= 1
    assert m["Cycle"]["swaps"] > 0


def test_change_config_spec(teardown):
    m = _run_spec("ChangeConfigTest.toml")
    assert m["ChangeConfig"]["changed"] == 1


def test_movekeys_cycle_spec(teardown):
    m = _run_spec("MoveKeysCycle.toml",
                  config=DatabaseConfiguration(
                      n_tlogs=2, log_replication=2, n_storage=3,
                      storage_replication=2),
                  n_workers=8, n_storage_workers=3)
    assert m["RandomMoveKeys"]["moves"] >= 1
    assert m["ConsistencyCheck"]["shards_audited"] >= 1


def test_watches_spec(teardown):
    m = _run_spec("WatchesTest.toml")
    assert m["Watches"]["watches_fired"] == 8


def test_kill_region_spec(teardown):
    """Region failover under a live cycle workload: the ring invariant
    holds on the adopted remote replicas after the primary dc dies."""
    c = SimFdbCluster(config=DatabaseConfiguration(),
                      n_workers=5, n_storage_workers=2)
    spec = load_spec(os.path.join(SPECS, "KillRegionTest.toml"))

    async def go():
        metrics = await run_test(c, spec)
        assert metrics["Cycle"]["swaps"] > 0
        assert metrics["KillRegion"]["killed"] >= 4
        assert metrics["KillRegion"]["adopted_remote"] == 1.0
        return metrics

    metrics = c.run_until(c.loop.spawn(go()), timeout=1200)
    print("metrics:", metrics)
