"""Workload-harness tests: TOML specs driving chaos + invariant workloads
against a full simulated cluster (reference `fdbserver -r simulation -f
tests/fast/CycleTest.toml`, SURVEY.md §3.5/§4)."""

import os

import pytest

from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration
from foundationdb_tpu.testing import load_spec, run_test

SPECS = os.path.join(os.path.dirname(__file__), "specs")


@pytest.fixture()
def teardown():
    from foundationdb_tpu.core import (DeterministicRandom,
                                       set_deterministic_random)
    set_deterministic_random(DeterministicRandom(21))
    yield
    from foundationdb_tpu.core import set_event_loop
    from foundationdb_tpu.rpc.sim import set_simulator
    set_simulator(None)
    set_event_loop(None)


def test_cycle_spec_under_chaos(teardown):
    c = SimFdbCluster(config=DatabaseConfiguration(n_tlogs=2,
                                                   log_replication=2),
                      n_workers=7, n_storage_workers=2)
    spec = load_spec(os.path.join(SPECS, "CycleTest.toml"))

    async def go():
        metrics = await run_test(c, spec)
        assert metrics["Cycle"]["swaps"] > 0
        assert metrics["Attrition"]["kills"] >= 1
        return metrics

    metrics = c.run_until(c.loop.spawn(go()), timeout=1200)
    print("metrics:", metrics)


def test_serializability_spec(teardown):
    c = SimFdbCluster(config=DatabaseConfiguration(n_resolvers=2),
                      n_workers=5, n_storage_workers=2)
    spec = load_spec(os.path.join(SPECS, "SerializabilityTest.toml"))

    async def go():
        return await run_test(c, spec)

    metrics = c.run_until(c.loop.spawn(go()), timeout=600)
    assert metrics["ReadWrite"]["operations"] > 0


def test_unknown_workload_rejected(teardown):
    c = SimFdbCluster(config=DatabaseConfiguration())
    spec = load_spec("""
[[test]]
testTitle = 'Bogus'
  [[test.workload]]
  testName = 'DoesNotExist'
""")

    async def go():
        try:
            await run_test(c, spec)
        except KeyError as e:
            return str(e)
        return None

    assert "DoesNotExist" in c.run_until(c.loop.spawn(go()), timeout=30)
