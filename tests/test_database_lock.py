"""Database lock (reference ManagementAPI lockDatabase/unlockDatabase,
SystemData databaseLockedKey): while \\xff/dbLocked is set, commit
proxies reject every non-LOCK_AWARE commit with database_locked; reads
are unaffected.  The lock is committed data — it survives recovery and
a full power failure — and is the write fence DR switchover uses."""

import pytest

from foundationdb_tpu.client.management import (lock_database,
                                                unlock_database)
from foundationdb_tpu.core.error import FdbError
from foundationdb_tpu.server.cluster import SimFdbCluster
from foundationdb_tpu.server.interfaces import DatabaseConfiguration

from test_recovery import commit_kv, read_key, teardown  # noqa: F401


async def _expect_locked(db, key=b"lk/denied"):
    t = db.create_transaction()
    t.set(key, b"x")
    try:
        await t.commit()
        raise AssertionError("locked database accepted a commit")
    except FdbError as e:
        assert e.name == "database_locked", e.name


def test_lock_fences_commits_and_survives_recovery(teardown):  # noqa: F811
    c = SimFdbCluster(config=DatabaseConfiguration(), n_workers=4,
                      n_storage_workers=2)
    db = c.database()

    async def go():
        from foundationdb_tpu.core.scheduler import delay
        await commit_kv(db, b"lk/pre", b"v1")
        uid = await lock_database(db)
        await _expect_locked(db)
        # Reads pass; lock-aware commits pass.
        assert await read_key(db, b"lk/pre") == b"v1"
        t = db.create_transaction()
        t.lock_aware = True
        t.set(b"lk/aware", b"yes")
        await t.commit()
        assert await read_key(db, b"lk/aware") == b"yes"
        # Re-locking with the same uid is idempotent; another uid bounces.
        assert await lock_database(db, uid) == uid
        try:
            await lock_database(db, b"other-uid")
            raise AssertionError("double lock succeeded")
        except FdbError as e:
            assert e.name == "database_locked"
        # The fence survives recovery: kill the master, wait for the next
        # epoch, still locked.
        epoch0 = c.current_cc().db_info.epoch
        mp = c.process_of(c.current_cc().db_info.master)
        c.sim.kill_process(mp)
        for _ in range(300):
            cc = c.current_cc()
            if cc is not None and cc.db_info.epoch > epoch0 and \
                    cc.db_info.recovery_state in ("accepting_commits",
                                                  "fully_recovered"):
                break
            await delay(0.25)
        await _expect_locked(db)
        # Wrong-uid unlock bounces; the right uid releases the fence.
        try:
            await unlock_database(db, b"wrong")
            raise AssertionError("wrong-uid unlock succeeded")
        except FdbError as e:
            assert e.name == "database_locked"
        await unlock_database(db, uid)
        await commit_kv(db, b"lk/after", b"v2")
        assert await read_key(db, b"lk/after") == b"v2"
        return True

    assert c.run_until(c.loop.spawn(go()), timeout=300)


def test_dbcorestate_lock_pack_roundtrip(teardown):  # noqa: F811
    """The lock must survive a FULL power failure: it rides the packed
    DBCoreState the coordinators persist."""
    from foundationdb_tpu.server.master import DBCoreState
    st = DBCoreState(epoch=3, recovery_version=7, locked=b"uid-1")
    assert DBCoreState.unpack(st.pack()).locked == b"uid-1"
    st2 = DBCoreState(epoch=3, recovery_version=7)
    assert DBCoreState.unpack(st2.pack()).locked is None
