#!/usr/bin/env python
"""Chaos seed-matrix runner: N seeds x spec list through the
deterministic chaos engine, with optional unseed verification.

Reference: contrib/TestHarness — run many (spec, seed, buggify) tuples,
triage failures, and hand back an exact repro line.  Unlike
run_ensemble.py this runner (a) uses testing.run_simulation, so every
run carries its unseed (the determinism witness), (b) can double-run
each tuple and fail on unseed mismatch (--verify-unseed), in-process or
against a freshly spawned subprocess (--cross-process), and (c) emits a
machine-readable JSON summary with a copy-pastable repro command per
failure plus the current flowlint static findings (a chaos failure
sitting next to a fresh FTL001 wall-clock finding is usually not a
coincidence).

PYTHONHASHSEED: str-set iteration orders depend on the per-process hash
salt, so cross-process unseed reproduction REQUIRES a pinned seed.  This
runner re-execs itself once with PYTHONHASHSEED=0 when it finds hashing
randomized, pins the same seed into every subprocess it spawns, and
prefixes every repro command it prints accordingly.

    python scripts/run_chaos.py --seeds 5
    python scripts/run_chaos.py --spec tests/specs/ChaosTest.toml --seed 17
    python scripts/run_chaos.py --seeds 3 --verify-unseed --json out.json
    python scripts/run_chaos.py --seeds 3 --verify-unseed --cross-process
"""

import argparse
import glob
import json
import os
import subprocess
import sys
import tempfile
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The default matrix: the canonical nemesis trio plus the DR battery
# (ISSUE 10) — region failover + coordinator restarts, and
# backup/restore under attrition + fatal disk faults — plus the
# scheduling battery (ISSUE 12): all three SCHED_* stages on under
# resolver attrition with the SchedRepairLoad duplicate-commit audit.
# Their coverage markers (ChaosRegionFailover, ChaosCoordinatorRestart,
# ChaosFatalDiskRestart, BackupRestoreUnderChaos, ProxyTxnRepaired,
# GrvSchedDeferral, ProxyBatchReordered) land in the summary's coverage
# ledger like every other registered marker.  GrayFailureTest (ISSUE 18)
# runs the latency-inflation nemesis — deliveries succeed, only the
# peer-health plane can observe the fault (ChaosNemesisGrayClog marker).
DEFAULT_SPECS = ("ChaosTest.toml", "CycleTest.toml", "TenantTest.toml",
                 "TwoRegionChaosTest.toml", "BackupRestoreChaosTest.toml",
                 "SchedChaosTest.toml", "E2eThroughputTest.toml",
                 "ReadStormTest.toml", "GrayFailureTest.toml")


def _ensure_hash_seed_pinned() -> None:
    """Re-exec once with PYTHONHASHSEED=0 if str hashing is randomized:
    every run this matrix produces must be reproducible from the repro
    command it prints, including across processes."""
    from foundationdb_tpu.testing import effective_hash_seed
    if effective_hash_seed() is not None:
        return
    env = dict(os.environ, PYTHONHASHSEED="0")
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def repro_command(spec_path: str, seed: int, buggify: bool,
                  verify: bool, cross_process: bool = False) -> str:
    from foundationdb_tpu.testing import repro_hash_seed_prefix
    cmd = (f"{repro_hash_seed_prefix()}python scripts/run_chaos.py "
           f"--spec {spec_path} --seed {seed}")
    if not buggify:
        cmd += " --no-buggify"
    elif seed % 2 != 0:
        # Seed parity alone would leave buggify off for this tuple.
        cmd += " --buggify"
    if verify:
        cmd += " --verify-unseed"
        if cross_process:
            # A divergence caught only across processes often passes the
            # in-process double run — the repro must use the same mode.
            cmd += " --cross-process"
    return cmd


def _run_in_subprocess(spec_path: str, seed: int, buggify: bool) -> dict:
    """One run of the tuple in a FRESH process (PYTHONHASHSEED pinned to
    this process's effective seed) via --emit-run-json; returns its
    result record."""
    from foundationdb_tpu.testing import effective_hash_seed
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
        out_path = tf.name
    cmd = [sys.executable, os.path.abspath(__file__),
           "--spec", spec_path, "--seed", str(seed),
           "--emit-run-json", out_path,
           # Explicit, not re-derived from seed parity in the child: the
           # verification run must use EXACTLY the caller's buggify.
           "--buggify" if buggify else "--no-buggify"]
    env = dict(os.environ,
               PYTHONHASHSEED=effective_hash_seed() or "0",
               JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"))
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=1800, env=env)
        # A failed child still writes its full result record (kind,
        # error, traceback) before exiting 1 — prefer that to scraping
        # stderr, which is usually empty.
        try:
            with open(out_path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return {"ok": False, "kind": "subprocess_error",
                    "error": (proc.stderr or proc.stdout)[-2000:]}
    finally:
        try:
            os.unlink(out_path)
        except OSError:
            pass


def run_tuple(spec_path: str, seed: int, buggify: bool,
              verify_unseed: bool, cross_process: bool = False) -> dict:
    """One (spec, seed, buggify) run; returns a result record.  With
    verify_unseed the tuple runs TWICE — in-process, or with the second
    run in a fresh subprocess (cross_process) — and an unseed mismatch
    is a failure in its own right (kind 'nondeterminism')."""
    from foundationdb_tpu.testing import run_simulation, run_test_twice
    spec_text = open(spec_path).read()
    t0 = time.time()
    rec = {"spec": os.path.basename(spec_path), "seed": seed,
           "buggify": buggify, "ok": False}
    try:
        if verify_unseed and cross_process:
            r1 = run_simulation(spec_text, seed, buggify=buggify)
            r2 = _run_in_subprocess(spec_path, seed, buggify)
            if not r2.get("ok"):
                raise RuntimeError(f"cross-process run failed: "
                                   f"{r2.get('error', r2)}")
            mine = {"unseed": r1.unseed, "digest": r1.digest,
                    "folds": r1.folds}
            theirs = {k: r2.get(k) for k in mine}
            if mine != theirs:
                raise AssertionError(
                    f"unseed mismatch for seed {seed} ACROSS PROCESSES: "
                    f"in-process {mine} vs subprocess {theirs} "
                    "(PYTHONHASHSEED is pinned, so this is real "
                    "nondeterminism, not str-hash order)")
        elif verify_unseed:
            r1, _r2 = run_test_twice(spec_text, seed, buggify=buggify)
        else:
            r1 = run_simulation(spec_text, seed, buggify=buggify)
        rec.update(ok=True, unseed=r1.unseed, digest=r1.digest,
                   folds=r1.folds, metrics=r1.metrics,
                   nondeterminism=r1.nondeterminism)
    except AssertionError as e:
        kind = ("nondeterminism" if "unseed mismatch" in str(e)
                else "check_failed")
        rec.update(kind=kind, error=str(e))
    except (KeyboardInterrupt, SystemExit):
        raise                    # ^C must abort the matrix, not log a tuple
    except BaseException as e:  # noqa: BLE001 - triage, don't crash
        rec.update(kind="error", error=f"{type(e).__name__}: {e}",
                   traceback=traceback.format_exc())
    rec["seconds"] = round(time.time() - t0, 1)
    if not rec["ok"]:
        rec["repro"] = repro_command(spec_path, seed, buggify,
                                     verify_unseed, cross_process)
    return rec


def collect_flowlint() -> dict:
    """Static findings for the summary, via the flowlint CLI's JSON
    output (so the chaos report and the lint CLI can never disagree).
    Fail-soft: a lint crash must not take the chaos matrix down."""
    here = os.path.dirname(os.path.abspath(__file__))
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(here, "flowlint.py"),
             "--format", "json"],
            capture_output=True, text=True, timeout=300)
        data = json.loads(proc.stdout)
        return {"exit_code": proc.returncode,
                "counts": data.get("counts", {}),
                "findings": data.get("findings", [])[:20]}
    except Exception as e:  # noqa: BLE001
        return {"exit_code": -1, "error": f"{type(e).__name__}: {e}"}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--specs", default=None,
                    help="directory of .toml specs (default: the chaos "
                         f"trio {DEFAULT_SPECS} under tests/specs)")
    ap.add_argument("--spec", default=None, help="run one spec file only")
    ap.add_argument("--seeds", type=int, default=3,
                    help="seeds per spec (default 3)")
    ap.add_argument("--seed", type=int, default=None,
                    help="run one seed only (repro mode)")
    ap.add_argument("--first-seed", type=int, default=100)
    ap.add_argument("--no-buggify", action="store_true")
    ap.add_argument("--buggify", action="store_true",
                    help=argparse.SUPPRESS)  # subprocess-side of
    #                        --cross-process: force buggify ON regardless
    #                        of the seed-parity default
    ap.add_argument("--verify-unseed", action="store_true",
                    help="run every tuple twice; unseed mismatch fails")
    ap.add_argument("--cross-process", action="store_true",
                    help="with --verify-unseed: second run in a fresh "
                         "subprocess (PYTHONHASHSEED pinned) instead of "
                         "in-process")
    ap.add_argument("--emit-run-json", default=None, metavar="PATH",
                    help=argparse.SUPPRESS)   # subprocess-side of
    #                                           --cross-process
    ap.add_argument("--json", default=None,
                    help="write the JSON summary here (default stdout)")
    args = ap.parse_args()

    _ensure_hash_seed_pinned()

    if args.emit_run_json:
        if not args.spec or args.seed is None:
            ap.error("--emit-run-json requires --spec and --seed")
        buggify = args.buggify or \
            ((not args.no_buggify) and args.seed % 2 == 0)
        rec = run_tuple(args.spec, args.seed, buggify, False)
        with open(args.emit_run_json, "w") as f:
            json.dump(rec, f, default=str)
        return 0 if rec["ok"] else 1

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if args.spec:
        specs = [args.spec]
    elif args.specs:
        specs = sorted(glob.glob(os.path.join(args.specs, "*.toml")))
    else:
        specs = [os.path.join(here, "tests", "specs", name)
                 for name in DEFAULT_SPECS]
    seeds = [args.seed] if args.seed is not None else \
        [args.first_seed + i for i in range(args.seeds)]

    results = []
    for spec_path in specs:
        for seed in seeds:
            buggify = args.buggify or \
                ((not args.no_buggify) and seed % 2 == 0)
            rec = run_tuple(spec_path, seed, buggify, args.verify_unseed,
                            args.cross_process)
            status = "PASS" if rec["ok"] else f"FAIL({rec.get('kind')})"
            print(f"{status} {rec['spec']} seed={seed} buggify={buggify} "
                  f"({rec['seconds']}s)"
                  + (f" unseed={rec['unseed']:#010x}" if rec["ok"] else ""))
            results.append(rec)

    from foundationdb_tpu.core.coverage import missing, report
    from foundationdb_tpu.testing import effective_hash_seed
    failures = [r for r in results if not r["ok"]]
    summary = {
        "total": len(results),
        "passed": len(results) - len(failures),
        "hash_seed": effective_hash_seed(),
        "failures": failures,
        "coverage_hit": sorted(k for k, v in report().items() if v),
        "coverage_missing": missing(),
        "flowlint": collect_flowlint(),
    }
    out = json.dumps(summary, indent=2, default=str)
    if args.json:
        with open(args.json, "w") as f:
            f.write(out + "\n")
        print(f"summary written to {args.json}")
    else:
        print(out)
    for r in failures:
        print(f"REPRO: {r['repro']}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
