#!/usr/bin/env python
"""Chaos seed-matrix runner: N seeds x spec list through the
deterministic chaos engine, with optional unseed verification.

Reference: contrib/TestHarness — run many (spec, seed, buggify) tuples,
triage failures, and hand back an exact repro line.  Unlike
run_ensemble.py this runner (a) uses testing.run_simulation, so every
run carries its unseed (the determinism witness), (b) can double-run
each tuple and fail on unseed mismatch (--verify-unseed), and (c) emits
a machine-readable JSON summary with a copy-pastable repro command per
failure.

    python scripts/run_chaos.py --seeds 5
    python scripts/run_chaos.py --spec tests/specs/ChaosTest.toml --seed 17
    python scripts/run_chaos.py --seeds 3 --verify-unseed --json out.json
"""

import argparse
import glob
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

DEFAULT_SPECS = ("ChaosTest.toml", "CycleTest.toml", "TenantTest.toml")


def repro_command(spec_path: str, seed: int, buggify: bool,
                  verify: bool) -> str:
    cmd = (f"python scripts/run_chaos.py --spec {spec_path} "
           f"--seed {seed}")
    if not buggify:
        cmd += " --no-buggify"
    if verify:
        cmd += " --verify-unseed"
    return cmd


def run_tuple(spec_path: str, seed: int, buggify: bool,
              verify_unseed: bool) -> dict:
    """One (spec, seed, buggify) run; returns a result record.  With
    verify_unseed the tuple runs TWICE and an unseed mismatch is a
    failure in its own right (kind 'nondeterminism')."""
    from foundationdb_tpu.testing import run_simulation, run_test_twice
    spec_text = open(spec_path).read()
    t0 = time.time()
    rec = {"spec": os.path.basename(spec_path), "seed": seed,
           "buggify": buggify, "ok": False}
    try:
        if verify_unseed:
            r1, _r2 = run_test_twice(spec_text, seed, buggify=buggify)
        else:
            r1 = run_simulation(spec_text, seed, buggify=buggify)
        rec.update(ok=True, unseed=r1.unseed, folds=r1.folds,
                   metrics=r1.metrics,
                   nondeterminism=r1.nondeterminism)
    except AssertionError as e:
        kind = ("nondeterminism" if "unseed mismatch" in str(e)
                else "check_failed")
        rec.update(kind=kind, error=str(e))
    except (KeyboardInterrupt, SystemExit):
        raise                    # ^C must abort the matrix, not log a tuple
    except BaseException as e:  # noqa: BLE001 - triage, don't crash
        rec.update(kind="error", error=f"{type(e).__name__}: {e}",
                   traceback=traceback.format_exc())
    rec["seconds"] = round(time.time() - t0, 1)
    if not rec["ok"]:
        rec["repro"] = repro_command(spec_path, seed, buggify,
                                     verify_unseed)
    return rec


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--specs", default=None,
                    help="directory of .toml specs (default: the chaos "
                         f"trio {DEFAULT_SPECS} under tests/specs)")
    ap.add_argument("--spec", default=None, help="run one spec file only")
    ap.add_argument("--seeds", type=int, default=3,
                    help="seeds per spec (default 3)")
    ap.add_argument("--seed", type=int, default=None,
                    help="run one seed only (repro mode)")
    ap.add_argument("--first-seed", type=int, default=100)
    ap.add_argument("--no-buggify", action="store_true")
    ap.add_argument("--verify-unseed", action="store_true",
                    help="run every tuple twice; unseed mismatch fails")
    ap.add_argument("--json", default=None,
                    help="write the JSON summary here (default stdout)")
    args = ap.parse_args()

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if args.spec:
        specs = [args.spec]
    elif args.specs:
        specs = sorted(glob.glob(os.path.join(args.specs, "*.toml")))
    else:
        specs = [os.path.join(here, "tests", "specs", name)
                 for name in DEFAULT_SPECS]
    seeds = [args.seed] if args.seed is not None else \
        [args.first_seed + i for i in range(args.seeds)]

    results = []
    for spec_path in specs:
        for seed in seeds:
            buggify = (not args.no_buggify) and seed % 2 == 0
            rec = run_tuple(spec_path, seed, buggify, args.verify_unseed)
            status = "PASS" if rec["ok"] else f"FAIL({rec.get('kind')})"
            print(f"{status} {rec['spec']} seed={seed} buggify={buggify} "
                  f"({rec['seconds']}s)"
                  + (f" unseed={rec['unseed']:#010x}" if rec["ok"] else ""))
            results.append(rec)

    from foundationdb_tpu.core.coverage import missing, report
    failures = [r for r in results if not r["ok"]]
    summary = {
        "total": len(results),
        "passed": len(results) - len(failures),
        "failures": failures,
        "coverage_hit": sorted(k for k, v in report().items() if v),
        "coverage_missing": missing(),
    }
    out = json.dumps(summary, indent=2, default=str)
    if args.json:
        with open(args.json, "w") as f:
            f.write(out + "\n")
        print(f"summary written to {args.json}")
    else:
        print(out)
    for r in failures:
        print(f"REPRO: {r['repro']}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
