#!/usr/bin/env python
"""flowlint CLI: repo-wide static analysis for actor, determinism, and
key-type hazards (foundationdb_tpu/analysis/).

    python scripts/flowlint.py                      # lint the package
    python scripts/flowlint.py foundationdb_tpu     # same, explicit
    python scripts/flowlint.py --changed            # only files in
                                                    #   `git diff HEAD`
    python scripts/flowlint.py --changed main       # ... vs a ref
    python scripts/flowlint.py --format json        # machine-readable
    python scripts/flowlint.py --format sarif       # SARIF 2.1.0 for
                                                    #   PR annotations
    python scripts/flowlint.py --list-rules
    python scripts/flowlint.py --write-baseline     # grandfather current
    python scripts/flowlint.py --dump-callgraph     # resolved call edges
    python scripts/flowlint.py --summary-cache none # no interproc cache

Exit codes: 0 = clean (or every finding baselined), 1 = new findings,
2 = internal error.  Suppress a single line with
``# flowlint: disable=FTL0NN -- <why>``; the committed baseline
(flowlint_baseline.json) holds grandfathered findings, line-free so
they survive unrelated edits.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_BASELINE = os.path.join(REPO, "flowlint_baseline.json")
# Interprocedural fact cache (ISSUE 11): per-file summaries keyed by
# content hash, so `--changed` links the whole program without
# re-parsing the unchanged files.  Never committed (.gitignore).
DEFAULT_SUMMARY_CACHE = os.path.join(REPO, ".flowlint_cache.json")


def changed_files(paths, ref):
    """The .py files under `paths` that differ from `ref` (incremental
    mode): ``git diff --name-only`` plus untracked files (``git
    ls-files --others``), anchored at the first lint path's repository,
    names resolved against that repo's toplevel, filtered to existing
    .py files inside the requested lint roots.  Deleted files drop out
    (nothing to parse); finding paths/baseline identity are untouched —
    each surviving file is linted as a single-file root, which the
    engine rel-ifies exactly like a directory scan."""
    import subprocess
    anchor = os.path.abspath(paths[0])
    anchor_dir = anchor if os.path.isdir(anchor) else \
        os.path.dirname(anchor)
    git = ["git", "-C", anchor_dir]
    top = subprocess.run(git + ["rev-parse", "--show-toplevel"],
                         capture_output=True, text=True)
    if top.returncode != 0:
        raise RuntimeError(f"--changed needs a git checkout: "
                           f"{top.stderr.strip()}")
    toplevel = top.stdout.strip()
    diff = subprocess.run(git + ["diff", "--name-only", ref, "--"],
                          capture_output=True, text=True)
    if diff.returncode != 0:
        raise RuntimeError(f"git diff --name-only {ref} failed: "
                           f"{diff.stderr.strip()}")
    # Untracked files never appear in `git diff` output, yet a brand-new
    # module is the file MOST likely to carry new findings — union them
    # in (fail-soft: an odd git version just degrades to diff-only).
    # Run from the TOPLEVEL: unlike diff, `ls-files --others` lists only
    # the subtree under its cwd, which would drop untracked files under
    # every lint root but the first.
    untracked = subprocess.run(
        ["git", "-C", toplevel, "ls-files", "--others",
         "--exclude-standard"],
        capture_output=True, text=True)
    names = diff.stdout.splitlines()
    if untracked.returncode == 0:
        names += untracked.stdout.splitlines()
    # realpath BOTH sides: `--show-toplevel` is symlink-resolved while
    # the lint roots may be spelled through a symlink (macOS /tmp,
    # symlinked CI workspaces) — a prefix mismatch would silently lint
    # zero files and report the gate green.
    roots = [os.path.realpath(p) for p in paths]
    out = []
    for name in names:
        if not name.endswith(".py"):
            continue
        path = os.path.realpath(os.path.join(toplevel, name))
        if not os.path.exists(path):
            continue
        if any(path == r or path.startswith(r + os.sep) for r in roots):
            out.append(path)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="flowlint: actor/determinism/key-type static analysis")
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(REPO, "foundationdb_tpu")],
                    help="files or directories to lint (default: the "
                         "foundationdb_tpu package)")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="incremental mode: lint only .py files in `git "
                         "diff --name-only REF` (default HEAD) that fall "
                         "under the given paths; baseline and "
                         "suppressions behave exactly as in a full scan. "
                         "Cross-file checks (FTL007 schema drift) only "
                         "see the changed subset — the tier-1 gate "
                         "still runs the full scan")
    ap.add_argument("--format", choices=("text", "json", "sarif"),
                    default="text",
                    help="output format; 'sarif' emits SARIF 2.1.0 "
                         "(rule metadata + error-level results with "
                         "witness chains in the message) for PR "
                         "annotation pipelines")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON path, or 'none' to disable "
                         f"(default: {DEFAULT_BASELINE})")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings to the baseline file "
                         "and exit 0")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--summary-cache", default=DEFAULT_SUMMARY_CACHE,
                    metavar="PATH",
                    help="interprocedural summary-cache path, or 'none' "
                         "to extract everything live "
                         f"(default: {DEFAULT_SUMMARY_CACHE})")
    ap.add_argument("--stats", action="store_true",
                    help="after linting, print per-rule finding/"
                         "suppression counts and phase timings "
                         "(scan/link/total) as JSON to stdout; the "
                         "findings themselves go to stderr so the "
                         "stats stay machine-parseable")
    ap.add_argument("--dump-callgraph", action="store_true",
                    help="print the resolved call graph as JSON edges "
                         "(caller/line/callee/raw target — the "
                         "resolution-debugging view) and exit 0")
    args = ap.parse_args(argv)

    from foundationdb_tpu.analysis import format_text, load_baseline
    from foundationdb_tpu.analysis.engine import Analyzer, write_baseline
    from foundationdb_tpu.analysis.rules import make_rules

    if args.list_rules:
        for rule in make_rules():
            print(f"{rule.id}  {rule.title}")
        return 0

    summary_cache = None if args.summary_cache == "none" \
        else args.summary_cache

    baseline_path = None if args.baseline == "none" else args.baseline
    if args.write_baseline and baseline_path is None:
        # Without this, the fallback below would silently overwrite the
        # committed default baseline with whatever was being inspected.
        ap.error("--write-baseline conflicts with --baseline none")
    if args.write_baseline and args.changed is not None:
        ap.error("--write-baseline needs a full scan, not --changed "
                 "(a partial baseline would un-grandfather every "
                 "unchanged file's findings)")
    if args.changed is not None:
        try:
            args.paths = changed_files(args.paths, args.changed)
        except RuntimeError as e:
            print(f"flowlint: {e}", file=sys.stderr)
            return 2
        if not args.paths:
            if args.dump_callgraph:
                print("[]")         # no changed files: empty graph
                return 0
            from foundationdb_tpu.analysis.engine import (LintResult,
                                                          format_sarif)
            empty = LintResult()
            if args.format == "json":
                print(json.dumps(empty.to_dict(), indent=2))
            elif args.format == "sarif":
                print(format_sarif(empty, make_rules()))
            else:
                print(format_text(empty) +
                      f" (no .py changes vs {args.changed})")
            return 0

    if args.dump_callgraph:
        # AFTER the --changed rewrite, so the dump describes the same
        # file set (hence the same ProgramIndex) the lint would use.
        from foundationdb_tpu.analysis.summaries import ProgramIndex
        try:
            program = ProgramIndex.for_roots(args.paths,
                                             cache_path=summary_cache)
            program.link()
            program.save_cache()
        except Exception as e:  # noqa: BLE001 - CLI boundary
            print(f"flowlint: internal error: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 2
        print(json.dumps(program.dump_callgraph(), indent=2))
        return 0

    try:
        baseline = load_baseline(baseline_path) if baseline_path else []
        rules = make_rules()
        # The wall clock is INJECTED here rather than imported by the
        # engine: analysis/ itself must stay clean under FTL001
        # (wall-clock reads in actor code), and the CLI boundary is
        # where nondeterminism is allowed in.
        clock = None
        if args.stats:
            import time
            clock = time.perf_counter
        result = Analyzer(rules, summary_cache=summary_cache,
                          clock=clock).run(args.paths, baseline)
    except Exception as e:  # noqa: BLE001 - CLI boundary: exit 2, not a trace
        print(f"flowlint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.write_baseline:
        target = baseline_path or DEFAULT_BASELINE
        write_baseline(target, result.new + result.baselined)
        print(f"flowlint: baseline of "
              f"{len(result.new) + len(result.baselined)} finding(s) "
              f"written to {target}")
        return 0

    if args.stats:
        # Findings to stderr, stats JSON to stdout: `flowlint --stats |
        # jq .phases.total` works even when the lint is red.
        if args.format == "json":
            print(json.dumps(result.to_dict(), indent=2),
                  file=sys.stderr)
        else:
            print(format_text(result), file=sys.stderr)
        print(json.dumps(result.stats_dict(), indent=2))
        return result.exit_code

    if args.format == "json":
        print(json.dumps(result.to_dict(), indent=2))
    elif args.format == "sarif":
        from foundationdb_tpu.analysis.engine import format_sarif
        print(format_sarif(result, rules))
    else:
        print(format_text(result))
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
