#!/usr/bin/env python
"""flowlint CLI: repo-wide static analysis for actor, determinism, and
key-type hazards (foundationdb_tpu/analysis/).

    python scripts/flowlint.py                      # lint the package
    python scripts/flowlint.py foundationdb_tpu     # same, explicit
    python scripts/flowlint.py --format json        # machine-readable
    python scripts/flowlint.py --list-rules
    python scripts/flowlint.py --write-baseline     # grandfather current

Exit codes: 0 = clean (or every finding baselined), 1 = new findings,
2 = internal error.  Suppress a single line with
``# flowlint: disable=FTL0NN -- <why>``; the committed baseline
(flowlint_baseline.json) holds grandfathered findings, line-free so
they survive unrelated edits.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_BASELINE = os.path.join(REPO, "flowlint_baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="flowlint: actor/determinism/key-type static analysis")
    ap.add_argument("paths", nargs="*",
                    default=[os.path.join(REPO, "foundationdb_tpu")],
                    help="files or directories to lint (default: the "
                         "foundationdb_tpu package)")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="baseline JSON path, or 'none' to disable "
                         f"(default: {DEFAULT_BASELINE})")
    ap.add_argument("--write-baseline", action="store_true",
                    help="write current findings to the baseline file "
                         "and exit 0")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    from foundationdb_tpu.analysis import format_text, load_baseline
    from foundationdb_tpu.analysis.engine import Analyzer, write_baseline
    from foundationdb_tpu.analysis.rules import make_rules

    if args.list_rules:
        for rule in make_rules():
            print(f"{rule.id}  {rule.title}")
        return 0

    baseline_path = None if args.baseline == "none" else args.baseline
    if args.write_baseline and baseline_path is None:
        # Without this, the fallback below would silently overwrite the
        # committed default baseline with whatever was being inspected.
        ap.error("--write-baseline conflicts with --baseline none")
    try:
        baseline = load_baseline(baseline_path) if baseline_path else []
        result = Analyzer(make_rules()).run(args.paths, baseline)
    except Exception as e:  # noqa: BLE001 - CLI boundary: exit 2, not a trace
        print(f"flowlint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return 2

    if args.write_baseline:
        target = baseline_path or DEFAULT_BASELINE
        write_baseline(target, result.new + result.baselined)
        print(f"flowlint: baseline of "
              f"{len(result.new) + len(result.baselined)} finding(s) "
              f"written to {target}")
        return 0

    if args.format == "json":
        print(json.dumps(result.to_dict(), indent=2))
    else:
        print(format_text(result))
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
