#!/usr/bin/env python
"""Manual smoke driver: boot a real multi-process cluster, run txns.

Usage: python scripts/real_cluster_smoke.py [basedir]
Spawns 4 fdbserver OS processes (1 coordinator+stateless, 1 stateless,
2 storage) on localhost, connects a real client, commits and reads keys,
then (optionally) kills a storage process and checks recovery.
"""

import json
import os
import shutil
import subprocess
import sys
import time

BASE = sys.argv[1] if len(sys.argv) > 1 else "/tmp/fdb_real_smoke"
PORTS = {"coord0": 4700, "stateless1": 4701, "storage0": 4702,
         "storage1": 4703}
COORDS = "127.0.0.1:4700"
CONFIG = json.dumps({"n_storage": 2, "min_workers": 3})

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def spawn_all():
    shutil.rmtree(BASE, ignore_errors=True)
    procs = {}
    for name, port in PORTS.items():
        datadir = os.path.join(BASE, name)
        pclass = "storage" if name.startswith("storage") else "stateless"
        cmd = [sys.executable, "-m", "foundationdb_tpu.server.fdbserver",
               "--port", str(port), "--coordinators", COORDS,
               "--datadir", datadir, "--class", pclass,
               "--config", CONFIG, "--name", name]
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        procs[name] = subprocess.Popen(
            cmd, cwd=REPO, env=env,
            stdout=open(os.path.join(BASE, name + ".out"), "wb")
            if os.path.isdir(BASE) or os.makedirs(BASE) or True else None,
            stderr=subprocess.STDOUT)
    return procs


def client_setup():
    sys.path.insert(0, REPO)
    from foundationdb_tpu.client.database import open_cluster
    return open_cluster(COORDS)


async def commit_kv(db, k, v):
    t = db.create_transaction()
    while True:
        try:
            t.set(k, v)
            return await t.commit()
        except Exception as e:
            await t.on_error(e)


async def read_key(db, k):
    t = db.create_transaction()
    while True:
        try:
            return await t.get(k)
        except Exception as e:
            await t.on_error(e)


def main():
    procs = spawn_all()
    try:
        time.sleep(3)
        dead = {n: p.poll() for n, p in procs.items() if p.poll() is not None}
        if dead:
            print("DEAD AT BOOT:", dead)
            for n in dead:
                print(open(os.path.join(BASE, n + ".out")).read()[-3000:])
            return 1
        print("cluster up; running client txns...")
        loop, db = client_setup()

        async def phase1():
            for i in range(10):
                await commit_kv(db, b"k%02d" % i, b"v%02d" % i)
            assert await read_key(db, b"k07") == b"v07"
            return "ok"

        print("phase1 (10 txns):", loop.run_until(loop.spawn(phase1()), timeout=60))

        # Kill the process hosting the TLog (transaction system member):
        # the cluster must recover into a new epoch over real sockets.
        victim = None
        for name in procs:
            d = os.path.join(BASE, name)
            if any(f.startswith("tlog-") for f in os.listdir(d)):
                victim = name
                break
        assert victim, "no tlog host found"
        print("killing TLog host:", victim)
        procs[victim].kill()
        procs[victim].wait()
        time.sleep(2)
        # Restart it from its datadir (the fdbmonitor role): the boot scan
        # re-instantiates the TLog from its WAL, recovery locks the old
        # generation and the cluster rolls into a new epoch.
        port = PORTS[victim]
        pclass = "storage" if victim.startswith("storage") else "stateless"
        cmd = [sys.executable, "-m", "foundationdb_tpu.server.fdbserver",
               "--port", str(port), "--coordinators", COORDS,
               "--datadir", os.path.join(BASE, victim), "--class", pclass,
               "--config", CONFIG, "--name", victim + ".r2"]
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=REPO)
        procs[victim] = subprocess.Popen(
            cmd, cwd=REPO, env=env,
            stdout=open(os.path.join(BASE, victim + ".r2.out"), "wb"),
            stderr=subprocess.STDOUT)

        async def phase2():
            await commit_kv(db, b"post-kill", b"recovered")
            assert await read_key(db, b"post-kill") == b"recovered"
            assert await read_key(db, b"k03") == b"v03"
            return "ok"

        t0 = time.time()
        print("phase2 (post-kill):",
              loop.run_until(loop.spawn(phase2()), timeout=120),
              f"recovery+txn took {time.time()-t0:.1f}s")
        print("SMOKE OK")
        return 0
    finally:
        for p in procs.values():
            p.kill()
    return 0


if __name__ == "__main__":
    sys.exit(main())
