#!/usr/bin/env python
"""Per-piece cost profile of the resolve step on the live backend.

Times the bench-configuration step (BASELINE config 2 shapes: 100K txns,
200K reads + 100K writes, CAP 2^21, DCAP 2^20) and its constituent device
programs SEPARATELY, each materialized with np.asarray (the axon tunnel's
block_until_ready does not actually block).  Prints one line per piece so
the top cost is obvious; run on TPU (default) or
JAX_PLATFORMS=cpu for the XLA-CPU comparison.

Usage: python scripts/profile_tpu.py [reps] [--small]
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

REPS = int(sys.argv[1]) if len(sys.argv) > 1 and sys.argv[1].isdigit() else 3
SMALL = "--small" in sys.argv

if SMALL:
    T, CAP, DCAP = 2_000, 1 << 16, 1 << 15
else:
    T, CAP, DCAP = 100_000, 1 << 21, 1 << 20
R, W = 2 * T, T


def bucket(n):
    b = 256
    while b < n:
        b <<= 1
    return b


def timed(label, fn, *args, reps=REPS, **kw):
    # warmup/compile
    out = fn(*args, **kw)
    np.asarray(jax.tree_util.tree_leaves(out)[0])
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args, **kw)
    for leaf in jax.tree_util.tree_leaves(out):
        np.asarray(leaf)
    dt = (time.perf_counter() - t0) / reps
    print(f"{label:35s} {dt * 1e3:9.2f} ms")
    return out


import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

print(f"# backend={jax.default_backend()} T={T} CAP={CAP} DCAP={DCAP}")

from bench import gen_batch  # noqa: E402
import bench as _bench  # noqa: E402

_bench.TXNS_PER_BATCH = T
from foundationdb_tpu.conflict.tpu_backend import TpuConflictSet  # noqa: E402
from foundationdb_tpu.ops.digest import searchsorted_left  # noqa: E402
from foundationdb_tpu.ops.rangemax import build_sparse_table  # noqa: E402

rng = np.random.default_rng(2026)
cs = TpuConflictSet(0, capacity=CAP, delta_capacity=DCAP)

batches = []
version = 1_000
for _ in range(4):
    prev, version = version, version + 1_000
    batches.append((version, *gen_batch(rng, version, prev)))

# ---- host-side pack cost (numpy unique/searchsorted grouping) -------------
v0, enc0, _k, _s = batches[0]
t0 = time.perf_counter()
for _ in range(REPS):
    packed = cs._pack(enc0)
print(f"{'host _pack (incl. grouping)':35s} "
      f"{(time.perf_counter() - t0) / REPS * 1e3:9.2f} ms")

# ---- h2d transfer of the packed block(s) ----------------------------------
if packed.get("compact"):
    buf = packed["buf"]

    def h2d(a):
        return jax.device_put(a)

    da = timed(f"h2d compact buf ({buf.nbytes / 1e6:.1f} MB)", h2d, buf)
else:
    dig = packed["digests"]
    meta = packed["meta"]

    def h2d(a, b):
        return jax.device_put(a), jax.device_put(b)

    da, db = timed("h2d digests+meta "
                   f"({(dig.nbytes + meta.nbytes) / 1e6:.0f} MB)",
                   h2d, dig, meta)

# ---- full step + merge ----------------------------------------------------
for v, enc, _k, _s in batches[:2]:
    cs.resolve_encoded(enc, v, 0)     # compile both programs

v, enc, _k, _s = batches[2]


def full_step():
    h = cs.resolve_encoded_async(enc, v + 50_000, 0)
    return h.wait_codes()


timed("full resolve step (steady delta)", full_step, reps=1)
t0 = time.perf_counter()
cs.merge()
np.asarray(cs.bv)
print(f"{'merge (overlay+GC+rebase+table)':35s} "
      f"{(time.perf_counter() - t0) * 1e3:9.2f} ms")

# ---- isolated pieces at the same shapes -----------------------------------
r_cap = bucket(R)
from foundationdb_tpu.ops.digest import max_digest_block  # noqa: E402
qsrc = max_digest_block(r_cap)
qsrc[:, :enc0.r_begin.shape[1]] = enc0.r_begin[:, :r_cap]
qb = jnp.asarray(qsrc)
timed("searchsorted R queries into CAP",
      jax.jit(lambda bk, q: searchsorted_left(bk, q)), cs.bk, qb)
timed("searchsorted R queries into DCAP",
      jax.jit(lambda dk, q: searchsorted_left(dk, q)), cs.dk, qb)
# The fused probe pass (ISSUE 6): begin (right-side) + end (left-side)
# probes in ONE loop per table — compare against 2x the single-sided rows.
from foundationdb_tpu.ops.digest import searchsorted_interval  # noqa: E402
timed("fused begin+end probe into CAP",
      jax.jit(lambda bk, q: searchsorted_interval(bk, q, q)), cs.bk, qb)
# Hoisted delta range-max table (ISSUE 6): built by this SEPARATE program
# after each insert (tpu_backend threads it through the step signature);
# the per-batch resolve step itself contains no table build.
timed("delta_table_step(DCAP) [hoisted]",
      jax.jit(build_sparse_table), cs.dv)

cover = jnp.zeros((bucket(W) + 1,), jnp.int32)
widx = jnp.asarray(np.arange(bucket(W)) % bucket(W), dtype=np.int32)
wtxn = jnp.asarray(np.arange(bucket(W), dtype=np.int32))


def fixpoint_round(c, wi, wt):
    cv = c.at[wi].min(wt)
    return cv[jnp.clip(wi, 0, bucket(W))]


timed("one point fixpoint round", jax.jit(fixpoint_round), cover, widx, wtxn)

out8 = jnp.zeros((bucket(T) + 12,), jnp.int8)
t0 = time.perf_counter()
for _ in range(REPS):
    np.asarray(out8)
print(f"{'d2h out int8[t_cap+12]':35s} "
      f"{(time.perf_counter() - t0) / REPS * 1e3:9.2f} ms")
