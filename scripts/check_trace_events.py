#!/usr/bin/env python
"""Lint TraceEvent type names across the codebase.

Run from a tier-1 test (tests/test_metrics.py) so drift fails fast:

1. every ``TraceEvent("Name")`` literal must be UpperCamelCase
   (``^[A-Z][A-Za-z0-9]*$`` — the reference's convention, and what keeps
   the JSONL greppable);
2. no two MODULES may emit the same Type with different *chained* detail
   schemas: a Type is a contract for trace consumers (commit_debug,
   tests, dashboards), so the same name meaning different shapes in
   different files is a bug.  Only details chained directly onto the
   TraceEvent(...) constructor call are compared — details added later
   through a variable are invisible to static analysis and treated as
   "open" (that callsite exempts itself from the schema comparison).

Exit status 0 = clean; 1 = violations (printed one per line).
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Optional, Set, Tuple

CAMEL = re.compile(r"^[A-Z][A-Za-z0-9]*$")

# Types allowed to differ across modules: established cross-role
# correlation events whose Location field IS the schema discriminator,
# emitted via the shared trace_batch_event helper.
SCHEMA_ALLOWLIST = {"CommitDebug", "TransactionDebug"}


def _chain(call: ast.Call) -> Optional[Tuple[str, Optional[Set[str]]]]:
    """For the OUTERMOST call of a TraceEvent(...).detail(...)... chain,
    return (type_name, chained detail keys or None when a key is not a
    literal).  None for calls that are not such a chain."""
    keys: Set[str] = set()
    opaque = False
    node = call
    while True:
        f = node.func
        if isinstance(f, ast.Attribute):
            if f.attr == "detail":
                if node.args and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    keys.add(node.args[0].value)
                else:
                    opaque = True
            elif f.attr not in ("error", "log"):
                return None
            if not isinstance(f.value, ast.Call):
                return None
            node = f.value
            continue
        if isinstance(f, ast.Name) and f.id == "TraceEvent":
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                return node.args[0].value, (None if opaque else keys)
            return None
        return None


def scan_file(path: str):
    """Yield (type_name, keys_or_None, lineno) for every TraceEvent chain
    rooted in `path`."""
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    # Only the outermost call of each chain: collect every Call that is
    # the .func.value of another chain member and skip those.
    inner = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Call):
            inner.add(id(node.func.value))
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and id(node) not in inner:
            got = _chain(node)
            if got is not None:
                yield got[0], got[1], node.lineno


def check(root: str) -> List[str]:
    errors: List[str] = []
    # type -> {module: [keyset or None, ...]}
    by_type: Dict[str, Dict[str, List[Optional[Set[str]]]]] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, root)
            for type_name, keys, lineno in scan_file(path):
                if not CAMEL.match(type_name):
                    errors.append(
                        f"{rel}:{lineno}: TraceEvent type "
                        f"{type_name!r} is not UpperCamelCase")
                by_type.setdefault(type_name, {}).setdefault(
                    rel, []).append(keys)
    for type_name, modules in sorted(by_type.items()):
        if len(modules) < 2 or type_name in SCHEMA_ALLOWLIST:
            continue
        # Compare the union of literal keysets per module; an opaque
        # callsite (None) makes that module "open" and exempt.
        schemas = {}
        for mod, keysets in modules.items():
            if any(k is None for k in keysets):
                continue
            schemas[mod] = frozenset().union(*keysets)
        distinct = set(schemas.values())
        if len(distinct) > 1:
            detail = "; ".join(
                f"{m}: {sorted(s) or ['<none>']}"
                for m, s in sorted(schemas.items()))
            errors.append(
                f"TraceEvent type {type_name!r} emitted from "
                f"{len(modules)} modules with different detail "
                f"schemas: {detail}")
    return errors


def main(argv=None) -> int:
    root = (argv or sys.argv[1:] or [None])[0]
    if root is None:
        root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            os.pardir, "foundationdb_tpu")
    errors = check(os.path.abspath(root))
    for e in errors:
        print(e)
    print(f"check_trace_events: {len(errors)} violation(s)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
