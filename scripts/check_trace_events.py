#!/usr/bin/env python
"""Lint TraceEvent type names across the codebase — THIN SHIM.

The actual analysis now lives in flowlint rule FTL007
(foundationdb_tpu/analysis/rules.py TraceEventRule); this script keeps
the original CLI surface (and the ``check(root)`` entry point
tests/test_metrics.py imports) for compatibility:

1. every ``TraceEvent("Name")`` literal must be UpperCamelCase;
2. no two MODULES may emit the same Type with different *chained*
   detail schemas (details added through a variable make a callsite
   "open" and exempt from the comparison).

Exit status 0 = clean; 1 = violations (printed one per line).
Prefer ``python scripts/flowlint.py`` for the full rule set.
"""

from __future__ import annotations

import os
import sys
from typing import List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def check(root: str) -> List[str]:
    """Run FTL007 only over `root`; returns the old-format error lines."""
    from foundationdb_tpu.analysis.engine import Analyzer
    from foundationdb_tpu.analysis.rules import TraceEventRule
    result = Analyzer([TraceEventRule()]).run([root])
    errors = []
    for f in result.new:
        errors.append(f"{f.path}:{f.line}: {f.message}" if f.line
                      else f.message)
    return errors


def main(argv=None) -> int:
    root = (argv or sys.argv[1:] or [None])[0]
    if root is None:
        root = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            os.pardir, "foundationdb_tpu")
    errors = check(os.path.abspath(root))
    for e in errors:
        print(e)
    print(f"check_trace_events: {len(errors)} violation(s)")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
