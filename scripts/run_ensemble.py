#!/usr/bin/env python
"""Simulation ensemble runner: (spec, seed, buggify) tuples in sequence.

Reference: contrib/TestHarness/Program.cs.cmake — the C# orchestrator that
picks random (test file, seed, buggify) tuples, runs `fdbserver -r
simulation` for each, and triages failures.  Here every run is a fresh
deterministic event loop + simulated cluster in-process; a failure is
reproducible from its printed (spec, seed, buggify) tuple:

    python scripts/run_ensemble.py --seeds 5 --specs tests/specs
    python scripts/run_ensemble.py --spec tests/specs/CycleTest.toml --seed 17
"""

import argparse
import glob
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_one(spec_path: str, seed: int, buggify: bool) -> dict:
    from foundationdb_tpu.core import (DeterministicRandom, enable_buggify,
                                       set_deterministic_random,
                                       set_event_loop)
    from foundationdb_tpu.rpc.sim import set_simulator
    from foundationdb_tpu.server.cluster import SimFdbCluster
    from foundationdb_tpu.server.interfaces import DatabaseConfiguration
    from foundationdb_tpu.testing import load_spec, run_test

    set_deterministic_random(DeterministicRandom(seed))
    enable_buggify(buggify)
    try:
        # MoveKeys-style specs need spare storage teams to actually move
        # data; everything else runs on the lean default topology.
        if "MoveKeys" in os.path.basename(spec_path):
            config = DatabaseConfiguration(
                n_tlogs=2, log_replication=2, n_storage=3,
                storage_replication=2)
            n_workers, n_storage_workers = 8, 3
        else:
            config = DatabaseConfiguration(
                n_tlogs=2, log_replication=2, n_storage=2,
                storage_replication=2)
            n_workers, n_storage_workers = 7, 2
        cluster = SimFdbCluster(config=config, n_workers=n_workers,
                                n_storage_workers=n_storage_workers)
        spec = load_spec(open(spec_path).read())

        async def go():
            return await run_test(cluster, spec)

        return cluster.run_until(cluster.loop.spawn(go()), timeout=1800)
    finally:
        enable_buggify(False)
        set_simulator(None)
        set_event_loop(None)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--specs", default="tests/specs",
                    help="directory of .toml specs (default tests/specs)")
    ap.add_argument("--spec", default=None, help="run one spec file only")
    ap.add_argument("--seeds", type=int, default=3,
                    help="seeds per spec (default 3)")
    ap.add_argument("--seed", type=int, default=None,
                    help="run one seed only (repro mode)")
    ap.add_argument("--no-buggify", action="store_true")
    args = ap.parse_args()

    specs = [args.spec] if args.spec else sorted(
        glob.glob(os.path.join(args.specs, "*.toml")))
    seeds = [args.seed] if args.seed is not None else \
        [100 + i for i in range(args.seeds)]

    failures = []
    total = 0
    for spec_path in specs:
        for seed in seeds:
            buggify = (not args.no_buggify) and seed % 2 == 0
            total += 1
            tag = (f"{os.path.basename(spec_path)} seed={seed} "
                   f"buggify={buggify}")
            t0 = time.time()
            try:
                run_one(spec_path, seed, buggify)
                print(f"PASS {tag} ({time.time() - t0:.1f}s)")
            except BaseException:
                print(f"FAIL {tag} ({time.time() - t0:.1f}s)")
                traceback.print_exc()
                failures.append(tag)
    from foundationdb_tpu.core.coverage import missing, report
    hit = {k: v for k, v in report().items() if v}
    print(f"\ncoverage markers hit: {sorted(hit)}")
    if missing():
        print(f"coverage markers NEVER hit: {missing()} "
              "(reference TestHarness-style coverage ledger)")
    print(f"\n{total - len(failures)}/{total} passed")
    for f in failures:
        print(f"  FAILED: {f}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
