"""Conflict-aware transaction scheduling (ISSUE 12).

Three independently knob-gated stages that convert doomed resolve-and-
abort round trips into useful work, grounded in "Intelligent Transaction
Scheduling via Conflict Prediction in OLTP DBMS" (arXiv 2409.01675) and
"Transaction Repair: Full Serializability Without Locks" (arXiv
1403.5645):

* **predictor** (GRV admission, ``SCHED_PREDICTOR_ENABLED``): a
  deterministic per-proxy hot-range table of decayed abort-probability
  EMAs, fed from the resolvers' conflict-heat trackers via a
  ratekeeper-pattern piggyback.  A transaction whose declared tag maps
  to a predicted-doomed range is briefly deferred (starvation-proof:
  ``SCHED_MAX_DEFERRALS``) instead of resolving into a guaranteed abort
  — when it is finally admitted it reads at a FRESHER version, which is
  what actually saves it.
* **reorder** (commit-proxy batch assembly, ``SCHED_REORDER_ENABLED``):
  a cheap host-side pre-pass ordering same-batch transactions so
  intra-batch readers run before the writers that would abort them
  (greedy topological order over write-vs-read interval overlap,
  deterministic tiebreak).  Identity — provably verdict-order-
  independent — when disabled.
* **repair** (commit proxy post-resolution, ``SCHED_REPAIR_ENABLED`` +
  per-transaction opt-in): a transaction aborted purely on read-set
  staleness with EXACT culprit attribution is re-stamped at a fresh
  read version and re-resolved once server-side
  (``TXN_REPAIR_MAX_ATTEMPTS``), converting a full client bounce into
  one extra resolver round trip.  Opt-in because the server cannot
  re-run client logic: the client declares its mutations remain valid
  under re-read (blind writes, atomic ops, existence guards).

Everything here is deterministic under simulation: no wall clock (decay
is driven by feed cadence), dict/sorted iteration only, and every stage
is bit-invisible when its knob is off (the abort-set parity guard in
tests/test_sched.py pins that).
"""

from .predictor import ConflictPredictor
from .reorder import reorder_batch
from .repair import repair_eligible

__all__ = ["ConflictPredictor", "reorder_batch", "repair_eligible"]
