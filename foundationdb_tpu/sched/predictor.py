"""Conflict predictor: the per-proxy hot-range abort-probability table.

Reference shape: the admission-control model of "Intelligent Transaction
Scheduling via Conflict Prediction in OLTP DBMS" (arXiv 2409.01675),
instantiated on the feed this cluster already produces — the resolvers'
``ConflictHeatTracker`` rows (decayed per-range conflict/load counts
with per-tag/per-tenant attribution, conflict/heat.py) ride the
ratekeeper's ``GetRateInfoReply`` to every GRV proxy, exactly like the
tps budget does.

Each GRV proxy folds the rows into ONE deterministic table:

* per range: an EMA of the observed abort probability (attributed
  conflicts vs sampled load), decayed toward zero when a range stops
  appearing in the feed;
* per tag / tenant: which predicted-doomed range (abort-prob EMA above
  ``SCHED_PREDICTOR_ABORT_P``) the identity currently maps to, derived
  from the rows' own attribution breakdowns.

Admission consults :meth:`ConflictPredictor.is_doomed` with the GRV
request's declared tags; the proxy defers doomed requests by a short
knob-bounded delay (starvation-proof via the max-defer count — the
proxy's job, not this table's).

Determinism: no wall clock anywhere — decay advances once per
:meth:`update` call (feed cadence); iteration is over insertion-ordered
dicts and sorted projections only, so two predictors fed the same rows
are bit-identical under any PYTHONHASHSEED.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple


class ConflictPredictor:
    """Decayed abort-probability EMAs keyed by conflict range, with the
    tag/tenant -> predicted-doomed-range mapping admission consults."""

    __slots__ = ("alpha", "abort_p", "min_conflicts", "table_max",
                 "ranges", "doomed_tags", "doomed_tenants", "updates")

    def __init__(self, alpha: float = 0.5, abort_p: float = 0.5,
                 min_conflicts: float = 4.0, table_max: int = 512) -> None:
        self.alpha = min(max(float(alpha), 0.01), 1.0)
        self.abort_p = float(abort_p)
        self.min_conflicts = float(min_conflicts)
        self.table_max = max(16, int(table_max))
        # (begin, end) -> [prob_ema, conflicts_ema, {tag: conflicts},
        # {tenant: conflicts}]; insertion-ordered for determinism.
        self.ranges: Dict[Tuple[bytes, bytes], list] = {}
        self.doomed_tags: Dict[str, Tuple[bytes, bytes]] = {}
        self.doomed_tenants: Dict[int, Tuple[bytes, bytes]] = {}
        self.updates = 0

    @classmethod
    def from_knobs(cls, knobs) -> "ConflictPredictor":
        return cls(alpha=float(knobs.SCHED_PREDICTOR_ALPHA),
                   abort_p=float(knobs.SCHED_PREDICTOR_ABORT_P),
                   min_conflicts=float(knobs.SCHED_PREDICTOR_MIN_CONFLICTS),
                   table_max=int(knobs.SCHED_PREDICTOR_TABLE_MAX))

    # -- feed ----------------------------------------------------------------
    @staticmethod
    def _row_prob(conflicts: float, load: float) -> float:
        """Observed abort weight of one feed row: attributed conflicts
        vs the load column.  Load is already 1-in-SAMPLE_EVERY
        subsampled upstream, so this ratio deliberately overweights
        conflicts — every attributed abort is hard evidence, a load
        sample stands for ~one-eighth of the traffic — which is what
        lets a genuinely doomed range clear SCHED_PREDICTOR_ABORT_P
        while cold ranges stay far below it."""
        denom = conflicts + load
        return conflicts / denom if denom > 0 else 0.0

    def update(self, rows: Iterable) -> None:
        """Fold one feed snapshot.  ``rows`` are the resolver heat rows:
        ``(begin, end, conflicts, load, {tag: conflicts},
        {tenant: conflicts})`` tuples (trailing members optional).
        Ranges absent from the snapshot decay toward zero and drop out;
        the doom maps are recomputed from the post-fold table."""
        self.updates += 1
        a = self.alpha
        seen = set()
        for row in rows or ():
            begin, end, conflicts, load = row[0], row[1], row[2], row[3]
            tags = row[4] if len(row) > 4 else {}
            tenants = row[5] if len(row) > 5 else {}
            key = (bytes(begin), bytes(end))
            seen.add(key)
            p_obs = self._row_prob(float(conflicts), float(load))
            e = self.ranges.get(key)
            if e is None:
                e = self.ranges[key] = [p_obs, float(conflicts),
                                        dict(tags or {}),
                                        dict(tenants or {})]
            else:
                e[0] += a * (p_obs - e[0])
                e[1] += a * (float(conflicts) - e[1])
                e[2] = dict(tags or {})
                e[3] = dict(tenants or {})
        # Ranges gone cold (absent from the feed) decay toward zero and
        # drop below noise — a hotspot that moved must stop dooming its
        # old identities within a few cadences.
        for key in [k for k in self.ranges if k not in seen]:
            e = self.ranges[key]
            e[0] *= (1.0 - a)
            e[1] *= (1.0 - a)
            if e[1] < 0.5:
                del self.ranges[key]
        if len(self.ranges) > self.table_max:
            # Keep the hottest table_max rows; deterministic ordering
            # (prob desc, then range key) so equal-prob ties never
            # depend on insertion history.
            keep = sorted(self.ranges.items(),
                          key=lambda kv: (-kv[1][0], kv[0]))[:self.table_max]
            self.ranges = dict(keep)
        self._recompute_doom()

    def _recompute_doom(self) -> None:
        tags: Dict[str, Tuple[bytes, bytes]] = {}
        tenants: Dict[int, Tuple[bytes, bytes]] = {}
        for key in sorted(self.ranges):
            prob, conflicts, row_tags, row_tenants = self.ranges[key]
            if prob < self.abort_p or conflicts < self.min_conflicts:
                continue
            for tag in sorted(row_tags):
                if tag and tag not in tags:
                    tags[tag] = key
            for tenant in sorted(row_tenants):
                if tenant >= 0 and tenant not in tenants:
                    tenants[tenant] = key
        self.doomed_tags = tags
        self.doomed_tenants = tenants

    # -- queries -------------------------------------------------------------
    def is_doomed(self, tags: Iterable[str] = (),
                  tenant_id: int = -1) -> bool:
        """Does any declared identity map to a predicted-doomed range?"""
        for tag in tags or ():
            if tag in self.doomed_tags:
                return True
        return tenant_id is not None and tenant_id >= 0 and \
            tenant_id in self.doomed_tenants

    def doomed_range_for(self, tags: Iterable[str] = (),
                         tenant_id: int = -1
                         ) -> Optional[Tuple[bytes, bytes]]:
        for tag in tags or ():
            r = self.doomed_tags.get(tag)
            if r is not None:
                return r
        if tenant_id is not None and tenant_id >= 0:
            return self.doomed_tenants.get(tenant_id)
        return None

    def range_prob(self, begin: bytes, end: bytes) -> float:
        e = self.ranges.get((begin, end))
        return e[0] if e is not None else 0.0

    def hot_ranges(self, k: int = 8) -> List[Tuple[bytes, bytes, float]]:
        rows = [(b, e, v[0]) for (b, e), v in self.ranges.items()]
        rows.sort(key=lambda r: (-r[2], r[0], r[1]))
        return rows[:k]

    def status(self) -> dict:
        """The per-proxy slice of status cluster.scheduler."""
        def pr(b: bytes) -> str:
            return b.decode("utf-8", "backslashreplace")

        return {
            "tracked_ranges": len(self.ranges),
            "updates": self.updates,
            "doomed_tags": sorted(self.doomed_tags),
            "doomed_tenants": sorted(self.doomed_tenants),
            "hot_ranges": [
                {"begin": pr(b), "end": pr(e), "abort_p": round(p, 4)}
                for b, e, p in self.hot_ranges()],
        }
