"""Transaction repair eligibility: staleness-only aborts, exactly blamed.

Reference: "Transaction Repair: Full Serializability Without Locks"
(arXiv 1403.5645) — an aborted transaction whose only sin is a stale
read set can be salvaged by re-executing against fresh reads instead of
bouncing to the client.  This plane cannot re-run client logic, so the
salvage is OPT-IN (``Transaction.repairable``): the client declares its
mutations remain valid under re-read — blind writes, atomic ops,
existence guards.  The commit proxy then re-stamps the transaction at a
fresh read version and re-resolves it once (``TXN_REPAIR_MAX_ATTEMPTS``),
converting a full client round trip into one extra resolver hop.

The eligibility predicate is deliberately strict:

* the abort's attribution must be EXACT (the resolvers pinned the true
  culprit ranges; conservative whole-read-set blame proves nothing);
* every culprit must lie INSIDE the transaction's declared read set —
  pure read-set staleness, no write-write component to re-stamp away
  (in this OCC plane conflicts are read-vs-write by construction, so a
  culprit escaping the read set marks attribution breakage, not a
  repairable abort);
* the attempt budget must not be exhausted.

Pure functions, no clock, no RNG — callable from the proxy's commit
path and from the bench's host-side pipeline model alike.
"""

from __future__ import annotations

from typing import Iterable, List, Sequence, Tuple


def culprits_in_read_set(read_ranges: Sequence,
                         culprits: Iterable[Tuple[bytes, bytes]]) -> bool:
    """Every culprit [b, e) contained in some declared read range.
    Culprits arrive clipped per resolver, so containment (not equality)
    is the right test."""
    spans = [(r.begin, r.end) for r in read_ranges]
    for b, e in culprits:
        if not any(rb <= b and e <= re for rb, re in spans):
            return False
    return True


def repair_eligible(txn, culprits: List[Tuple[bytes, bytes]],
                    exact: bool, attempt: int, max_attempts: int) -> bool:
    """Can this CONFLICT-verdict transaction be re-stamped and
    re-resolved server-side?  See the module doc for the gates."""
    if attempt >= max_attempts:
        return False
    if not exact or not culprits:
        return False
    return culprits_in_read_set(txn.read_conflict_ranges, culprits)
