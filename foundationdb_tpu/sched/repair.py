"""Transaction repair eligibility: staleness-only aborts, exactly blamed.

Reference: "Transaction Repair: Full Serializability Without Locks"
(arXiv 1403.5645) — an aborted transaction whose only sin is a stale
read set can be salvaged by re-executing against fresh reads instead of
bouncing to the client.  This plane cannot re-run client logic, so the
salvage is OPT-IN (``Transaction.repairable``): the client declares its
mutations remain valid under re-read — blind writes, atomic ops,
existence guards.  The commit proxy then re-stamps the transaction at a
fresh read version and re-resolves it once (``TXN_REPAIR_MAX_ATTEMPTS``),
converting a full client round trip into one extra resolver hop.

The eligibility predicate is deliberately strict:

* the abort's attribution must be EXACT (the resolvers pinned the true
  culprit ranges; conservative whole-read-set blame proves nothing);
* every culprit must lie INSIDE the transaction's declared read set —
  pure read-set staleness, no write-write component to re-stamp away
  (in this OCC plane conflicts are read-vs-write by construction, so a
  culprit escaping the read set marks attribution breakage, not a
  repairable abort);
* the attempt budget must not be exhausted.

Beyond the single re-resolution, ``RepairLadder`` implements the bounded
multi-attempt ladder (``TXN_REPAIR_MAX_ATTEMPTS`` > 1): each FAILED
re-resolution of a culprit range backs that RANGE off for
``backoff_versions`` doubling per rung, on the commit-VERSION clock — no
wall time, so the ladder is deterministic in simulation and identical in
the bench's pipeline model.  A range rewritten faster than one batch
interval stops burning resolver round trips after a couple of rungs,
while cold ranges keep repairing at full speed; entries expire as the
version clock passes them.

Pure functions + a pure-state class, no clock, no RNG — callable from
the proxy's commit path and from the bench's host-side pipeline model
alike.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence, Tuple


def culprits_in_read_set(read_ranges: Sequence,
                         culprits: Iterable[Tuple[bytes, bytes]]) -> bool:
    """Every culprit [b, e) contained in some declared read range.
    Culprits arrive clipped per resolver, so containment (not equality)
    is the right test."""
    spans = [(r.begin, r.end) for r in read_ranges]
    for b, e in culprits:
        if not any(rb <= b and e <= re for rb, re in spans):
            return False
    return True


def repair_eligible(txn, culprits: List[Tuple[bytes, bytes]],
                    exact: bool, attempt: int, max_attempts: int) -> bool:
    """Can this CONFLICT-verdict transaction be re-stamped and
    re-resolved server-side?  See the module doc for the gates."""
    if attempt >= max_attempts:
        return False
    if not exact or not culprits:
        return False
    return culprits_in_read_set(txn.read_conflict_ranges, culprits)


class RepairLadder:
    """Per-range repair backoff on the commit-version clock.

    ``note_failure(culprits, version)`` is called when a repair
    attempt's re-resolution STILL conflicted: every culprit range climbs
    one rung and is blocked until ``version + backoff << (rung-1)``.
    ``should_attempt(culprits, version)`` gates the next repair of any
    transaction blaming a blocked range.  State is bounded by
    ``table_max`` (expired entries trimmed first, then the
    earliest-expiring — the least-blocked — so the hottest ranges keep
    their rungs).  Deliberately version-driven: deterministic in
    simulation, replayable in the bench model, and self-expiring as the
    cluster's version clock advances."""

    __slots__ = ("backoff_versions", "table_max", "_entries")

    def __init__(self, backoff_versions: int = 1000,
                 table_max: int = 1024) -> None:
        self.backoff_versions = max(1, int(backoff_versions))
        self.table_max = max(1, int(table_max))
        # (begin, end) -> [blocked_until_version, rung]
        self._entries: Dict[Tuple[bytes, bytes], list] = {}

    def should_attempt(self, culprits: Iterable[Tuple[bytes, bytes]],
                       version: int) -> bool:
        entries = self._entries
        for key in culprits:
            ent = entries.get(key)
            if ent is not None and version < ent[0]:
                return False
        return True

    def note_failure(self, culprits: Iterable[Tuple[bytes, bytes]],
                     version: int) -> None:
        entries = self._entries
        for key in culprits:
            ent = entries.get(key)
            if ent is None:
                entries[key] = [version + self.backoff_versions, 1]
            else:
                rung = min(ent[1] + 1, 16)   # cap the shift, not the block
                ent[0] = version + (self.backoff_versions << (rung - 1))
                ent[1] = rung
        if len(entries) > self.table_max:
            self._trim(version)

    def note_success(self, spans: Iterable[Tuple[bytes, bytes]]) -> None:
        """A repair covering these read spans committed: drop the rungs
        of every blocked range CONTAINED in them.  Containment, not
        equality — entries are keyed by resolver-CLIPPED culprit
        fragments (see culprits_in_read_set), so a straddling range's
        fragments must still clear when the whole declared range
        repairs."""
        entries = self._entries
        if not entries:
            return
        spans = list(spans)
        if not spans:
            return
        for key in [k for k in entries
                    if any(sb <= k[0] and k[1] <= se for sb, se in spans)]:
            del entries[key]

    def blocked_count(self, version: int) -> int:
        return sum(1 for until, _ in self._entries.values()
                   if version < until)

    def _trim(self, version: int) -> None:
        entries = self._entries
        expired = [k for k, (until, _r) in entries.items()
                   if until <= version]
        for k in expired:
            del entries[k]
        if len(entries) > self.table_max:
            for k in sorted(entries, key=lambda k: entries[k][0])[
                    :len(entries) - self.table_max]:
                del entries[k]
