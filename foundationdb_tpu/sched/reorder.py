"""Intra-batch conflict-aware reordering: readers before their writers.

The resolver's intra-batch rule (conflict/oracle.py step 3, reference
SkipList.cpp checkIntraBatchConflicts) is ORDER-SENSITIVE: a transaction
aborts when an EARLIER surviving transaction's write ranges overlap its
reads.  Batch order is the commit proxy's choice — so order the batch to
minimize self-inflicted aborts before resolution ever sees it.

Model: hazard edge ``x -> y`` when ``W(x) ∩ R(y) != ∅`` (x placed before
y aborts y).  The greedy topological order places, at every step, a
transaction none of whose writes are read by any still-unplaced
transaction (placing it can abort nobody — and, inductively, nothing
already placed threatens IT either, so an acyclic batch reorders to ZERO
intra-batch aborts).  Cycles — mutual read-modify-write cliques, whose
aborts are genuine — break on minimum remaining in-degree.  Ties break
on the original index everywhere, so the order is deterministic.

Cost: interval overlap is computed once between DISTINCT read and write
intervals (point writes — the dominant shape — by bisect; true range
writes by a short linear scan).  Past ``exact_max`` transactions the
per-edge Kahn bookkeeping would be quadratic on hot-key cliques, so the
pre-pass degrades to its one-round approximation: a stable sort by
initial in-degree (readers of contested ranges first, contested writers
last), which preserves determinism and captures most of the win at
bench batch sizes.

Disabled-path guarantee: the proxy skips this module entirely when
``SCHED_REORDER_ENABLED`` is off, so verdicts are bit-identical to the
pre-scheduler pipeline (the parity guard in tests/test_sched.py).
"""

from __future__ import annotations

import heapq
from bisect import bisect_left
from typing import Dict, List, Sequence, Tuple


def _point_end(begin: bytes, end: bytes) -> bool:
    """Single-key range (k, k + b"\\x00")?  For these, overlap with
    [rb, re) reduces to rb <= begin < re — pure bisect territory."""
    return end == begin + b"\x00"


class _Intervals:
    """Distinct-interval registry + overlap queries for one batch."""

    def __init__(self) -> None:
        self.ids: Dict[Tuple[bytes, bytes], int] = {}
        self.spans: List[Tuple[bytes, bytes]] = []

    def intern(self, begin: bytes, end: bytes) -> int:
        key = (begin, end)
        iv = self.ids.get(key)
        if iv is None:
            iv = self.ids[key] = len(self.spans)
            self.spans.append(key)
        return iv


def _overlaps(reads: _Intervals, writes: _Intervals
              ) -> List[List[int]]:
    """overlapping[riv] = write interval ids intersecting read iv riv
    (ascending).  Point writes via one bisect window per read; wide
    writes via a linear scan of the (short) wide list."""
    points: List[Tuple[bytes, int]] = []
    wide: List[Tuple[bytes, bytes, int]] = []
    for wiv, (wb, we) in enumerate(writes.spans):
        if _point_end(wb, we):
            points.append((wb, wiv))
        else:
            wide.append((wb, we, wiv))
    points.sort()
    p_begins = [b for b, _iv in points]
    out: List[List[int]] = []
    for rb, re_ in reads.spans:
        hit = [iv for _b, iv in points[bisect_left(p_begins, rb):
                                       bisect_left(p_begins, re_)]]
        for wb, we, wiv in wide:
            if wb < re_ and we > rb:
                hit.append(wiv)
        out.append(hit)
    return out


def _batch_intervals(txns: Sequence) -> Tuple[
        _Intervals, _Intervals, List[List[int]], List[List[int]]]:
    reads = _Intervals()
    writes = _Intervals()
    reads_of: List[List[int]] = []
    writes_of: List[List[int]] = []
    for t in txns:
        reads_of.append(sorted({reads.intern(r.begin, r.end)
                                for r in t.read_conflict_ranges
                                if r.begin < r.end}))
        writes_of.append(sorted({writes.intern(w.begin, w.end)
                                 for w in t.write_conflict_ranges
                                 if w.begin < w.end}))
    return reads, writes, reads_of, writes_of


def reorder_batch(txns: Sequence, exact_max: int = 1024) -> List[int]:
    """New batch order as a list of original indices (a permutation of
    range(len(txns))).  Pure function of the transactions' conflict
    ranges — no clock, no RNG."""
    n = len(txns)
    if n <= 1:
        return list(range(n))
    reads, writes, reads_of, writes_of = _batch_intervals(txns)
    overlapping = _overlaps(reads, writes)

    # readers[riv] / writers[wiv]: txn ids using each distinct interval.
    readers: List[List[int]] = [[] for _ in reads.spans]
    writers: List[List[int]] = [[] for _ in writes.spans]
    for t in range(n):
        for riv in reads_of[t]:
            readers[riv].append(t)
        for wiv in writes_of[t]:
            writers[wiv].append(t)

    if n <= exact_max:
        return _greedy_topological(n, reads_of, overlapping, readers,
                                   writers)
    return _static_indegree_order(n, reads_of, writes_of, overlapping,
                                  readers, writers)


def _greedy_topological(n: int, reads_of, overlapping, readers,
                        writers) -> List[int]:
    """Exact greedy Kahn: in-degree of x = number of distinct unplaced
    transactions reading something x writes.  Placing a reader y
    decrements every such x (out_edges[y])."""
    out_edges: List[set] = [set() for _ in range(n)]
    for riv, rdrs in enumerate(readers):
        if not rdrs:
            continue
        union: set = set()
        for wiv in overlapping[riv]:
            union.update(writers[wiv])
        if not union:
            continue
        for y in rdrs:
            out_edges[y].update(union)
    indeg = [0] * n
    for y in range(n):
        for x in out_edges[y]:
            if x != y:
                indeg[x] += 1
    heap = [(indeg[x], x) for x in range(n)]
    heapq.heapify(heap)
    placed = [False] * n
    order: List[int] = []
    while heap:
        d, x = heapq.heappop(heap)
        if placed[x]:
            continue
        if d != indeg[x]:
            heapq.heappush(heap, (indeg[x], x))
            continue
        placed[x] = True
        order.append(x)
        for z in out_edges[x]:
            if not placed[z] and z != x:
                indeg[z] -= 1
                heapq.heappush(heap, (indeg[z], z))
    return order


def _static_indegree_order(n: int, reads_of, writes_of, overlapping,
                           readers, writers) -> List[int]:
    """One-round approximation for big batches: stable sort by initial
    in-degree (reader-instance counts, not deduped across intervals —
    the dedup is what costs quadratic memory on hot-key cliques)."""
    # readers_over[wiv]: read instances hitting write interval wiv.
    readers_over = [0] * len(writers)
    for riv, wivs in enumerate(overlapping):
        cnt = len(readers[riv])
        if cnt:
            for wiv in wivs:
                readers_over[wiv] += cnt
    # Self pairs: a txn reading what it writes must not inflate its own
    # in-degree (RMW is the common case, not a hazard against itself).
    indeg = [0] * n
    for t in range(n):
        for wiv in writes_of[t]:
            indeg[t] += readers_over[wiv]
        own_writes = set(writes_of[t])
        for riv in reads_of[t]:
            for wiv in overlapping[riv]:
                if wiv in own_writes:
                    indeg[t] -= 1
    return sorted(range(n), key=lambda t: (indeg[t], t))


def moved_count(order: List[int]) -> int:
    """Transactions not at their original position (the ReorderSwaps
    metric's per-batch increment)."""
    return sum(1 for pos, t in enumerate(order) if pos != t)
