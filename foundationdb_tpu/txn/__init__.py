"""Transaction payload types (reference fdbclient/CommitTransaction.h)."""

from .types import (ALL_KEYS, ALL_KEYS_WITH_SYSTEM, ATOMIC_OPS,
                    INVALID_VERSION, MAX_VERSION, SYSTEM_KEYS, CommitResult,
                    CommitTransactionRef, KeyRange, Mutation, MutationType,
                    Version, key_after, single_key_range, strinc)

__all__ = [
    "ALL_KEYS", "ALL_KEYS_WITH_SYSTEM", "ATOMIC_OPS", "INVALID_VERSION",
    "MAX_VERSION", "SYSTEM_KEYS", "CommitResult", "CommitTransactionRef",
    "KeyRange", "Mutation", "MutationType", "Version", "key_after",
    "single_key_range", "strinc",
]
