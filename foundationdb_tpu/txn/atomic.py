"""Atomic mutation operators, matching the reference bit-for-bit.

Reference: fdbclient/Atomic.h (doLittleEndianAdd, doAnd/doAndV2, doOr,
doXor, doAppendIfFits, doMin/doMinV2, doMax, doByteMin, doByteMax,
doCompareAndClear).  Applied by storage servers when ingesting mutations
and by the client's read-your-writes cache when merging uncommitted
writes into reads.  `existing=None` means the key is absent; a returned
None means the key becomes absent (CompareAndClear).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from .types import MutationType

VALUE_SIZE_LIMIT = 100_000  # reference CLIENT_KNOBS->VALUE_SIZE_LIMIT


def do_little_endian_add(existing: Optional[bytes], operand: bytes) -> bytes:
    existing = existing or b""
    if not existing or not operand:
        return operand
    out = bytearray(len(operand))
    carry = 0
    n = min(len(existing), len(operand))
    for i in range(n):
        s = existing[i] + operand[i] + carry
        out[i] = s & 0xFF
        carry = s >> 8
    for i in range(n, len(operand)):
        s = operand[i] + carry
        out[i] = s & 0xFF
        carry = s >> 8
    return bytes(out)


def do_and(existing: Optional[bytes], operand: bytes) -> bytes:
    existing = existing or b""
    if not operand:
        return operand
    n = min(len(existing), len(operand))
    out = bytearray(len(operand))   # tail beyond existing stays zero
    for i in range(n):
        out[i] = existing[i] & operand[i]
    return bytes(out)


def do_and_v2(existing: Optional[bytes], operand: bytes) -> bytes:
    if existing is None:
        return operand
    return do_and(existing, operand)


def do_or(existing: Optional[bytes], operand: bytes) -> bytes:
    existing = existing or b""
    if not existing or not operand:
        return operand
    n = min(len(existing), len(operand))
    out = bytearray(operand)
    for i in range(n):
        out[i] = existing[i] | operand[i]
    return bytes(out)


def do_xor(existing: Optional[bytes], operand: bytes) -> bytes:
    existing = existing or b""
    if not existing or not operand:
        return operand
    n = min(len(existing), len(operand))
    out = bytearray(operand)
    for i in range(n):
        out[i] = existing[i] ^ operand[i]
    return bytes(out)


def do_append_if_fits(existing: Optional[bytes], operand: bytes) -> bytes:
    existing = existing or b""
    if not existing:
        return operand
    if not operand:
        return existing
    if len(existing) + len(operand) > VALUE_SIZE_LIMIT:
        return existing
    return existing + operand


def _le_truncated_existing(existing: bytes, operand: bytes) -> bytes:
    """existing truncated/zero-padded to operand length (doMax/doMin reply)."""
    out = bytearray(len(operand))
    n = min(len(existing), len(operand))
    out[:n] = existing[:n]
    return bytes(out)


def do_max(existing: Optional[bytes], operand: bytes) -> bytes:
    existing = existing or b""
    if not existing or not operand:
        return operand
    # Compare as little-endian unsigned ints of operand's width.
    for i in range(len(operand) - 1, len(existing) - 1, -1):
        if operand[i] != 0:
            return operand
    for i in range(min(len(operand), len(existing)) - 1, -1, -1):
        if operand[i] > existing[i]:
            return operand
        if operand[i] < existing[i]:
            return _le_truncated_existing(existing, operand)
    return operand


def do_min(existing: Optional[bytes], operand: bytes) -> bytes:
    if not operand:
        return operand
    existing = existing or b""
    for i in range(len(operand) - 1, len(existing) - 1, -1):
        if operand[i] != 0:
            return _le_truncated_existing(existing, operand)
    for i in range(min(len(operand), len(existing)) - 1, -1, -1):
        if operand[i] > existing[i]:
            return _le_truncated_existing(existing, operand)
        if operand[i] < existing[i]:
            return operand
    return operand


def do_min_v2(existing: Optional[bytes], operand: bytes) -> bytes:
    if existing is None:
        return operand
    return do_min(existing, operand)


def do_byte_max(existing: Optional[bytes], operand: bytes) -> bytes:
    if existing is None:
        return operand
    return existing if existing > operand else operand


def do_byte_min(existing: Optional[bytes], operand: bytes) -> bytes:
    if existing is None:
        return operand
    return existing if existing < operand else operand


def do_compare_and_clear(existing: Optional[bytes],
                         operand: bytes) -> Optional[bytes]:
    if existing is None or existing == operand:
        return None
    return existing


_OPS: Dict[MutationType, Callable[[Optional[bytes], bytes], Optional[bytes]]] = {
    MutationType.AddValue: do_little_endian_add,
    MutationType.And: do_and,
    MutationType.AndV2: do_and_v2,
    MutationType.Or: do_or,
    MutationType.Xor: do_xor,
    MutationType.AppendIfFits: do_append_if_fits,
    MutationType.Max: do_max,
    MutationType.Min: do_min,
    MutationType.MinV2: do_min_v2,
    MutationType.ByteMax: do_byte_max,
    MutationType.ByteMin: do_byte_min,
    MutationType.CompareAndClear: do_compare_and_clear,
}


def apply_atomic(op: MutationType, existing: Optional[bytes],
                 operand: bytes) -> Optional[bytes]:
    """Apply atomic op; returns new value or None (key cleared)."""
    fn = _OPS.get(op)
    if fn is None:
        raise ValueError(f"not an atomic op: {op!r}")
    return fn(existing, operand)
