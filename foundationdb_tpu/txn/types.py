"""Transaction payload types.

Equivalents of the reference's fdbclient/CommitTransaction.h (MutationRef
:55-96, CommitTransactionRef :179) and fdbclient/FDBTypes.h (KeyRangeRef,
Version).  Keys are raw bytes, ordered lexicographically; ranges are
half-open [begin, end).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import IntEnum
from typing import List, Optional, Tuple

Version = int
INVALID_VERSION = -1
MAX_VERSION = (1 << 62) - 1


def strinc(key: bytes) -> bytes:
    """Smallest key strictly greater than every key with prefix `key`.

    Reference: flow strinc() — strips trailing 0xff bytes then increments the
    last byte. Raises if key is empty or all 0xff (no such key exists)."""
    key = key.rstrip(b"\xff")
    if not key:
        raise ValueError("strinc on empty/all-0xff key")
    return key[:-1] + bytes([key[-1] + 1])


def key_after(key: bytes) -> bytes:
    """Smallest key strictly greater than `key` (append \\x00)."""
    return key + b"\x00"


def single_key_range(key: bytes) -> "KeyRange":
    return KeyRange(key, key_after(key))


@dataclass(frozen=True, order=True)
class KeyRange:
    """Half-open key interval [begin, end); empty if begin >= end."""

    begin: bytes
    end: bytes

    def __post_init__(self) -> None:
        if self.begin > self.end:
            from ..core.error import err
            raise err("inverted_range", f"{self.begin!r} > {self.end!r}")

    def empty(self) -> bool:
        return self.begin >= self.end

    def contains(self, key: bytes) -> bool:
        return self.begin <= key < self.end

    def overlaps(self, other: "KeyRange") -> bool:
        return self.begin < other.end and other.begin < self.end

    def intersect(self, other: "KeyRange") -> Optional["KeyRange"]:
        b, e = max(self.begin, other.begin), min(self.end, other.end)
        return KeyRange(b, e) if b < e else None


# The whole legal keyspace. b"\xff"-prefixed keys are system metadata, as in
# the reference (fdbclient/SystemData.cpp); b"\xff\xff" is the special keyspace.
ALL_KEYS = KeyRange(b"", b"\xff")
SYSTEM_KEYS = KeyRange(b"\xff", b"\xff\xff")
ALL_KEYS_WITH_SYSTEM = KeyRange(b"", b"\xff\xff")


def make_versionstamp(version: int, batch_index: int) -> bytes:
    """The 10-byte versionstamp: 8B big-endian commit version + 2B
    big-endian transaction batch index (reference CommitTransaction.h:55).
    Shared by the commit proxy (key/value splice) and the client's
    versionstamp future so the two can never drift."""
    return version.to_bytes(8, "big") + batch_index.to_bytes(2, "big")


class MutationType(IntEnum):
    """Mutation op codes (reference fdbclient/CommitTransaction.h:55-96)."""

    SetValue = 0
    ClearRange = 1
    AddValue = 2
    DebugKeyRange = 3
    DebugKey = 4
    NoOp = 5
    And = 6
    Or = 7
    Xor = 8
    AppendIfFits = 9
    AvailableForReuse = 10
    Reserved_For_LogProtocolMessage = 11
    Max = 12
    Min = 13
    SetVersionstampedKey = 14
    SetVersionstampedValue = 15
    ByteMin = 16
    ByteMax = 17
    MinV2 = 18
    AndV2 = 19
    CompareAndClear = 20


ATOMIC_OPS = {
    MutationType.AddValue, MutationType.And, MutationType.Or, MutationType.Xor,
    MutationType.AppendIfFits, MutationType.Max, MutationType.Min,
    MutationType.SetVersionstampedKey, MutationType.SetVersionstampedValue,
    MutationType.ByteMin, MutationType.ByteMax, MutationType.MinV2,
    MutationType.AndV2, MutationType.CompareAndClear,
}


@dataclass
class Mutation:
    """One mutation: (type, param1, param2).

    SetValue: param1=key, param2=value. ClearRange: param1=begin, param2=end.
    Atomic ops: param1=key, param2=operand."""

    type: MutationType
    param1: bytes
    param2: bytes

    def expected_size(self) -> int:
        return len(self.param1) + len(self.param2) + 12

    @staticmethod
    def set_value(key: bytes, value: bytes) -> "Mutation":
        return Mutation(MutationType.SetValue, key, value)

    @staticmethod
    def clear_range(begin: bytes, end: bytes) -> "Mutation":
        return Mutation(MutationType.ClearRange, begin, end)


@dataclass
class CommitTransactionRef:
    """A transaction as submitted for commit.

    Reference: fdbclient/CommitTransaction.h:179 CommitTransactionRef with
    read_conflict_ranges, write_conflict_ranges, mutations, read_snapshot,
    report_conflicting_keys."""

    read_conflict_ranges: List[KeyRange] = field(default_factory=list)
    write_conflict_ranges: List[KeyRange] = field(default_factory=list)
    mutations: List[Mutation] = field(default_factory=list)
    read_snapshot: Version = 0
    report_conflicting_keys: bool = False
    # LOCK_AWARE transaction option (reference FDBTransactionOptions):
    # commits pass the \xff/dbLocked fence — management/DR traffic only.
    lock_aware: bool = False
    # Tenant identity (reference TenantInfo riding the commit): -1 = raw.
    # Commit proxies validate tenant-tagged transactions against their
    # tenant cache post-resolution — a deleted tenant can never commit —
    # and reject mutations outside the tenant's 8-byte prefix.
    tenant_id: int = -1
    # Throttling tag (reference TransactionOptions::tags; tenant txns
    # carry "t/<name>"): rides the commit so the resolvers' conflict-heat
    # tracker can break hot ranges down per tag (conflict/heat.py) —
    # the same identity storage uses for busy-read sampling.
    tag: str = ""

    def expected_size(self) -> int:
        s = sum(len(r.begin) + len(r.end) for r in
                self.read_conflict_ranges + self.write_conflict_ranges)
        return s + sum(m.expected_size() for m in self.mutations)


class CommitResult(IntEnum):
    """Per-transaction resolver verdict.

    Reference ConflictBatch::TransactionCommitResult (ConflictSet.h:41-45)."""

    CONFLICT = 0
    TOO_OLD = 1
    COMMITTED = 2
