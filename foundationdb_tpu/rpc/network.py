"""Simulated network: deterministic latency, clogging, partitions.

Reference: fdbrpc/sim2.actor.cpp — Sim2Conn (:181) models per-connection
latency and delivery; SimClogging (:121) delays traffic between process
pairs; FlowTransport delivers by endpoint token (FlowTransport.actor.cpp:919
deliver()).  This module collapses transport + sim-network into one object:
every message delivery is a scheduled callback on the shared deterministic
event loop, with latency drawn from the deterministic RNG.

Failure semantics (matching what upper layers can observe in the reference):
  * target process dead / endpoint unregistered / pair partitioned
      → caller's reply future gets broken_promise after ~latency
        (the transport's connection-failure signal);
  * receiver drops its ReplyPromise unset (actor cancelled by kill/reboot)
      → broken_promise routed back;
  * clogged pair → delivery (or reply) deferred until unclogged, never lost.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

from ..core.error import err
from ..core.futures import Future, Promise
from ..core.rng import deterministic_random
from ..core.scheduler import TaskPriority, get_event_loop
from ..core.trace import Severity, TraceEvent
from .endpoint import Endpoint, NetworkAddress, ReplyPromise, RequestStream


class SimNetwork:
    """All inter-process message passing in a simulation."""

    MIN_LATENCY = 0.0001
    MAX_LATENCY = 0.0015

    def __init__(self) -> None:
        # (address, token) -> (stream, epoch of registering process)
        self._endpoints: Dict[Endpoint, Tuple[RequestStream, int]] = {}
        # address -> SimProcess (set by Simulator)
        self.processes: Dict[NetworkAddress, Any] = {}
        # (ip, ip) -> virtual time until which the pair is clogged
        self._clog_until: Dict[Tuple[str, str], float] = {}
        # ip -> virtual time until which ALL its traffic is clogged
        # (reference sim2 clogInterface — the unit the nemesis swizzles).
        self._clog_ip_until: Dict[str, float] = {}
        self._partitioned: set = set()  # frozenset({ip, ip})
        # Gray clog (ISSUE 18): (ip, ip) -> (extra latency, until).
        # Unlike a clog, delivery still HAPPENS — just inflated: the
        # slow-but-alive link shape that quorum checks can never see and
        # the peer-health plane exists to detect.
        self._gray_until: Dict[Tuple[str, str], Tuple[float, float]] = {}
        # Per-source-ip peer telemetry (rpc/peer_metrics.py): each
        # simulated process observes its own peers, exactly like one
        # real-mode process's transport table.
        self._peer_tables: Dict[str, Any] = {}
        self.messages_sent = 0

    # -- registration -------------------------------------------------------
    def register(self, process, stream: RequestStream,
                 token: Optional[str] = None) -> Endpoint:
        token = token or (stream.name + ":" +
                          deterministic_random().random_unique_id()[:16])
        ep = Endpoint(process.address, token)
        self._endpoints[ep] = (stream, process.epoch)
        stream.set_endpoint(ep)
        process._tokens.add(token)
        return ep

    def unregister_process(self, address: NetworkAddress) -> None:
        """Drop every endpoint at `address` (process killed/rebooted).

        Buffered-but-unserved requests get their reply promises broken
        DETERMINISTICALLY here: leaving them to reply-wrapper __del__ means
        a request caught in a reference cycle only breaks when cyclic GC
        happens to run — observed as wall-clock-dependent post-kill stalls
        (the reference's SAV destruction is deterministic by refcount)."""
        for ep in [e for e in self._endpoints if e.address == address]:
            stream, _epoch = self._endpoints.pop(ep)
            stream.queue.break_buffered_replies()

    def unregister_stream(self, stream: RequestStream) -> None:
        """Drop ONE stream's endpoint (a replaced role halting while its
        process lives on): senders from then on get broken_promise instead
        of buffering into a queue nobody serves."""
        ep = stream._endpoint
        if ep is not None:
            self._endpoints.pop(ep, None)
        stream.queue.break_buffered_replies()

    # -- fault injection ----------------------------------------------------
    def clog_pair(self, a: str, b: str, seconds: float) -> None:
        """Delay all traffic between ips a and b for `seconds` (reference
        ISimulator::clogPair, sim2 SimClogging)."""
        until = get_event_loop().now() + seconds
        for pair in ((a, b), (b, a)):
            self._clog_until[pair] = max(self._clog_until.get(pair, 0.0), until)
        TraceEvent("ClogPair", Severity.Info).detail("A", a).detail("B", b) \
            .detail("Seconds", seconds).log()

    def clog_ip(self, ip: str, seconds: float) -> None:
        """Delay ALL traffic to and from `ip` (reference
        ISimulator::clogInterface): the swizzle nemesis clogs whole
        machines one at a time and unclogs them in reverse order."""
        until = get_event_loop().now() + seconds
        self._clog_ip_until[ip] = max(self._clog_ip_until.get(ip, 0.0),
                                      until)
        TraceEvent("ClogInterface", Severity.Info).detail(
            "IP", ip).detail("Seconds", seconds).log()

    def unclog_ip(self, ip: str) -> None:
        if self._clog_ip_until.pop(ip, None) is not None:
            TraceEvent("UnclogInterface", Severity.Info).detail(
                "IP", ip).log()

    def gray_clog_pair(self, a: str, b: str, extra_latency: float,
                       seconds: float) -> None:
        """Inflate latency between ips a and b by `extra_latency` for
        `seconds` WITHOUT stopping delivery — the gray-failure shape
        (half-broken NIC, saturated link): every request still succeeds,
        just slowly, so only the peer-health plane can see it."""
        until = get_event_loop().now() + seconds
        for pair in ((a, b), (b, a)):
            old = self._gray_until.get(pair)
            self._gray_until[pair] = (
                max(extra_latency, old[0]) if old else extra_latency,
                max(old[1], until) if old else until)
        TraceEvent("GrayClogPair", Severity.Info).detail("A", a).detail(
            "B", b).detail("ExtraLatency", extra_latency).detail(
            "Seconds", seconds).log()

    def ungray_pair(self, a: str, b: str) -> None:
        if self._gray_until.pop((a, b), None) is not None or \
                self._gray_until.pop((b, a), None) is not None:
            TraceEvent("UngrayPair", Severity.Info).detail(
                "A", a).detail("B", b).log()

    def partition_pair(self, a: str, b: str) -> None:
        self._partitioned.add(frozenset((a, b)))

    def heal_partition(self, a: str, b: str) -> None:
        self._partitioned.discard(frozenset((a, b)))

    def heal_all(self) -> None:
        self._partitioned.clear()
        self._clog_until.clear()
        self._clog_ip_until.clear()
        self._gray_until.clear()

    # -- peer telemetry (ISSUE 18) ------------------------------------------
    def peer_table(self, src_ip: str):
        """The PeerMetricsTable of the process at `src_ip` (lazily
        created): what its worker health monitor folds into verdicts."""
        t = self._peer_tables.get(src_ip)
        if t is None:
            from .peer_metrics import PeerMetricsTable
            t = self._peer_tables[src_ip] = PeerMetricsTable(src_ip)
        return t

    # -- delivery -----------------------------------------------------------
    def _latency(self) -> float:
        rng = deterministic_random()
        return (self.MIN_LATENCY +
                rng.random01() * (self.MAX_LATENCY - self.MIN_LATENCY))

    _CLOG_RECHECK_S = 0.25

    def _delivery_time(self, src: str, dst: str) -> Optional[float]:
        """Virtual time at which a message sent now arrives (latency
        only — clogging is re-evaluated at delivery time, see
        _deliver_when_unclogged), or None if the pair is partitioned."""
        if frozenset((src, dst)) in self._partitioned and src != dst:
            return None
        return (get_event_loop().now() + self._latency() +
                self._gray_extra(src, dst))

    def _gray_extra(self, src: str, dst: str) -> float:
        """Extra one-way latency from an active gray clog (0.0 when the
        pair is clean or the inflation window has expired)."""
        if not self._gray_until:
            return 0.0
        entry = self._gray_until.get((src, dst))
        if entry is None:
            return 0.0
        extra, until = entry
        if get_event_loop().now() >= until:
            del self._gray_until[(src, dst)]
            return 0.0
        return extra

    def _clog_time(self, src: str, dst: str) -> float:
        clog = self._clog_until.get((src, dst), 0.0)
        if self._clog_ip_until and src != dst:
            # Self-traffic is exempt, like partitions: co-hosted roles
            # talk in-process, not over the clogged interface.
            clog = max(clog, self._clog_ip_until.get(src, 0.0),
                       self._clog_ip_until.get(dst, 0.0))
        return clog

    def _deliver_when_unclogged(self, src: str, dst: str, when: float,
                                fn, priority: TaskPriority) -> None:
        """Run `fn` at `when`, deferred while the (src, dst) path is
        clogged — re-checked AT DELIVERY TIME, not frozen at send time:
        an unclog (the nemesis's reverse-order swizzle release) must
        free traffic captured mid-clog, and a clog extended after the
        send must keep holding it.  While clogged, the re-check hops at
        min(clog expiry, now + _CLOG_RECHECK_S) so a shrunk clog
        releases within one bounded, deterministic step."""
        loop = get_event_loop()

        def step() -> None:
            clog = self._clog_time(src, dst)
            t = loop.now()
            if clog > t:
                loop.call_at(min(clog, t + self._CLOG_RECHECK_S), step,
                             priority)
            else:
                fn()

        loop.call_at(when, step, priority)

    def _process_alive(self, address: NetworkAddress, epoch: int) -> bool:
        p = self.processes.get(address)
        return p is not None and p.alive and p.epoch == epoch

    @staticmethod
    def _ambient_src_ip(ep: Endpoint) -> str:
        """The sender's ip when the caller didn't say: the address of
        the simulated process whose actor is executing right now
        (ActorTask.process, inherited through spawns).  Without this,
        src defaulted to the DESTINATION ip and the src==dst self-traffic
        exemption silently bypassed every clog and partition for request
        delivery — the whole network fault plane was cosmetic (found by
        the regionFailover forced-replication-lag scenario, ISSUE 10).
        Harness/client actors have no process: they send from a sentinel
        outside the machine set, so interface clogs on the TARGET still
        apply while pair faults never match."""
        from ..core.futures import current_task
        t = current_task()
        p = t.process if t is not None else None
        if p is not None:
            return p.address.ip
        return "0.0.0.0"

    def send_request(self, ep: Endpoint, request: Any,
                     priority: TaskPriority = TaskPriority.DefaultEndpoint,
                     from_address: Optional[NetworkAddress] = None) -> Future:
        """Deliver `request` to the endpoint; Future of its reply."""
        loop = get_event_loop()
        self.messages_sent += 1
        reply_promise: Promise = Promise()
        src_ip = from_address.ip if from_address \
            else self._ambient_src_ip(ep)
        when = self._delivery_time(src_ip, ep.address.ip)
        # Peer telemetry (ISSUE 18): sample the full request->reply RTT
        # into the sender's table.  Self-traffic is exempt (co-hosted
        # roles talk in-process — a process is never its own peer), and
        # the whole plane is knob-gated so the bench overhead gate can
        # measure it.
        from ..core.knobs import server_knobs
        table = None
        peer_key = ""
        t0 = 0.0
        if src_ip != ep.address.ip and server_knobs().PEER_HEALTH_ENABLED:
            table = self.peer_table(src_ip)
            peer_key = str(ep.address)
            table.sample_request(peer_key)
            t0 = loop.now()

        def fail() -> None:
            if not reply_promise.is_set() and \
                    not reply_promise.get_future().is_ready():
                if table is not None:
                    table.sample_disconnect(peer_key)
                reply_promise.send_error(err("broken_promise"))

        if when is None:  # partitioned: connection failure after a delay
            loop.call_at(loop.now() + self._latency(), fail, priority)
            return reply_promise.get_future()

        def route_reply(value: Any, e: Optional[BaseException]) -> None:
            # Reply path: receiver -> sender, re-clogged/partitioned/timed.
            # May fire from the GC (dropped ReplyPromise) AFTER this sim
            # world was torn down — never touch the current world's RNG or
            # loop from a stale one (it would break determinism).
            from ..core.scheduler import current_event_loop_or_none
            if current_event_loop_or_none() is not loop:
                if not reply_promise.is_set() and \
                        not reply_promise.get_future().is_ready():
                    reply_promise.send_error(err("broken_promise"))
                return
            back = self._delivery_time(ep.address.ip, src_ip)

            def deliver_reply() -> None:
                if reply_promise.is_set() or \
                        reply_promise.get_future().is_ready():
                    return
                if table is not None:
                    # An application-level error reply is still a reply:
                    # the link carried it, so it samples as RTT.
                    table.sample_rtt(peer_key, loop.now() - t0, loop.now())
                if e is not None:
                    reply_promise.send_error(e)
                else:
                    reply_promise.send(value)

            if back is None:
                loop.call_at(loop.now() + self._latency(), fail, priority)
            else:
                self._deliver_when_unclogged(ep.address.ip, src_ip, back,
                                             deliver_reply, priority)

        def deliver() -> None:
            entry = self._endpoints.get(ep)
            if entry is None or not self._process_alive(ep.address, entry[1]):
                fail()
                return
            stream, _ = entry
            request.reply = ReplyPromise(route_reply)
            stream.deliver(request)

        self._deliver_when_unclogged(src_ip, ep.address.ip, when, deliver,
                                     priority)
        return reply_promise.get_future()

    def send_one_way(self, ep: Endpoint, message: Any,
                     priority: TaskPriority = TaskPriority.DefaultEndpoint,
                     from_address: Optional[NetworkAddress] = None) -> None:
        """Fire-and-forget delivery (reference sendUnreliable)."""
        self.messages_sent += 1
        src_ip = from_address.ip if from_address \
            else self._ambient_src_ip(ep)
        when = self._delivery_time(src_ip, ep.address.ip)
        if when is None:
            return

        def deliver() -> None:
            entry = self._endpoints.get(ep)
            if entry is None or not self._process_alive(ep.address, entry[1]):
                return
            entry[0].deliver(message)

        self._deliver_when_unclogged(src_ip, ep.address.ip, when, deliver,
                                     priority)


_network: Optional[SimNetwork] = None


def set_network(net: Optional[SimNetwork]) -> None:
    global _network
    _network = net


def get_network() -> SimNetwork:
    if _network is None:
        raise err("internal_error", "no SimNetwork installed (set_network)")
    return _network
