"""Real TCP transport skeleton: token-addressed frames in the wire format.

Reference: fdbrpc/FlowTransport.actor.cpp — one connection per peer pair, a
`ConnectPacket` version handshake (:355), token-addressed endpoint delivery
(`deliver` :919).  This module is the multi-process half of that design for
the framework's wire format (core/wire.py):

    frame    := u32 length | u64 token | u8 kind | u8 span_len | span
                | payload
    kind     := 0 request (payload ends with a u64 reply token)
                1 reply
    handshake:= u32 magic 0x0FDB7C01 | u16 protocol version

The span field is the cross-process trace context (reference
flow/Tracing.h SpanContext riding every FlowTransport packet): a request
carries its caller's span id, the server installs it as the ambient span
(core/trace.py set_current_span) while the handler runs — so every
TraceEvent the handler emits is stamped with it — and the reply echoes it
back.  Protocol version 2 (v1 frames had no span field).

Serialization of the demonstrator messages lives in `serialize_kv_*` —
the classic length-prefixed field order of flow/serialize.h.  The
simulation transport (rpc/network.py) remains the test vehicle for the
full role surface; this transport is deployed process-to-process over real
sockets (tests/test_tcp_transport.py runs a durable KV service in a
separate OS process).  Wiring every role interface through it — i.e. a
multi-process fdbserver — is the remaining step and needs the event loop's
real-IO reactor; the framing, handshake, token dispatch, and reply
correlation here are that future reactor's data plane.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Callable, Dict, Optional, Tuple

from ..core.wire import Reader, Writer

MAGIC = 0x0FDB7C01
PROTOCOL_VERSION = 2                # v2: frames carry a span context
_HDR = struct.Struct("<I")          # frame length
_TOKEN_KIND = struct.Struct("<QB")  # token, kind

KIND_REQUEST = 0
KIND_REPLY = 1


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except OSError:
            # close() raced this reader thread (EBADF/ECONNRESET at
            # teardown): same as a clean peer close — end the frame
            # loop instead of dying with an unhandled thread exception.
            return None
        if not chunk:
            return None
        buf += chunk
    return buf


def _send_frame(sock: socket.socket, token: int, kind: int,
                payload: bytes, span: str = "") -> None:
    sb = span.encode()[:255]
    body = (_TOKEN_KIND.pack(token, kind) + bytes([len(sb)]) + sb +
            payload)
    sock.sendall(_HDR.pack(len(body)) + body)


def _recv_frame(sock: socket.socket
                ) -> Optional[Tuple[int, int, bytes, str]]:
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (n,) = _HDR.unpack(hdr)
    body = _recv_exact(sock, n)
    if body is None:
        return None
    token, kind = _TOKEN_KIND.unpack_from(body, 0)
    o = _TOKEN_KIND.size
    span_len = body[o]
    span = body[o + 1:o + 1 + span_len].decode(errors="replace")
    return token, kind, body[o + 1 + span_len:], span


class TcpTransport:
    """Thread-per-connection transport endpoint (server and client halves).

    register(token, handler) installs `handler(payload: bytes) -> bytes`;
    incoming request frames dispatch by token and the returned bytes go
    back as a reply frame correlated by the embedded reply token."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0) -> None:
        self._handlers: Dict[int, Callable[[bytes], bytes]] = {}
        self._lock = threading.Lock()
        # Serializes whole frames onto shared sockets: sendall can
        # interleave across threads on partial sends, corrupting the
        # peer's framing.
        self._send_lock = threading.Lock()
        self._replies: Dict[int, threading.Event] = {}
        self._reply_data: Dict[int, bytes] = {}
        self._next_reply_token = 1 << 32
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self.address = self._listener.getsockname()
        self._stopping = False
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        self._peer_socks: Dict[Tuple[str, int], socket.socket] = {}
        # Per-peer health telemetry (ISSUE 18, rpc/peer_metrics.py):
        # request/reply RTTs, timeout/disconnect counters, bytes both
        # ways — the real-TCP half of the gray-failure plane.  Samples
        # are gated on PEER_HEALTH_ENABLED at each call site.
        from .peer_metrics import PeerMetricsTable
        self.peer_metrics = PeerMetricsTable(
            f"{self.address[0]}:{self.address[1]}")
        self._ever_connected: set = set()

    # -- server half ---------------------------------------------------------
    def register(self, token: int, handler: Callable[[bytes], bytes]) -> None:
        self._handlers[token] = handler

    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        # ConnectPacket-style handshake (reference :355): refuse mismatched
        # protocol versions up front.
        hs = _recv_exact(conn, 6)
        if hs is None:
            return
        magic, ver = struct.unpack("<IH", hs)
        if magic != MAGIC or ver != PROTOCOL_VERSION:
            conn.close()
            return
        conn.sendall(struct.pack("<IH", MAGIC, PROTOCOL_VERSION))
        self._frame_loop(conn)

    def _frame_loop(self, conn: socket.socket) -> None:
        from ..core.trace import set_current_span
        while True:
            frame = _recv_frame(conn)
            if frame is None:
                return
            token, kind, payload, span = frame
            if kind == KIND_REQUEST:
                r = Reader(payload)
                body = r.bytes_()
                reply_token = r.i64()
                handler = self._handlers.get(token)
                if handler is None:
                    continue   # unknown endpoint: drop (broken promise)
                # The caller's span becomes the ambient context while the
                # handler runs: every TraceEvent it emits carries it, and
                # the reply echoes it back (reference: SpanContext rides
                # each FlowTransport packet).
                prev = set_current_span(span)
                try:
                    result = handler(body)
                except Exception:  # noqa: BLE001 — one bad request must
                    # not tear down the connection; the caller's reply
                    # promise breaks via its timeout.
                    continue
                finally:
                    set_current_span(prev)
                with self._send_lock:
                    _send_frame(conn, reply_token, KIND_REPLY, result,
                                span)
            elif kind == KIND_REPLY:
                with self._lock:
                    ev = self._replies.get(token)
                    if ev is None:
                        continue   # late reply after timeout: drop, don't leak
                    self._reply_data[token] = payload
                ev.set()

    # -- client half ---------------------------------------------------------
    def _connect(self, addr: Tuple[str, int]) -> socket.socket:
        with self._lock:
            sock = self._peer_socks.get(addr)
        if sock is not None:
            return sock
        sock = socket.create_connection(addr)
        sock.sendall(struct.pack("<IH", MAGIC, PROTOCOL_VERSION))
        ack = _recv_exact(sock, 6)
        if ack is None:
            raise ConnectionError("peer closed during handshake")
        magic, ver = struct.unpack("<IH", ack)
        if magic != MAGIC or ver != PROTOCOL_VERSION:
            raise ConnectionError("protocol version mismatch")
        with self._lock:
            existing = self._peer_socks.get(addr)
            if existing is not None:
                sock.close()   # lost the connect race; use the winner
                return existing
            self._peer_socks[addr] = sock
            if addr in self._ever_connected:
                from ..core.knobs import server_knobs
                if server_knobs().PEER_HEALTH_ENABLED:
                    self.peer_metrics.sample_reconnect(
                        f"{addr[0]}:{addr[1]}")
            self._ever_connected.add(addr)
        # The outbound handshake already happened; run the bare frame loop
        # (replies and peer-initiated requests both arrive here).
        threading.Thread(target=self._frame_loop, args=(sock,),
                         daemon=True).start()
        return sock

    def request(self, addr: Tuple[str, int], token: int, payload: bytes,
                timeout: float = 10.0, span: str = "") -> bytes:
        """Blocking request/reply over the peer connection.  `span`
        (default: the ambient current span) rides the frame so the far
        side's TraceEvents correlate with this caller's."""
        if not span:
            from ..core.trace import get_current_span
            span = get_current_span()
        from ..core.knobs import server_knobs
        sample = bool(server_knobs().PEER_HEALTH_ENABLED)
        peer_key = f"{addr[0]}:{addr[1]}"
        try:
            sock = self._connect(addr)
        except (OSError, ConnectionError):
            if sample:
                self.peer_metrics.sample_disconnect(peer_key)
            raise
        with self._lock:
            reply_token = self._next_reply_token
            self._next_reply_token += 1
            ev = threading.Event()
            self._replies[reply_token] = ev
        body = Writer().bytes_(payload).i64(reply_token).done()
        if sample:
            self.peer_metrics.sample_request(peer_key, len(body))
            import time as _time
            t0 = _time.monotonic()  # flowlint: disable=FTL001 -- real mode
        with self._send_lock:
            _send_frame(sock, token, KIND_REQUEST, body, span)
        try:
            if not ev.wait(timeout):
                if sample:
                    self.peer_metrics.sample_timeout(peer_key)
                raise TimeoutError(f"no reply for token {token}")
            with self._lock:
                reply = self._reply_data.pop(reply_token)
            if sample:
                import time as _time
                self.peer_metrics.sample_rtt(
                    peer_key,
                    _time.monotonic() - t0,  # flowlint: disable=FTL001 -- real mode
                    nbytes=len(reply))
            return reply
        finally:
            # Always unregister both entries, or timed-out waits leak
            # (late replies are dropped at the frame loop once the wait
            # entry is gone).
            with self._lock:
                self._replies.pop(reply_token, None)
                self._reply_data.pop(reply_token, None)

    def close(self) -> None:
        self._stopping = True
        try:
            self._listener.close()
        except OSError:
            pass
        # Snapshot under the lock: _connect threads may still be
        # registering winners of a connect race (FTL012 catch).
        with self._lock:
            socks = list(self._peer_socks.values())
        for s in socks:
            try:
                s.close()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# Demonstrator message serialization (classic field-order style)
# ---------------------------------------------------------------------------

TOKEN_KV_GET = 0x100
TOKEN_KV_SET = 0x101
TOKEN_KV_RANGE = 0x102


def pack_kv_set(key: bytes, value: bytes) -> bytes:
    return Writer().bytes_(key).bytes_(value).done()


def unpack_kv_set(b: bytes) -> Tuple[bytes, bytes]:
    r = Reader(b)
    return r.bytes_(), r.bytes_()


def pack_kv_get(key: bytes) -> bytes:
    return Writer().bytes_(key).done()


def pack_value_reply(value: Optional[bytes]) -> bytes:
    w = Writer().u8(1 if value is not None else 0)
    if value is not None:
        w.bytes_(value)
    return w.done()


def unpack_value_reply(b: bytes) -> Optional[bytes]:
    r = Reader(b)
    return r.bytes_() if r.u8() else None
